"""Tree learner unit tests: histogram math, split finding, growth
(ref strategy: the CUDA learner decomposition, SURVEY.md §2.4)."""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.histogram import build_histogram, subtract_histogram
from lightgbm_tpu.ops.split import (FeatureMeta, SplitHyperParams,
                                    find_best_split, leaf_output,
                                    threshold_l1)
from lightgbm_tpu.learner import grow_tree
from lightgbm_tpu.config import Config


def _meta(num_bins, missing=None, cat=None):
    f = len(num_bins)
    return FeatureMeta(
        num_bins=jnp.asarray(num_bins, jnp.int32),
        missing_type=jnp.asarray(missing if missing is not None
                                 else [0] * f, jnp.int32),
        default_bin=jnp.asarray([0] * f, jnp.int32),
        is_categorical=jnp.asarray(cat if cat is not None else [False] * f),
        monotone=jnp.asarray([0] * f, jnp.int8),
        penalty=jnp.asarray([1.0] * f, jnp.float32),
        cegb_feat=jnp.zeros(f, jnp.float32),
        cegb_lazy=jnp.zeros(f, jnp.float32),
    )


def _hp(**kw):
    cfg = Config()
    for k, v in kw.items():
        setattr(cfg, k, v)
    return SplitHyperParams.from_config(cfg)


class TestHistogram:
    def test_matches_numpy(self):
        rng = np.random.RandomState(0)
        n, f, b = 500, 4, 16
        bins = rng.randint(0, b, (f, n)).astype(np.uint8)
        g = rng.randn(n).astype(np.float32)
        h = rng.rand(n).astype(np.float32)
        mask = (rng.rand(n) > 0.3).astype(np.float32)
        hist = np.asarray(build_histogram(jnp.asarray(bins), jnp.asarray(g),
                                          jnp.asarray(h), jnp.asarray(mask),
                                          max_bins=b))
        for fi in range(f):
            for bi in range(b):
                sel = (bins[fi] == bi) & (mask > 0)
                np.testing.assert_allclose(hist[fi, bi, 0], g[sel].sum(),
                                           rtol=1e-4, atol=1e-4)
                np.testing.assert_allclose(hist[fi, bi, 1], h[sel].sum(),
                                           rtol=1e-4, atol=1e-4)
                np.testing.assert_allclose(hist[fi, bi, 2], sel.sum(),
                                           rtol=1e-5)

    def test_chunked_matches_unchunked(self):
        rng = np.random.RandomState(1)
        n, f, b = 1000, 3, 8
        bins = jnp.asarray(rng.randint(0, b, (f, n)).astype(np.uint8))
        g = jnp.asarray(rng.randn(n).astype(np.float32))
        h = jnp.ones(n, jnp.float32)
        m = jnp.ones(n, jnp.float32)
        h1 = build_histogram(bins, g, h, m, max_bins=b)
        h2 = build_histogram(bins, g, h, m, max_bins=b, row_chunk=256)
        np.testing.assert_allclose(np.asarray(h1), np.asarray(h2),
                                   rtol=1e-5, atol=1e-5)

    def test_subtraction(self):
        rng = np.random.RandomState(2)
        parent = jnp.asarray(rng.rand(2, 8, 3).astype(np.float32)) + 1.0
        child = parent * 0.4
        sib = subtract_histogram(parent, child)
        np.testing.assert_allclose(np.asarray(sib), np.asarray(parent) * 0.6,
                                   rtol=1e-5)


class TestSplitFinder:
    def test_finds_obvious_split(self):
        # feature 0: clean signal, feature 1: noise
        n, b = 1000, 8
        rng = np.random.RandomState(3)
        bins0 = (np.arange(n) % b).astype(np.uint8)
        bins1 = rng.randint(0, b, n).astype(np.uint8)
        g = np.where(bins0 < 4, -1.0, 1.0).astype(np.float32)
        h = np.ones(n, np.float32)
        hist = build_histogram(jnp.asarray(np.stack([bins0, bins1])),
                               jnp.asarray(g), jnp.asarray(h),
                               jnp.ones(n, jnp.float32), max_bins=b)
        info = find_best_split(hist, jnp.float32(g.sum()), jnp.float32(n),
                               jnp.float32(n), _meta([b, b]),
                               _hp(min_data_in_leaf=1), jnp.ones(2, bool))
        assert int(info.feature) == 0
        assert int(info.threshold) == 3
        assert float(info.gain) > 0
        assert float(info.left_count) == pytest.approx(n / 2)

    def test_min_data_constraint(self):
        n, b = 100, 4
        bins = np.zeros((1, n), np.uint8)
        bins[0, :5] = 1  # only 5 rows on one side
        g = np.where(bins[0] == 1, -5.0, 1.0).astype(np.float32)
        hist = build_histogram(jnp.asarray(bins), jnp.asarray(g),
                               jnp.ones(n, jnp.float32),
                               jnp.ones(n, jnp.float32), max_bins=b)
        info = find_best_split(hist, jnp.float32(g.sum()), jnp.float32(n),
                               jnp.float32(n), _meta([b]),
                               _hp(min_data_in_leaf=10), jnp.ones(1, bool))
        assert float(info.gain) <= 0  # blocked by min_data_in_leaf

    def test_lambda_l1_threshold(self):
        assert float(threshold_l1(jnp.float32(5.0), jnp.float32(2.0))) == 3.0
        assert float(threshold_l1(jnp.float32(-5.0), jnp.float32(2.0))) == -3.0
        assert float(threshold_l1(jnp.float32(1.0), jnp.float32(2.0))) == 0.0

    def test_leaf_output_l2(self):
        hp = _hp(lambda_l2=1.0)
        out = leaf_output(jnp.float32(10.0), jnp.float32(4.0), hp)
        assert float(out) == pytest.approx(-10.0 / 5.0)

    def test_missing_nan_dual_direction(self):
        # NaN rows (last bin) carry strong negative gradient -> want them
        # grouped with low bins (default_left with nan-left variant)
        n, b = 300, 5
        bins = np.zeros((1, n), np.uint8)
        bins[0, :100] = 0
        bins[0, 100:200] = 1
        bins[0, 200:] = b - 1  # NaN bin
        g = np.concatenate([-np.ones(100), np.ones(100), -np.ones(100)]) \
            .astype(np.float32)
        hist = build_histogram(jnp.asarray(bins), jnp.asarray(g),
                               jnp.ones(n, jnp.float32),
                               jnp.ones(n, jnp.float32), max_bins=b)
        info = find_best_split(hist, jnp.float32(g.sum()), jnp.float32(n),
                               jnp.float32(n), _meta([b], missing=[2]),
                               _hp(min_data_in_leaf=1), jnp.ones(1, bool))
        assert float(info.gain) > 0
        assert bool(info.default_left)  # nan joins the negative side
        assert int(info.threshold) == 0
        assert float(info.left_count) == pytest.approx(200)

    def test_feature_mask(self):
        n, b = 200, 4
        bins0 = (np.arange(n) % b).astype(np.uint8)
        g = np.where(bins0 < 2, -1.0, 1.0).astype(np.float32)
        hist = build_histogram(jnp.asarray(bins0[None]), jnp.asarray(g),
                               jnp.ones(n, jnp.float32),
                               jnp.ones(n, jnp.float32), max_bins=b)
        info = find_best_split(hist, jnp.float32(g.sum()), jnp.float32(n),
                               jnp.float32(n), _meta([b]),
                               _hp(min_data_in_leaf=1),
                               jnp.zeros(1, bool))
        assert float(info.gain) <= 0


class TestGrowTree:
    def _grow(self, bins, g, h, num_leaves=7, **hp_kw):
        f, n = bins.shape
        b = int(bins.max()) + 1
        meta = _meta([b] * f)
        return grow_tree(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                         jnp.ones(n, jnp.float32), jnp.ones(f, bool),
                         meta, _hp(**hp_kw), jnp.int32(-1),
                         num_leaves=num_leaves, max_bins=b)

    def test_perfect_split_tree(self):
        n = 400
        bins = (np.arange(n) % 4).astype(np.uint8)[None, :]
        y = np.array([0.0, 1.0, 2.0, 3.0])[bins[0]].astype(np.float32)
        g = (0.0 - y).astype(np.float32)  # L2 grad at score 0
        rec, row_leaf = self._grow(bins, g, np.ones(n, np.float32),
                                   num_leaves=4, min_data_in_leaf=1)
        assert int(rec.num_leaves) == 4
        # each bin gets its own leaf with value == its label mean
        leaves = np.asarray(row_leaf)
        values = np.asarray(rec.leaf_value)
        for b in range(4):
            leaf_ids = np.unique(leaves[bins[0] == b])
            assert len(leaf_ids) == 1
            assert values[leaf_ids[0]] == pytest.approx(float(b), abs=1e-3)

    def test_gain_ordering_leafwise(self):
        # two features; feature 0 has much higher gain -> split first
        n = 800
        rng = np.random.RandomState(7)
        f0 = rng.randint(0, 2, n).astype(np.uint8)
        f1 = rng.randint(0, 2, n).astype(np.uint8)
        y = 10.0 * f0 + 1.0 * f1
        g = (0.0 - y).astype(np.float32)
        rec, _ = self._grow(np.stack([f0, f1]), g, np.ones(n, np.float32),
                            num_leaves=4, min_data_in_leaf=1)
        assert int(np.asarray(rec.split_feature)[0]) == 0

    def test_stops_when_no_gain(self):
        n = 100
        bins = np.zeros((1, n), np.uint8)  # nothing to split on
        g = np.random.RandomState(8).randn(n).astype(np.float32)
        rec, _ = self._grow(bins, g, np.ones(n, np.float32), num_leaves=8)
        assert int(rec.num_leaves) == 1

    def test_max_depth(self):
        n = 512
        rng = np.random.RandomState(9)
        bins = rng.randint(0, 8, (3, n)).astype(np.uint8)
        y = bins.sum(0).astype(np.float32)
        g = -y
        f, _ = bins.shape
        meta = _meta([8] * f)
        rec, _ = grow_tree(jnp.asarray(bins), jnp.asarray(g),
                           jnp.ones(n, jnp.float32),
                           jnp.ones(n, jnp.float32), jnp.ones(f, bool),
                           meta, _hp(min_data_in_leaf=1), jnp.int32(2),
                           num_leaves=31, max_bins=8)
        # depth <= 2 means at most 4 leaves
        assert int(rec.num_leaves) <= 4

    @pytest.mark.slow
    def test_leaf_counts_sum_to_n(self):
        n = 600
        rng = np.random.RandomState(10)
        bins = rng.randint(0, 16, (4, n)).astype(np.uint8)
        g = rng.randn(n).astype(np.float32)
        rec, row_leaf = self._grow(bins, g, np.ones(n, np.float32),
                                   num_leaves=15, min_data_in_leaf=5)
        counts = np.asarray(rec.leaf_count)
        nl = int(rec.num_leaves)
        assert counts[:nl].sum() == pytest.approx(n)
        # row_leaf consistent with leaf_count
        bc = np.bincount(np.asarray(row_leaf), minlength=15)
        np.testing.assert_allclose(bc[:nl], counts[:nl])

    def test_histogram_subtraction_consistency(self):
        """Grown tree leaf sums must equal direct per-leaf recomputation."""
        n = 500
        rng = np.random.RandomState(11)
        bins = rng.randint(0, 8, (3, n)).astype(np.uint8)
        g = rng.randn(n).astype(np.float32)
        rec, row_leaf = self._grow(bins, g, np.ones(n, np.float32),
                                   num_leaves=8, min_data_in_leaf=10)
        leaves = np.asarray(row_leaf)
        sums = np.asarray(rec.leaf_value)
        nl = int(rec.num_leaves)
        for leaf in range(nl):
            sel = leaves == leaf
            if sel.sum() == 0:
                continue
            expect = -g[sel].sum() / sel.sum()
            assert sums[leaf] == pytest.approx(expect, abs=1e-3)
