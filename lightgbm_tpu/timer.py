"""Named-phase wall-clock timers (ref: Common::Timer / FunctionTimer,
include/LightGBM/utils/common.h:980,1044; global_timer printed at exit
under USE_TIMETAG, src/boosting/gbdt.cpp:29).

Enabled by ``LGBM_TPU_TIMETAG=1`` in the environment or
``global_timer.enable()``; when enabled, a summary prints at interpreter
exit exactly like the reference's atexit dump. ``timed`` phases nest via
a stack so self-time is attributable. jax device work is asynchronous —
phases that must charge device time to themselves should pass
``block=`` the arrays to wait on.
"""

from __future__ import annotations

import atexit
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Any, Dict, Optional


class Timer:
    def __init__(self) -> None:
        self.enabled = os.environ.get("LGBM_TPU_TIMETAG", "") not in ("", "0")
        self._total: Dict[str, float] = defaultdict(float)
        self._count: Dict[str, int] = defaultdict(int)
        self._printed = False

    def enable(self) -> None:
        self.enabled = True

    def reset(self) -> None:
        self._total.clear()
        self._count.clear()

    @contextmanager
    def timed(self, name: str, block: Optional[Any] = None):
        """Time a phase. ``block`` (optional pytree of jax arrays) is
        waited on before the clock stops, so asynchronously-dispatched
        device work is charged to the phase that launched it."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if block is not None:
                import jax
                jax.block_until_ready(block() if callable(block) else block)
            self._total[name] += time.perf_counter() - t0
            self._count[name] += 1

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {name: {"seconds": self._total[name],
                       "count": self._count[name]}
                for name in sorted(self._total)}

    def report(self) -> str:
        lines = ["LightGBM-TPU phase timers:"]
        for name in sorted(self._total, key=self._total.get, reverse=True):
            lines.append(f"  {name:32s} {self._total[name]:10.3f}s "
                         f"x{self._count[name]}")
        return "\n".join(lines)

    def print_at_exit(self) -> None:
        if self.enabled and self._total and not self._printed:
            self._printed = True
            print(self.report(), flush=True)


global_timer = Timer()
atexit.register(global_timer.print_at_exit)
