"""Generate reference-parity golden metrics from the reference CLI.

Builds (if needed) the reference LightGBM CLI from /root/reference via a
shadow source tree (the vendored submodules are absent offline, so small
build shims for fmt / fast_double_parser / Eigen / nanoarrow are injected;
see tools/ref_shims/ in-tree docs), runs each of the five BASELINE example
configs (ref: examples/*/train.conf), parses the final-iteration metrics
from the CLI log, and writes tests/data/reference_golden.json.

The committed JSON is the pinned golden for tests/test_consistency.py —
re-run this script to regenerate it when the reference changes.

Usage: python tools/gen_reference_golden.py [--binary /path/to/lightgbm]
"""

import argparse
import json
import re
import subprocess
import sys
import tempfile
from pathlib import Path

REFERENCE = Path("/root/reference")
REPO = Path(__file__).resolve().parent.parent

CONFIGS = [
    "binary_classification",
    "regression",
    "multiclass_classification",
    "lambdarank",
    "xendcg",
]

# config keys that name input files relative to the example dir
DATA_KEYS = {"data", "valid_data"}


def rewrite_conf(example_dir: Path, out_dir: Path,
                 overrides: dict | None = None) -> Path:
    """Copy train.conf with data paths made absolute; model outputs go to
    the (writable) out_dir. `overrides` force config values (used for the
    deterministic variants: sampling off so RNG streams don't matter)."""
    lines = []
    seen = set()
    for raw in (example_dir / "train.conf").read_text().splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line or "=" not in line:
            continue
        key, val = [t.strip() for t in line.split("=", 1)]
        if key in DATA_KEYS:
            val = str(example_dir / val)
        if key == "output_model":
            val = str(out_dir / val)
        if overrides and key in overrides:
            val = str(overrides[key])
        seen.add(key)
        lines.append(f"{key} = {val}")
    for key, val in (overrides or {}).items():
        if key not in seen:
            lines.append(f"{key} = {val}")
    conf = out_dir / "train.conf"
    conf.write_text("\n".join(lines) + "\n")
    return conf


# deterministic variants: no row/feature sampling, so the only divergence
# between implementations is binning + split math, not RNG streams
DETERMINISTIC_OVERRIDES = {
    "bagging_fraction": 1.0,
    "bagging_freq": 0,
    "feature_fraction": 1.0,
}


# CLI log lines look like:
#   [LightGBM] [Info] Iteration:100, valid_1 auc : 0.812345
#   [LightGBM] [Info] Iteration:100, training binary_logloss : 0.31
_METRIC_RE = re.compile(
    r"Iteration:(\d+), (\S+) (\S+) : ([-+0-9.eEinfan]+)")


def run_and_parse(binary: Path, conf: Path, cwd: Path) -> dict:
    proc = subprocess.run([str(binary), f"config={conf}"], cwd=cwd,
                          capture_output=True, text=True, timeout=1200)
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout + proc.stderr)
        raise RuntimeError(f"reference CLI failed on {conf}")
    metrics = {}  # (dataset, metric) -> value at the LAST logged iteration
    last_iter = {}
    for line in proc.stdout.splitlines():
        m = _METRIC_RE.search(line)
        if not m:
            continue
        it, dataset, metric, value = (int(m.group(1)), m.group(2),
                                      m.group(3), float(m.group(4)))
        key = f"{dataset}:{metric}"
        if it >= last_iter.get(key, -1):
            last_iter[key] = it
            metrics[key] = value
    return {"metrics": metrics, "iterations": max(last_iter.values(), default=0)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--binary", default="/tmp/lgbsrc/lightgbm")
    ap.add_argument("--out", default=str(REPO / "tests/data/reference_golden.json"))
    args = ap.parse_args()

    binary = Path(args.binary)
    if not binary.exists():
        sys.stderr.write(
            f"reference binary not found at {binary}; build it first "
            "(see docstring)\n")
        return 1

    golden = {"source": "reference CLI run on examples/*/train.conf",
              "binary": str(binary), "configs": {}}
    for name in CONFIGS:
        example_dir = REFERENCE / "examples" / name
        with tempfile.TemporaryDirectory() as td:
            out_dir = Path(td)
            conf = rewrite_conf(example_dir, out_dir)
            result = run_and_parse(binary, conf, out_dir)
        golden["configs"][name] = result
        print(f"{name}: {result['metrics']}")
        with tempfile.TemporaryDirectory() as td:
            out_dir = Path(td)
            conf = rewrite_conf(example_dir, out_dir,
                                DETERMINISTIC_OVERRIDES)
            result = run_and_parse(binary, conf, out_dir)
        golden["configs"][name + "_deterministic"] = result
        print(f"{name}_deterministic: {result['metrics']}")

    Path(args.out).write_text(json.dumps(golden, indent=2) + "\n")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
