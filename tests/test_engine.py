"""End-to-end training semantics (ref strategy:
tests/python_package_test/test_engine.py)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from tests.conftest import (make_binary, make_multiclass, make_ranking,
                            make_regression)


def _split(X, y, frac=0.75):
    n = int(len(X) * frac)
    return X[:n], y[:n], X[n:], y[n:]


class TestRegression:
    def test_l2_learning(self):
        X, y = make_regression(1200)
        Xt, yt, Xv, yv = _split(X, y)
        dtrain = lgb.Dataset(Xt, label=yt)
        bst = lgb.train({"objective": "regression", "num_leaves": 31,
                         "learning_rate": 0.1, "min_data_in_leaf": 5,
                         "verbosity": -1},
                        dtrain, num_boost_round=50)
        pred = bst.predict(Xv)
        mse = np.mean((pred - yv) ** 2)
        base = np.mean((yv - yt.mean()) ** 2)
        assert mse < base * 0.2

    def test_l1_objective(self):
        X, y = make_regression(800)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "regression_l1", "num_leaves": 15,
                         "min_data_in_leaf": 5, "verbosity": -1},
                        dtrain, num_boost_round=30)
        mae = np.mean(np.abs(bst.predict(X) - y))
        base = np.mean(np.abs(y - np.median(y)))
        assert mae < base * 0.5

    def test_training_loss_decreases(self):
        X, y = make_regression(600)
        dtrain = lgb.Dataset(X, label=y)
        record = {}
        lgb.train({"objective": "regression", "metric": "l2",
                   "num_leaves": 15, "verbosity": -1,
                   "is_provide_training_metric": True},
                  dtrain, num_boost_round=20,
                  valid_sets=[dtrain], valid_names=["training"],
                  callbacks=[lgb.record_evaluation(record)])
        losses = record["training"]["l2"]
        assert losses[-1] < losses[0] * 0.5
        assert all(b <= a * 1.001 for a, b in zip(losses, losses[1:]))

    def test_poisson(self):
        rng = np.random.RandomState(0)
        X = rng.randn(800, 5)
        y = rng.poisson(np.exp(0.5 * X[:, 0] + 0.2 * X[:, 1])).astype(float)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "poisson", "num_leaves": 15,
                         "verbosity": -1}, dtrain, num_boost_round=40)
        pred = bst.predict(X)
        assert np.all(pred > 0)  # ConvertOutput = exp
        assert np.corrcoef(pred, y)[0, 1] > 0.5


class TestBinary:
    def test_auc_quality(self):
        X, y = make_binary(2000)
        Xt, yt, Xv, yv = _split(X, y)
        dtrain = lgb.Dataset(Xt, label=yt)
        dvalid = lgb.Dataset(Xv, label=yv, reference=dtrain)
        record = {}
        bst = lgb.train({"objective": "binary", "metric": "auc",
                         "num_leaves": 31, "min_data_in_leaf": 5,
                         "verbosity": -1},
                        dtrain, num_boost_round=40, valid_sets=[dvalid],
                        callbacks=[lgb.record_evaluation(record)])
        assert record["valid_0"]["auc"][-1] > 0.92
        pred = bst.predict(Xv)
        assert pred.min() >= 0 and pred.max() <= 1

    def test_boost_from_average_init(self):
        X, y = make_binary(500)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "verbosity": -1},
                        dtrain, num_boost_round=1, )
        # raw prediction at iteration 1 includes the init bias
        raw = bst.predict(X, raw_score=True)
        prior = np.log(y.mean() / (1 - y.mean()))
        assert abs(raw.mean() - prior) < 0.5

    def test_early_stopping(self):
        X, y = make_binary(1500)
        Xt, yt, Xv, yv = _split(X, y)
        dtrain = lgb.Dataset(Xt, label=yt)
        dvalid = lgb.Dataset(Xv, label=yv, reference=dtrain)
        bst = lgb.train({"objective": "binary", "metric": "binary_logloss",
                         "num_leaves": 63, "min_data_in_leaf": 2,
                         "learning_rate": 0.3, "verbosity": -1},
                        dtrain, num_boost_round=200, valid_sets=[dvalid],
                        callbacks=[lgb.early_stopping(5, verbose=False)])
        assert bst.best_iteration < 200

    def test_weights_change_model(self):
        X, y = make_binary(600)
        w = np.where(y > 0, 10.0, 1.0)
        d1 = lgb.Dataset(X, label=y)
        d2 = lgb.Dataset(X, label=y, weight=w)
        p1 = lgb.train({"objective": "binary", "verbosity": -1}, d1,
                       num_boost_round=5).predict(X)
        p2 = lgb.train({"objective": "binary", "verbosity": -1}, d2,
                       num_boost_round=5).predict(X)
        assert p2.mean() > p1.mean()  # upweighted positives -> higher probs


class TestMulticlass:
    def test_softmax(self):
        X, y = make_multiclass(900, k=4)
        Xt, yt, Xv, yv = _split(X, y)
        dtrain = lgb.Dataset(Xt, label=yt)
        bst = lgb.train({"objective": "multiclass", "num_class": 4,
                         "num_leaves": 15, "min_data_in_leaf": 5,
                         "verbosity": -1},
                        dtrain, num_boost_round=18)
        pred = bst.predict(Xv)
        assert pred.shape == (len(Xv), 4)
        np.testing.assert_allclose(pred.sum(1), 1.0, rtol=1e-5)
        acc = (np.argmax(pred, 1) == yv).mean()
        assert acc > 0.8

    def test_ova(self):
        X, y = make_multiclass(900, k=3)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "multiclassova", "num_class": 3,
                         "num_leaves": 15, "verbosity": -1},
                        dtrain, num_boost_round=20)
        pred = bst.predict(X)
        acc = (np.argmax(pred, 1) == y).mean()
        assert acc > 0.85


class TestRanking:
    def test_lambdarank_improves_ndcg(self):
        X, y, group = make_ranking(60, 20)
        dtrain = lgb.Dataset(X, label=y, group=group)
        record = {}
        lgb.train({"objective": "lambdarank", "metric": "ndcg",
                   "eval_at": [5], "num_leaves": 15, "min_data_in_leaf": 2,
                   "verbosity": -1, "is_provide_training_metric": True},
                  dtrain, num_boost_round=30, valid_sets=[dtrain],
                  valid_names=["training"],
                  callbacks=[lgb.record_evaluation(record)])
        ndcgs = record["training"]["ndcg@5"]
        assert ndcgs[-1] > 0.75
        assert ndcgs[-1] > ndcgs[0]

    def test_xendcg(self):
        X, y, group = make_ranking(60, 20)
        dtrain = lgb.Dataset(X, label=y, group=group)
        record = {}
        lgb.train({"objective": "rank_xendcg", "metric": "ndcg",
                   "eval_at": [5], "num_leaves": 15, "min_data_in_leaf": 2,
                   "verbosity": -1, "is_provide_training_metric": True},
                  dtrain, num_boost_round=30, valid_sets=[dtrain],
                  valid_names=["training"],
                  callbacks=[lgb.record_evaluation(record)])
        assert record["training"]["ndcg@5"][-1] > 0.7


class TestSampling:
    def test_bagging(self):
        X, y = make_binary(1000)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "bagging_fraction": 0.5,
                         "bagging_freq": 1, "num_leaves": 15,
                         "verbosity": -1}, dtrain, num_boost_round=20)
        from lightgbm_tpu.metrics import _auc
        assert _auc(y, bst.predict(X)) > 0.85

    def test_goss(self):
        X, y = make_binary(1000)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary",
                         "data_sample_strategy": "goss",
                         "num_leaves": 15, "verbosity": -1},
                        dtrain, num_boost_round=20)
        from lightgbm_tpu.metrics import _auc
        assert _auc(y, bst.predict(X)) > 0.85

    def test_goss_via_boosting_alias(self):
        X, y = make_binary(600)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "boosting": "goss",
                         "num_leaves": 7, "verbosity": -1},
                        dtrain, num_boost_round=5)
        assert bst.num_trees() == 5

    def test_feature_fraction(self):
        X, y = make_binary(800)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "feature_fraction": 0.5,
                         "num_leaves": 15, "verbosity": -1},
                        dtrain, num_boost_round=20)
        from lightgbm_tpu.metrics import _auc
        assert _auc(y, bst.predict(X)) > 0.8


class TestBoostingVariants:
    def test_dart(self):
        X, y = make_regression(600)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "regression", "boosting": "dart",
                         "num_leaves": 15, "verbosity": -1},
                        dtrain, num_boost_round=20)
        mse = np.mean((bst.predict(X) - y) ** 2)
        assert mse < np.var(y) * 0.5

    def test_rf(self):
        X, y = make_binary(800)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "boosting": "rf",
                         "bagging_fraction": 0.7, "bagging_freq": 1,
                         "num_leaves": 31, "min_data_in_leaf": 5,
                         "verbosity": -1},
                        dtrain, num_boost_round=20)
        from lightgbm_tpu.metrics import _auc
        pred = bst.predict(X)
        assert _auc(y, pred) > 0.85
        assert pred.min() >= 0 and pred.max() <= 1


class TestFusedRenewal:
    """Renewing objectives (L1 family) must stay on the fused
    one-XLA-program-per-iteration path (VERDICT r3 #8) and match the
    host-loop renewal exactly."""

    @pytest.mark.parametrize("objective,extra", [
        ("regression_l1", {}),
        ("quantile", {"alpha": 0.7}),
        ("mape", {}),
        ("huber", {}),
    ])
    def test_l1_family_fused_matches_host(self, objective, extra):
        X, y = make_regression(700)
        y = np.abs(y) + 1.0  # mape needs labels away from 0
        params = {"objective": objective, "num_leaves": 15,
                  "min_data_in_leaf": 5, "learning_rate": 0.15,
                  "verbosity": -1, **extra}
        rounds = 8

        bst_fast = lgb.Booster(params, lgb.Dataset(X, label=y))
        for _ in range(rounds):
            bst_fast.update()
        # every iteration must have taken the fused path (one XLA program
        # per iter, zero host round-trips: device records accumulate)
        assert len(bst_fast._gbdt._device_records) == rounds

        bst_host = lgb.Booster(params, lgb.Dataset(X, label=y))
        bst_host._gbdt._fast_path_ok = lambda *a, **k: False
        for _ in range(rounds):
            bst_host.update()
        assert len(bst_host._gbdt._device_records) == 0

        np.testing.assert_allclose(bst_fast.predict(X), bst_host.predict(X),
                                   rtol=2e-4, atol=2e-5)


class TestFusedDart:
    """DART must train as one fused XLA program per iteration (VERDICT r3
    #8): drop selection stays on host (RNG + weight floats only), dropped
    contributions are recomputed on device from the leaf history. Both
    paths share the same host RNG stream, so results must match exactly
    up to f32 rounding."""

    def _train_pair(self, params, X, y, rounds, valid=None):
        def mk():
            ds = lgb.Dataset(X, label=y)
            b = lgb.Booster(params, ds)
            if valid is not None:
                b.add_valid(lgb.Dataset(valid[0], label=valid[1],
                                        reference=ds), "v0")
            return b
        fast = mk()
        for _ in range(rounds):
            fast.update()
        assert len(fast._gbdt._device_records) == rounds, \
            "DART iteration fell off the fused path"
        host = mk()
        host._gbdt._dart_fast_disabled = True
        for _ in range(rounds):
            host.update()
        assert len(host._gbdt._device_records) == 0
        return fast, host

    @pytest.mark.parametrize("mode", [
        {"uniform_drop": True},
        {"uniform_drop": False},
        {"xgboost_dart_mode": True},
    ])
    def test_dart_fused_matches_host(self, mode):
        X, y = make_binary(600)
        params = {"objective": "binary", "boosting": "dart",
                  "num_leaves": 15, "min_data_in_leaf": 5,
                  "drop_rate": 0.4, "max_drop": 5, "learning_rate": 0.2,
                  "verbosity": -1, **mode}
        fast, host = self._train_pair(params, X, y, rounds=10)
        # f32 rounding compounds over drop/re-add cycles; the paths are
        # semantically identical (same RNG stream, same drop decisions)
        np.testing.assert_allclose(fast.predict(X), host.predict(X),
                                   rtol=2e-3, atol=2e-4)

    def test_dart_fused_multiclass_with_valid(self):
        X, y = make_multiclass(600)
        Xv, yv = make_multiclass(300, seed=1)
        params = {"objective": "multiclass", "num_class": 4,
                  "boosting": "dart", "num_leaves": 11,
                  "min_data_in_leaf": 5, "drop_rate": 0.4, "max_drop": 4,
                  "metric": "multi_logloss", "verbosity": -1}
        fast, host = self._train_pair(params, X, y, rounds=6,
                                      valid=(Xv, yv))
        np.testing.assert_allclose(fast.predict(X), host.predict(X),
                                   rtol=5e-4, atol=5e-5)
        # incremental valid scores must agree with the host replay
        ef = {m: v for _, m, v, _ in fast.eval_valid()}
        eh = {m: v for _, m, v, _ in host.eval_valid()}
        assert ef["multi_logloss"] == pytest.approx(eh["multi_logloss"],
                                                    rel=1e-3)

    def test_dart_fused_predict_mid_training(self):
        """Materialize-rebuild: a mid-training predict must not corrupt
        later normalization (factors are retroactive)."""
        X, y = make_regression(500)
        params = {"objective": "regression", "boosting": "dart",
                  "num_leaves": 15, "drop_rate": 0.5, "max_drop": 3,
                  "verbosity": -1}
        oneshot = lgb.Booster(params, lgb.Dataset(X, label=y))
        for _ in range(8):
            oneshot.update()
        paused = lgb.Booster(params, lgb.Dataset(X, label=y))
        for _ in range(4):
            paused.update()
        _ = paused.predict(X)  # forces materialization mid-run
        for _ in range(4):
            paused.update()
        assert len(paused._gbdt._dart_unshrunk) + \
            len(paused._gbdt._device_records) == 8
        np.testing.assert_allclose(paused.predict(X), oneshot.predict(X),
                                   rtol=1e-5, atol=1e-6)

    def test_dart_fused_model_roundtrip(self):
        """Saved model text from the fused path reloads to identical
        predictions (factors baked into leaf values)."""
        from lightgbm_tpu.model_io import load_model_from_string
        X, y = make_regression(500)
        params = {"objective": "regression", "boosting": "dart",
                  "num_leaves": 15, "drop_rate": 0.5, "max_drop": 3,
                  "verbosity": -1}
        bst = lgb.Booster(params, lgb.Dataset(X, label=y))
        for _ in range(8):
            bst.update()
        direct = bst.predict(X)
        loaded = load_model_from_string(bst.model_to_string())
        via_text = np.asarray(loaded.predict_raw(X)).reshape(-1)
        np.testing.assert_allclose(direct, via_text, rtol=1e-4, atol=1e-5)


class TestAPI:
    def test_cv(self):
        X, y = make_binary(600)
        dtrain = lgb.Dataset(X, label=y)
        res = lgb.cv({"objective": "binary", "metric": "auc",
                      "num_leaves": 7, "verbosity": -1},
                     dtrain, num_boost_round=10, nfold=3)
        key = [k for k in res if k.endswith("-mean")][0]
        assert len(res[key]) == 10
        assert res[key][-1] > 0.8

    def test_custom_objective(self):
        X, y = make_regression(500)

        def fobj(preds, dataset):
            labels = np.asarray(dataset.get_label())
            return preds - labels, np.ones_like(preds)

        # custom fobj path through Booster.update (objective=none)
        bst2 = lgb.Booster({"objective": "none", "num_leaves": 15,
                            "verbosity": -1}, lgb.Dataset(X, label=y))
        for _ in range(20):
            bst2.update(fobj=fobj)
        mse = np.mean((bst2.predict(X, raw_score=True) - y) ** 2)
        assert mse < np.var(y) * 0.3

    def test_custom_feval(self):
        X, y = make_binary(400)
        dtrain = lgb.Dataset(X, label=y)
        seen = []

        def feval(preds, dataset):
            seen.append(len(preds))
            return "my_metric", 1.23, True

        record = {}
        lgb.train({"objective": "binary", "metric": "none",
                   "num_leaves": 7, "verbosity": -1},
                  dtrain, num_boost_round=3,
                  valid_sets=[lgb.Dataset(X, label=y, reference=dtrain)],
                  feval=feval, callbacks=[lgb.record_evaluation(record)])
        assert seen
        assert record["valid_0"]["my_metric"] == [1.23] * 3

    def test_feature_importance(self):
        X, y = make_regression(600)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "regression", "num_leaves": 15,
                         "verbosity": -1}, dtrain, num_boost_round=10)
        imp_split = bst.feature_importance("split")
        imp_gain = bst.feature_importance("gain")
        assert imp_split.sum() > 0
        # features 0,1,2 are the signal
        assert imp_gain[:3].sum() > imp_gain[3:].sum()

    def test_reset_parameter_callback(self):
        X, y = make_regression(400)
        dtrain = lgb.Dataset(X, label=y)
        lrs = [0.3, 0.2, 0.1]
        bst = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1}, dtrain, num_boost_round=3,
                        callbacks=[lgb.reset_parameter(learning_rate=lrs)])
        assert bst.num_trees() == 3

    def test_rollback(self):
        X, y = make_regression(300)
        bst = lgb.Booster({"objective": "regression", "num_leaves": 7,
                           "verbosity": -1}, lgb.Dataset(X, label=y))
        for _ in range(3):
            bst.update()
        assert bst.current_iteration() == 3
        bst.rollback_one_iter()
        assert bst.current_iteration() == 2

    def test_pred_leaf(self):
        X, y = make_regression(300)
        bst = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1}, lgb.Dataset(X, label=y),
                        num_boost_round=4)
        leaves = bst.predict(X, pred_leaf=True)
        assert leaves.shape == (300, 4)
        assert leaves.max() < 7

    def test_monotone_constraints(self):
        rng = np.random.RandomState(0)
        X = rng.rand(800, 2)
        y = 2 * X[:, 0] + 0.1 * rng.randn(800)
        bst = lgb.train({"objective": "regression", "num_leaves": 15,
                         "monotone_constraints": [1, 0], "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=20)
        # predictions must be monotone increasing in feature 0
        grid = np.linspace(0.05, 0.95, 20)
        test = np.column_stack([grid, np.full(20, 0.5)])
        pred = bst.predict(test)
        assert np.all(np.diff(pred) >= -1e-6)


class TestCategorical:
    def test_categorical_feature(self):
        rng = np.random.RandomState(1)
        n = 1000
        cat = rng.randint(0, 5, n).astype(np.float64)
        noise = rng.randn(n)
        effect = np.array([0.0, 3.0, -2.0, 5.0, 1.0])
        y = effect[cat.astype(int)] + 0.1 * rng.randn(n)
        X = np.column_stack([cat, noise])
        dtrain = lgb.Dataset(X, label=y, categorical_feature=[0])
        bst = lgb.train({"objective": "regression", "num_leaves": 15,
                         "min_data_in_leaf": 5, "verbosity": -1},
                        dtrain, num_boost_round=30)
        mse = np.mean((bst.predict(X) - y) ** 2)
        assert mse < np.var(y) * 0.1
