"""Deterministic fault injection for the resilience paths.

A recovery path that is never exercised is a recovery path that does
not work. ``FaultPlan`` is a small, fully deterministic schedule of
failures that the tests and ``tools/check_resilience.py`` drive through
the REAL production code paths — no monkeypatched shortcuts:

- ``kill_at_iter=k`` — engine.train treats the boundary after iteration
  k exactly like a SIGTERM: finish the iteration, snapshot, exit with
  ``EXIT_PREEMPTED``.
- ``resize_at_iter=k`` — the same boundary preemption, counted as a
  *resize* event: the supervisor re-runs the command with a different
  ``tpu_num_shards`` and the elastic resume path (resilience/elastic.py)
  restores the checkpoint onto the resized mesh.
- ``corrupt_checkpoint_byte=off`` — after a checkpoint lands on disk,
  flip the byte at offset ``off`` of the payload (validates that the
  digest footer rejects it on load).
- ``poison_labels_at_iter=k`` — overwrite the first label with NaN
  before iteration k trains (drives the obs/health NaN sentinel and the
  interrupt-safety paths with a *realistic* data fault).
- ``slow_iter_ms=m`` (optionally ``slow_shard=ordinal``) — sleep m ms at
  every iteration boundary on the matching process (straggler shape for
  the obs/health skew probes; all processes when ``slow_shard`` unset).
- ``registry_load_failures=n`` — the first n ``ModelRegistry.load``
  calls raise ``TransientServeError`` mid-load (after parsing, before
  registration) — the transactional-registration regression fixture.
- ``serve_predict_failures=n`` — the first n serve dispatches raise
  ``TransientServeError`` before touching the model (drives the
  retry/backoff path and, once retries exhaust, the circuit breaker).
- ``serve_slow_ms=m`` — each serve dispatch sleeps m ms on the executor
  (deterministic queue pressure for the deadline / load-shed tests).
- ``hang_peer_at_iter=k`` (optionally ``hang_peer_s=s``) — the heartbeat
  worker of ``resilience/watchdog.py`` stalls for s seconds (default
  30) at iteration k, simulating a peer hung mid-collective; the
  watchdog deadline must convert the stall into ``PeerLostError``
  instead of waiting it out. The sleep runs on the watchdog's daemon
  thread, so an escalating process still exits cleanly.

Plans parse from the ``LGBM_TPU_FAULTS`` env var (comma-separated
``key=value``) or install programmatically via ``install(plan)``.
Disabled cost: every hook starts with one truthiness check of
``global_faults.armed``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from .errors import TransientServeError

_INT_KEYS = {"kill_at_iter", "resize_at_iter", "corrupt_checkpoint_byte",
             "poison_labels_at_iter", "registry_load_failures",
             "serve_predict_failures", "slow_shard", "hang_peer_at_iter"}
_FLOAT_KEYS = {"slow_iter_ms", "serve_slow_ms", "hang_peer_s"}


class FaultPlan:
    """One deterministic fault schedule. All counters are internal to
    the plan, so installing a fresh plan resets every fault."""

    def __init__(self, **kwargs: Any) -> None:
        self.kill_at_iter: Optional[int] = None
        self.resize_at_iter: Optional[int] = None
        self.corrupt_checkpoint_byte: Optional[int] = None
        self.poison_labels_at_iter: Optional[int] = None
        self.slow_iter_ms: float = 0.0
        self.slow_shard: Optional[int] = None
        self.registry_load_failures: int = 0
        self.serve_predict_failures: int = 0
        self.serve_slow_ms: float = 0.0
        self.hang_peer_at_iter: Optional[int] = None
        self.hang_peer_s: float = 30.0
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise ValueError(f"unknown fault knob {key!r}")
            setattr(self, key, value)
        self._lock = threading.Lock()
        self._fired: Dict[str, int] = {}

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse ``"kill_at_iter=4,serve_slow_ms=20"``."""
        kwargs: Dict[str, Any] = {}
        for tok in str(spec).split(","):
            tok = tok.strip()
            if not tok:
                continue
            if "=" not in tok:
                raise ValueError(f"fault spec token {tok!r} is not "
                                 "key=value")
            key, value = tok.split("=", 1)
            key = key.strip()
            if key in _INT_KEYS:
                kwargs[key] = int(value)
            elif key in _FLOAT_KEYS:
                kwargs[key] = float(value)
            else:
                raise ValueError(f"unknown fault knob {key!r}")
        return cls(**kwargs)

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        spec = os.environ.get("LGBM_TPU_FAULTS", "")
        return cls.from_spec(spec) if spec else None

    # ------------------------------------------------------------------
    def _note(self, kind: str) -> None:
        with self._lock:
            self._fired[kind] = self._fired.get(kind, 0) + 1
        from ..obs.metrics import global_metrics
        global_metrics.inc_counter("resilience/fault_injections")
        global_metrics.inc_counter(f"resilience/fault_{kind}")
        from ..obs.flightrec import global_flightrec
        if global_flightrec.armed:
            # the black box records every injected fault so a postmortem
            # distinguishes induced failures from organic ones
            global_flightrec.record("fault_injection", fault=kind)

    def fired(self, kind: str) -> int:
        with self._lock:
            return self._fired.get(kind, 0)

    def _take(self, budget_attr: str) -> bool:
        """Atomically consume one failure from a counted budget."""
        with self._lock:
            left = int(getattr(self, budget_attr))
            if left <= 0:
                return False
            setattr(self, budget_attr, left - 1)
        return True

    # -- hooks (each called from exactly one production site) ----------
    def kill_now(self, iteration: int) -> bool:
        """True at the boundary after `iteration` when the plan says to
        simulate preemption there (once). ``resize_at_iter`` is the same
        engine-boundary preemption, noted as a *resize* event: the
        supervisor (tools/check_continual.py, tests) re-runs the command
        on a different ``tpu_num_shards`` so kill -> resume-on-resized-
        mesh is a deterministic chaos scenario."""
        if self.kill_at_iter is not None and \
                iteration == self.kill_at_iter:
            self.kill_at_iter = None  # one shot — the resumed run
            self._note("kill")        # survives
            return True
        if self.resize_at_iter is not None and \
                iteration == self.resize_at_iter:
            self.resize_at_iter = None
            self._note("resize")
            return True
        return False

    def maybe_corrupt_checkpoint(self, path: str) -> bool:
        """Flip one payload byte of the checkpoint just written."""
        off = self.corrupt_checkpoint_byte
        if off is None:
            return False
        self.corrupt_checkpoint_byte = None
        with open(path, "r+b") as fh:
            fh.seek(int(off))
            byte = fh.read(1)
            fh.seek(int(off))
            fh.write(bytes([(byte[0] ^ 0xFF) if byte else 0xFF]))
        self._note("corrupt_checkpoint")
        return True

    def maybe_poison_labels(self, booster, iteration: int) -> bool:
        """NaN-poison the first label before `iteration` trains."""
        if self.poison_labels_at_iter is None or \
                iteration != self.poison_labels_at_iter:
            return False
        self.poison_labels_at_iter = None
        obj = getattr(getattr(booster, "_gbdt", None), "objective", None)
        if obj is None or getattr(obj, "label", None) is None:
            return False
        import jax.numpy as jnp
        obj.label = obj.label.at[0].set(jnp.nan)
        if getattr(obj, "label_np", None) is not None:
            obj.label_np = obj.label_np.copy()
            obj.label_np[0] = float("nan")
        self._note("poison_labels")
        return True

    def maybe_slow_iteration(self) -> None:
        if self.slow_iter_ms <= 0:
            return
        if self.slow_shard is not None:
            try:
                import jax
                if jax.process_index() != int(self.slow_shard):
                    return
            except Exception:
                return
        self._note("slow_iter")
        time.sleep(self.slow_iter_ms / 1e3)

    def check_registry_load(self, name: str) -> None:
        if self._take("registry_load_failures"):
            self._note("registry_load")
            raise TransientServeError(
                f"injected registry load failure for model {name!r}")

    def check_serve_dispatch(self, name: str) -> None:
        if self.serve_slow_ms > 0:
            self._note("serve_slow")
            time.sleep(self.serve_slow_ms / 1e3)
        if self._take("serve_predict_failures"):
            self._note("serve_predict")
            raise TransientServeError(
                f"injected predict failure for model {name!r}")

    def maybe_hang_peer(self, iteration: int) -> None:
        """Stall the watchdog heartbeat at iteration `iteration` as if a
        peer hung mid-collective. Called from the watchdog's daemon
        heartbeat thread, never the main thread — the main thread's
        deadline keeps ticking and must fire while this sleeps."""
        if self.hang_peer_at_iter is None or \
                iteration != self.hang_peer_at_iter:
            return
        self.hang_peer_at_iter = None  # one shot
        self._note("hang_peer")
        time.sleep(max(0.0, self.hang_peer_s))


class _NoFaults:
    """The disabled plan: armed=False, every hook a no-op."""

    armed = False

    def kill_now(self, iteration: int) -> bool:
        return False

    def maybe_corrupt_checkpoint(self, path: str) -> bool:
        return False

    def maybe_poison_labels(self, booster, iteration: int) -> bool:
        return False

    def maybe_slow_iteration(self) -> None:
        pass

    def check_registry_load(self, name: str) -> None:
        pass

    def check_serve_dispatch(self, name: str) -> None:
        pass

    def maybe_hang_peer(self, iteration: int) -> None:
        pass


FaultPlan.armed = True  # any real plan is armed
_DISABLED = _NoFaults()
global_faults = FaultPlan.from_env() or _DISABLED


def install(plan: Optional[FaultPlan]):
    """Install `plan` as the process-wide fault schedule (None resets
    to disabled). Returns the active plan."""
    global global_faults
    global_faults = plan if plan is not None else _DISABLED
    return global_faults


def reset() -> None:
    install(None)
