// Build shim for the vendored {fmt} (submodule not present in this offline
// environment). LightGBM uses exactly one entry point:
// fmt::format_to_n(buffer, n, format, value) with formats "{}", "{:g}",
// "{:.17g}" (utils/common.h format_to_buf). snprintf equivalents are exact
// for these cases ("%.17g" round-trips doubles; "%g" matches "{:g}").
#ifndef FMT_FORMAT_SHIM_H_
#define FMT_FORMAT_SHIM_H_

#include <cstdio>
#include <cstring>
#include <string>
#include <type_traits>

namespace fmt {

struct format_to_n_result {
  char* out;
  size_t size;
};

template <typename T>
inline format_to_n_result format_to_n(char* buffer, size_t n,
                                      const char* format, T value) {
  int written = 0;
  if (std::strstr(format, ".17g") != nullptr) {
    written = std::snprintf(buffer, n, "%.17g", static_cast<double>(value));
  } else if (std::strchr(format, 'g') != nullptr) {
    written = std::snprintf(buffer, n, "%g", static_cast<double>(value));
  } else if (std::is_floating_point<T>::value) {
    written = std::snprintf(buffer, n, "%.17g", static_cast<double>(value));
  } else if (std::is_signed<T>::value) {
    written = std::snprintf(buffer, n, "%lld",
                            static_cast<long long>(value));
  } else {
    written = std::snprintf(buffer, n, "%llu",
                            static_cast<unsigned long long>(value));
  }
  size_t size = written < 0 ? n : static_cast<size_t>(written);
  return {buffer + (size < n ? size : n), size};
}

}  // namespace fmt

#endif  // FMT_FORMAT_SHIM_H_
