"""Voting-parallel tree learner — explicit shard_map collectives.

TPU-native PV-tree (ref: src/treelearner/voting_parallel_tree_learner.cpp,
parallel_tree_learner.h:127). Rows are sharded over the mesh "data" axis;
histograms stay LOCAL to each shard. Per leaf, every shard proposes its
top-k features by local gain (the "vote",
voting_parallel_tree_learner.cpp:353-373 MaxK + Allgather), a global vote
picks 2k candidate features (GlobalVoting, :152), and ONLY those
candidates' histograms are summed across shards (:396) — ICI traffic per
split drops from O(F * B) to O(W * k + 2k * B), the same bandwidth
reduction PV-tree buys over plain data-parallel.

Collectives used (all over ICI via shard_map):
  psum        — root/candidate histogram reduction (HistogramSumReducer)
  all_gather  — top-k vote exchange (SyncUpGlobalBestSplit's Allgather)
  psum_scatter— hist_reduce="scatter": each shard reduces only its owned
                slice of the candidate axis (ReduceScatter,
                data_parallel_tree_learner.cpp:287) and searches it; one
                SplitInfo all_gather + argmax picks the winner. Another
                W-fold cut on the already-voted candidate traffic,
                bit-identical to the psum path (see parallel/scatter.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..learner import TreeArrays, _LeafSplits, _store_split
from ..obs import health as obs_health
from ..obs import xla as obs_xla
from ..ops import histogram as hist_ops
from ..ops import partition as part_ops
from ..ops import split as split_ops
from ..ops.histogram import COUNT, GRAD, HESS
from ..ops.split import (FeatureMeta, K_MIN_SCORE, SplitHyperParams,
                         find_best_split, leaf_output, per_feature_best_gain,
                         propagate_monotone_bounds)
from . import mesh as mesh_lib
from .scatter import allgather_argmax_best


def _local_leaf_sums(local_hist: jax.Array):
    """This shard's (grad, hess, count) sums for a leaf, from its local
    histogram: feature 0's bins partition all local rows."""
    s = jnp.sum(local_hist[0], axis=0)
    return s[GRAD], s[HESS], s[COUNT]


def _vote_and_reduce(local_hist, pg, ph, pc, parent_out, min_b, max_b,
                     depth, meta, hp, feature_mask, *,
                     num_candidates: int, top_k: int, axis_name: str,
                     has_categorical: bool = True, loop_factor: int = 1,
                     hist_reduce: str = "psum", num_shards: int = 1):
    """One voting round for one leaf: local top-k proposal -> global vote
    -> candidate-only histogram psum -> global best split.

    local_hist: [F, B, 3] this shard's histogram for the leaf.
    pg/ph/pc: GLOBAL leaf sums (replicated). Returns a SplitInfo whose
    `feature` is a real feature index.

    loop_factor: static trip count of the enclosing ``lax.scan`` (the
    per-split step body) — the health wrappers attribute this many
    issued collectives per program run, so the runtime byte/call
    counters match what the ICI actually carries.
    """
    lg, lh, lc = _local_leaf_sums(local_hist)
    local_gain = per_feature_best_gain(local_hist, lg, lh, lc, meta, hp,
                                       feature_mask, parent_out,
                                       min_b, max_b, depth,
                                       has_categorical)  # [F]
    num_features = local_gain.shape[0]

    # --- vote: each shard proposes its top-k features
    _, prop = lax.top_k(local_gain, top_k)                    # [k]
    all_props = obs_health.all_gather(
        prop, axis_name, tag="vote/all_gather",
        loop_factor=loop_factor).reshape(-1)                   # [W*k]
    votes = jnp.zeros((num_features,), jnp.float32).at[all_props].add(1.0)
    # tie-break votes by the summed local gains (deterministic; the
    # reference breaks ties arbitrarily by machine order)
    gain_sum = obs_health.psum(jnp.maximum(local_gain, K_MIN_SCORE * 1e-3),
                               axis_name, tag="vote/psum_gain",
                               loop_factor=loop_factor)
    norm = jnp.max(jnp.abs(gain_sum)) + 1.0
    _, cand = lax.top_k(votes + gain_sum / (norm * 4.0), num_candidates)
    cand = cand.astype(jnp.int32)                              # [C]

    # --- reduce only the candidates' histograms (ref: :396)
    cand_meta = jax.tree_util.tree_map(lambda a: a[cand], meta)
    if hist_reduce == "scatter" and num_shards > 1:
        # ReduceScatter over the candidate axis: each shard owns a
        # contiguous slice of C, embeds it back at its global offset in
        # an all-zero [C, B, 3] (the ORACLE's shape, so XLA emits the
        # same split-search arithmetic bit for bit), searches with
        # non-owned candidates masked off, and one SplitInfo-sized
        # all_gather + first-max argmax recovers exactly the psum
        # winner (see parallel/scatter.py for the parity argument).
        w = num_shards
        c_pad = -(-num_candidates // w) * w
        cand_padded = jnp.pad(cand, (0, c_pad - num_candidates),
                              mode="edge")
        part = obs_health.psum_scatter(
            local_hist[cand_padded], axis_name, tag="hist/psum_scatter",
            loop_factor=loop_factor, scatter_dimension=0)
        c_loc = c_pad // w
        idx = lax.axis_index(axis_name)
        full = lax.dynamic_update_slice(
            jnp.zeros((c_pad,) + part.shape[1:], part.dtype), part,
            (idx * c_loc, jnp.int32(0), jnp.int32(0)))[:num_candidates]
        slot = jnp.arange(num_candidates, dtype=jnp.int32)
        owned = (slot >= idx * c_loc) & (slot < (idx + 1) * c_loc)
        info = find_best_split(full, pg, ph, pc, cand_meta, hp,
                               feature_mask[cand] & owned, parent_out,
                               min_b, max_b, depth, has_categorical)
        info = allgather_argmax_best(info, axis_name,
                                     tag="split/allgather_best",
                                     loop_factor=loop_factor)
    else:
        cand_hist = obs_health.psum(local_hist[cand], axis_name,
                                    tag="vote/psum_hist",
                                    loop_factor=loop_factor)  # [C, B, 3]
        info = find_best_split(cand_hist, pg, ph, pc, cand_meta, hp,
                               feature_mask[cand], parent_out, min_b,
                               max_b, depth, has_categorical)
    return info._replace(feature=cand[info.feature])


def grow_tree_voting(bins_fm, grad, hess, sample_mask, feature_mask,
                     meta: FeatureMeta, hp: SplitHyperParams, max_depth,
                     *, num_leaves: int, max_bins: int, top_k: int,
                     axis_name: str = mesh_lib.DATA_AXIS,
                     hist_dtype=jnp.float32, hist_impl: str = "xla",
                     has_categorical: bool = True,
                     mono_pairwise: bool = False,
                     hist_deterministic: bool = False,
                     hist_reduce: str = "psum", num_shards: int = 1):
    """Grow one tree with voting-parallel split search. Runs INSIDE
    shard_map: all row-indexed inputs are this shard's slice; returned
    TreeArrays are replicated, row_leaf is the local slice.

    mono_pairwise: exact pairwise leaf-box monotone bounds
    (monotone_constraints_method intermediate/advanced). The [L, F] box
    state is replicated across shards — every shard runs the identical
    deterministic update, so no extra collective is needed (the
    reference's constraint factory is likewise learner-agnostic,
    monotone_constraints.hpp:330)."""
    num_data = bins_fm.shape[1]
    num_features = bins_fm.shape[0]
    L = num_leaves
    f32 = hist_dtype
    C = min(2 * top_k, num_features)
    k_eff = min(top_k, num_features)

    build = functools.partial(hist_ops.build_histogram, max_bins=max_bins,
                              dtype=f32, row_chunk=0, impl=hist_impl,
                              deterministic=hist_deterministic)
    vote = functools.partial(_vote_and_reduce, meta=meta, hp=hp,
                             feature_mask=feature_mask, num_candidates=C,
                             top_k=k_eff, axis_name=axis_name,
                             has_categorical=has_categorical,
                             hist_reduce=hist_reduce, num_shards=num_shards)

    # --- root: local histogram; global sums by psum (ref: data_parallel
    # root Allreduce, data_parallel_tree_learner.cpp:170)
    root_hist = build(bins_fm, grad, hess, sample_mask)
    root_g, root_h, root_c = obs_health.psum(
        (jnp.sum(grad * sample_mask, dtype=f32),
         jnp.sum(hess * sample_mask, dtype=f32),
         jnp.sum(sample_mask, dtype=f32)),
        axis_name, tag="root/psum")
    root_out = leaf_output(root_g, root_h, hp)
    neg_inf, pos_inf = jnp.float32(-jnp.inf), jnp.float32(jnp.inf)
    root_split = vote(root_hist, root_g, root_h, root_c, root_out,
                      neg_inf, pos_inf, jnp.int32(0))

    zero_l = jnp.zeros((L,), f32)
    leaves = _LeafSplits(
        sum_grad=zero_l, sum_hess=zero_l, count=zero_l,
        depth=jnp.zeros((L,), jnp.int32), output=zero_l,
        gain=jnp.full((L,), K_MIN_SCORE, f32),
        feature=jnp.zeros((L,), jnp.int32),
        threshold=jnp.zeros((L,), jnp.int32),
        default_left=jnp.zeros((L,), jnp.bool_),
        left_sum_grad=zero_l, left_sum_hess=zero_l, left_count=zero_l,
        left_output=zero_l, right_output=zero_l,
        cat_mask=jnp.zeros((L, max_bins), jnp.bool_),
        min_bound=jnp.full((L,), -jnp.inf, f32),
        max_bound=jnp.full((L,), jnp.inf, f32),
    )
    leaves = _store_split(leaves, 0, root_split, jnp.int32(1), root_out,
                          root_g, root_h, root_c, neg_inf, pos_inf, True)

    pool = jnp.zeros((L, num_features, max_bins,
                      hist_ops.NUM_HIST_CHANNELS), f32)
    pool = pool.at[0].set(root_hist)
    row_leaf0 = jnp.zeros((num_data,), jnp.int32)
    box_lo0 = (jnp.zeros((L, num_features), jnp.int32)
               if mono_pairwise else None)
    box_hi0 = (jnp.full((L, num_features), max_bins - 1, jnp.int32)
               if mono_pairwise else None)

    def step(carry, step_idx):
        row_leaf, pool, leaves, box_lo, box_hi = carry
        best_leaf = jnp.argmax(leaves.gain).astype(jnp.int32)
        valid = leaves.gain[best_leaf] > 0.0
        new_leaf = (step_idx + 1).astype(jnp.int32)

        feat = leaves.feature[best_leaf]
        thr = leaves.threshold[best_leaf]
        dleft = leaves.default_left[best_leaf]
        cmask = leaves.cat_mask[best_leaf]

        row_leaf = part_ops.apply_split(
            row_leaf, bins_fm, best_leaf, new_leaf, feat, thr, dleft, cmask,
            meta.num_bins, meta.missing_type, meta.is_categorical, valid)

        # global child sums come from the stored (globally-reduced) split
        lg = leaves.left_sum_grad[best_leaf]
        lh = leaves.left_sum_hess[best_leaf]
        lc = leaves.left_count[best_leaf]
        pg, ph, pc = (leaves.sum_grad[best_leaf],
                      leaves.sum_hess[best_leaf], leaves.count[best_leaf])
        rg, rh, rc = pg - lg, ph - lh, pc - lc

        # local histograms: build smaller child locally, subtract
        left_smaller = lc <= rc
        small_id = jnp.where(left_smaller, best_leaf, new_leaf)
        small_mask = sample_mask * (row_leaf == small_id) * valid
        small_hist = build(bins_fm, grad, hess, small_mask)
        parent_hist = pool[best_leaf]
        large_hist = hist_ops.subtract_histogram(parent_hist, small_hist)
        left_hist = jnp.where(left_smaller, small_hist, large_hist)
        right_hist = jnp.where(left_smaller, large_hist, small_hist)
        pool = pool.at[best_leaf].set(
            jnp.where(valid, left_hist, parent_hist))
        pool = pool.at[new_leaf].set(
            jnp.where(valid, right_hist, pool[new_leaf]))

        parent_out = leaves.output[best_leaf]
        p_minb = leaves.min_bound[best_leaf]
        p_maxb = leaves.max_bound[best_leaf]
        out_l = leaves.left_output[best_leaf]
        out_r = leaves.right_output[best_leaf]

        if mono_pairwise:
            # bounds may have tightened after OTHER leaves split since
            # this candidate was stored (ref: RecomputeConstraintsIfNeeded
            # monotone_constraints.hpp:52) — re-clip, then refresh all
            # leaves' pairwise box bounds
            out_l = jnp.clip(out_l, p_minb, p_maxb)
            out_r = jnp.clip(out_r, p_minb, p_maxb)
            box_lo, box_hi = split_ops.split_child_boxes(
                box_lo, box_hi, best_leaf, new_leaf, feat, thr,
                meta.is_categorical[feat], valid)
            out_now = leaves.output.at[best_leaf].set(
                jnp.where(valid, out_l, parent_out))
            out_now = out_now.at[new_leaf].set(
                jnp.where(valid, out_r,
                          out_now[jnp.minimum(new_leaf, L - 1)]))
            # validity is monotone here (no forced-split revival): after
            # a valid step leaves 0..new_leaf are in use
            leaf_in_use = jnp.arange(L, dtype=jnp.int32) <= \
                jnp.where(valid, new_leaf, step_idx)
            minb_all, maxb_all = split_ops.compute_box_bounds(
                box_lo, box_hi, out_now, leaf_in_use, meta.monotone)
            leaves = leaves._replace(
                min_bound=jnp.where(valid, minb_all, leaves.min_bound),
                max_bound=jnp.where(valid, maxb_all, leaves.max_bound))
            l_min, l_max = minb_all[best_leaf], maxb_all[best_leaf]
            r_min, r_max = minb_all[new_leaf], maxb_all[new_leaf]
        else:
            l_min, l_max, r_min, r_max = propagate_monotone_bounds(
                out_l, out_r, meta.monotone[feat].astype(jnp.int32),
                meta.is_categorical[feat], p_minb, p_maxb)

        child_depth = leaves.depth[best_leaf] + 1
        pen_depth = child_depth - 1
        # inside the L-1-trip split scan: traced once, issued L-1 times
        split_l = vote(left_hist, lg, lh, lc, out_l, l_min, l_max,
                       pen_depth, loop_factor=L - 1)
        split_r = vote(right_hist, rg, rh, rc, out_r, r_min, r_max,
                       pen_depth, loop_factor=L - 1)
        depth_ok = (max_depth <= 0) | (child_depth < max_depth)
        split_l = split_l._replace(
            gain=jnp.where(depth_ok, split_l.gain, K_MIN_SCORE))
        split_r = split_r._replace(
            gain=jnp.where(depth_ok, split_r.gain, K_MIN_SCORE))

        chosen_gain = leaves.gain[best_leaf]
        leaves = _store_split(leaves, best_leaf, split_l, child_depth,
                              out_l, lg, lh, lc, l_min, l_max, valid)
        leaves = _store_split(leaves, new_leaf, split_r, child_depth,
                              out_r, rg, rh, rc, r_min, r_max, valid)

        record = dict(
            split_leaf=jnp.where(valid, best_leaf, -1),
            split_feature=feat,
            split_bin_threshold=thr,
            split_default_left=dleft,
            split_gain=jnp.where(valid, chosen_gain, 0.0),
            split_cat_mask=cmask,
            internal_value=parent_out,
            internal_weight=ph,
            internal_count=pc,
        )
        return (row_leaf, pool, leaves, box_lo, box_hi), record

    (row_leaf, pool, leaves, _, _), records = lax.scan(
        step, (row_leaf0, pool, leaves, box_lo0, box_hi0),
        jnp.arange(L - 1, dtype=jnp.int32), unroll=2 if L > 2 else 1)

    num_leaves_out = 1 + jnp.sum(records["split_leaf"] >= 0).astype(
        jnp.int32)
    tree = TreeArrays(
        split_leaf=records["split_leaf"],
        split_feature=records["split_feature"],
        split_bin_threshold=records["split_bin_threshold"],
        split_default_left=records["split_default_left"],
        split_gain=records["split_gain"],
        split_cat_mask=records["split_cat_mask"],
        internal_value=records["internal_value"],
        internal_weight=records["internal_weight"],
        internal_count=records["internal_count"],
        leaf_value=leaves.output,
        leaf_weight=leaves.sum_hess,
        leaf_count=leaves.count,
        num_leaves=num_leaves_out,
    )
    return tree, row_leaf


def make_sharded_voting_grow(mesh, *, num_leaves: int, max_bins: int,
                             top_k: int, hist_impl: str = "xla",
                             has_categorical: bool = True,
                             mono_pairwise: bool = False,
                             hist_deterministic: bool = False,
                             hist_reduce: str = "psum"):
    """jit(shard_map(grow_tree_voting)): rows sharded over "data",
    everything else replicated; tree replicated out, row_leaf sharded."""
    grow = functools.partial(grow_tree_voting, num_leaves=num_leaves,
                             max_bins=max_bins, top_k=top_k,
                             hist_impl=hist_impl,
                             has_categorical=has_categorical,
                             mono_pairwise=mono_pairwise,
                             hist_deterministic=hist_deterministic,
                             hist_reduce=hist_reduce,
                             num_shards=int(mesh.shape[mesh_lib.DATA_AXIS]))
    data = P(None, mesh_lib.DATA_AXIS)   # bins [F, N]
    rows = P(mesh_lib.DATA_AXIS)         # [N]
    rep = P()
    meta_spec = FeatureMeta(*([rep] * len(FeatureMeta._fields)))
    hp_spec = SplitHyperParams(*([rep] * len(SplitHyperParams._fields)))
    tree_spec = TreeArrays(*([rep] * len(TreeArrays._fields)))
    sharded = mesh_lib.shard_map(
        grow, mesh=mesh,
        in_specs=(data, rows, rows, rows, rep, meta_spec, hp_spec, rep),
        out_specs=(tree_spec, rows))
    # instrumented program boundary: recompile attribution + the health
    # manifest that attributes this program's collectives per call
    return obs_xla.instrumented_jit("parallel/voting_grow", sharded,
                                    phase="grow")
