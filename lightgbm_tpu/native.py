"""ctypes binding to the native host runtime (parser + binning).

(ref: the reference's C++ IO layer — src/io/parser.hpp, src/io/bin.cpp;
here a small C-ABI .so built from native/src/lgbm_tpu_native.cpp.)
The library is built on demand with g++ on first import (cached next to
the package); every entry point has a NumPy fallback, so the framework
works even where no C++ toolchain exists. `LIGHTGBM_TPU_NO_NATIVE=1`
disables the native path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC_PATH = os.path.join(os.path.dirname(_PKG_DIR), "native", "src",
                         "lgbm_tpu_native.cpp")


def _host_isa_tag() -> str:
    """A stable fingerprint of this host's ISA. The library filename is
    tagged with it, so a package directory shared between CPUs with
    different features (NFS homes, copied venvs) keeps one -march=native
    build per host class instead of thrashing one file (and never loads
    a library containing another host's illegal instructions)."""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    import hashlib
                    return hashlib.sha256(
                        " ".join(sorted(line.split()[2:])).encode()
                    ).hexdigest()[:16]
    except OSError:
        pass
    import platform
    return platform.machine()


_LIB_NAME = f"liblgbm_tpu_native.{_host_isa_tag()}.so"
_LIB_PATH = os.path.join(_PKG_DIR, _LIB_NAME)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    if not os.path.exists(_SRC_PATH):
        return False
    # build to a unique temp path, then atomically install: a concurrent
    # importer never dlopens a half-written library
    tmp_path = f"{_LIB_PATH}.build.{os.getpid()}"
    try:
        args = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
                "-march=native", _SRC_PATH, "-o", tmp_path]
        try:
            subprocess.run(args, check=True, capture_output=True,
                           timeout=120)
        except subprocess.CalledProcessError as exc:
            # retry portably only when the flag itself was the problem
            msg = (exc.stderr or b"").decode(errors="replace")
            if "march" not in msg and "arch" not in msg:
                return False
            subprocess.run([a for a in args if a != "-march=native"],
                           check=True, capture_output=True, timeout=120)
        os.replace(tmp_path, _LIB_PATH)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return False
    finally:
        try:
            if os.path.exists(tmp_path):
                os.remove(tmp_path)
        except OSError:
            pass


def _cached_lib_stale() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    return os.path.exists(_SRC_PATH) and \
        os.path.getmtime(_SRC_PATH) > os.path.getmtime(_LIB_PATH)


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it if needed; None if
    unavailable (callers fall back to NumPy)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("LIGHTGBM_TPU_NO_NATIVE"):
            return None
        if _cached_lib_stale():
            if not _build() and not os.path.exists(_LIB_PATH):
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.LGT_ParseFile.restype = ctypes.c_void_p
        lib.LGT_ParseFile.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                      ctypes.c_int]
        lib.LGT_ParseNumRows.restype = ctypes.c_int64
        lib.LGT_ParseNumRows.argtypes = [ctypes.c_void_p]
        lib.LGT_ParseNumCols.restype = ctypes.c_int32
        lib.LGT_ParseNumCols.argtypes = [ctypes.c_void_p]
        lib.LGT_ParseError.restype = ctypes.c_char_p
        lib.LGT_ParseError.argtypes = [ctypes.c_void_p]
        lib.LGT_ParseCopy.restype = None
        lib.LGT_ParseCopy.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_void_p]
        lib.LGT_ParseFree.restype = None
        lib.LGT_ParseFree.argtypes = [ctypes.c_void_p]
        lib.LGT_FindNumericalBounds.restype = ctypes.c_int32
        lib.LGT_FindNumericalBounds.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p]
        lib.LGT_TransformColumn.restype = None
        lib.LGT_TransformColumn.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_int, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_void_p]
        lib.LGT_TransformMatrix.restype = None
        lib.LGT_TransformMatrix.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
        try:
            lib.LGT_TransformMatrix2.restype = None
            lib.LGT_TransformMatrix2.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
        except AttributeError:
            pass  # stale pre-v2 .so; transform_matrix falls back
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


# ----------------------------------------------------------------------
def parse_file(path: str, label_idx: int = 0, has_header: bool = False
               ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Parse CSV/TSV/LibSVM -> (data [N, F] f64, label [N] f64), or None
    if the native library is unavailable. Raises ValueError on malformed
    input."""
    lib = get_lib()
    if lib is None:
        return None
    handle = lib.LGT_ParseFile(path.encode(), int(label_idx),
                               int(bool(has_header)))
    try:
        err = lib.LGT_ParseError(handle)
        if err:
            raise ValueError(err.decode())
        n = lib.LGT_ParseNumRows(handle)
        f = lib.LGT_ParseNumCols(handle)
        data = np.empty((n, f), np.float64)
        label = np.empty(n, np.float64)
        lib.LGT_ParseCopy(handle, data.ctypes.data, label.ctypes.data)
        return data, label
    finally:
        lib.LGT_ParseFree(handle)


def find_numerical_bounds(values: np.ndarray, max_bin: int,
                          min_data_in_bin: int, missing_type: int,
                          zero_as_missing: bool) -> Optional[np.ndarray]:
    """Numerical bin upper bounds (zero-as-one-bin semantics), or None."""
    lib = get_lib()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, np.float64)
    out = np.empty(max_bin + 2, np.float64)
    nb = lib.LGT_FindNumericalBounds(
        values.ctypes.data, len(values), int(max_bin),
        int(min_data_in_bin), int(missing_type), int(bool(zero_as_missing)),
        out.ctypes.data)
    if nb < 0:
        return None
    return out[:nb].copy()


def transform_column(values: np.ndarray, bounds: np.ndarray,
                     missing_type: int, default_bin: int, num_bins: int
                     ) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, np.float64)
    bounds = np.ascontiguousarray(bounds, np.float64)
    out = np.empty(len(values), np.int32)
    lib.LGT_TransformColumn(values.ctypes.data, len(values),
                            bounds.ctypes.data, len(bounds),
                            int(missing_type), int(default_bin),
                            int(num_bins), out.ctypes.data)
    return out


def transform_matrix(data: np.ndarray, mappers, dtype) -> Optional[np.ndarray]:
    """Bin all numerical columns at once (threaded). `data` is
    [N, F_used] with columns already gathered; any categorical mapper
    columns must be handled by the caller. Returns [F_used, N].

    The v2 kernel consumes float32/float64 in row- or column-major order
    directly — at Higgs scale the old mandatory float64 column-major
    copy cost more than the binning itself."""
    lib = get_lib()
    if lib is None:
        return None
    n, f = data.shape
    if any(m.is_categorical or m.bin_upper_bound is None for m in mappers):
        return None
    offsets = np.zeros(f + 1, np.int64)
    for j, m in enumerate(mappers):
        offsets[j + 1] = offsets[j] + len(m.bin_upper_bound)
    bounds_flat = np.concatenate([m.bin_upper_bound for m in mappers]) \
        .astype(np.float64)
    missing = np.array([m.missing_type for m in mappers], np.int32)
    default = np.array([m.default_bin for m in mappers], np.int32)
    nbins = np.array([m.num_bins for m in mappers], np.int32)
    elem = np.dtype(dtype).itemsize
    out = np.empty((f, n), dtype=dtype)
    if hasattr(lib, "LGT_TransformMatrix2"):
        if data.dtype not in (np.float32, np.float64) or not (
                data.flags["C_CONTIGUOUS"] or data.flags["F_CONTIGUOUS"]):
            data = np.ascontiguousarray(data, np.float64)
        row_major = 1 if data.flags["C_CONTIGUOUS"] else 0
        lib.LGT_TransformMatrix2(
            data.ctypes.data, int(data.dtype == np.float32), row_major,
            n, f, bounds_flat.ctypes.data, offsets.ctypes.data,
            missing.ctypes.data, default.ctypes.data, nbins.ctypes.data,
            elem, out.ctypes.data)
        return out
    data_cm = np.asfortranarray(data, np.float64)  # no-op if already F-order
    lib.LGT_TransformMatrix(
        data_cm.ctypes.data, n, f, bounds_flat.ctypes.data,
        offsets.ctypes.data, missing.ctypes.data, default.ctypes.data,
        nbins.ctypes.data, elem, out.ctypes.data)
    return out
