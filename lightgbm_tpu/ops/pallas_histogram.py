"""Pallas TPU histogram kernel.

The performance-critical op (ref: the CUDA shared-memory histogram kernels,
src/treelearner/cuda/cuda_histogram_constructor.cu:21). The XLA one-hot
formulation materializes the [N, B] one-hot in HBM (~B x 4 bytes per
element); this kernel builds one-hot tiles in VMEM only, so HBM traffic
drops to one read of the bin matrix (1 byte/element) plus the gh vectors —
the bandwidth floor.

Layout: bins [F, N] (feature-major), gh [3, N] (grad, hess, count rows,
pre-masked), output hist [F, 3, B].

Grid: (feature_blocks, row_chunks); row chunks accumulate into the same
output block (TPU grids execute sequentially, minor-dim fastest).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .bin_pack import PackedBins

_PRECISIONS = {
    "default": lax.Precision.DEFAULT,   # 1 bf16 MXU pass, f32 accumulation
    "high": lax.Precision.HIGH,         # 3 passes
    "highest": lax.Precision.HIGHEST,   # 6 passes (f32-faithful)
}

# byte-block width of the packed kernels' grid steps; bin_pack.PACK_ALIGN
# guarantees every packed section is a multiple of this
_PACKED_CHUNK_BYTES = 1024


def resolve_precision(precise) -> lax.Precision:
    """bool (legacy) or config string -> lax.Precision."""
    if isinstance(precise, bool):
        return lax.Precision.HIGHEST if precise else lax.Precision.DEFAULT
    return _PRECISIONS[precise]


def _resolve_interpret(interpret) -> bool:
    """None = auto: interpret mode on CPU (tests exercise the kernels and
    their shard_map mesh wrappers without a chip), Mosaic on TPU."""
    if interpret is not None:
        return interpret
    from .histogram import cpu_backend
    return cpu_backend()


def _hist_kernel(bins_ref, gh_ref, out_ref, *, f_blk: int, max_bins: int,
                 precise: bool):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    gh = gh_ref[...]  # [3, C] f32
    chunk = gh.shape[1]
    prec = resolve_precision(precise)

    # static unroll: dynamic sublane indexing into a uint8 tile is not
    # supported by Mosaic; keep f_blk * chunk * B * 4 bytes under VMEM
    for f in range(f_blk):
        b = bins_ref[f, :].astype(jnp.int32)  # [C]
        onehot = (b[:, None] == lax.broadcasted_iota(
            jnp.int32, (chunk, max_bins), 1)).astype(jnp.float32)
        out_ref[f, :, :] += jax.lax.dot(gh, onehot, precision=prec)


def _multi_kernel(bins_ref, ghT_ref, rlT_ref, leafsel_ref, out_ref, *,
                  f_blk: int, group: int, max_bins: int, precise: bool):
    """One grid step: f_blk features' transposed one-hots ([group*B, R]
    per dot, built in VMEM) x a shared [R, 128] leaf-selected gh operand
    -> accumulate [f_blk*B, 128]."""
    ch = pl.program_id(1)

    @pl.when(ch == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    rl = rlT_ref[...]      # [R, 1] int32 row -> leaf
    gh = ghT_ref[...]      # [R, 3] f32 (grad, hess, weight)
    r = rl.shape[0]
    lanes = lax.broadcasted_iota(jnp.int32, (r, 128), 1)
    csel = lanes % 3
    gsel = jnp.where(csel == 0, gh[:, 0:1],
                     jnp.where(csel == 1, gh[:, 1:2], gh[:, 2:3]))
    # leaf-block-diagonal gh operand: lane k = (leaf k//3, channel k%3)
    bop = jnp.where(rl == leafsel_ref[...], gsel, 0.0)  # [R, 128]
    prec = resolve_precision(precise)

    rows = group * max_bins
    riota = lax.broadcasted_iota(jnp.int32, (rows, r), 0)
    for q in range(f_blk // group):
        b_eff = jnp.zeros((rows, r), jnp.int32)
        for p in range(group):
            b_eff = jnp.where(
                riota // max_bins == p,
                bins_ref[q * group + p, :][None, :].astype(jnp.int32), b_eff)
        onehot_t = (b_eff == riota % max_bins).astype(jnp.float32)
        out_ref[0, q * rows:(q + 1) * rows, :] += jax.lax.dot(
            onehot_t, bop, precision=prec)


@functools.partial(jax.jit,
                   static_argnames=("max_bins", "num_slots", "row_chunk",
                                    "precise", "interpret"))
def hist_pallas_multi(bins_fm: jax.Array, ghT: jax.Array, row_leaf: jax.Array,
                      leaf_ids: jax.Array, *, max_bins: int, num_slots: int,
                      row_chunk: int = 2048, precise="highest",
                      interpret=None) -> jax.Array:
    """Histograms of up to `num_slots` leaves in ONE pass over the rows.

    The one-hot (bins) operand is leaf-independent, so packing the MXU's
    128 output columns with (leaf, channel) pairs builds J = 42 leaves'
    histograms for the cost of one (the reference instead loops leaves,
    touching each leaf's rows separately — cuda_histogram_constructor.cu:21
    one kernel per leaf). Rows route to their leaf's columns via a
    compare against row_leaf — the device analog of DataPartition.

    bins_fm: [F, N] uint8/16 (or PackedBins); ghT: [N, 3] f32 pre-masked
    (grad, hess, w); row_leaf: [N] int32; leaf_ids: [num_slots] int32
    (pad with -2). Returns hist [num_slots, F, B, 3] f32.
    """
    if isinstance(bins_fm, PackedBins):
        return _hist_multi_packed_f32(bins_fm, ghT, row_leaf, leaf_ids,
                                      max_bins=max_bins,
                                      num_slots=num_slots, precise=precise,
                                      interpret=interpret)
    num_features, n = bins_fm.shape
    assert num_slots * 3 <= 128, "num_slots capped at 42 by MXU columns"
    group = max(1, 128 // max_bins) if max_bins <= 128 else 1
    # bins tile first dim must be a multiple of 8 (Mosaic) AND of group
    # (the kernel consumes features in groups of `group` per dot)
    f_blk = group * 8 // math.gcd(group, 8)
    pad_f = (-num_features) % f_blk
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)),
                          constant_values=0)
    fp = bins_fm.shape[0]
    pad_n = (-n) % row_chunk
    if pad_n:
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_n)),
                          constant_values=0)
        ghT = jnp.pad(ghT, ((0, pad_n), (0, 0)))  # zero gh: no contribution
        row_leaf = jnp.pad(row_leaf, (0, pad_n), constant_values=-1)
    npad = bins_fm.shape[1]

    # lane k holds leaf_ids[k//3]; lanes beyond 3*num_slots get sentinel -2
    # (never equals a row_leaf entry, which is >= 0 or -1 padding)
    k = jnp.arange(128)
    leafsel = jnp.where(k < 3 * num_slots,
                        leaf_ids[jnp.minimum(k // 3, num_slots - 1)],
                        -2).astype(jnp.int32)[None, :]

    fblocks = fp // f_blk
    rows = f_blk * max_bins
    grid = (fblocks, npad // row_chunk)
    out = pl.pallas_call(
        functools.partial(_multi_kernel, f_blk=f_blk, group=group,
                          max_bins=max_bins, precise=precise),
        grid=grid,
        in_specs=[
            pl.BlockSpec((f_blk, row_chunk), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_chunk, 3), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_chunk, 1), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 128), lambda j, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, rows, 128), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fblocks, rows, 128), jnp.float32),
        interpret=_resolve_interpret(interpret),
    )(bins_fm, ghT, row_leaf[:, None].astype(jnp.int32), leafsel)
    # [fblocks, f_blk*B, 128] -> [F, B, J, 3] -> [J, F, B, 3]
    out = out[:, :, :3 * num_slots]
    out = out.reshape(fp, max_bins, num_slots, 3)
    out = jnp.moveaxis(out, 2, 0)
    return out[:, :num_features]


def _multi_kernel_int8(bins_ref, ghT_ref, rlT_ref, leafsel_ref, out_ref, *,
                       f_blk: int, group: int, max_bins: int):
    """Integer twin of _multi_kernel: int8 one-hot x int8 leaf-selected
    quantized (grad, hess, weight) -> int32 accumulation. This is the MXU
    shape of the reference's quantized histograms (ref:
    gradient_discretizer.hpp:23 int8 packed gradients, bin.h:351-421
    ConstructHistogramInt* variants) — exact integer arithmetic at twice
    the bf16 MXU rate."""
    ch = pl.program_id(1)

    @pl.when(ch == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    rl = rlT_ref[...]      # [R, 1] int32 row -> leaf
    gh = ghT_ref[...]      # [R, 3] int8 (g_int, h_int, weight)
    r = rl.shape[0]
    lanes = lax.broadcasted_iota(jnp.int32, (r, 128), 1)
    csel = lanes % 3
    gsel = jnp.where(csel == 0, gh[:, 0:1],
                     jnp.where(csel == 1, gh[:, 1:2], gh[:, 2:3]))
    bop = jnp.where(rl == leafsel_ref[...], gsel,
                    jnp.int8(0)).astype(jnp.int8)  # [R, 128]

    rows = group * max_bins
    riota = lax.broadcasted_iota(jnp.int32, (rows, r), 0)
    for q in range(f_blk // group):
        b_eff = jnp.zeros((rows, r), jnp.int32)
        for p in range(group):
            b_eff = jnp.where(
                riota // max_bins == p,
                bins_ref[q * group + p, :][None, :].astype(jnp.int32), b_eff)
        onehot_t = (b_eff == riota % max_bins).astype(jnp.int8)
        out_ref[0, q * rows:(q + 1) * rows, :] += jax.lax.dot_general(
            onehot_t, bop, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("max_bins", "num_slots", "row_chunk",
                                    "interpret"))
def hist_pallas_multi_int8(bins_fm: jax.Array, ghT_i8: jax.Array,
                           row_leaf: jax.Array, leaf_ids: jax.Array, *,
                           max_bins: int, num_slots: int,
                           row_chunk: int = 2048,
                           interpret=None) -> jax.Array:
    """Quantized multi-leaf histograms: one pass, int32 accumulation.

    ghT_i8: [N, 3] int8 (quantized grad, quantized hess, {0,1} weight),
    pre-masked. Returns [num_slots, F, B, 3] int32 — callers scale by
    (g_scale, h_scale, 1) to recover the f32 statistics. Safe for
    N < 2^31 / (num_grad_quant_bins): |g_int| <= bins/2, so per-bin int32
    sums cannot overflow at any realistic scale.
    """
    if isinstance(bins_fm, PackedBins):
        return _hist_multi_packed_int8(bins_fm, ghT_i8, row_leaf, leaf_ids,
                                       max_bins=max_bins,
                                       num_slots=num_slots,
                                       interpret=interpret)
    num_features, n = bins_fm.shape
    assert num_slots * 3 <= 128, "num_slots capped at 42 by MXU columns"
    group = max(1, 128 // max_bins) if max_bins <= 128 else 1
    f_blk = group * 8 // math.gcd(group, 8)
    pad_f = (-num_features) % f_blk
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)), constant_values=0)
    fp = bins_fm.shape[0]
    pad_n = (-n) % row_chunk
    if pad_n:
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_n)), constant_values=0)
        ghT_i8 = jnp.pad(ghT_i8, ((0, pad_n), (0, 0)))
        row_leaf = jnp.pad(row_leaf, (0, pad_n), constant_values=-1)
    npad = bins_fm.shape[1]

    k = jnp.arange(128)
    leafsel = jnp.where(k < 3 * num_slots,
                        leaf_ids[jnp.minimum(k // 3, num_slots - 1)],
                        -2).astype(jnp.int32)[None, :]

    fblocks = fp // f_blk
    rows = f_blk * max_bins
    grid = (fblocks, npad // row_chunk)
    out = pl.pallas_call(
        functools.partial(_multi_kernel_int8, f_blk=f_blk, group=group,
                          max_bins=max_bins),
        grid=grid,
        in_specs=[
            pl.BlockSpec((f_blk, row_chunk), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_chunk, 3), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_chunk, 1), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 128), lambda j, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, rows, 128), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fblocks, rows, 128), jnp.int32),
        interpret=_resolve_interpret(interpret),
    )(bins_fm, ghT_i8, row_leaf[:, None].astype(jnp.int32), leafsel)
    out = out[:, :, :3 * num_slots]
    out = out.reshape(fp, max_bins, num_slots, 3)
    out = jnp.moveaxis(out, 2, 0)
    return out[:, :num_features]


# ---------------------------------------------------------------------------
# packed-bin kernels: each grid step reads ONE block of packed bytes and
# consumes every bit-section in it, so the dominant bin read shrinks by
# the pack factor (bin_pack.PackedBins split-section layout: byte j of a
# section-aligned block covers rows j, j+section, ...; the v-th section's
# gh/row_leaf operands are the same arrays blocked at section-strided
# offsets — no lane interleave anywhere, just vpb dots per feature group)
# ---------------------------------------------------------------------------
def _leaf_bop(gh, rl, leafsel_ref, int8: bool):
    """The MXU's leaf-block-diagonal gh operand [R, 128] (lane k =
    (leaf k//3, channel k%3)) — shared by every multi-kernel variant."""
    r = rl.shape[0]
    lanes = lax.broadcasted_iota(jnp.int32, (r, 128), 1)
    csel = lanes % 3
    gsel = jnp.where(csel == 0, gh[:, 0:1],
                     jnp.where(csel == 1, gh[:, 1:2], gh[:, 2:3]))
    if int8:
        return jnp.where(rl == leafsel_ref[...], gsel,
                         jnp.int8(0)).astype(jnp.int8)
    return jnp.where(rl == leafsel_ref[...], gsel, 0.0)


def _accum_section_dots(bins_ref, out_ref, bops, *, f_blk: int, group: int,
                        max_bins: int, vpb: int, int8: bool, precise):
    """Accumulate all bit-sections of a packed byte block: one one-hot
    build + dot per (feature-group, section). vpb=1 degenerates to the
    unpacked kernels' loop (shift 0, mask 255)."""
    bits = 8 // vpb
    bmask = (1 << bits) - 1
    rows = group * max_bins
    cb = bops[0].shape[0]
    riota = lax.broadcasted_iota(jnp.int32, (rows, cb), 0)
    prec = None if int8 else resolve_precision(precise)
    for q in range(f_blk // group):
        for v in range(vpb):
            b_eff = jnp.zeros((rows, cb), jnp.int32)
            for p in range(group):
                col = (bins_ref[q * group + p, :].astype(jnp.int32)
                       >> (bits * v)) & bmask
                b_eff = jnp.where(riota // max_bins == p,
                                  col[None, :], b_eff)
            if int8:
                onehot_t = (b_eff == riota % max_bins).astype(jnp.int8)
                out_ref[0, q * rows:(q + 1) * rows, :] += lax.dot_general(
                    onehot_t, bops[v], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
            else:
                onehot_t = (b_eff == riota % max_bins).astype(jnp.float32)
                out_ref[0, q * rows:(q + 1) * rows, :] += jax.lax.dot(
                    onehot_t, bops[v], precision=prec)


def _multi_kernel_packed(bins_ref, *refs, f_blk: int, group: int,
                         max_bins: int, vpb: int, int8: bool, precise):
    """Packed twin of _multi_kernel/_multi_kernel_int8: refs =
    (gh_0..gh_{vpb-1}, rl_0..rl_{vpb-1}, leafsel, out)."""
    out_ref = refs[-1]
    leafsel_ref = refs[-2]
    gh_refs, rl_refs = refs[:vpb], refs[vpb:2 * vpb]
    ch = pl.program_id(1)

    @pl.when(ch == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bops = [_leaf_bop(gh_refs[v][...], rl_refs[v][...], leafsel_ref, int8)
            for v in range(vpb)]
    _accum_section_dots(bins_ref, out_ref, bops, f_blk=f_blk, group=group,
                        max_bins=max_bins, vpb=vpb, int8=int8,
                        precise=precise)


def _multi_kernel_fused(bins_ref, *refs, f_blk: int, group: int,
                        max_bins: int, vpb: int, precise, grad_fn,
                        has_weight: bool):
    """Gradient-fused multi kernel: instead of reading a pre-built
    [R, 3] ghT operand, read (score, label[, weight], mask) vectors and
    compute grad/hess with the objective's pointwise function INSIDE the
    kernel (VPU math under the MXU's bandwidth shadow). This removes the
    standalone gradient/bagging element-wise pass — ghT is never
    materialized in HBM — which is the ~0.5 GB/iter term of the cost
    model. Works for packed (vpb>1) and raw uint8 (vpb=1) bins alike."""
    out_ref = refs[-1]
    leafsel_ref = refs[-2]
    ch = pl.program_id(1)

    @pl.when(ch == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    # row operands are laid out operand-major: operand k's section v
    # lives at refs[k * vpb + v] (operands: score, label, [weight],
    # mask, rl — matching _packed_multi_call's row_vecs order)
    def op(k, v):
        return refs[k * vpb + v][...]

    iw = int(has_weight)
    bops = []
    for v in range(vpb):
        score, label = op(0, v), op(1, v)
        weight = op(2, v) if has_weight else None
        mask, rl = op(2 + iw, v), op(3 + iw, v)
        g, h = grad_fn(score, label, weight)
        gh = jnp.concatenate([g * mask, h * mask, mask], axis=1)  # [R, 3]
        bops.append(_leaf_bop(gh, rl, leafsel_ref, False))
    _accum_section_dots(bins_ref, out_ref, bops, f_blk=f_blk, group=group,
                        max_bins=max_bins, vpb=vpb, int8=False,
                        precise=precise)


def _fb_geometry(num_features: int, max_bins: int):
    """(group, f_blk) — the multi kernels' feature-block geometry."""
    group = max(1, 128 // max_bins) if max_bins <= 128 else 1
    f_blk = group * 8 // math.gcd(group, 8)
    return group, f_blk


def _leafsel_row(leaf_ids, num_slots: int):
    k = jnp.arange(128)
    return jnp.where(k < 3 * num_slots,
                     leaf_ids[jnp.minimum(k // 3, num_slots - 1)],
                     -2).astype(jnp.int32)[None, :]


def _packed_multi_call(pb: PackedBins, row_vecs, leaf_ids, kernel, *,
                       max_bins: int, num_slots: int, out_dtype,
                       interpret):
    """Shared pallas_call plumbing of the packed multi kernels.

    row_vecs: list of ([N] array, pad_value, block_width) triples; each
    becomes vpb operands blocked at section-strided offsets so grid step
    i sees the rows matching byte block i's bit-sections.
    Returns (call_output [fblocks, f_blk*B, 128], kernel kwargs dict).
    """
    num_features = pb.data.shape[0]
    vpb, sec, n = pb.vpb, pb.section, pb.num_data
    group, f_blk = _fb_geometry(num_features, max_bins)
    data = pb.data
    pad_f = (-num_features) % f_blk
    if pad_f:
        data = jnp.pad(data, ((0, pad_f), (0, 0)), constant_values=0)
    fp = data.shape[0]
    cb = min(_PACKED_CHUNK_BYTES, sec)
    assert sec % cb == 0, "bin_pack.PACK_ALIGN must tile the byte chunk"
    nsb = sec // cb
    n_rows = vpb * sec

    padded = []
    for vec, pad_val, width in row_vecs:
        v2 = vec.reshape(-1, width) if vec.ndim == 2 else vec[:, None]
        pad_n = n_rows - v2.shape[0]
        padded.append(jnp.pad(v2, ((0, pad_n), (0, 0)),
                              constant_values=pad_val))
    leafsel = _leafsel_row(leaf_ids, num_slots)

    in_specs = [pl.BlockSpec((f_blk, cb), lambda j, i: (j, i),
                             memory_space=pltpu.VMEM)]
    operands = [data]
    # operand-major layout (all of operand k's sections consecutively) —
    # the kernels index refs[k * vpb + v]
    for arr in padded:
        width = arr.shape[1]
        for v in range(vpb):
            in_specs.append(pl.BlockSpec(
                (cb, width), lambda j, i, v=v: (i + v * nsb, 0),
                memory_space=pltpu.VMEM))
            operands.append(arr)
    in_specs.append(pl.BlockSpec((1, 128), lambda j, i: (0, 0),
                                 memory_space=pltpu.VMEM))
    operands.append(leafsel)

    fblocks = fp // f_blk
    rows = f_blk * max_bins
    out = pl.pallas_call(
        functools.partial(kernel, f_blk=f_blk, group=group,
                          max_bins=max_bins, vpb=vpb),
        grid=(fblocks, nsb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, rows, 128), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fblocks, rows, 128), out_dtype),
        interpret=_resolve_interpret(interpret),
    )(*operands)
    out = out[:, :, :3 * num_slots]
    out = out.reshape(fp, max_bins, num_slots, 3)
    return jnp.moveaxis(out, 2, 0)[:, :num_features]


@functools.partial(jax.jit, static_argnames=("max_bins", "num_slots",
                                             "interpret", "precise"))
def _hist_multi_packed_f32(pb, ghT, row_leaf, leaf_ids, *, max_bins: int,
                           num_slots: int, precise="highest",
                           interpret=None):
    rl = row_leaf[:, None].astype(jnp.int32)
    kern = functools.partial(_multi_kernel_packed, int8=False,
                             precise=precise)
    return _packed_multi_call(
        pb, [(ghT, 0.0, 3), (rl, -1, 1)], leaf_ids, kern,
        max_bins=max_bins, num_slots=num_slots, out_dtype=jnp.float32,
        interpret=interpret)


@functools.partial(jax.jit, static_argnames=("max_bins", "num_slots",
                                             "interpret"))
def _hist_multi_packed_int8(pb, ghT_i8, row_leaf, leaf_ids, *,
                            max_bins: int, num_slots: int, interpret=None):
    rl = row_leaf[:, None].astype(jnp.int32)
    kern = functools.partial(_multi_kernel_packed, int8=True, precise=None)
    return _packed_multi_call(
        pb, [(ghT_i8, 0, 3), (rl, -1, 1)], leaf_ids, kern,
        max_bins=max_bins, num_slots=num_slots, out_dtype=jnp.int32,
        interpret=interpret)


@functools.partial(jax.jit,
                   static_argnames=("grad_fn", "max_bins", "num_slots",
                                    "precise", "interpret"))
def hist_pallas_multi_fused(bins_fm, score, label, weight, mask, row_leaf,
                            leaf_ids, *, grad_fn, max_bins: int,
                            num_slots: int, precise="highest",
                            interpret=None) -> jax.Array:
    """Multi-leaf histograms with the gradient pass fused in: operands
    are (score, label[, weight], mask) instead of a pre-built ghT, and
    grad_fn (the objective's pointwise gradient) runs inside the kernel.
    Accepts PackedBins or raw [F, N] uint8 bins. Returns [S, F, B, 3]."""
    # the kernel reads bins through the byte-sectioned path (vpb=1 masks
    # with & 255): uint16 ids would alias silently — refuse them
    assert max_bins <= 256, \
        "hist_pallas_multi_fused needs byte-representable bin ids"
    has_weight = weight is not None
    kern0 = functools.partial(_multi_kernel_fused, precise=precise,
                              grad_fn=grad_fn, has_weight=has_weight)
    vecs = [(score.astype(jnp.float32), 0.0, 1),
            (label.astype(jnp.float32), 0.0, 1)]
    if has_weight:
        vecs.append((weight.astype(jnp.float32), 0.0, 1))
    vecs.append((mask.astype(jnp.float32), 0.0, 1))
    if isinstance(bins_fm, PackedBins):
        rl = row_leaf[:, None].astype(jnp.int32)
        return _packed_multi_call(
            bins_fm, vecs + [(rl, -1, 1)], leaf_ids, kern0,
            max_bins=max_bins, num_slots=num_slots, out_dtype=jnp.float32,
            interpret=interpret)
    # unpacked: wrap the raw matrix as a vpb=1 "packed" layout — the
    # kernel's shift-0/mask-255 section loop is then the identity
    n = bins_fm.shape[1]
    cb = _PACKED_CHUNK_BYTES
    sec = -(-n // cb) * cb
    data = jnp.pad(bins_fm, ((0, 0), (0, sec - n)))
    pb1 = PackedBins(data, n, 1)
    rl = row_leaf[:, None].astype(jnp.int32)
    return _packed_multi_call(
        pb1, vecs + [(rl, -1, 1)], leaf_ids, kern0,
        max_bins=max_bins, num_slots=num_slots, out_dtype=jnp.float32,
        interpret=interpret)


def _hist_kernel_packed(bins_ref, *refs, f_blk: int, max_bins: int,
                        vpb: int, precise):
    """Packed twin of _hist_kernel (single-leaf): refs =
    (gh3_0..gh3_{vpb-1}, out); gh3 blocks are [3, C] at section-strided
    offsets along the row axis."""
    out_ref = refs[-1]
    gh_refs = refs[:-1]
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bits = 8 // vpb
    bmask = (1 << bits) - 1
    prec = resolve_precision(precise)
    for f in range(f_blk):
        for v in range(vpb):
            b = (bins_ref[f, :].astype(jnp.int32) >> (bits * v)) & bmask
            chunk = b.shape[0]
            onehot = (b[:, None] == lax.broadcasted_iota(
                jnp.int32, (chunk, max_bins), 1)).astype(jnp.float32)
            out_ref[f, :, :] += jax.lax.dot(gh_refs[v][...], onehot,
                                            precision=prec)


@functools.partial(jax.jit, static_argnames=("max_bins", "f_blk",
                                             "precise", "interpret"))
def _hist_pallas_packed(pb, gh3, *, max_bins: int, f_blk: int = 8,
                        precise="highest", interpret=None) -> jax.Array:
    """Single-leaf histogram over PackedBins: [F, section] bytes +
    gh3 [3, N] -> [F, B, 3]."""
    num_features = pb.data.shape[0]
    vpb, sec, n = pb.vpb, pb.section, pb.num_data
    data = pb.data
    pad_f = (-num_features) % f_blk
    if pad_f:
        data = jnp.pad(data, ((0, pad_f), (0, 0)), constant_values=0)
    fp = data.shape[0]
    cb = min(_PACKED_CHUNK_BYTES, sec)
    nsb = sec // cb
    n_rows = vpb * sec
    gh3p = jnp.pad(gh3, ((0, 0), (0, n_rows - gh3.shape[1])))

    in_specs = [pl.BlockSpec((f_blk, cb), lambda j, i: (j, i),
                             memory_space=pltpu.VMEM)]
    operands = [data]
    for v in range(vpb):
        in_specs.append(pl.BlockSpec((3, cb),
                                     lambda j, i, v=v: (0, i + v * nsb),
                                     memory_space=pltpu.VMEM))
        operands.append(gh3p)

    out = pl.pallas_call(
        functools.partial(_hist_kernel_packed, f_blk=f_blk,
                          max_bins=max_bins, vpb=vpb, precise=precise),
        grid=(fp // f_blk, nsb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((f_blk, 3, max_bins), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fp, 3, max_bins), jnp.float32),
        interpret=_resolve_interpret(interpret),
    )(*operands)
    return jnp.swapaxes(out[:num_features], 1, 2)


def _chunked_slot_hist(bins_fm, ghT, row_leaf, hist_of, *, max_bins: int,
                       num_slots: int, acc_dtype,
                       deterministic: bool = False) -> jax.Array:
    """Shared pad/chunk/scan scaffold of the XLA multi-slot builders:
    `hist_of(bins_part, gh_part, leaf_part) -> [F, B, S*3]` runs per
    row chunk and the partials accumulate in `acc_dtype`. Padded rows
    contribute nothing (gh channels zero, leaf sentinel -7 matches no
    slot — invalid slots are -2). Returns [S, F, B, 3].

    deterministic=True (f32 only): fixed 2048-row chunking with
    Kahan-compensated cross-chunk accumulation (the `deterministic_hist`
    knob) — the cross-chunk error no longer grows with the chunk count,
    keeping the result within the 1e-4 parity target regardless of N or
    of how sharding regroups rows."""
    from jax import lax

    from .histogram import _kahan_scan

    s = num_slots
    n = ghT.shape[0]
    f = bins_fm.shape[0]
    # 131072 bounds the [c, S*3] packed operand to ~64MB at S=42;
    # deterministic mode fixes 2048 (see histogram.build_histogram)
    chunk = 2048 if deterministic else 131072
    if n > chunk:
        pad = (-n) % chunk
        ghp = jnp.pad(ghT, ((0, pad), (0, 0)))
        binsp = jnp.pad(bins_fm, ((0, 0), (0, pad)))
        leafp = jnp.pad(row_leaf, (0, pad), constant_values=-7)
        nchunk = (n + pad) // chunk
        ghc = ghp.reshape(nchunk, chunk, ghT.shape[1])
        binsc = jnp.swapaxes(binsp.reshape(f, nchunk, chunk), 0, 1)
        leafc = leafp.reshape(nchunk, chunk)

        init = jnp.zeros((f, max_bins, s * 3), acc_dtype)
        if deterministic:
            hist = _kahan_scan(lambda inp: hist_of(*inp), init,
                               (binsc, ghc, leafc))
        else:
            def one_chunk(acc, inputs):
                b, g, lf = inputs
                return acc + hist_of(b, g, lf), None
            hist, _ = lax.scan(one_chunk, init, (binsc, ghc, leafc))
    else:
        hist = hist_of(bins_fm, ghT, row_leaf)
    hist = hist.reshape(f, max_bins, s, 3)
    return jnp.moveaxis(hist, 2, 0)  # [S, F, B, 3]


def hist_multi_xla(bins_fm, ghT, row_leaf, leaf_ids, *, max_bins: int,
                   num_slots: int, deterministic: bool = False) -> jax.Array:
    """XLA fallback (CPU tests + CPU bench): ALL leaf slots in one
    contraction per feature. The bin one-hot is built once and dotted
    against the per-slot masked channels packed side-by-side — the
    former per-slot loop rebuilt the one-hot `num_slots` times, roughly
    doubling the work and unrolling W separate passes into the HLO."""
    from .histogram import _hist_all_features

    s = num_slots

    def hist_of(bins_part, gh_part, leaf_part):
        # [S, c] row->slot selection; ghT channels are pre-masked
        # (g*w, h*w, w) with w in {0,1}, so multiplying by the selector
        # alone reproduces the old per-slot mask exactly
        sel = (leaf_part[None, :] == leaf_ids[:, None]).astype(jnp.float32)
        ghs = (sel[:, :, None] * gh_part[None, :, :])          # [S, c, 3]
        ghs = jnp.moveaxis(ghs, 0, 1).reshape(-1, s * 3)       # [c, S*3]
        # _hist_all_features is generic over the trailing dim
        return _hist_all_features(bins_part, ghs, max_bins, jnp.float32)

    return _chunked_slot_hist(bins_fm, ghT, row_leaf, hist_of,
                              max_bins=max_bins, num_slots=s,
                              acc_dtype=jnp.float32,
                              deterministic=deterministic)


def hist_multi(bins_fm, ghT, row_leaf, leaf_ids, *, max_bins: int,
               num_slots: int, impl: str = "xla",
               precision: str = "highest",
               deterministic: bool = False) -> jax.Array:
    if impl == "pallas" and not deterministic:
        return hist_pallas_multi(bins_fm, ghT, row_leaf, leaf_ids,
                                 max_bins=max_bins, num_slots=num_slots,
                                 precise=precision)
    # XLA path (CPU tests, deterministic_hist): f32 dots are exact
    # regardless of precision
    if isinstance(bins_fm, PackedBins):
        from .bin_pack import unpack_bins
        bins_fm = unpack_bins(bins_fm).astype(jnp.uint8)
    return hist_multi_xla(bins_fm, ghT, row_leaf, leaf_ids,
                          max_bins=max_bins, num_slots=num_slots,
                          deterministic=deterministic)


def hist_multi_int8_xla(bins_fm, ghT_i8, row_leaf, leaf_ids, *,
                        max_bins: int, num_slots: int) -> jax.Array:
    """XLA twin of the int8 pallas kernel: int8 one-hot x int8 packed
    leaf-channel operand with int32 accumulation — EXACT integer sums,
    so this path is interchangeable with the device kernel (and with
    the mesh's int32 psum) bit-for-bit. Makes use_quantized_grad
    default-capable on every backend, not just where Mosaic runs."""
    if isinstance(bins_fm, PackedBins):
        from .bin_pack import unpack_bins
        bins_fm = unpack_bins(bins_fm).astype(jnp.uint8)
    s = num_slots
    bidx = jnp.arange(max_bins, dtype=jnp.int32)

    def hist_of(bins_part, gh_part, leaf_part):
        sel = (leaf_part[None, :] == leaf_ids[:, None]).astype(jnp.int8)
        ghs = sel[:, :, None] * gh_part[None, :, :]            # [S, c, 3]
        ghs = jnp.moveaxis(ghs, 0, 1).reshape(-1, s * 3)       # [c, S*3]

        def one_feature(carry, feat_bins):
            onehot = (feat_bins[:, None].astype(jnp.int32)
                      == bidx[None, :]).astype(jnp.int8)       # [c, B]
            h = lax.dot_general(onehot, ghs, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.int32)
            return carry, h                                    # [B, S*3]

        _, hist = lax.scan(one_feature, None, bins_part)
        return hist                                            # [F, B, S*3]

    return _chunked_slot_hist(bins_fm, ghT_i8, row_leaf, hist_of,
                              max_bins=max_bins, num_slots=s,
                              acc_dtype=jnp.int32)


def hist_multi_int8(bins_fm, ghT_i8, row_leaf, leaf_ids, *, max_bins: int,
                    num_slots: int, impl: str = "xla") -> jax.Array:
    """Quantized multi-leaf histogram dispatch: the pallas MXU kernel on
    device backends, the exact-integer XLA contraction elsewhere. Both
    return identical int32 histograms (asserted in tests/test_waved.py),
    which is what lets the waved grower run quantized training on any
    backend — ROADMAP item 3's "promote int8 to default-capable"."""
    if impl == "pallas":
        return hist_pallas_multi_int8(bins_fm, ghT_i8, row_leaf, leaf_ids,
                                      max_bins=max_bins,
                                      num_slots=num_slots)
    return hist_multi_int8_xla(bins_fm, ghT_i8, row_leaf, leaf_ids,
                               max_bins=max_bins, num_slots=num_slots)


@functools.partial(jax.jit,
                   static_argnames=("max_bins", "f_blk", "row_chunk",
                                    "precise", "interpret"))
def hist_pallas(bins_fm: jax.Array, gh3: jax.Array, *, max_bins: int,
                f_blk: int = 8, row_chunk: int = 0,
                precise="highest", interpret=None) -> jax.Array:
    """bins_fm [F, N] uint8/uint16 (or PackedBins), gh3 [3, N] f32
    (pre-masked) -> hist [F, B, 3] f32."""
    if isinstance(bins_fm, PackedBins):
        return _hist_pallas_packed(bins_fm, gh3, max_bins=max_bins,
                                   f_blk=f_blk, precise=precise,
                                   interpret=interpret)
    num_features, n = bins_fm.shape
    if row_chunk == 0:
        # keep the f_blk unrolled one-hot buffers under ~8 MB of VMEM
        budget = 8 * 1024 * 1024 // (f_blk * max_bins * 4)
        row_chunk = max(512, min(2048, (budget // 512) * 512))
    # pad N to a multiple of row_chunk (pad bins with max_bins -> one-hot
    # of the padded rows is all-zero, and gh pads with zeros anyway)
    pad_n = (-n) % row_chunk
    if pad_n:
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_n)),
                          constant_values=max_bins)
        gh3 = jnp.pad(gh3, ((0, 0), (0, pad_n)))
    pad_f = (-num_features) % f_blk
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)),
                          constant_values=max_bins)
    fp = bins_fm.shape[0]
    npad = bins_fm.shape[1]

    grid = (fp // f_blk, npad // row_chunk)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, f_blk=f_blk, max_bins=max_bins,
                          precise=precise),
        grid=grid,
        in_specs=[
            pl.BlockSpec((f_blk, row_chunk), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, row_chunk), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((f_blk, 3, max_bins), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fp, 3, max_bins), jnp.float32),
        interpret=_resolve_interpret(interpret),
    )(bins_fm, gh3)
    # [F, 3, B] -> [F, B, 3] to match the XLA path's layout
    return jnp.swapaxes(out[:num_features], 1, 2)
