"""Row partition op (device).

TPU-native replacement for the reference DataPartition
(ref: src/treelearner/data_partition.hpp:22, cuda_data_partition.cu:291).
Rather than physically permuting row indices per leaf, we keep a full-length
``row_leaf: [N] int32`` map (row -> leaf id) and update it with masked
`where` — the mask-over-permutation idiom that XLA/TPU prefers.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .bin_pack import PackedBins, unpack_feature, unpack_rows
from .split import MISSING_NAN
from ..obs.metrics import global_metrics


class SparseBins(NamedTuple):
    """COO binned storage for ultra-sparse, non-bundleable data — the
    TPU-native analog of the reference's sparse row-wise MultiValBin
    (ref: include/LightGBM/bin.h:482, multi_val_sparse_bin.hpp:21).

    Only entries whose bin differs from the feature's implicit-zero bin
    are stored; histogram builds run one O(nnz) segment-sum instead of
    the O(N*F*B) dense one-hot contraction, and the implicit-zero bin
    mass is recovered per feature as (leaf totals - explicit bins) —
    the same residual trick the reference's sparse bins use. Flows
    through the growers in the `bins_fm` argument slot; every consumer
    dispatches on isinstance.

    coo_row/coo_feat/coo_bin: [nnz] int32; zero_bins: [F] int32
    (the bin an implicit zero maps to, per feature).
    """
    coo_row: jax.Array
    coo_feat: jax.Array
    coo_bin: jax.Array
    zero_bins: jax.Array


def sparse_feature_bins(sb: SparseBins, feature: jax.Array,
                        num_data: int) -> jax.Array:
    """Materialize one logical [N] bin column from the COO storage:
    rows absent from the column's explicit entries carry its
    implicit-zero bin."""
    sel = sb.coo_feat == feature
    rows = jnp.where(sel, sb.coo_row, num_data)  # OOB rows are dropped
    out = jnp.full((num_data,), sb.zero_bins[feature], jnp.int32)
    return out.at[rows].set(jnp.where(sel, sb.coo_bin, 0).astype(jnp.int32),
                            mode="drop")


def feature_bins(bins_fm, feature: jax.Array, bundle=None,
                 num_data: int = 0) -> jax.Array:
    """Logical [N] bin column of `feature` — a plain row slice for a
    dense matrix, an on-the-fly decode of the EFB-bundled matrix
    (bundle = (group_of, offset_of, num_bins) device arrays; ref:
    feature_group.h bin_offsets_ decoding), a shift/mask unpack for
    PackedBins, or a COO materialization for SparseBins storage."""
    if isinstance(bins_fm, SparseBins):
        return sparse_feature_bins(bins_fm, feature, num_data)
    if isinstance(bins_fm, PackedBins):
        return unpack_feature(bins_fm, feature)
    if bundle is None:
        return jnp.take(bins_fm, feature, axis=0).astype(jnp.int32)
    group_of, offset_of, nb = bundle
    col = jnp.take(bins_fm, group_of[feature], axis=0).astype(jnp.int32)
    return _decode_bundled(col, offset_of[feature], nb[feature])


def _decode_bundled(col: jax.Array, off: jax.Array,
                    nbf: jax.Array) -> jax.Array:
    """EFB stored-column -> logical bin (ref: feature_group.h
    bin_offsets_): values inside [off, off + nbf - 1) map to logical
    bins 1.., everything else is the feature's implicit bin 0. Single
    source of the decode rule for every device bin consumer."""
    in_range = (col >= off) & (col < off + nbf - 1)
    return jnp.where(in_range, col - off + 1, 0)


def _per_row_feature_bins(bins_fm: jax.Array, feat: jax.Array,
                          bundle=None) -> jax.Array:
    """bins of feature feat[i] for every row i — the gathered analog of
    feature_bins for per-row feature indices (feat: [N] int32)."""
    n = feat.shape[0]
    rows = jnp.arange(n)
    if isinstance(bins_fm, PackedBins):
        return unpack_rows(bins_fm, feat, rows)
    if bundle is None:
        return bins_fm[feat, rows].astype(jnp.int32)
    group_of, offset_of, nb = bundle
    col = bins_fm[group_of[feat], rows].astype(jnp.int32)
    return _decode_bundled(col, offset_of[feat], nb[feat])


def apply_wave_splits(row_leaf: jax.Array, bins_fm: jax.Array,
                      leaf_ids: jax.Array, right_ids: jax.Array,
                      features: jax.Array, thresholds: jax.Array,
                      default_lefts: jax.Array, cat_masks: jax.Array,
                      valid: jax.Array, num_bins: jax.Array,
                      missing_type: jax.Array, is_categorical: jax.Array,
                      num_leaves: int, bundle=None) -> jax.Array:
    """Apply a whole wave's W splits in ONE pass over the rows.

    A wave's split leaves are pairwise distinct and a leaf created
    within the wave is never split in the same wave (its candidates are
    unknown until the boundary), so each row moves AT MOST once per
    wave — the W sequential apply_split passes (each reading a bin row
    + row_leaf, ~9 bytes/row/split of HBM traffic) collapse into one
    gathered decision (~40 bytes/row/WAVE). This is the partition
    analog of the multi-leaf histogram kernel and the main HBM saving
    of waved growth beyond the histogram batching itself.
    """
    global_metrics.note_trace("ops/partition_wave")
    w_count = leaf_ids.shape[0]
    L = num_leaves
    lids = jnp.where(valid, leaf_ids, L)
    table = jnp.full((L + 1,), -1, jnp.int32).at[lids].set(
        jnp.arange(w_count, dtype=jnp.int32))
    widx = table[row_leaf]
    hit = widx >= 0
    w = jnp.maximum(widx, 0)
    feat = features[w]                              # [N]
    fbins = _per_row_feature_bins(bins_fm, feat, bundle)
    nan_bin = num_bins[feat] - 1
    is_nan = (missing_type[feat] == MISSING_NAN) & (fbins == nan_bin)
    go_num = jnp.where(is_nan, default_lefts[w], fbins <= thresholds[w])
    go_left = jnp.where(is_categorical[feat], cat_masks[w, fbins], go_num)
    move = hit & ~go_left
    return jnp.where(move, right_ids[w], row_leaf)


def apply_split(row_leaf: jax.Array, bins_fm: jax.Array,
                leaf_id: jax.Array, new_leaf_id: jax.Array,
                feature: jax.Array, threshold: jax.Array,
                default_left: jax.Array, cat_mask: jax.Array,
                num_bins: jax.Array, missing_type: jax.Array,
                is_categorical: jax.Array, valid: jax.Array,
                bundle=None) -> jax.Array:
    """Send rows of `leaf_id` that fail the decision to `new_leaf_id`.

    Numerical: bin <= threshold -> left; the NaN bin (last bin when
    missing_type == NAN) follows `default_left`. Categorical: bins set in
    `cat_mask` ([B] bool — the device analog of the reference's category
    bitset, tree.h:375) go left. No-op when `valid` is False.
    """
    global_metrics.note_trace("ops/partition")
    fbins = feature_bins(bins_fm, feature, bundle,
                         num_data=row_leaf.shape[0])  # [N]
    nan_bin = num_bins[feature] - 1
    is_nan = (missing_type[feature] == MISSING_NAN) & (fbins == nan_bin)
    numerical = jnp.where(is_nan, default_left, fbins <= threshold)
    go_left = jnp.where(is_categorical[feature], cat_mask[fbins], numerical)
    move = valid & (row_leaf == leaf_id) & ~go_left
    return jnp.where(move, new_leaf_id, row_leaf)
