"""OpenMetrics / Prometheus text-format export of the obs registries.

PR 1–5 accumulated rich internal telemetry (counters, latency
reservoirs, recompile counts, HBM watermarks, the analytic memory
model) that nothing could scrape. This module is the egress:

- ``render_openmetrics()`` — one Prometheus text-format (0.0.4)
  document over ``obs.metrics.global_metrics`` (event counters, latency
  reservoirs as summary metrics with quantile labels, predict
  throughput, trace-time jit counters, collective traffic), the
  per-device HBM stats + ``obs.memory`` watermark/model gauges where
  available, the ``obs.xla`` compile facts, and host identity labels.
- ``MetricsHTTPEndpoint`` — a daemon-thread HTTP listener serving
  ``/metrics`` (the rendered document), ``/healthz`` (process
  liveness — 200 whenever the listener is up) and ``/readyz`` (503
  until the owner's ``ready_fn`` turns true; ``ModelServer`` wires its
  warm()-in-progress state here). stdlib ``http.server`` on a thread,
  so it keeps answering while the main thread blocks in ``warm()`` or
  a training step.
- ``MetricsTextfileFlusher`` — the training-side egress for hosts with
  a node-exporter textfile collector instead of a scrape target:
  ``LGBM_TPU_METRICS_FILE=/path.prom`` makes the boosting loop flush
  the rendered document atomically (tmp + rename) at most every
  ``LGBM_TPU_METRICS_FLUSH_SECS`` (default 15), plus once at exit.

Disabled cost: with the env var unset, ``global_flusher.maybe_flush()``
is a single attribute check; nothing renders, nothing is written.
"""

from __future__ import annotations

import atexit
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .metrics import global_metrics

# Prometheus text exposition format 0.0.4 (the content type Prometheus'
# scraper negotiates for the text format)
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
# OpenMetrics 1.0: same rendered body (the document ends with the
# required `# EOF` terminator and stays within the common subset), so
# negotiation only changes the advertised content type
OPENMETRICS_CONTENT_TYPE = ("application/openmetrics-text; "
                            "version=1.0.0; charset=utf-8")


def negotiate_content_type(accept: Optional[str]) -> str:
    """Content type for a scrape given its Accept header: OpenMetrics
    when the scraper asks for ``application/openmetrics-text``
    (Prometheus does once per target to probe support), the classic
    0.0.4 text type otherwise."""
    return (OPENMETRICS_CONTENT_TYPE
            if "application/openmetrics-text" in (accept or "")
            else CONTENT_TYPE)

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str, suffix: str = "") -> str:
    """`serve/registry_hit` -> `lgbmtpu_serve_registry_hit<suffix>`."""
    return "lgbmtpu_" + _NAME_OK.sub("_", name).strip("_") + suffix


def _label_value(v: Any) -> str:
    s = str(v)
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: Any) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


class _Doc:
    """Accumulates families in render order, one TYPE header each."""

    def __init__(self) -> None:
        self.lines: List[str] = []
        self._typed: set = set()

    def sample(self, family: str, mtype: str, value: Any,
               labels: Optional[Dict[str, Any]] = None,
               help_text: str = "", name: Optional[str] = None) -> None:
        if family not in self._typed:
            self._typed.add(family)
            if help_text:
                self.lines.append(f"# HELP {family} {help_text}")
            self.lines.append(f"# TYPE {family} {mtype}")
        n = name or family
        if labels:
            lab = ",".join(f'{k}="{_label_value(v)}"'
                           for k, v in sorted(labels.items()))
            n += "{" + lab + "}"
        self.lines.append(f"{n} {_fmt(value)}")

    def text(self) -> str:
        # `# EOF` is the OpenMetrics 1.0 terminator; Prometheus 0.0.4
        # parsers treat it as a comment, so one body serves both
        return "\n".join(self.lines + ["# EOF"]) + "\n"


def render_openmetrics(registry=None,
                       extra_gauges: Optional[Dict[str, Any]] = None
                       ) -> str:
    """The full obs state as one Prometheus text-format document.

    `extra_gauges` maps already-sanitized family names to values
    (the ModelServer adds its pack/registry gauges this way)."""
    reg = registry if registry is not None else global_metrics
    doc = _Doc()

    # snapshot the concurrently-mutated dicts under the registry mutex:
    # the serve loop/executor insert new counter and reservoir names
    # while the HTTP daemon thread renders (a live iteration would
    # raise "dictionary changed size during iteration" mid-scrape)
    with reg._mutex:
        counters = dict(reg.counters)
        reservoirs = dict(reg.latency_reservoirs)
    trace_counts = dict(reg.trace_counts)
    meta = dict(reg.meta)

    # host identity: an info-style gauge (constant 1) carrying labels,
    # so multihost scrapes are mergeable by labels instead of by target
    from ..hostenv import host_labels
    doc.sample("lgbmtpu_host_info", "gauge", 1, labels=host_labels(),
               help_text="host/process identity labels (value is 1)")

    # flat event counters (serve/* registry + batcher + server events)
    for name in sorted(counters):
        doc.sample(_metric_name(name, "_total"), "counter",
                   counters[name])

    # latency reservoirs -> summary metrics with quantile labels
    fam = "lgbmtpu_latency_seconds"
    for name in sorted(reservoirs):
        res = reservoirs[name]
        p50, p95, p99 = res.quantiles((0.50, 0.95, 0.99))
        for q, v in (("0.5", p50), ("0.95", p95), ("0.99", p99)):
            doc.sample(fam, "summary", v,
                       labels={"name": name, "quantile": q},
                       help_text="latency quantiles from the bounded "
                                 "obs reservoirs")
        doc.sample(fam, "summary", res.total_seconds,
                   labels={"name": name}, name=fam + "_sum")
        doc.sample(fam, "summary", res.count,
                   labels={"name": name}, name=fam + "_count")

    # served-explanation latency: the generic block above already
    # carries name="explain/request"; this dedicated family gives the
    # explain SLO its own stable name, mirroring how the serve
    # dashboards key on lgbmtpu_latency_seconds{name="serve/request"}
    # (family linted by tools/check_shap.py)
    res = reservoirs.get("explain/request")
    if res is not None and res.count:
        fam = "lgbmtpu_explain_latency_seconds"
        p50, p95, p99 = res.quantiles((0.50, 0.95, 0.99))
        for q, v in (("0.5", p50), ("0.95", p95), ("0.99", p99)):
            doc.sample(fam, "summary", v, labels={"quantile": q},
                       help_text="served SHAP-explanation request "
                                 "latency (ModelServer.explain)")
        doc.sample(fam, "summary", res.total_seconds, name=fam + "_sum")
        doc.sample(fam, "summary", res.count, name=fam + "_count")

    # predict throughput accumulators (always-on)
    doc.sample("lgbmtpu_predict_rows_total", "counter",
               reg.predict_rows_total)
    doc.sample("lgbmtpu_predict_seconds_total", "counter",
               reg.predict_seconds_total)
    doc.sample("lgbmtpu_predict_rows_per_sec", "gauge",
               reg.predict_rows_per_sec())

    # trace-time jit counters + collective traffic
    for tag in sorted(trace_counts):
        doc.sample("lgbmtpu_jit_traces_total", "counter",
                   trace_counts[tag], labels={"tag": tag},
                   help_text="python traces per jit tag (one per "
                             "program (re)compile at top level)")
    doc.sample("lgbmtpu_collective_calls_total", "counter",
               reg.collective_calls)
    doc.sample("lgbmtpu_collective_bytes_total", "counter",
               reg.collective_bytes)

    # device memory gauges (accelerator backends only)
    stats = reg.per_device_memory_stats()
    for s in stats or ():
        lab = {"device": s.get("device", 0)}
        for key, fam_name in (("bytes_in_use", "lgbmtpu_device_bytes_in_use"),
                              ("peak_bytes_in_use",
                               "lgbmtpu_device_peak_bytes_in_use"),
                              ("bytes_limit", "lgbmtpu_device_bytes_limit")):
            if isinstance(s.get(key), (int, float)):
                doc.sample(fam_name, "gauge", s[key], labels=lab)

    # per-phase HBM watermarks (obs/memory.py; armed on accelerators)
    from .memory import global_watermarks
    for phase, ph in sorted(global_watermarks.summary().items()):
        doc.sample("lgbmtpu_phase_peak_bytes", "gauge", ph["peak_bytes"],
                   labels={"phase": phase},
                   help_text="span-boundary HBM peak per phase")

    # analytic-model gauges published through obs meta
    mm = meta.get("mem_model")
    if isinstance(mm, dict) and "peak_bytes" in mm:
        doc.sample("lgbmtpu_mem_peak_model_bytes", "gauge",
                   mm["peak_bytes"],
                   help_text="analytic peak-HBM model (obs/memory.py)")
    ht = meta.get("hist_traffic")
    if isinstance(ht, dict) and "hist_bytes_per_iter" in ht:
        doc.sample("lgbmtpu_hist_bytes_per_iter", "gauge",
                   ht["hist_bytes_per_iter"],
                   help_text="analytic per-iteration histogram HBM "
                             "traffic (learner.hist_traffic_model)")

    # checkpoint accounting (resilience/checkpoint.py; the snapshot
    # COUNT rides the generic resilience/* counters above)
    rc = meta.get("resilience_checkpoint")
    if isinstance(rc, dict) and "seconds_total" in rc:
        doc.sample("lgbmtpu_resilience_checkpoint_seconds_total",
                   "counter", rc["seconds_total"],
                   help_text="wall time spent writing training "
                             "checkpoints (atomic snapshot + fsync "
                             "path, resilience/checkpoint.py)")
        doc.sample("lgbmtpu_resilience_checkpoint_last_iteration",
                   "gauge", rc.get("last_iteration", -1))

    # continual-training accounting (resilience/continual.py; the
    # generation/rollback/swap COUNTS ride the generic continual/*
    # counters above — these are the summary-shaped extras)
    ct = meta.get("continual")
    if isinstance(ct, dict) and "generations" in ct:
        doc.sample("lgbmtpu_continual_swap_seconds_total", "counter",
                   ct.get("swap_seconds_total", 0.0),
                   help_text="wall time spent in validated hot-swaps "
                             "(reload-parity check + transactional "
                             "registry registration)")
        doc.sample("lgbmtpu_continual_last_swap_seconds", "gauge",
                   ct.get("last_swap_seconds", 0.0))
        doc.sample("lgbmtpu_continual_model_iterations", "gauge",
                   ct.get("model_iterations", 0),
                   help_text="boosting iterations in the last-good "
                             "continual model")
        doc.sample("lgbmtpu_continual_retained_snapshots", "gauge",
                   ct.get("retained_snapshots", 0))
        doc.sample("lgbmtpu_continual_resumes_total", "counter",
                   ct.get("resumes", 0),
                   help_text="checkpoint resumes observed by the "
                             "continual loop (incl. elastic mesh "
                             "resizes, counted separately)")
        doc.sample("lgbmtpu_continual_mesh_resizes_total", "counter",
                   ct.get("mesh_resizes", 0))

    # serving-fleet health (serve/fleet.py FleetRouter; the
    # failover/hedge/quarantine COUNTS ride the generic fleet/*
    # counters above — these are the per-replica state gauges the
    # chaos validator scrapes to see the kill and the recovery)
    fl = meta.get("fleet")
    if isinstance(fl, dict) and "replicas" in fl:
        doc.sample("lgbmtpu_fleet_replicas", "gauge", fl["replicas"],
                   help_text="configured replicas behind the "
                             "FleetRouter")
        for name in sorted(fl.get("replica_up", {})):
            doc.sample("lgbmtpu_fleet_replica_up", "gauge",
                       fl["replica_up"][name],
                       labels={"replica": name},
                       help_text="1 while the replica answers its "
                                 "liveness probe")
        for name in sorted(fl.get("replica_quarantined", {})):
            doc.sample("lgbmtpu_fleet_replica_quarantined", "gauge",
                       fl["replica_quarantined"][name],
                       labels={"replica": name},
                       help_text="1 while the router holds the replica "
                                 "out of rotation")

    # out-of-core streaming accounting (io/streaming.py StreamStats,
    # published per iteration by the streamed boosting paths): the
    # driver-visible proof that slab uploads overlap the histogram
    # kernels without silicon counters
    sm = meta.get("stream")
    if isinstance(sm, dict) and sm.get("slabs_total"):
        doc.sample("lgbmtpu_stream_slabs_total", "counter",
                   sm.get("slabs_total", 0),
                   help_text="host-resident bin slabs fed to the device "
                             "(tpu_stream out-of-core training)")
        doc.sample("lgbmtpu_stream_uploads_total", "counter",
                   sm.get("uploads_total", 0))
        doc.sample("lgbmtpu_stream_bytes_uploaded_total", "counter",
                   sm.get("bytes_uploaded_total", 0))
        doc.sample("lgbmtpu_stream_upload_seconds_total", "counter",
                   sm.get("upload_seconds_total", 0.0))
        doc.sample("lgbmtpu_stream_overlapped_uploads_total", "counter",
                   sm.get("overlapped_uploads_total", 0))
        doc.sample("lgbmtpu_stream_kernel_seconds_total", "counter",
                   sm.get("kernel_seconds_total", 0.0),
                   help_text="host wall time blocked on streamed-"
                             "pipeline device compute")
        doc.sample("lgbmtpu_stream_iterations_total", "counter",
                   sm.get("iterations_total", 0))
        doc.sample("lgbmtpu_stream_overlap_ratio", "gauge",
                   sm.get("overlap_ratio", 0.0),
                   help_text="fraction of upload wall-time issued while "
                             "device compute was in flight (the "
                             "double-buffer's measured overlap)")
        doc.sample("lgbmtpu_stream_slab_rows", "gauge",
                   sm.get("slab_rows", 0))
        doc.sample("lgbmtpu_stream_n_slabs", "gauge",
                   sm.get("n_slabs", 0))

    # XLA introspection (obs/xla.py; populated while enabled)
    from .xla import global_xla
    xs = global_xla.summary()
    doc.sample("lgbmtpu_xla_compile_seconds_total", "counter",
               xs["compile_s_total"],
               help_text="wall time spent compiling XLA programs")
    doc.sample("lgbmtpu_xla_trace_seconds_total", "counter",
               xs.get("trace_s_total", 0.0),
               help_text="wall time spent tracing/lowering before "
                         "compile (no cache can skip it)")
    doc.sample("lgbmtpu_xla_cache_load_seconds_total", "counter",
               xs.get("cache_load_s_total", 0.0),
               help_text="wall time loading programs from the "
                         "persistent compilation cache")
    doc.sample("lgbmtpu_xla_cache_hits_total", "counter",
               xs.get("n_cache_hits", 0))
    doc.sample("lgbmtpu_xla_programs_total", "counter", xs["n_programs"])
    for phase in sorted(xs["n_recompiles_by_phase"]):
        doc.sample("lgbmtpu_xla_compiles_total", "counter",
                   xs["n_recompiles_by_phase"][phase],
                   labels={"phase": phase})
    for tag in sorted(xs["by_tag"]):
        t = xs["by_tag"][tag]
        if "flops" in t:
            doc.sample("lgbmtpu_xla_flops", "gauge", t["flops"],
                       labels={"tag": tag},
                       help_text="XLA cost-analysis flops per compiled "
                                 "program set")
        if "bytes_accessed" in t:
            doc.sample("lgbmtpu_xla_bytes_accessed", "gauge",
                       t["bytes_accessed"], labels={"tag": tag})

    # device-time attribution + roofline (obs/profile.py; emits nothing
    # until a tpu_profile window captured something)
    from .profile import global_profile
    ps = global_profile.summary()
    if ps.get("device_seconds_by_tag"):
        doc.sample("lgbmtpu_profile_window_seconds", "gauge",
                   ps.get("window_wall_s", 0.0),
                   help_text="cumulative wall time of tpu_profile "
                             "capture windows")
        if "coverage" in ps:
            doc.sample("lgbmtpu_profile_coverage", "gauge",
                       ps["coverage"],
                       help_text="attributed device seconds / window "
                                 "wall time (perf-gate check 11 band)")
        src = ps.get("source", "fallback")
        for tag in sorted(ps["device_seconds_by_tag"]):
            doc.sample("lgbmtpu_profile_device_seconds_total", "counter",
                       ps["device_seconds_by_tag"][tag],
                       labels={"tag": tag, "source": src},
                       help_text="measured device-busy seconds per "
                                 "program tag (jax.profiler trace or "
                                 "the block_until_ready fallback)")
        for tag in sorted(ps.get("calls_by_tag", {})):
            doc.sample("lgbmtpu_profile_calls_total", "counter",
                       ps["calls_by_tag"][tag], labels={"tag": tag})
        rl = global_profile.last_roofline
        if rl is None:
            try:
                rl = global_profile.roofline()
            except Exception:
                rl = None
        if isinstance(rl, dict):
            for tag in sorted(rl.get("by_tag", {})):
                row = rl["by_tag"][tag]
                if "achieved_bytes_per_s" in row:
                    doc.sample("lgbmtpu_profile_achieved_bytes_per_second",
                               "gauge", row["achieved_bytes_per_s"],
                               labels={"tag": tag},
                               help_text="achieved HBM bytes/s per tag "
                                         "vs hostenv.platform_peaks")
                for res, key in (("bytes", "bytes_utilization"),
                                 ("flops", "flops_utilization")):
                    if key in row:
                        doc.sample("lgbmtpu_profile_utilization", "gauge",
                                   row[key],
                                   labels={"tag": tag, "resource": res},
                                   help_text="achieved/peak throughput "
                                             "fraction (roofline)")

    # training-health families (obs/health.py; empty summary — health
    # never armed — emits nothing, asserted by tools/check_health.py)
    from .health import global_health
    hs = global_health.summary()
    for tag in sorted(hs.get("collectives", {})):
        ent = hs["collectives"][tag]
        lab = {"tag": tag, "op": ent.get("op", "")}
        doc.sample("lgbmtpu_health_collective_calls_total", "counter",
                   ent.get("calls", 0), labels=lab,
                   help_text="collectives actually issued at runtime, "
                             "attributed per program call (obs/health.py)")
        doc.sample("lgbmtpu_health_collective_bytes_total", "counter",
                   ent.get("bytes", 0), labels=lab)
    for op in sorted(hs.get("collective_probe", {})):
        p = hs["collective_probe"][op]
        doc.sample("lgbmtpu_health_collective_seconds_total", "counter",
                   p.get("seconds", 0.0), labels={"op": op},
                   help_text="device-synchronized wall time of the "
                             "collective microprobe")
        doc.sample("lgbmtpu_health_collective_probe_bytes_total",
                   "counter", p.get("bytes", 0), labels={"op": op})
    strag = hs.get("straggler") or {}
    for phase in sorted(strag.get("phases", {})):
        ph = strag["phases"][phase]
        skew = ph.get("skew", 1.0)
        if isinstance(skew, (int, float)) and skew == skew \
                and skew not in (float("inf"),):
            doc.sample("lgbmtpu_health_straggler_skew", "gauge", skew,
                       labels={"phase": phase},
                       help_text="per-phase max/median host-time skew "
                                 "across shards (worst-shard ordinal in "
                                 "the health summary)")
    drift = hs.get("drift") or {}
    if drift:
        doc.sample("lgbmtpu_health_drift_checks_total", "counter",
                   drift.get("checks", 0),
                   help_text="cross-shard replicated-state digest "
                             "comparisons run")
        doc.sample("lgbmtpu_health_drift_mismatch_total", "counter",
                   drift.get("mismatches", 0))
    nf = hs.get("nonfinite") or {}
    for kind in ("grad", "hess", "scores"):
        if kind in nf:
            doc.sample("lgbmtpu_health_nonfinite_total", "counter",
                       nf[kind], labels={"kind": kind},
                       help_text="NaN/Inf entries caught by the "
                                 "per-iteration sentinel")
    if nf:
        doc.sample("lgbmtpu_health_nonfinite_iterations_total", "counter",
                   nf.get("flagged_iterations", 0))
    for kind in sorted(k for k in (hs.get("eval") or {})
                       if k != "last"):
        doc.sample("lgbmtpu_health_eval_anomalies_total", "counter",
                   hs["eval"][kind], labels={"kind": kind},
                   help_text="eval-loss anomaly flags "
                             "(nan/spike/plateau)")

    for fam_name in sorted(extra_gauges or {}):
        doc.sample(fam_name, "gauge", extra_gauges[fam_name])
    return doc.text()


# ---------------------------------------------------------------------------
class MetricsHTTPEndpoint:
    """Daemon-thread HTTP listener for /metrics, /healthz, /readyz.

    `render_fn` produces the /metrics body; `ready_fn` (optional)
    gates /readyz (False -> 503). Binds `port` (0 = ephemeral; read the
    chosen one back from ``.port``)."""

    def __init__(self, render_fn: Callable[[], str],
                 ready_fn: Optional[Callable[[], bool]] = None,
                 port: int = 0, host: str = "127.0.0.1") -> None:
        import http.server

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes,
                      ctype: str = "text/plain; charset=utf-8") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    try:
                        body = render_fn().encode()
                    except Exception as exc:
                        self._send(500, f"render failed: {exc}\n".encode())
                        return
                    self._send(200, body, negotiate_content_type(
                        self.headers.get("Accept")))
                elif path == "/healthz":
                    self._send(200, b"ok\n")
                elif path == "/readyz":
                    ready = True if ready_fn is None else bool(ready_fn())
                    self._send(200 if ready else 503,
                               b"ready\n" if ready else b"warming\n")
                else:
                    self._send(404, b"not found\n")

            def log_message(self, *args) -> None:
                pass  # scrapes must not spam the training log

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="lgbm-metrics-http",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


# ---------------------------------------------------------------------------
class MetricsTextfileFlusher:
    """Periodic atomic flush of the rendered document to a textfile
    (node-exporter textfile-collector shape). Armed by the
    ``LGBM_TPU_METRICS_FILE`` env var; ``maybe_flush()`` is the
    per-iteration hook — one attribute check when unarmed."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._last = 0.0
        self.rearm()

    def rearm(self) -> None:
        """Re-read the env knobs (tests toggle them at runtime)."""
        self.path = os.environ.get("LGBM_TPU_METRICS_FILE", "")
        self.armed = bool(self.path)
        try:
            self.interval_s = float(os.environ.get(
                "LGBM_TPU_METRICS_FLUSH_SECS", "") or 15.0)
        except ValueError:
            self.interval_s = 15.0

    def maybe_flush(self, force: bool = False) -> bool:
        if not self.armed:
            return False
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last < self.interval_s:
                return False
            self._last = now
        return self.flush()

    def flush(self) -> bool:
        if not self.armed:
            return False
        try:
            text = render_openmetrics()
            tmp = self.path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(text)
            os.replace(tmp, self.path)  # scrapers never see a torn file
            return True
        except Exception:
            return False  # egress must never take training down


global_flusher = MetricsTextfileFlusher()


def _flush_at_exit() -> None:
    if global_flusher.armed:
        global_flusher.flush()


atexit.register(_flush_at_exit)
