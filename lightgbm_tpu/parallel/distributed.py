"""Multi-host (multi-process) training seam.

TPU-native replacement for the reference's socket/MPI transport layer
(ref: src/network/linkers.h:38 Linkers, linkers_socket.cpp machine-list
handshake). Instead of a TCP mesh with hand-rolled Bruck/halving
collectives, processes join one JAX distributed runtime
(`jax.distributed.initialize`): every chip in every process lands in one
global device list, a `Mesh` spans them, and XLA lowers the same
`psum`/`psum_scatter`/`all_gather` the single-host path uses — over
ICI within a slice and DCN across slices.

The reference's machine-list convention is kept as the user-facing
config surface (`machines="ip:port,ip:port"`, `num_machines`,
`local_listen_port`): the first machine is the coordinator, and each
process identifies itself by `process_id` (or the LGBM_TPU_RANK env
var), mirroring how each reference worker finds itself in mlist.txt.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from .. import log

_initialized = False


def parse_machine_list(machines) -> List[str]:
    """Accept the reference's formats: comma list "ip:port,ip:port", or
    lines "ip port" (mlist.txt, ref: examples/parallel_learning)."""
    if isinstance(machines, (list, tuple)):
        entries = [str(m) for m in machines]
    else:
        text = str(machines)
        if "\n" in text or (os.path.sep in text and os.path.exists(text)):
            if os.path.exists(text):
                text = open(text).read()
            entries = [ln.strip() for ln in text.splitlines() if ln.strip()]
        else:
            entries = [tok.strip() for tok in text.split(",") if tok.strip()]
    out = []
    for e in entries:
        out.append(e.replace(" ", ":") if ":" not in e else e)
    return out


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     machines=None,
                     local_device_ids=None,
                     initialization_timeout: Optional[float] = None,
                     connect_retries: int = 4,
                     connect_backoff_s: float = 0.5) -> None:
    """Join this process into the global JAX runtime.

    Either pass `coordinator_address`/`num_processes`/`process_id`
    directly, or a reference-style `machines` list (first entry is the
    coordinator; `process_id` falls back to the LGBM_TPU_RANK env var).
    Idempotent per process — a second call (even through a different
    layer that already ran ``jax.distributed.initialize``) is a no-op.

    A coordinator that is still coming up is the common fleet-restart
    race (every worker execs at once; rank 0's service binds last), so
    the connection is retried ``connect_retries`` times with exponential
    backoff before giving up with a structured
    :class:`~lightgbm_tpu.resilience.errors.DistributedInitError` that a
    supervisor can match on without string-parsing a JAX traceback.
    """
    global _initialized
    if _initialized:
        return
    import jax

    if machines is not None:
        mlist = parse_machine_list(machines)
        if not mlist:
            raise ValueError("empty machine list")
        coordinator_address = coordinator_address or mlist[0]
        num_processes = num_processes or len(mlist)
    if process_id is None:
        env_rank = os.environ.get("LGBM_TPU_RANK")
        if env_rank is None:
            raise ValueError(
                "process_id is required (or set LGBM_TPU_RANK): each "
                "worker must know its rank, like each reference worker "
                "finds itself in mlist.txt")
        process_id = int(env_rank)
    kwargs = {}
    if initialization_timeout is not None:
        kwargs["initialization_timeout"] = int(initialization_timeout)

    from ..resilience.degrade import backoff_delays
    from ..resilience.errors import DistributedInitError

    attempts = max(1, int(connect_retries) + 1)
    delays = backoff_delays(attempts - 1, float(connect_backoff_s),
                            cap_s=10.0)
    last_error: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids,
                **kwargs)
            break
        except RuntimeError as exc:
            # a prior direct jax.distributed.initialize() by the caller
            # (or a framework above us) — adopt it, don't fight it
            if "already initialized" in str(exc).lower():
                log.info("distributed runtime was already initialized; "
                         "adopting the existing client")
                break
            last_error = exc
        except (ValueError, TypeError):
            raise  # misconfiguration, retrying cannot fix it
        except Exception as exc:  # connect/handshake faults
            last_error = exc
        if attempt < attempts - 1:
            delay = delays[attempt]
            log.warning(
                f"distributed init attempt {attempt + 1}/{attempts} "
                f"failed ({last_error}); retrying in {delay:.2f}s")
            import time
            time.sleep(delay)
    else:
        raise DistributedInitError(
            f"could not join the distributed runtime at "
            f"{coordinator_address!r} after {attempts} attempts: "
            f"{last_error}", attempts=attempts, last_error=last_error)
    _initialized = True
    log.info(f"distributed runtime up: process {process_id}/"
             f"{num_processes}, {len(jax.devices())} global devices "
             f"({len(jax.local_devices())} local)")


def is_initialized() -> bool:
    return _initialized


def process_count() -> int:
    import jax
    return jax.process_count()


def process_index() -> int:
    import jax
    return jax.process_index()


# ----------------------------------------------------------------------
# host-metadata sync (the analog of the reference's rank-0 bin-mapper
# sync during distributed loading, dataset_loader.cpp:211)


def _broadcast_bytes(payload: Optional[bytes]) -> bytes:
    """Broadcast a byte string from process 0 to all (two-phase:
    length, then padded data)."""
    import jax
    from jax.experimental import multihost_utils

    root = jax.process_index() == 0
    length = np.array([len(payload) if root and payload is not None else 0],
                      np.int64)
    length = np.asarray(
        multihost_utils.broadcast_one_to_all(length))
    n = int(length[0])
    buf = np.zeros(n, np.uint8)
    if root and payload is not None:
        buf[:] = np.frombuffer(payload, np.uint8)
    buf = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    return buf.tobytes()


def sync_bin_mappers(mappers):
    """Rank 0's bin mappers win; everyone else re-bins its local shard
    with them (ref: dataset_loader.cpp:211 — rank 0 samples, finds
    boundaries and syncs them so all machines agree on bin ids)."""
    import jax
    from ..io.binary_format import _mapper_from_state, _mapper_state

    if jax.process_count() <= 1:
        return mappers
    payload = None
    if jax.process_index() == 0:
        payload = json.dumps([_mapper_state(m) for m in mappers]).encode()
    data = _broadcast_bytes(payload)
    states = json.loads(data.decode())
    return [_mapper_from_state(s) for s in states]


def sync_dataset(dataset) -> None:
    """Align a constructed basic.Dataset's binning with rank 0
    (ref: dataset_loader.cpp:211 — rank 0's bin boundaries win and every
    machine re-extracts its local rows with them). In-place."""
    import jax
    if jax.process_count() <= 1:
        return
    binned = dataset._binned
    if binned.bundle_info is not None:
        raise ValueError("EFB bundling is not supported with multi-host "
                         "training yet; set enable_bundle=false")
    from ..io.binary_format import _mapper_from_state, _mapper_state
    payload = None
    if jax.process_index() == 0:
        payload = json.dumps({
            "mappers": [_mapper_state(m) for m in binned.mappers],
            "used_features": [int(c) for c in binned.used_features],
        }).encode()
    blob = json.loads(_broadcast_bytes(payload).decode())
    if jax.process_index() != 0:
        from ..dataset import _transform_all
        raw = binned.raw_data
        if raw is None:
            raise ValueError(
                "multi-host bin sync needs raw feature values on every "
                "process (in-memory datasets only for now)")
        from ..dataset import is_sparse
        if is_sparse(raw):
            raise ValueError(
                "multi-host bin sync does not support sparse matrices "
                "yet; densify the per-rank partition or pre-bin with a "
                "shared reference dataset")
        binned.mappers = [_mapper_from_state(s) for s in blob["mappers"]]
        binned.used_features = list(blob["used_features"])
        binned.bins_fm = _transform_all(
            np.asarray(raw), binned.mappers, binned.used_features,
            binned.bins_fm.dtype)
        binned._device_cache.clear()


def make_global_array(mesh, local_rows: np.ndarray, row_axis: int):
    """Assemble a globally-sharded array from per-process row shards
    (the multi-host version of mesh.shard_data)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from .mesh import DATA_AXIS

    spec = [None] * local_rows.ndim
    spec[row_axis] = DATA_AXIS
    sharding = NamedSharding(mesh, P(*spec))
    if jax.process_count() <= 1:
        return jax.device_put(local_rows, sharding)
    return jax.make_array_from_process_local_data(sharding, local_rows)
