"""scikit-learn estimator API.

(ref: python-package/lightgbm/sklearn.py:535 LGBMModel, :1409
LGBMRegressor, :1524 LGBMClassifier, :1832 LGBMRanker.)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset, LightGBMError
from .engine import train as train_fn


def _same_data(a, b) -> bool:
    """Is the eval-set matrix the training matrix (so its Dataset can be
    reused)? Sparse matrices compare by identity only."""
    if a is b:
        return True
    try:
        import scipy.sparse as sp
        if sp.issparse(a) or sp.issparse(b):
            return False
    except ImportError:
        pass
    a, b = np.asarray(a), np.asarray(b)
    return bool(np.shares_memory(a, b) or
                (a.shape == b.shape and
                 np.array_equal(a.astype(np.float64),
                                b.astype(np.float64))))


class LGBMModel:
    """Base estimator (ref: sklearn.py:535)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None,
                 class_weight=None, min_split_gain: float = 0.0,
                 min_child_weight: float = 1e-3, min_child_samples: int = 20,
                 subsample: float = 1.0, subsample_freq: int = 0,
                 colsample_bytree: float = 1.0, reg_alpha: float = 0.0,
                 reg_lambda: float = 0.0, random_state=None,
                 n_jobs: Optional[int] = None, importance_type: str = "split",
                 **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._n_features: Optional[int] = None
        self._objective = objective
        self.fitted_ = False

    # -- sklearn plumbing ------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type, "num_leaves": self.num_leaves,
            "max_depth": self.max_depth, "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective, "class_weight": self.class_weight,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample, "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha, "reg_lambda": self.reg_lambda,
            "random_state": self.random_state, "n_jobs": self.n_jobs,
            "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    def _lgb_params(self) -> Dict[str, Any]:
        p = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "objective": self._objective,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbosity": -1,
        }
        if self.random_state is not None:
            p["seed"] = int(self.random_state) if not hasattr(
                self.random_state, "randint") else \
                int(self.random_state.randint(0, 2 ** 31))
        p.update(self._other_params)
        return p

    # -- fitting ---------------------------------------------------------
    def _sample_weight_with_class_weight(self, y, sample_weight):
        if self.class_weight is None:
            return sample_weight
        classes, counts = np.unique(y, return_counts=True)
        if self.class_weight == "balanced":
            cw = {c: len(y) / (len(classes) * cnt)
                  for c, cnt in zip(classes, counts)}
        else:
            cw = dict(self.class_weight)
        w = np.array([cw.get(v, 1.0) for v in y], np.float64)
        if sample_weight is not None:
            w = w * np.asarray(sample_weight, np.float64)
        return w

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            feature_name="auto", categorical_feature="auto",
            callbacks=None) -> "LGBMModel":
        params = self._lgb_params()
        if eval_metric is not None:
            params["metric"] = eval_metric
        sample_weight = self._sample_weight_with_class_weight(y, sample_weight)

        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            params=params)
        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = (eval_sample_weight[i]
                      if eval_sample_weight is not None else None)
                vg = eval_group[i] if eval_group is not None else None
                vi = (eval_init_score[i]
                      if eval_init_score is not None else None)
                if _same_data(vx, X):
                    valid_sets.append(train_set)
                else:
                    valid_sets.append(Dataset(
                        vx, label=vy, weight=vw, group=vg, init_score=vi,
                        reference=train_set, params=params))
                valid_names.append(
                    eval_names[i] if eval_names else f"valid_{i}")

        self._Booster = train_fn(params, train_set,
                                 num_boost_round=self.n_estimators,
                                 valid_sets=valid_sets,
                                 valid_names=valid_names,
                                 callbacks=callbacks)
        self._n_features = (X.shape[1] if hasattr(X, "shape")
                            else np.asarray(X).shape[1])
        self.fitted_ = True
        return self

    # -- introspection ---------------------------------------------------
    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def n_features_(self) -> int:
        self._check_fitted()
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self.n_features_

    @property
    def best_iteration_(self) -> int:
        self._check_fitted()
        return self._Booster.best_iteration

    @property
    def best_score_(self):
        self._check_fitted()
        return self._Booster.best_score

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_name()

    def _check_fitted(self):
        if not self.fitted_:
            raise LightGBMError("Estimator not fitted; call fit first")

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: int = -1, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        self._check_fitted()
        # serving-engine kwargs (tpu_predict_chunk, ...) pass through to
        # Booster.predict; pred_contrib=True rides the batched device
        # TreeSHAP kernel (ops/shap.py) under the same chunk override
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, **kwargs)


class LGBMRegressor(LGBMModel):
    """(ref: sklearn.py:1409)"""

    def fit(self, X, y, **kwargs) -> "LGBMRegressor":
        if self._objective is None:
            self._objective = "regression"
        super().fit(X, y, **kwargs)
        return self

    def score(self, X, y, sample_weight=None) -> float:
        pred = self.predict(X)
        y = np.asarray(y, np.float64)
        u = np.sum((y - pred) ** 2)
        v = np.sum((y - y.mean()) ** 2)
        return 1.0 - u / v if v > 0 else 0.0


class LGBMClassifier(LGBMModel):
    """(ref: sklearn.py:1524)"""

    def fit(self, X, y, **kwargs) -> "LGBMClassifier":
        y = np.asarray(y)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        y_enc = np.searchsorted(self._classes, y).astype(np.float64)
        if self._objective is None:
            self._objective = ("binary" if self._n_classes <= 2
                               else "multiclass")
        params_extra = {}
        if self._n_classes > 2:
            self._other_params.setdefault("num_class", self._n_classes)
        super().fit(X, y_enc, **kwargs)
        del params_extra
        return self

    @property
    def classes_(self):
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        self._check_fitted()
        return self._n_classes

    def predict_proba(self, X, **kwargs) -> np.ndarray:
        prob = super().predict(X, **kwargs)
        if prob.ndim == 1:
            prob = np.column_stack([1.0 - prob, prob])
        return prob

    def predict(self, X, raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        if raw_score or pred_leaf or pred_contrib:
            return super().predict(X, raw_score=raw_score,
                                   pred_leaf=pred_leaf,
                                   pred_contrib=pred_contrib, **kwargs)
        prob = self.predict_proba(X, **kwargs)
        return self._classes[np.argmax(prob, axis=1)]

    def score(self, X, y, sample_weight=None) -> float:
        return float(np.mean(self.predict(X) == np.asarray(y)))


class LGBMRanker(LGBMModel):
    """(ref: sklearn.py:1832)"""

    def fit(self, X, y, group=None, **kwargs) -> "LGBMRanker":
        if group is None:
            raise LightGBMError("LGBMRanker.fit requires group")
        if self._objective is None:
            self._objective = "lambdarank"
        super().fit(X, y, group=group, **kwargs)
        return self
