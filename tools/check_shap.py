#!/usr/bin/env python
"""CI smoke for the TreeSHAP explanation path (ops/shap.py + the
served ``explain`` route).

Three assertions, mirroring tools/check_serve.py for the explain
subsystem:

1. **Oracle parity**: the batched device kernel's contributions match
   the reference-recursion host oracle (shap._tree_shap) on a mixed
   fixture — binary model trained on data with NaNs — within f32
   recurrence tolerance, and additivity holds (contributions sum to
   the raw prediction per row).
2. **Served bit-parity**: every ``ModelServer.explain`` response —
   low-latency AOT ladder and coalesced micro-batches alike — is
   BIT-identical to calling ``predict_contrib`` directly on that
   request's rows, with ZERO steady-state recompiles after warmup on
   both the streaming kernel tag and the AOT explain tag.
3. **Metrics lint**: the rendered OpenMetrics document carries the
   ``lgbmtpu_explain_*`` families (request/row counters + the
   dedicated latency summary).

Exit 0 = pass. Usage: python tools/check_shap.py
"""

import asyncio
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> int:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs.export import render_openmetrics
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.ops.shap import SHAP_TRACE_TAG
    from lightgbm_tpu.serve import (ModelRegistry, ModelServer,
                                    SERVE_EXPLAIN_TAG)
    from lightgbm_tpu import shap as shap_mod

    failures = 0
    rng = np.random.RandomState(0)
    n, f = 1200, 10
    x = rng.randn(n, f)
    x[::7, 2] = np.nan
    y = ((np.nan_to_num(x[:, 2]) + x[:, 4]) > 0.5).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                    num_boost_round=10)

    # 1. device kernel vs host recursive oracle + additivity
    probe = x[:256]
    dev = bst.predict(probe, pred_contrib=True)
    gbdt = bst._gbdt
    oracle = shap_mod._contrib_over_trees(
        lambda it, ki: gbdt.models[it][ki], gbdt.current_iteration(), 1,
        probe, f, 0, -1)
    scale = max(np.abs(oracle).max(), 1.0)
    err = np.abs(dev - oracle).max() / scale
    if err > 2e-3:
        print(f"FAIL: device contribs vs host oracle rel err {err:g}")
        failures += 1
    raw = bst.predict(probe, raw_score=True)
    add_err = np.abs(dev.sum(axis=1) - raw).max() / max(
        np.abs(raw).max(), 1.0)
    if add_err > 2e-3:
        print(f"FAIL: additivity rel err {add_err:g}")
        failures += 1

    # 2. served explain route: bit-parity + zero steady-state recompiles
    registry = ModelRegistry()
    registry.load("smoke", booster=bst)
    direct = registry.get("smoke").model
    server = ModelServer(registry, max_batch_rows=1024, max_wait_ms=1.0)
    server.warm("smoke", f, explain=True)

    warm_explain = global_metrics.recompiles(SERVE_EXPLAIN_TAG)
    warm_kernel = global_metrics.recompiles(SHAP_TRACE_TAG)

    # mixed sizes: lowlat ladder (<=64), coalescable mediums, and one
    # oversized request per cycle; uneven counts exercise the buckets
    cycle = (1, 3, 8, 17, 64, 2, 130, 31, 257, 5, 700, 16, 64, 1, 23)
    sizes = [cycle[i % len(cycle)] for i in range(60)]
    xt = rng.randn(sum(sizes), f)
    xt[::9, 2] = np.nan

    async def run():
        async def one(lo, hi):
            return await server.explain("smoke", xt[lo:hi])

        tasks = []
        lo = 0
        for s in sizes:
            tasks.append(asyncio.ensure_future(one(lo, lo + s)))
            lo += s
        try:
            return await asyncio.gather(*tasks)
        finally:
            await server.close()

    t0 = time.perf_counter()
    outs = asyncio.run(run())
    elapsed = time.perf_counter() - t0

    lo = 0
    for i, (s, out) in enumerate(zip(sizes, outs)):
        hi = lo + s
        want = direct.predict_contrib(xt[lo:hi])
        if not np.array_equal(out, want):
            print(f"FAIL: explain request {i} ({s} rows) != direct "
                  f"predict_contrib (max abs diff "
                  f"{np.abs(out - want).max():g})")
            failures += 1
        lo = hi

    d_explain = global_metrics.recompiles(SERVE_EXPLAIN_TAG) - warm_explain
    d_kernel = global_metrics.recompiles(SHAP_TRACE_TAG) - warm_kernel
    if d_explain or d_kernel:
        print(f"FAIL: steady-state recompiles (explain_lowlat="
              f"{d_explain}, shap_kernel={d_kernel}) — the warm "
              "bucket set leaked")
        failures += 1
    coalesced = global_metrics.counters.get("explain/coalesced_requests", 0)
    if not coalesced:
        print("FAIL: no explain requests coalesced — the mixed replay "
              "must exercise the explain micro-batcher")
        failures += 1

    # 3. OpenMetrics lint: the explain families must render
    doc = render_openmetrics()
    for family in ("lgbmtpu_explain_requests_total",
                   "lgbmtpu_explain_rows_total",
                   "lgbmtpu_explain_lowlat_requests_total",
                   "lgbmtpu_explain_batched_requests_total",
                   "lgbmtpu_explain_latency_seconds"):
        if family not in doc:
            print(f"FAIL: family {family} missing from the rendered "
                  "OpenMetrics document")
            failures += 1

    lat = global_metrics.latency_summary("explain/request")
    counters = {k: v for k, v in sorted(global_metrics.counters.items())
                if k.startswith("explain/")}
    print(f"explained {len(outs)} requests ({lo} rows) in {elapsed:.2f}s "
          f"({lo / elapsed:.0f} rows/s); p50={lat['p50_ms']:.2f}ms "
          f"p99={lat['p99_ms']:.2f}ms; counters={counters}")
    if failures:
        print(f"check_shap: {failures} failure(s)")
        return 1
    print("check_shap: OK (oracle parity, served bit-parity incl. "
          "coalesced batches, zero steady-state recompiles, "
          "lgbmtpu_explain_* families present)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
