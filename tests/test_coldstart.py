"""Warm start everywhere (ISSUE 14): persistent compile-cache policy +
serialized AOT serving artifacts.

Covers:
- ``compile_cache`` policy semantics: auto respects an existing
  configuration (the conftest's), off never touches jax config, on
  forces a directory; the version-gated donation guard
  (``donation_allowed``) and the env force-off;
- cache hygiene: the LRU prune caps the directory, oldest entries
  first, env-tunable, unbounded = no-op;
- serialized artifacts (serve/artifacts.py): export/restore round trip
  is bit-identical with ZERO serve/lowlat compiles, warm() is
  idempotent per (bucket, width), a foreign fingerprint or a corrupt
  artifact transparently falls back to a fresh compile (counted), and
  predictions are bit-identical either way;
- second-process warm start: the same small train in two fresh
  interpreters sharing a fresh cache dir — the warm rerun HITS the
  persistent cache and its real compile seconds collapse (obs/xla
  attributes cache hits to ``cache_load_s_total``);
- the quick-tier tools: perf-gate check 10 units + the
  tools/check_coldstart.py validator wiring.
"""

import json
import os
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import compile_cache
from lightgbm_tpu.config import Config
from lightgbm_tpu.obs.metrics import global_metrics
from lightgbm_tpu.serve import (ModelRegistry, SERVE_LOWLAT_TAG,
                                serialize_available)
from lightgbm_tpu.serve import artifacts as artifacts_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
for _p in (REPO, TOOLS):
    if _p not in sys.path:
        sys.path.insert(0, _p)

_F = 6


@pytest.fixture(scope="module")
def model_str():
    r = np.random.RandomState(3)
    X = r.randn(500, _F)
    y = (X[:, 0] + 0.4 * X[:, 1] ** 2 > 0.2).astype(np.float32)
    params = dict(objective="binary", num_leaves=7, max_bin=31,
                  min_data_in_leaf=5, verbosity=-1)
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    return lgb.train(params, ds, num_boost_round=3).model_to_string()


class TestCompileCachePolicy:
    def test_auto_respects_existing_configuration(self):
        # conftest armed the cache for the whole test process; auto at
        # a later entry (every Booster/train call) must be a no-op
        import jax
        before = jax.config.jax_compilation_cache_dir
        assert before, "test process should run with the conftest cache"
        assert compile_cache.configure("auto") is True
        assert jax.config.jax_compilation_cache_dir == before

    def test_off_never_touches(self):
        import jax
        before = jax.config.jax_compilation_cache_dir
        assert compile_cache.configure("off") is False
        assert jax.config.jax_compilation_cache_dir == before

    def test_on_forces_dir(self, tmp_path):
        import jax
        before = jax.config.jax_compilation_cache_dir
        try:
            assert compile_cache.configure("on", str(tmp_path)) is True
            assert jax.config.jax_compilation_cache_dir == str(tmp_path)
        finally:
            compile_cache.configure("on", before)

    def test_unknown_mode_treated_as_auto(self):
        import jax
        before = jax.config.jax_compilation_cache_dir
        assert compile_cache.configure("bogus") is True
        assert jax.config.jax_compilation_cache_dir == before

    def test_cache_active_reports_jax_config(self):
        assert compile_cache.cache_active() is True  # conftest armed it

    def test_donation_env_force_off(self, monkeypatch):
        monkeypatch.setenv("LGBM_TPU_NO_DONATE", "1")
        assert compile_cache.donation_allowed() is False

    def test_donation_version_gate(self, monkeypatch):
        monkeypatch.delenv("LGBM_TPU_NO_DONATE", raising=False)
        # cache is active (conftest): affected jaxlib drops donation,
        # a fixed one keeps it
        monkeypatch.setattr(compile_cache, "_jaxlib_version",
                            lambda: (0, 4, 36))
        assert compile_cache.donation_allowed() is False
        monkeypatch.setattr(compile_cache, "_jaxlib_version",
                            lambda: (0, 4, 38))
        assert compile_cache.donation_allowed() is True
        # no cache => donation always allowed
        monkeypatch.setattr(compile_cache, "cache_active", lambda: False)
        monkeypatch.setattr(compile_cache, "_jaxlib_version",
                            lambda: (0, 4, 30))
        assert compile_cache.donation_allowed() is True

    def test_default_dir_resolution(self, monkeypatch):
        monkeypatch.setenv("LGBM_TPU_COMPILE_CACHE_DIR", "/tmp/xyz_cc")
        assert compile_cache.default_cache_dir() == "/tmp/xyz_cc"
        monkeypatch.delenv("LGBM_TPU_COMPILE_CACHE_DIR")
        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
        assert compile_cache.default_cache_dir() == \
            compile_cache.repo_cache_dir()

    def test_knob_aliases(self):
        cfg = Config.from_params({"compile_cache": "off",
                                  "compile_cache_dir": "/tmp/d",
                                  "artifact_dir": "/tmp/a"})
        assert cfg.tpu_compile_cache == "off"
        assert cfg.tpu_compile_cache_dir == "/tmp/d"
        assert cfg.serve_artifact_dir == "/tmp/a"
        assert Config.from_params({}).tpu_compile_cache == "auto"


class TestCachePrune:
    def _fill(self, root, sizes):
        os.makedirs(root, exist_ok=True)
        paths = []
        for i, size in enumerate(sizes):
            p = os.path.join(root, f"f{i}.bin")
            with open(p, "wb") as fh:
                fh.write(b"x" * size)
            # strictly increasing mtimes: f0 oldest
            os.utime(p, (1000 + i, 1000 + i))
            paths.append(p)
        return paths

    def test_prune_caps_and_removes_oldest_first(self, tmp_path):
        root = str(tmp_path / "cache")
        paths = self._fill(root, [100, 100, 100, 100])
        removed = compile_cache.prune_cache(root, max_bytes=250)
        assert removed == 200
        assert not os.path.exists(paths[0])
        assert not os.path.exists(paths[1])
        assert os.path.exists(paths[2]) and os.path.exists(paths[3])
        assert compile_cache.cache_size_bytes(root) == 200

    def test_prune_unbounded_is_noop(self, tmp_path):
        root = str(tmp_path / "cache")
        paths = self._fill(root, [100, 100])
        assert compile_cache.prune_cache(root, max_bytes=0) == 0
        assert all(os.path.exists(p) for p in paths)

    def test_prune_under_cap_is_noop(self, tmp_path):
        root = str(tmp_path / "cache")
        self._fill(root, [100])
        assert compile_cache.prune_cache(root, max_bytes=1000) == 0

    def test_prune_env_tunable(self, tmp_path, monkeypatch):
        root = str(tmp_path / "cache")
        self._fill(root, [100, 100])
        monkeypatch.setenv("LGBM_TPU_COMPILE_CACHE_MAX_BYTES", "150")
        assert compile_cache.prune_cache(root) == 100

    def test_prune_missing_dir_is_safe(self, tmp_path):
        assert compile_cache.prune_cache(str(tmp_path / "nope"),
                                         max_bytes=1) == 0


@pytest.mark.skipif(not serialize_available(),
                    reason="no executable serialization on this jax")
class TestArtifactStore:
    def test_roundtrip_after_eviction_zero_compiles(self, tmp_path,
                                                    model_str):
        reg = ModelRegistry(artifact_dir=str(tmp_path))
        entry = reg.load("m", model_str=model_str)
        n = entry.lowlat.warm(_F)
        assert n == len(entry.lowlat.buckets())
        assert len(os.listdir(str(tmp_path))) == n
        req = np.random.RandomState(0).randn(5, _F)
        ref = entry.lowlat(req)

        entry.drop_packs()  # LRU eviction drops packs + executables
        c0 = global_metrics.recompiles(SERVE_LOWLAT_TAG)
        loads0 = global_metrics.counters.get("serve/aot_loads", 0)
        entry.lowlat.warm(_F)
        assert global_metrics.recompiles(SERVE_LOWLAT_TAG) - c0 == 0
        assert global_metrics.counters.get("serve/aot_loads",
                                           0) - loads0 == n
        assert np.array_equal(ref, entry.lowlat(req))

    def test_fresh_registry_restores_from_disk(self, tmp_path, model_str):
        reg_a = ModelRegistry(artifact_dir=str(tmp_path))
        entry_a = reg_a.load("m", model_str=model_str)
        entry_a.lowlat.warm(_F)
        req = np.random.RandomState(1).randn(3, _F)
        ref = entry_a.lowlat(req)
        # the replica-restart twin: nothing shared but the directory
        reg_b = ModelRegistry(artifact_dir=str(tmp_path))
        entry_b = reg_b.load("m", model_str=model_str)
        c0 = global_metrics.recompiles(SERVE_LOWLAT_TAG)
        entry_b.lowlat.warm(_F)
        assert global_metrics.recompiles(SERVE_LOWLAT_TAG) - c0 == 0
        assert np.array_equal(ref, entry_b.lowlat(req))

    def test_warm_is_idempotent(self, tmp_path, model_str):
        reg = ModelRegistry(artifact_dir=str(tmp_path))
        entry = reg.load("m", model_str=model_str)
        entry.lowlat.warm(_F)
        c0 = global_metrics.recompiles(SERVE_LOWLAT_TAG)
        loads0 = global_metrics.counters.get("serve/aot_loads", 0)
        entry.lowlat.warm(_F)  # everything resident: no compile, no load
        assert global_metrics.recompiles(SERVE_LOWLAT_TAG) - c0 == 0
        assert global_metrics.counters.get("serve/aot_loads",
                                           0) - loads0 == 0

    def test_warm_idempotent_without_store_too(self, model_str):
        reg = ModelRegistry()  # no artifact dir
        entry = reg.load("m", model_str=model_str)
        entry.lowlat.warm(_F)
        c0 = global_metrics.recompiles(SERVE_LOWLAT_TAG)
        entry.lowlat.warm(_F)
        assert global_metrics.recompiles(SERVE_LOWLAT_TAG) - c0 == 0

    def test_export_artifacts_explicit(self, tmp_path, model_str):
        reg = ModelRegistry(artifact_dir=str(tmp_path))
        entry = reg.load("m", model_str=model_str)
        n = entry.lowlat.export_artifacts(_F)
        assert n == len(entry.lowlat.buckets())
        assert len([f for f in os.listdir(str(tmp_path))
                    if f.endswith(".aotx")]) == n

    def test_no_store_without_dir(self):
        assert artifacts_mod.open_store("") is None
        assert artifacts_mod.open_store(None) is None

    def test_fingerprint_mismatch_recompiles_bit_identical(
            self, tmp_path, model_str):
        reg_a = ModelRegistry(artifact_dir=str(tmp_path))
        entry_a = reg_a.load("m", model_str=model_str)
        entry_a.lowlat.warm(_F)
        req = np.random.RandomState(2).randn(4, _F)
        ref = entry_a.lowlat(req)
        orig = artifacts_mod.ARTIFACT_VERSION
        artifacts_mod.ARTIFACT_VERSION = orig + 1  # "new jaxlib" replica
        try:
            reg_b = ModelRegistry(artifact_dir=str(tmp_path))
            entry_b = reg_b.load("m", model_str=model_str)
            c0 = global_metrics.recompiles(SERVE_LOWLAT_TAG)
            entry_b.lowlat.warm(_F)
            assert global_metrics.recompiles(SERVE_LOWLAT_TAG) - c0 > 0
            assert np.array_equal(ref, entry_b.lowlat(req))
        finally:
            artifacts_mod.ARTIFACT_VERSION = orig

    def test_corrupt_artifact_falls_back(self, tmp_path, model_str):
        reg_a = ModelRegistry(artifact_dir=str(tmp_path))
        entry_a = reg_a.load("m", model_str=model_str)
        entry_a.lowlat.warm(_F)
        req = np.random.RandomState(4).randn(2, _F)
        ref = entry_a.lowlat(req)
        for name in os.listdir(str(tmp_path)):
            with open(os.path.join(str(tmp_path), name), "wb") as fh:
                fh.write(b"not an artifact")
        fails0 = global_metrics.counters.get("serve/aot_load_failures", 0)
        reg_b = ModelRegistry(artifact_dir=str(tmp_path))
        entry_b = reg_b.load("m", model_str=model_str)
        c0 = global_metrics.recompiles(SERVE_LOWLAT_TAG)
        entry_b.lowlat.warm(_F)
        assert global_metrics.recompiles(SERVE_LOWLAT_TAG) - c0 > 0
        assert global_metrics.counters.get("serve/aot_load_failures",
                                           0) > fails0
        assert np.array_equal(ref, entry_b.lowlat(req))

    def test_mutated_model_digest_never_loads_stale(self, tmp_path):
        from lightgbm_tpu.serve.lowlat import LowLatencyPredictor
        import bench as bench_mod
        rng = np.random.RandomState(5)
        trees = bench_mod._random_trees(rng, 4, 7, _F)
        p1 = LowLatencyPredictor(trees, 1, artifact_dir=str(tmp_path))
        p1.warm(_F)
        # a retrained twin: same shapes, different leaf values
        trees2 = bench_mod._random_trees(np.random.RandomState(6), 4, 7,
                                         _F)
        p2 = LowLatencyPredictor(trees2, 1, artifact_dir=str(tmp_path))
        c0 = global_metrics.recompiles(SERVE_LOWLAT_TAG)
        p2.warm(_F)
        assert global_metrics.recompiles(SERVE_LOWLAT_TAG) - c0 > 0, \
            "a different model's artifacts must never be loaded"


class TestSecondProcessWarmStart:
    def test_warm_rerun_hits_cache_and_compiles_near_zero(self, tmp_path):
        import bench as bench_mod
        os.environ["COLDSTART_ITERS"] = "2"
        os.environ["COLDSTART_LEAVES"] = "15"
        try:
            cold = bench_mod._coldstart_child_run(str(tmp_path), 3000)
            warm = bench_mod._coldstart_child_run(str(tmp_path), 3000)
        finally:
            os.environ.pop("COLDSTART_ITERS", None)
            os.environ.pop("COLDSTART_LEAVES", None)
        assert cold["compile_s_total"] > 0
        assert cold.get("n_cache_hits", 0) == 0
        assert warm.get("n_cache_hits", 0) > 0, \
            f"warm rerun never hit the persistent cache: {warm}"
        # "compile_s_total ~ 0": everything the warm process acquired
        # came off disk (attributed to cache_load_s_total instead)
        assert warm["compile_s_total"] <= \
            max(0.2 * cold["compile_s_total"], 0.05), (cold, warm)

    def test_bench_mode_registered(self):
        import bench as bench_mod
        assert bench_mod.parse_bench_mode(["--coldstart"], {}) == \
            "coldstart"
        assert "coldstart" in bench_mod._MODE_MEASURE


class TestGateCheck10:
    def _floor(self):
        return {"coldstart": {"min_compile_reduction": 5.0,
                              "max_warm_acquire_s": 5.0,
                              "max_restore_lowlat_compiles": 0}}

    def _candidate(self, tmp_path, cold=10.0, warm=0.1, load=1.0,
                   restore_compiles=0, bit_identical=True,
                   serialize=True):
        rec = {"metric": "coldstart_compile_reduction", "value": 1.0,
               "unit": "x (platform=cpu)", "vs_baseline": 1.0,
               "coldstart": {
                   "cold_compile_s": cold, "warm_compile_s": warm,
                   "warm_cache_load_s": load,
                   "artifact_serialize_available": serialize,
                   "restore_lowlat_compiles": restore_compiles,
                   "restore_aot_loads": 7,
                   "restore_bit_identical": bit_identical}}
        p = tmp_path / "BENCH_cand.json"
        p.write_text(json.dumps(rec))
        return str(p)

    def test_gate_passes(self, tmp_path):
        import check_perf_gate
        failures = []
        check_perf_gate.check_coldstart(self._floor(), failures,
                                        self._candidate(tmp_path))
        assert failures == []

    def test_gate_fails_weak_reduction(self, tmp_path):
        import check_perf_gate
        failures = []
        check_perf_gate.check_coldstart(
            self._floor(), failures,
            self._candidate(tmp_path, cold=2.0, warm=1.0))
        assert any("not biting" in f for f in failures)

    def test_gate_fails_acquire_ceiling(self, tmp_path):
        import check_perf_gate
        failures = []
        check_perf_gate.check_coldstart(
            self._floor(), failures,
            self._candidate(tmp_path, cold=100.0, warm=0.5, load=6.0))
        assert any("ratchet ceiling" in f for f in failures)

    def test_gate_fails_restore_compiles(self, tmp_path):
        import check_perf_gate
        failures = []
        check_perf_gate.check_coldstart(
            self._floor(), failures,
            self._candidate(tmp_path, restore_compiles=7))
        assert any("not restoring" in f for f in failures)

    def test_gate_fails_parity(self, tmp_path):
        import check_perf_gate
        failures = []
        check_perf_gate.check_coldstart(
            self._floor(), failures,
            self._candidate(tmp_path, bit_identical=False))
        assert any("bit-identical" in f for f in failures)

    def test_gate_skips_restore_without_serialization(self, tmp_path):
        import check_perf_gate
        failures = []
        check_perf_gate.check_coldstart(
            self._floor(), failures,
            self._candidate(tmp_path, restore_compiles=7,
                            serialize=False))
        assert failures == []

    def test_gate_skips_without_floor_or_bench(self, tmp_path):
        import check_perf_gate
        failures = []
        check_perf_gate.check_coldstart({}, failures, None)
        empty = tmp_path / "BENCH_none.json"
        empty.write_text(json.dumps({"metric": "x"}))
        check_perf_gate.check_coldstart(self._floor(), failures,
                                        str(empty))
        assert failures == []


class TestObsSplit:
    def test_summary_separates_compiles_from_cache_hits(self):
        from lightgbm_tpu.obs.xla import XlaIntrospector
        reg = XlaIntrospector()
        reg.note_compile("t", "train", "s", 2.0, object(), trace_s=1.0)
        reg.note_compile("t", "train", "s", 0.5, object(), trace_s=1.0,
                         cache_hit=True)
        s = reg.summary()
        assert s["compile_s_total"] == 2.0
        assert s["cache_load_s_total"] == 0.5
        assert s["n_cache_hits"] == 1
        assert s["trace_s_total"] == 2.0
        assert s["by_tag"]["t"]["compile_s"] == 2.0
        assert s["by_tag"]["t"]["cache_load_s"] == 0.5


class TestToolsWiring:
    @pytest.mark.slow
    def test_check_coldstart_tool(self):
        import check_coldstart
        assert check_coldstart.main() == 0
