"""Device (XLA) batch prediction over packed tree ensembles.

TPU-native analog of the reference prediction kernels
(ref: src/boosting/gbdt_prediction.cpp:16, CUDATree prediction kernels in
src/io/cuda/cuda_tree.cu). Trees are packed into dense [T, ...] tensors;
traversal is a `fori_loop` over depth with per-row gathers — all rows
advance one level per step (leaves self-loop), so the program has static
shape and vectorizes over the batch.

Categorical splits carry their category-value bitsets in a packed
[T, W] word tensor (the device mirror of tree.h:375 cat_threshold_ +
cat_boundaries_), checked with a dynamic word gather per row.
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

_DEFAULT_LEFT_MASK = 2


class PackedEnsemble(NamedTuple):
    """Dense ensemble tensors. T trees, I = max internal nodes, L = max
    leaves, D = max depth. Child convention: >=0 internal, <0 = ~leaf."""
    split_feature: jax.Array   # [T, I] int32
    threshold: jax.Array       # [T, I] f32 (real-valued)
    decision_type: jax.Array   # [T, I] int32
    left_child: jax.Array      # [T, I] int32
    right_child: jax.Array     # [T, I] int32
    leaf_value: jax.Array      # [T, L] f32
    num_internal: jax.Array    # [T] int32
    cat_start: jax.Array       # [T, I] int32 word offset into cat_words
    cat_nwords: jax.Array      # [T, I] int32 word count (0 = not cat)
    cat_words: jax.Array       # [T, W] uint32 bitset words
    max_depth: int             # static
    num_trees_per_class: int   # static (for multiclass reshape)


def pack_ensemble(trees: List, num_tree_per_iteration: int = 1
                  ) -> PackedEnsemble:
    """Pack host Tree objects (tree.py) into device tensors."""
    t = len(trees)
    max_i = max((tr.num_internal for tr in trees), default=0)
    max_i = max(max_i, 1)
    max_l = max((tr.num_leaves for tr in trees), default=1)
    max_w = max((len(tr.cat_threshold) for tr in trees), default=0)
    max_w = max(max_w, 1)
    sf = np.zeros((t, max_i), np.int32)
    th = np.zeros((t, max_i), np.float64)
    dt = np.zeros((t, max_i), np.int32)
    lc = np.full((t, max_i), -1, np.int32)
    rc = np.full((t, max_i), -1, np.int32)
    lv = np.zeros((t, max_l), np.float32)
    ni = np.zeros(t, np.int32)
    cs = np.zeros((t, max_i), np.int32)
    cn = np.zeros((t, max_i), np.int32)
    cw = np.zeros((t, max_w), np.uint32)
    depth = 1
    for i, tr in enumerate(trees):
        n = tr.num_internal
        ni[i] = n
        if n:
            sf[i, :n] = tr.split_feature
            dt[i, :n] = tr.decision_type
            lc[i, :n] = tr.left_child
            rc[i, :n] = tr.right_child
            th[i, :n] = tr.threshold
            if tr.num_cat:
                cw[i, :len(tr.cat_threshold)] = np.asarray(
                    tr.cat_threshold, np.uint32)
                for nd in range(n):
                    if tr.decision_type[nd] & 1:
                        cat_idx = int(tr.threshold[nd])
                        cs[i, nd] = tr.cat_boundaries[cat_idx]
                        cn[i, nd] = (tr.cat_boundaries[cat_idx + 1]
                                     - tr.cat_boundaries[cat_idx])
        lv[i, :tr.num_leaves] = tr.leaf_value
        depth = max(depth, _tree_depth(tr))
    return PackedEnsemble(
        split_feature=jnp.asarray(sf), threshold=jnp.asarray(th, jnp.float32),
        decision_type=jnp.asarray(dt), left_child=jnp.asarray(lc),
        right_child=jnp.asarray(rc), leaf_value=jnp.asarray(lv),
        num_internal=jnp.asarray(ni),
        cat_start=jnp.asarray(cs), cat_nwords=jnp.asarray(cn),
        cat_words=jnp.asarray(cw),
        max_depth=int(depth),
        num_trees_per_class=num_tree_per_iteration)


def _tree_depth(tr) -> int:
    if tr.num_internal == 0:
        return 1
    depth = np.zeros(tr.num_internal, np.int32)
    out = 1
    for nd in range(tr.num_internal):  # parents precede children
        for child in (tr.left_child[nd], tr.right_child[nd]):
            if child >= 0:
                depth[child] = depth[nd] + 1
                out = max(out, int(depth[child]) + 1)
    return out + 1


def _predict_leaf_one_tree(tree, x, max_depth: int):
    """Leaf index per row for one packed tree (tuple of arrays)."""
    sf, th, dt, lc, rc, ni, cs, cn, cw = tree
    num_rows = x.shape[0]

    def body(_, node):
        nd = jnp.maximum(node, 0)
        feat = sf[nd]
        val = jnp.take_along_axis(x, feat[:, None], axis=1)[:, 0]
        thr = th[nd]
        d = dt[nd]
        default_left = (d & _DEFAULT_LEFT_MASK) > 0
        missing_type = (d >> 2) & 3
        is_cat = (d & 1) > 0
        isnan = jnp.isnan(val)
        v0 = jnp.where(isnan, 0.0, val)
        # categorical bitset decision (ref: tree.h:375 CategoricalDecision)
        v_int = v0.astype(jnp.int32)
        widx = jnp.clip(cs[nd] + v_int // 32, 0, cw.shape[0] - 1)
        word = cw[widx]
        in_range = (~isnan) & (v0 >= 0) & (v_int // 32 < cn[nd])
        cat_left = in_range & (
            (word >> (v_int % 32).astype(jnp.uint32)) & 1 > 0)
        go_left = jnp.where(is_cat, cat_left, v0 <= thr)
        use_default = (isnan & (missing_type == 2)) | \
            ((missing_type == 1) & (isnan | (jnp.abs(v0) <= 1e-35)))
        go_left = jnp.where(use_default & ~is_cat, default_left, go_left)
        nxt = jnp.where(go_left, lc[nd], rc[nd])
        # leaves (node < 0) self-loop
        return jnp.where(node < 0, node, nxt)

    node0 = jnp.where(ni > 0, jnp.zeros(num_rows, jnp.int32),
                      jnp.full(num_rows, -1, jnp.int32))
    node = lax.fori_loop(0, max_depth, body, node0)
    return jnp.where(node < 0, ~node, 0)


def _tree_operands(ens: PackedEnsemble):
    return (ens.split_feature, ens.threshold, ens.decision_type,
            ens.left_child, ens.right_child, ens.num_internal,
            ens.cat_start, ens.cat_nwords, ens.cat_words)


def predict_raw(ens: PackedEnsemble, x: jax.Array) -> jax.Array:
    """x: [B, F] raw features (NaN = missing) -> raw scores [B]."""
    num_rows = x.shape[0]

    def one_tree(carry, tree):
        *nav, lv = tree
        leaf = _predict_leaf_one_tree(tuple(nav), x, ens.max_depth)
        return carry + lv[leaf], None

    total, _ = lax.scan(
        one_tree, jnp.zeros(num_rows, jnp.float32),
        _tree_operands(ens) + (ens.leaf_value,))
    return total


def predict_leaf_index(ens: PackedEnsemble, x: jax.Array) -> jax.Array:
    """x: [B, F] -> leaf indices [B, T] (ref: PredictLeafIndex)."""
    def one_tree(_, tree):
        return None, _predict_leaf_one_tree(tree, x, ens.max_depth)

    _, leaves = lax.scan(one_tree, None, _tree_operands(ens))
    return jnp.swapaxes(leaves, 0, 1)


def predict_raw_cached(owner, trees: List, num_tree_per_iteration: int,
                       data: np.ndarray, cache_key,
                       chunk: int = 1 << 20) -> np.ndarray:
    """Raw [N, K] prediction through the packed device ensemble, with the
    packed tensors cached on `owner` under `cache_key`. GBDT and
    LoadedModel (model_io.py) both predict through this helper, so a
    save/load round trip runs the identical XLA program and returns
    bit-equal outputs (the reference gets the same property by sharing
    GBDT::PredictRaw between live and loaded boosters,
    gbdt_prediction.cpp:16)."""
    if getattr(owner, "_packed_key", None) != cache_key:
        owner._packed = pack_ensemble(trees, num_tree_per_iteration)
        owner._packed_key = cache_key
    n = data.shape[0]
    k = max(owner._packed.num_trees_per_class, 1)
    if n == 0:
        return np.zeros((0, k))
    outs = []
    for lo in range(0, n, chunk):
        x = jnp.asarray(data[lo:lo + chunk], jnp.float32)
        outs.append(np.asarray(predict_raw_multiclass(owner._packed, x),
                               np.float64))
    return np.concatenate(outs, axis=0)


def predict_raw_multiclass(ens: PackedEnsemble, x: jax.Array) -> jax.Array:
    """-> [B, K] for K = num_trees_per_class class streams."""
    k = ens.num_trees_per_class
    if k == 1:
        return predict_raw(ens, x)[:, None]
    t = ens.split_feature.shape[0]
    outs = []
    for ki in range(k):
        idx = jnp.arange(ki, t, k)
        sub = PackedEnsemble(
            split_feature=ens.split_feature[idx],
            threshold=ens.threshold[idx],
            decision_type=ens.decision_type[idx],
            left_child=ens.left_child[idx],
            right_child=ens.right_child[idx],
            leaf_value=ens.leaf_value[idx],
            num_internal=ens.num_internal[idx],
            cat_start=ens.cat_start[idx],
            cat_nwords=ens.cat_nwords[idx],
            cat_words=ens.cat_words[idx],
            max_depth=ens.max_depth, num_trees_per_class=1)
        outs.append(predict_raw(sub, x))
    return jnp.stack(outs, axis=1)
