"""Observability egress + XLA introspection: the OpenMetrics renderer
(obs/export.py), the textfile flusher, the HTTP endpoint smoke
(tools/check_metrics_endpoint.py), and the obs/xla.py program
introspector (AOT routing, cost capture, fallback safety, disabled
fast path)."""

import os
import sys

import numpy as np
import pytest

from lightgbm_tpu.obs.export import (MetricsTextfileFlusher,
                                     render_openmetrics)
from lightgbm_tpu.obs.metrics import MetricsRegistry, global_metrics
from lightgbm_tpu.obs.xla import (XlaIntrospector, aot_cost_summary,
                                  instrumented_jit)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from check_metrics_endpoint import validate_exposition  # noqa: E402

pytestmark = pytest.mark.quick


# ---------------------------------------------------------------------------
class TestRenderOpenmetrics:
    def _fresh_registry(self):
        m = MetricsRegistry()
        m.enabled = False
        m.inc_counter("serve/requests", 3)
        m.inc_counter("serve/registry_hit", 2)
        m.note_latency("serve/request", 0.004)
        m.note_latency("serve/request", 0.008)
        m.note_predict(100, 0.01)
        m.note_trace("boosting/grow")
        m.note_collective("psum", 4096)
        return m

    def test_document_is_valid_prometheus_text(self):
        text = render_openmetrics(self._fresh_registry())
        errors, families = validate_exposition(text)
        assert errors == []
        assert families["lgbmtpu_serve_requests_total"] == "counter"
        assert families["lgbmtpu_latency_seconds"] == "summary"
        assert families["lgbmtpu_host_info"] == "gauge"

    def test_counters_quantiles_and_host_labels_present(self):
        import socket
        text = render_openmetrics(self._fresh_registry())
        assert "lgbmtpu_serve_requests_total 3" in text
        assert "lgbmtpu_serve_registry_hit_total 2" in text
        assert ('lgbmtpu_latency_seconds{name="serve/request",'
                'quantile="0.99"}') in text
        assert 'lgbmtpu_latency_seconds_count{name="serve/request"} 2' \
            in text
        assert "lgbmtpu_predict_rows_total 100" in text
        assert 'lgbmtpu_jit_traces_total{tag="boosting/grow"} 1' in text
        assert "lgbmtpu_collective_bytes_total 4096" in text
        assert f'hostname="{socket.gethostname()}"' in text

    def test_meta_model_gauges_exported(self):
        m = self._fresh_registry()
        m.set_meta("mem_model", {"peak_bytes": 123456})
        m.set_meta("hist_traffic", {"hist_bytes_per_iter": 789})
        text = render_openmetrics(m)
        assert "lgbmtpu_mem_peak_model_bytes 123456" in text
        assert "lgbmtpu_hist_bytes_per_iter 789" in text
        errors, _ = validate_exposition(text)
        assert errors == []

    def test_extra_gauges_and_label_escaping(self):
        text = render_openmetrics(MetricsRegistry(),
                                  extra_gauges={"lgbmtpu_custom_gauge": 7})
        assert "lgbmtpu_custom_gauge 7" in text
        from lightgbm_tpu.obs.export import _label_value
        assert _label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'

    def test_validator_rejects_garbage(self):
        errors, _ = validate_exposition("not a metric line!!\n")
        assert errors
        errors, _ = validate_exposition(
            "# TYPE lgbmtpu_x counter\nlgbmtpu_x{bad-label=\"1\"} 1\n")
        assert errors
        # a sample without a TYPE header is flagged
        errors, _ = validate_exposition("lgbmtpu_orphan 1\n")
        assert errors


# ---------------------------------------------------------------------------
class TestTextfileFlusher:
    def test_unarmed_is_noop(self, monkeypatch):
        monkeypatch.delenv("LGBM_TPU_METRICS_FILE", raising=False)
        fl = MetricsTextfileFlusher()
        assert not fl.armed
        assert fl.maybe_flush() is False
        assert fl.flush() is False

    def test_armed_flushes_valid_document_atomically(self, monkeypatch,
                                                     tmp_path):
        path = str(tmp_path / "metrics.prom")
        monkeypatch.setenv("LGBM_TPU_METRICS_FILE", path)
        monkeypatch.setenv("LGBM_TPU_METRICS_FLUSH_SECS", "0")
        fl = MetricsTextfileFlusher()
        assert fl.armed and fl.interval_s == 0.0
        assert fl.maybe_flush() is True
        assert os.path.exists(path)
        assert not os.path.exists(path + ".tmp")  # rename, not write
        with open(path) as fh:
            errors, families = validate_exposition(fh.read())
        assert errors == [] and families

    def test_interval_throttles(self, monkeypatch, tmp_path):
        monkeypatch.setenv("LGBM_TPU_METRICS_FILE",
                           str(tmp_path / "m.prom"))
        monkeypatch.setenv("LGBM_TPU_METRICS_FLUSH_SECS", "3600")
        fl = MetricsTextfileFlusher()
        assert fl.maybe_flush() is True
        assert fl.maybe_flush() is False  # inside the interval
        assert fl.maybe_flush(force=True) is True

    def test_training_hook_writes_file(self, monkeypatch, tmp_path):
        """The boosting loop's per-iteration hook flushes when armed —
        no telemetry enable required (counters are always-on)."""
        import lightgbm_tpu as lgb
        from lightgbm_tpu.obs import export as export_mod
        path = str(tmp_path / "train.prom")
        monkeypatch.setenv("LGBM_TPU_METRICS_FILE", path)
        monkeypatch.setenv("LGBM_TPU_METRICS_FLUSH_SECS", "0")
        export_mod.global_flusher.rearm()
        try:
            rng = np.random.RandomState(0)
            X = rng.randn(300, 6)
            y = (X[:, 0] > 0).astype(np.float64)
            lgb.train({"objective": "binary", "num_leaves": 7,
                       "verbosity": -1}, lgb.Dataset(X, label=y),
                      num_boost_round=2)
        finally:
            monkeypatch.delenv("LGBM_TPU_METRICS_FILE")
            export_mod.global_flusher.rearm()
        assert os.path.exists(path)
        with open(path) as fh:
            errors, _ = validate_exposition(fh.read())
        assert errors == []


# ---------------------------------------------------------------------------
class TestXlaIntrospector:
    def test_enabled_routes_aot_and_records_cost(self):
        import jax
        # A persistent-cache-served compile is attributed to
        # cache_load_s_total, NOT compile_s_total — so if a prior run
        # already wrote this tiny program to the disk cache (conftest
        # arms it), the compile_s_total assertions below would see 0.
        # Pin the test to real compiles by detaching the disk cache.
        prev_cache = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", None)
        try:
            reg = XlaIntrospector()
            reg.enable()
            calls = []

            def f(x):
                calls.append(1)
                return (x * 2.0).sum()

            g = instrumented_jit("test/prog", f, phase="testing",
                                 registry=reg)
            a = np.ones((64, 4), np.float32)
            out1 = g(a)
            out2 = g(a)  # same shape bucket: no second compile
            assert float(out1) == float(out2) == 512.0
            assert reg.n_programs == 1
            recs = reg.records()
            assert recs[0]["tag"] == "test/prog"
            assert recs[0]["phase"] == "testing"
            assert recs[0]["compile_s"] > 0
            assert "64x4" in recs[0]["shapes"]
            g(np.ones((128, 4), np.float32))  # new bucket: +1 program
            assert reg.n_programs == 2
            s = reg.summary()
            assert s["n_recompiles_by_phase"] == {"testing": 2}
            assert s["compile_s_total"] > 0
            assert s["by_tag"]["test/prog"]["programs"] == 2
            # the AOT result equals the jit path bit-for-bit
            assert float(g(a)) == float(jax.jit(f)(a))
        finally:
            jax.config.update("jax_compilation_cache_dir", prev_cache)

    def test_cost_analysis_fields_when_backend_exposes_them(self):
        reg = XlaIntrospector()
        reg.enable()
        g = instrumented_jit("test/cost", lambda x: x @ x.T, registry=reg)
        g(np.ones((32, 8), np.float32))
        rec = reg.records()[0]
        # CPU XLA exposes both analyses; tolerate absence elsewhere but
        # under the test conftest (CPU) they must be captured
        assert rec.get("flops", 0) > 0
        assert rec.get("bytes_accessed", 0) > 0
        assert rec.get("argument_bytes", 0) >= 32 * 8 * 4

    def test_fallback_on_uncompilable_keeps_results(self, monkeypatch):
        """lower/compile failure must fall back to the plain jit path
        (and stay there) without changing results."""
        reg = XlaIntrospector()
        reg.enable()
        g = instrumented_jit("test/fb", lambda x: x + 1, registry=reg)
        jitted = g.__wrapped_jit__

        def boom(*a, **k):
            raise RuntimeError("no AOT here")

        monkeypatch.setattr(jitted, "lower", boom)
        out = g(np.arange(4.0, dtype=np.float32))
        np.testing.assert_array_equal(np.asarray(out),
                                      [1.0, 2.0, 3.0, 4.0])
        assert reg.n_programs == 0
        assert "test/fb" in reg.summary()["aot_fallbacks"]
        # subsequent calls stay on the fallback path, still correct
        out = g(np.arange(4.0, dtype=np.float32))
        np.testing.assert_array_equal(np.asarray(out),
                                      [1.0, 2.0, 3.0, 4.0])

    def test_aot_cost_summary_shape(self):
        cost = aot_cost_summary(lambda x: (x * x).sum(),
                                np.ones((16, 16), np.float32))
        if cost is None:  # backend without analyses: the skip contract
            return
        assert cost["compile_s"] > 0
        assert cost.get("argument_bytes", 0) >= 16 * 16 * 4

    def test_lowlat_compiles_recorded_when_enabled(self):
        import lightgbm_tpu as lgb
        from lightgbm_tpu.obs.xla import global_xla
        from lightgbm_tpu.serve import SERVE_LOWLAT_TAG, ModelRegistry
        rng = np.random.RandomState(0)
        X = rng.randn(240, 5)
        y = (X[:, 0] > 0).astype(np.float64)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1}, lgb.Dataset(X, label=y),
                        num_boost_round=2)
        registry = ModelRegistry()
        entry = registry.load("m", booster=bst)
        was = global_xla.enabled
        n0 = global_xla.n_programs
        global_xla.enable()
        try:
            entry.lowlat_predict(X[:3])
        finally:
            if not was:
                global_xla.disable()
        recs = [r for r in global_xla.records()[n0:]
                if r["tag"] == SERVE_LOWLAT_TAG]
        assert recs and recs[0]["phase"] == "serve"
        assert recs[0]["compile_s"] > 0


# ---------------------------------------------------------------------------
def test_check_metrics_endpoint_smoke():
    """The full endpoint smoke (train, serve, scrape, validate,
    readiness flip) — the quick-tier wiring for the CI tool."""
    import check_metrics_endpoint
    assert check_metrics_endpoint.main() == 0
    # the smoke leaves global serve counters behind; no global tracer
    # or metrics enable leaks
    assert not global_metrics.enabled


# ---------------------------------------------------------------------------
def test_bench_partial_obs_line_on_failed_attempt(monkeypatch, capsys):
    """bench.py satellite: a failed child attempt emits its partial obs
    phase summary + compile attribution as one stderr comment line the
    parent's spam filter forwards (the old path dropped it)."""
    import json
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    import bench
    from lightgbm_tpu.obs.trace import global_tracer
    from lightgbm_tpu.obs.xla import global_xla
    monkeypatch.setenv("LGBM_TPU_TELEMETRY", "1")
    was = global_tracer.enabled
    global_tracer.enable()
    try:
        with global_tracer.span("train/doomed"):
            pass
        bench._emit_partial_obs("train", RuntimeError("relay died"))
    finally:
        if not was:
            global_tracer.disable()
        global_tracer.reset()
        global_xla.disable()
    err = capsys.readouterr().err
    lines = [ln for ln in err.splitlines()
             if ln.startswith("# obs-partial: ")]
    assert len(lines) == 1
    rec = json.loads(lines[0][len("# obs-partial: "):])
    assert rec["partial"] is True
    assert "relay died" in rec["error"]
    assert rec["metric"] == "boosting_iters_per_sec_higgs_shape"
    assert "train/doomed" in rec["phases"]
    # the line survives the parent's stderr spam filter
    assert not bench._STDERR_SPAM.match(lines[0])
