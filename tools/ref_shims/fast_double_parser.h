// Build shim for the vendored fast_double_parser (submodule not present in
// this offline environment). strtod is correctly rounded per C11, matching
// fast_double_parser's exact-parse contract; returns nullptr on failure so
// LightGBM's AtofPrecise fallback logic is preserved.
#ifndef FAST_DOUBLE_PARSER_SHIM_H_
#define FAST_DOUBLE_PARSER_SHIM_H_

#include <cerrno>
#include <cstdlib>

namespace fast_double_parser {

inline const char* parse_number(const char* p, double* out) {
  char* end = nullptr;
  errno = 0;
  *out = std::strtod(p, &end);
  if (end == p) return nullptr;
  return end;
}

}  // namespace fast_double_parser

#endif  // FAST_DOUBLE_PARSER_SHIM_H_
