"""Best-split search over histograms (device).

TPU-native replacement for the reference split kernels
(ref: src/treelearner/feature_histogram.hpp:166 FindBestThreshold,
src/treelearner/cuda/cuda_best_split_finder.cu:776). The per-feature
sequential threshold scan becomes a fully vectorized prefix-sum + gain
evaluation over ``[F, B]`` with a global argmax, evaluated for both
missing-value directions (the reference's two-direction scan).

Split semantics (numerical): rows with ``bin <= threshold`` go left; the
NaN bin (when missing_type == NAN) is the feature's last bin and goes to
the side indicated by ``default_left``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .histogram import GRAD, HESS, COUNT

MISSING_NONE, MISSING_ZERO, MISSING_NAN = 0, 1, 2
K_MIN_SCORE = -1e30
K_EPSILON = 1e-15


class SplitHyperParams(NamedTuple):
    """Dynamic (traced) regularization scalars (ref: config.h)."""
    lambda_l1: jax.Array
    lambda_l2: jax.Array
    min_data_in_leaf: jax.Array
    min_sum_hessian_in_leaf: jax.Array
    min_gain_to_split: jax.Array
    max_delta_step: jax.Array
    path_smooth: jax.Array     # (ref: config.h path_smooth)
    cegb_split_pen: jax.Array  # cegb_tradeoff * cegb_penalty_split

    @classmethod
    def from_config(cls, cfg) -> "SplitHyperParams":
        f = jnp.float32
        return cls(
            lambda_l1=jnp.asarray(cfg.lambda_l1, f),
            lambda_l2=jnp.asarray(cfg.lambda_l2, f),
            min_data_in_leaf=jnp.asarray(cfg.min_data_in_leaf, f),
            min_sum_hessian_in_leaf=jnp.asarray(
                max(cfg.min_sum_hessian_in_leaf, K_EPSILON), f),
            min_gain_to_split=jnp.asarray(cfg.min_gain_to_split, f),
            max_delta_step=jnp.asarray(cfg.max_delta_step, f),
            path_smooth=jnp.asarray(cfg.path_smooth, f),
            cegb_split_pen=jnp.asarray(
                cfg.cegb_tradeoff * cfg.cegb_penalty_split, f),
        )


class FeatureMeta(NamedTuple):
    """Static per-feature binning metadata, as device arrays.

    num_bins: [F] actual bin count per feature (<= B).
    missing_type: [F] MISSING_* code.
    default_bin: [F] bin that value 0.0 maps to.
    is_categorical: [F] bool.
    monotone: [F] int8 in {-1, 0, +1}.
    penalty: [F] multiplicative gain penalty (feature_contri; 1.0 = none).
    """
    num_bins: jax.Array
    missing_type: jax.Array
    default_bin: jax.Array
    is_categorical: jax.Array
    monotone: jax.Array
    penalty: jax.Array
    cegb_feat: jax.Array  # [F] additive gain penalty (CEGB coupled, pre-scaled)
    cegb_lazy: jax.Array  # [F] per-row additive penalty (CEGB lazy, pre-scaled)


class SplitInfo(NamedTuple):
    """Best split for one leaf — scalar fields (ref: split_info.hpp:22)."""
    gain: jax.Array          # gain above (parent_gain + min_gain_to_split); <=0 => no split
    feature: jax.Array       # int32 feature index
    threshold: jax.Array     # int32 bin threshold (bin <= threshold -> left)
    default_left: jax.Array  # bool
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array


def threshold_l1(s: jax.Array, l1: jax.Array) -> jax.Array:
    """Soft-threshold by lambda_l1 (ref: feature_histogram.hpp ThresholdL1)."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(sum_grad, sum_hess, hp: SplitHyperParams):
    """Optimal leaf value -TL1(G)/(H+l2), clipped by max_delta_step
    (ref: feature_histogram.hpp CalculateSplittedLeafOutput)."""
    raw = -threshold_l1(sum_grad, hp.lambda_l1) / (sum_hess + hp.lambda_l2)
    return jnp.where(hp.max_delta_step > 0,
                     jnp.clip(raw, -hp.max_delta_step, hp.max_delta_step), raw)


def leaf_gain_given_output(sum_grad, sum_hess, output, hp: SplitHyperParams):
    """-(2*TL1(G)*w + (H+l2)*w^2) — equals TL1(G)^2/(H+l2) at the optimum
    (ref: feature_histogram.hpp GetLeafGainGivenOutput)."""
    g = threshold_l1(sum_grad, hp.lambda_l1)
    return -(2.0 * g * output + (sum_hess + hp.lambda_l2) * output * output)


def leaf_gain(sum_grad, sum_hess, hp: SplitHyperParams):
    return leaf_gain_given_output(sum_grad, sum_hess,
                                  leaf_output(sum_grad, sum_hess, hp), hp)


def smooth_output(raw, count, parent_output, hp: SplitHyperParams):
    """Path smoothing: pull a leaf's output toward its parent's,
    weighted by leaf size (ref: feature_histogram.hpp
    CalculateSplittedLeafOutput USE_SMOOTHING branch:
    w' = w * (n/a)/(n/a+1) + parent/(n/a+1), a = path_smooth)."""
    ratio = count / jnp.maximum(hp.path_smooth, K_EPSILON)
    smoothed = (raw * ratio + parent_output) / (ratio + 1.0)
    return jnp.where(hp.path_smooth > 0, smoothed, raw)


def leaf_output_smooth(sum_grad, sum_hess, count, parent_output,
                       hp: SplitHyperParams):
    return smooth_output(leaf_output(sum_grad, sum_hess, hp), count,
                         parent_output, hp)


def _gain_tensors(hist: jax.Array,
                  parent_sum_grad: jax.Array,
                  parent_sum_hess: jax.Array,
                  parent_count: jax.Array,
                  meta: FeatureMeta,
                  hp: SplitHyperParams,
                  feature_mask: jax.Array,
                  parent_output):
    """Candidate gains for every (feature, threshold, missing-direction)
    variant. Returns (gains [F, B, 3], left_a, right_b, left_c, parent)."""
    num_features, num_bin_slots, _ = hist.shape
    prefix = jnp.cumsum(hist, axis=1)  # [F, B, 3]
    t_idx = jnp.arange(num_bin_slots, dtype=jnp.int32)[None, :]  # [1, B]
    nb = meta.num_bins[:, None]  # [F, 1]

    # --- variant A: missing (NaN bin = last) goes RIGHT; left = prefix[t]
    left_a = prefix  # [F, B, 3]
    # --- variant B: missing goes LEFT. right = (non-NaN rows above t)
    #     = prefix[nb-2] - prefix[t]; left = parent - right.
    last_non_nan = jnp.take_along_axis(
        prefix, jnp.maximum(meta.num_bins - 2, 0)[:, None, None], axis=1)  # [F,1,3]
    right_b = jnp.maximum(last_non_nan - prefix, 0.0)

    parent = jnp.stack([parent_sum_grad, parent_sum_hess, parent_count])

    # CEGB delta per feature (ref: cost_effective_gradient_boosting.hpp
    # DeltaGain: tradeoff*penalty_split*n_leaf + coupled-first-use +
    # lazy per-row costs; coupled/lazy are pre-scaled by tradeoff on host)
    cegb_delta = (meta.cegb_feat
                  + (hp.cegb_split_pen + meta.cegb_lazy) * parent_count)

    def eval_variant(left, right, valid_extra):
        gl, hl, cl = left[..., GRAD], left[..., HESS], left[..., COUNT]
        gr, hr, cr = right[..., GRAD], right[..., HESS], right[..., COUNT]
        out_l = smooth_output(leaf_output(gl, hl, hp), cl, parent_output, hp)
        out_r = smooth_output(leaf_output(gr, hr, hp), cr, parent_output, hp)
        gain = (leaf_gain_given_output(gl, hl, out_l, hp)
                + leaf_gain_given_output(gr, hr, out_r, hp))
        # monotone constraints, basic method (ref: monotone_constraints.hpp:466):
        # increasing (+1) requires left_output <= right_output.
        mono = meta.monotone[:, None]
        mono_ok = jnp.where(
            mono == 0, True,
            jnp.where(mono > 0, out_l <= out_r, out_l >= out_r))
        valid = (
            valid_extra
            & mono_ok
            & (cl >= jnp.maximum(hp.min_data_in_leaf, 1.0))
            & (cr >= jnp.maximum(hp.min_data_in_leaf, 1.0))
            & (hl >= hp.min_sum_hessian_in_leaf)
            & (hr >= hp.min_sum_hessian_in_leaf)
            & feature_mask[:, None]
        )
        gain = gain * meta.penalty[:, None] - cegb_delta[:, None]
        return jnp.where(valid, gain, K_MIN_SCORE)

    is_cat = meta.is_categorical[:, None]
    base_valid_a = (t_idx < nb - 1) & ~is_cat
    gains_a = eval_variant(left_a, parent[None, None, :] - left_a, base_valid_a)

    has_nan = meta.missing_type[:, None] == MISSING_NAN
    base_valid_b = has_nan & (t_idx < nb - 2) & ~is_cat
    gains_b = eval_variant(parent[None, None, :] - right_b, right_b, base_valid_b)

    # --- variant C: categorical one-hot split, bin == t goes LEFT
    # (ref: feature_histogram.hpp categorical one-hot branch when
    # num_bins <= max_cat_to_onehot; bin 0 = "other/unseen" never splits
    # left so binned and raw-value prediction stay consistent)
    left_c = hist
    base_valid_c = is_cat & (t_idx >= 1) & (t_idx < nb)
    gains_c = eval_variant(left_c, parent[None, None, :] - left_c,
                           base_valid_c)

    gains = jnp.stack([gains_a, gains_b, gains_c], axis=-1)  # [F, B, 3]
    return gains, left_a, right_b, left_c, parent


def per_feature_best_gain(hist, parent_sum_grad, parent_sum_hess,
                          parent_count, meta: FeatureMeta,
                          hp: SplitHyperParams, feature_mask,
                          parent_output=None) -> jax.Array:
    """Best candidate gain per feature ([F]) — the voting statistic each
    worker computes from its local histograms (ref:
    voting_parallel_tree_learner.cpp:353 local FindBestThreshold + MaxK)."""
    if parent_output is None:
        parent_output = jnp.float32(0.0)
    gains, *_ = _gain_tensors(hist, parent_sum_grad, parent_sum_hess,
                              parent_count, meta, hp, feature_mask,
                              parent_output)
    return jnp.max(gains, axis=(1, 2))


def find_best_split(hist: jax.Array,
                    parent_sum_grad: jax.Array,
                    parent_sum_hess: jax.Array,
                    parent_count: jax.Array,
                    meta: FeatureMeta,
                    hp: SplitHyperParams,
                    feature_mask: jax.Array,
                    parent_output=None) -> SplitInfo:
    """Find the best numerical split across all features for one leaf.

    hist: [F, B, 3]; parent_*: scalars; feature_mask: [F] bool (feature
    fraction / interaction constraints); parent_output: scalar output of
    the leaf being split (path smoothing). Returns scalar SplitInfo.
    """
    if parent_output is None:
        parent_output = jnp.float32(0.0)
    num_bin_slots = hist.shape[1]
    gains, left_a, right_b, left_c, parent = _gain_tensors(
        hist, parent_sum_grad, parent_sum_hess, parent_count, meta, hp,
        feature_mask, parent_output)
    flat = gains.reshape(-1)
    best = jnp.argmax(flat)
    best_gain_raw = flat[best]

    num_variants = 3
    feature = (best // (num_bin_slots * num_variants)).astype(jnp.int32)
    threshold = ((best // num_variants) % num_bin_slots).astype(jnp.int32)
    variant = (best % num_variants).astype(jnp.int32)
    variant_b = variant == 1
    variant_c = variant == 2

    la = left_a[feature, threshold]
    rb = right_b[feature, threshold]
    lc_ = left_c[feature, threshold]
    left = jnp.where(variant_b, parent - rb, jnp.where(variant_c, lc_, la))
    right = parent - left

    # with smoothing, the parent's gain is evaluated at its actual
    # (smoothed) output (ref: FindBestThresholdFromHistogram min_gain_shift)
    parent_gain = jnp.where(
        hp.path_smooth > 0,
        leaf_gain_given_output(parent_sum_grad, parent_sum_hess,
                               parent_output, hp),
        leaf_gain(parent_sum_grad, parent_sum_hess, hp))
    gain = best_gain_raw - parent_gain - hp.min_gain_to_split
    gain = jnp.where(best_gain_raw <= K_MIN_SCORE * 0.5, K_MIN_SCORE, gain)

    mt = meta.missing_type[feature]
    default_left = jnp.where(
        mt == MISSING_NAN, variant_b,
        jnp.where(mt == MISSING_ZERO,
                  meta.default_bin[feature] <= threshold, False))

    return SplitInfo(
        gain=gain,
        feature=feature,
        threshold=threshold,
        default_left=default_left,
        left_sum_grad=left[GRAD], left_sum_hess=left[HESS], left_count=left[COUNT],
        right_sum_grad=right[GRAD], right_sum_hess=right[HESS], right_count=right[COUNT],
        left_output=leaf_output_smooth(left[GRAD], left[HESS], left[COUNT],
                                       parent_output, hp),
        right_output=leaf_output_smooth(right[GRAD], right[HESS],
                                        right[COUNT], parent_output, hp),
    )
