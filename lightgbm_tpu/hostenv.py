"""Environment helpers for spawning CPU-only helper processes.

The TPU is reached through a fragile local relay; the axon PJRT plugin
registered by this image's sitecustomize hangs in a nanosleep retry
loop if anything touches the backend while the relay is down. Every
subprocess that should run on CPU (cluster workers, the multichip
dryrun, the bench fallback) must therefore (a) pin JAX_PLATFORMS=cpu
and (b) drop PALLAS_AXON_POOL_IPS so the plugin is never registered at
interpreter startup.
"""

from __future__ import annotations

import os
import socket
import sys
from typing import Dict, Optional


def host_labels() -> Dict[str, str]:
    """Host/process identity labels for trace metadata (obs/trace.py
    emits them as Chrome ``process_labels`` so multi-process Perfetto
    traces are tellable apart).

    Deliberately does NOT probe a jax backend: reading
    ``jax.distributed.global_state`` is passive, while touching devices
    can hang on the downed relay (module docstring). Process index /
    count appear only when jax.distributed is initialized."""
    labels = {"hostname": socket.gethostname(), "pid": str(os.getpid())}
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            state = jax_mod.distributed.global_state
            if getattr(state, "process_id", None) is not None:
                labels["process_index"] = str(state.process_id)
            if getattr(state, "num_processes", None):
                labels["num_processes"] = str(state.num_processes)
        except Exception:
            pass
    return labels


#: Nominal per-chip peak throughputs feeding the roofline layer
#: (obs/profile.py) and perf-gate check 11. Deliberately conservative
#: round numbers — docs/PERF_PROJECTION.md records the sources — and
#: env-overridable (LGBM_TPU_PEAK_BYTES_PER_S / LGBM_TPU_PEAK_FLOPS)
#: so a real part's datasheet numbers can be pinned per deployment.
_PLATFORM_PEAKS: Dict[str, Dict[str, float]] = {
    # one modern x86 core: ~50 GF/s fp32 FMA, ~20 GB/s streaming DRAM
    "cpu": {"flops_per_s": 5.0e10, "bytes_per_s": 2.0e10},
    # TPU v4 class: 275 TF/s bf16, 1.2 TB/s HBM2e
    "tpu": {"flops_per_s": 2.75e14, "bytes_per_s": 1.2e12},
    # A100 class: 156 TF/s tf32, 2.0 TB/s HBM2e
    "gpu": {"flops_per_s": 1.56e14, "bytes_per_s": 2.0e12},
}


def platform_peaks(platform: str) -> Dict[str, float]:
    """``{"flops_per_s", "bytes_per_s"}`` roofline peaks for a backend
    platform string (unknown platforms get the TPU row — accelerator
    first). Passive: the caller supplies the platform; this module
    never probes a backend (module docstring)."""
    peaks = dict(_PLATFORM_PEAKS.get(
        str(platform).lower(), _PLATFORM_PEAKS["tpu"]))
    for env, key in (("LGBM_TPU_PEAK_FLOPS", "flops_per_s"),
                     ("LGBM_TPU_PEAK_BYTES_PER_S", "bytes_per_s")):
        raw = os.environ.get(env, "")
        if raw:
            try:
                peaks[key] = float(raw)
            except ValueError:
                pass
    return peaks


def cpu_child_env(n_devices: Optional[int] = None,
                  base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """A copy of the environment made safe for a CPU-only child.

    n_devices: when given, force that many virtual CPU devices via
    --xla_force_host_platform_device_count (replacing any inherited
    setting of that flag).
    """
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if n_devices is not None:
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    # Persistent compilation cache: the driver invokes helper processes
    # (multichip dryrun, bench) cold on a contended 1-core host; without a
    # warm cache every invocation recompiles from scratch and can blow the
    # driver's timeout (rounds 3+4: rc=124). Cache everything, however
    # small/fast, so a warmed program is a disk hit for the driver.
    # (In-process entries — train/serve — arm the same cache through
    # compile_cache.configure; this env path is only for children.)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", _repo_cache_dir())
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    # best-effort LRU hygiene before handing the dir to another process:
    # the repo-local cache grows without bound on a long-lived host.
    # ONLY the repo-local default is pruned — an inherited
    # JAX_COMPILATION_CACHE_DIR is a user-managed directory this
    # library must never delete from.
    try:
        from .compile_cache import prune_cache_once, repo_cache_dir
        if env["JAX_COMPILATION_CACHE_DIR"] == repo_cache_dir():
            prune_cache_once(env["JAX_COMPILATION_CACHE_DIR"])
    except Exception:
        pass
    return env


def _repo_cache_dir() -> str:
    from .compile_cache import repo_cache_dir
    return repo_cache_dir()
