"""Fault-tolerant training & serving (ISSUE 11).

- Checkpoint/resume bit-parity across the fixture matrix: train N
  straight == train k / injected kill / resume / train N-k, asserted
  on ``model_to_string()`` equality — plain, bagging, GOSS, DART,
  linear-tree (+ feature_fraction RNG stream), quantized, 2-shard mesh.
- The preemption exit-code contract (EXIT_PREEMPTED = 75) and the
  SIGTERM handler plumbing.
- Atomic checkpoint container: digest-footer rejection of corrupted /
  truncated files, resume-mismatch detection.
- engine.train interrupt safety: KeyboardInterrupt/SystemExit
  mid-iteration returns the best-so-far booster and flushes obs.
- Corrupt/truncated model files raise structured CorruptModelError
  naming a byte offset.
- Serve graceful degradation: per-request deadlines, bounded admission
  with retry-after, transient-fault retry (bit-exact), per-model
  circuit breaker incl. half-open recovery; transactional registry
  registration under an injected load fault.
- tools/check_resilience.py (quick-tier chaos validator) and
  check_perf_gate.py check 7 (checkpoint-overhead ceiling).
"""

import asyncio
import json
import os
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import Booster
from lightgbm_tpu.resilience import checkpoint as ckpt_mod
from lightgbm_tpu.resilience import faults as faults_mod
from lightgbm_tpu.resilience.degrade import CircuitBreaker
from lightgbm_tpu.resilience.errors import (EXIT_PREEMPTED,
                                            CircuitOpenError,
                                            CorruptCheckpointError,
                                            CorruptModelError,
                                            DeadlineExceeded,
                                            ResumeMismatchError,
                                            ServerOverloaded,
                                            TransientServeError)
from lightgbm_tpu.obs.metrics import global_metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

N_ROUNDS = 8
KILL_AT = 3


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults_mod.reset()


def _data(n=264, f=8, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.2 * r.randn(n) > 0.4)
    return X, y.astype(np.float32), (
        X[:, 0] * 2 - X[:, 1] + 0.1 * r.randn(n)).astype(np.float32)


# the resume-parity fixture matrix: every sampling / boosting / storage
# mode whose iteration state differs structurally
MATRIX = {
    "plain": dict(objective="binary", num_leaves=7),
    "bagging": dict(objective="binary", num_leaves=7,
                    bagging_fraction=0.7, bagging_freq=2),
    "goss": dict(objective="binary", num_leaves=7,
                 data_sample_strategy="goss"),
    "dart": dict(objective="binary", num_leaves=7, boosting="dart",
                 drop_rate=0.5, max_drop=3),
    "linear": dict(objective="regression", num_leaves=7,
                   linear_tree=True, feature_fraction=0.8),
    "quantized": dict(objective="binary", num_leaves=7,
                      use_quantized_grad=True),
    "shard2": dict(objective="binary", num_leaves=7, tpu_num_shards=2),
}


class TestResumeBitParity:
    # the heavy boosting-mode variants ride the full/quick tiers only;
    # tier-1 keeps one of each structural family (plain sampling,
    # bagging RNG, GOSS RNG, 2-shard mesh)
    @pytest.mark.parametrize("name", [
        pytest.param(n, marks=pytest.mark.slow)
        if n in ("dart", "linear", "quantized") else n
        for n in sorted(MATRIX)])
    def test_kill_resume_bit_identical(self, name, tmp_path):
        """train-N-straight == train-k, kill, resume, train-(N-k), to
        the last bit of model_to_string()."""
        X, y_bin, y_reg = _data()
        extra = MATRIX[name]
        label = y_reg if extra["objective"] == "regression" else y_bin
        ck = str(tmp_path / f"{name}.ckpt")
        params = dict(learning_rate=0.1, verbosity=-1,
                      tpu_checkpoint_path=ck, **extra)

        straight = lgb.train(dict(params), lgb.Dataset(X, label),
                             num_boost_round=N_ROUNDS).model_to_string()
        if os.path.exists(ck):
            os.remove(ck)

        faults_mod.install(faults_mod.FaultPlan(kill_at_iter=KILL_AT))
        with pytest.raises(SystemExit) as exc_info:
            lgb.train(dict(params), lgb.Dataset(X, label),
                      num_boost_round=N_ROUNDS)
        assert exc_info.value.code == EXIT_PREEMPTED
        assert os.path.exists(ck), "preemption must leave a checkpoint"
        faults_mod.reset()

        resumed_bst = lgb.train(dict(params), lgb.Dataset(X, label),
                                num_boost_round=N_ROUNDS)
        assert resumed_bst.current_iteration() == N_ROUNDS
        assert resumed_bst.model_to_string() == straight

    def test_periodic_snapshots_written(self, tmp_path):
        """tpu_checkpoint_every writes at every boundary multiple and
        the totals feed obs meta (perf-gate check 7's input)."""
        ckpt_mod.reset_totals()
        X, y, _ = _data()
        ck = str(tmp_path / "periodic.ckpt")
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1, "tpu_checkpoint_path": ck,
                   "tpu_checkpoint_every": 2},
                  lgb.Dataset(X, y), num_boost_round=6)
        assert os.path.exists(ck)
        totals = ckpt_mod.checkpoint_totals()
        assert totals["checkpoints"] == 3       # iters 2, 4, 6
        assert totals["seconds_total"] > 0
        assert totals["last_iteration"] == 6
        meta = global_metrics.meta.get("resilience_checkpoint")
        assert meta and meta["checkpoints"] == 3
        # the checkpoint is loadable and carries the model string
        state = ckpt_mod.load_checkpoint(ck)
        assert state["iteration"] == 6
        assert "tree" in state["model_str"]

    def test_preempt_on_early_stopped_run_marks_finished(self, tmp_path):
        """SIGTERM landing on the iteration that early-stopped still
        snapshots + exits 75, and the snapshot is marked finished: the
        supervisor's re-run returns immediately with the recorded best
        iteration instead of training the remaining rounds."""
        import lightgbm_tpu.callback as cb_mod
        X, y, _ = _data()
        ck = str(tmp_path / "es.ckpt")
        params = {"objective": "binary", "num_leaves": 7,
                  "verbosity": -1, "tpu_checkpoint_path": ck}

        def stop_and_preempt(env):
            if env.iteration == 3:
                # the preemption signal arrived during this iteration...
                os.kill(os.getpid(), __import__("signal").SIGTERM)
                # ...whose evaluation then decides to early-stop
                raise cb_mod.EarlyStopException(2, [("t", "l2", 0.1, False)])

        with pytest.raises(SystemExit) as ei:
            lgb.train(dict(params), lgb.Dataset(X, y),
                      num_boost_round=20, callbacks=[stop_and_preempt])
        assert ei.value.code == EXIT_PREEMPTED
        state = ckpt_mod.load_checkpoint(ck)
        assert state["finished"] is True
        resumed = lgb.train(dict(params), lgb.Dataset(X, y),
                            num_boost_round=20,
                            callbacks=[stop_and_preempt])
        assert resumed.current_iteration() == 4  # no further training
        assert resumed.best_iteration == 3       # restored, not -1

    def test_resume_skips_completed_training(self, tmp_path):
        """A checkpoint at or past the target round count returns the
        restored booster without training further."""
        X, y, _ = _data()
        ck = str(tmp_path / "done.ckpt")
        params = {"objective": "binary", "num_leaves": 7,
                  "verbosity": -1, "tpu_checkpoint_path": ck,
                  "tpu_checkpoint_every": 3}
        done = lgb.train(dict(params), lgb.Dataset(X, y),
                         num_boost_round=6)
        again = lgb.train(dict(params), lgb.Dataset(X, y),
                          num_boost_round=6)
        assert again.current_iteration() == 6
        assert again.model_to_string() == done.model_to_string()


class TestCheckpointContainer:
    def _checkpoint(self, tmp_path, **extra):
        X, y, _ = _data(n=200)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1, **extra},
                        lgb.Dataset(X, y), num_boost_round=3)
        ck = str(tmp_path / "c.ckpt")
        ckpt_mod.save_checkpoint(bst, ck)
        return bst, ck

    def test_corrupt_byte_rejected(self, tmp_path):
        _, ck = self._checkpoint(tmp_path)
        with open(ck, "r+b") as fh:
            fh.seek(300)
            b = fh.read(1)
            fh.seek(300)
            fh.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(CorruptCheckpointError) as ei:
            ckpt_mod.load_checkpoint(ck)
        assert ei.value.offset is not None

    def test_truncation_rejected(self, tmp_path):
        _, ck = self._checkpoint(tmp_path)
        data = open(ck, "rb").read()
        with open(ck, "wb") as fh:
            fh.write(data[:len(data) // 2])
        with pytest.raises(CorruptCheckpointError):
            ckpt_mod.load_checkpoint(ck)

    def test_bad_magic_rejected(self, tmp_path):
        p = str(tmp_path / "junk.ckpt")
        with open(p, "wb") as fh:
            fh.write(b"definitely not a checkpoint")
        with pytest.raises(CorruptCheckpointError) as ei:
            ckpt_mod.load_checkpoint(p)
        assert ei.value.offset == 0

    def test_fault_plan_corruption_rejected(self, tmp_path):
        """The corrupt-checkpoint-byte fault flips a byte AFTER the
        atomic rename; the digest must catch exactly that artifact."""
        X, y, _ = _data(n=200)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1},
                        lgb.Dataset(X, y), num_boost_round=3)
        ck = str(tmp_path / "f.ckpt")
        faults_mod.install(
            faults_mod.FaultPlan(corrupt_checkpoint_byte=150))
        ckpt_mod.save_checkpoint(bst, ck)
        with pytest.raises(CorruptCheckpointError):
            ckpt_mod.load_checkpoint(ck)

    def test_resume_mismatch_detected(self, tmp_path):
        """Resuming under a structurally different config must refuse,
        not silently mix states."""
        _, ck = self._checkpoint(tmp_path)
        X, y, _ = _data(n=200)
        with pytest.raises(ResumeMismatchError):
            lgb.train({"objective": "binary", "num_leaves": 15,
                       "verbosity": -1, "tpu_checkpoint_path": ck},
                      lgb.Dataset(X, y), num_boost_round=3)

    def test_corrupt_checkpoint_blocks_resume(self, tmp_path):
        """engine.train must surface the corruption, never silently
        retrain from scratch over a torn checkpoint."""
        _, ck = self._checkpoint(tmp_path)
        data = open(ck, "rb").read()
        with open(ck, "wb") as fh:
            fh.write(data[:200])
        X, y, _ = _data(n=200)
        with pytest.raises(CorruptCheckpointError):
            lgb.train({"objective": "binary", "num_leaves": 7,
                       "verbosity": -1, "tpu_checkpoint_path": ck},
                      lgb.Dataset(X, y), num_boost_round=3)


class TestInterruptSafety:
    def test_keyboard_interrupt_mid_iteration(self, monkeypatch,
                                              tmp_path):
        """KeyboardInterrupt inside update() finalizes and returns the
        best-so-far booster (and flushes the obs textfile) instead of
        propagating with a half-updated booster."""
        prom = str(tmp_path / "train.prom")
        monkeypatch.setenv("LGBM_TPU_METRICS_FILE", prom)
        from lightgbm_tpu.obs.export import global_flusher
        global_flusher.rearm()
        try:
            calls = {"n": 0}
            orig = Booster.update

            def flaky(self, *args, **kwargs):
                if calls["n"] == 3:
                    raise KeyboardInterrupt
                calls["n"] += 1
                return orig(self, *args, **kwargs)

            monkeypatch.setattr(Booster, "update", flaky)
            X, y, _ = _data(n=200)
            bst = lgb.train({"objective": "binary", "num_leaves": 7,
                             "verbosity": -1},
                            lgb.Dataset(X, y), num_boost_round=8)
            assert bst.current_iteration() == 3
            assert bst.best_iteration == 3
            # the model is consistent: it serializes and round-trips
            assert lgb.Booster(model_str=bst.model_to_string())
            assert os.path.exists(prom), \
                "interrupt must flush the obs textfile"
        finally:
            monkeypatch.delenv("LGBM_TPU_METRICS_FILE", raising=False)
            global_flusher.rearm()

    def test_system_exit_from_callback_finalizes(self):
        """A SystemExit raised by user code mid-loop also finalizes
        (the engine's own preemption exit is raised OUTSIDE the guard
        and still propagates — TestResumeBitParity asserts that)."""
        def bomb(env):
            if env.iteration == 2:
                raise SystemExit(1)

        X, y, _ = _data(n=200)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1},
                        lgb.Dataset(X, y), num_boost_round=8,
                        callbacks=[bomb])
        assert bst.current_iteration() == 3  # iterations 0..2 landed


class TestFaultPlan:
    def test_poison_labels_trips_health_sentinel(self):
        """The poison-labels fault is a REALISTIC data fault: it flows
        through the normal gradient path and the obs/health NaN
        sentinel (tpu_health=error) must catch it within the poisoned
        iteration."""
        from lightgbm_tpu.obs.health import NonFiniteError
        X, _, y_reg = _data(n=200)
        faults_mod.install(
            faults_mod.FaultPlan(poison_labels_at_iter=2))
        with pytest.raises(NonFiniteError):
            lgb.train({"objective": "regression", "num_leaves": 7,
                       "verbosity": -1, "tpu_health": "error"},
                      lgb.Dataset(X, y_reg), num_boost_round=6)
        assert faults_mod.global_faults.fired("poison_labels") == 1

    def test_slow_iteration_fault_fires_per_iteration(self):
        plan = faults_mod.install(
            faults_mod.FaultPlan(slow_iter_ms=1.0))
        X, y, _ = _data(n=200)
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1}, lgb.Dataset(X, y),
                  num_boost_round=3)
        assert plan.fired("slow_iter") >= 3

    def test_spec_parsing(self):
        plan = faults_mod.FaultPlan.from_spec(
            "kill_at_iter=4, serve_slow_ms=2.5,registry_load_failures=2")
        assert plan.kill_at_iter == 4
        assert plan.serve_slow_ms == 2.5
        assert plan.registry_load_failures == 2
        with pytest.raises(ValueError):
            faults_mod.FaultPlan.from_spec("not_a_knob=1")
        with pytest.raises(ValueError):
            faults_mod.FaultPlan(bogus=1)


class TestCorruptModelFiles:
    def _model_str(self):
        X, y, _ = _data(n=200)
        return lgb.train({"objective": "binary", "num_leaves": 7,
                          "verbosity": -1},
                         lgb.Dataset(X, y),
                         num_boost_round=4).model_to_string()

    def test_mid_file_truncation_names_offset(self):
        from lightgbm_tpu.model_io import load_model_from_string
        s = self._model_str()
        cut = s.index("Tree=2") + 120  # mid tree block
        with pytest.raises(CorruptModelError) as ei:
            load_model_from_string(s[:cut])
        assert ei.value.offset is not None and 0 < ei.value.offset
        assert "byte offset" in str(ei.value)

    def test_truncated_model_file_via_booster(self, tmp_path):
        s = self._model_str()
        p = tmp_path / "trunc.txt"
        p.write_text(s[:s.index("end of trees") - 25])
        with pytest.raises(CorruptModelError):
            lgb.Booster(model_file=str(p))

    def test_header_truncation_rejected(self):
        """A cut BEFORE the tree_sizes line must not load as a silent
        0-tree model that serves constants."""
        from lightgbm_tpu.model_io import load_model_from_string
        s = self._model_str()
        with pytest.raises(CorruptModelError):
            load_model_from_string(s[:s.index("tree_sizes")])

    def test_garbage_rejected_at_offset_zero(self):
        from lightgbm_tpu.model_io import load_model_from_string
        with pytest.raises(CorruptModelError) as ei:
            load_model_from_string("this is not a model")
        assert ei.value.offset == 0

    def test_intact_model_still_parses(self):
        from lightgbm_tpu.model_io import load_model_from_string
        m = load_model_from_string(self._model_str())
        assert len(m.trees) == 4


# ---------------------------------------------------------------------------
def _served(n_rounds=3):
    from lightgbm_tpu.serve.registry import ModelRegistry
    X, y, _ = _data(n=400, f=6, seed=1)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, y),
                    num_boost_round=n_rounds)
    registry = ModelRegistry()
    registry.load("m", booster=bst)
    return registry, X


class TestServeDegradation:
    def test_deadline_fails_fast(self):
        from lightgbm_tpu.serve.server import ModelServer
        registry, X = _served()
        srv = ModelServer(registry, deadline_ms=1e-6)
        before = global_metrics.counter("resilience/deadline_exceeded")

        async def run():
            with pytest.raises(DeadlineExceeded):
                await srv.predict("m", X[:200])
            await srv.close()

        asyncio.run(run())
        assert global_metrics.counter(
            "resilience/deadline_exceeded") > before

    def test_expired_request_never_occupies_batcher(self):
        """A request that expires while queued is failed at flush and
        excluded from the dispatched batch; fresh requests still get
        bit-exact answers."""
        from lightgbm_tpu.serve.batcher import MicroBatcher
        registry, X = _served()
        entry = registry.get("m")
        direct = entry.model.predict_raw(X[100:200])

        async def run():
            import time as _t
            b = MicroBatcher(entry.predict_raw, max_batch_rows=4096,
                             max_wait_s=0.05)
            dead = b.submit(X[:100], deadline=_t.perf_counter() - 1.0)
            live = b.submit(X[100:200])
            with pytest.raises(DeadlineExceeded):
                await dead
            out = await live
            assert np.array_equal(np.asarray(out), direct)

        asyncio.run(run())

    def test_admission_queue_sheds_with_retry_after(self):
        from lightgbm_tpu.serve.server import ModelServer
        registry, X = _served()
        faults_mod.install(faults_mod.FaultPlan(serve_slow_ms=120))
        srv = ModelServer(registry, max_queue_rows=64)
        before = global_metrics.counter("resilience/load_shed")

        async def run():
            first = asyncio.ensure_future(srv.predict("m", X[:60]))
            await asyncio.sleep(0.02)
            with pytest.raises(ServerOverloaded) as ei:
                await srv.predict("m", X[:60])
            assert ei.value.retry_after_s > 0
            await first  # the admitted request still completes
            await srv.close()

        asyncio.run(run())
        assert global_metrics.counter("resilience/load_shed") > before

    def test_transient_fault_retried_bit_exact(self):
        from lightgbm_tpu.serve.server import ModelServer
        registry, X = _served()
        direct = registry.get("m").model.predict(X[:4])
        faults_mod.install(
            faults_mod.FaultPlan(serve_predict_failures=1))
        srv = ModelServer(registry, retry_max=2, retry_backoff_ms=1)
        before = global_metrics.counter("resilience/retries")

        async def run():
            out = await srv.predict("m", X[:4])
            assert np.array_equal(np.asarray(out), np.asarray(direct))
            await srv.close()

        asyncio.run(run())
        assert global_metrics.counter("resilience/retries") > before

    def test_breaker_trips_and_fails_fast(self):
        from lightgbm_tpu.serve.server import ModelServer
        registry, X = _served()
        faults_mod.install(
            faults_mod.FaultPlan(serve_predict_failures=100))
        srv = ModelServer(registry, retry_max=0, breaker_threshold=3,
                          breaker_reset_s=60.0)

        async def run():
            for _ in range(3):
                with pytest.raises(TransientServeError):
                    await srv.predict("m", X[:4])
            with pytest.raises(CircuitOpenError) as ei:
                await srv.predict("m", X[:4])
            assert ei.value.retry_after_s > 0
            await srv.close()

        asyncio.run(run())
        assert srv._breakers["m"].is_open

    def test_breaker_probe_death_releases_slot(self):
        """A half-open probe that dies WITHOUT a verdict on the model
        (deadline expiry / cancellation / shed) must release its slot —
        otherwise the breaker would deny the model service forever."""
        br = CircuitBreaker("x", threshold=1, reset_s=0.02)
        br.record_failure()
        assert br.is_open
        import time as _t
        _t.sleep(0.03)
        br.admit()          # half-open, probe slot taken
        with pytest.raises(CircuitOpenError):
            br.admit()      # second concurrent probe rejected
        br.release_probe()  # probe died via deadline, not model fault
        br.admit()          # a fresh probe may go immediately
        br.record_success()
        assert br.state == "closed"

    def test_deadline_killed_probe_reopens_breaker_path(self):
        """End-to-end: breaker trips, half-opens, the probe request
        expires via deadline — the NEXT request must still be able to
        probe (no permanent 'probe in flight' lockout)."""
        from lightgbm_tpu.serve.server import ModelServer
        registry, X = _served()
        faults_mod.install(
            faults_mod.FaultPlan(serve_predict_failures=2))
        srv = ModelServer(registry, retry_max=0, breaker_threshold=2,
                          breaker_reset_s=0.05)

        async def run():
            for _ in range(2):
                with pytest.raises(TransientServeError):
                    await srv.predict("m", X[:4])
            assert srv._breakers["m"].is_open
            await asyncio.sleep(0.06)
            # half-open probe, killed by an expired deadline
            srv.deadline_s = 1e-9
            with pytest.raises(DeadlineExceeded):
                await srv.predict("m", X[:4])
            # slot released: the next probe goes through and closes
            srv.deadline_s = 0.0
            faults_mod.reset()
            out = await srv.predict("m", X[:4])
            assert out is not None
            assert srv._breakers["m"].state == "closed"
            await srv.close()

        asyncio.run(run())

    def test_registry_validate_smoke_gates_registration(self, monkeypatch):
        """validate=True proves pack+predict BEFORE the swap: a model
        that cannot predict must not replace a working entry."""
        from lightgbm_tpu.model_io import LoadedModel
        registry, X = _served()
        old_entry = registry.get("m")
        X2, y2, _ = _data(n=200)
        bst2 = lgb.train({"objective": "binary", "num_leaves": 7,
                          "verbosity": -1}, lgb.Dataset(X2, y2),
                         num_boost_round=2)

        def broken(self, data, **kw):
            raise RuntimeError("pack exploded")

        monkeypatch.setattr(LoadedModel, "predict_raw", broken)
        with pytest.raises(RuntimeError):
            registry.load("m", booster=bst2, validate=True)
        monkeypatch.undo()
        assert registry.get("m") is old_entry  # old entry kept serving
        entry = registry.load("m", booster=bst2, validate=True)
        assert registry.get("m") is entry

    def test_reloading_model_resets_its_breaker(self):
        """A fixed model re-loaded under the same name must not fail
        fast on the broken predecessor's open circuit."""
        from lightgbm_tpu.serve.server import ModelServer
        registry, X = _served()
        faults_mod.install(
            faults_mod.FaultPlan(serve_predict_failures=2))
        srv = ModelServer(registry, retry_max=0, breaker_threshold=2,
                          breaker_reset_s=60.0)

        async def run():
            for _ in range(2):
                with pytest.raises(TransientServeError):
                    await srv.predict("m", X[:4])
            with pytest.raises(CircuitOpenError):
                await srv.predict("m", X[:4])
            faults_mod.reset()
            # operator ships a fixed model under the same name
            X2, y2, _ = _data(n=200, f=6, seed=1)
            bst2 = lgb.train({"objective": "binary", "num_leaves": 7,
                              "verbosity": -1}, lgb.Dataset(X2, y2),
                             num_boost_round=2)
            registry.load("m", booster=bst2)
            out = await srv.predict("m", X[:4])  # fresh breaker, flows
            assert out is not None
            await srv.close()

        asyncio.run(run())

    def test_breaker_half_open_recovers(self):
        br = CircuitBreaker("x", threshold=2, reset_s=0.02)
        br.record_failure()
        br.record_failure()
        assert br.is_open
        with pytest.raises(CircuitOpenError):
            br.admit()
        import time as _t
        _t.sleep(0.03)
        br.admit()  # half-open probe admitted
        br.record_success()
        assert br.state == "closed"
        br.admit()  # closed again: flows freely

    def test_breaker_concurrent_tasks_single_half_open_probe(self):
        """Two asyncio tasks racing into a half-open breaker: exactly
        ONE wins the probe slot, the other fails fast with a
        retry-after hint — and a probe success reopens the gate for
        everyone (the fleet router's per-replica admission pattern)."""
        br = CircuitBreaker("x", threshold=1, reset_s=0.02)
        br.record_failure()
        import time as _t
        _t.sleep(0.03)
        outcomes = []

        async def contender(i):
            # interleave: both tasks alive before either admits
            await asyncio.sleep(0.001 * i)
            try:
                held = br.admit()
                outcomes.append(("admitted", held))
                if held:
                    await asyncio.sleep(0.01)  # probe in flight
                    br.record_success()
            except CircuitOpenError as exc:
                assert exc.retry_after_s > 0
                outcomes.append(("rejected", None))

        async def run():
            await asyncio.gather(contender(0), contender(1))
            # after the probe's success the breaker is closed: a late
            # third task flows freely (plain admission, no probe slot)
            assert br.admit() is False

        asyncio.run(run())
        assert sorted(o[0] for o in outcomes) == \
            ["admitted", "rejected"]
        assert ("admitted", True) in outcomes
        assert br.state == "closed"

    def test_breaker_concurrent_probe_failure_relocks_loser(self):
        """Race the other way: the winning probe FAILS, re-opening the
        breaker — a loser retrying right after must see open (with the
        full reset window), not a free pass."""
        br = CircuitBreaker("x", threshold=1, reset_s=60.0)
        br.record_failure()
        br._opened_at -= 61.0  # age the window out deterministically

        async def run():
            held = br.admit()
            assert held is True  # half-open probe slot taken
            with pytest.raises(CircuitOpenError):
                br.admit()  # concurrent task: probe in flight
            br.record_failure()  # probe verdict: still broken
            assert br.is_open
            with pytest.raises(CircuitOpenError) as ei:
                br.admit()  # loser's retry hits a RE-armed open window
            assert ei.value.retry_after_s > 1.0

        asyncio.run(run())

    def test_breaker_threaded_failures_open_once(self):
        """record_failure from many executor threads at once (the
        server reports outcomes off-loop): exactly one open transition,
        counted once."""
        from concurrent.futures import ThreadPoolExecutor
        br = CircuitBreaker("x", threshold=8, reset_s=60.0)
        opens0 = global_metrics.counters.get(
            "resilience/breaker_open", 0)
        with ThreadPoolExecutor(max_workers=8) as pool:
            list(pool.map(lambda _: br.record_failure(), range(32)))
        assert br.is_open
        assert br.consecutive_failures == 32
        assert global_metrics.counters["resilience/breaker_open"] \
            == opens0 + 1

    def test_registry_load_transactional(self):
        registry, X = _served()
        old_entry = registry.get("m")
        faults_mod.install(
            faults_mod.FaultPlan(registry_load_failures=2))
        X2, y2, _ = _data(n=200)
        bst2 = lgb.train({"objective": "binary", "num_leaves": 7,
                          "verbosity": -1}, lgb.Dataset(X2, y2),
                         num_boost_round=2)
        with pytest.raises(TransientServeError):
            registry.load("m", booster=bst2)
        with pytest.raises(TransientServeError):
            registry.load("m_new", booster=bst2)
        faults_mod.reset()
        # the failed re-load left the OLD entry fully served ...
        assert registry.get("m") is old_entry
        # ... and the failed fresh load registered nothing
        assert "m_new" not in registry
        # without the fault, load succeeds and replaces
        registry.load("m", booster=bst2)
        assert registry.get("m") is not old_entry


class TestToolsWiring:
    @pytest.mark.slow
    def test_check_resilience_tool(self):
        """The chaos validator passes in-process (quick-tier wiring,
        same idiom as check_health)."""
        import check_resilience
        assert check_resilience.main() == 0

    @pytest.mark.slow
    def test_check_continual_tool(self):
        """The elastic-continual chaos validator passes in-process
        (quick-tier wiring, same idiom as check_resilience): resize
        rejoin parity, poisoned-generation rollback with serve
        isolation, and the full lgbmtpu_continual_* scrape."""
        import check_continual
        assert check_continual.main() == 0

    def test_perf_gate_check8_skips_without_continual_bench(self,
                                                            capsys,
                                                            tmp_path):
        import check_perf_gate
        with open(check_perf_gate.FLOOR_PATH) as fh:
            floor = json.load(fh)
        assert floor["continual"]["max_swap_share"] > 0
        failures = []
        check_perf_gate.check_continual_overhead(
            floor, failures, str(tmp_path / "absent.json"))
        assert failures == []
        assert "skipped" in capsys.readouterr().out

    def test_perf_gate_check8_flags_slow_swaps(self, tmp_path):
        import check_perf_gate
        with open(check_perf_gate.FLOOR_PATH) as fh:
            floor = json.load(fh)
        bad = {"metric": "continual_rows_per_sec", "value": 1.0,
               "continual": {"generations": 4, "rollbacks": 1,
                             "wall_seconds": 10.0, "swap_share": 0.5,
                             "overhead_seconds": 6.0,
                             "swap_seconds_total": 5.0}}
        p = tmp_path / "cand.json"
        p.write_text(json.dumps(bad))
        failures = []
        check_perf_gate.check_continual_overhead(floor, failures,
                                                 str(p))
        assert len(failures) == 2
        assert "hot-swap share" in failures[0]
        assert "overhead share" in failures[1]

        ok = dict(bad, continual=dict(bad["continual"], swap_share=0.01,
                                      overhead_seconds=0.2,
                                      swap_seconds_total=0.1))
        p.write_text(json.dumps(ok))
        failures = []
        check_perf_gate.check_continual_overhead(floor, failures,
                                                 str(p))
        assert failures == []

    def test_perf_gate_check7_skips_without_checkpointing(self, capsys):
        import check_perf_gate
        with open(check_perf_gate.FLOOR_PATH) as fh:
            floor = json.load(fh)
        assert floor["resilience"]["max_checkpoint_time_share"] > 0
        failures = []
        check_perf_gate.check_resilience_overhead(
            floor, failures, [("BENCH_a.json", {"unit": "iters/sec"})])
        assert failures == []
        assert "skipped" in capsys.readouterr().out

    def test_perf_gate_check7_flags_slow_snapshots(self):
        import check_perf_gate
        with open(check_perf_gate.FLOOR_PATH) as fh:
            floor = json.load(fh)
        lines = [("BENCH_x.json", {
            "unit": "iters/sec (platform=cpu)",
            "resilience": {"checkpoints": 4,
                           "checkpoint_seconds_total": 5.0,
                           "train_seconds": 10.0}})]
        failures = []
        check_perf_gate.check_resilience_overhead(floor, failures, lines)
        assert len(failures) == 1 and "checkpoint overhead" in failures[0]

        ok = [("BENCH_x.json", {
            "unit": "iters/sec (platform=cpu)",
            "resilience": {"checkpoints": 4,
                           "checkpoint_seconds_total": 0.1,
                           "train_seconds": 10.0}})]
        failures = []
        check_perf_gate.check_resilience_overhead(floor, failures, ok)
        assert failures == []

    def test_checkpoint_metrics_exported(self, tmp_path):
        """The checkpoint accounting surfaces as lgbmtpu_resilience_*
        families in the OpenMetrics render."""
        X, y, _ = _data(n=200)
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1,
                   "tpu_checkpoint_path": str(tmp_path / "e.ckpt"),
                   "tpu_checkpoint_every": 2},
                  lgb.Dataset(X, y), num_boost_round=4)
        from lightgbm_tpu.obs.export import render_openmetrics
        text = render_openmetrics()
        assert "lgbmtpu_resilience_checkpoints_total" in text
        assert "lgbmtpu_resilience_checkpoint_seconds_total" in text
        import check_metrics_endpoint
        errors, families = check_metrics_endpoint.validate_exposition(text)
        assert not errors, errors[:5]
        assert families["lgbmtpu_resilience_checkpoints_total"] == \
            "counter"


class TestElasticResume:
    """ISSUE 12: restore a checkpoint taken on W shards onto a W'-shard
    mesh (resilience/elastic.py) — quality parity with the unresized
    run, and refusal semantics for everything that is NOT a pure mesh
    resize."""

    PARAMS = {"objective": "binary", "num_leaves": 7,
              "learning_rate": 0.1, "verbosity": -1}

    @pytest.mark.parametrize("w_from,w_to", [(1, 2), (2, 1)])
    def test_resize_resume_matches_unresized(self, w_from, w_to,
                                             tmp_path):
        """Kill at iteration k on a W-shard mesh, resume on W' shards:
        the finished model must match the never-preempted W-shard run
        within the mesh-parity tolerance the distributed suite pins
        (the sharded histogram reduce carries ulp-level f32 ordering
        noise across mesh widths, which can flip a knife-edge split —
        bit equality holds only within one mesh shape, and THAT is
        what TestResumeBitParity[shard2] asserts)."""
        X, y, _ = _data()
        ck = str(tmp_path / f"resize_{w_from}to{w_to}.ckpt")
        params = dict(self.PARAMS, tpu_checkpoint_path=ck,
                      tpu_num_shards=w_from)

        straight = lgb.train(dict(params), lgb.Dataset(X, y),
                             num_boost_round=N_ROUNDS)
        p_straight = straight.predict(X)
        os.remove(ck) if os.path.exists(ck) else None

        # the deterministic chaos scenario: resize_at_iter preempts at
        # the boundary (exit 75) and the supervisor re-runs resized
        faults_mod.install(faults_mod.FaultPlan(resize_at_iter=KILL_AT))
        with pytest.raises(SystemExit) as exc_info:
            lgb.train(dict(params), lgb.Dataset(X, y),
                      num_boost_round=N_ROUNDS)
        assert exc_info.value.code == EXIT_PREEMPTED
        assert os.path.exists(ck)
        faults_mod.reset()

        resizes_before = int(global_metrics.counters.get(
            "resilience/mesh_resizes", 0))
        params_resized = dict(params, tpu_num_shards=w_to)
        resumed = lgb.train(dict(params_resized), lgb.Dataset(X, y),
                            num_boost_round=N_ROUNDS)
        assert resumed.current_iteration() == N_ROUNDS
        assert resumed.num_trees() == straight.num_trees()
        np.testing.assert_allclose(resumed.predict(X), p_straight,
                                   rtol=1e-4, atol=1e-4)
        # the resize was a named, counted event — not a silent accident
        assert int(global_metrics.counters.get(
            "resilience/mesh_resizes", 0)) == resizes_before + 1

    def test_resize_resume_with_valid_set(self, tmp_path):
        """Elastic resume with a REGISTERED valid set: fresh runs hold
        valid scores/bins as uncommitted single-device arrays that jit
        replicates onto the mesh, so the restore must not commit them
        to device 0 (that conflicts with the mesh-committed train state
        inside the fused program — 'incompatible devices for jitted
        computation'; regression for checkpoint._put_like)."""
        X, y, _ = _data()
        Xv, yv = X[:80].copy(), y[:80].copy()
        ck = str(tmp_path / "resize_valid.ckpt")
        params = dict(self.PARAMS, tpu_checkpoint_path=ck,
                      tpu_num_shards=1)
        faults_mod.install(faults_mod.FaultPlan(resize_at_iter=KILL_AT))
        with pytest.raises(SystemExit):
            lgb.train(dict(params), lgb.Dataset(X, y),
                      num_boost_round=N_ROUNDS,
                      valid_sets=[lgb.Dataset(Xv, yv)],
                      valid_names=["v"])
        faults_mod.reset()
        evals = {}
        resumed = lgb.train(dict(params, tpu_num_shards=2),
                            lgb.Dataset(X, y),
                            num_boost_round=N_ROUNDS,
                            valid_sets=[lgb.Dataset(Xv, yv)],
                            valid_names=["v"],
                            callbacks=[lgb.record_evaluation(evals)])
        assert resumed.current_iteration() == N_ROUNDS
        assert evals["v"]  # eval ran on the resized mesh post-resume

    def test_mesh_drift_refused_when_elastic_off(self, tmp_path):
        X, y, _ = _data()
        ck = str(tmp_path / "noelastic.ckpt")
        params = dict(self.PARAMS, tpu_checkpoint_path=ck,
                      tpu_num_shards=1)
        faults_mod.install(faults_mod.FaultPlan(kill_at_iter=KILL_AT))
        with pytest.raises(SystemExit):
            lgb.train(dict(params), lgb.Dataset(X, y),
                      num_boost_round=N_ROUNDS)
        faults_mod.reset()
        params2 = dict(params, tpu_num_shards=2,
                       tpu_elastic_resume=False)
        with pytest.raises(ResumeMismatchError, match="mesh"):
            lgb.train(dict(params2), lgb.Dataset(X, y),
                      num_boost_round=N_ROUNDS)

    def test_structural_drift_always_refused(self, tmp_path):
        """Non-mesh drift (here: num_leaves) refuses even with elastic
        resume on — a resize never licenses resuming a different
        model."""
        X, y, _ = _data()
        ck = str(tmp_path / "structdrift.ckpt")
        params = dict(self.PARAMS, tpu_checkpoint_path=ck)
        faults_mod.install(faults_mod.FaultPlan(kill_at_iter=KILL_AT))
        with pytest.raises(SystemExit):
            lgb.train(dict(params), lgb.Dataset(X, y),
                      num_boost_round=N_ROUNDS)
        faults_mod.reset()
        params2 = dict(params, num_leaves=15, tpu_num_shards=2,
                       tpu_elastic_resume=True)
        with pytest.raises(ResumeMismatchError, match="num_leaves"):
            lgb.train(dict(params2), lgb.Dataset(X, y),
                      num_boost_round=N_ROUNDS)

    def test_fingerprint_diff_helpers(self):
        from lightgbm_tpu.resilience import elastic
        fp_ck = {"objective": "binary", "mesh_shards": 1}
        fp_now = {"objective": "binary", "mesh_shards": 4}
        assert elastic.check_fingerprint(fp_ck, fp_now, elastic=True)
        with pytest.raises(ResumeMismatchError):
            elastic.check_fingerprint(fp_ck, fp_now, elastic=False)
        # a key the checkpoint predates is never blamed
        assert not elastic.check_fingerprint(
            {"objective": "binary"}, fp_now, elastic=False)
        from lightgbm_tpu.resilience.errors import ElasticResumeError
        err = ElasticResumeError("diverged", shards=[3])
        assert err.shards == [3]


class TestContinualTraining:
    """ISSUE 12: the generation loop — extend, eval-anomaly
    accept-vs-rollback, validated hot-swap; a rejected generation is
    never observable from the serve registry."""

    def _chunk(self, n, seed):
        r = np.random.RandomState(seed)
        X = r.randn(n, 6)
        y = (X[:, 0] * 2.0 - X[:, 1] + 0.1 * r.randn(n)).astype(
            np.float32)
        return X, y

    def test_accept_rollback_and_serve_isolation(self):
        from lightgbm_tpu.serve.registry import ModelRegistry
        reg = ModelRegistry()
        params = {"objective": "regression", "num_leaves": 7,
                  "metric": "l2", "verbosity": -1,
                  "tpu_continual_rounds": 4,
                  "tpu_continual_eval_fraction": 0.25,
                  "tpu_continual_retain": 2}
        tr = lgb.ContinualTrainer(params, num_features=6, registry=reg,
                                  serve_name="m")
        X0, y0 = self._chunk(240, 0)
        r0 = tr.push_rows(X0, label=y0).step()
        assert r0.accepted and tr.model_iterations == 4
        served0 = reg.get("m")
        probe = X0[:8]
        p0 = served0.predict_raw(probe)

        # a poisoned chunk (labels blown up 1000x) spikes the held-out
        # eval against the cross-generation history -> auto-rollback
        X1, y1 = self._chunk(240, 1)
        r1 = tr.push_rows(X1, label=y1 * 1000.0).step()
        assert not r1.accepted
        assert r1.reason == "spike"
        assert tr.rollbacks == 1
        assert tr.model_iterations == 4  # last-good stands
        # the serve side never saw the rejected generation
        assert reg.get("m") is served0
        np.testing.assert_array_equal(served0.predict_raw(probe), p0)

        # a healthy chunk extends the LAST-GOOD model, not the rejected
        # one, and hot-swaps a new serve entry
        X2, y2 = self._chunk(240, 2)
        r2 = tr.push_rows(X2, label=y2).step()
        assert r2.accepted and tr.model_iterations == 8
        served2 = reg.get("m")
        assert served2 is not served0
        s = tr.summary()
        assert (s["generations"], s["accepted"], s["rollbacks"],
                s["swaps"]) == (3, 2, 1, 2)
        assert s["swap_seconds_total"] > 0

    def test_operator_rollback_reinstalls_previous(self):
        params = {"objective": "regression", "num_leaves": 7,
                  "metric": "l2", "verbosity": -1,
                  "tpu_continual_rounds": 3,
                  "tpu_continual_eval_fraction": 0.2,
                  "tpu_continual_retain": 3}
        tr = lgb.ContinualTrainer(params, num_features=6)
        for seed in range(3):
            X, y = self._chunk(200, seed)
            assert tr.push_rows(X, label=y).step().accepted
        assert tr.model_iterations == 9
        assert tr.rollback()
        assert tr.booster().current_iteration() == 6
        # the exported gauge tracks the reinstalled snapshot, not the
        # last accepted step
        assert tr.model_iterations == 6
        assert tr.rollback()
        assert tr.booster().current_iteration() == 3
        assert tr.model_iterations == 3
        assert not tr.rollback()  # retained floor reached

    def test_continual_metrics_exported(self):
        params = {"objective": "regression", "num_leaves": 7,
                  "metric": "l2", "verbosity": -1,
                  "tpu_continual_rounds": 2,
                  "tpu_continual_eval_fraction": 0.2}
        tr = lgb.ContinualTrainer(params, num_features=6)
        X, y = self._chunk(160, 7)
        tr.push_rows(X, label=y).step()
        from lightgbm_tpu.obs.export import render_openmetrics
        text = render_openmetrics()
        for family in ("lgbmtpu_continual_swap_seconds_total",
                       "lgbmtpu_continual_last_swap_seconds",
                       "lgbmtpu_continual_model_iterations",
                       "lgbmtpu_continual_retained_snapshots"):
            assert family in text, family
        import check_metrics_endpoint
        errors, _families = check_metrics_endpoint.validate_exposition(
            text)
        assert not errors, errors[:5]
