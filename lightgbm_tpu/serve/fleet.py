"""Serving fleet: health-gated routing over N ModelServer replicas.

One ``ModelServer`` in one process is one failure domain: a wedged
replica under traffic is an outage. ``FleetRouter`` fronts N replicas —
in-process (tests, ``bench.py --fleet``) or subprocesses speaking the
replica HTTP protocol (``tools/check_fleet.py``) — and survives the
faults a single server cannot:

- **health-gated routing**: a daemon probe loop hits every replica's
  ``/readyz`` + ``/healthz`` on a ``serve_probe_interval_ms`` cadence
  and drives a quarantine/reinstate state machine — consecutive probe
  failures pull a replica out of rotation, consecutive successes put
  it back (a SIGSTOPped process times out its probes, gets
  quarantined, and is reinstated after SIGCONT without operator
  action);
- **failover retry**: predicts are idempotent and replicas are
  bit-identical by the PR-3 pack contract, so a dispatch that dies
  (connection refused, timeout, transient fault) retries on the next
  healthy replica — the caller sees one answer, not the dead replica;
- **hedged dispatch** (``serve_hedge_ms`` > 0): a request still
  unanswered after the hedge delay fires a duplicate on another
  healthy replica and the first answer wins; when both complete, the
  answers are ASSERTED bit-identical (the pack contract, checked in
  production, not just in tests);
- **graceful drain**: ``begin_drain()`` stops admitting, in-flight
  requests finish, replicas deregister (``ready`` flips false) — the
  fleet half of the SIGTERM/exit-75 contract (each subprocess replica
  independently honors the single-replica half in ``serve_file`` /
  ``_replica_main``).

Fleet events land in the ``fleet/*`` obs counters
(``lgbmtpu_fleet_*_total``: failovers, hedges, quarantines,
reinstates, drains), per-replica up/quarantined gauges render from
``global_metrics.meta["fleet"]`` (obs/export.py), every
quarantine/reinstate/failover is flight-recorded, and
``aggregate_counter_totals`` merges the replicas' own ``/metrics``
scrapes into fleet-wide totals.

The replica subprocess entry (``python -m lightgbm_tpu.serve.fleet
--replica ...``) reuses ``serve_file``'s construction recipe
(``registry_from_config`` + ``server_from_config``) and adds a
``POST /predict`` endpoint next to the stock /metrics, /healthz,
/readyz — raw float64 bytes in, raw float64 bytes out, shape in
headers, errors mapped back to the structured resilience taxonomy.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..obs.flightrec import global_flightrec
from ..obs.metrics import global_metrics
from ..resilience.degrade import CircuitBreaker
from ..resilience.errors import (CircuitOpenError, DeadlineExceeded,
                                 ServerOverloaded, TransientServeError)
from .server import ModelServer

# replica-side error -> HTTP status + X-Error header; router-side the
# same table maps the header back to the structured exception, so the
# taxonomy survives the process boundary
_ERROR_STATUS = {"ServerOverloaded": 503, "CircuitOpenError": 503,
                 "DeadlineExceeded": 504, "TransientServeError": 500}
_ERROR_CLASS = {"ServerOverloaded": ServerOverloaded,
                "CircuitOpenError": CircuitOpenError,
                "DeadlineExceeded": DeadlineExceeded,
                "TransientServeError": TransientServeError}


class InProcessReplica:
    """A ModelServer in this process wearing the replica interface
    (tests and ``bench.py --fleet``; fault injection kills these by
    flipping ``fail_dispatch``)."""

    def __init__(self, name: str, server: ModelServer):
        self.name = str(name)
        self.server = server
        self.fail_dispatch = False  # test hook: simulate a dead replica

    def probe(self, timeout_s: float):
        """(alive, ready) — in-process liveness is the process itself."""
        if self.fail_dispatch:
            return False, False
        return True, bool(self.server.ready)

    async def predict(self, name: str, x: np.ndarray,
                      raw_score: bool = False) -> np.ndarray:
        if self.fail_dispatch:
            raise ConnectionError(f"replica {self.name} is down "
                                  "(injected)")
        return await self.server.predict(name, x, raw_score=raw_score)

    def metrics_text(self) -> str:
        from ..obs.export import render_openmetrics
        return render_openmetrics()

    def close(self) -> None:
        pass  # owner closes the server


class HTTPReplica:
    """A subprocess replica behind the fleet HTTP protocol. Blocking
    urllib I/O — the router runs these calls on its I/O executor."""

    def __init__(self, name: str, base_url: str,
                 request_timeout_s: float = 10.0):
        self.name = str(name)
        self.base_url = str(base_url).rstrip("/")
        self.request_timeout_s = float(request_timeout_s)

    def _get(self, path: str, timeout_s: float):
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(self.base_url + path,
                                        timeout=timeout_s) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read()

    def probe(self, timeout_s: float):
        """(alive, ready): /healthz answering at all is liveness;
        /readyz 200 is readiness. A dead process refuses the connect,
        a stopped (SIGSTOP) one times out the read — both unalive."""
        try:
            alive = self._get("/healthz", timeout_s)[0] == 200
        except Exception:
            return False, False
        try:
            ready = self._get("/readyz", timeout_s)[0] == 200
        except Exception:
            ready = False
        return alive, ready

    def predict_blocking(self, name: str, x: np.ndarray,
                         raw_score: bool = False) -> np.ndarray:
        import urllib.error
        import urllib.request
        x = np.ascontiguousarray(x, np.float64)
        req = urllib.request.Request(
            self.base_url + "/predict", data=x.tobytes(), method="POST",
            headers={"X-Model": name,
                     "X-Shape": ",".join(str(d) for d in x.shape),
                     "X-Raw-Score": "1" if raw_score else "0",
                     "Content-Type": "application/octet-stream"})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s) as resp:
                body = resp.read()
                shape = tuple(int(d) for d in
                              resp.headers["X-Shape"].split(","))
        except urllib.error.HTTPError as exc:
            err = exc.headers.get("X-Error", "")
            detail = exc.read().decode(errors="replace").strip()
            cls = _ERROR_CLASS.get(err)
            if cls is not None:
                raise cls(f"replica {self.name}: {detail}")
            raise ConnectionError(
                f"replica {self.name} answered {exc.code}: {detail}")
        return np.frombuffer(body, np.float64).reshape(shape)

    def metrics_text(self) -> str:
        status, body = self._get("/metrics", self.request_timeout_s)
        if status != 200:
            raise ConnectionError(
                f"replica {self.name} /metrics answered {status}")
        return body.decode()

    def close(self) -> None:
        pass  # the subprocess has its own lifecycle (SIGTERM contract)


class _ReplicaState:
    __slots__ = ("up", "quarantined", "fail_streak", "ok_streak",
                 "breaker")

    def __init__(self, breaker: CircuitBreaker):
        self.up = True
        self.quarantined = False
        self.fail_streak = 0
        self.ok_streak = 0
        self.breaker = breaker


class FleetRouter:
    """Health-gated request router over replica objects.

    ``predict`` is the fleet's serving API — same signature and same
    bits as ``ModelServer.predict`` on any single replica. ``start()``
    launches the probe loop; ``stop()`` (or ``drain()`` first for
    graceful shutdown) tears it down."""

    def __init__(self, replicas: Sequence, probe_interval_ms: float = 50.0,
                 hedge_ms: float = 0.0, fail_threshold: int = 2,
                 ok_threshold: int = 2, probe_timeout_s: float = 0.25,
                 breaker_threshold: int = 5, breaker_reset_s: float = 1.0,
                 max_attempts: int = 0):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.replicas = list(replicas)
        self.probe_interval_s = max(float(probe_interval_ms), 1.0) / 1e3
        self.hedge_s = max(float(hedge_ms), 0.0) / 1e3
        self.fail_threshold = max(int(fail_threshold), 1)
        self.ok_threshold = max(int(ok_threshold), 1)
        self.probe_timeout_s = float(probe_timeout_s)
        # one failover pass over every replica plus one second chance:
        # enough to ride out the kill->quarantine window without
        # retrying forever into a fully-dead fleet
        self.max_attempts = int(max_attempts) or (2 * len(self.replicas))
        self._state: Dict[str, _ReplicaState] = {
            r.name: _ReplicaState(CircuitBreaker(
                f"fleet/{r.name}", threshold=int(breaker_threshold),
                reset_s=float(breaker_reset_s)))
            for r in self.replicas}
        self._rr = itertools.count()  # round-robin cursor
        self._lock = threading.Lock()
        self._inflight = 0
        self._draining = False
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        # blocking replica I/O (HTTP predicts, scrapes) rides here so
        # the event loop keeps routing while a replica is slow
        self._io_executor = ThreadPoolExecutor(
            max_workers=max(8, 2 * len(self.replicas)),
            thread_name_prefix="lgbm-fleet-io")
        self._metrics_endpoint = None
        self._publish_meta()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "FleetRouter":
        """Start the health-probe loop (idempotent)."""
        if self._probe_thread is None or not self._probe_thread.is_alive():
            self._stop.clear()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="lgbm-fleet-probe",
                daemon=True)
            self._probe_thread.start()
        return self

    def stop(self) -> None:
        """Stop probing and release the I/O executor (no drain — use
        ``drain()`` first for the graceful path)."""
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None
        self._io_executor.shutdown(wait=False)
        if self._metrics_endpoint is not None:
            self._metrics_endpoint.close()
            self._metrics_endpoint = None

    def begin_drain(self) -> None:
        """Stop admitting fleet requests (idempotent): the fleet
        ``/readyz`` deregisters immediately, while requests already
        admitted keep routing — replica servers only begin their own
        drain inside :meth:`drain`, AFTER the fleet's in-flight count
        hits zero, so an admitted request is never shed by its own
        shutdown. Subprocess replicas drain on their own SIGTERM."""
        if self._draining:
            return
        self._draining = True
        global_metrics.inc_counter("fleet/drains")
        if global_flightrec.armed:
            global_flightrec.record("fleet_drain", inflight=self._inflight)

    async def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful fleet drain: stop admitting, wait (bounded) for
        in-flight requests, drain in-process replicas, stop probing.
        Returns True when everything flushed within the timeout."""
        self.begin_drain()
        deadline = time.perf_counter() + max(float(timeout_s), 0.0)
        while self._inflight > 0 and time.perf_counter() < deadline:
            await asyncio.sleep(0.002)
        ok = self._inflight == 0
        for rep in self.replicas:
            if isinstance(rep, InProcessReplica):
                rep.server.begin_drain()
                ok = await rep.server.drain(
                    timeout_s=max(deadline - time.perf_counter(), 0.0)) \
                    and ok
        self.stop()
        if global_flightrec.armed:
            global_flightrec.record("fleet_drained", ok=ok)
        return ok

    # -- health state machine -------------------------------------------
    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            self.probe_once()
            self._stop.wait(self.probe_interval_s)

    def probe_once(self) -> None:
        """One probe sweep (the loop body; callable directly in tests)."""
        for rep in self.replicas:
            st = self._state[rep.name]
            try:
                alive, ready = rep.probe(self.probe_timeout_s)
            except Exception:
                alive, ready = False, False
            st.up = bool(alive)
            if alive and ready:
                st.ok_streak += 1
                st.fail_streak = 0
            else:
                st.fail_streak += 1
                st.ok_streak = 0
            if not st.quarantined and st.fail_streak >= self.fail_threshold:
                self._quarantine(rep.name, st)
            elif st.quarantined and st.ok_streak >= self.ok_threshold:
                self._reinstate(rep.name, st)
        self._publish_meta()

    def _quarantine(self, name: str, st: _ReplicaState) -> None:
        st.quarantined = True
        global_metrics.inc_counter("fleet/quarantines")
        if global_flightrec.armed:
            global_flightrec.record("fleet_quarantine", replica=name,
                                    up=st.up, fail_streak=st.fail_streak)

    def _reinstate(self, name: str, st: _ReplicaState) -> None:
        st.quarantined = False
        global_metrics.inc_counter("fleet/reinstates")
        if global_flightrec.armed:
            global_flightrec.record("fleet_reinstate", replica=name,
                                    ok_streak=st.ok_streak)

    def _publish_meta(self) -> None:
        global_metrics.set_meta("fleet", {
            "replicas": len(self.replicas),
            "replica_up": {r.name: int(self._state[r.name].up)
                           for r in self.replicas},
            "replica_quarantined": {
                r.name: int(self._state[r.name].quarantined)
                for r in self.replicas},
        })

    def healthy_replicas(self) -> List:
        return [r for r in self.replicas
                if not self._state[r.name].quarantined]

    # -- routing ---------------------------------------------------------
    def _pick(self, exclude: Optional[set] = None):
        """Next in-rotation replica, round-robin; quarantined and
        excluded (already tried this request) replicas are skipped.
        Falls back to ANY in-rotation replica when every one was tried
        (a second chance beats failing the request), then None."""
        pool = self.healthy_replicas()
        if not pool:
            return None
        fresh = [r for r in pool if not exclude or r.name not in exclude]
        pick_from = fresh or pool
        return pick_from[next(self._rr) % len(pick_from)]

    async def predict(self, name: str, data, raw_score: bool = False
                      ) -> np.ndarray:
        """Serve one request through the fleet. Bit-identical to any
        single replica's answer (pack contract); survives replica death
        mid-request via failover; sheds only when the fleet is draining
        or every attempt on every replica failed."""
        if self._draining:
            global_metrics.inc_counter("resilience/drain_rejected")
            raise ServerOverloaded(
                "fleet is draining (shutdown requested): not admitting "
                "new requests", retry_after_s=0.0)
        x = np.asarray(data, np.float64)
        global_metrics.inc_counter("fleet/requests")
        with self._lock:
            self._inflight += 1
        try:
            return await self._route(name, x, raw_score)
        finally:
            with self._lock:
                self._inflight -= 1

    async def _route(self, name: str, x: np.ndarray,
                     raw_score: bool) -> np.ndarray:
        tried: set = set()
        last_exc: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            rep = self._pick(exclude=tried)
            if rep is None:
                break  # whole fleet quarantined
            st = self._state[rep.name]
            try:
                probe_held = st.breaker.admit()
            except CircuitOpenError as exc:
                tried.add(rep.name)
                last_exc = exc
                continue
            try:
                out = await self._dispatch_hedged(rep, name, x, raw_score)
            except (DeadlineExceeded, asyncio.CancelledError):
                # load condition, not a replica fault: no failover (a
                # request past its deadline is dead on every replica)
                if probe_held:
                    st.breaker.release_probe()
                raise
            except ServerOverloaded as exc:
                # the replica shed (bounded admission / its own drain):
                # not a fault verdict, but another replica may have room
                if probe_held:
                    st.breaker.release_probe()
                self._note_failover(rep.name, attempt, exc)
                tried.add(rep.name)
                last_exc = exc
                continue
            except Exception as exc:
                # replica death / transient exhausted: breaker failure
                # + failover to the next healthy replica
                st.breaker.record_failure()
                st.fail_streak += 1  # dispatch faults feed quarantine too
                self._note_failover(rep.name, attempt, exc)
                tried.add(rep.name)
                last_exc = exc
                continue
            st.breaker.record_success()
            return out
        if last_exc is not None:
            raise last_exc
        raise ServerOverloaded(
            f"no replica in rotation ({len(self.replicas)} configured, "
            "all quarantined)", retry_after_s=self.probe_interval_s)

    def _note_failover(self, name: str, attempt: int,
                       exc: BaseException) -> None:
        global_metrics.inc_counter("fleet/failovers")
        if global_flightrec.armed:
            global_flightrec.record("fleet_failover", replica=name,
                                    attempt=attempt,
                                    error=type(exc).__name__)

    async def _dispatch(self, rep, name: str, x: np.ndarray,
                        raw_score: bool) -> np.ndarray:
        if isinstance(rep, InProcessReplica):
            return await rep.predict(name, x, raw_score=raw_score)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._io_executor, rep.predict_blocking, name, x, raw_score)

    async def _dispatch_hedged(self, rep, name: str, x: np.ndarray,
                               raw_score: bool) -> np.ndarray:
        """Primary dispatch with an optional hedge: if the primary has
        not answered within ``hedge_s``, fire a duplicate on another
        healthy replica and return whichever answers first. When both
        complete, the answers must be bit-identical — the failover
        safety argument, asserted in the hot path."""
        primary = asyncio.ensure_future(
            self._dispatch(rep, name, x, raw_score))
        if self.hedge_s <= 0:
            return await primary
        try:
            return await asyncio.wait_for(asyncio.shield(primary),
                                          self.hedge_s)
        except asyncio.TimeoutError:
            pass
        except Exception:
            raise  # primary failed fast: the failover loop handles it
        alt = self._pick(exclude={rep.name})
        if alt is None:
            return await primary  # nobody to hedge on
        global_metrics.inc_counter("fleet/hedges")
        if global_flightrec.armed:
            global_flightrec.record("fleet_hedge", primary=rep.name,
                                    hedge=alt.name)
        secondary = asyncio.ensure_future(
            self._dispatch(alt, name, x, raw_score))
        done, pending = await asyncio.wait(
            {primary, secondary}, return_when=asyncio.FIRST_COMPLETED)
        winner_out, winner_exc = None, None
        for fut in done:
            if fut.exception() is None:
                winner_out = fut.result()
                break
            winner_exc = fut.exception()
        if winner_out is None:
            # every completed future failed; the still-pending one is
            # the last hope
            if pending:
                return await next(iter(pending))
            raise winner_exc
        if pending:
            # let the loser finish in the background and hold it to the
            # bit-parity contract when it does
            loser = next(iter(pending))
            loser.add_done_callback(
                lambda fut, ref=winner_out: self._check_hedge_parity(
                    fut, ref))
        else:
            for fut in done:
                if fut.exception() is None and fut.result() is not \
                        winner_out:
                    self._assert_parity(winner_out, fut.result())
        global_metrics.inc_counter("fleet/hedge_wins")
        return winner_out

    def _check_hedge_parity(self, fut: "asyncio.Future", ref) -> None:
        if fut.cancelled() or fut.exception() is not None:
            return  # the loser died; the winner already answered
        self._assert_parity(ref, fut.result())

    def _assert_parity(self, a, b) -> None:
        same = (np.asarray(a).shape == np.asarray(b).shape
                and np.array_equal(np.asarray(a), np.asarray(b)))
        if not same:
            global_metrics.inc_counter("fleet/parity_violations")
            if global_flightrec.armed:
                global_flightrec.record("fleet_parity_violation")
            raise AssertionError(
                "hedged replicas returned different bits for the same "
                "request — the pack contract (PR-3) is broken")

    # -- observability ----------------------------------------------------
    def scrape_replicas(self) -> Dict[str, str]:
        """Each in-rotation replica's own /metrics document (the
        aggregator input). Quarantined/dead replicas are skipped — a
        scrape must not block on a corpse."""
        out: Dict[str, str] = {}
        for rep in self.healthy_replicas():
            try:
                out[rep.name] = rep.metrics_text()
            except Exception:
                pass
        return out

    def start_metrics_endpoint(self, port: int = 0,
                               host: Optional[str] = None):
        """Fleet-level /metrics (+ /healthz, /readyz): the process-wide
        obs document — which includes the fleet counters and the
        per-replica gauges from meta["fleet"]. Ready while at least one
        replica is in rotation and the fleet is not draining."""
        from ..obs.export import MetricsHTTPEndpoint, render_openmetrics
        if host is None:
            host = os.environ.get("LGBM_TPU_METRICS_HOST", "") \
                or "127.0.0.1"
        self._metrics_endpoint = MetricsHTTPEndpoint(
            render_openmetrics,
            ready_fn=lambda: (not self._draining
                              and bool(self.healthy_replicas())),
            port=port, host=host)
        return self._metrics_endpoint

    def stats(self) -> Dict[str, Any]:
        return {
            "replicas": {
                r.name: {"up": self._state[r.name].up,
                         "quarantined": self._state[r.name].quarantined,
                         "breaker": self._state[r.name].breaker.state}
                for r in self.replicas},
            "inflight": self._inflight,
            "draining": self._draining,
            "counters": {k: v for k, v in
                         sorted(global_metrics.counters.items())
                         if k.startswith("fleet/")},
        }


def aggregate_counter_totals(texts: Dict[str, str]) -> Dict[str, float]:
    """Merge replica ``/metrics`` scrapes into fleet-wide counter
    totals: every ``*_total`` family summed across replicas (labels
    ignored — the per-replica breakdown is what the individual scrape
    is for). Pure text processing, usable on any OpenMetrics input."""
    totals: Dict[str, float] = {}
    for text in texts.values():
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            name_part, _, value = line.rpartition(" ")
            family = name_part.split("{", 1)[0].strip()
            if not family.endswith("_total"):
                continue
            try:
                totals[family] = totals.get(family, 0.0) + float(value)
            except ValueError:
                continue
    return totals


# ----------------------------------------------------------------------
# fleet construction + the subprocess replica protocol


def build_inprocess_fleet(model_str: str, cfg,
                          n_replicas: Optional[int] = None
                          ) -> FleetRouter:
    """N in-process replicas, each its own registry + ModelServer (the
    shared model tier is the model STRING — each replica packs it
    independently, and the pack contract makes the packs bit-identical).
    For tests and ``bench.py --fleet``; the chaos validator uses real
    subprocesses instead."""
    from .server import registry_from_config, server_from_config
    n = int(n_replicas if n_replicas is not None
            else getattr(cfg, "serve_fleet_replicas", 3))
    replicas = []
    for i in range(n):
        registry = registry_from_config(cfg)
        registry.load("default", model_str=model_str)
        replicas.append(InProcessReplica(
            f"r{i}", server_from_config(registry, cfg)))
    return FleetRouter(
        replicas,
        probe_interval_ms=getattr(cfg, "serve_probe_interval_ms", 50.0),
        hedge_ms=getattr(cfg, "serve_hedge_ms", 0.0),
        breaker_threshold=getattr(cfg, "serve_breaker_threshold", 5),
        breaker_reset_s=getattr(cfg, "serve_breaker_reset_s", 30.0))


class ReplicaHTTPEndpoint:
    """The subprocess replica's HTTP front: ``POST /predict`` next to
    the stock GET /metrics, /healthz, /readyz. Handler threads submit
    coroutines onto the replica's event loop and block on the result —
    the asyncio server keeps coalescing while many requests wait."""

    def __init__(self, server: ModelServer, loop: asyncio.AbstractEventLoop,
                 port: int = 0, host: str = "127.0.0.1",
                 request_timeout_s: float = 60.0):
        import http.server

        from ..obs.export import negotiate_content_type, render_openmetrics

        def render() -> str:
            return render_openmetrics(extra_gauges={
                "lgbmtpu_serve_pack_bytes": server.registry.pack_bytes(),
                "lgbmtpu_serve_models": len(server.registry),
            })

        timeout_s = float(request_timeout_s)

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes,
                      headers: Optional[Dict[str, str]] = None,
                      ctype: str = "application/octet-stream") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = render().encode()
                    self._send(200, body, ctype=negotiate_content_type(
                        self.headers.get("Accept")))
                elif path == "/healthz":
                    self._send(200, b"ok\n", ctype="text/plain")
                elif path == "/readyz":
                    ok = bool(server.ready)
                    self._send(200 if ok else 503,
                               b"ready\n" if ok else b"not ready\n",
                               ctype="text/plain")
                else:
                    self._send(404, b"not found\n", ctype="text/plain")

            def do_POST(self) -> None:
                if self.path.split("?", 1)[0] != "/predict":
                    self._send(404, b"not found\n", ctype="text/plain")
                    return
                try:
                    shape = tuple(int(d) for d in
                                  self.headers["X-Shape"].split(","))
                    n = int(self.headers.get("Content-Length", "0"))
                    x = np.frombuffer(self.rfile.read(n),
                                      np.float64).reshape(shape)
                    name = self.headers.get("X-Model", "default")
                    raw = self.headers.get("X-Raw-Score", "0") == "1"
                except Exception as exc:
                    self._send(400, f"bad request: {exc}\n".encode(),
                               ctype="text/plain")
                    return
                fut = asyncio.run_coroutine_threadsafe(
                    server.predict(name, x, raw_score=raw), loop)
                try:
                    out = np.ascontiguousarray(fut.result(timeout_s),
                                               np.float64)
                except Exception as exc:
                    fut.cancel()
                    kind = type(exc).__name__
                    code = _ERROR_STATUS.get(kind, 500)
                    self._send(code, f"{exc}\n".encode(),
                               headers={"X-Error": kind},
                               ctype="text/plain")
                    return
                self._send(200, out.tobytes(), headers={
                    "X-Shape": ",".join(str(d) for d in out.shape)})

            def log_message(self, *args) -> None:
                pass  # request logging rides the obs counters instead

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="lgbm-replica-http",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def _replica_main(argv: Optional[List[str]] = None) -> int:
    """Entry of one subprocess replica: ``python -m
    lightgbm_tpu.serve.fleet --replica model=<file> port=<p>
    [key=value ...]``.

    Builds the same registry/server serve_file does, serves the replica
    HTTP protocol, prints one ``READY <port>`` line (the spawner's
    rendezvous), and on SIGTERM drains and exits ``EXIT_PREEMPTED``."""
    import signal
    import sys

    from ..config import Config
    from ..resilience.errors import EXIT_PREEMPTED
    from .server import registry_from_config, server_from_config

    args = list(argv if argv is not None else sys.argv[1:])
    if args and args[0] == "--replica":
        args = args[1:]
    params: Dict[str, Any] = {}
    for tok in args:
        if "=" not in tok:
            raise SystemExit(f"replica args are key=value, got {tok!r}")
        k, v = tok.split("=", 1)
        params[k.strip()] = v.strip()
    model_file = params.pop("model", "")
    port = int(params.pop("port", "0"))
    if not model_file:
        raise SystemExit("replica needs model=<file>")

    cfg = Config.from_params(params)
    registry = registry_from_config(cfg)
    registry.load("default", model_file=model_file, validate=True)
    server = server_from_config(registry, cfg)

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    endpoint = ReplicaHTTPEndpoint(server, loop, port=port)
    exit_code = {"code": 0}

    def _on_sigterm() -> None:
        async def _drain_and_stop() -> None:
            server.begin_drain()  # /readyz deregisters immediately
            await server.drain()
            await server.close()
            exit_code["code"] = EXIT_PREEMPTED
            loop.stop()
        asyncio.ensure_future(_drain_and_stop())

    loop.add_signal_handler(signal.SIGTERM, _on_sigterm)
    print(f"READY {endpoint.port}", flush=True)
    try:
        loop.run_forever()
    finally:
        endpoint.close()
        loop.close()
    return exit_code["code"]


if __name__ == "__main__":  # pragma: no cover - exercised by check_fleet
    import sys
    sys.exit(_replica_main())
