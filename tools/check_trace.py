#!/usr/bin/env python
"""Validate a Chrome trace-event JSON file produced by
``lightgbm_tpu.obs.trace`` (``LGBM_TPU_TRACE=/path.json`` or the
``trace_output`` param).

Checks, in order:
  1. the file is valid JSON;
  2. it is either a bare event list or an object with a
     ``traceEvents`` list (both forms are valid Chrome traces);
  3. every event has the required fields with the right types
     (``name`` str, ``ph`` str, and for complete events ``ph == "X"``:
     numeric non-negative ``ts`` and ``dur``);
  4. metadata events (``ph == "M"``) named ``process_name`` /
     ``thread_name`` / ``process_labels`` carry a dict ``args`` with
     the string payload Perfetto renders (``name`` / ``labels``);
  5. when the trace declares our exporter as producer
     (``otherData.producer == "lightgbm_tpu.obs.trace"``), every pid
     must have a ``process_name`` and every (pid, tid) track with
     complete spans a ``thread_name`` — multi-thread / multi-process
     traces are unreadable pid/tid soup without them;
  6. per (pid, tid) track, ``ts`` is monotonically non-decreasing in
     file order (the exporter sorts by start time; a violation means a
     corrupted or hand-edited trace).

Usage:  python tools/check_trace.py TRACE.json
Exit 0 when the trace is valid; 1 with a diagnostic otherwise — so a
CI or bench run can assert trace integrity with one command.
"""

from __future__ import annotations

import json
import sys
from typing import Any, List, Tuple


def check_trace(path: str) -> Tuple[bool, str]:
    """-> (ok, message). Importable for tests; no side effects."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except OSError as exc:
        return False, f"cannot read {path}: {exc}"
    except json.JSONDecodeError as exc:
        return False, f"{path} is not valid JSON: {exc}"

    our_producer = False
    if isinstance(doc, list):
        events: List[Any] = doc
    elif isinstance(doc, dict):
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            return False, "top-level object has no 'traceEvents' list"
        our_producer = (doc.get("otherData", {}).get("producer")
                        == "lightgbm_tpu.obs.trace")
    else:
        return False, f"unexpected top-level JSON type {type(doc).__name__}"

    _META_PAYLOAD = {"process_name": "name", "thread_name": "name",
                     "process_labels": "labels"}
    last_ts = {}  # (pid, tid) -> ts
    named_pids, named_tracks = set(), set()  # from metadata events
    n_complete = n_meta = 0
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            return False, f"event {i} is not an object"
        name, ph = ev.get("name"), ev.get("ph")
        if not isinstance(name, str) or not name:
            return False, f"event {i} has no string 'name'"
        if not isinstance(ph, str) or not ph:
            return False, f"event {i} ({name!r}) has no string 'ph'"
        if ph == "M" and name in _META_PAYLOAD:
            key = _META_PAYLOAD[name]
            args = ev.get("args")
            if not isinstance(args, dict) or \
                    not isinstance(args.get(key), str) or not args[key]:
                return False, (f"metadata event {i} ({name!r}) lacks a "
                               f"string args.{key}")
            n_meta += 1
            if name == "process_name":
                named_pids.add(ev.get("pid"))
            elif name == "thread_name":
                named_tracks.add((ev.get("pid"), ev.get("tid")))
        if ph != "X":
            continue  # metadata/counter events need no ts ordering
        n_complete += 1
        ts, dur = ev.get("ts"), ev.get("dur")
        if not isinstance(ts, (int, float)) or ts < 0:
            return False, f"event {i} ({name!r}) has invalid ts={ts!r}"
        if not isinstance(dur, (int, float)) or dur < 0:
            return False, f"event {i} ({name!r}) has invalid dur={dur!r}"
        track = (ev.get("pid"), ev.get("tid"))
        prev = last_ts.get(track)
        if prev is not None and ts < prev:
            return False, (f"event {i} ({name!r}) breaks ts monotonicity "
                           f"on track {track}: {ts} < {prev}")
        last_ts[track] = ts
    if our_producer and n_complete:
        for pid, tid in last_ts:
            if pid not in named_pids:
                return False, (f"trace from lightgbm_tpu.obs.trace lacks a "
                               f"process_name metadata event for pid {pid}")
            if (pid, tid) not in named_tracks:
                return False, (f"trace from lightgbm_tpu.obs.trace lacks a "
                               f"thread_name metadata event for track "
                               f"({pid}, {tid})")
    return True, (f"ok: {n_complete} complete spans on {len(last_ts)} "
                  f"track(s), {n_meta} metadata event(s)")


def main(argv: List[str]) -> int:
    if len(argv) != 2:
        print("usage: python tools/check_trace.py TRACE.json",
              file=sys.stderr)
        return 2
    ok, msg = check_trace(argv[1])
    print(msg, file=sys.stdout if ok else sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
