"""Training telemetry subsystem.

The TPU-native expansion of the reference's ``USE_TIMETAG`` phase
timers (ref: Common::Timer / FunctionTimer, include/LightGBM/utils/
common.h:980,1044; global_timer dump at src/boosting/gbdt.cpp:29):

- ``obs.trace``   — nested named spans with parent/child self-time
  attribution, exportable as Chrome trace-event JSON
  (``LGBM_TPU_TRACE=/path.json`` or the ``trace_output`` param) and as
  an aggregated summary dict.
- ``obs.metrics`` — per-iteration metrics registry: phase times,
  grad/hess norms, leaves grown, split-gain stats, JIT recompilation
  counts, device memory, collective traffic.
- ``obs.memory`` — HBM memory observability: the analytic peak-memory
  model (``train_memory_model`` / ``predict_memory_model``), live
  per-phase watermarks sampled at span boundaries
  (``global_watermarks``), and the ``preflight`` capacity planner that
  fails fast (with knob recommendations) instead of OOMing mid-run.
- ``obs.xla``    — XLA program introspection: per-executable
  ``cost_analysis()`` / ``memory_analysis()`` capture, compile
  wall-time, and per-phase/shape-bucket recompile attribution
  (``instrumented_jit`` at the program boundaries).
- ``obs.health`` — training-health: runtime-attributed collective
  byte/call counters with a timed mesh microprobe, host straggler-skew
  attribution, cross-shard drift sentinels over replicated state
  (``tpu_health=off/warn/error`` — warn records, error raises
  ``DriftError``/``NonFiniteError``), per-iteration NaN/Inf sentinels
  folded into the fused programs, and an eval-loss anomaly detector.
- ``obs.profile`` — device-time attribution: ``jax.profiler``-backed
  capture windows (``tpu_profile=off/window/bench`` +
  ``LGBM_TPU_PROFILE_DIR``) parsed into per-program device-busy
  seconds keyed to the obs tags, a profiler-free
  ``block_until_ready`` fallback for CPU CI, and the roofline layer
  (achieved bytes/s + utilization vs ``hostenv.platform_peaks`` + a
  memory/compute-bound verdict per tag).
- ``obs.flightrec`` — crash flight recorder: a bounded ring of recent
  structured events (iterations, serve outcomes, health anomalies,
  fault injections, checkpoint/resume transitions) atomically dumped
  on DriftError/NonFiniteError/SIGTERM/exit-75/exit and on demand
  (``LGBM_TPU_FLIGHTREC=/path.json``).
- ``obs.export`` — OpenMetrics egress: the Prometheus text-format
  renderer over all of the above, the ``/metrics``+``/healthz``+
  ``/readyz`` HTTP endpoint (Accept-negotiated OpenMetrics vs
  Prometheus content type, ``# EOF``-terminated), and the
  ``LGBM_TPU_METRICS_FILE`` textfile flusher.

All are disabled by default and their hot-path guards are single
attribute checks — training with telemetry off records nothing and
allocates nothing per span/observation.
"""

from .trace import Tracer, global_tracer  # noqa: F401
from .metrics import (LatencyReservoir, MetricsRegistry,  # noqa: F401
                      global_metrics)
from .memory import (PhaseWatermarks, PreflightError,  # noqa: F401
                     PreflightReport, device_capacity_bytes,
                     global_watermarks, predict_memory_model, preflight,
                     preflight_predict, train_memory_model)
from .xla import (XlaIntrospector, aot_cost_summary,  # noqa: F401
                  global_xla, instrumented_jit)
from .health import (DriftError, HealthError,  # noqa: F401
                     HealthRegistry, NonFiniteError, global_health)
from .profile import (ProfileRegistry, global_profile,  # noqa: F401
                      parse_trace_events)
from .flightrec import (FlightRecorder, global_flightrec,  # noqa: F401
                        validate_dump)
from .export import (MetricsHTTPEndpoint,  # noqa: F401
                     MetricsTextfileFlusher, global_flusher,
                     render_openmetrics)

__all__ = ["Tracer", "global_tracer", "LatencyReservoir",
           "MetricsRegistry", "global_metrics",
           "PhaseWatermarks", "PreflightError", "PreflightReport",
           "device_capacity_bytes", "global_watermarks",
           "train_memory_model", "predict_memory_model",
           "preflight", "preflight_predict",
           "XlaIntrospector", "global_xla", "instrumented_jit",
           "aot_cost_summary", "HealthError", "DriftError",
           "NonFiniteError", "HealthRegistry", "global_health",
           "ProfileRegistry", "global_profile", "parse_trace_events",
           "FlightRecorder", "global_flightrec", "validate_dump",
           "MetricsHTTPEndpoint",
           "MetricsTextfileFlusher", "global_flusher",
           "render_openmetrics"]
