"""Objective functions (gradients/hessians as XLA element-wise ops).

TPU-native re-implementation of the reference objective layer
(ref: src/objective/objective_function.cpp:72 factory;
regression_objective.hpp, binary_objective.hpp, multiclass_objective.hpp,
xentropy_objective.hpp, rank_objective.hpp). Each objective exposes
`get_gradients(score) -> (grad, hess)` as traced jnp ops so the gradient
computation fuses into the per-iteration XLA program (the analog of
boosting_on_gpu_, gbdt.cpp:111).

Ranking objectives operate on query-padded [num_queries, max_docs] views
built once at init (segment layout replaces the reference's per-query
OpenMP loops).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .config import Config
from .dataset import Metadata


class ObjectiveFunction:
    """Base objective (ref: include/LightGBM/objective_function.h)."""

    name: str = "custom"
    is_ranking: bool = False
    num_model_per_iteration: int = 1

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label_np = metadata.label if metadata.label is not None else \
            np.zeros(num_data, np.float32)
        self.weight_np = metadata.weight
        self.label = jnp.asarray(self.label_np)
        self.weight = (jnp.asarray(self.weight_np)
                       if self.weight_np is not None else None)

    def _apply_weight(self, grad, hess):
        if self.weight is not None:
            return grad * self.weight, hess * self.weight
        return grad, hess

    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def pointwise_grad_fn(self):
        """Optional pure POINTWISE form of `get_gradients`: a function
        ``(score, label, weight_or_None) -> (grad, hess)`` whose formula
        is bitwise-identical to `get_gradients` but closes over no [N]
        device buffers — so the waved grower can evaluate it inline (or
        inside the pallas histogram kernel) and the standalone
        gradient/bagging element-wise pass disappears from the per-
        iteration HBM traffic (the `tpu_fused_grad` knob). None when
        the objective's gradients aren't pointwise in (score, label)
        (ranking pairs, softmax cross-class coupling, ...)."""
        return None

    # -- device-state plumbing ------------------------------------------
    # N-sized device buffers (labels, weights, ranking pad layouts) must
    # enter jitted programs as *arguments*, never as closed-over constants:
    # closure capture bakes them into the HLO as literals, which at
    # Higgs scale (10.5M rows) overflows the compile payload entirely
    # (the reference never faces this: its objectives read raw pointers,
    # objective_function.h GetGradients).
    # attribute names that EVOLVE across iterations (e.g. lambdarank
    # position biases). Only these come back out of the fused program —
    # returning the full state would force XLA to copy every constant
    # [N] label/weight buffer as a fresh program output each iteration.
    _evolving_attrs: tuple = ()

    def device_state(self, evolving_only: bool = False):
        """Pytree of this objective's device-resident arrays (recursing
        into sub-objectives), for passing as explicit jit arguments.
        evolving_only=True restricts to `_evolving_attrs` — the subset a
        fused iteration needs to return as outputs."""
        arrays = {k: v for k, v in vars(self).items()
                  if isinstance(v, jax.Array)
                  and (not evolving_only or k in self._evolving_attrs)}
        sub = {}
        for k, v in vars(self).items():
            if isinstance(v, list) and v and all(
                    isinstance(o, ObjectiveFunction) for o in v):
                sub[k] = [o.device_state(evolving_only) for o in v]
        return {"arrays": arrays, "sub": sub}

    def swap_device_state(self, state):
        """Install `state`'s arrays as attributes, returning the previous
        state (call again with the return value to restore). Used inside
        jit tracing so traced gradient code references argument tracers."""
        old = {"arrays": {}, "sub": {}}
        for k, v in state["arrays"].items():
            old["arrays"][k] = getattr(self, k)
            setattr(self, k, v)
        for k, lst in state["sub"].items():
            objs = getattr(self, k)
            old["sub"][k] = [o.swap_device_state(s)
                             for o, s in zip(objs, lst)]
        return old

    @property
    def is_constant_hessian(self) -> bool:
        """(ref: ObjectiveFunction::IsConstantHessian — true when every
        row's hessian is the same, letting quantized training keep full
        hessian precision.)"""
        return False

    def boost_from_score(self, class_id: int = 0) -> float:
        """Initial raw score (ref: BoostFromScore per objective)."""
        return 0.0

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        """Raw score -> prediction output (ref: ConvertOutput)."""
        return raw

    def renew_tree_output(self, tree, score_np, row_leaf_np, sample_mask_np):
        """Optionally recompute leaf outputs after growth (ref:
        RenewTreeOutput for L1-family objectives). Returns tree or None."""
        return None

    def renew_leaves_traced(self, leaf_value, row_leaf, score, mask):
        """Traced twin of `renew_tree_output` for the fused fast path:
        given device arrays (leaf_value [L], row_leaf [N], score [N],
        sample mask [N]) return renewed leaf values [L], or None when the
        objective has no device renewal. Objectives overriding
        `renew_tree_output` should override this too so they keep the
        one-XLA-program-per-iteration path (the reference's equivalent
        host work runs inside the training loop, gbdt.cpp:420)."""
        return None

    def _weights_or_ones(self):
        if self.weight_np is not None:
            return self.weight_np.astype(np.float64)
        return np.ones(self.num_data, np.float64)


# ---------------------------------------------------------------------------
# Regression family (ref: src/objective/regression_objective.hpp)
# ---------------------------------------------------------------------------
class RegressionL2(ObjectiveFunction):
    name = "regression"

    @property
    def is_constant_hessian(self) -> bool:
        return self.weight_np is None and type(self) is RegressionL2

    def get_gradients(self, score):
        return self._apply_weight(score - self.label,
                                  jnp.ones_like(score))

    def pointwise_grad_fn(self):
        if type(self) is not RegressionL2:
            return None  # subclasses redefine get_gradients

        def fn(score, label, weight):
            grad, hess = score - label, jnp.ones_like(score)
            if weight is not None:
                grad, hess = grad * weight, hess * weight
            return grad, hess
        return fn

    def boost_from_score(self, class_id: int = 0) -> float:
        w = self._weights_or_ones()
        return float(np.sum(self.label_np * w) / np.sum(w))


def _weighted_percentile(values: np.ndarray, weights: np.ndarray,
                         alpha: float) -> float:
    """Weighted alpha-percentile (ref: PercentileFun/WeightedPercentileFun,
    regression_objective.hpp:23-60)."""
    if len(values) == 0:
        return 0.0
    order = np.argsort(values)
    v, w = values[order], weights[order]
    cw = np.cumsum(w)
    target = alpha * cw[-1]
    idx = int(np.searchsorted(cw, target))
    idx = min(idx, len(v) - 1)
    return float(v[idx])


class RegressionL1(RegressionL2):
    name = "regression_l1"

    def get_gradients(self, score):
        diff = score - self.label
        return self._apply_weight(jnp.sign(diff), jnp.ones_like(score))

    def boost_from_score(self, class_id: int = 0) -> float:
        return _weighted_percentile(self.label_np.astype(np.float64),
                                    self._weights_or_ones(), 0.5)

    def renew_tree_output(self, tree, score_np, row_leaf_np, sample_mask_np):
        return _renew_by_percentile(tree, self.label_np - score_np,
                                    self._weights_or_ones(), row_leaf_np,
                                    sample_mask_np, 0.5)

    def renew_leaves_traced(self, leaf_value, row_leaf, score, mask):
        w = self.weight if self.weight is not None else jnp.ones_like(score)
        return _percentile_renew_traced(leaf_value, row_leaf,
                                        self.label - score, w, mask, 0.5)


class Huber(RegressionL2):
    name = "huber"

    def get_gradients(self, score):
        a = self.config.alpha
        diff = score - self.label
        grad = jnp.clip(diff, -a, a)
        return self._apply_weight(grad, jnp.ones_like(score))

    def renew_tree_output(self, tree, score_np, row_leaf_np, sample_mask_np):
        return _renew_by_percentile(tree, self.label_np - score_np,
                                    self._weights_or_ones(), row_leaf_np,
                                    sample_mask_np, 0.5)

    def renew_leaves_traced(self, leaf_value, row_leaf, score, mask):
        w = self.weight if self.weight is not None else jnp.ones_like(score)
        return _percentile_renew_traced(leaf_value, row_leaf,
                                        self.label - score, w, mask, 0.5)


class Fair(RegressionL2):
    name = "fair"

    def get_gradients(self, score):
        c = self.config.fair_c
        diff = score - self.label
        denom = jnp.abs(diff) + c
        return self._apply_weight(c * diff / denom, c * c / (denom * denom))


class Poisson(RegressionL2):
    name = "poisson"

    def get_gradients(self, score):
        mu = jnp.exp(score)
        grad = mu - self.label
        hess = jnp.exp(score + self.config.poisson_max_delta_step)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        w = self._weights_or_ones()
        mean = np.sum(self.label_np * w) / np.sum(w)
        return float(np.log(max(mean, 1e-20)))

    def convert_output(self, raw):
        return np.exp(raw)


class Quantile(RegressionL2):
    name = "quantile"

    def get_gradients(self, score):
        a = self.config.alpha
        grad = jnp.where(score > self.label, 1.0 - a, -a)
        return self._apply_weight(grad, jnp.ones_like(score))

    def boost_from_score(self, class_id: int = 0) -> float:
        return _weighted_percentile(self.label_np.astype(np.float64),
                                    self._weights_or_ones(),
                                    self.config.alpha)

    def renew_tree_output(self, tree, score_np, row_leaf_np, sample_mask_np):
        return _renew_by_percentile(tree, self.label_np - score_np,
                                    self._weights_or_ones(), row_leaf_np,
                                    sample_mask_np, self.config.alpha)

    def renew_leaves_traced(self, leaf_value, row_leaf, score, mask):
        w = self.weight if self.weight is not None else jnp.ones_like(score)
        return _percentile_renew_traced(leaf_value, row_leaf,
                                        self.label - score, w, mask,
                                        self.config.alpha)


class MAPE(RegressionL2):
    name = "mape"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._trans = 1.0 / np.maximum(1.0, np.abs(self.label_np))
        self.trans = jnp.asarray(self._trans.astype(np.float32))

    def get_gradients(self, score):
        diff = score - self.label
        return self._apply_weight(jnp.sign(diff) * self.trans, self.trans)

    def boost_from_score(self, class_id: int = 0) -> float:
        return _weighted_percentile(
            self.label_np.astype(np.float64),
            self._weights_or_ones() * self._trans, 0.5)

    def renew_tree_output(self, tree, score_np, row_leaf_np, sample_mask_np):
        return _renew_by_percentile(tree, self.label_np - score_np,
                                    self._weights_or_ones() * self._trans,
                                    row_leaf_np, sample_mask_np, 0.5)

    def renew_leaves_traced(self, leaf_value, row_leaf, score, mask):
        w = self.trans if self.weight is None else self.weight * self.trans
        return _percentile_renew_traced(leaf_value, row_leaf,
                                        self.label - score, w, mask, 0.5)


class Gamma(Poisson):
    name = "gamma"

    def get_gradients(self, score):
        e = jnp.exp(-score)
        return self._apply_weight(1.0 - self.label * e, self.label * e)


class Tweedie(Poisson):
    name = "tweedie"

    def get_gradients(self, score):
        rho = self.config.tweedie_variance_power
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        grad = -self.label * e1 + e2
        hess = -self.label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return self._apply_weight(grad, hess)


def _percentile_renew_traced(leaf_value, row_leaf, residual, weights, mask,
                             alpha):
    """Traced per-leaf weighted percentile: the device twin of
    `_renew_by_percentile` (ref: RegressionL1loss::RenewTreeOutput +
    PercentileFun, regression_objective.hpp:23-60), restructured for XLA:
    one lexicographic sort by (leaf, residual) replaces the per-leaf
    host loops, then each leaf's percentile index is a searchsorted into
    the global weight cumsum restricted to its segment."""
    num_slots = leaf_value.shape[0]
    n = residual.shape[0]
    valid = mask > 0
    leaf = jnp.where(valid, row_leaf, num_slots).astype(jnp.int32)
    res = residual.astype(jnp.float32)
    w = jnp.where(valid, weights, 0.0).astype(jnp.float32)
    leaf_s, res_s, w_s = jax.lax.sort((leaf, res, w), num_keys=2)
    cumw = jnp.cumsum(w_s)
    ids = jnp.arange(num_slots)
    start = jnp.searchsorted(leaf_s, ids, side="left")
    end = jnp.searchsorted(leaf_s, ids, side="right")
    base = jnp.where(start > 0, cumw[jnp.clip(start - 1, 0, n - 1)], 0.0)
    endw = jnp.where(end > 0, cumw[jnp.clip(end - 1, 0, n - 1)], 0.0)
    total = endw - base
    # first in-segment index where cumulative weight reaches alpha*total
    # (== np.searchsorted(cw_local, alpha * cw_local[-1]) in the host twin)
    idx = jnp.searchsorted(cumw, base + alpha * total, side="left")
    idx = jnp.clip(idx, start, jnp.maximum(end - 1, start))
    vals = res_s[jnp.clip(idx, 0, n - 1)]
    occupied = (end > start) & (total > 0)
    return jnp.where(occupied, vals, leaf_value)


def _renew_by_percentile(tree, residual, weights, row_leaf, sample_mask,
                         alpha):
    """Set each leaf value to the weighted alpha-percentile of its
    residuals (ref: RegressionL1loss::RenewTreeOutput).

    Routed through ``_percentile_renew_traced`` — the SAME device
    function the fused fast path runs — so the two paths cannot
    disagree on knife-edge percentile picks. An f64 host loop
    (``_weighted_percentile`` per leaf) and the f32 traced selection
    round ``alpha * total_weight`` differently when it lands within an
    ulp of a cumulative-weight step (e.g. alpha=0.7 over a leaf of 10
    unit-weight rows: f64 says 7.000…001, f32 says 6.999…99 — an
    off-by-one order-statistic pick that compounds through later
    iterations). One implementation, two callers, zero cliffs;
    tests/test_objectives.py keeps the traced selection within 1e-5 of
    the f64 host oracle on non-degenerate fixtures."""
    import jax.numpy as jnp
    lv = _percentile_renew_traced(
        jnp.asarray(np.asarray(tree.leaf_value, np.float32)),
        jnp.asarray(np.asarray(row_leaf, np.int32)),
        jnp.asarray(np.asarray(residual, np.float32)),
        jnp.asarray(np.asarray(weights, np.float32)),
        jnp.asarray(np.asarray(sample_mask, np.float32)), float(alpha))
    tree.leaf_value = np.asarray(lv, np.float64).copy()
    return tree


def _renew_by_percentile_host(tree, residual, weights, row_leaf,
                              sample_mask, alpha):
    """The f64 host-loop oracle of `_renew_by_percentile` (per-leaf
    ``_weighted_percentile``) — kept as the reference semantics the
    traced selection is tested against."""
    sel = sample_mask > 0
    leaves = row_leaf[sel]
    res = residual[sel].astype(np.float64)
    w = weights[sel]
    new_values = tree.leaf_value.copy()
    for leaf in np.unique(leaves):
        m = leaves == leaf
        new_values[leaf] = _weighted_percentile(res[m], w[m], alpha)
    tree.leaf_value = new_values
    return tree


# ---------------------------------------------------------------------------
# Binary (ref: src/objective/binary_objective.hpp:22)
# ---------------------------------------------------------------------------
class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        cfg = self.config
        pos = float(np.sum((self.label_np > 0) *
                           (self.weight_np if self.weight_np is not None
                            else 1.0)))
        neg_w = (self.weight_np if self.weight_np is not None else
                 np.ones_like(self.label_np))
        neg = float(np.sum((self.label_np <= 0) * neg_w))
        self._cnt_pos, self._cnt_neg = pos, neg
        # label weights (ref: binary_objective.hpp is_unbalance/scale_pos_weight)
        if cfg.is_unbalance and pos > 0 and neg > 0:
            if pos > neg:
                self._pos_w, self._neg_w = 1.0, pos / neg
            else:
                self._pos_w, self._neg_w = neg / pos, 1.0
        else:
            self._pos_w, self._neg_w = float(cfg.scale_pos_weight), 1.0

    def get_gradients(self, score):
        sig = self.config.sigmoid
        y = (self.label > 0).astype(score.dtype)
        p = jax.nn.sigmoid(sig * score)
        lw = jnp.where(y > 0, self._pos_w, self._neg_w)
        grad = sig * (p - y) * lw
        hess = sig * sig * p * (1.0 - p) * lw
        return self._apply_weight(grad, hess)

    def pointwise_grad_fn(self):
        if type(self) is not BinaryLogloss:
            return None
        sig = float(self.config.sigmoid)
        pos_w, neg_w = self._pos_w, self._neg_w

        def fn(score, label, weight):
            # op-for-op the get_gradients formula, so values are bitwise
            # identical whether computed here, in XLA, or in-kernel
            y = (label > 0).astype(score.dtype)
            p = jax.nn.sigmoid(sig * score)
            lw = jnp.where(y > 0, pos_w, neg_w)
            grad = sig * (p - y) * lw
            hess = sig * sig * p * (1.0 - p) * lw
            if weight is not None:
                grad, hess = grad * weight, hess * weight
            return grad, hess
        return fn

    def boost_from_score(self, class_id: int = 0) -> float:
        if not self.config.boost_from_average:
            return 0.0
        w = self._weights_or_ones()
        pavg = float(np.sum((self.label_np > 0) * w) / np.sum(w))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return math.log(pavg / (1.0 - pavg)) / self.config.sigmoid

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.config.sigmoid * raw))


# ---------------------------------------------------------------------------
# Multiclass (ref: src/objective/multiclass_objective.hpp:25,187)
# ---------------------------------------------------------------------------
class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_model_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.label_int = jnp.asarray(self.label_np.astype(np.int32))

    def get_gradients_multi(self, scores):
        """scores: [K, N] -> grads, hesses [K, N]."""
        p = jax.nn.softmax(scores, axis=0)
        k = scores.shape[0]
        onehot = (self.label_int[None, :] ==
                  jnp.arange(k, dtype=jnp.int32)[:, None]).astype(scores.dtype)
        grad = p - onehot
        # hessian upper-bound factor K/(K-1)
        # (ref: multiclass_objective.hpp:32 factor_)
        factor = k / (k - 1.0) if k > 1 else 2.0
        hess = factor * p * (1.0 - p)
        if self.weight is not None:
            grad = grad * self.weight[None, :]
            hess = hess * self.weight[None, :]
        return grad, hess

    def boost_from_score(self, class_id: int = 0) -> float:
        if not self.config.boost_from_average:
            return 0.0
        w = self._weights_or_ones()
        p = float(np.sum((self.label_np.astype(int) == class_id) * w)
                  / np.sum(w))
        return math.log(max(p, 1e-15))

    def convert_output(self, raw):
        """raw: [N, K] -> softmax probabilities."""
        e = np.exp(raw - raw.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_model_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._binary = []
        for k in range(self.config.num_class):
            sub = BinaryLogloss(self.config)
            meta_k = Metadata(num_data)
            meta_k.label = (self.label_np.astype(int) == k).astype(np.float32)
            meta_k.weight = self.weight_np
            sub.init(meta_k, num_data)
            self._binary.append(sub)

    def get_gradients_multi(self, scores):
        grads, hesses = [], []
        for k in range(scores.shape[0]):
            g, h = self._binary[k].get_gradients(scores[k])
            grads.append(g)
            hesses.append(h)
        return jnp.stack(grads), jnp.stack(hesses)

    def boost_from_score(self, class_id: int = 0) -> float:
        return self._binary[class_id].boost_from_score()

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.config.sigmoid * raw))


# ---------------------------------------------------------------------------
# Cross-entropy on [0,1] labels (ref: src/objective/xentropy_objective.hpp)
# ---------------------------------------------------------------------------
class CrossEntropy(ObjectiveFunction):
    name = "cross_entropy"

    def get_gradients(self, score):
        p = jax.nn.sigmoid(score)
        return self._apply_weight(p - self.label, p * (1.0 - p))

    def boost_from_score(self, class_id: int = 0) -> float:
        w = self._weights_or_ones()
        pavg = float(np.sum(self.label_np * w) / np.sum(w))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-raw))


class CrossEntropyLambda(ObjectiveFunction):
    """Alternative parametrization with weights folded in
    (ref: xentropy_objective.hpp:186 CrossEntropyLambdaloss)."""
    name = "cross_entropy_lambda"

    def get_gradients(self, score):
        w = self.weight if self.weight is not None else 1.0
        epf = jnp.exp(score)
        # grad = (1 - label/hhat) * (w*epf/(1+w*epf)) with hhat = log1p(w*epf)
        wepf = w * epf
        hhat = jnp.log1p(wepf)
        s = wepf / (1.0 + wepf)
        grad = (1.0 - self.label / jnp.maximum(hhat, 1e-30)) * s
        hess = s * (1.0 - s) * (1.0 - self.label / jnp.maximum(hhat, 1e-30)) \
            + self.label * (s / jnp.maximum(hhat, 1e-30)) ** 2
        return grad, hess

    def boost_from_score(self, class_id: int = 0) -> float:
        w = self._weights_or_ones()
        pavg = float(np.sum(self.label_np * w) / np.sum(w))
        pavg = max(pavg, 1e-15)
        return math.log(max(math.expm1(pavg), 1e-15))

    def convert_output(self, raw):
        return np.log1p(np.exp(raw))


# ---------------------------------------------------------------------------
# Ranking (ref: src/objective/rank_objective.hpp:26,139,385)
# ---------------------------------------------------------------------------
class _RankingObjective(ObjectiveFunction):
    is_ranking = True
    # position biases are Newton-updated inside the fused iteration;
    # everything else (labels, pad layout) is constant
    _evolving_attrs = ("pos_biases",)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        qb = metadata.query_boundaries
        if qb is None:
            raise ValueError(f"{self.name} objective requires query/group data")
        self.query_boundaries = qb
        sizes = np.diff(qb)
        self.max_docs = int(sizes.max())
        self.num_queries = len(sizes)
        # padded [Q, S] gather index + mask layout
        idx = np.zeros((self.num_queries, self.max_docs), np.int32)
        mask = np.zeros((self.num_queries, self.max_docs), np.float32)
        for q in range(self.num_queries):
            s, e = qb[q], qb[q + 1]
            idx[q, :e - s] = np.arange(s, e)
            mask[q, :e - s] = 1.0
        self.pad_idx = jnp.asarray(idx)
        self.pad_mask = jnp.asarray(mask)
        self.label_pad = jnp.asarray(self.label_np)[self.pad_idx] * self.pad_mask
        # position-bias debiasing state (ref: rank_objective.hpp:45-99):
        # per-position-id additive score bias, Newton-updated each
        # iteration from the accumulated lambdas
        positions = metadata.positions
        self.has_position_bias = positions is not None
        if self.has_position_bias:
            uniq, inv = np.unique(np.asarray(positions, np.int64),
                                  return_inverse=True)
            self.num_position_ids = len(uniq)
            self.position_ids = uniq
            self.pos_index = jnp.asarray(inv.astype(np.int32))  # [N]
            self.pos_biases = jnp.zeros(self.num_position_ids, jnp.float32)

    def _adjusted_score(self, score):
        """Score with the current position biases added before lambda
        computation (ref: rank_objective.hpp:69-74 score_adjusted)."""
        if not self.has_position_bias:
            return score
        return score + self.pos_biases[self.pos_index]

    def _update_position_bias(self, grad, hess):
        """Newton-Raphson update of per-position biases from the final
        lambdas (ref: rank_objective.hpp:303 UpdatePositionBiasFactors).
        Assigns self.pos_biases — inside a jit trace this produces a
        tracer that the fused program returns as updated objective state."""
        if not self.has_position_bias:
            return
        reg = self.config.lambdarank_position_bias_regularization
        lr = self.config.learning_rate
        p = self.num_position_ids
        first = jnp.zeros(p, jnp.float32).at[self.pos_index].add(-grad)
        second = jnp.zeros(p, jnp.float32).at[self.pos_index].add(-hess)
        counts = jnp.zeros(p, jnp.float32).at[self.pos_index].add(1.0)
        first = first - self.pos_biases * reg * counts
        second = second - reg * counts
        self.pos_biases = self.pos_biases + \
            lr * first / (jnp.abs(second) + 0.001)

    def _scatter_back(self, grad_pad, hess_pad):
        n = self.num_data
        flat_idx = self.pad_idx.reshape(-1)
        m = self.pad_mask.reshape(-1)
        grad = jnp.zeros(n, grad_pad.dtype).at[flat_idx].add(
            grad_pad.reshape(-1) * m)
        hess = jnp.zeros(n, hess_pad.dtype).at[flat_idx].add(
            hess_pad.reshape(-1) * m)
        return grad, hess


class LambdarankNDCG(_RankingObjective):
    name = "lambdarank"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        cfg = self.config
        gains = cfg.label_gain
        if gains is None:
            max_label = int(self.label_np.max()) if num_data else 0
            gains = [(1 << i) - 1 for i in range(max(max_label + 1, 2))]
        self.label_gain = jnp.asarray(np.asarray(gains, np.float64)
                                      .astype(np.float32))
        # per-query inverse max DCG at truncation level
        trunc = cfg.lambdarank_truncation_level
        inv_max_dcg = np.zeros(self.num_queries, np.float32)
        qb = self.query_boundaries
        lg = np.asarray(gains, np.float64)
        for q in range(self.num_queries):
            lab = self.label_np[qb[q]:qb[q + 1]].astype(int)
            srt = np.sort(lab)[::-1][:trunc]
            dcg = np.sum((lg[srt]) / np.log2(np.arange(len(srt)) + 2))
            inv_max_dcg[q] = 1.0 / dcg if dcg > 0 else 0.0
        self.inv_max_dcg = jnp.asarray(inv_max_dcg)
        self.trunc = trunc
        # eager, not lazy: creating this inside a jit trace would leak a
        # tracer into objective state
        self._lab_pad_int = (jnp.asarray(self.label_np.astype(np.int32))
                             [self.pad_idx] *
                             self.pad_mask.astype(jnp.int32))

    def get_gradients(self, score):
        """Pairwise lambdarank over padded queries
        (ref: rank_objective.hpp:139 GetGradientsForOneQuery). Faithful
        to the reference's pair rule and normalizations:
          - a pair participates iff the better-SCORED doc ranks inside
            truncation_level; both docs keep their TRUE rank discounts
          - with lambdarank_norm, delta_NDCG is regularized by the score
            distance (/(0.01 + |ds|)) when the query's scores are not
            all equal, and the final per-query scale is
            log2(1 + sum_pair 2|lambda|) / sum_pair 2|lambda|
        """
        sig = self.config.sigmoid
        s_pad = self._adjusted_score(score)[self.pad_idx]  # [Q, S]
        s_pad = jnp.where(self.pad_mask > 0, s_pad, -jnp.inf)
        lab = self.label_np_pad_int()
        gain = self.label_gain[lab] * self.pad_mask  # [Q, S]

        # rank of each doc by score (descending) within query; stable,
        # like the reference's std::stable_sort
        order = jnp.argsort(-s_pad, axis=1, stable=True)
        ranks = jnp.argsort(order, axis=1, stable=True)  # 0-based position
        disc = 1.0 / jnp.log2(ranks.astype(jnp.float32) + 2.0)

        sd = s_pad[:, :, None] - s_pad[:, None, :]        # s_i - s_j
        sd = jnp.where(jnp.isfinite(sd), sd, 0.0)
        lab_d = lab[:, :, None] - lab[:, None, :]
        better = (lab_d > 0).astype(jnp.float32)          # i truly better than j
        # truncation: the better-SCORED doc of the pair must rank inside
        # truncation_level (ref: the i < truncation_level loop bound)
        top = (jnp.minimum(ranks[:, :, None], ranks[:, None, :])
               < self.trunc).astype(jnp.float32)
        pair_m = (self.pad_mask[:, :, None] * self.pad_mask[:, None, :]
                  * better * top)
        # |delta NDCG| for swapping i,j — TRUE discounts for both ranks
        dgain = gain[:, :, None] - gain[:, None, :]
        ddisc = disc[:, :, None] - disc[:, None, :]
        delta = jnp.abs(dgain * ddisc) * self.inv_max_dcg[:, None, None]

        if self.config.lambdarank_norm:
            # regularize by score distance unless the query's scores are
            # all equal (ref: norm_ && best_score != worst_score)
            s_valid_max = jnp.max(jnp.where(self.pad_mask > 0, s_pad,
                                            -jnp.inf), axis=1)
            s_valid_min = jnp.min(jnp.where(self.pad_mask > 0, s_pad,
                                            jnp.inf), axis=1)
            spread = (s_valid_max != s_valid_min)[:, None, None]
            delta = jnp.where(spread, delta / (0.01 + jnp.abs(sd)), delta)

        rho = jax.nn.sigmoid(-sig * sd)                   # prob j beats i
        lam = -sig * rho * delta * pair_m                 # grad wrt s_i (i better)
        lam_h = sig * sig * rho * (1.0 - rho) * delta * pair_m

        grad_pad = jnp.sum(lam, axis=2) - jnp.sum(lam, axis=1)
        hess_pad = jnp.sum(lam_h, axis=2) + jnp.sum(lam_h, axis=1)

        if self.config.lambdarank_norm:
            # sum over pairs of 2|lambda| (ref: sum_lambdas -= 2*p_lambda)
            sum_lambdas = -2.0 * jnp.sum(lam, axis=(1, 2), keepdims=False)
            scale = jnp.where(
                sum_lambdas > 0,
                jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas,
                                                          1e-20),
                1.0)[:, None]
            grad_pad = grad_pad * scale
            hess_pad = hess_pad * scale
        grad, hess = self._scatter_back(grad_pad, hess_pad)
        # per-row weights scale the final lambdas
        # (ref: rank_objective.hpp:80-86)
        grad, hess = self._apply_weight(grad, hess)
        self._update_position_bias(grad, hess)
        return grad, hess

    def label_np_pad_int(self):
        return self._lab_pad_int


class RankXENDCG(_RankingObjective):
    name = "rank_xendcg"
    # the per-iteration gamma-sampling key evolves through the fused
    # program like pos_biases does
    _evolving_attrs = ("pos_biases", "xendcg_key")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.xendcg_key = jax.random.PRNGKey(self.config.objective_seed)
        lab = np.asarray(self.label_pad)
        self._pow2_label = jnp.asarray((2.0 ** np.floor(lab)) *
                                       np.asarray(self.pad_mask))

    def get_gradients(self, score):
        """Cross-entropy surrogate for NDCG, arxiv.org/abs/1911.09798
        (ref: rank_objective.hpp:396 RankXENDCG::GetGradientsForOneQuery).
        Faithful to the reference's estimator: the ground-truth
        distribution is sampled — Phi(l, g) = 2^l - g with g ~ U(0,1)
        fresh each iteration — and the gradient includes the second- and
        third-order correction terms of the XE-NDCG mean loss."""
        s_pad = self._adjusted_score(score)[self.pad_idx]
        neg_inf = jnp.finfo(s_pad.dtype).min
        s_masked = jnp.where(self.pad_mask > 0, s_pad, neg_inf)
        rho = jax.nn.softmax(s_masked, axis=1) * self.pad_mask  # [Q, S]

        self.xendcg_key, sub = jax.random.split(self.xendcg_key)
        g = jax.random.uniform(sub, self.pad_mask.shape)
        params = (self._pow2_label - g) * self.pad_mask  # Phi(l, g)
        eps = 1e-15  # kEpsilon (ref: meta.h:55)
        inv_den = 1.0 / jnp.maximum(
            jnp.sum(params, axis=1, keepdims=True), eps)

        # first-order terms
        term1 = (-params * inv_den + rho) * self.pad_mask
        one_minus_rho = jnp.maximum(1.0 - rho, eps)
        p2 = term1 / one_minus_rho
        sum_l1 = jnp.sum(p2 * self.pad_mask, axis=1, keepdims=True)
        # second-order terms
        term2 = rho * (sum_l1 - p2) * self.pad_mask
        p3 = term2 / one_minus_rho
        sum_l2 = jnp.sum(p3 * self.pad_mask, axis=1, keepdims=True)
        # third-order terms
        term3 = rho * (sum_l2 - p3) * self.pad_mask

        grad_pad = term1 + term2 + term3
        hess_pad = rho * (1.0 - rho) * self.pad_mask
        # the reference zeroes single-doc queries (cnt <= 1)
        multi = (jnp.sum(self.pad_mask, axis=1, keepdims=True) > 1.0)
        grad_pad = jnp.where(multi, grad_pad, 0.0)
        hess_pad = jnp.where(multi, hess_pad, 0.0)
        grad, hess = self._scatter_back(grad_pad, hess_pad)
        grad, hess = self._apply_weight(grad, hess)
        self._update_position_bias(grad, hess)
        return grad, hess


# ---------------------------------------------------------------------------
_OBJECTIVES = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": Huber,
    "fair": Fair,
    "poisson": Poisson,
    "quantile": Quantile,
    "mape": MAPE,
    "gamma": Gamma,
    "tweedie": Tweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """Factory (ref: ObjectiveFunction::CreateObjectiveFunction,
    src/objective/objective_function.cpp:72)."""
    if config.objective in ("none", None, ""):
        return None
    cls = _OBJECTIVES.get(config.objective)
    if cls is None:
        raise ValueError(f"Unknown objective: {config.objective}")
    return cls(config)
