// Build shim for the vendored nanoarrow (submodule not present in this
// offline environment). Provides exactly the surface LightGBM's
// src/arrow/array.hpp consumes: the Arrow C data interface structs (a public
// ABI spec), a minimal ArrowSchemaView with format-string parsing for the
// primitive types LightGBM supports, and RAII Unique* holders. Functional —
// the Arrow ingestion C API works for primitive arrays — though the CLI
// (the artifact this build exists for) never exercises it.
#ifndef NANOARROW_SHIM_HPP_
#define NANOARROW_SHIM_HPP_

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <stdexcept>
#include <string>
#include <utility>

#ifndef ARROW_C_DATA_INTERFACE
#define ARROW_C_DATA_INTERFACE

#define ARROW_FLAG_DICTIONARY_ORDERED 1
#define ARROW_FLAG_NULLABLE 2
#define ARROW_FLAG_MAP_KEYS_SORTED 4

struct ArrowSchema {
  const char* format;
  const char* name;
  const char* metadata;
  int64_t flags;
  int64_t n_children;
  struct ArrowSchema** children;
  struct ArrowSchema* dictionary;
  void (*release)(struct ArrowSchema*);
  void* private_data;
};

struct ArrowArray {
  int64_t length;
  int64_t null_count;
  int64_t offset;
  int64_t n_buffers;
  int64_t n_children;
  const void** buffers;
  struct ArrowArray** children;
  struct ArrowArray* dictionary;
  void (*release)(struct ArrowArray*);
  void* private_data;
};

#endif  // ARROW_C_DATA_INTERFACE

#ifndef ARROW_C_STREAM_INTERFACE
#define ARROW_C_STREAM_INTERFACE

struct ArrowArrayStream {
  int (*get_schema)(struct ArrowArrayStream*, struct ArrowSchema* out);
  int (*get_next)(struct ArrowArrayStream*, struct ArrowArray* out);
  const char* (*get_last_error)(struct ArrowArrayStream*);
  void (*release)(struct ArrowArrayStream*);
  void* private_data;
};

#endif  // ARROW_C_STREAM_INTERFACE

#define NANOARROW_OK 0

enum ArrowType {
  NANOARROW_TYPE_UNINITIALIZED = 0,
  NANOARROW_TYPE_BOOL,
  NANOARROW_TYPE_INT8,
  NANOARROW_TYPE_INT16,
  NANOARROW_TYPE_INT32,
  NANOARROW_TYPE_INT64,
  NANOARROW_TYPE_UINT8,
  NANOARROW_TYPE_UINT16,
  NANOARROW_TYPE_UINT32,
  NANOARROW_TYPE_UINT64,
  NANOARROW_TYPE_FLOAT,
  NANOARROW_TYPE_DOUBLE,
  NANOARROW_TYPE_STRUCT,
  NANOARROW_TYPE_UNKNOWN,
};

struct ArrowError {
  char message[1024];
};

struct ArrowSchemaView {
  enum ArrowType type;
};

inline const char* ArrowErrorMessage(struct ArrowError* error) {
  return error->message;
}

inline const char* ArrowTypeString(enum ArrowType type) {
  switch (type) {
    case NANOARROW_TYPE_BOOL: return "bool";
    case NANOARROW_TYPE_INT8: return "int8";
    case NANOARROW_TYPE_INT16: return "int16";
    case NANOARROW_TYPE_INT32: return "int32";
    case NANOARROW_TYPE_INT64: return "int64";
    case NANOARROW_TYPE_UINT8: return "uint8";
    case NANOARROW_TYPE_UINT16: return "uint16";
    case NANOARROW_TYPE_UINT32: return "uint32";
    case NANOARROW_TYPE_UINT64: return "uint64";
    case NANOARROW_TYPE_FLOAT: return "float";
    case NANOARROW_TYPE_DOUBLE: return "double";
    case NANOARROW_TYPE_STRUCT: return "struct";
    default: return "unknown";
  }
}

inline int ArrowSchemaViewInit(struct ArrowSchemaView* view,
                               const struct ArrowSchema* schema,
                               struct ArrowError* error) {
  const char* f = schema ? schema->format : nullptr;
  if (f == nullptr) {
    if (error) std::snprintf(error->message, sizeof(error->message),
                             "null schema/format");
    return 1;
  }
  if (std::strcmp(f, "b") == 0) view->type = NANOARROW_TYPE_BOOL;
  else if (std::strcmp(f, "c") == 0) view->type = NANOARROW_TYPE_INT8;
  else if (std::strcmp(f, "s") == 0) view->type = NANOARROW_TYPE_INT16;
  else if (std::strcmp(f, "i") == 0) view->type = NANOARROW_TYPE_INT32;
  else if (std::strcmp(f, "l") == 0) view->type = NANOARROW_TYPE_INT64;
  else if (std::strcmp(f, "C") == 0) view->type = NANOARROW_TYPE_UINT8;
  else if (std::strcmp(f, "S") == 0) view->type = NANOARROW_TYPE_UINT16;
  else if (std::strcmp(f, "I") == 0) view->type = NANOARROW_TYPE_UINT32;
  else if (std::strcmp(f, "L") == 0) view->type = NANOARROW_TYPE_UINT64;
  else if (std::strcmp(f, "f") == 0) view->type = NANOARROW_TYPE_FLOAT;
  else if (std::strcmp(f, "g") == 0) view->type = NANOARROW_TYPE_DOUBLE;
  else if (std::strcmp(f, "+s") == 0) view->type = NANOARROW_TYPE_STRUCT;
  else view->type = NANOARROW_TYPE_UNKNOWN;
  return NANOARROW_OK;
}

inline bool ArrowBitGet(const uint8_t* bits, int64_t i) {
  return (bits[i >> 3] >> (i & 0x07)) & 1;
}

namespace nanoarrow {

class Exception : public std::runtime_error {
 public:
  explicit Exception(const std::string& msg) : std::runtime_error(msg) {}
};

namespace internal {

inline void release(struct ArrowSchema* s) {
  if (s && s->release) s->release(s);
}
inline void release(struct ArrowArray* a) {
  if (a && a->release) a->release(a);
}
inline void release(struct ArrowArrayStream* st) {
  if (st && st->release) st->release(st);
}

// RAII holder over an Arrow C struct; move-only; calls release on destroy.
template <typename T>
class Unique {
 public:
  Unique() { std::memset(&data_, 0, sizeof(T)); }
  // Takes ownership of *ptr: moves the struct in and marks the source
  // released (standard Arrow C ABI ownership transfer).
  explicit Unique(T* ptr) {
    std::memcpy(&data_, ptr, sizeof(T));
    ptr->release = nullptr;
  }
  Unique(Unique&& o) noexcept {
    std::memcpy(&data_, &o.data_, sizeof(T));
    o.data_.release = nullptr;
  }
  Unique& operator=(Unique&& o) noexcept {
    if (this != &o) {
      release(&data_);
      std::memcpy(&data_, &o.data_, sizeof(T));
      o.data_.release = nullptr;
    }
    return *this;
  }
  Unique(const Unique&) = delete;
  Unique& operator=(const Unique&) = delete;
  ~Unique() { release(&data_); }

  T* get() { return &data_; }
  const T* get() const { return &data_; }
  T* operator->() { return &data_; }
  const T* operator->() const { return &data_; }

 private:
  T data_;
};

}  // namespace internal

using UniqueSchema = internal::Unique<struct ArrowSchema>;
using UniqueArray = internal::Unique<struct ArrowArray>;
using UniqueArrayStream = internal::Unique<struct ArrowArrayStream>;

}  // namespace nanoarrow

#endif  // NANOARROW_SHIM_HPP_
