"""Waved (batched-histogram) tree growth: quality parity vs the exact
per-split grower, feature coverage (categorical, monotone), and the
multi-leaf histogram kernel (Pallas, run in interpreter mode so CI
executes it on CPU) vs the XLA reference implementation.

Ref strategy: the reference gates its GPU learner on CPU/GPU output
agreement (tests/python_package_test/test_dual.py:19); waved-vs-exact is
the analogous gate for the batched TPU grower.
"""

import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.pallas_histogram import (hist_multi_xla,
                                               hist_pallas_multi)
from tests.conftest import make_binary, make_regression


def _auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(y))
    ranks[order] = np.arange(1, len(y) + 1)
    pos = y > 0
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def _train(X, y, wave_max, **extra):
    params = {"objective": "binary", "num_leaves": 63, "learning_rate": 0.1,
              "min_data_in_leaf": 5, "verbosity": -1,
              "tpu_wave_max": wave_max, **extra}
    return lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=20)


def test_waved_default_is_auto():
    """tpu_wave_max=-1 (auto): waved for single-output objectives, exact
    for multiclass (softmax calibration is split-order-sensitive; the
    waved path at wave size 1 is bit-identical to exact, batching >= 2
    drifts multiclass logloss — see config.py tpu_wave_max)."""
    from lightgbm_tpu.config import Config
    assert Config().tpu_wave_max == -1
    X, y = make_binary(400)
    bst = lgb.Booster({"objective": "binary", "num_leaves": 7,
                       "verbosity": -1}, lgb.Dataset(X, label=y))
    assert bst._gbdt._use_waved()
    from tests.conftest import make_multiclass
    Xm, ym = make_multiclass(400)
    bstm = lgb.Booster({"objective": "multiclass", "num_class": 4,
                        "num_leaves": 7, "verbosity": -1},
                       lgb.Dataset(Xm, label=ym))
    assert not bstm._gbdt._use_waved()
    # explicit setting overrides auto in both directions
    bstm2 = lgb.Booster({"objective": "multiclass", "num_class": 4,
                         "num_leaves": 7, "verbosity": -1,
                         "tpu_wave_max": 42}, lgb.Dataset(Xm, label=ym))
    assert bstm2._gbdt._use_waved()
    # OVA trains independent per-class binary trees (no softmax
    # coupling), so auto keeps the waved default there
    bsto = lgb.Booster({"objective": "multiclassova", "num_class": 4,
                        "num_leaves": 7, "verbosity": -1},
                       lgb.Dataset(Xm, label=ym))
    assert bsto._gbdt._use_waved()


@pytest.mark.slow
def test_waved_quality_parity_binary():
    X, y = make_binary(4000)
    auc_exact = _auc(y, _train(X, y, 0).predict(X))
    auc_waved = _auc(y, _train(X, y, 32).predict(X))
    # waved defers within-wave children to the wave boundary; with
    # boosting on top the quality gap must stay small
    assert auc_waved > auc_exact - 0.02
    assert auc_waved > 0.9


@pytest.mark.slow
def test_waved_quality_parity_regression():
    # held-out comparison: exact leaf-wise overfits deeper at equal
    # rounds, so train-set error would mis-rank the growers
    X, y = make_regression(6000)
    Xtr, ytr, Xte, yte = X[:4000], y[:4000], X[4000:], y[4000:]
    params = {"objective": "regression", "num_leaves": 63,
              "min_data_in_leaf": 5, "verbosity": -1}
    preds = {}
    for wave in (0, 32):
        bst = lgb.train({**params, "tpu_wave_max": wave},
                        lgb.Dataset(Xtr, label=ytr), num_boost_round=20)
        preds[wave] = bst.predict(Xte)
    mse_exact = np.mean((preds[0] - yte) ** 2)
    mse_waved = np.mean((preds[32] - yte) ** 2)
    assert mse_waved < mse_exact * 1.15
    assert mse_waved < np.var(yte) * 0.2


def test_waved_first_splits_match_exact():
    """Wave sizes start at 1, 1 — so a 3-leaf tree (two splits, each in
    its own wave) must be IDENTICAL to the exact grower's."""
    X, y = make_binary(2000)
    m_exact = _train(X, y, 0, num_leaves=3).model_to_string()
    m_waved = _train(X, y, 32, num_leaves=3).model_to_string()

    def first_split(text):
        for line in text.splitlines():
            if line.startswith("split_feature="):
                return line
        return None

    assert first_split(m_exact) == first_split(m_waved)


@pytest.mark.slow
def test_waved_categorical():
    r = np.random.RandomState(7)
    n = 3000
    cat = r.randint(0, 40, n)
    num = r.randn(n)
    logit = np.where(np.isin(cat, [3, 7, 11, 22, 35]), 1.5, -0.8) + num
    y = (logit + 0.3 * r.randn(n) > 0).astype(np.float32)
    X = np.column_stack([cat.astype(np.float64), num])
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 5, "tpu_wave_max": 32,
              "categorical_feature": [0]}
    bst = lgb.train(params, lgb.Dataset(X, label=y,
                                        categorical_feature=[0]),
                    num_boost_round=20)
    auc = _auc(y, bst.predict(X))
    assert auc > 0.85
    # round-trip: categorical bitsets survive serialization
    loaded = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(loaded.predict(X), bst.predict(X), rtol=1e-9)


@pytest.mark.slow
def test_waved_monotone():
    r = np.random.RandomState(3)
    n = 3000
    X = r.randn(n, 4)
    y = (2.0 * X[:, 0] + np.sin(X[:, 1]) * 2 + 0.5 * X[:, 2]
         + 0.2 * r.randn(n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 63, "verbosity": -1,
              "min_data_in_leaf": 5, "tpu_wave_max": 32,
              "monotone_constraints": [1, 0, 0, 0]}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=30)
    # sweep feature 0 over its range with the others pinned: prediction
    # must be non-decreasing at every probed point
    base = np.tile(np.median(X, axis=0), (200, 1))
    base[:, 0] = np.linspace(X[:, 0].min(), X[:, 0].max(), 200)
    p = bst.predict(base)
    assert np.all(np.diff(p) >= -1e-10)


def test_waved_with_bagging_and_feature_fraction():
    X, y = make_binary(3000)
    bst = _train(X, y, 32, bagging_fraction=0.7, bagging_freq=1,
                 feature_fraction=0.8)
    assert _auc(y, bst.predict(X)) > 0.85


def test_hist_pallas_multi_matches_xla():
    """Execute the Pallas multi-leaf kernel in interpreter mode on CPU and
    require exact agreement with the XLA loop implementation."""
    r = np.random.RandomState(0)
    n, f, b, slots = 700, 5, 16, 42
    bins = jnp.asarray(r.randint(0, b, (f, n)), jnp.uint8)
    mask = (r.rand(n) < 0.8).astype(np.float32)
    ghT = jnp.asarray(
        np.stack([r.randn(n) * mask, np.abs(r.randn(n)) * mask, mask],
                 axis=1), jnp.float32)
    row_leaf = jnp.asarray(r.randint(0, 6, n), jnp.int32)
    leaf_ids = jnp.asarray([0, 2, 5, 1] + [-2] * (slots - 4), jnp.int32)

    ref = hist_multi_xla(bins, ghT, row_leaf, leaf_ids,
                         max_bins=b, num_slots=slots)
    pal = hist_pallas_multi(bins, ghT, row_leaf, leaf_ids,
                            max_bins=b, num_slots=slots, interpret=True)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
    # padded slots stay empty
    assert np.all(np.asarray(pal[4:]) == 0.0)


def test_hist_pallas_multi_int8_matches_xla():
    """The int8 quantized multi-leaf kernel (interpret mode) must agree
    EXACTLY with the f32 XLA path on integer-valued inputs: both compute
    sums of small integers, which f32 represents exactly."""
    from lightgbm_tpu.ops.pallas_histogram import hist_pallas_multi_int8
    r = np.random.RandomState(2)
    n, f, b, slots = 600, 5, 16, 42
    bins = jnp.asarray(r.randint(0, b, (f, n)), jnp.uint8)
    mask = (r.rand(n) < 0.8).astype(np.int8)
    g_int = (r.randint(-3, 4, n) * mask).astype(np.int8)
    h_int = (r.randint(0, 5, n) * mask).astype(np.int8)
    ghT_i8 = jnp.asarray(np.stack([g_int, h_int, mask], axis=1), jnp.int8)
    row_leaf = jnp.asarray(r.randint(0, 6, n), jnp.int32)
    leaf_ids = jnp.asarray([0, 3, 5, 1] + [-2] * (slots - 4), jnp.int32)

    hist_i = hist_pallas_multi_int8(bins, ghT_i8, row_leaf, leaf_ids,
                                    max_bins=b, num_slots=slots,
                                    interpret=True)
    ghT_f = jnp.asarray(np.stack([g_int, h_int, mask], axis=1), jnp.float32)
    ref = hist_multi_xla(bins, ghT_f, row_leaf, leaf_ids,
                         max_bins=b, num_slots=slots)
    assert hist_i.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(hist_i, np.float32),
                                  np.asarray(ref))


def test_waved_quantized_grad_trains():
    """use_quantized_grad + waved growth end-to-end (CPU falls back to the
    XLA f32 hist on dequantized values — numerically identical to the
    int8 device path, which sums the same integers)."""
    X, y = make_binary(3000)
    bst = _train(X, y, 32, use_quantized_grad=True,
                 quant_train_renew_leaf=True)
    assert _auc(y, bst.predict(X)) > 0.85


def test_hist_pallas_single_matches_xla():
    from lightgbm_tpu.ops.histogram import build_histogram
    from lightgbm_tpu.ops.pallas_histogram import hist_pallas
    r = np.random.RandomState(1)
    n, f, b = 900, 11, 32
    bins = jnp.asarray(r.randint(0, b, (f, n)), jnp.uint8)
    grad = jnp.asarray(r.randn(n), jnp.float32)
    hess = jnp.asarray(np.abs(r.randn(n)), jnp.float32)
    mask = jnp.asarray((r.rand(n) < 0.9), jnp.float32)
    ref = build_histogram(bins, grad, hess, mask, max_bins=b, impl="xla")
    gh3 = jnp.stack([grad * mask, hess * mask, mask]).astype(jnp.float32)
    pal = hist_pallas(bins, gh3, max_bins=b, interpret=True)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_apply_wave_splits_matches_sequential():
    """The batched wave partition must be BIT-equivalent to the
    sequential apply_split chain it replaced (dense + EFB-bundled,
    categorical, NaN default-left routing, invalid steps)."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops import partition as part_ops

    rng = np.random.RandomState(0)
    N, F, B, L, W = 500, 6, 16, 15, 5
    for trial in range(8):
        bins = rng.randint(0, B, (F, N)).astype(np.uint8)
        row_leaf = rng.randint(0, 8, N).astype(np.int32)
        # distinct split leaves; last one invalid
        leaves = rng.permutation(8)[:W].astype(np.int32)
        rights = (8 + np.arange(W)).astype(np.int32)
        feats = rng.randint(0, F, W).astype(np.int32)
        thrs = rng.randint(0, B - 1, W).astype(np.int32)
        dlefts = rng.rand(W) > 0.5
        cmasks = rng.rand(W, B) > 0.5
        valid = np.ones(W, bool)
        valid[-1] = False
        num_bins = np.full(F, B, np.int32)
        missing = rng.randint(0, 3, F).astype(np.int32)
        is_cat = rng.rand(F) > 0.7

        seq = jnp.asarray(row_leaf)
        for w in range(W):
            seq = part_ops.apply_split(
                seq, jnp.asarray(bins), jnp.int32(leaves[w]),
                jnp.int32(rights[w]), jnp.int32(feats[w]),
                jnp.int32(thrs[w]), jnp.bool_(dlefts[w]),
                jnp.asarray(cmasks[w]), jnp.asarray(num_bins),
                jnp.asarray(missing), jnp.asarray(is_cat),
                jnp.bool_(valid[w]))
        batched = part_ops.apply_wave_splits(
            jnp.asarray(row_leaf), jnp.asarray(bins),
            jnp.asarray(leaves), jnp.asarray(rights), jnp.asarray(feats),
            jnp.asarray(thrs), jnp.asarray(dlefts), jnp.asarray(cmasks),
            jnp.asarray(valid), jnp.asarray(num_bins),
            jnp.asarray(missing), jnp.asarray(is_cat), L)
        np.testing.assert_array_equal(np.asarray(seq),
                                      np.asarray(batched))


@pytest.mark.slow
def test_batched_partition_through_grower_with_bundle():
    """Force the batched wave partition (the TPU default) through the
    FULL waved grower on CPU, on EFB-bundled one-hot data, and require
    agreement with the per-split partition (the CPU default) — covers
    the call-site wiring and the bundle-decode path of
    partition._per_row_feature_bins end-to-end."""
    import functools
    import jax.numpy as jnp
    from lightgbm_tpu import Dataset
    from lightgbm_tpu.learner import grow_tree_waved

    rng = np.random.RandomState(9)
    n = 1500
    # one-hot-ish mutually exclusive features so EFB actually bundles
    hot = rng.randint(0, 6, n)
    X = np.zeros((n, 6))
    X[np.arange(n), hot] = rng.rand(n) * 3 + 0.5
    y = np.isin(hot, [1, 4]).astype(np.float32)
    ds = Dataset(X, label=y, params={"max_bin": 15,
                                     "verbosity": -1}).construct()
    binned = ds._binned
    assert binned.bundle_info is not None, "EFB must engage for this test"
    from lightgbm_tpu.basic import Booster
    bst = Booster({"objective": "binary", "num_leaves": 15,
                   "min_data_in_leaf": 5, "verbosity": -1}, ds)
    g = bst._gbdt
    grad = jnp.asarray(y - 0.5, jnp.float32)
    hess = jnp.full(n, 0.25, jnp.float32)
    mask = jnp.ones(n, jnp.float32)
    fmask = jnp.ones(binned.num_features, bool)
    kw = dict(g._grow_kwargs(), hist_dtype=jnp.float32, hist_impl="xla",
              hist_precision="highest",
              has_categorical=g._has_categorical)
    outs = {}
    for batched in (False, True):
        rec, row_leaf = grow_tree_waved(
            g.bins_fm, grad, hess, mask, fmask, g.feature_meta, g.hp,
            g.max_depth, None, None, batched_partition=batched, **kw)
        outs[batched] = (np.asarray(row_leaf), np.asarray(rec.leaf_count),
                        np.asarray(rec.split_feature))
    np.testing.assert_array_equal(outs[False][0], outs[True][0])
    np.testing.assert_array_equal(outs[False][1], outs[True][1])
    np.testing.assert_array_equal(outs[False][2], outs[True][2])
