"""Telemetry subsystem tests: span tracer (nesting, self-time, Chrome
export), metrics registry (recompile counter, disabled fast path),
telemetry callbacks, the timer facade, and the log.py custom-logger
round trip."""

import json
import os
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import log
from lightgbm_tpu.obs.metrics import MetricsRegistry, global_metrics
from lightgbm_tpu.obs.trace import Tracer, _NULL_SPAN
from lightgbm_tpu.timer import Timer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from check_trace import check_trace  # noqa: E402

from conftest import make_binary  # noqa: E402


# ---------------------------------------------------------------------------
# span tracer
class TestTracer:
    def test_nesting_and_self_time(self):
        tr = Tracer()
        tr.enable()
        with tr.span("outer"):
            time.sleep(0.02)
            with tr.span("inner"):
                time.sleep(0.02)
        s = tr.summary()
        assert set(s) == {"outer", "inner"}
        assert s["outer"]["count"] == 1 and s["inner"]["count"] == 1
        # parent total covers the child; parent self excludes it
        assert s["outer"]["seconds"] >= s["inner"]["seconds"]
        assert abs(s["outer"]["self_seconds"]
                   - (s["outer"]["seconds"] - s["inner"]["seconds"])) < 1e-9
        assert s["inner"]["self_seconds"] == pytest.approx(
            s["inner"]["seconds"])
        assert s["outer"]["self_seconds"] >= 0.015
        assert s["inner"]["seconds"] >= 0.015

    def test_sibling_spans_accumulate(self):
        tr = Tracer()
        tr.enable()
        for _ in range(3):
            with tr.span("phase"):
                pass
        assert tr.summary()["phase"]["count"] == 3

    def test_depth_recorded(self):
        tr = Tracer()
        tr.enable()
        with tr.span("a"):
            with tr.span("b"):
                pass
        events = {e["name"]: e for e in tr.chrome_events()}
        assert events["a"]["args"]["depth"] == 0
        assert events["b"]["args"]["depth"] == 1

    def test_chrome_export_valid(self, tmp_path):
        tr = Tracer()
        tr.enable()
        with tr.span("outer"):
            with tr.span("inner"):
                pass
        path = str(tmp_path / "trace.json")
        tr.export_chrome(path)
        with open(path) as fh:
            doc = json.load(fh)  # loadable JSON
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(spans) == 2
        for ev in spans:
            assert isinstance(ev["name"], str)
            assert ev["ts"] >= 0 and ev["dur"] >= 0
        # checker accepts it
        ok, msg = check_trace(path)
        assert ok, msg

    def test_chrome_metadata_events(self, tmp_path):
        """Perfetto readability: the export carries ph:"M" process/thread
        naming — process_name, host/pid process_labels, and a
        thread_name for every recorded thread."""
        import threading
        tr = Tracer()
        tr.enable()
        with tr.span("main_phase"):
            pass

        # record a span from a named worker thread
        def worker():
            with tr.span("worker_phase"):
                pass
        t = threading.Thread(target=worker, name="lgbm-worker")
        t.start()
        t.join()
        events = tr.chrome_events()
        meta = [e for e in events if e["ph"] == "M"]
        by_name = {}
        for e in meta:
            by_name.setdefault(e["name"], []).append(e)
        assert by_name["process_name"][0]["args"]["name"].startswith(
            "lightgbm_tpu")
        labels = by_name["process_labels"][0]["args"]["labels"]
        assert "hostname=" in labels and "pid=" in labels
        thread_names = {e["args"]["name"] for e in by_name["thread_name"]}
        assert "lgbm-worker" in thread_names
        # metadata precedes spans and the validator enforces it
        assert events[0]["ph"] == "M"
        path = str(tmp_path / "trace.json")
        tr.export_chrome(path)
        ok, msg = check_trace(path)
        assert ok, msg
        assert "metadata" in msg

    def test_check_trace_requires_metadata_from_our_producer(self,
                                                            tmp_path):
        doc = {"traceEvents": [
            {"name": "a", "ph": "X", "ts": 1, "dur": 2, "pid": 7,
             "tid": 9}],
            "otherData": {"producer": "lightgbm_tpu.obs.trace"}}
        p = tmp_path / "t.json"
        p.write_text(json.dumps(doc))
        ok, msg = check_trace(str(p))
        assert not ok and "process_name" in msg
        # foreign traces without metadata stay acceptable
        doc.pop("otherData")
        p.write_text(json.dumps(doc))
        ok, _ = check_trace(str(p))
        assert ok
        # malformed metadata payload is rejected everywhere
        doc["traceEvents"].insert(0, {"name": "thread_name", "ph": "M",
                                      "args": {}})
        p.write_text(json.dumps(doc))
        ok, msg = check_trace(str(p))
        assert not ok and "args.name" in msg

    def test_check_trace_rejects_garbage(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text("not json {")
        ok, _ = check_trace(str(p))
        assert not ok
        p.write_text(json.dumps({"traceEvents": [
            {"name": "a", "ph": "X", "ts": 100, "dur": 5},
            {"name": "b", "ph": "X", "ts": 50, "dur": 5},
        ]}))
        ok, msg = check_trace(str(p))
        assert not ok and "monotonicity" in msg

    def test_disabled_is_shared_noop(self, monkeypatch):
        monkeypatch.delenv("LGBM_TPU_TRACE", raising=False)
        monkeypatch.delenv("LGBM_TPU_TIMETAG", raising=False)
        tr = Tracer()
        assert not tr.enabled
        cm = tr.span("anything")
        assert cm is _NULL_SPAN  # no allocation on the disabled path
        with cm:
            pass
        assert tr.summary() == {}
        assert tr._events == []

    def test_block_waits_on_device_work(self):
        import jax.numpy as jnp
        tr = Tracer()
        tr.enable()
        with tr.span("device", block=lambda: out):
            out = jnp.arange(1024.0).sum()
        assert tr.summary()["device"]["count"] == 1


# ---------------------------------------------------------------------------
# metrics registry
class TestMetrics:
    def test_disabled_records_nothing(self):
        m = MetricsRegistry()
        m.disable()
        m.begin_iteration(0)
        m.observe("x", 1.0)
        m.inc("y")
        m.end_iteration()
        assert m.history == [] and m._current is None
        assert m.snapshot() is None

    def test_iteration_lifecycle(self):
        m = MetricsRegistry()
        m.enabled = True  # direct flag: avoid touching the global tracer
        m.begin_iteration(3)
        m.observe("leaves_grown", 31)
        m.inc("jit_recompiles")
        m.end_iteration()
        snap = m.snapshot()
        assert snap["iteration"] == 3
        assert snap["leaves_grown"] == 31
        assert snap["jit_recompiles"] == 1
        assert snap["iteration_seconds"] >= 0.0

    def test_recompile_counter_once_per_shape(self):
        import jax
        m = MetricsRegistry()
        fn = jax.jit(m.wrap_traced("f", lambda x: x * 2))
        a = np.ones(8, np.float32)
        fn(a)
        fn(a)  # cache hit: no new trace
        assert m.recompiles("f") == 1
        fn(np.ones(16, np.float32))  # shape change: exactly one retrace
        assert m.recompiles("f") == 2
        fn(np.ones(16, np.float32))
        assert m.recompiles("f") == 2

    def test_op_level_note_trace_does_not_inflate_jit_recompiles(self):
        m = MetricsRegistry()
        m.enabled = True
        m.begin_iteration(0)
        # inner op call sites fire many times per program compile; only
        # top-level program wrappers feed the jit_recompiles metric
        m.note_trace("ops/split_search")
        m.note_trace("ops/split_search")
        m.note_trace("ops/histogram")
        m.note_trace("prog", top_level=True)
        m.end_iteration()
        assert m.snapshot()["jit_recompiles"] == 1
        assert m.recompiles("ops/split_search") == 2

    def test_collective_accounting(self):
        m = MetricsRegistry()
        m.note_collective("psum", 4096)
        m.note_collective("all_gather", 128)
        assert m.collective_calls == 2
        assert m.collective_bytes == 4096 + 128
        assert m.trace_counts["collective/psum"] == 1

    def test_concurrent_recording_is_lossless(self):
        """Regression for the unsynchronized read-modify-write in
        LatencyReservoir.note / inc_counter / note_predict: serve/
        records from the asyncio loop AND its executor thread, so
        concurrent notes must not lose updates."""
        import threading
        m = MetricsRegistry()
        threads, per_thread = 8, 2000

        def hammer(tid):
            for i in range(per_thread):
                m.note_latency("serve/request", 0.001 * (tid + 1))
                m.inc_counter("serve/requests")
                m.note_predict(rows=3, seconds=0.002)

        ts = [threading.Thread(target=hammer, args=(t,))
              for t in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        total = threads * per_thread
        res = m.latency("serve/request")
        assert res.count == total
        assert m.counter("serve/requests") == total
        assert m.predict_rows_total == 3 * total
        assert m.latency("predict").count == total
        assert m.predict_seconds_total == pytest.approx(0.002 * total)
        # reservoir stayed bounded and readable
        assert len(res._samples) == min(total, res.capacity)
        assert res.summary()["count"] == total

    def test_per_device_memory_stats_shape(self):
        """Per-device stats: None on CPU (no memory_stats), a list of
        per-ordinal dicts on accelerator backends — end_iteration folds
        sum/max so multi-chip runs don't under-report peak."""
        stats = MetricsRegistry.per_device_memory_stats()
        if stats is None:
            return  # CPU backend under conftest
        assert all("device" in s for s in stats)
        assert [s["device"] for s in stats] == sorted(
            s["device"] for s in stats)

    def test_end_iteration_folds_max_and_sum(self, monkeypatch):
        m = MetricsRegistry()
        m.enabled = True
        fake = [{"device": 0, "bytes_in_use": 10, "peak_bytes_in_use": 40},
                {"device": 1, "bytes_in_use": 30, "peak_bytes_in_use": 90}]
        monkeypatch.setattr(MetricsRegistry, "per_device_memory_stats",
                            staticmethod(lambda: fake))
        m.begin_iteration(0)
        m.end_iteration()
        snap = m.snapshot()
        assert snap["device_bytes_in_use"] == 40       # fleet sum
        assert snap["device_peak_bytes_in_use"] == 90  # worst device
        assert snap["device_peak_bytes_per_device"] == [40, 90]

    def test_phase_sink_uses_self_time(self):
        m = MetricsRegistry()
        m.enabled = True
        m.begin_iteration(0)
        m.phase_sink("train/grow", dur_s=1.0, self_s=0.75)
        m.phase_sink("train/grow", dur_s=0.5, self_s=0.25)
        m.end_iteration()
        assert m.snapshot()["phases"]["train/grow"] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# training integration
def _train_with_telemetry(n_rounds=4, **extra_params):
    X, y = make_binary(400, 6)
    rec = {}
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              **extra_params}
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=n_rounds,
                    callbacks=[lgb.record_telemetry(rec)])
    return bst, rec


class TestTelemetryTraining:
    def setup_method(self):
        from lightgbm_tpu.obs.trace import global_tracer
        self._tracer_was_enabled = global_tracer.enabled
        global_metrics.disable()
        global_metrics.reset()

    def teardown_method(self):
        # metrics.enable() also switches the global tracer on; restore
        # both so later (unrelated) tests run with telemetry truly off
        from lightgbm_tpu.obs.trace import global_tracer
        global_metrics.disable()
        global_metrics.reset()
        if not self._tracer_was_enabled:
            global_tracer.disable()

    def test_record_telemetry_populates_across_iterations(self):
        bst, rec = _train_with_telemetry(4)
        assert bst.current_iteration() == 4
        # every list is iteration-aligned (None-padded where absent)
        assert all(len(v) == 4 for v in rec.values()), \
            {k: len(v) for k, v in rec.items()}
        assert all(1 <= v <= 7 for v in rec["leaves_grown"])
        assert all(v > 0 for v in rec["grad_norm"])
        assert rec["iteration"] == [0, 1, 2, 3]
        # fused-path compile shows up as a recompile on iteration 0;
        # non-compiling iterations hold the None placeholder
        assert rec["jit_recompiles"][0] >= 1
        assert rec["jit_recompiles"][-1] is None
        # phase times flowed from tracer spans into the iteration dicts
        assert any(k.startswith("phase/") for k in rec)

    def test_telemetry_enable_is_scoped_to_the_run(self):
        from lightgbm_tpu.obs.trace import global_tracer
        assert not global_metrics.enabled
        tracer_was = global_tracer.enabled
        _train_with_telemetry(2)
        # the callback's opt-in must not outlive its train() call
        assert not global_metrics.enabled
        assert global_tracer.enabled == tracer_was

    def test_log_telemetry_prints(self, capsys):
        X, y = make_binary(300, 6)
        lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=2,
                  callbacks=[lgb.log_telemetry(period=1)])
        out = capsys.readouterr().out
        assert "iter=" in out and "leaves_grown=" in out

    def test_disabled_training_records_nothing(self):
        X, y = make_binary(300, 6)
        global_metrics.disable()
        h0 = len(global_metrics.history)
        lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3)
        assert len(global_metrics.history) == h0
        assert global_metrics._current is None

    def test_trace_output_param_writes_trace(self, tmp_path):
        from lightgbm_tpu.obs.trace import global_tracer
        path = str(tmp_path / "train_trace.json")
        X, y = make_binary(300, 6)
        was_enabled = global_tracer.enabled
        prev_path = global_tracer.trace_path
        try:
            lgb.train({"objective": "binary", "num_leaves": 7,
                       "verbosity": -1, "trace_output": path},
                      lgb.Dataset(X, label=y), num_boost_round=2)
            global_tracer.export_chrome(path)
        finally:
            global_tracer.trace_path = prev_path
            if not was_enabled:
                global_tracer.disable()
        ok, msg = check_trace(path)
        assert ok, msg
        names = {e["name"] for e in json.load(open(path))["traceEvents"]}
        assert "train/iteration" in names

    def test_histogram_recompile_counted_on_new_shape(self):
        from lightgbm_tpu.ops import histogram as hist_ops
        import jax.numpy as jnp
        before = global_metrics.recompiles("ops/histogram")
        bins = jnp.zeros((3, 64), jnp.int32)
        g = jnp.ones(64); h = jnp.ones(64); mk = jnp.ones(64)
        hist_ops.build_histogram(bins, g, h, mk, max_bins=4, impl="xla")
        after_first = global_metrics.recompiles("ops/histogram")
        assert after_first >= before + 1
        hist_ops.build_histogram(bins, g, h, mk, max_bins=4, impl="xla")
        assert global_metrics.recompiles("ops/histogram") == after_first


# ---------------------------------------------------------------------------
# timer facade
class TestTimerFacade:
    def test_timed_nests_with_self_time(self):
        tr = Tracer()
        timer = Timer(tracer=tr)
        tr.enabled = True  # enable without installing exit-print
        with timer.timed("outer"):
            with timer.timed("inner"):
                time.sleep(0.01)
        s = timer.summary()
        assert s["outer"]["seconds"] >= s["inner"]["seconds"]
        assert s["outer"]["self_seconds"] == pytest.approx(
            s["outer"]["seconds"] - s["inner"]["seconds"], abs=1e-9)
        assert "phase timers" in timer.report()

    def test_global_timer_shares_global_tracer(self):
        from lightgbm_tpu.timer import global_timer
        from lightgbm_tpu.obs.trace import global_tracer
        assert global_timer._tracer is global_tracer


# ---------------------------------------------------------------------------
# log.py custom logger round trip
class _CollectingLogger:
    def __init__(self):
        self.lines = []

    def my_info(self, msg):
        self.lines.append(("info", msg))

    def my_warning(self, msg):
        self.lines.append(("warning", msg))

    def my_debug(self, msg):
        self.lines.append(("debug", msg))


class TestRegisterLogger:
    def _restore(self):
        log._logger = None
        log._info_method = "info"
        log._warning_method = "warning"
        log._debug_method = None
        log.set_verbosity(1)

    def test_round_trip_all_levels(self, capsys):
        logger = _CollectingLogger()
        try:
            log.register_logger(logger, info_method_name="my_info",
                                warning_method_name="my_warning",
                                debug_method_name="my_debug")
            log.set_verbosity(2)  # debug level
            log.info("i")
            log.warning("w")
            log.debug("d")
            assert ("info", "i") in logger.lines
            assert ("warning", "w") in logger.lines
            # Debug routed through the registered method, not print
            assert ("debug", "d") in logger.lines
            assert capsys.readouterr().out == ""
        finally:
            self._restore()

    def test_debug_falls_back_to_info_method(self):
        logger = _CollectingLogger()
        try:
            log.register_logger(logger, info_method_name="my_info",
                                warning_method_name="my_warning")
            log.set_verbosity(2)
            log.debug("d")
            assert ("info", "d") in logger.lines  # via info override
        finally:
            self._restore()

    def test_invalid_logger_rejected(self):
        with pytest.raises(TypeError):
            log.register_logger(object())
        logger = _CollectingLogger()
        with pytest.raises(TypeError):
            log.register_logger(logger, info_method_name="my_info",
                                warning_method_name="my_warning",
                                debug_method_name="nope")


# ---------------------------------------------------------------------------
# disabled-path cost of the introspection layer (exporter, xla, request
# tracing): telemetry off must mean guard checks only — nothing routed,
# nothing recorded, nothing allocated
class TestDisabledIntrospectionLayer:
    def test_xla_introspector_disabled_is_passthrough(self):
        from lightgbm_tpu.obs.xla import XlaIntrospector, instrumented_jit
        reg = XlaIntrospector()
        assert not reg.enabled  # env-gated, off under the test env
        compiles = []
        g = instrumented_jit("off/prog", lambda x: x * 3, registry=reg)
        # break AOT entry points: if the disabled path ever touched
        # them the call would explode
        g.__wrapped_jit__.lower = lambda *a, **k: compiles.append(1)
        out = g(np.ones(4, np.float32))
        np.testing.assert_array_equal(np.asarray(out), [3.0] * 4)
        assert reg.n_programs == 0 and compiles == []
        assert reg.summary()["compile_s_total"] == 0.0

    def test_flusher_unarmed_is_attribute_check(self, monkeypatch,
                                                tmp_path):
        from lightgbm_tpu.obs.export import MetricsTextfileFlusher
        monkeypatch.delenv("LGBM_TPU_METRICS_FILE", raising=False)
        fl = MetricsTextfileFlusher()
        assert not fl.armed
        assert fl.maybe_flush() is False
        assert list(tmp_path.iterdir()) == []

    def test_span_args_disabled_returns_shared_noop(self):
        tr = Tracer()
        assert tr.span("x", args={"trace_id": "t"}) is _NULL_SPAN
        tr.add_complete_span("late", 0, 100, args={"trace_id": "t"})
        assert tr._events == [] and tr.summary() == {}

    def test_enabled_span_args_reach_chrome_events(self):
        tr = Tracer()
        tr.enable()
        with tr.span("phase", args={"k": "v"}):
            pass
        tr.add_complete_span("late", 10, 100, args={"trace_id": "t-1"})
        by_name = {e["name"]: e for e in tr.chrome_events()
                   if e["ph"] == "X"}
        assert by_name["phase"]["args"]["k"] == "v"
        assert by_name["phase"]["args"]["depth"] == 0  # std args kept
        assert by_name["late"]["args"]["trace_id"] == "t-1"
        assert by_name["late"]["dur"] == pytest.approx(0.1)  # us

    def test_metrics_enable_arms_xla_and_restore_disarms(self):
        from lightgbm_tpu.obs.trace import global_tracer
        from lightgbm_tpu.obs.xla import global_xla
        assert not global_metrics.enabled and not global_xla.enabled
        tracer_was = global_tracer.enabled
        _train_with_telemetry(2)
        # the scoped enable armed the introspector for the run only
        assert not global_xla.enabled
        assert not global_metrics.enabled
        assert global_tracer.enabled == tracer_was


# ---------------------------------------------------------------------------
# structured JSON log mode (LGBM_TPU_LOG_JSON)
class TestJsonLogMode:
    def test_json_records_carry_host_labels(self, capsys):
        import socket
        log.set_json_mode(True)
        log.set_verbosity(1)  # earlier trainings lower the threshold
        try:
            log.info("hello world")
            log.warning("watch out")
        finally:
            log.set_json_mode(False)
        lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
        recs = [json.loads(ln) for ln in lines]
        assert [r["msg"] for r in recs] == ["hello world", "watch out"]
        assert [r["level"] for r in recs] == ["Info", "Warning"]
        for r in recs:
            assert r["hostname"] == socket.gethostname()
            assert r["pid"] == str(os.getpid())
            assert r["ts"] > 0

    def test_env_var_arms_json_mode(self, monkeypatch, capsys):
        import importlib
        monkeypatch.setenv("LGBM_TPU_LOG_JSON", "1")
        importlib.reload(log)
        try:
            log.set_verbosity(1)
            log.info("from env")
            rec = json.loads(capsys.readouterr().out.strip())
            assert rec["msg"] == "from env"
        finally:
            monkeypatch.delenv("LGBM_TPU_LOG_JSON")
            importlib.reload(log)
        assert not log._json_mode

    def test_registered_logger_bypasses_json_wrapping(self, capsys):
        logger = _CollectingLogger()
        log.set_json_mode(True)
        log.set_verbosity(1)
        try:
            log.register_logger(logger, info_method_name="my_info",
                                warning_method_name="my_warning")
            log.info("plain")
            assert ("info", "plain") in logger.lines  # raw msg, not JSON
            assert capsys.readouterr().out == ""
        finally:
            log.set_json_mode(False)
            log._logger = None
            log._info_method = "info"
            log._warning_method = "warning"
            log._debug_method = None
