from . import histogram, partition, split  # noqa: F401
