"""Position-bias lambdarank (ref: rank_objective.hpp:45-99 score
adjustment by pos_biases_ + :303 UpdatePositionBiasFactors Newton step).

Simulates click data where observation probability decays with the
PRESENTED position (which correlates with a non-relevance feature);
debiasing must recover ranking quality that the biased clicks obscure.
"""

import numpy as np

import lightgbm_tpu as lgb


def _make_click_data(seed=0, nq=120, dq=10):
    r = np.random.RandomState(seed)
    n = nq * dq
    X = r.randn(n, 6)
    true_rel = X[:, 0] + 0.7 * X[:, 1]
    pos = np.zeros(n, np.int32)
    clicks = np.zeros(n, np.float32)
    for q in range(nq):
        s = q * dq
        order = np.argsort(-X[s:s + dq, 2])  # presentation by feature 2
        for p, j in enumerate(order):
            pos[s + j] = p
            p_obs = 1.0 / (1.0 + 0.7 * p)
            rel = true_rel[s + j] > np.median(true_rel[s:s + dq])
            clicks[s + j] = 1.0 if (rel and r.rand() < p_obs) else 0.0
    group = np.full(nq, dq)
    return X, true_rel, clicks, pos, group


def _ndcg5(scores, true_rel, nq, dq):
    total = 0.0
    for q in range(nq):
        s = q * dq
        o = np.argsort(-scores[s:s + dq])[:5]
        gains = (true_rel[s:s + dq] >
                 np.median(true_rel[s:s + dq])).astype(float)
        dcg = np.sum(gains[o] / np.log2(np.arange(5) + 2))
        ideal = np.sum(np.sort(gains)[::-1][:5] / np.log2(np.arange(5) + 2))
        total += dcg / max(ideal, 1e-9)
    return total / nq


def test_position_bias_correction_improves_ranking():
    X, true_rel, clicks, pos, group = _make_click_data()
    params = {"objective": "lambdarank", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "learning_rate": 0.1}
    plain = lgb.train(params, lgb.Dataset(X, label=clicks, group=group),
                      num_boost_round=30)
    debiased = lgb.train(params,
                         lgb.Dataset(X, label=clicks, group=group,
                                     position=pos),
                         num_boost_round=30)
    nq, dq = len(group), group[0]
    n_plain = _ndcg5(plain.predict(X), true_rel, nq, dq)
    n_corr = _ndcg5(debiased.predict(X), true_rel, nq, dq)
    assert n_corr > n_plain + 0.01

    # learned biases decay with position (position 0 most clicked)
    biases = np.asarray(debiased._gbdt.objective.pos_biases)
    assert biases[0] > biases[-1]
    assert biases[0] > 0


def test_position_bias_xendcg_runs():
    X, true_rel, clicks, pos, group = _make_click_data(seed=3)
    params = {"objective": "rank_xendcg", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    bst = lgb.train(params, lgb.Dataset(X, label=clicks, group=group,
                                        position=pos), num_boost_round=10)
    assert np.isfinite(bst.predict(X)).all()
    assert np.isfinite(np.asarray(bst._gbdt.objective.pos_biases)).all()


def test_no_positions_no_bias_state():
    X, true_rel, clicks, pos, group = _make_click_data(seed=5)
    bst = lgb.train({"objective": "lambdarank", "verbosity": -1},
                    lgb.Dataset(X, label=clicks, group=group),
                    num_boost_round=3)
    assert not bst._gbdt.objective.has_position_bias
