"""Graceful-degradation primitives for the serving path.

``CircuitBreaker`` is the classic three-state machine, per served
model:

- **closed** — requests flow; consecutive dispatch faults count up.
- **open** — after ``threshold`` consecutive faults, requests fail
  fast with ``CircuitOpenError`` (carrying a retry-after hint) for
  ``reset_s`` seconds, so a model whose packs/compiles are broken
  stops eating executor time that healthy tenants need.
- **half-open** — after the timer, exactly ONE probe request is let
  through; success closes the breaker, failure re-opens it for another
  ``reset_s``.

State transitions are counted into ``obs.metrics`` under
``resilience/*`` (exported as ``lgbmtpu_resilience_*`` OpenMetrics
families). Thread-safe: the server's event loop checks admission while
the executor thread reports outcomes.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

from ..obs.metrics import global_metrics
from .errors import CircuitOpenError

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    def __init__(self, name: str, threshold: int = 5,
                 reset_s: float = 30.0) -> None:
        self.name = name
        self.threshold = max(int(threshold), 1)
        self.reset_s = max(float(reset_s), 1e-3)
        self.state = CLOSED
        self.consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._probe_started = 0.0
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def admit(self) -> bool:
        """Gate one request. Raises ``CircuitOpenError`` while open;
        while half-open, admits a single probe and rejects the rest.
        Returns True when THIS admission took the half-open probe slot
        (the caller must pair it with record_success/record_failure or
        release_probe), False for a plain closed-state admission."""
        with self._lock:
            if self.state == CLOSED:
                return False
            now = time.monotonic()
            if self.state == OPEN:
                remaining = self._opened_at + self.reset_s - now
                if remaining > 0:
                    global_metrics.inc_counter(
                        "resilience/breaker_rejected")
                    raise CircuitOpenError(
                        f"circuit for model '{self.name}' is open "
                        f"({self.consecutive_failures} consecutive "
                        f"faults); retry in {remaining:.3f}s",
                        retry_after_s=remaining)
                self.state = HALF_OPEN
                self._probe_in_flight = False
                global_metrics.inc_counter(
                    "resilience/breaker_half_open")
            # half-open: one probe at a time. A probe that never
            # reported back (died via deadline/cancellation/shed — not
            # a model fault) releases its slot after reset_s, so an
            # abandoned probe can never deny the model service forever.
            if self._probe_in_flight and \
                    now - self._probe_started < self.reset_s:
                global_metrics.inc_counter("resilience/breaker_rejected")
                raise CircuitOpenError(
                    f"circuit for model '{self.name}' is half-open with "
                    "a probe in flight; retry shortly",
                    retry_after_s=self.reset_s / 10.0)
            self._probe_in_flight = True
            self._probe_started = now
            return True

    def release_probe(self) -> None:
        """The in-flight request ended without a verdict on the model
        (deadline expiry, cancellation, load shed): free the half-open
        probe slot without changing breaker state."""
        with self._lock:
            self._probe_in_flight = False

    def record_success(self) -> None:
        with self._lock:
            if self.state != CLOSED:
                global_metrics.inc_counter("resilience/breaker_closed")
            self.state = CLOSED
            self.consecutive_failures = 0
            self._probe_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            self.consecutive_failures += 1
            self._probe_in_flight = False
            if self.state == HALF_OPEN or (
                    self.state == CLOSED
                    and self.consecutive_failures >= self.threshold):
                self.state = OPEN
                self._opened_at = time.monotonic()
                global_metrics.inc_counter("resilience/breaker_open")

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self.state == OPEN


def backoff_delays(max_retries: int, base_s: float,
                   cap_s: float = 1.0) -> list:
    """Exponential backoff schedule: [base, 2*base, 4*base, ...] capped.
    Deterministic (no jitter) so the chaos validator's timings are
    reproducible; a fleet-scale deployment would add jitter upstream."""
    return [min(base_s * (2 ** i), cap_s)
            for i in range(max(int(max_retries), 0))]
