"""Logging facade (ref: include/LightGBM/utils/log.h:89 `Log`,
python-package register_logger in basic.py).

Levels mirror the reference (Fatal < Warning < Info < Debug); the
threshold is driven by Config.verbosity exactly as the reference maps it
(config.h verbosity: <0 fatal, 0 warning+error, 1 info, >1 debug). A
custom logger object or callback can be registered, as with
``lightgbm.register_logger``.

``LGBM_TPU_LOG_JSON=1`` (or ``set_json_mode(True)``) switches the
default print path to one JSON object per line — ``ts``/``level``/
``msg`` plus every ``hostenv.host_labels()`` entry (hostname, pid, and
the jax.distributed process index when initialized) — so multihost
logs interleaved from many workers stay machine-mergeable. A registered
custom logger still receives the plain message (it owns its own
formatting).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

FATAL = -1
WARNING = 0
INFO = 1
DEBUG = 2

_LEVEL_NAMES = {FATAL: "Fatal", WARNING: "Warning", INFO: "Info",
                DEBUG: "Debug"}

_level = INFO
_logger: Optional[Any] = None
_info_method = "info"
_warning_method = "warning"
_debug_method: Optional[str] = None
_json_mode = os.environ.get("LGBM_TPU_LOG_JSON", "") not in ("", "0")


def set_json_mode(on: bool) -> None:
    """Toggle structured JSON log records on the default print path
    (the runtime twin of the ``LGBM_TPU_LOG_JSON`` env var)."""
    global _json_mode
    _json_mode = bool(on)


def set_verbosity(verbosity: int) -> None:
    """Map Config.verbosity onto the log threshold
    (ref: c_api.cpp LGBM_BoosterResetParameter verbosity handling)."""
    global _level
    if verbosity < 0:
        _level = FATAL
    elif verbosity == 0:
        _level = WARNING
    elif verbosity == 1:
        _level = INFO
    else:
        _level = DEBUG


def register_logger(logger: Any, info_method_name: str = "info",
                    warning_method_name: str = "warning",
                    debug_method_name: Optional[str] = None) -> None:
    """Replace the default print-based output with a custom logger
    (ref: python-package/lightgbm/basic.py register_logger).

    ``debug_method_name`` optionally routes Debug-level messages to a
    dedicated method; when omitted, Debug falls back to the info method
    (but still through the registered logger — Debug never bypasses it).
    """
    for name in (info_method_name, warning_method_name):
        if not callable(getattr(logger, name, None)):
            raise TypeError(
                f"Logger must provide a callable {name}() method")
    if debug_method_name is not None and \
            not callable(getattr(logger, debug_method_name, None)):
        raise TypeError(
            f"Logger must provide a callable {debug_method_name}() method")
    global _logger, _info_method, _warning_method, _debug_method
    _logger = logger
    _info_method = info_method_name
    _warning_method = warning_method_name
    _debug_method = debug_method_name


def _emit(level: int, msg: str, force: bool = False) -> None:
    if level > _level and not force:
        return
    if _logger is not None:
        if level <= WARNING:
            meth = _warning_method
        elif level >= DEBUG and _debug_method is not None:
            meth = _debug_method
        else:
            meth = _info_method
        getattr(_logger, meth)(msg)
    elif _json_mode:
        import json
        import time
        from .hostenv import host_labels
        rec = {"ts": round(time.time(), 3),
               "level": _LEVEL_NAMES[level], "msg": msg}
        rec.update(host_labels())  # hostname/pid/process_index stamps
        print(json.dumps(rec), flush=True)
    else:
        print(f"[LightGBM-TPU] [{_LEVEL_NAMES[level]}] {msg}", flush=True)


def debug(msg: str) -> None:
    _emit(DEBUG, msg)


def info(msg: str, force: bool = False) -> None:
    """force=True bypasses the level gate — for output the user
    explicitly asked for (e.g. an attached log_evaluation callback),
    matching the reference python package where callback prints route
    through _log_info regardless of the lib verbosity param."""
    _emit(INFO, msg, force)


def warning(msg: str) -> None:
    _emit(WARNING, msg)


def fatal(msg: str) -> None:
    """Log and raise (ref: Log::Fatal always throws, log.h:89)."""
    _emit(FATAL, msg)
    from .basic import LightGBMError
    raise LightGBMError(msg)


def check(condition: bool, msg: str = "check failed") -> None:
    """CHECK macro analog (ref: utils/log.h:44)."""
    if not condition:
        fatal(msg)
