#!/usr/bin/env python
"""Validator for out-of-core streaming training (ISSUE 13).

Drives the REAL code paths end-to-end — the acceptance scenario of the
streaming PR, kept honest in CI:

1. **Forced streaming under a clamped HBM budget** — with
   ``LGBM_TPU_HBM_BYTES`` set below the resident peak of the analytic
   memory model, ``lgb.preflight`` stays honest (``fits`` False for
   resident, ``fits_streaming`` True, a ``tpu_stream`` recommendation
   with a modeled slab size), and a ``tpu_stream=auto`` train actually
   streams: host-resident bins, a multi-slab plan, training to
   completion with a measured ``overlap_ratio > 0``.
2. **Bit-identity** — a single-slab streamed train produces the exact
   ``model_to_string()`` of the resident train (same fused program on
   an uploaded operand), and int8-quantized streaming is bit-identical
   across DIFFERENT slab counts (integer partial sums dequantized
   after accumulation).
3. **OpenMetrics export** — the rendered document carries every
   ``lgbmtpu_stream_*`` family and passes the exposition lint
   (tools/check_metrics_endpoint.py).

Exit 0 = all steps passed. Wired into the quick verification tier via
tests/test_stream.py (TestToolsWiring).
"""

import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

_N_SMALL = 1200
_N_MULTI = 5000
_F = 8


def _fixture(n, seed=7):
    r = np.random.RandomState(seed)
    X = r.randn(n, _F)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.3).astype(np.float32)
    return X, y


def _train(X, y, extra, iters=3):
    import lightgbm_tpu as lgb
    params = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                  max_bin=63, min_data_in_leaf=5, verbosity=-1, **extra)
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    return lgb.train(params, ds, num_boost_round=iters)


def _strip_params(model_str: str) -> str:
    """Models trained with different tpu_stream settings differ only in
    the echoed parameters block; strip it for the bit-identity compare
    (the established idiom of the fused/packed parity tests)."""
    return re.sub(r"\nparameters:.*?end of parameters",
                  "", model_str, flags=re.S)


def step1_forced_streaming() -> None:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.obs import memory as obs_memory
    from lightgbm_tpu.ops.bin_pack import slab_align

    n = _N_MULTI
    X, y = _fixture(n)
    params = dict(objective="binary", num_leaves=15, max_bin=63,
                  min_data_in_leaf=5, verbosity=-1,
                  tpu_fused_grad="off")
    cfg = Config.from_params(dict(params))
    kw = obs_memory._resolve_train_knobs(cfg, n, _F, 1)
    kw["valid_rows"] = []
    resident_peak = obs_memory.train_memory_model(**kw)["peak_bytes"]
    streamed_min = obs_memory.train_memory_model(
        **kw, stream_slab_rows=slab_align(63))["peak_bytes"]
    assert streamed_min < resident_peak, \
        "fixture must make the bin tensor the dominant operand"
    clamp = (streamed_min + resident_peak) // 2

    os.environ["LGBM_TPU_HBM_BYTES"] = str(clamp)
    try:
        # the planner stays honest: resident does NOT fit, streaming does
        report = lgb.preflight(dict(params), shape=(n, _F))
        assert report.fits is False, report.render()
        assert report.fits_streaming is True, report.render()
        rec_knobs = {r["knob"]: r for r in report.recommendations}
        assert "tpu_stream" in rec_knobs, \
            f"non-fit must recommend streaming: {report.render()}"
        assert rec_knobs["tpu_stream"]["slab_rows"] >= slab_align(63)

        # tpu_stream=auto now picks streaming and trains to completion
        # (same fused-grad setting the clamp was computed against)
        from lightgbm_tpu.io.streaming import global_stream_stats
        global_stream_stats.reset()
        bst = _train(X, y, {"tpu_fused_grad": "off"}, iters=3)
        plan = bst._gbdt._stream
        assert plan is not None, "auto mode must have engaged streaming"
        assert plan.n_slabs >= 2, \
            f"clamped budget must force a multi-slab plan ({plan.n_slabs})"
        stats = global_stream_stats.summary()
        assert stats["overlap_ratio"] > 0.0, stats
        assert stats["uploads_total"] >= plan.n_slabs
        pred = bst.predict(X[:64])
        assert np.all(np.isfinite(pred))
    finally:
        del os.environ["LGBM_TPU_HBM_BYTES"]
    print(f"# step 1 OK: clamped budget ({clamp} B) -> preflight "
          f"fits(resident)=False fits(streaming)=True, auto-streamed "
          f"{plan.n_slabs}-slab train, overlap "
          f"{stats['overlap_ratio']:.2%}")


def step2_bit_identity() -> None:
    X, y = _fixture(_N_SMALL)
    resident = _train(X, y, {}).model_to_string()
    streamed = _train(X, y, {"tpu_stream": "on"}).model_to_string()
    assert _strip_params(resident) == _strip_params(streamed), \
        "single-slab streamed training must be bit-identical to resident"

    Xm, ym = _fixture(_N_MULTI)
    q2 = _train(Xm, ym, {"use_quantized_grad": True, "tpu_stream": "on",
                         "tpu_stream_slab_rows": 4096}).model_to_string()
    q3 = _train(Xm, ym, {"use_quantized_grad": True, "tpu_stream": "on",
                         "tpu_stream_slab_rows": 2048}).model_to_string()
    assert _strip_params(q2) == _strip_params(q3), \
        "int8-quantized streaming must be slab-count invariant"
    print("# step 2 OK: single-slab bit-identity + quantized "
          "slab-count invariance")


def step3_metrics_export() -> None:
    from lightgbm_tpu.obs.export import render_openmetrics
    doc = render_openmetrics()
    required = [
        "lgbmtpu_stream_slabs_total",
        "lgbmtpu_stream_uploads_total",
        "lgbmtpu_stream_bytes_uploaded_total",
        "lgbmtpu_stream_upload_seconds_total",
        "lgbmtpu_stream_kernel_seconds_total",
        "lgbmtpu_stream_overlap_ratio",
        "lgbmtpu_stream_slab_rows",
        "lgbmtpu_stream_n_slabs",
    ]
    missing = [fam for fam in required if f"\n{fam}" not in doc
               and not doc.startswith(fam)]
    assert not missing, f"missing stream families: {missing}"
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import check_metrics_endpoint as lint
    errors, _families = lint.validate_exposition(doc)
    assert not errors, errors[:5]
    print(f"# step 3 OK: {len(required)} lgbmtpu_stream_* families "
          "exported, document passes exposition lint")


def main() -> int:
    step1_forced_streaming()
    step2_bit_identity()
    step3_metrics_export()
    print("# stream validator OK (3/3 steps)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
