#!/usr/bin/env bash
# Build the reference LightGBM CLI from /root/reference in this offline
# image. The vendored submodules (fmt, fast_double_parser, eigen,
# nanoarrow, compute) are empty, so small build shims from
# tools/ref_shims/ are injected via a symlink shadow tree; the top
# CMakeLists' cmake_minimum_required is lowered to match the image's
# cmake. Produces /tmp/lgbsrc/lightgbm (used by gen_reference_golden.py).
set -euo pipefail

SRC=/tmp/lgbsrc
BUILD=/tmp/lgbref
REF=/root/reference
SHIMS="$(cd "$(dirname "$0")/ref_shims" && pwd)"

rm -rf "$SRC" "$BUILD"
mkdir -p "$SRC"
for f in "$REF"/* ; do
  ln -s "$f" "$SRC/$(basename "$f")"
done
rm "$SRC/CMakeLists.txt" "$SRC/external_libs"
sed 's/cmake_minimum_required(VERSION 3.28)/cmake_minimum_required(VERSION 3.25)/' \
    "$REF/CMakeLists.txt" > "$SRC/CMakeLists.txt"

E="$SRC/external_libs"
mkdir -p "$E/fast_double_parser/include" "$E/fmt/include/fmt" \
         "$E/eigen/Eigen" "$E/nanoarrow/include/nanoarrow" \
         "$E/compute/include"
cp "$SHIMS/fast_double_parser.h" "$E/fast_double_parser/include/"
cp "$SHIMS/fmt_format.h" "$E/fmt/include/fmt/format.h"
cp "$SHIMS/eigen_dense.h" "$E/eigen/Eigen/Dense"
cp "$SHIMS/nanoarrow.hpp" "$E/nanoarrow/include/nanoarrow/nanoarrow.hpp"
cat > "$E/nanoarrow/CMakeLists.txt" <<'EOF'
cmake_minimum_required(VERSION 3.25)
project(nanoarrow_shim C)
add_library(nanoarrow_static STATIC nanoarrow_stub.c)
target_include_directories(nanoarrow_static PUBLIC ${CMAKE_CURRENT_SOURCE_DIR}/include)
EOF
cat > "$E/nanoarrow/nanoarrow_stub.c" <<'EOF'
/* nanoarrow shim: all functionality lives in the header. */
int lgbm_nanoarrow_shim_anchor = 0;
EOF

cmake -S "$SRC" -B "$BUILD" -DCMAKE_BUILD_TYPE=Release
cmake --build "$BUILD" -j"$(nproc)"
ls -la "$SRC/lightgbm"
echo "reference CLI: $SRC/lightgbm"
