"""Exclusive Feature Bundling (EFB) — the wide-sparse data path.

TPU-native re-think of the reference's FeatureGroup/EFB machinery
(ref: src/io/dataset.cpp:112 FindGroups, :251 FastFeatureBundling,
include/LightGBM/feature_group.h:27). The reference bundles mutually
exclusive features so one Bin column stores many features. On TPU the
dense ``[F, N]`` bin tensor is the memory ceiling for wide one-hot data
(10k features x 10M rows = 100 GB unbundled), so bundling compresses
STORAGE to ``[G, N]`` with G = #bundles; histograms are built on the
bundled columns and expanded back to the logical per-feature layout with
a static gather, so the split finder and all tree semantics are
unchanged.

Encoding inside a bundle (ref: feature_group.h bin_offsets_): bundle bin
0 = every member feature at its default bin; member f's non-default bins
``1..nb_f-1`` occupy the half-open range ``[offset_f, offset_f+nb_f-1)``.
The logical bin-0 row of each member's histogram is recovered as
``leaf_total - sum(non-default bins)`` — exact for conflict-free
bundles (and the bundler only merges conflict-free features unless
`max_conflict_rate` allows otherwise, like the reference).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import numpy as np


class BundleInfo(NamedTuple):
    """Static bundle structure (host). F = logical used features,
    G = stored columns."""
    bundles: Tuple[Tuple[int, ...], ...]  # member feature idxs per bundle
    group_of: np.ndarray   # [F] int32: stored column of feature f
    offset_of: np.ndarray  # [F] int32: bundle bin of f's logical bin 1
    num_bundle_bins: int   # max bins over stored columns (B_tot)

    @classmethod
    def from_bundles(cls, bundles, num_bins) -> "BundleInfo":
        """Derive the offset layout from bundle membership — the single
        source of truth for the encoding (build + binary reload both
        call this)."""
        f = len(num_bins)
        group_of = np.zeros(f, np.int32)
        offset_of = np.zeros(f, np.int32)
        widths = []
        for g, members in enumerate(bundles):
            off = 1
            for feat in members:
                group_of[feat] = g
                offset_of[feat] = off
                off += int(num_bins[feat]) - 1
            widths.append(off)
        return cls(bundles=tuple(tuple(m) for m in bundles),
                   group_of=group_of, offset_of=offset_of,
                   num_bundle_bins=max(widths) if widths else 1)


def find_bundles(nonzero_masks: np.ndarray, num_bins: np.ndarray,
                 *, max_conflict_rate: float = 0.0,
                 max_bundle_bins: int = 256,
                 bundleable: Optional[np.ndarray] = None) -> List[List[int]]:
    """Greedy conflict-bounded grouping (ref: dataset.cpp:112 FindGroups).

    nonzero_masks: [F, S] bool over the binning SAMPLE rows — True where
    the feature is at a non-default bin. Features are scanned in
    decreasing nonzero count (the reference's ordering) and placed into
    the first bundle whose accumulated conflict count and total bin width
    allow it. Features with `bundleable[f] == False` (e.g. default bin
    != 0, which the offset encoding can't represent) are forced into
    singleton bundles — stored verbatim.
    """
    f, s = nonzero_masks.shape
    max_conflicts = int(max_conflict_rate * s)
    order = np.argsort(-nonzero_masks.sum(axis=1, dtype=np.int64))
    # cap the per-feature candidate search like the reference's
    # max_search_group (ref: dataset.cpp:118 FindGroups) — without it,
    # wide data where most features conflict degrades quadratically
    max_search = 100
    search_rng = np.random.RandomState(3)

    bundle_members: List[List[int]] = []
    bundle_masks: List[np.ndarray] = []
    bundle_conflicts: List[int] = []
    bundle_bins: List[int] = []
    for feat in order:
        feat = int(feat)
        width = int(num_bins[feat]) - 1  # non-default bins it adds
        placed = False
        if bundleable is None or bundleable[feat]:
            n_groups = len(bundle_members)
            if n_groups > max_search:
                candidates = search_rng.choice(n_groups, max_search,
                                               replace=False)
            else:
                candidates = range(n_groups)
            for g in candidates:
                if bundle_masks[g] is None:  # singleton-only bundle
                    continue
                if bundle_bins[g] + width + 1 > max_bundle_bins:
                    continue
                conflicts = int(np.sum(bundle_masks[g] & nonzero_masks[feat]))
                if bundle_conflicts[g] + conflicts <= max_conflicts:
                    bundle_members[g].append(feat)
                    bundle_masks[g] = bundle_masks[g] | nonzero_masks[feat]
                    bundle_conflicts[g] += conflicts
                    bundle_bins[g] += width
                    placed = True
                    break
        if not placed:
            bundle_members.append([feat])
            bundle_masks.append(
                nonzero_masks[feat].copy()
                if (bundleable is None or bundleable[feat]) else None)
            bundle_conflicts.append(0)
            bundle_bins.append(width + 1)
    return bundle_members


def build_bundled_matrix(bins_fm: np.ndarray, num_bins: np.ndarray,
                         bundles: List[List[int]]
                         ) -> Tuple[np.ndarray, BundleInfo]:
    """Merge a logical [F, N] bin matrix into stored [G, N] columns.

    Rows with several non-default members in one bundle (conflicts, when
    max_conflict_rate > 0) keep the LAST member's code, like the
    reference's push order.
    """
    f, n = bins_fm.shape
    info = BundleInfo.from_bundles(bundles, num_bins)
    dtype = np.uint8 if info.num_bundle_bins <= 256 else np.uint16
    out = np.zeros((len(bundles), n), dtype)
    for g, members in enumerate(bundles):
        col = np.zeros(n, np.int64)
        for feat in members:
            fb = bins_fm[feat].astype(np.int64)
            nz = fb > 0
            col[nz] = info.offset_of[feat] + fb[nz] - 1
        out[g] = col.astype(dtype)
    return out, info


def should_bundle(bundles: List[List[int]], num_features: int) -> bool:
    """Bundling pays when it actually shrinks the matrix (ref:
    dataset.cpp FastFeatureBundling only groups when beneficial)."""
    return len(bundles) < num_features


# ----------------------------------------------------------------------
# logical views. Device-side decode lives in ops/partition.feature_bins
# (the jit-traced twin of this helper); keep the two in sync.


def decode_stored_host(col_stored: np.ndarray, offset: np.ndarray,
                       width: np.ndarray) -> np.ndarray:
    """Host decode of stored bundle codes to logical bins (vectorized
    over rows with per-row offsets/widths): stored in
    [off, off+width) -> stored - off + 1; else default 0."""
    in_range = (col_stored >= offset) & (col_stored < offset + width)
    return np.where(in_range, col_stored - offset + 1, 0)


def expand_bundle_hist(bundle_hist, group_of, offset_of, nb,
                       max_bins: int, totals):
    """[..., G, B_tot, C] bundled histogram -> [..., F, B, C] logical.

    nb: [F] logical bin counts; totals: [..., C] per-leaf channel totals
    (each feature's default-bin row = total - sum of its own non-default
    bins). Rows b >= nb[f] contain neighboring features' bins — the
    split finder masks them via FeatureMeta.num_bins, and the bin-0
    subtraction here masks them explicitly.
    """
    import jax.numpy as jnp
    b_tot = bundle_hist.shape[-2]
    # gather non-default bins: logical (f, b >= 1) <- bundled
    # (group_of[f], offset_of[f] + b - 1)
    bidx = jnp.arange(max_bins)  # [B]
    src_bin = jnp.clip(offset_of[:, None] + bidx[None, :] - 1, 0, b_tot - 1)
    gathered = bundle_hist[..., group_of, :, :]  # [..., F, B_tot, C]
    idx = jnp.broadcast_to(
        src_bin[..., None],
        gathered.shape[:-2] + (max_bins, gathered.shape[-1]))
    hist = jnp.take_along_axis(gathered, idx, axis=-2)  # [..., F, B, C]
    own = (bidx[None, :] >= 1) & (bidx[None, :] < nb[:, None])  # [F, B]
    nondefault = jnp.sum(hist * own[..., None], axis=-2)  # [..., F, C]
    default_row = totals[..., None, :] - nondefault
    hist = hist.at[..., 0, :].set(default_row)
    return hist
