#!/usr/bin/env python
"""CI validator for the device-time attribution pillar
(obs/profile.py) and the crash flight recorder (obs/flightrec.py).

Runs the whole plumbing on the CPU fixture — the profiler-free
fallback path re-times the instrumented_jit dispatches inline, so a
host with no TPU exercises the exact attribution/rollup/export code a
device capture feeds:

1. **Fallback attribution** — a knob-armed capture window
   (``tpu_profile=window``) over a small training run must attribute
   device seconds and calls to the training program tag(s) the run
   dispatched, with window coverage (attributed seconds over window
   wall time) inside the perf_floor.json ``profile`` band — the same
   band perf-gate check 11 holds bench records to. A second, manual
   window around a predict call must attribute ``predict/traversal``.
2. **Roofline** — the measured-vs-peak join must carry a valid
   memory-bound/compute-bound verdict per attributed tag, and (CPU
   exposes cost analysis) at least one tag must join achieved bytes/s
   + utilization against the hostenv.platform_peaks row.
3. **OpenMetrics egress** — render_openmetrics() must surface every
   ``lgbmtpu_profile_*`` family, lint clean line-by-line
   (check_metrics_endpoint.validate_exposition), and stay
   ``# EOF``-terminated.
4. **Bit-identity** — the model trained with the capture window armed
   must serialize byte-for-byte identical to the same fixture trained
   with profiling off: attribution is a sync, never a value change.
5. **Flight recorder** — with the recorder armed, an injected
   poisoned-label fault under ``tpu_health=error`` must raise
   NonFiniteError AND leave a schema-valid dump
   (flightrec.validate_dump) containing the fault_injection event, the
   health_anomaly event, and the offending iteration's entry — the
   postmortem a dead run leaves behind.

Exit 0 = pass. Usage: python tools/check_profile.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

import numpy as np  # noqa: E402

_PROFILE_FAMILIES = [
    "lgbmtpu_profile_window_seconds",
    "lgbmtpu_profile_coverage",
    "lgbmtpu_profile_device_seconds_total",
    "lgbmtpu_profile_calls_total",
    "lgbmtpu_profile_achieved_bytes_per_second",
    "lgbmtpu_profile_utilization",
]


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def main() -> int:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs.export import render_openmetrics
    from lightgbm_tpu.obs.flightrec import global_flightrec, validate_dump
    from lightgbm_tpu.obs.health import HealthError, global_health
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.obs.profile import global_profile
    from lightgbm_tpu.obs.xla import global_xla
    from lightgbm_tpu.resilience import faults
    from check_metrics_endpoint import validate_exposition

    with open(os.path.join(_REPO, "tools", "perf_floor.json")) as fh:
        band = json.load(fh)["profile"]
    min_cov = float(band["min_coverage"])
    max_cov = float(band["max_coverage"])

    rng = np.random.RandomState(0)
    n, f = 800, 8
    x = rng.randn(n, f)
    y = ((x[:, 2] + x[:, 4]) > 0.3).astype(np.float64)
    base = {"objective": "binary", "num_leaves": 7,
            "min_data_in_leaf": 5, "verbosity": -1}

    # --- 1. fallback attribution over a knob-armed window ------------
    global_metrics.enable()
    global_xla.enable()
    global_profile.reset()
    params = dict(base, tpu_profile="window", tpu_profile_window=3)
    bst = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                    num_boost_round=6)
    model_profiled = bst.model_to_string()
    s = global_profile.stop_window()  # idempotent: the tick closed it
    secs = s.get("device_seconds_by_tag", {})
    if not secs:
        return _fail("capture window attributed no device seconds")
    train_tags = [t for t in secs
                  if t.startswith(("boosting/", "parallel/", "stream/"))]
    if not train_tags:
        return _fail(f"no training program tag attributed; got "
                     f"{sorted(secs)}")
    for tag in train_tags:
        if s["calls_by_tag"].get(tag, 0) <= 0 or secs[tag] <= 0.0:
            return _fail(f"tag {tag!r} has no calls/seconds")
    cov = s.get("coverage")
    if cov is None:
        return _fail("window summary carries no coverage")
    if not (min_cov <= cov <= max_cov):
        return _fail(f"window coverage {cov:.2%} outside the "
                     f"[{min_cov:.0%}, {max_cov:.0%}] floor band")
    print(f"# fallback attribution: {sorted(train_tags)} captured, "
          f"coverage {cov:.2%}: OK")

    # --- 1b. predict attribution over a manual window ----------------
    global_profile.start_window()
    pred_prof = bst.predict(x[:256], raw_score=True)
    s2 = global_profile.stop_window()
    if s2["device_seconds_by_tag"].get("predict/traversal", 0.0) <= 0.0:
        return _fail("predict window did not attribute "
                     "predict/traversal; got "
                     f"{sorted(s2['device_seconds_by_tag'])}")
    print("# predict attribution: predict/traversal captured: OK")

    # --- 2. roofline join --------------------------------------------
    rl = global_profile.roofline()
    for tag, row in rl["by_tag"].items():
        if row.get("verdict") not in ("memory-bound", "compute-bound"):
            return _fail(f"roofline tag {tag!r} has verdict "
                         f"{row.get('verdict')!r}")
        if row.get("device_s", 0.0) <= 0.0:
            return _fail(f"roofline tag {tag!r} has no device seconds")
    joined = [t for t, row in rl["by_tag"].items()
              if "achieved_bytes_per_s" in row
              and "bytes_utilization" in row]
    if not joined:
        return _fail("no tag joined cost-analysis bytes into achieved "
                     "bytes/s + utilization (CPU exposes cost analysis)")
    peaks = rl.get("peaks", {})
    if not (peaks.get("bytes_per_s", 0) > 0
            and peaks.get("flops_per_s", 0) > 0):
        return _fail(f"roofline peaks row is degenerate: {peaks}")
    print(f"# roofline: {len(joined)}/{len(rl['by_tag'])} tag(s) "
          f"joined vs {rl['platform']} peaks: OK")

    # --- 3. OpenMetrics families -------------------------------------
    text = render_openmetrics()
    errors, families = validate_exposition(text)
    if errors:
        return _fail(f"exposition lint: {errors[:5]}")
    missing = [fam for fam in _PROFILE_FAMILIES if fam not in families]
    if missing:
        return _fail(f"lgbmtpu_profile_* families missing from "
                     f"/metrics: {missing}")
    if text.splitlines()[-1].strip() != "# EOF":
        return _fail("exposition is not '# EOF'-terminated")
    print(f"# OpenMetrics: all {len(_PROFILE_FAMILIES)} profile "
          "families surfaced, lint clean, EOF-terminated: OK")

    # --- 4. bit-identity: profiling must never change the model ------
    global_profile.reset()
    bst_off = lgb.train(base, lgb.Dataset(x, label=y, params=base),
                        num_boost_round=6)

    def _strip_knob_echo(model: str) -> str:
        # the serialized params block faithfully echoes the profile
        # knobs, which differ by construction; the trees must not
        return "\n".join(line for line in model.splitlines()
                         if not line.startswith("[tpu_profile"))

    if _strip_knob_echo(bst_off.model_to_string()) != \
            _strip_knob_echo(model_profiled):
        return _fail("model trained under the capture window differs "
                     "from the unprofiled model — the attribution sync "
                     "changed values")
    pred_off = bst_off.predict(x[:256], raw_score=True)
    if not np.array_equal(np.asarray(pred_prof), np.asarray(pred_off)):
        return _fail("profiled-window predictions differ from the "
                     "unprofiled model's")
    print("# bit-identity profiling on vs off: OK")

    # --- 5. flight recorder on an injected fault ---------------------
    dump_path = os.path.join(tempfile.gettempdir(),
                             f"flightrec_check_{os.getpid()}.json")
    try:
        global_flightrec.reset()
        global_flightrec.enable(path=dump_path)
        faults.install(faults.FaultPlan(poison_labels_at_iter=1))
        # regression: the poisoned NaN label flows straight into the
        # gradient (binary's label threshold would swallow it)
        params_h = dict(base, objective="regression",
                        tpu_health="error")
        raised = None
        try:
            lgb.train(params_h,
                      lgb.Dataset(x, label=x[:, 0].astype(np.float64),
                                  params=params_h),
                      num_boost_round=4)
        except HealthError as exc:
            raised = exc
        finally:
            faults.reset()
        if raised is None:
            return _fail("poisoned-label fault under tpu_health=error "
                         "did not raise a HealthError")
        if not os.path.exists(dump_path):
            return _fail("no flight-recorder dump written on the "
                         "injected fault")
        with open(dump_path) as fh:
            doc = json.load(fh)
        schema_errors = validate_dump(doc)
        if schema_errors:
            return _fail(f"flight-recorder dump schema: "
                         f"{schema_errors[:5]}")
        if doc.get("reason") != type(raised).__name__:
            return _fail(f"dump reason {doc.get('reason')!r} != raised "
                         f"{type(raised).__name__!r}")
        kinds = {e["kind"] for e in doc["events"]}
        for want in ("iteration", "fault_injection", "health_anomaly"):
            if want not in kinds:
                return _fail(f"dump lacks a {want!r} event; got "
                             f"{sorted(kinds)}")
        anomaly = [e for e in doc["events"]
                   if e["kind"] == "health_anomaly"][-1]
        bad_iter = anomaly.get("iteration")
        if not any(e["kind"] == "iteration"
                   and e.get("iteration") == bad_iter
                   for e in doc["events"]):
            return _fail(f"dump lacks the offending iteration "
                         f"{bad_iter}'s own event")
        print(f"# flight recorder: {type(raised).__name__} dump with "
              f"{len(doc['events'])} event(s) incl. iteration "
              f"{bad_iter}: OK")
    finally:
        global_flightrec.reset()
        global_flightrec.disable()
        if os.path.exists(dump_path):
            os.remove(dump_path)
        global_health.reset()
        global_profile.reset()
        global_metrics.reset()
        global_metrics.disable()
        global_xla.disable()
        # undo the rest of global_metrics.enable()'s fan-out so an
        # in-process caller (tests) doesn't inherit an armed tracer
        from lightgbm_tpu.obs.memory import global_watermarks
        from lightgbm_tpu.obs.trace import global_tracer
        global_health.disable()
        global_tracer.disable()
        global_tracer.reset()
        global_watermarks.disable()

    print("check_profile: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
