"""Per-iteration training metrics registry.

Collects, per boosting iteration: wall time, per-phase times (fed from
``obs.trace`` span self-times), gradient/hessian norms and clip counts,
leaves grown, best-split gain stats, JIT recompilation counts, device
memory stats, and collective traffic for the data-/voting-parallel
paths (ref: the reference attributes wins via exactly such per-phase
breakdowns — Common::Timer dumps, and the per-phase tables in
arXiv:1806.11248 / arXiv:2005.09148).

Two cost regimes, by design:

- **Disabled (default):** every per-iteration entry point
  (``begin_iteration`` / ``observe`` / ``inc`` / ``end_iteration``)
  returns after a single attribute check — nothing is recorded,
  nothing is allocated.
- **Trace-time counters** (``note_trace`` / ``note_collective``) are
  always live: they execute only while jax traces a program (i.e. at
  compile time, never per iteration), so JIT recompilations are
  detectable even with telemetry off.

Enabled via ``LGBM_TPU_TELEMETRY=1``, ``enable()``, or by attaching
the ``callback.log_telemetry`` / ``callback.record_telemetry``
callbacks to ``train``.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple


class LatencyReservoir:
    """Streaming latency quantiles over a bounded uniform reservoir
    (Vitter's Algorithm R): O(1) `note`, O(capacity) memory no matter
    how many samples arrive, and any retained sample is a uniform draw
    from the full stream — so p50/p95/p99 stay unbiased over a run.

    This is the ONE percentile primitive for serving telemetry:
    ``note_predict`` (bulk predict dispatches) and the serve/ request
    path both record through it instead of keeping local sample lists.
    The RNG is seeded per reservoir, so summaries are reproducible for
    a deterministic request sequence.

    Thread-safe: ``note`` is a read-modify-write of count/totals/samples
    and the serve/ path records from both the asyncio loop and its
    single-thread executor, so every mutation (and the quantile read's
    sample snapshot) holds the per-reservoir lock. The lock is
    uncontended in the common case — ~100 ns per note, far below the
    events being timed.
    """

    __slots__ = ("capacity", "count", "total_seconds", "max_seconds",
                 "_samples", "_rng", "_lock")

    def __init__(self, capacity: int = 4096, seed: int = 0) -> None:
        self.capacity = max(int(capacity), 1)
        self.count = 0
        self.total_seconds = 0.0
        self.max_seconds = 0.0
        self._samples: List[float] = []
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def note(self, seconds: float) -> None:
        s = float(seconds)
        with self._lock:
            self.count += 1
            self.total_seconds += s
            if s > self.max_seconds:
                self.max_seconds = s
            if len(self._samples) < self.capacity:
                self._samples.append(s)
            else:
                j = self._rng.randrange(self.count)
                if j < self.capacity:
                    self._samples[j] = s

    def quantiles(self, qs: Sequence[float]) -> Tuple[float, ...]:
        """Nearest-rank quantiles over the reservoir (0.0 when empty)."""
        with self._lock:
            ordered = sorted(self._samples)
        if not ordered:
            return tuple(0.0 for _ in qs)
        last = len(ordered) - 1
        return tuple(ordered[min(int(q * len(ordered)), last)] for q in qs)

    def summary(self) -> Dict[str, Any]:
        """p50/p95/p99 + count/mean/max, in milliseconds — the shape
        emitted into bench/serve JSON lines."""
        p50, p95, p99 = self.quantiles((0.50, 0.95, 0.99))
        mean = self.total_seconds / self.count if self.count else 0.0
        return {
            "count": self.count,
            "p50_ms": round(p50 * 1e3, 4),
            "p95_ms": round(p95 * 1e3, 4),
            "p99_ms": round(p99 * 1e3, 4),
            "mean_ms": round(mean * 1e3, 4),
            "max_ms": round(self.max_seconds * 1e3, 4),
        }


class MetricsRegistry:
    def __init__(self) -> None:
        self.enabled = os.environ.get(
            "LGBM_TPU_TELEMETRY", "") not in ("", "0")
        self.history: List[Dict[str, Any]] = []
        self._current: Optional[Dict[str, Any]] = None
        self._iter_t0 = 0.0
        # trace-time counters (always live; see module docstring)
        self.trace_counts: Dict[str, int] = {}
        self.collective_calls = 0
        self.collective_bytes = 0
        # static run facts (mesh size, learner kind, ...), set once at
        # setup — not per-iteration, so always-on is free
        self.meta: Dict[str, Any] = {}
        # serving throughput accumulators (always live: two adds per
        # predict CALL, not per row — the predict analog of the
        # trace-time counters)
        self.predict_rows_total = 0
        self.predict_seconds_total = 0.0
        # serving-path telemetry (always live, O(1) per event): named
        # latency reservoirs ("predict", "serve/request", ...) and flat
        # event counters ("serve/registry_hit", "serve/pack_evictions",
        # ...) — the serve/ subsystem records through these instead of
        # keeping server-local sample lists
        self.latency_reservoirs: Dict[str, LatencyReservoir] = {}
        self.counters: Dict[str, int] = {}
        # guards the always-on serving accumulators (counters, reservoir
        # creation, predict totals): serve/ records from the asyncio
        # loop AND its executor thread concurrently
        self._mutex = threading.Lock()

    # ------------------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True
        # phase times come from span self-times; the tracer must run for
        # the sink to fire (summary-only: no exit print, no export)
        from .trace import global_tracer
        global_tracer.enable()
        # arm the span-boundary HBM watermark sampler (self-disables on
        # backends without memory_stats — obs/memory.py)
        from .memory import global_watermarks
        global_watermarks.enable()
        # and the XLA program introspector (compile time + cost analysis
        # per program boundary — obs/xla.py)
        from .xla import global_xla
        global_xla.enable()
        # and the training-health registry (runtime collective
        # attribution, straggler skew, eval anomalies — obs/health.py)
        from .health import global_health
        global_health.enable()

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        self.history.clear()
        self._current = None
        self.trace_counts.clear()
        self.collective_calls = 0
        self.collective_bytes = 0
        self.meta.clear()
        self.predict_rows_total = 0
        self.predict_seconds_total = 0.0
        self.latency_reservoirs.clear()
        self.counters.clear()

    def set_meta(self, key: str, value) -> None:
        self.meta[key] = value

    # ------------------------------------------------------------------
    # per-iteration lifecycle (called by GBDT.train_one_iter)
    def begin_iteration(self, iteration: int) -> None:
        if not self.enabled:
            return
        self._current = {"iteration": iteration, "phases": {}}
        self._iter_t0 = time.perf_counter()

    def end_iteration(self) -> None:
        cur = self._current
        if not self.enabled or cur is None:
            return
        cur["iteration_seconds"] = time.perf_counter() - self._iter_t0
        mem = self.per_device_memory_stats()
        if mem:
            # multi-chip runs must not under-report: the record carries
            # the SUM of live bytes (fleet footprint) and the MAX peak
            # (the device that OOMs first), plus the per-device rows
            cur["device_bytes_in_use"] = sum(
                int(s.get("bytes_in_use", 0) or 0) for s in mem)
            cur["device_peak_bytes_in_use"] = max(
                int(s.get("peak_bytes_in_use", 0) or 0) for s in mem)
            if len(mem) > 1:
                cur["device_bytes_in_use_per_device"] = [
                    int(s.get("bytes_in_use", 0) or 0) for s in mem]
                cur["device_peak_bytes_per_device"] = [
                    int(s.get("peak_bytes_in_use", 0) or 0) for s in mem]
        cur["collective_calls_total"] = self.collective_calls
        cur["collective_bytes_total"] = self.collective_bytes
        self._current = None
        self.history.append(cur)

    def observe(self, name: str, value) -> None:
        # local ref: another thread's end_iteration may null _current
        # between the check and the write (predict during train)
        cur = self._current
        if not self.enabled or cur is None:
            return
        cur[name] = value

    def inc(self, name: str, n: int = 1) -> None:
        cur = self._current
        if not self.enabled or cur is None:
            return
        cur[name] = cur.get(name, 0) + n

    def phase_sink(self, name: str, dur_s: float, self_s: float) -> None:
        """Span sink (registered on the global tracer): accumulate span
        SELF time into the open iteration's phase table — self time sums
        to wall time without double-counting nested spans."""
        cur = self._current
        if not self.enabled or cur is None:
            return
        phases = cur["phases"]
        phases[name] = phases.get(name, 0.0) + self_s

    # ------------------------------------------------------------------
    # trace-time counters (executed while jax traces, i.e. per compile)
    def note_trace(self, tag: str, top_level: bool = False) -> None:
        """Mark one Python trace of `tag`'s function body. The
        per-tag counter advances once per body execution under a trace —
        for a top-level jitted program that is exactly once per
        (re)compile; an op called N times inside one program advances
        its tag N times per compile (a call-site count, still zero when
        the program cache hits). Only ``top_level=True`` calls (the
        wrap_traced program wrappers) feed the per-iteration
        ``jit_recompiles`` metric, so it counts program recompiles, not
        inner call sites."""
        self.trace_counts[tag] = self.trace_counts.get(tag, 0) + 1
        if top_level and self.enabled:
            cur = self._current
            if cur is not None:
                cur["jit_recompiles"] = cur.get("jit_recompiles", 0) + 1

    def wrap_traced(self, tag: str, fn):
        """fn -> fn that notes a trace each time jax traces it; jit the
        RESULT (``jax.jit(registry.wrap_traced("tag", f))``). Also opens
        a health-manifest capture frame for the trace, so collective
        call sites traced inside the body register themselves against
        this program tag (obs/health.py runtime attribution) — trace
        time only, never a per-call cost."""
        def wrapped(*args, **kwargs):
            self.note_trace(tag, top_level=True)
            from .health import global_health
            global_health.begin_program_trace(tag)
            try:
                return fn(*args, **kwargs)
            finally:
                global_health.end_program_trace(tag)
        wrapped.__name__ = getattr(fn, "__name__", tag)
        return wrapped

    def recompiles(self, tag: Optional[str] = None) -> int:
        if tag is not None:
            return self.trace_counts.get(tag, 0)
        return sum(self.trace_counts.values())

    # ------------------------------------------------------------------
    # serving telemetry (always live, O(1) per event)
    def latency(self, name: str) -> LatencyReservoir:
        """The named latency reservoir, created on first use."""
        res = self.latency_reservoirs.get(name)
        if res is None:
            with self._mutex:  # one reservoir per name under races
                res = self.latency_reservoirs.get(name)
                if res is None:
                    res = self.latency_reservoirs[name] = LatencyReservoir()
        return res

    def note_latency(self, name: str, seconds: float) -> None:
        self.latency(name).note(seconds)

    def reset_latency(self, name: str) -> LatencyReservoir:
        """Replace the named reservoir (bench --serve resets between the
        warmup and measured phases) and return the fresh one."""
        res = self.latency_reservoirs[name] = LatencyReservoir()
        return res

    def latency_summary(self, name: str) -> Dict[str, Any]:
        return self.latency(name).summary()

    def inc_counter(self, name: str, n: int = 1) -> None:
        with self._mutex:
            self.counters[name] = self.counters.get(name, 0) + int(n)

    def counter(self, name: str) -> int:
        return self.counters.get(name, 0)

    def note_predict(self, rows: int, seconds: float) -> None:
        """Account one serving-path predict dispatch (ops/predict.py
        streaming engine). Always-on and O(1); feeds the
        `predict_rows_per_sec` serving metric (bench.py --predict), the
        "predict" latency reservoir, and, when an iteration record is
        open (predict during training), the per-iteration totals."""
        with self._mutex:
            self.predict_rows_total += int(rows)
            self.predict_seconds_total += float(seconds)
        self.note_latency("predict", seconds)
        cur = self._current
        if self.enabled and cur is not None:
            cur["predict_rows"] = cur.get("predict_rows", 0) + int(rows)
            cur["predict_seconds"] = (cur.get("predict_seconds", 0.0)
                                      + float(seconds))

    def predict_rows_per_sec(self) -> float:
        """Cumulative serving throughput since the last reset()."""
        if self.predict_seconds_total <= 0.0:
            return 0.0
        return self.predict_rows_total / self.predict_seconds_total

    def note_collective(self, op: str, nbytes: int) -> None:
        """Account one collective (psum/all_gather) emitted into a traced
        program. Trace-time: counts collectives per compiled program, the
        static analog of the reference's per-split network byte counts
        (ref: data_parallel_tree_learner.cpp HistogramSumReducer)."""
        self.collective_calls += 1
        self.collective_bytes += int(nbytes)
        self.trace_counts[f"collective/{op}"] = \
            self.trace_counts.get(f"collective/{op}", 0) + 1

    # ------------------------------------------------------------------
    @staticmethod
    def device_memory_stats() -> Optional[Dict[str, Any]]:
        """device.memory_stats() of the default device, when the backend
        provides it (TPU/GPU do; CPU returns None). Single-device compat
        entry — multi-chip consumers use per_device_memory_stats."""
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats()
            return dict(stats) if stats else None
        except Exception:
            return None

    @staticmethod
    def per_device_memory_stats() -> Optional[List[Dict[str, Any]]]:
        """memory_stats() of EVERY local device (each dict carries a
        "device" ordinal), or None when the backend reports none —
        sharded runs peak on whichever device holds the fattest shard,
        which device 0 alone cannot see."""
        try:
            import jax
            out = []
            for i, dev in enumerate(jax.local_devices()):
                stats = dev.memory_stats()
                if stats:
                    d = dict(stats)
                    d["device"] = i
                    out.append(d)
            return out or None
        except Exception:
            return None

    def snapshot(self) -> Optional[Dict[str, Any]]:
        """The most recent completed iteration's metrics dict."""
        return self.history[-1] if self.history else None


global_metrics = MetricsRegistry()

# phase-time feed: span self-times land in the open iteration's table
from .trace import global_tracer as _gt  # noqa: E402
_gt.add_sink(global_metrics.phase_sink)
if global_metrics.enabled:
    _gt.enable()
