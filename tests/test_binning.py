"""Binning semantics tests (ref strategy: tests/cpp_tests + binning parts
of tests/python_package_test/test_basic.py)."""

import numpy as np
import pytest

from lightgbm_tpu.binning import (BinMapper, MISSING_NAN, MISSING_NONE,
                                  MISSING_ZERO)


def test_few_distinct_values_one_bin_each():
    vals = np.array([1.0, 2.0, 3.0] * 50)
    m = BinMapper().fit(vals, max_bin=255, min_data_in_bin=1)
    b = m.transform(np.array([1.0, 2.0, 3.0]))
    assert len(set(b.tolist())) == 3
    assert m.missing_type == MISSING_NONE


def test_bin_bounds_monotone():
    rng = np.random.RandomState(0)
    vals = rng.randn(10000)
    m = BinMapper().fit(vals, max_bin=63)
    assert np.all(np.diff(m.bin_upper_bound) > 0)
    assert m.num_bins <= 64
    # transform respects bounds: value <= ub -> that bin
    b = m.transform(vals)
    assert b.min() >= 0 and b.max() < m.num_bins


def test_equal_count_binning():
    rng = np.random.RandomState(1)
    vals = rng.rand(100000) + 1.0  # no zeros
    m = BinMapper().fit(vals, max_bin=16)
    b = m.transform(vals)
    counts = np.bincount(b, minlength=m.num_bins)
    nonzero = counts[counts > 0]
    # roughly equal-count bins
    assert nonzero.max() / max(nonzero.mean(), 1) < 2.5


def test_zero_gets_own_bin():
    vals = np.concatenate([np.zeros(500), np.random.RandomState(2).randn(500)])
    m = BinMapper().fit(vals, max_bin=32)
    zb = m.transform(np.array([0.0]))[0]
    near = m.transform(np.array([1e-40, -1e-40]))
    assert (near == zb).all()
    assert m.default_bin == zb


def test_nan_missing_gets_last_bin():
    vals = np.array([1.0, 2.0, np.nan, 3.0, np.nan] * 20)
    m = BinMapper().fit(vals, max_bin=32)
    assert m.missing_type == MISSING_NAN
    b = m.transform(np.array([np.nan]))
    assert b[0] == m.num_bins - 1


def test_zero_as_missing():
    vals = np.array([0.0, 1.0, 2.0, np.nan] * 25)
    m = BinMapper().fit(vals, max_bin=32, zero_as_missing=True)
    assert m.missing_type == MISSING_ZERO
    assert m.transform(np.array([np.nan]))[0] == \
        m.transform(np.array([0.0]))[0]


def test_heavy_hitter_isolated():
    rng = np.random.RandomState(3)
    vals = np.concatenate([np.full(50000, 7.5), rng.rand(1000) * 10 + 10])
    m = BinMapper().fit(vals, max_bin=8)
    b_hh = m.transform(np.array([7.5]))[0]
    b_near = m.transform(np.array([10.4]))[0]
    assert b_hh != b_near


def test_categorical_mapping():
    vals = np.array([3.0] * 100 + [7.0] * 50 + [1.0] * 10 + [9.0] * 2)
    m = BinMapper().fit(vals, max_bin=32, is_categorical=True)
    assert m.is_categorical
    b3 = m.transform(np.array([3.0]))[0]
    b7 = m.transform(np.array([7.0]))[0]
    assert b3 == 1  # most frequent category is bin 1 (bin 0 = other)
    assert b7 == 2
    assert m.transform(np.array([555.0]))[0] == 0  # unseen -> other
    assert float(m.bin_to_value(b3)) == 3.0


def test_categorical_negative_is_missing():
    vals = np.array([1.0, 2.0, -1.0] * 30)
    m = BinMapper().fit(vals, max_bin=8, is_categorical=True)
    assert m.transform(np.array([-5.0]))[0] == 0


def test_trivial_feature():
    m = BinMapper().fit(np.full(100, 3.14), max_bin=255)
    assert m.is_trivial


def test_forced_bounds():
    vals = np.random.RandomState(4).rand(1000) * 10
    m = BinMapper().fit(vals, max_bin=255, forced_bounds=[2.5, 5.0, 7.5])
    assert 2.5 in m.bin_upper_bound and 5.0 in m.bin_upper_bound
    assert m.transform(np.array([2.4]))[0] != m.transform(np.array([2.6]))[0]


def test_bin_to_value_roundtrip():
    rng = np.random.RandomState(5)
    vals = rng.randn(5000)
    m = BinMapper().fit(vals, max_bin=64)
    for b in range(m.num_bins - 1):
        ub = m.bin_to_value(b)
        if np.isfinite(ub):
            assert m.transform(np.array([ub]))[0] == b
