#!/usr/bin/env python
"""Perf-regression gate (ROADMAP item 4: convert "should be fast" into
driver-visible proof).

Fourteen checks, all against the recorded floor in tools/perf_floor.json:

1. **Histogram traffic model** — recomputes the static per-iteration
   HBM byte model (learner.hist_traffic_model) for the recorded
   benchmark fixture shape under the current scheduler/encodings and
   fails if bytes/iter regressed more than 10% over the recorded
   floor, or if the reduction vs the unpacked/no-subtraction oracle
   fell below the recorded minimum (1.8x — the ISSUE 7 acceptance
   number). A code change that silently widens a wave schedule, drops
   bin packing, or fattens the gh operand trips this without any
   hardware in the loop.

2. **Peak-memory model ceiling** — recomputes the analytic peak-HBM
   model (obs.memory.train_memory_model) for the recorded bench
   fixture and fails if the predicted peak grew more than 10% over the
   recorded ceiling (a silently-fattened resident buffer class). A
   candidate JSON carrying BOTH `mem_peak_model_bytes` and
   `mem_peak_measured_bytes` (accelerator runs) is additionally held
   to the recorded model-vs-measured band (1.5x either way) — the
   out-of-core streaming work needs a fit/doesn't-fit oracle it can
   trust.

3. **Bench trajectory** — reads the BENCH_*.json lines in the repo
   root (plus an optional candidate JSON passed as argv[1]); for each
   platform the best recorded `vs_baseline` is the floor, and the
   LATEST same-platform value must not drop more than 10% below it.
   A candidate JSON carrying `hist_bytes_per_iter` is additionally
   held to the byte floor.

4. **Phase-time trajectory** — over the obs phase summaries bench.py
   folds into its JSON line when telemetry is on (`phases`): per
   platform, a phase above the absolute-noise floor may not exceed its
   best (lowest) recorded time by the configured fraction. No recorded
   phase summaries => the check reports itself skipped.

5. **XLA cross-check of the analytic models** — compiles the actual
   packed+quantized wave histogram kernel for the recorded fixture
   shape and holds the analytic traffic/memory models to what XLA's
   OWN analyses say about the executable (obs/xla.py): the compiled
   program's argument bytes must agree with the traffic model's
   per-pass operand bytes within the declared band (so
   `hist_bytes_per_iter` = passes x per-pass is cross-validated
   end-to-end), XLA's `bytes accessed` must not fall BELOW the model
   (a model that claims more streaming than the program can touch is
   broken), and the memory model's operand/slab components must cover
   the executable's argument/output buffers. Independent, silicon-free
   proof; skips gracefully where the backend exposes no cost analysis.

6. **Comms health** — over the obs/health summaries bench.py folds
   into its JSON line (`health` field): the latest record's per-phase
   straggler skew (above an absolute-noise floor) must stay under the
   recorded ceiling, and the estimated collective time share (runtime
   collective bytes x the timed mesh probe's per-byte rate, over
   measured train seconds) must not make iterations comms-bound.
   No mesh run recorded => the check reports itself skipped — the
   same graceful-skip pattern as the other obs pillars.

7. **Checkpoint overhead** — over the ``resilience`` dict bench.py
   folds into its JSON line when a run checkpointed
   (resilience/checkpoint.py): the snapshot wall-time share of train
   wall-time must stay under the floor-configured ceiling — fault
   tolerance is only free if the snapshots are. Graceful skip when no
   checkpointing ran (the common bench config).

8. **Continual-loop overhead** — over the latest bench record carrying
   a ``continual`` summary (bench.py --continual,
   resilience/continual.py): the validated hot-swap share of continual
   wall-time and the total non-training overhead share must stay under
   the floor-configured caps — a long-lived model is only viable if
   accepting a generation is nearly free. Graceful skip when no
   continual bench ran.

9.  **Stream overhead** — streamed-vs-resident slowdown ceiling and
    upload/compute overlap floor over the latest ``stream`` bench
    record (check_stream_overhead). Graceful skip when absent.

10. **Cold start** — warm-start compile reduction, program-acquisition
    ratchet ceiling, and the serialized-artifact restore sub-checks
    over the latest ``coldstart`` bench record (check_coldstart).
    Graceful skip when absent.

11. **Device-time roofline** — over the latest bench record carrying a
    ``roofline`` summary (obs/profile.py window folded into bench.py's
    JSON line): the attributed-device-seconds coverage of the profile
    window's wall time must land inside the floor-configured band, and
    the best per-tag utilization vs the hostenv.platform_peaks row
    must clear the RATCHETING ``min_utilization`` floor. Graceful skip
    when no profiled bench ran or the record is unattributable.

12. **Fleet availability** — over the latest bench record carrying a
    ``fleet`` summary (bench.py --fleet: open-loop load through the
    FleetRouter with one replica killed at the 40% mark): the served
    fraction must clear the ``min_availability`` floor (0.999), the
    killed replica must land in quarantine, and the served answers
    must stay bit-identical to a direct predict (check_fleet_
    availability). Graceful skip when no fleet bench ran.

13. **SHAP contributions** — over the latest bench record carrying a
    ``shap`` summary (bench.py --shap: the batched device TreeSHAP
    kernel vs the same-run host recursive oracle): the device speedup
    must clear the per-platform ``min_speedup_vs_host`` floor, the
    kernel must have matched the oracle on the parity subset, and the
    measured path-table pack bytes must land inside the configured
    band of the analytic memory model's ``shap_pack`` component
    (check_shap). Graceful skip when no shap bench ran.

14. **Collective scatter reduction** — recomputes the static
    per-iteration cross-device collective byte model
    (learner.collective_traffic_model) for the recorded fixture shape
    under both reductions and fails if the reduce-scatter learner's
    modeled collective bytes stopped beating the full-histogram psum
    oracle by the recorded factor at the fixture width (ISSUE 20
    acceptance: >= 1.8x at W=4). Purely analytic — no devices in the
    loop — so a code change that silently re-widens the all_gather
    payload or drops the feature partition trips this on any host.
    Graceful skip when no scatter floor is recorded.

Exit 0 = gate passed; exit 1 = regression, with one line per failure.
Wired into the quick verification tier via tests/test_perf_gate.py.

Usage: python tools/check_perf_gate.py [candidate_bench.json]
"""

import glob
import json
import os
import re
import sys

# never let a jax import probe a down TPU relay from a CI gate
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLOOR_PATH = os.path.join(REPO, "tools", "perf_floor.json")
if REPO not in sys.path:  # runnable from anywhere
    sys.path.insert(0, REPO)


def _platform_of(unit: str) -> str:
    m = re.search(r"platform=(\w+)", unit or "")
    return m.group(1) if m else "tpu"


def _extract_metric_record(blob):
    """A bench contract record from either shape: the raw JSON line
    bench.py emits, or the driver's {"n", "cmd", "rc", "tail"} wrapper
    whose `tail` embeds that line in captured output."""
    if blob.get("metric") == "boosting_iters_per_sec_higgs_shape":
        return blob
    for line in reversed(str(blob.get("tail", "")).splitlines()):
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("metric") == "boosting_iters_per_sec_higgs_shape":
                return rec
    return None


def _load_bench_lines(candidate_path=None):
    """[(round_tag, record)] for every train-metric BENCH line, oldest
    first; the candidate (if any) sorts last."""
    out = []
    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_*.json"))):
        try:
            with open(path) as fh:
                rec = _extract_metric_record(json.load(fh))
        except (OSError, ValueError):
            continue
        if rec is not None:
            out.append((os.path.basename(path), rec))
    if candidate_path:
        with open(candidate_path) as fh:
            rec = _extract_metric_record(json.load(fh))
        if rec is not None:
            out.append((os.path.basename(candidate_path), rec))
    return out


def check_traffic_model(floor, failures):
    from lightgbm_tpu.learner import hist_traffic_model
    fx = floor["hist"]["fixture"]
    shape = dict(num_data=fx["num_data"],
                 storage_features=fx["storage_features"],
                 max_bins=fx["max_bins"], num_leaves=fx["num_leaves"],
                 wave_max=fx["wave_max"])
    # pack_vpb defaults from max_bins inside the model (tpu_bin_pack=auto)
    actual = hist_traffic_model(
        **shape, gh_read_bytes=fx.get("gh_read_bytes", 3), subtract=True,
        fused_grad=False)
    oracle = hist_traffic_model(**shape, pack_vpb=1, gh_read_bytes=12,
                                subtract=False, fused_grad=False)
    bytes_now = actual["hist_bytes_per_iter"]
    reduction = oracle["hist_bytes_per_iter"] / bytes_now
    max_bytes = floor["hist"]["max_bytes_per_iter"] * 1.10
    if bytes_now > max_bytes:
        failures.append(
            f"hist traffic model regressed: {bytes_now/1e9:.3f} GB/iter "
            f"> floor {floor['hist']['max_bytes_per_iter']/1e9:.3f} GB "
            f"(+10%)")
    if reduction < floor["hist"]["min_bytes_reduction"]:
        failures.append(
            f"hist byte reduction vs oracle fell to {reduction:.2f}x "
            f"< required {floor['hist']['min_bytes_reduction']}x")
    print(f"# traffic model: {bytes_now/1e9:.3f} GB/iter, "
          f"{reduction:.2f}x vs oracle "
          f"({actual['passes']} passes vs {oracle['passes']})")
    return actual


def check_memory_model(floor, failures, candidate_rec=None):
    """Analytic peak-HBM ceiling + model-vs-measured band (check 2)."""
    from lightgbm_tpu.obs.memory import train_memory_model
    mem = floor.get("memory")
    if not mem:
        print("# no memory floor recorded; memory check skipped")
        return
    model = train_memory_model(**mem["fixture"])
    peak = model["peak_bytes"]
    ceiling = mem["max_peak_model_bytes"] * 1.10
    if peak > ceiling:
        failures.append(
            f"peak-memory model regressed: {peak / 1e9:.3f} GB "
            f"> floor {mem['max_peak_model_bytes'] / 1e9:.3f} GB (+10%)")
    print(f"# memory model: {peak / 1e9:.3f} GB predicted peak "
          f"(phase: {model['peak_phase']})")
    if not candidate_rec:
        return
    modeled = candidate_rec.get("mem_peak_model_bytes")
    measured = candidate_rec.get("mem_peak_measured_bytes")
    if not modeled or not measured:
        return  # CPU runs carry no measured peak
    band = float(mem.get("model_vs_measured_band", 1.5))
    ratio = modeled / measured
    if ratio > band or ratio < 1.0 / band:
        failures.append(
            f"memory model {modeled / 1e9:.3f} GB is outside the "
            f"{band}x band of measured peak {measured / 1e9:.3f} GB "
            f"(ratio {ratio:.2f})")
    else:
        print(f"# memory model vs measured: {ratio:.2f}x "
              f"(band {1 / band:.2f}..{band:.2f})")


def check_phase_trajectory(floor, failures, lines):
    """Per-phase obs time summaries in BENCH lines (check 4): the
    latest same-platform run's phase seconds may not exceed the best
    (lowest) recorded value by more than the configured fraction, for
    phases above the absolute-noise floor — the ROADMAP item-4 gate
    over *where* iteration time goes, not just the headline rate."""
    cfg = floor.get("phases") or {}
    max_inc = float(cfg.get("max_seconds_increase", 0.5))
    min_abs = float(cfg.get("min_abs_seconds", 0.1))
    by_platform = {}
    for tag, rec in lines:
        phases = rec.get("phases")
        if isinstance(phases, dict) and phases:
            by_platform.setdefault(
                _platform_of(rec.get("unit", "")), []).append((tag, phases))
    if not by_platform:
        print("# no obs phase summaries recorded; phase check skipped")
        return
    for platform, recs in by_platform.items():
        tag, latest = recs[-1]
        checked = 0
        for name, seconds in latest.items():
            if not isinstance(seconds, (int, float)):
                continue
            history = [p[name] for _, p in recs[:-1]
                       if isinstance(p.get(name), (int, float))]
            if not history:
                continue
            best = min(history)
            if seconds < min_abs:
                continue  # latest is below the noise floor
            # a best below the noise floor is lifted TO the floor, not
            # exempted: a 0.09s phase regressing to 10s must still trip
            floor_s = max(best, min_abs)
            checked += 1
            if seconds > floor_s * (1.0 + max_inc):
                failures.append(
                    f"{tag}: {platform} phase '{name}' took {seconds:.3f}s "
                    f"> {1 + max_inc:.1f}x recorded floor {floor_s:.3f}s")
        print(f"# phases[{platform}]: {checked} phase(s) checked "
              f"against floor ({tag})")


def check_xla_cost_model(floor, failures):
    """XLA-vs-analytic-model band (check 5). Compiles the packed+int8
    wave histogram kernel (the exact program the quantized fixture
    trains through on every backend) at the recorded fixture shape and
    cross-validates both PR-4/5 models against the executable's own
    cost/memory analyses. Returns silently-skipped when the backend
    exposes neither analysis."""
    cfg = floor.get("xla")
    if not cfg:
        print("# no xla floor recorded; xla cross-check skipped")
        return
    fx = cfg["fixture"]
    n, f = int(fx["num_data"]), int(fx["storage_features"])
    b, s = int(fx["max_bins"]), int(fx["num_slots"])
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from lightgbm_tpu.learner import hist_traffic_model
        from lightgbm_tpu.obs.memory import train_memory_model
        from lightgbm_tpu.obs.xla import aot_cost_summary
        from lightgbm_tpu.ops import bin_pack as bp
        from lightgbm_tpu.ops import pallas_histogram as ph

        rng = np.random.RandomState(0)
        host = bp.pack_bins_host(
            rng.randint(0, b, size=(f, n)).astype(np.uint8), b)
        packed = bp.to_device(host)
        leaves, treedef = jax.tree_util.tree_flatten(packed)
        ghT = jnp.asarray(rng.randint(-8, 8, size=(n, 3)), jnp.int8)
        row_leaf = jnp.zeros(n, jnp.int32)
        leaf_ids = jnp.arange(s, dtype=jnp.int32)

        def run(leaves, ghT, row_leaf, leaf_ids):
            pb = jax.tree_util.tree_unflatten(treedef, leaves)
            return ph.hist_multi_int8_xla(pb, ghT, row_leaf, leaf_ids,
                                          max_bins=b, num_slots=s)

        cost = aot_cost_summary(run, leaves, ghT, row_leaf, leaf_ids)
    except Exception as exc:
        print(f"# xla cross-check skipped (introspection unavailable: "
              f"{exc!r})")
        return
    if cost is None:
        print("# xla cross-check skipped (no cost_analysis on this "
              "backend)")
        return

    traffic = hist_traffic_model(
        num_data=n, storage_features=f, max_bins=b,
        num_leaves=fx.get("num_leaves", 255), wave_max=s,
        gh_read_bytes=3, subtract=True)
    per_pass = traffic["bytes_per_pass"]
    band = float(cfg.get("arg_bytes_band", 1.25))

    arg = cost.get("argument_bytes")
    if arg:
        ratio = arg / per_pass
        if ratio > band or ratio < 1.0 / band:
            failures.append(
                f"xla cross-check: compiled wave-kernel argument bytes "
                f"{arg / 1e6:.2f} MB vs traffic model per-pass "
                f"{per_pass / 1e6:.2f} MB — ratio {ratio:.3f} outside "
                f"the {1 / band:.2f}..{band:.2f} band "
                f"(hist_bytes_per_iter no longer matches what XLA "
                f"streams)")
        else:
            print(f"# xla vs traffic model: argument bytes ratio "
                  f"{ratio:.3f} (band {1 / band:.2f}..{band:.2f}), "
                  f"compile {cost['compile_s']:.2f}s")
    ba = cost.get("bytes_accessed")
    min_ratio = float(cfg.get("min_bytes_accessed_ratio", 1.0))
    if ba is not None and ba < per_pass * min_ratio:
        failures.append(
            f"xla cross-check: XLA bytes-accessed {ba / 1e6:.2f} MB is "
            f"BELOW the analytic per-pass model {per_pass / 1e6:.2f} MB "
            f"x{min_ratio} — the traffic model overstates what the "
            f"program touches")

    # memory-model side: the model's operand components must cover the
    # executable's resident argument buffers (within the same band) and
    # the wave slab must cover the program's output
    mem = train_memory_model(
        num_data=n, num_features=f, max_bins=b,
        num_leaves=fx.get("num_leaves", 255), wave_max=s,
        pack_vpb=traffic["pack_vpb"], quantized=True)
    comp = mem["components"]
    operand_cover = comp["bins"] + comp["ght"] + comp["row_leaf"]
    if arg and operand_cover * band < arg:
        failures.append(
            f"xla cross-check: memory-model operand components "
            f"{operand_cover / 1e6:.2f} MB under-account the compiled "
            f"kernel's argument buffers {arg / 1e6:.2f} MB "
            f"(mem_peak_model_bytes misses a resident operand class)")
    out_b = cost.get("output_bytes")
    if out_b and comp["hist_wave"] * band < out_b:
        failures.append(
            f"xla cross-check: memory-model hist_wave slab "
            f"{comp['hist_wave'] / 1e6:.2f} MB smaller than the "
            f"compiled wave output {out_b / 1e6:.2f} MB")
    elif arg and out_b:
        print(f"# xla vs memory model: operands {operand_cover / 1e6:.2f}"
              f" MB cover args {arg / 1e6:.2f} MB; wave slab "
              f"{comp['hist_wave'] / 1e6:.3f} MB covers output "
              f"{out_b / 1e6:.3f} MB")


def check_health_summaries(floor, failures, lines):
    """Comms-health gate (check 6) over the obs/health summaries bench
    folds into its JSON line — the same pattern as the other obs
    pillars: the latest record carrying a `health` dict is held to the
    recorded straggler-skew ceiling (phases above the absolute-noise
    floor only) and to the collective-time-share ceiling (estimated
    collective seconds / measured train seconds). Runs without a mesh
    record nothing -> the check reports itself skipped."""
    cfg = floor.get("health")
    if not cfg:
        print("# no health floor recorded; health check skipped")
        return
    with_health = [(tag, rec) for tag, rec in lines
                   if isinstance(rec.get("health"), dict)]
    if not with_health:
        print("# no health summaries recorded (no mesh run); "
              "health check skipped")
        return
    tag, rec = with_health[-1]
    hs = rec["health"]
    max_skew = float(cfg.get("max_straggler_skew", 4.0))
    min_abs = float(cfg.get("min_abs_straggler_seconds", 0.05))
    strag = hs.get("straggler") or {}
    checked = 0
    for phase, ph in (strag.get("phases") or {}).items():
        if not isinstance(ph, dict):
            continue
        # the noise floor applies to the skew DENOMINATOR: a phase the
        # median host barely ran (host-local work like binning on
        # process 0) has a meaningless max/median ratio, not a straggler
        if float(ph.get("median_s", 0.0)) < min_abs:
            continue
        checked += 1
        skew = float(ph.get("skew", 1.0))
        if skew > max_skew:
            failures.append(
                f"{tag}: straggler skew {skew:.2f}x on phase '{phase}' "
                f"(worst shard {ph.get('worst')}) exceeds the "
                f"{max_skew}x ceiling")
    est = hs.get("collectives_est") or {}
    share = est.get("time_share")
    max_share = float(cfg.get("max_collective_time_share", 0.6))
    if isinstance(share, (int, float)) and share > max_share:
        failures.append(
            f"{tag}: estimated collective time share {share:.2%} "
            f"exceeds the {max_share:.0%} ceiling — comms-bound "
            f"iterations (est {est.get('est_seconds')}s of "
            f"{est.get('train_seconds')}s)")
    print(f"# health[{tag}]: {checked} straggler phase(s) checked"
          + (f", collective share {share:.2%}"
             if isinstance(share, (int, float)) else
             ", no collective share estimate"))


def check_resilience_overhead(floor, failures, lines):
    """Checkpoint-overhead ceiling (check 7): the latest record that
    actually checkpointed (bench `resilience` field) may not have spent
    more than the configured share of train wall-time writing
    snapshots. No checkpointing recorded => the check reports itself
    skipped — same graceful-skip pattern as the obs pillars."""
    cfg = floor.get("resilience")
    if not cfg:
        print("# no resilience floor recorded; checkpoint-overhead "
              "check skipped")
        return
    with_res = [(tag, rec) for tag, rec in lines
                if isinstance(rec.get("resilience"), dict)]
    if not with_res:
        print("# no checkpointing ran in any recorded bench; "
              "checkpoint-overhead check skipped")
        return
    tag, rec = with_res[-1]
    rs = rec["resilience"]
    ck_s = float(rs.get("checkpoint_seconds_total", 0.0))
    train_s = float(rs.get("train_seconds", 0.0))
    n = int(rs.get("checkpoints", 0))
    if n <= 0 or train_s <= 0.0:
        print(f"# resilience[{tag}]: no snapshots recorded; "
              "checkpoint-overhead check skipped")
        return
    share = ck_s / train_s
    max_share = float(cfg.get("max_checkpoint_time_share", 0.15))
    if share > max_share:
        failures.append(
            f"{tag}: checkpoint overhead {share:.2%} of train wall-time "
            f"({ck_s:.3f}s snapshots / {train_s:.3f}s train over {n} "
            f"snapshot(s)) exceeds the {max_share:.0%} ceiling")
    else:
        print(f"# resilience[{tag}]: checkpoint share {share:.2%} over "
              f"{n} snapshot(s) (ceiling {max_share:.0%})")


def _load_keyed_records(key, candidate_path=None):
    """[(tag, record)] for every bench line carrying a `key` summary
    dict (bench.py --continual / --stream), oldest first; candidate
    last. Accepts both a bare record blob and the driver's n/cmd/rc/
    tail wrapper (the summary line is fished out of the tail)."""
    out = []
    paths = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))
    if candidate_path and os.path.exists(candidate_path):
        paths.append(candidate_path)
    for path in paths:
        try:
            with open(path) as fh:
                blob = json.load(fh)
        except (OSError, ValueError):
            continue
        rec = None
        if isinstance(blob.get(key), dict):
            rec = blob
        else:
            for line in reversed(str(blob.get("tail", "")).splitlines()):
                line = line.strip()
                if line.startswith("{") and f'"{key}"' in line:
                    try:
                        cand = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(cand.get(key), dict):
                        rec = cand
                        break
        if rec is not None:
            out.append((os.path.basename(path), rec))
    return out


def _load_continual_records(candidate_path=None):
    return _load_keyed_records("continual", candidate_path)


def check_continual_overhead(floor, failures, candidate_path=None):
    """Continual-loop overhead ceilings (check 8): over the latest
    bench record carrying a `continual` summary (bench.py --continual),
    the validated hot-swap share of continual wall-time and the total
    non-training overhead share (swap + rollback/snapshot bookkeeping +
    ingest) must stay under the floor-configured caps — a long-lived
    model is only viable if accepting a generation is nearly free.
    No continual bench recorded => the check reports itself skipped."""
    cfg = floor.get("continual")
    if not cfg:
        print("# no continual floor recorded; continual-overhead "
              "check skipped")
        return
    recs = _load_continual_records(candidate_path)
    if not recs:
        print("# no continual bench recorded; continual-overhead "
              "check skipped")
        return
    tag, rec = recs[-1]
    ct = rec["continual"]
    wall = float(ct.get("wall_seconds", 0.0))
    gens = int(ct.get("generations", 0))
    if wall <= 0.0 or gens <= 0:
        print(f"# continual[{tag}]: no generations recorded; "
              "continual-overhead check skipped")
        return
    swap_share = float(ct.get("swap_share",
                              float(ct.get("swap_seconds_total", 0.0))
                              / wall))
    overhead_share = float(ct.get("overhead_seconds", 0.0)) / wall
    max_swap = float(cfg.get("max_swap_share", 0.10))
    max_overhead = float(cfg.get("max_overhead_share", 0.25))
    if swap_share > max_swap:
        failures.append(
            f"{tag}: hot-swap share {swap_share:.2%} of continual "
            f"wall-time over {gens} generation(s) exceeds the "
            f"{max_swap:.0%} ceiling")
    if overhead_share > max_overhead:
        failures.append(
            f"{tag}: non-training overhead share {overhead_share:.2%} "
            f"of continual wall-time (swap + rollback + ingest) "
            f"exceeds the {max_overhead:.0%} ceiling")
    if swap_share <= max_swap and overhead_share <= max_overhead:
        print(f"# continual[{tag}]: swap share {swap_share:.2%}, "
              f"overhead share {overhead_share:.2%} over {gens} "
              f"generation(s), {int(ct.get('rollbacks', 0))} "
              f"rollback(s) (ceilings {max_swap:.0%}/{max_overhead:.0%})")


def check_stream_overhead(floor, failures, candidate_path=None):
    """Out-of-core streaming ceilings (check 9): over the latest bench
    record carrying a `stream` summary (bench.py --stream), the
    streamed run may not be more than the floor-configured factor
    slower than the same-run resident anchor, and the measured
    upload/compute overlap ratio must clear its floor — streaming is
    only a win if the double buffer actually hides the slab uploads.
    No streaming bench recorded => the check reports itself skipped."""
    cfg = floor.get("stream")
    if not cfg:
        print("# no stream floor recorded; stream-overhead check skipped")
        return
    recs = _load_keyed_records("stream", candidate_path)
    if not recs:
        print("# no streaming bench recorded; stream-overhead check "
              "skipped")
        return
    tag, rec = recs[-1]
    sm = rec["stream"]
    vs_resident = float(sm.get("vs_resident",
                               rec.get("vs_baseline", 0.0)) or 0.0)
    overlap = float(sm.get("stream_overlap_ratio",
                           sm.get("overlap_ratio", 0.0)) or 0.0)
    n_slabs = int(sm.get("n_slabs", 0))
    if vs_resident <= 0.0:
        print(f"# stream[{tag}]: no resident anchor recorded; "
              "stream-overhead check skipped")
        return
    platform = _platform_of(rec.get("unit", ""))
    key = f"max_overhead_vs_resident_{platform}"
    max_overhead = float(cfg.get(key, cfg.get("max_overhead_vs_resident",
                                              1.25)))
    min_overlap = float(cfg.get("min_overlap_ratio", 0.05))
    slowdown = 1.0 / vs_resident
    if slowdown > max_overhead:
        failures.append(
            f"{tag}: streamed training is {slowdown:.2f}x the resident "
            f"wall-time ({n_slabs} slabs, platform={platform}); ceiling "
            f"{max_overhead:.2f}x")
    if overlap < min_overlap:
        failures.append(
            f"{tag}: stream overlap ratio {overlap:.2%} is under the "
            f"{min_overlap:.0%} floor — uploads are not hiding behind "
            "device compute")
    if slowdown <= max_overhead and overlap >= min_overlap:
        print(f"# stream[{tag}]: {slowdown:.2f}x resident "
              f"({n_slabs} slabs), overlap {overlap:.2%} "
              f"(ceilings {max_overhead:.2f}x / >={min_overlap:.0%})")


def check_coldstart(floor, failures, candidate_path=None):
    """Warm-start ceilings (check 10): over the latest bench record
    carrying a `coldstart` summary (bench.py --coldstart):

    - the cache-warm rerun's REAL compile seconds must be at least
      ``min_compile_reduction`` x smaller than the cold run's (warm
      processes load, they don't compile — obs/xla attributes
      persistent-cache hits to cache_load_s, not compile_s);
    - total warm-start program-acquisition time (compile + cache load)
      must stay under the RATCHETING ``max_warm_acquire_s`` ceiling —
      lower it as cold start keeps shrinking;
    - a server restored from serialized artifacts must have served its
      first lowlat request with at most
      ``max_restore_lowlat_compiles`` serve/lowlat compiles (0: the
      whole ladder came from disk) — skipped, not failed, where the
      backend cannot serialize executables at all;
    - the restored executables' predictions must be bit-identical.

    No coldstart bench recorded => the check reports itself skipped."""
    cfg = floor.get("coldstart")
    if not cfg:
        print("# no coldstart floor recorded; coldstart check skipped")
        return
    recs = _load_keyed_records("coldstart", candidate_path)
    if not recs:
        print("# no coldstart bench recorded; coldstart check skipped")
        return
    tag, rec = recs[-1]
    cs = rec["coldstart"]
    cold = float(cs.get("cold_compile_s", 0.0))
    warm = float(cs.get("warm_compile_s", 0.0))
    if cold <= 0.0:
        print(f"# coldstart[{tag}]: no cold compile recorded; "
              "coldstart check skipped")
        return
    min_red = float(cfg.get("min_compile_reduction", 5.0))
    max_acquire = float(cfg.get("max_warm_acquire_s", 5.0))
    reduction = cold / max(warm, 1e-2)
    acquire = warm + float(cs.get("warm_cache_load_s", 0.0))
    if reduction < min_red:
        failures.append(
            f"{tag}: warm-start compile {warm:.3f}s is only "
            f"{reduction:.2f}x below the cold run's {cold:.3f}s "
            f"(floor {min_red:.1f}x) — the persistent compile cache "
            "is not biting")
    if acquire > max_acquire:
        failures.append(
            f"{tag}: warm-start program acquisition "
            f"(compile {warm:.3f}s + cache load "
            f"{cs.get('warm_cache_load_s', 0.0):.3f}s) exceeds the "
            f"{max_acquire:.1f}s ratchet ceiling")
    restore_ok = True
    if not cs.get("artifact_serialize_available", True):
        print(f"# coldstart[{tag}]: backend cannot serialize "
              "executables; artifact-restore sub-check skipped")
    else:
        max_restore = int(cfg.get("max_restore_lowlat_compiles", 0))
        restore = int(cs.get("restore_lowlat_compiles", 0))
        if restore > max_restore:
            restore_ok = False
            failures.append(
                f"{tag}: artifact-restored server paid {restore} "
                f"serve/lowlat compile(s) (ceiling {max_restore}) — "
                "the serialized-artifact path is not restoring")
        if cs.get("restore_bit_identical") is False:
            restore_ok = False
            failures.append(
                f"{tag}: artifact-restored predictions are NOT "
                "bit-identical to the exporter's")
    if reduction >= min_red and acquire <= max_acquire and restore_ok:
        print(f"# coldstart[{tag}]: compile {cold:.2f}s -> {warm:.2f}s "
              f"({reduction:.1f}x, floor {min_red:.0f}x), acquisition "
              f"{acquire:.2f}s (ceiling {max_acquire:.1f}s), restore "
              f"{int(cs.get('restore_lowlat_compiles', 0))} compile(s) "
              f"/ {int(cs.get('restore_aot_loads', 0))} load(s)")


def check_profile_roofline(floor, failures, candidate_path=None):
    """Device-time attribution + roofline (check 11): over the latest
    bench record carrying a ``roofline`` summary (the obs/profile.py
    post-loop window bench.py folds into its JSON line):

    - coverage — attributed device seconds over the profile window's
      wall time — must land inside the floor-configured band. Too low
      means the instrumented program boundaries are no longer where the
      time goes (an untagged hot program appeared); above the ceiling
      means double-counted or mis-rebased slices.
    - the best per-tag utilization (achieved bytes/s or flops/s over
      the hostenv.platform_peaks row) must clear the RATCHETING
      ``min_utilization`` floor — raise it as the kernels improve.
    - the same record must carry non-empty ``device_seconds_by_tag``.

    No profiled bench recorded => the check reports itself skipped;
    records without a cost-analysis join skip the utilization sub-check
    (the backend exposes no bytes/flops there)."""
    cfg = floor.get("profile")
    if not cfg:
        print("# no profile floor recorded; roofline check skipped")
        return
    recs = _load_keyed_records("roofline", candidate_path)
    if not recs:
        print("# no profiled bench recorded; roofline check skipped")
        return
    tag, rec = recs[-1]
    rl = rec["roofline"]
    by_tag = rl.get("by_tag") or {}
    if not by_tag or not rec.get("device_seconds_by_tag"):
        print(f"# profile[{tag}]: no attributed device seconds; "
              "roofline check skipped")
        return
    n_fail0 = len(failures)
    coverage = rl.get("coverage")
    min_cov = float(cfg.get("min_coverage", 0.2))
    max_cov = float(cfg.get("max_coverage", 1.5))
    if coverage is None:
        print(f"# profile[{tag}]: no coverage recorded; coverage band "
              "sub-check skipped")
    elif not (min_cov <= float(coverage) <= max_cov):
        failures.append(
            f"{tag}: device-time coverage {float(coverage):.2%} of the "
            f"profile window is outside the [{min_cov:.0%}, "
            f"{max_cov:.0%}] band — attribution is missing hot "
            "programs or double-counting slices")
    with_util = [r for r in by_tag.values()
                 if "bytes_utilization" in r or "flops_utilization" in r]
    if with_util:
        best_util = max(
            max(float(r.get("bytes_utilization", 0.0) or 0.0),
                float(r.get("flops_utilization", 0.0) or 0.0))
            for r in with_util)
        min_util = float(cfg.get("min_utilization", 0.0))
        if best_util < min_util:
            failures.append(
                f"{tag}: best roofline utilization {best_util:.2e} is "
                f"under the {min_util:.0e} ratchet floor — the "
                "attributed programs are not moving bytes/flops at a "
                "credible rate for this platform")
    else:
        best_util = 0.0
        print(f"# profile[{tag}]: no cost-analysis join (backend "
              "exposes no bytes/flops); utilization sub-check skipped")
    if len(failures) == n_fail0:
        cov_s = ("n/a" if coverage is None
                 else f"{float(coverage):.2%}")
        verdicts = {t: r.get("verdict", "?") for t, r in
                    sorted(by_tag.items())}
        print(f"# profile[{tag}]: coverage {cov_s} (band "
              f"[{min_cov:.0%}, {max_cov:.0%}]), best utilization "
              f"{best_util:.2e}, {len(by_tag)} tag(s) {verdicts}")


def check_fleet_availability(floor, failures, candidate_path=None):
    """Fleet chaos availability (check 12): over the latest bench
    record carrying a ``fleet`` summary (bench.py --fleet — open-loop
    load through the FleetRouter with one replica killed at the 40%
    mark), the served fraction must clear the floor-configured
    ``min_availability`` (ISSUE 17: kill a replica under load, lose
    zero requests), the killed replica must have been quarantined, and
    every served answer must have stayed bit-identical to a direct
    predict (the pack contract that makes failover retries safe).
    No fleet bench recorded => the check reports itself skipped."""
    cfg = floor.get("fleet")
    if not cfg:
        print("# no fleet floor recorded; fleet-availability check "
              "skipped")
        return
    recs = _load_keyed_records("fleet", candidate_path)
    if not recs:
        print("# no fleet bench recorded; fleet-availability check "
              "skipped")
        return
    tag, rec = recs[-1]
    ft = rec["fleet"]
    total = int(ft.get("requests", 0))
    if total <= 0:
        print(f"# fleet[{tag}]: no requests recorded; "
              "fleet-availability check skipped")
        return
    n_fail0 = len(failures)
    availability = float(ft.get("availability", 0.0))
    min_avail = float(cfg.get("min_availability", 0.999))
    if availability < min_avail:
        failures.append(
            f"{tag}: fleet availability {availability:.4%} over {total} "
            f"request(s) with a mid-run replica kill is under the "
            f"{min_avail:.1%} floor — failover is dropping requests")
    if not ft.get("parity_ok", True):
        failures.append(
            f"{tag}: fleet answers diverged bitwise from a direct "
            "predict — the idempotent-failover pack contract is broken")
    if "killed_quarantined" in ft and not ft["killed_quarantined"]:
        failures.append(
            f"{tag}: the killed replica was never quarantined — the "
            "health probe loop is not converting dispatch failures "
            "into routing decisions")
    if len(failures) == n_fail0:
        print(f"# fleet[{tag}]: availability {availability:.4%} over "
              f"{total} request(s) ({int(ft.get('failovers', 0))} "
              f"failover(s), {int(ft.get('quarantines', 0))} "
              f"quarantine(s), fleet p99 {ft.get('p99_ms', 0)}ms vs "
              f"single {ft.get('single_p99_ms', 0)}ms; floor "
              f"{min_avail:.1%})")


def check_shap(floor, failures, candidate_path=None):
    """SHAP-contribution floors (check 13): over the latest bench
    record carrying a ``shap`` summary (bench.py --shap), the batched
    device kernel must be at least ``min_speedup_vs_host_<platform>`` x
    faster than the same-run host recursive oracle (the whole point of
    the path-decomposed reformulation), the parity subset must have
    matched (no PARITY-MISMATCH marker), and the measured path-table
    pack bytes must sit within ``pack_vs_model_band`` of the analytic
    memory model's shap_pack component — the band that keeps
    preflight's fit/doesn't-fit verdicts honest for explain traffic.
    No shap bench recorded => the check reports itself skipped."""
    cfg = floor.get("shap")
    if not cfg:
        print("# no shap floor recorded; shap check skipped")
        return
    recs = _load_keyed_records("shap", candidate_path)
    if not recs:
        print("# no shap bench recorded; shap check skipped")
        return
    tag, rec = recs[-1]
    sh = rec["shap"]
    speedup = float(rec.get("vs_baseline", 0.0) or 0.0)
    if speedup <= 0.0:
        print(f"# shap[{tag}]: no oracle anchor recorded; shap check "
              "skipped")
        return
    n_fail0 = len(failures)
    platform = _platform_of(rec.get("unit", ""))
    min_speedup = float(cfg.get(
        f"min_speedup_vs_host_{platform}",
        cfg.get("min_speedup_vs_host_cpu", 5.0)))
    if speedup < min_speedup:
        failures.append(
            f"{tag}: device TreeSHAP is only {speedup:.2f}x the host "
            f"recursive oracle (platform={platform}, floor "
            f"{min_speedup:.1f}x) — the batched kernel lost its edge")
    if "PARITY-MISMATCH" in str(rec.get("unit", "")):
        failures.append(
            f"{tag}: shap bench flagged PARITY-MISMATCH — device "
            "contributions diverged from the host oracle beyond f32 "
            "recurrence tolerance")
    pack = float(sh.get("pack_bytes", 0.0) or 0.0)
    model = float(sh.get("model_pack_bytes", 0.0) or 0.0)
    band = float(cfg.get("pack_vs_model_band", 2.0))
    if pack > 0.0 and model > 0.0:
        ratio = pack / model
        if ratio > band or ratio < 1.0 / band:
            failures.append(
                f"{tag}: measured path-table pack {pack / 1e6:.2f} MB is "
                f"outside the {band}x band of the analytic model's "
                f"{model / 1e6:.2f} MB (ratio {ratio:.2f}) — "
                "predict_memory_model(contrib=True) no longer tracks "
                "the packer")
    if len(failures) == n_fail0:
        print(f"# shap[{tag}]: {speedup:.1f}x vs host oracle "
              f"(platform={platform}, floor {min_speedup:.1f}x), "
              f"pack {pack / 1e6:.2f} MB vs model {model / 1e6:.2f} MB, "
              f"paths={int(sh.get('paths', 0))} "
              f"depth={int(sh.get('depth', 0))}")


def check_collective_scatter(floor, failures):
    """Reduce-scatter collective byte model vs psum oracle (check 14)."""
    sc = floor.get("scatter")
    if not sc:
        print("# no scatter floor recorded; collective-scatter check "
              "skipped")
        return
    from lightgbm_tpu.learner import collective_traffic_model
    fx = sc["fixture"]
    shape = dict(num_features=fx["num_features"], max_bins=fx["max_bins"],
                 num_leaves=fx["num_leaves"], wave_max=fx["wave_max"],
                 width=fx["width"])
    psum = collective_traffic_model(**shape, reduction="psum")
    scat = collective_traffic_model(**shape, reduction="scatter")
    ratio = (psum["collective_bytes_per_iter"]
             / scat["collective_bytes_per_iter"])
    min_red = float(sc["min_collective_reduction_w4"])
    if ratio < min_red:
        failures.append(
            f"collective scatter reduction fell to {ratio:.2f}x "
            f"< required {min_red}x at W={fx['width']} "
            f"(scatter {scat['collective_bytes_per_iter']/1e3:.0f} KB/iter "
            f"vs psum {psum['collective_bytes_per_iter']/1e3:.0f} KB/iter)")
    print(f"# collective scatter: {ratio:.2f}x vs psum at W={fx['width']} "
          f"({scat['collective_bytes_per_iter']/1e3:.0f} KB/iter vs "
          f"{psum['collective_bytes_per_iter']/1e3:.0f} KB/iter)")


def check_bench_trajectory(floor, failures, lines, candidate_rec=None):
    if not lines:
        print("# no BENCH_*.json lines found; trajectory check skipped")
        return
    drop = float(floor["bench"].get("max_value_drop", 0.10))
    by_platform = {}
    for tag, rec in lines:
        by_platform.setdefault(_platform_of(rec.get("unit", "")),
                               []).append((tag, rec))
    for platform, recs in by_platform.items():
        values = [r.get("vs_baseline", 0.0) or 0.0 for _, r in recs]
        best, latest = max(values), values[-1]
        tag = recs[-1][0]
        if best > 0 and latest < best * (1.0 - drop):
            failures.append(
                f"{tag}: {platform} vs_baseline {latest:.4f} dropped "
                f">{drop:.0%} below recorded floor {best:.4f}")
        else:
            print(f"# bench[{platform}]: latest {latest:.4f} vs floor "
                  f"{best:.4f} ({tag})")
    if candidate_rec:
        # the candidate's absolute bytes depend on its row count and
        # bin width (the driver shrinks N on relay failures; bench's
        # train config is 63-bin/unpacked while the floor fixture is
        # the 15-bin packed shape) — so gate on the candidate's OWN
        # reduction ratio vs its oracle, which is N-invariant. The
        # subtraction-aware schedule + fused gradient pass alone give
        # >= ~1.35 at any config; losing either drops below the floor.
        red = candidate_rec.get("hist_bytes_reduction")
        min_red = float(floor["bench"].get("min_candidate_reduction", 1.3))
        if red is not None and red < min_red:
            failures.append(
                f"candidate hist_bytes_reduction {red:.2f}x < "
                f"floor {min_red}x (scheduler/encoding regression)")


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    candidate = argv[0] if argv else None
    with open(FLOOR_PATH) as fh:
        floor = json.load(fh)
    # one disk pass: every trajectory check reads the same line list
    lines = _load_bench_lines(candidate)
    candidate_rec = None
    if candidate and lines and \
            lines[-1][0] == os.path.basename(candidate):
        candidate_rec = lines[-1][1]
    failures = []
    actual = check_traffic_model(floor, failures)
    check_memory_model(floor, failures, candidate_rec)
    check_xla_cost_model(floor, failures)
    check_bench_trajectory(floor, failures, lines, candidate_rec)
    check_phase_trajectory(floor, failures, lines)
    check_health_summaries(floor, failures, lines)
    check_resilience_overhead(floor, failures, lines)
    check_continual_overhead(floor, failures, candidate)
    check_stream_overhead(floor, failures, candidate)
    check_coldstart(floor, failures, candidate)
    check_profile_roofline(floor, failures, candidate)
    check_fleet_availability(floor, failures, candidate)
    check_shap(floor, failures, candidate)
    check_collective_scatter(floor, failures)
    if failures:
        for f in failures:
            print(f"PERF GATE FAIL: {f}")
        return 1
    print(f"# perf gate OK ({actual['passes']}-pass schedule, "
          f"{actual['hist_bytes_per_iter']/1e9:.2f} GB/iter model)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
