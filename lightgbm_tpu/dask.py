"""Dask-module analog: distributed sklearn-style estimators.

The reference's dask module (ref: python-package/lightgbm/dask.py
DaskLGBMRegressor/Classifier/Ranker) wires one LightGBM worker per dask
partition and trains over its socket collectives. Here the same estimator
surface partitions the input and trains one jax.distributed worker
process per partition through `cluster.train_distributed` (XLA
collectives over Gloo/ICI — see parallel/distributed.py); dask itself is
not required, so the input is plain arrays plus an `n_partitions` knob
(or an explicit list of per-partition dicts, the shape dask collections
reduce to).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor


class _DistributedFitMixin(LGBMModel):
    """Replaces LGBMModel.fit's training step with a
    cluster.train_distributed run over row partitions."""

    def __init__(self, *args, n_partitions: int = 2, **kwargs):
        super().__init__(*args, **kwargs)
        self.n_partitions = max(int(n_partitions), 1)

    def get_params(self, deep: bool = True):
        params = super().get_params(deep=deep)
        params["n_partitions"] = self.n_partitions
        return params

    def _make_parts(self, X, y, sample_weight, group):
        if isinstance(X, (list, tuple)) and X and isinstance(X[0], dict):
            return list(X)  # pre-partitioned {"X": ..., "y": ...} dicts
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        w = None if sample_weight is None else np.asarray(sample_weight,
                                                          np.float64)
        k = min(self.n_partitions, X.shape[0])
        if group is None and X.shape[0] % k != 0:
            # the backend requires equal shards; pad with weight-0 copies
            # of the last row — zero weight contributes nothing to any
            # statistic, so the model is unchanged
            pad = k - X.shape[0] % k
            if w is None:
                w = np.ones(X.shape[0], np.float64)
            X = np.concatenate([X, np.repeat(X[-1:], pad, axis=0)])
            y = np.concatenate([y, np.repeat(y[-1:], pad)])
            w = np.concatenate([w, np.zeros(pad)])
        if group is not None:
            # ranker: partitions must respect query boundaries AND end
            # up equal-sized (the multi-host equal-shard contract) —
            # greedy row-balanced split over query boundaries
            sizes = np.asarray(group, np.int64)
            bounds = np.concatenate([[0], np.cumsum(sizes)])
            target = X.shape[0] / k
            parts = []
            qi = 0
            for pi in range(k):
                lo_q = qi
                lo = bounds[lo_q]
                want = (pi + 1) * target
                while qi < len(sizes) and (pi == k - 1
                                           or bounds[qi + 1] <= want):
                    qi += 1
                hi = bounds[qi]
                parts.append({"X": X[lo:hi], "y": y[lo:hi],
                              "weight": None if w is None else w[lo:hi],
                              "group": sizes[lo_q:qi]})
            return [p for p in parts if p["X"].shape[0] > 0]
        idx = np.array_split(np.arange(X.shape[0]), k)
        return [{"X": X[i], "y": y[i],
                 "weight": None if w is None else w[i]} for i in idx]

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, categorical_feature="auto", **kwargs):
        from .cluster import train_distributed
        dropped = [name for name, v in
                   [("eval_set", eval_set), ("init_score", init_score)]
                   + sorted(kwargs.items()) if v is not None
                   and v != "auto" and v != []]
        if dropped:
            import warnings
            warnings.warn(f"fit arguments {dropped} are not supported by "
                          "the distributed estimators; ignoring")
        params = self._lgb_params()
        if categorical_feature != "auto":
            params["categorical_feature"] = categorical_feature
        sample_weight = self._sample_weight_with_class_weight(
            y, sample_weight)
        parts = self._make_parts(X, y, sample_weight, group)
        self._Booster = train_distributed(
            params, parts, num_boost_round=self.n_estimators)
        self._n_features = int(np.asarray(parts[0]["X"]).shape[1])
        self.fitted_ = True
        return self


class DaskLGBMRegressor(LGBMRegressor, _DistributedFitMixin):
    """(ref: dask.py DaskLGBMRegressor)"""


class DaskLGBMClassifier(LGBMClassifier, _DistributedFitMixin):
    """(ref: dask.py DaskLGBMClassifier)"""


class DaskLGBMRanker(LGBMRanker, _DistributedFitMixin):
    """(ref: dask.py DaskLGBMRanker)"""
