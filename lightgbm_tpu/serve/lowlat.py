"""Dedicated low-latency predict path for small requests (B <= 64).

The streaming engine (ops/predict.py predict_raw_cached) is built for
throughput: packer token revalidation, chunk planning, double-buffered
staging. At B=1..64 that machinery costs more than the traversal, so
the server routes small requests here instead: per model, the traversal
program is AOT-compiled ONCE per (row-bucket, feature-width) via
``jax.jit(...).lower(...).compile()`` and then invoked directly as an
executable — no jit-cache lookup, no tracing, structurally zero
steady-state recompiles (the compiled handle cannot re-trace).

Rows pad up to a power-of-two bucket ({1, 2, 4, ..., max_rows}), so a
model serves any small request with at most ~7 compiled programs.
Padding rows are zeros and each row's traversal is independent, so the
sliced output is bit-identical to the batch engine's (and therefore to
``predict`` called directly) — asserted by tests/test_serve.py.

This is the AOT variant of ISSUE's low-latency options; the
``codegen.py`` tree-to-C route (now with an ``extern "C"`` batch ABI)
remains the off-process alternative for environments without jax.
"""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..obs.metrics import global_metrics
from ..obs.xla import global_xla
from ..ops.predict import (_ARRAY_FIELDS, PackedEnsemble, _next_pow2,
                           pack_ensemble, predict_raw_multiclass)
from .artifacts import backend_fingerprint, open_store, trees_digest

# AOT warmup compiles are counted under this tag (the low-latency twin
# of PREDICT_TRACE_TAG); steady-state stability is asserted through
# global_metrics.recompiles(SERVE_LOWLAT_TAG). Artifact restores count
# serve/aot_loads INSTEAD — a loaded executable never traces, so the
# recompile counter staying flat is the proof a restore really skipped
# the compiler.
SERVE_LOWLAT_TAG = "serve/lowlat"

# the explain route's AOT twin (LowLatencyExplainer); steady-state
# stability is asserted through recompiles(SERVE_EXPLAIN_TAG)
SERVE_EXPLAIN_TAG = "serve/explain_lowlat"


def _compile_for_store(store, lowered):
    """``lowered.compile()``, bypassing the persistent XLA compile
    cache when an artifact store will serialize the result: on
    affected jaxlibs an executable that was itself DESERIALIZED
    from the disk cache re-serializes incompletely ("Symbols not
    found" on a later load), so an exportable executable must come
    from a fresh backend compile. The artifact store IS this
    ladder's persistent cache, so the bypass costs one fresh
    compile exactly where a serialized artifact replaces the disk
    cache anyway. No store => plain (cache-served) compile.

    Mechanics: clearing the cache dir alone is NOT enough — jax
    memoizes its "cache in use" verdict process-wide
    (compilation_cache._cache_checked), so the verdict is reset
    around the un-cached compile and again after the dir is
    restored (the next ordinary compile then re-initializes the
    cache lazily). Internal-API use is fully guarded: if it drifts,
    we fall back to the cache-served compile and rely on the
    store's save-time validation to refuse a bad artifact."""
    if store is None:
        return lowered.compile()
    import jax as _jax
    try:
        from jax._src import compilation_cache as _cc
        prev = _jax.config.jax_compilation_cache_dir
        if prev is None:
            return lowered.compile()
        _cc.reset_cache()
        _jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        return lowered.compile()
    try:
        return lowered.compile()
    finally:
        try:
            _jax.config.update("jax_compilation_cache_dir", prev)
            _cc.reset_cache()
        except Exception:
            pass


class LowLatencyPredictor:
    """Per-model AOT-compiled small-batch predictor.

    Packs the ensemble once (exact shapes — a static serving model pays
    no capacity headroom) and compiles one executable per
    (row-bucket, feature-width) on first use. ``warm()`` precompiles
    the whole bucket ladder so the first real request doesn't pay it.
    """

    def __init__(self, trees: List, num_tree_per_iteration: int = 1,
                 max_rows: int = 64, average_output: bool = False,
                 artifact_dir: str = ""):
        self._trees = trees
        self._k = max(int(num_tree_per_iteration), 1)
        self.max_rows = max(int(max_rows), 1)
        self._average_output = bool(average_output)
        self._iterations = max(len(trees) // self._k, 1)
        self._ens: PackedEnsemble = None
        self._arrs: Tuple[jax.Array, ...] = ()
        self._compiled: Dict[Tuple[int, int], object] = {}
        # serialized-artifact store (serve/artifacts.py): compiled
        # executables write through to disk and later instances (replica
        # restart, LRU re-admission) load instead of recompiling. None
        # when no dir is configured or jax can't serialize.
        self._store = open_store(artifact_dir)
        self._fingerprint = None  # model-identity half of artifact keys

    # ------------------------------------------------------------------
    def _ensure_packed(self) -> None:
        if self._ens is None:
            self._ens = pack_ensemble(self._trees, self._k)
            self._arrs = tuple(getattr(self._ens, f) for f in _ARRAY_FIELDS)

    @property
    def nbytes(self) -> int:
        """Device bytes held by the packed tensors (0 until first use)."""
        return sum(a.nbytes for a in self._arrs)

    def buckets(self) -> List[int]:
        """The power-of-two row-bucket ladder up to max_rows."""
        out = []
        b = 1
        while b < self.max_rows:
            out.append(b)
            b <<= 1
        out.append(self.max_rows)
        return out

    def bucket(self, rows: int) -> int:
        return min(_next_pow2(rows), self.max_rows) if rows else 1

    def _artifact_key(self, rows_bucket: int, num_features: int) -> dict:
        """Full artifact fingerprint for one (bucket, width) program:
        runtime identity + packed-tensor layout names ("pack version")
        + packed shapes/dtypes + the host trees' content digest + the
        program shape itself. Everything is host-known — key
        construction never reads device memory back."""
        if self._fingerprint is None:
            fp = backend_fingerprint()
            fp["pack_fields"] = list(_ARRAY_FIELDS)
            fp["pack_shapes"] = [[list(a.shape), str(a.dtype)]
                                 for a in self._arrs]
            fp["model_digest"] = trees_digest(self._trees, self._k)
            fp["k"] = self._k
            self._fingerprint = fp
        return dict(self._fingerprint, bucket=int(rows_bucket),
                    width=int(num_features))

    def _compile_for_store(self, lowered):
        return _compile_for_store(self._store, lowered)

    def _program(self, rows_bucket: int, num_features: int):
        key = (rows_bucket, num_features)
        prog = self._compiled.get(key)
        if prog is not None:
            # idempotent per (bucket, width): a resident executable is
            # NEVER rebuilt — warm() re-runs, repeated requests, and
            # overlapping widths all land here
            return prog
        if self._store is not None:
            prog = self._store.load(self._artifact_key(rows_bucket,
                                                       num_features))
            if prog is not None:
                # restored from disk: no trace, no compile — the
                # SERVE_LOWLAT_TAG recompile counter stays flat and
                # serve/aot_loads (counted by the store) ticks instead
                self._compiled[key] = prog
                return prog
        ens = self._ens

        def run(*args):
            e = PackedEnsemble(
                *args[:-1], max_depth=ens.max_depth,
                num_trees_per_class=ens.num_trees_per_class,
                num_trees=ens.num_trees,
                has_categorical=ens.has_categorical)
            return predict_raw_multiclass(e, args[-1])

        shapes = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                  for a in self._arrs]
        shapes.append(jax.ShapeDtypeStruct(
            (rows_bucket, num_features), jnp.float32))
        t0 = time.perf_counter()
        lowered = jax.jit(global_metrics.wrap_traced(SERVE_LOWLAT_TAG, run)
                          ).lower(*shapes)
        t1 = time.perf_counter()
        hits0 = global_xla.cache_hits() if global_xla.enabled else 0
        prog = self._compile_for_store(lowered)
        if global_xla.enabled:
            # this path IS the lower/compile boundary — record the
            # executable's cost facts straight into the introspector
            global_xla.note_compile(
                SERVE_LOWLAT_TAG, "serve",
                f"{rows_bucket}x{num_features}",
                time.perf_counter() - t1, prog, trace_s=t1 - t0,
                cache_hit=global_xla.cache_hits() > hits0)
        self._compiled[key] = prog
        if self._store is not None:
            # write-through: the NEXT predictor instance (restart,
            # re-admission) warms from disk instead of this code path
            self._store.save(self._artifact_key(rows_bucket,
                                                num_features), prog)
        return prog

    def warm(self, num_features: int) -> int:
        """Make every bucket for `num_features`-wide requests resident —
        loading serialized artifacts where the store has them, compiling
        (and exporting) the rest; returns the number of executables now
        resident. Idempotent: re-warming an already-resident ladder
        compiles nothing."""
        self._ensure_packed()
        for b in self.buckets():
            self._program(b, num_features)
        return len(self._compiled)

    def export_artifacts(self, num_features: int) -> int:
        """Warm the full ladder AND ensure every executable is on disk
        (the explicit export entry for a build/deploy step; write-
        through already covers the incremental case). Returns the
        number of artifacts present for this ladder. 0 when no artifact
        store is configured."""
        if self._store is None:
            return 0
        self.warm(num_features)
        n = 0
        for b in self.buckets():
            akey = self._artifact_key(b, num_features)
            if self._store.has(akey) or \
                    self._store.save(akey, self._compiled[(b, num_features)]):
                n += 1
        return n

    # ------------------------------------------------------------------
    def __call__(self, data: np.ndarray) -> np.ndarray:
        """Raw scores [B, K] float64 for B <= max_rows rows — the same
        values predict_raw_cached produces for the same rows."""
        x = np.asarray(data, np.float64)
        if x.ndim == 1:
            x = x.reshape(1, -1)
        rows, f = x.shape
        if rows > self.max_rows:
            raise ValueError(f"low-latency path takes <= {self.max_rows} "
                             f"rows, got {rows} (use the batched path)")
        self._ensure_packed()
        t0 = time.perf_counter()
        b = self.bucket(rows)
        xb = np.zeros((b, f), np.float32)
        xb[:rows] = x
        out = self._program(b, f)(*self._arrs, jnp.asarray(xb))
        out = np.asarray(out, np.float64)[:rows]
        if self._average_output:
            out /= self._iterations
        dt = time.perf_counter() - t0
        global_metrics.note_predict(rows, dt)
        global_metrics.note_latency(SERVE_LOWLAT_TAG, dt)
        return out


class LowLatencyExplainer:
    """Per-model AOT-compiled small-batch TreeSHAP explainer — the
    `explain` route's twin of LowLatencyPredictor.

    Packs the path-decomposed tables (ops/predict.py shap_update) once
    and AOT-compiles one executable per (row-bucket, feature-width) over
    the whole pack, so small explanation requests ride the same
    zero-steady-state-recompile ladder as predictions. Outputs are
    bit-identical to the streaming device path for the same rows: the
    program body is shared (ops/shap.py contrib_run), per-row results
    are row-block independent, and both paths bucket rows to the same
    powers of two."""

    def __init__(self, trees: List, num_tree_per_iteration: int = 1,
                 max_rows: int = 64, artifact_dir: str = "",
                 pack_chunk_rows: int = 0):
        from ..ops.predict import EnsemblePacker
        from ..ops.shap import MAX_CHUNK_ROWS
        self._trees = trees
        self._k = max(int(num_tree_per_iteration), 1)
        self.max_rows = max(int(max_rows), 1)
        # the pack's path-chunk layout MUST match the streaming path's
        # (same effective row-chunk -> same Pc): the in-program chunk
        # accumulation order is part of the f32 bits, and the bit-parity
        # contract says lowlat == batched == direct on the same rows
        self.pack_chunk_rows = max(1, min(
            int(pack_chunk_rows) or MAX_CHUNK_ROWS, MAX_CHUNK_ROWS))
        self._packer = EnsemblePacker()
        self._pack = None
        self._compiled: Dict[Tuple[int, int], object] = {}
        self._store = open_store(artifact_dir)
        self._fingerprint = None

    # ------------------------------------------------------------------
    def _ensure_packed(self, num_features: int):
        if self._pack is None or self._pack.num_features != num_features:
            self._pack = self._packer.shap_update(
                self._trees, self._k, num_features,
                chunk_rows=self.pack_chunk_rows)
            self._compiled.clear()
            self._fingerprint = None
        return self._pack

    @property
    def nbytes(self) -> int:
        """Path-table bytes held by the pack (0 until first use)."""
        return 0 if self._pack is None else self._pack.nbytes

    def buckets(self) -> List[int]:
        # floored at 16 like the streaming path's shap_row_bucket: both
        # routes must run the IDENTICAL row bucket for the same request
        # so the compiled program (and its f32 bits) is the same — tiny
        # static batch sizes can lower differently under XLA
        out = []
        b = min(16, self.max_rows)
        while b < self.max_rows:
            out.append(b)
            b <<= 1
        out.append(self.max_rows)
        return out

    def bucket(self, rows: int) -> int:
        return min(max(_next_pow2(max(rows, 1)), 16), self.max_rows)

    def _operands(self) -> tuple:
        from ..ops.shap import shap_program_args
        return shap_program_args(self._pack)

    def _artifact_key(self, rows_bucket: int, num_features: int) -> dict:
        if self._fingerprint is None:
            fp = backend_fingerprint()
            fp["kind"] = "explain"
            fp["pack_shapes"] = [[list(a.shape), str(a.dtype)]
                                 for a in self._operands()]
            fp["model_digest"] = trees_digest(self._trees, self._k)
            fp["k"] = self._k
            self._fingerprint = fp
        return dict(self._fingerprint, bucket=int(rows_bucket),
                    width=int(num_features))

    def _program(self, rows_bucket: int, num_features: int):
        key = (rows_bucket, num_features)
        prog = self._compiled.get(key)
        if prog is not None:
            return prog
        if self._store is not None:
            prog = self._store.load(self._artifact_key(rows_bucket,
                                                       num_features))
            if prog is not None:
                self._compiled[key] = prog
                return prog
        from ..ops.shap import contrib_run
        pack = self._pack
        num_out = pack.num_class * (pack.num_features + 1)
        run = contrib_run(num_out, pack.has_categorical)
        shapes = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                  for a in self._operands()]
        shapes.append(jax.ShapeDtypeStruct(
            (rows_bucket, num_features), jnp.float32))
        t0 = time.perf_counter()
        lowered = jax.jit(global_metrics.wrap_traced(SERVE_EXPLAIN_TAG, run)
                          ).lower(*shapes)
        t1 = time.perf_counter()
        hits0 = global_xla.cache_hits() if global_xla.enabled else 0
        prog = _compile_for_store(self._store, lowered)
        if global_xla.enabled:
            global_xla.note_compile(
                SERVE_EXPLAIN_TAG, "serve",
                f"{rows_bucket}x{num_features}",
                time.perf_counter() - t1, prog, trace_s=t1 - t0,
                cache_hit=global_xla.cache_hits() > hits0)
        self._compiled[key] = prog
        if self._store is not None:
            self._store.save(self._artifact_key(rows_bucket,
                                                num_features), prog)
        return prog

    def warm(self, num_features: int) -> int:
        """Make every explain bucket resident (load-or-compile);
        idempotent like the predictor's warm."""
        self._ensure_packed(num_features)
        for b in self.buckets():
            self._program(b, num_features)
        return len(self._compiled)

    # ------------------------------------------------------------------
    def __call__(self, data: np.ndarray) -> np.ndarray:
        """[B, K * (F + 1)] f64 SHAP contributions for B <= max_rows
        rows — the same bits shap_contrib_cached produces."""
        from ..ops.shap import add_bias
        x = np.asarray(data, np.float64)
        if x.ndim == 1:
            x = x.reshape(1, -1)
        rows, f = x.shape
        if rows > self.max_rows:
            raise ValueError(f"low-latency explain takes <= "
                             f"{self.max_rows} rows, got {rows} "
                             "(use the batched path)")
        pack = self._ensure_packed(f)
        t0 = time.perf_counter()
        b = self.bucket(rows)
        xb = np.zeros((b, f), np.float32)
        xb[:rows] = x
        out = self._program(b, f)(*self._operands(), jnp.asarray(xb))
        out = np.asarray(out, np.float64)[:rows]
        out = add_bias(out, pack)
        dt = time.perf_counter() - t0
        global_metrics.note_predict(rows, dt)
        global_metrics.note_latency(SERVE_EXPLAIN_TAG, dt)
        return out
