"""Device (XLA) TreeSHAP — batched path-decomposed contributions.

GPUTreeShap-style reformulation of the reference TreeSHAP recursion
(src/io/tree.cpp TreeSHAP; Lundberg et al.): instead of walking each
tree per row, the pack (ops/predict.py `EnsemblePacker.shap_update`)
enumerates every root->leaf path once on the host into depth-padded
unique-element tables, and the kernel evaluates rows x paths with fully
vectorized permutation-weight recurrences:

- **extend** runs once per element slot over the whole [B, Pc, D]
  pweight tensor (the python loop over D is static and unrolls into the
  XLA program);
- the **unwound sum** — the reference computes it per element by
  re-walking the pweights — is evaluated for ALL D elements
  simultaneously: each element carries its own (one, zero) fractions,
  so one pass over j = D-2..0 yields every element's weight at once;
- per-element phi = w * (one - zero) * leaf_value scatter-adds into the
  [B, K * (F + 1)] output via a precomputed segment-id table (neutral
  padding slots target a trash column that is sliced off).

Paths stream through the kernel in fixed [Pc, D] chunks via an
in-program `fori_loop` over the stacked chunk axis, so the working set
stays bounded by the pack-time budget while the whole ensemble remains
ONE program — the same shape-stability story as the traversal engine:
row chunks bucket through `_row_bucket`, so steady-state serving never
recompiles (assertable through `recompiles(SHAP_TRACE_TAG)`).

One-fractions are 0/1 per (row, element) — a row either follows the
whole path at that feature or not — which is what lets the reference's
hot/cold recursion collapse into a closed-form per-path evaluation.
Per-row results are independent of the row block (row padding is pure
garbage rows that are sliced off), so serve-side micro-batch coalescing
returns bit-identical slices.
"""

from __future__ import annotations

import functools
import time
from typing import List

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..obs.metrics import global_metrics
from ..obs.trace import global_tracer
from .predict import (ShapPack, _get_packer, _next_pow2)

# shap program recompile tag (tests assert row/path chunk-shape
# stability through global_metrics.recompiles(SHAP_TRACE_TAG))
SHAP_TRACE_TAG = "shap/contrib"

# per-row working set scales with paths x depth, so the row chunk is
# capped well below the traversal engine's default 1M-row chunks
MAX_CHUNK_ROWS = 4096


def shap_row_bucket(rows: int, chunk: int) -> int:
    """Pad target for a chunk of `rows`: pure power-of-two, capped at
    the (small) shap chunk. The traversal engine's grain-based
    `_row_bucket` would emit chunk/16 multiples here — at a 4096-row
    cap that's a 16-shape set the pow2 warm ladder doesn't cover; pow2
    keeps the compiled set at <= 9 shapes and the worst-case tail waste
    at 2x of an already-small chunk."""
    return min(_next_pow2(max(int(rows), 16)), max(int(chunk), 16))


def _one_fractions(tbl: dict, cat_words: jax.Array, x: jax.Array,
                   has_cat: bool) -> jax.Array:
    """[B, Pc, D] bool: does row b follow the whole path p at element
    slot d? Mirrors the device traversal's decision math
    (predict.py predict_leaves_all) against the pack-time merged
    interval / bitset / missing-routing tables."""
    fs = jnp.clip(tbl["feature"], 0, x.shape[1] - 1)
    v = x[:, fs]                       # [B, Pc, D]
    isnan = jnp.isnan(v)
    v0 = jnp.where(isnan, jnp.float32(0), v)
    mt = tbl["mt"]
    use_default = (isnan & (mt == 2)) | \
        ((mt == 1) & (isnan | (jnp.abs(v0) <= 1e-35)))
    # merged numeric interval: lo < v <= hi (no_lo elides the lower
    # bound so v = -inf can't falsely fail `v > -inf`)
    o_num = jnp.where(use_default, tbl["default_follows"],
                      (tbl["no_lo"] | (v0 > tbl["lo"])) & (v0 <= tbl["hi"]))
    if not has_cat:
        return o_num
    v_int = v0.astype(jnp.int32)
    widx = jnp.clip(tbl["cat_start"] + v_int // 32, 0,
                    cat_words.shape[0] - 1)
    word = cat_words[widx]
    in_range = (~isnan) & (v0 >= 0) & (v_int // 32 < tbl["cat_nwords"])
    bit = (word >> (v_int % 32).astype(jnp.uint32)) & 1 > 0
    o_cat = jnp.where(in_range, bit, tbl["oor_follows"])
    return jnp.where(tbl["is_cat"], o_cat, o_num)


def _contrib_chunk(tbl: dict, leaf_value: jax.Array, cat_words: jax.Array,
                   x: jax.Array, num_out: int, has_cat: bool) -> jax.Array:
    """One [Pc, D] path chunk -> [B, num_out + 1] contributions (last
    column is the neutral-slot trash segment)."""
    b = x.shape[0]
    pc, depth = tbl["z"].shape
    o = _one_fractions(tbl, cat_words, x, has_cat)
    o_f = o.astype(jnp.float32)
    z = tbl["z"][None]                 # [1, Pc, D]
    z_inv = tbl["z_inv"][None]

    # extend: pw[k] <- z_u*pw[k]*(u-k)/(u+1) + o_u*pw[k-1]*k/(u+1),
    # exactly _extend_path's recurrence vectorized over (rows, paths).
    # Entries past the current element count stay 0, so the negative
    # (u-k) coefficients beyond u never see non-zero weight.
    pw = jnp.zeros((b, pc, depth), jnp.float32).at[:, :, 0].set(1.0)
    karr = np.arange(depth, dtype=np.float32)
    for u in range(1, depth):
        c1 = jnp.asarray((u - karr) / (u + 1.0))
        c2 = jnp.asarray(karr / (u + 1.0))
        shifted = jnp.concatenate(
            [jnp.zeros((b, pc, 1), jnp.float32), pw[:, :, :-1]], axis=-1)
        pw = (tbl["z"][:, u][None, :, None] * pw * c1
              + o_f[:, :, u][:, :, None] * shifted * c2)

    # unwound sum for ALL elements at once (_unwound_path_sum with
    # U = D-1): each element d uses its own (o, z); one_fraction is
    # 0/1, so the reference's `one != 0` branch is a where() select.
    u_top = depth - 1
    total = jnp.zeros((b, pc, depth), jnp.float32)
    next_one = jnp.broadcast_to(pw[:, :, u_top:u_top + 1],
                                (b, pc, depth))
    for j in range(u_top - 1, -1, -1):
        pwj = pw[:, :, j:j + 1]
        tmp = next_one * ((u_top + 1.0) / (j + 1.0))
        total_if_one = total + tmp
        next_if_one = pwj - tmp * z * ((u_top - j) / (u_top + 1.0))
        total_if_zero = total + pwj * ((u_top + 1.0) / (u_top - j)) * z_inv
        total = jnp.where(o, total_if_one, total_if_zero)
        next_one = jnp.where(o, next_if_one, next_one)

    # phi = w * (one - zero) * leaf_value; neutral slots have
    # one = zero = 1, so they contribute exactly 0 (and their segid
    # targets the trash column anyway)
    contrib = total * (o_f - z) * leaf_value[None, :, None]
    seg = tbl["segid"].reshape(-1)
    return jnp.zeros((b, num_out + 1), jnp.float32).at[:, seg].add(
        contrib.reshape(b, -1))


def contrib_run(num_out: int, has_cat: bool):
    """The traceable program body over (13 stacked path tables,
    leaf_value, cat_words, x) -> [B, num_out] f32 contributions —
    shared by the jitted streaming path below and the serve-side AOT
    explain ladder (serve/lowlat.py). The path-chunk axis streams
    through an in-program fori_loop so the working set stays at one
    [B, Pc, D] chunk while the whole pack remains a single program;
    accumulation order over chunks is fixed, so outputs are
    deterministic and independent of the row-block size."""
    from .predict import _SHAP_TABLE_FIELDS

    def run(*args):
        tables = args[:len(_SHAP_TABLE_FIELDS)]
        leaf_value, cat_words, x = args[len(_SHAP_TABLE_FIELDS):]
        b = x.shape[0]
        n_chunks = leaf_value.shape[0]

        def body(i, acc):
            tbl = {name: lax.dynamic_index_in_dim(a, i, keepdims=False)
                   for name, a in zip(_SHAP_TABLE_FIELDS, tables)}
            lv = lax.dynamic_index_in_dim(leaf_value, i, keepdims=False)
            return acc + _contrib_chunk(tbl, lv, cat_words, x,
                                        num_out, has_cat)

        out = lax.fori_loop(0, n_chunks, body,
                            jnp.zeros((b, num_out + 1), jnp.float32))
        return out[:, :num_out]

    return run


@functools.lru_cache(maxsize=32)
def _contrib_program(num_out: int, has_cat: bool):
    from ..obs import xla as obs_xla
    return obs_xla.instrumented_jit(SHAP_TRACE_TAG,
                                    contrib_run(num_out, has_cat),
                                    phase="predict")


def shap_program_args(pack: ShapPack) -> tuple:
    """The packed operand tuple `_contrib_program` expects before x."""
    return pack.tables + (pack.leaf_value, pack.cat_words)


def contrib_program_for(pack: ShapPack):
    num_out = pack.num_class * (pack.num_features + 1)
    return _contrib_program(num_out, pack.has_categorical)


def add_bias(out: np.ndarray, pack: ShapPack) -> np.ndarray:
    """Host-side f64 bias add: per-class expected value into the last
    slot of each class block (matches the reference accumulating
    _expected_value into out[:, ki, -1])."""
    f = pack.num_features
    for ki in range(pack.num_class):
        out[:, ki * (f + 1) + f] += pack.bias[ki]
    return out


def shap_contrib_cached(owner, trees: List, num_tree_per_iteration: int,
                        data: np.ndarray, num_features: int, cache_key,
                        chunk: int = 1 << 20) -> np.ndarray:
    """[N, K * (F + 1)] SHAP contributions through the packed path
    tables — the device analog of shap._contrib_over_trees. The path
    pack is cached on the SAME owner packers the traversal engine uses
    (`_get_packer(owner, cache_key)`), so identity-token invalidation
    (DART renorm, refit, rollback) covers both packs at once. Rows
    stream in bucketed chunks with the double-buffered feed; the bias
    column is added host-side in f64."""
    k = max(int(num_tree_per_iteration), 1)
    f = max(int(num_features), 1)
    chunk = max(1, min(int(chunk), MAX_CHUNK_ROWS))
    packer = _get_packer(owner, cache_key)
    with global_tracer.span("shap/pack"):
        pack = packer.shap_update(trees, k, f, chunk_rows=chunk)
    owner._packed_key = cache_key
    n = data.shape[0]
    num_out = k * (f + 1)
    out = np.zeros((n, num_out), np.float64)
    if n and pack.num_paths:
        prog = contrib_program_for(pack)
        args = shap_program_args(pack)
        bounds = [(lo, min(lo + chunk, n)) for lo in range(0, n, chunk)]

        def stage(lo, hi):
            rows = hi - lo
            b = shap_row_bucket(rows, chunk)
            xb = np.zeros((b, data.shape[1]), np.float32)
            xb[:rows] = data[lo:hi]
            return jax.device_put(xb), lo, rows

        t0 = time.perf_counter()
        with global_tracer.span("shap/contrib"):
            from ..io.streaming import double_buffered
            parts = []
            for dev, lo, rows in double_buffered(bounds,
                                                 lambda bd: stage(*bd)):
                parts.append((prog(*args, dev), lo, rows))
            for y, lo, rows in parts:
                out[lo:lo + rows] = np.asarray(y, np.float64)[:rows]
        global_metrics.note_predict(n, time.perf_counter() - t0)
    return add_bias(out, pack)
