"""Metric correctness tests (ref: src/metric/ semantics)."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Metadata
from lightgbm_tpu.metrics import _auc, create_metrics


def _eval(name, label, prob, raw=None, weight=None, group=None, **params):
    cfg = Config.from_params({"metric": name, **params})
    ms = create_metrics(cfg)
    meta = Metadata(len(label))
    meta.set_label(np.asarray(label, np.float32))
    if weight is not None:
        meta.set_weight(weight)
    if group is not None:
        meta.set_group(group)
    ms[0].init(meta, len(label))
    return ms[0].eval(np.asarray(prob),
                      np.asarray(raw if raw is not None else prob))


def test_l2_rmse():
    y = np.array([1.0, 2.0, 3.0])
    p = np.array([1.5, 2.0, 2.0])
    assert _eval("l2", y, p)[0][1] == pytest.approx((0.25 + 0 + 1) / 3)
    assert _eval("rmse", y, p)[0][1] == pytest.approx(
        np.sqrt((0.25 + 0 + 1) / 3))


def test_auc_perfect_and_random():
    y = np.array([0, 0, 1, 1], np.float32)
    assert _auc(y, np.array([0.1, 0.2, 0.8, 0.9])) == 1.0
    assert _auc(y, np.array([0.9, 0.8, 0.2, 0.1])) == 0.0
    assert _auc(y, np.array([0.5, 0.5, 0.5, 0.5])) == 0.5


def test_auc_matches_sklearn_formula():
    rng = np.random.RandomState(0)
    y = (rng.rand(500) > 0.6).astype(np.float32)
    p = rng.rand(500) + y * 0.3
    # rank-based reference computation
    order = np.argsort(p)
    ranks = np.empty(500)
    ranks[order] = np.arange(1, 501)
    # midrank correction for ties (none expected here)
    npos, nneg = y.sum(), (1 - y).sum()
    expected = (ranks[y > 0].sum() - npos * (npos + 1) / 2) / (npos * nneg)
    assert _auc(y, p) == pytest.approx(expected, abs=1e-10)


def test_weighted_auc():
    y = np.array([0, 1], np.float32)
    p = np.array([0.3, 0.7])
    w = np.array([2.0, 5.0])
    assert _auc(y, p, w) == 1.0


def test_binary_logloss():
    y = np.array([1.0, 0.0])
    p = np.array([0.8, 0.3])
    expected = -(np.log(0.8) + np.log(0.7)) / 2
    assert _eval("binary_logloss", y, p)[0][1] == pytest.approx(expected)


def test_binary_error():
    y = np.array([1.0, 0.0, 1.0, 0.0])
    p = np.array([0.8, 0.3, 0.2, 0.9])
    assert _eval("binary_error", y, p)[0][1] == pytest.approx(0.5)


def test_multi_logloss():
    y = np.array([0.0, 1.0])
    prob = np.array([[0.7, 0.2, 0.1], [0.1, 0.6, 0.3]])
    expected = -(np.log(0.7) + np.log(0.6)) / 2
    cfg = Config.from_params({"metric": "multi_logloss", "num_class": 3,
                              "objective": "multiclass"})
    ms = create_metrics(cfg)
    meta = Metadata(2)
    meta.set_label(y)
    ms[0].init(meta, 2)
    assert ms[0].eval(prob, prob)[0][1] == pytest.approx(expected)


def test_ndcg():
    # one query, perfect ranking -> ndcg = 1
    y = np.array([3.0, 2.0, 1.0, 0.0])
    raw = np.array([4.0, 3.0, 2.0, 1.0])
    res = _eval("ndcg", y, raw, group=np.array([4]), eval_at=[2, 4])
    assert res[0][0] == "ndcg@2"
    assert res[0][1] == pytest.approx(1.0)
    assert res[1][1] == pytest.approx(1.0)
    # inverted ranking -> ndcg < 1
    res2 = _eval("ndcg", y, -raw, group=np.array([4]), eval_at=[4])
    assert res2[0][1] < 1.0


def test_map():
    y = np.array([1.0, 0.0, 1.0, 0.0])
    raw = np.array([4.0, 3.0, 2.0, 1.0])  # relevant at positions 1,3
    res = _eval("map", y, raw, group=np.array([4]), eval_at=[4])
    expected = (1.0 / 1.0 + 2.0 / 3.0) / 2.0
    assert res[0][1] == pytest.approx(expected)


def test_r2():
    y = np.array([1.0, 2.0, 3.0, 4.0])
    assert _eval("r2", y, y)[0][1] == pytest.approx(1.0)
    assert _eval("r2", y, np.full(4, y.mean()))[0][1] == pytest.approx(0.0)


def test_mape():
    y = np.array([100.0, 200.0])
    p = np.array([110.0, 180.0])
    assert _eval("mape", y, p)[0][1] == pytest.approx((0.1 + 0.1) / 2)


def test_average_precision():
    y = np.array([1.0, 0.0, 1.0, 0.0])
    p = np.array([0.9, 0.8, 0.7, 0.1])
    res = _eval("average_precision", y, p)
    expected = (1.0 + 2.0 / 3.0) / 2.0
    assert res[0][1] == pytest.approx(expected)


def test_higher_better_flags():
    y = np.array([0.0, 1.0])
    p = np.array([0.2, 0.8])
    assert _eval("auc", y, p)[0][2] is True
    assert _eval("binary_logloss", y, p)[0][2] is False
