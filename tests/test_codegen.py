"""Codegen oracle tests (codegen.py model_to_if_else).

The emitted standalone C++ must route every row to the SAME leaf as the
tree-parallel device engine (ops/predict.py) — per tree, exactly —
including categorical bitset splits and all three missing-value types.
The serve/ low-latency path and the C++ route are the two small-batch
serving options, so they must agree on decision semantics.

Strictness tiers:
- per-tree: C++ ``PredictTreeRows`` raw leaf outputs (f64) vs the
  engine's leaf INDICES gathered into the host f64 leaf values —
  bit-exact equality (leaf routing has no rounding once inputs are
  f32-representable, which the test data is by construction).
- aggregate: C++ ``PredictRows`` accumulates in f64, the packed device
  ensemble in f32 — agreement at f32 resolution.
"""

import ctypes
import shutil
import subprocess

import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.codegen import model_to_if_else
from lightgbm_tpu.model_io import load_model_from_string
from lightgbm_tpu.ops.predict import pack_ensemble, predict_leaf_index

pytestmark = [
    pytest.mark.quick,
    pytest.mark.skipif(shutil.which("g++") is None,
                       reason="g++ not available"),
]


def _data(n=300, f=8, seed=0, nans=False, zeros=False, cats=False):
    rng = np.random.RandomState(seed)
    # f32-representable values: the engine compares in f32, the C++ in
    # f64 — exactly-representable inputs make leaf routing identical
    x = rng.randn(n, f).astype(np.float32).astype(np.float64)
    if cats:
        x[:, 0] = rng.randint(0, 12, n)
        x[:, 1] = rng.randint(0, 5, n)
    if nans:
        x[::7, 2] = np.nan
    if zeros:
        x[::5, 3] = 0.0
    y = ((np.nan_to_num(x[:, 2]) + x[:, 4]
          + (x[:, 0] % 3 == 1) * 2.0 + (x[:, 1] == 2) * 1.5)
         > 1.0).astype(np.float64)
    return x, y


def _loaded(x, y, extra=None, rounds=5, categorical=None):
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1}
    params.update(extra or {})
    ds = lgb.Dataset(x, label=y, params=params,
                     categorical_feature=categorical or "auto")
    bst = lgb.train(params, ds, num_boost_round=rounds)
    return load_model_from_string(bst.model_to_string())


def _compile(tmp_path, model) -> ctypes.CDLL:
    src = model_to_if_else(model, extern_c=True)
    cpp = tmp_path / "pred.cpp"
    cpp.write_text(src)
    so = tmp_path / "pred.so"
    # -O0: parity is optimization-independent and compile time is the
    # dominant test cost (test_cli.py keeps an -O2 compile)
    subprocess.run(["g++", "-O0", "-shared", "-fPIC", str(cpp),
                    "-o", str(so)], check=True)
    lib = ctypes.CDLL(str(so))
    dptr = ctypes.POINTER(ctypes.c_double)
    lib.PredictRows.argtypes = [dptr, ctypes.c_longlong,
                                ctypes.c_longlong, dptr]
    lib.PredictTreeRows.argtypes = [ctypes.c_longlong, dptr,
                                    ctypes.c_longlong, ctypes.c_longlong,
                                    dptr]
    lib.GetNumClass.restype = ctypes.c_longlong
    lib.GetNumTrees.restype = ctypes.c_longlong
    return lib


def _dptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def _cpp_rows(lib, x, k=1):
    x = np.ascontiguousarray(x)
    out = np.zeros((x.shape[0], k))
    lib.PredictRows(_dptr(x), x.shape[0], x.shape[1], _dptr(out))
    return out[:, 0] if k == 1 else out


def _cpp_tree(lib, tree_idx, x):
    x = np.ascontiguousarray(x)
    out = np.zeros(x.shape[0])
    lib.PredictTreeRows(tree_idx, _dptr(x), x.shape[0], x.shape[1],
                        _dptr(out))
    return out


def _assert_pertree_parity(lib, model, x):
    """Every tree, every row: C++ leaf output == the engine's routed
    leaf's (host f64) value, bit-exact."""
    ens = pack_ensemble(model.trees, max(model.num_tree_per_iteration, 1))
    leaves = np.asarray(predict_leaf_index(ens, jnp.asarray(x, jnp.float32)))
    for i, tree in enumerate(model.trees):
        want = tree.leaf_value[leaves[:, i]]
        np.testing.assert_array_equal(
            _cpp_tree(lib, i, x), want,
            err_msg=f"tree {i} routed differently in C++ vs engine")


@pytest.mark.parametrize("variant", ["missing_none", "missing_nan",
                                     "missing_zero"])
def test_pertree_parity_all_missing_types(tmp_path, variant):
    x, y = _data(nans=variant == "missing_nan",
                 zeros=variant == "missing_zero")
    extra = {}
    if variant == "missing_zero":
        extra["zero_as_missing"] = True
    elif variant == "missing_none":
        extra["use_missing"] = False
    model = _loaded(x, y, extra)
    lib = _compile(tmp_path, model)
    assert lib.GetNumTrees() == len(model.trees)
    _assert_pertree_parity(lib, model, x)


def test_pertree_parity_categorical(tmp_path):
    x, y = _data(cats=True, nans=True)
    model = _loaded(x, y, {"min_data_per_group": 2, "cat_smooth": 1.0},
                    categorical=[0, 1])
    assert any(t.num_cat > 0 for t in model.trees), "no categorical splits"
    lib = _compile(tmp_path, model)
    _assert_pertree_parity(lib, model, x)
    # unseen / out-of-range category values must also agree (bitset
    # range check vs the engine's in_range mask)
    xq = x.copy()
    xq[:40, 0] = np.asarray([99, 1e6, -3, 31, 32, 63, 64, 12] * 5)
    _assert_pertree_parity(lib, model, xq)


def test_aggregate_matches_engine_binary(tmp_path):
    x, y = _data(nans=True, zeros=True)
    model = _loaded(x, y)
    lib = _compile(tmp_path, model)
    got = _cpp_rows(lib, x)
    want = model.predict(x, raw_score=True)
    # C++ sums in f64, the packed device ensemble in f32: agreement is
    # at f32 resolution, not bitwise (same contract as test_cli.py)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_aggregate_matches_engine_multiclass(tmp_path):
    x, _ = _data(n=400)
    rng = np.random.RandomState(3)
    y = rng.randint(0, 3, 400).astype(np.float64)
    model = _loaded(x, y, {"objective": "multiclass", "num_class": 3,
                           "num_leaves": 7}, rounds=4)
    lib = _compile(tmp_path, model)
    assert lib.GetNumClass() == 3
    got = _cpp_rows(lib, x, k=3)
    want = model.predict(x, raw_score=True)
    assert want.shape == (400, 3)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
    _assert_pertree_parity(lib, model, x)
