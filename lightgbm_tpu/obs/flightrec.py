"""Crash flight recorder: a bounded ring of recent structured events,
atomically dumped to disk on DriftError / NonFiniteError / SIGTERM /
exit-75 / process exit and on demand — so every postmortem ships with a
black box (ISSUE 16).

Event sources (all gated on a single ``armed`` attribute check so the
disabled path costs one branch):

* ``boosting.train_one_iter`` — one ``iteration`` event per call;
* ``engine.train`` — ``resume`` / ``checkpoint`` / ``preempt`` /
  ``sigterm`` transitions and ``health_anomaly`` on a propagating
  DriftError/NonFiniteError (the anomaly triggers an immediate dump);
* ``serve/server.py`` — per-request outcomes including degradation
  errors (load shed, deadline, circuit open) and the SIGTERM drain
  (``serve_drain`` / ``serve_drained``);
* ``serve/fleet.py`` — replica quarantine/reinstate transitions,
  failovers, hedges, parity violations, and fleet drain events — a
  fleet postmortem names which replica died and when the router
  noticed;
* ``resilience/watchdog.py`` — ``watchdog_heartbeat_miss`` (with an
  immediate postmortem dump) when a heartbeat collective blows its
  deadline; engine.train adds the ``peer_lost`` escalation event;
* ``resilience/faults.py`` — every injected fault.

Arming: ``LGBM_TPU_FLIGHTREC=/path/dump.json`` (dump target; a bare
``1`` records to the default path ``flightrec.json`` in the cwd) or
``global_flightrec.enable(path)``. ``LGBM_TPU_FLIGHTREC_EVENTS`` sizes
the ring (default 512). Recording never raises and dumping never masks
the real outcome — the same contract as the rest of the obs stack.

Dump format (``validate_dump`` checks it; tools/check_profile.py and
tests/test_profile.py consume it)::

    {"format": "lightgbm_tpu.flightrec.v1",
     "reason": "<why the dump happened>",
     "dumped_at_unix": <float>,
     "host": {...hostenv.host_labels()...},
     "n_recorded": <total events ever recorded>,
     "n_dropped": <events evicted from the ring>,
     "events": [{"seq": int, "ts_unix": float, "kind": str,
                 "iteration": int?, ...payload}, ...]}

Writes are atomic (tmp + ``os.replace``) so a crash mid-dump never
leaves a truncated black box.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

FORMAT = "lightgbm_tpu.flightrec.v1"
DEFAULT_CAPACITY = 512
_ENV_PATH = "LGBM_TPU_FLIGHTREC"
_ENV_CAPACITY = "LGBM_TPU_FLIGHTREC_EVENTS"


def _jsonable(value: Any) -> Any:
    """Best-effort JSON-safe coercion; the recorder must accept any
    payload without raising."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    try:
        return float(value)  # numpy scalars
    except Exception:
        return repr(value)[:200]


class FlightRecorder:
    """Bounded in-memory ring of structured events with atomic dumps.

    ``armed`` is the one-attribute fast gate every instrumentation site
    checks before paying for an event append."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.armed = False
        self.path: Optional[str] = None
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        self._n_dumps = 0
        self._atexit_installed = False

    # -- lifecycle ----------------------------------------------------
    def enable(self, path: Optional[str] = None,
               capacity: Optional[int] = None) -> None:
        """Arm recording. ``path`` is the default dump target; when set,
        an atexit hook dumps whatever the ring holds at process exit
        (reason ``atexit``) unless a dump already happened."""
        with self._lock:
            if capacity is not None and \
                    capacity != self._ring.maxlen:
                self._ring = collections.deque(
                    self._ring, maxlen=max(int(capacity), 8))
            if path:
                self.path = path
        self.armed = True
        if self.path and not self._atexit_installed:
            self._atexit_installed = True
            atexit.register(self._at_exit)

    def disable(self) -> None:
        self.armed = False

    def reset(self) -> None:
        """Testing hook: drop all state but keep the atexit handle."""
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._n_dumps = 0
        self.armed = False
        self.path = None

    # -- recording ----------------------------------------------------
    def record(self, kind: str, iteration: Optional[int] = None,
               **payload: Any) -> None:
        """Append one event; silently drops the oldest when full.
        Never raises (telemetry must never kill training/serving)."""
        if not self.armed:
            return
        try:
            ev: Dict[str, Any] = {"seq": self._seq, "ts_unix": time.time(),
                                  "kind": str(kind)}
            if iteration is not None:
                ev["iteration"] = int(iteration)
            for k, v in payload.items():
                ev[k] = _jsonable(v)
            with self._lock:
                ev["seq"] = self._seq
                self._seq += 1
                self._ring.append(ev)
        except Exception:
            pass

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    # -- dumping ------------------------------------------------------
    def dump(self, path: Optional[str] = None,
             reason: str = "on_demand") -> Optional[str]:
        """Atomically write the ring to ``path`` (default: the armed
        path). Returns the written path, or None when there is nowhere
        to write. Never raises."""
        target = path or self.path
        if not target:
            return None
        try:
            with self._lock:
                events = list(self._ring)
                seq = self._seq
            try:
                from ..hostenv import host_labels
                host = host_labels()
            except Exception:
                host = {}
            doc = {"format": FORMAT, "reason": str(reason),
                   "dumped_at_unix": time.time(), "host": host,
                   "n_recorded": seq,
                   "n_dropped": max(seq - len(events), 0),
                   "events": events}
            parent = os.path.dirname(os.path.abspath(target))
            if parent and not os.path.isdir(parent):
                os.makedirs(parent, exist_ok=True)
            tmp = target + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(doc, fh, indent=1)
                fh.write("\n")
            os.replace(tmp, target)
            with self._lock:
                self._n_dumps += 1
            return target
        except Exception:
            return None

    def maybe_dump(self, reason: str = "on_demand") -> Optional[str]:
        """Dump iff armed with a target and at least one event; the
        crash-path helper (exit-75, health anomalies, atexit)."""
        if not self.armed:
            return None
        with self._lock:
            empty = not self._ring
        if empty:
            return None
        return self.dump(reason=reason)

    def _at_exit(self) -> None:
        # the black box flushes at process exit when nothing dumped it
        # earlier — a hard crash postmortem still has the tail events
        if self.armed and self._n_dumps == 0:
            self.maybe_dump(reason="atexit")


def validate_dump(doc: Any) -> List[str]:
    """-> list of schema violations (empty = valid). Importable by
    tools/check_profile.py and tests; no side effects."""
    errors: List[str] = []
    if not isinstance(doc, dict):
        return [f"dump is {type(doc).__name__}, expected object"]
    if doc.get("format") != FORMAT:
        errors.append(f"format is {doc.get('format')!r}, expected {FORMAT!r}")
    if not isinstance(doc.get("reason"), str) or not doc.get("reason"):
        errors.append("missing non-empty string 'reason'")
    if not isinstance(doc.get("dumped_at_unix"), (int, float)):
        errors.append("missing numeric 'dumped_at_unix'")
    for key in ("n_recorded", "n_dropped"):
        if not isinstance(doc.get(key), int) or doc[key] < 0:
            errors.append(f"missing non-negative int {key!r}")
    events = doc.get("events")
    if not isinstance(events, list):
        return errors + ["missing 'events' list"]
    last_seq = -1
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errors.append(f"event {i} is not an object")
            continue
        if not isinstance(ev.get("kind"), str) or not ev["kind"]:
            errors.append(f"event {i} lacks a string 'kind'")
        if not isinstance(ev.get("ts_unix"), (int, float)):
            errors.append(f"event {i} lacks numeric 'ts_unix'")
        seq = ev.get("seq")
        if not isinstance(seq, int):
            errors.append(f"event {i} lacks int 'seq'")
        elif seq <= last_seq:
            errors.append(f"event {i} seq {seq} not increasing "
                          f"(prev {last_seq})")
        else:
            last_seq = seq
        if "iteration" in ev and not isinstance(ev["iteration"], int):
            errors.append(f"event {i} has non-int 'iteration'")
    return errors


def _capacity_from_env() -> int:
    try:
        return max(int(os.environ.get(_ENV_CAPACITY, DEFAULT_CAPACITY)), 8)
    except ValueError:
        return DEFAULT_CAPACITY


global_flightrec = FlightRecorder(capacity=_capacity_from_env())

_env_target = os.environ.get(_ENV_PATH, "")
if _env_target and _env_target not in ("0", "false", "off"):
    global_flightrec.enable(
        path=_env_target if _env_target != "1" else "flightrec.json")
