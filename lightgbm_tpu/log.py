"""Logging facade (ref: include/LightGBM/utils/log.h:89 `Log`,
python-package register_logger in basic.py).

Levels mirror the reference (Fatal < Warning < Info < Debug); the
threshold is driven by Config.verbosity exactly as the reference maps it
(config.h verbosity: <0 fatal, 0 warning+error, 1 info, >1 debug). A
custom logger object or callback can be registered, as with
``lightgbm.register_logger``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

FATAL = -1
WARNING = 0
INFO = 1
DEBUG = 2

_LEVEL_NAMES = {FATAL: "Fatal", WARNING: "Warning", INFO: "Info",
                DEBUG: "Debug"}

_level = INFO
_logger: Optional[Any] = None
_info_method = "info"
_warning_method = "warning"
_debug_method: Optional[str] = None


def set_verbosity(verbosity: int) -> None:
    """Map Config.verbosity onto the log threshold
    (ref: c_api.cpp LGBM_BoosterResetParameter verbosity handling)."""
    global _level
    if verbosity < 0:
        _level = FATAL
    elif verbosity == 0:
        _level = WARNING
    elif verbosity == 1:
        _level = INFO
    else:
        _level = DEBUG


def register_logger(logger: Any, info_method_name: str = "info",
                    warning_method_name: str = "warning",
                    debug_method_name: Optional[str] = None) -> None:
    """Replace the default print-based output with a custom logger
    (ref: python-package/lightgbm/basic.py register_logger).

    ``debug_method_name`` optionally routes Debug-level messages to a
    dedicated method; when omitted, Debug falls back to the info method
    (but still through the registered logger — Debug never bypasses it).
    """
    for name in (info_method_name, warning_method_name):
        if not callable(getattr(logger, name, None)):
            raise TypeError(
                f"Logger must provide a callable {name}() method")
    if debug_method_name is not None and \
            not callable(getattr(logger, debug_method_name, None)):
        raise TypeError(
            f"Logger must provide a callable {debug_method_name}() method")
    global _logger, _info_method, _warning_method, _debug_method
    _logger = logger
    _info_method = info_method_name
    _warning_method = warning_method_name
    _debug_method = debug_method_name


def _emit(level: int, msg: str, force: bool = False) -> None:
    if level > _level and not force:
        return
    if _logger is not None:
        if level <= WARNING:
            meth = _warning_method
        elif level >= DEBUG and _debug_method is not None:
            meth = _debug_method
        else:
            meth = _info_method
        getattr(_logger, meth)(msg)
    else:
        print(f"[LightGBM-TPU] [{_LEVEL_NAMES[level]}] {msg}", flush=True)


def debug(msg: str) -> None:
    _emit(DEBUG, msg)


def info(msg: str, force: bool = False) -> None:
    """force=True bypasses the level gate — for output the user
    explicitly asked for (e.g. an attached log_evaluation callback),
    matching the reference python package where callback prints route
    through _log_info regardless of the lib verbosity param."""
    _emit(INFO, msg, force)


def warning(msg: str) -> None:
    _emit(WARNING, msg)


def fatal(msg: str) -> None:
    """Log and raise (ref: Log::Fatal always throws, log.h:89)."""
    _emit(FATAL, msg)
    from .basic import LightGBMError
    raise LightGBMError(msg)


def check(condition: bool, msg: str = "check failed") -> None:
    """CHECK macro analog (ref: utils/log.h:44)."""
    if not condition:
        fatal(msg)
