"""Host-side feature binning.

Re-implementation of the reference binning semantics
(ref: include/LightGBM/bin.h:86 BinMapper, src/io/bin.cpp:81 GreedyFindBin,
src/io/bin.cpp:247 FindBinWithZeroAsOneBin, src/io/bin.cpp:316 FindBin) in
NumPy. Binning runs once on the host at Dataset construction; the result is
a dense feature-major bin tensor shipped to the TPU (the analog of
CUDARowData, include/LightGBM/cuda/cuda_row_data.hpp:33).

Semantics preserved:
  - greedy quantile bins: each distinct value its own bin when few distincts;
    otherwise ~equal-count bins, with any single value holding >= mean bin
    count isolated in its own bin;
  - zero always gets its own bin (zero threshold +/-1e-35);
  - missing handling None/Zero/NaN: NaN values get a dedicated last bin
    (missing_type NAN) or map to the zero bin (zero_as_missing);
  - categorical: categories sorted by frequency, capped at max_bin, rare
    categories filtered;
  - trivial features (single bin) are dropped from training.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

K_ZERO_THRESHOLD = 1e-35
MISSING_NONE, MISSING_ZERO, MISSING_NAN = 0, 1, 2
_MISSING_NAMES = {MISSING_NONE: "none", MISSING_ZERO: "zero", MISSING_NAN: "nan"}


def _greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                     max_bin: int, total_cnt: int,
                     min_data_in_bin: int) -> List[float]:
    """Upper bounds for ~equal-count bins over sorted distinct values
    (ref: src/io/bin.cpp:81). Returns list of upper bounds; last is +inf."""
    num_distinct = len(distinct_values)
    bounds: List[float] = []
    if num_distinct == 0:
        return [np.inf]
    if num_distinct <= max_bin:
        # each distinct value gets a bin, merging tiny bins forward
        cur_cnt = 0
        for i in range(num_distinct - 1):
            cur_cnt += counts[i]
            if cur_cnt >= min_data_in_bin:
                bounds.append((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                cur_cnt = 0
        bounds.append(np.inf)
        return bounds

    # greedy: targets of mean size; isolate heavy hitters
    max_bin = max(1, max_bin)
    mean_bin_size = total_cnt / max_bin
    is_big = counts >= mean_bin_size
    rest_cnt = total_cnt - counts[is_big].sum()
    rest_bins = max_bin - int(is_big.sum())
    if rest_bins > 0:
        mean_bin_size = rest_cnt / rest_bins

    bin_cnt = 0
    bins_left = max_bin
    for i in range(num_distinct):
        bin_cnt += counts[i]
        # close the bin if: heavy hitter, reached target size, or the next
        # value is heavy (so it starts its own bin)
        next_big = is_big[i + 1] if i + 1 < num_distinct else False
        if i == num_distinct - 1:
            break
        if is_big[i] or bin_cnt >= mean_bin_size or \
                (next_big and bin_cnt >= max(1.0, mean_bin_size * 0.5)):
            if bin_cnt >= min_data_in_bin or is_big[i]:
                bounds.append((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                bin_cnt = 0
                bins_left -= 1
                if bins_left <= 1:
                    break
    bounds.append(np.inf)
    return bounds


class BinMapper:
    """Per-feature value <-> bin mapping (ref: include/LightGBM/bin.h:86)."""

    def __init__(self):
        self.num_bins: int = 1
        self.is_categorical: bool = False
        self.missing_type: int = MISSING_NONE
        self.bin_upper_bound: Optional[np.ndarray] = None  # numerical
        self.cat_bin_to_value: Optional[np.ndarray] = None  # categorical
        self.cat_value_to_bin: Optional[dict] = None
        self.default_bin: int = 0      # bin of value 0.0
        self.most_freq_bin: int = 0
        self.min_value: float = 0.0
        self.max_value: float = 0.0
        self.is_trivial: bool = True

    # ------------------------------------------------------------------
    def fit(self, values: np.ndarray, *, max_bin: int = 255,
            min_data_in_bin: int = 3, use_missing: bool = True,
            zero_as_missing: bool = False,
            is_categorical: bool = False,
            forced_bounds: Optional[Sequence[float]] = None) -> "BinMapper":
        values = np.asarray(values, dtype=np.float64)
        na_mask = np.isnan(values)
        na_cnt = int(na_mask.sum())
        clean = values[~na_mask]
        self.is_categorical = is_categorical

        if is_categorical:
            self._fit_categorical(clean, na_cnt, max_bin, min_data_in_bin,
                                  use_missing)
            return self

        # missing type resolution (ref: bin.cpp:316 FindBin)
        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        elif na_cnt > 0:
            self.missing_type = MISSING_NAN
        else:
            self.missing_type = MISSING_NONE

        if zero_as_missing:
            # zeros (and NaN) are treated as missing -> zero bin
            clean = clean[np.abs(clean) > K_ZERO_THRESHOLD]

        if clean.size == 0:
            self.bin_upper_bound = np.array([np.inf])
            self.num_bins = 1 + (1 if self.missing_type == MISSING_NAN else 0)
            self._finalize_numerical(values, na_cnt)
            return self

        self.min_value = float(clean.min())
        self.max_value = float(clean.max())

        if forced_bounds is not None and len(forced_bounds) > 0:
            inner = sorted(float(b) for b in forced_bounds
                           if self.min_value < b < self.max_value)
            bounds = inner + [np.inf]
        else:
            # native fast path (bit-identical; see native/src) — before
            # np.unique, which is the dominant cost it replaces
            from . import native as _native
            nb = _native.find_numerical_bounds(
                values, max_bin, min_data_in_bin, self.missing_type,
                zero_as_missing)
            if nb is not None:
                self.bin_upper_bound = nb
                self.num_bins = len(nb)
                if self.missing_type == MISSING_NAN:
                    self.num_bins += 1
                self._finalize_numerical(values, na_cnt)
                return self

            distinct, counts = np.unique(clean, return_counts=True)
            bounds = self._bounds_from_distinct(distinct, counts, max_bin,
                                                min_data_in_bin)

        self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
        self.num_bins = len(bounds)
        if self.missing_type == MISSING_NAN:
            self.num_bins += 1  # dedicated NaN bin at the end
        self._finalize_numerical(values, na_cnt)
        return self

    def _bounds_from_distinct(self, distinct: np.ndarray, counts: np.ndarray,
                              max_bin: int, min_data_in_bin: int) -> List[float]:
        """Numerical bounds from sorted distinct values + counts.

        Zero-as-one-bin (ref: bin.cpp:247): bin the negative and positive
        halves separately, keep [-eps, eps] as zero's own bin. Shared by
        the dense fit() path and fit_sparse() (which injects the implicit
        zero count instead of materializing a dense column).
        """
        neg = distinct < -K_ZERO_THRESHOLD
        pos = distinct > K_ZERO_THRESHOLD
        zero_cnt = int(counts[~neg & ~pos].sum())
        n_neg, n_pos = int(neg.sum()), int(pos.sum())
        avail = max_bin - 1  # reserve NaN bin later via max_bin arg below
        if self.missing_type == MISSING_NAN:
            avail = max(avail, 1)
        else:
            avail = max_bin
        # share bins between halves proportional to distinct counts
        left_max = int(round(avail * n_neg / max(n_neg + n_pos, 1)))
        left_max = min(max(left_max, 1 if n_neg else 0), avail - (1 if n_pos else 0))
        right_max = avail - left_max - 1  # -1 for the zero bin
        bounds: List[float] = []
        if n_neg:
            lb = _greedy_find_bin(distinct[neg], counts[neg],
                                  max(left_max, 1), int(counts[neg].sum()),
                                  min_data_in_bin)
            bounds.extend(b for b in lb[:-1])
            bounds.append(-K_ZERO_THRESHOLD)
        if n_pos:
            bounds.append(K_ZERO_THRESHOLD)
            rb = _greedy_find_bin(distinct[pos], counts[pos],
                                  max(right_max, 1), int(counts[pos].sum()),
                                  min_data_in_bin)
            bounds.extend(b for b in rb[:-1])
        elif zero_cnt or n_neg:
            bounds.append(K_ZERO_THRESHOLD)
        bounds.append(np.inf)
        return sorted(set(bounds))

    def fit_sparse(self, nz_values: np.ndarray, num_rows: int, *,
                   max_bin: int = 255, min_data_in_bin: int = 3,
                   use_missing: bool = True, zero_as_missing: bool = False,
                   forced_bounds: Optional[Sequence[float]] = None
                   ) -> "BinMapper":
        """Fit a NUMERICAL mapper from a sparse column: the explicit
        nonzero sample values plus `num_rows - len(nz_values)` implicit
        zeros, without ever materializing the dense column (the analog of
        the reference binning CSC columns through their iterators,
        src/io/dataset_loader.cpp:1080 + sparse_bin.hpp:74)."""
        nz = np.asarray(nz_values, dtype=np.float64).reshape(-1)
        na_mask = np.isnan(nz)
        na_cnt = int(na_mask.sum())
        nz = nz[~na_mask]
        zero_cnt = int(num_rows) - len(nz) - na_cnt
        self.is_categorical = False

        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        elif na_cnt > 0:
            self.missing_type = MISSING_NAN
        else:
            self.missing_type = MISSING_NONE

        # zeros excluded from BOUNDS when zero_as_missing (they count as
        # missing), but they still land in the default bin at transform
        # time, so they must still feed the bin-occupancy stats
        stats_zero_cnt = 0
        if zero_as_missing:
            small = np.abs(nz) <= K_ZERO_THRESHOLD
            stats_zero_cnt = int(small.sum()) + zero_cnt  # explicit + implicit
            nz = nz[~small]
            zero_cnt = 0

        distinct, counts = np.unique(nz, return_counts=True)
        if zero_cnt > 0:
            at = int(np.searchsorted(distinct, 0.0))
            if at < len(distinct) and distinct[at] == 0.0:
                counts = counts.copy()
                counts[at] += zero_cnt
            else:
                distinct = np.insert(distinct, at, 0.0)
                counts = np.insert(counts, at, zero_cnt)

        if distinct.size == 0:
            self.bin_upper_bound = np.array([np.inf])
            self.num_bins = 1 + (1 if self.missing_type == MISSING_NAN else 0)
            self._finalize_from_distinct(distinct, counts, na_cnt,
                                         stats_zero_cnt)
            return self

        self.min_value = float(distinct[0])
        self.max_value = float(distinct[-1])
        if forced_bounds is not None and len(forced_bounds) > 0:
            inner = sorted(float(b) for b in forced_bounds
                           if self.min_value < b < self.max_value)
            bounds = inner + [np.inf]
        else:
            bounds = self._bounds_from_distinct(distinct, counts, max_bin,
                                                min_data_in_bin)
        self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
        self.num_bins = len(bounds)
        if self.missing_type == MISSING_NAN:
            self.num_bins += 1
        self._finalize_from_distinct(distinct, counts, na_cnt,
                                     stats_zero_cnt)
        return self

    def _finalize_from_distinct(self, distinct: np.ndarray,
                                counts: np.ndarray, na_cnt: int,
                                zero_as_missing_cnt: int = 0) -> None:
        """default/most-frequent bin + triviality from distinct+counts —
        the sparse twin of _finalize_numerical. `zero_as_missing_cnt`
        holds zeros excluded from the bounds (zero_as_missing mode);
        like the dense path's transform they still occupy the default
        bin for occupancy stats."""
        self.default_bin = int(np.searchsorted(self.bin_upper_bound, 0.0,
                                               side="left"))
        bc = np.zeros(self.num_bins, np.int64)
        if distinct.size:
            dbins = self.transform(distinct)
            np.add.at(bc, dbins, counts.astype(np.int64))
        if na_cnt and self.missing_type == MISSING_NAN:
            bc[self.num_bins - 1] += na_cnt
        elif na_cnt:
            bc[self.default_bin] += na_cnt
        bc[self.default_bin] += zero_as_missing_cnt
        self.most_freq_bin = int(bc.argmax()) if bc.size else 0
        self.is_trivial = int((bc > 0).sum()) <= 1

    def _finalize_numerical(self, values: np.ndarray, na_cnt: int) -> None:
        self.default_bin = int(np.searchsorted(self.bin_upper_bound, 0.0,
                                               side="left"))
        binned = self.transform(values)
        if binned.size:
            bc = np.bincount(binned, minlength=self.num_bins)
            self.most_freq_bin = int(bc.argmax())
            effective = int(np.count_nonzero(bc))
        else:
            effective = 1
        self.is_trivial = effective <= 1

    def _fit_categorical(self, clean: np.ndarray, na_cnt: int, max_bin: int,
                         min_data_in_bin: int, use_missing: bool) -> None:
        # (ref: bin.cpp FindBin categorical branch): categories sorted by
        # frequency, capped at max_bin; negative values treated as missing.
        cats = clean[clean >= 0].astype(np.int64)
        self.missing_type = (MISSING_NAN
                             if (na_cnt > 0 or clean.size != cats.size)
                             and use_missing else MISSING_NONE)
        if cats.size:
            distinct, counts = np.unique(cats, return_counts=True)
            order = np.argsort(-counts, kind="stable")
            distinct, counts = distinct[order], counts[order]
            keep = min(len(distinct), max_bin - 1)
            # drop ultra-rare categories like the reference's 99.9% cut
            total = counts.sum()
            cum = np.cumsum(counts)
            cut = int(np.searchsorted(cum, total * 0.999)) + 1
            keep = min(keep, max(cut, 1))
            distinct = distinct[:keep]
        else:
            distinct = np.array([], dtype=np.int64)
        # bin 0 = "other / missing"; known categories from bin 1
        self.cat_bin_to_value = distinct
        self.cat_value_to_bin = {int(v): i + 1 for i, v in enumerate(distinct)}
        order2 = np.argsort(distinct, kind="stable")
        self._cat_sorted_vals = distinct[order2]
        self._cat_sorted_bins = (order2 + 1).astype(np.int32)
        self.num_bins = 1 + len(distinct)
        self.default_bin = 0
        self.most_freq_bin = 1 if len(distinct) else 0
        self.is_trivial = self.num_bins <= 2
        if cats.size:
            self.min_value = float(distinct.min())
            self.max_value = float(distinct.max())

    # ------------------------------------------------------------------
    def transform(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value -> bin (ref: BinMapper::ValueToBin)."""
        values = np.asarray(values, dtype=np.float64)
        if self.is_categorical:
            out = np.zeros(values.shape, dtype=np.int32)
            if self.cat_bin_to_value is not None and len(self.cat_bin_to_value):
                ok = np.isfinite(values) & (values >= 0)
                iv = np.where(ok, values, -1).astype(np.int64)
                pos = np.searchsorted(self._cat_sorted_vals, iv)
                pos = np.clip(pos, 0, len(self._cat_sorted_vals) - 1)
                hit = ok & (self._cat_sorted_vals[pos] == iv)
                out = np.where(hit, self._cat_sorted_bins[pos], 0).astype(np.int32)
            return out

        if values.size >= 65536 and values.ndim == 1:
            from . import native as _native
            nb = _native.transform_column(
                values, self.bin_upper_bound, self.missing_type,
                self.default_bin, self.num_bins)
            if nb is not None:
                return nb
        na_mask = np.isnan(values)
        if self.missing_type == MISSING_ZERO:
            values = np.where(na_mask, 0.0, values)
            na_mask = np.zeros_like(na_mask)
        bins = np.searchsorted(self.bin_upper_bound, values, side="left")
        bins = np.clip(bins, 0, len(self.bin_upper_bound) - 1)
        if self.missing_type == MISSING_NAN:
            bins = np.where(na_mask, self.num_bins - 1, bins)
        else:
            bins = np.where(na_mask, self.default_bin, bins)
        return bins.astype(np.int32)

    def bin_to_value(self, bin_idx: int) -> float:
        """Threshold value for model serialization (ref: BinMapper::BinToValue)."""
        if self.is_categorical:
            if 1 <= bin_idx <= len(self.cat_bin_to_value):
                return float(self.cat_bin_to_value[bin_idx - 1])
            return -1.0
        ub = self.bin_upper_bound
        if bin_idx >= len(ub):
            return float("inf")
        return float(ub[bin_idx])

    @property
    def missing_name(self) -> str:
        return _MISSING_NAMES[self.missing_type]

    def feature_info_str(self) -> str:
        """Feature info for the model header (ref: gbdt_model_text.cpp
        feature_infos: `[min:max]` numerical, colon list categorical)."""
        if self.is_trivial:
            return "none"
        if self.is_categorical:
            return ":".join(str(int(v)) for v in self.cat_bin_to_value)
        return f"[{self.min_value:g}:{self.max_value:g}]"
