"""Device TreeSHAP (ops/shap.py + the shap.py dispatch): parity vs the
host recursive oracle across the fixture matrix, the additivity
invariant, prediction-window slicing, shape-stable recompile behavior,
and the served ``explain`` route's bit-parity contract.

Tolerances: the device kernel evaluates the permutation-weight
recurrences in f32 (the f64 merged-path algorithm is exact to ~1e-13;
the f32 noise floor is ~5e-4 relative), so parity against the f64 host
recursion is asserted at 2e-3 relative — bit-parity is only claimed
between the two DEVICE routes (direct predict_contrib vs served
explain), which execute the identical compiled program.
"""

import asyncio
import os
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import shap as shap_mod
from lightgbm_tpu.obs.metrics import global_metrics
from lightgbm_tpu.ops.shap import (MAX_CHUNK_ROWS, SHAP_TRACE_TAG,
                                   shap_row_bucket)

pytestmark = pytest.mark.quick

TOL = 2e-3  # f32 recurrence vs the f64 recursive oracle


def _train(x, y, extra=None, rounds=8, categorical=None):
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1}
    params.update(extra or {})
    ds = lgb.Dataset(x, label=y, params=params,
                     categorical_feature=categorical or "auto")
    return lgb.train(params, ds, num_boost_round=rounds)


def _oracle(bst, data, start=0, num=-1):
    g = bst._gbdt
    k = max(getattr(g, "num_tree_per_iteration", 1), 1)
    f = bst.num_feature()
    return shap_mod._contrib_over_trees(
        lambda it, ki: g.models[it][ki], g.current_iteration(), k,
        np.asarray(data, np.float64), f, start, num)


def _assert_close(dev, oracle):
    scale = max(np.abs(oracle).max(), 1.0)
    err = np.abs(np.asarray(dev) - oracle).max() / scale
    assert err <= TOL, f"device vs oracle rel err {err:g}"


def _nan_data(n=500, f=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f)
    x[::7, 2] = np.nan
    y = ((np.nan_to_num(x[:, 2]) + x[:, 4]) > 0.5).astype(np.float64)
    return x, y


# ----------------------------------------------------------------------
# parity matrix: device kernel vs the host recursion
class TestOracleParity:
    def test_binary_with_nans(self):
        x, y = _nan_data()
        bst = _train(x, y)
        dev = bst.predict(x[:200], pred_contrib=True)
        _assert_close(dev, _oracle(bst, x[:200]))

    def test_multiclass_layout_and_parity(self):
        from conftest import make_multiclass
        x, y = make_multiclass(n=800, f=8, k=4)
        params = {"objective": "multiclass", "num_class": 4,
                  "num_leaves": 15, "min_data_in_leaf": 5,
                  "verbosity": -1}
        bst = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                        num_boost_round=5)
        dev = bst.predict(x[:150], pred_contrib=True)
        # reference layout: K blocks of (F + 1) columns per row
        assert dev.shape == (150, 4 * (8 + 1))
        _assert_close(dev, _oracle(bst, x[:150]))

    def test_categorical_bitset(self):
        rng = np.random.RandomState(1)
        n, f = 600, 6
        x = rng.randn(n, f)
        x[:, 0] = rng.randint(0, 12, n)  # categorical columns
        x[:, 1] = rng.randint(0, 40, n)  # spills past one bitset word
        y = ((x[:, 0] % 3 == 1) * 2.0 + (x[:, 1] > 20) * 1.5
             + x[:, 3] > 1.0).astype(np.float64)
        bst = _train(x, y, categorical=[0, 1])
        probe = x[:150].copy()
        probe[5, 0] = 99.0   # out-of-range category
        probe[6, 1] = -3.0   # negative -> out of range
        dev = bst.predict(probe, pred_contrib=True)
        _assert_close(dev, _oracle(bst, probe))

    def test_dart_shrinkage_invalidates_pack(self):
        # DART renormalizes leaf values BETWEEN iterations — the pack's
        # identity tokens must catch the in-place mutation, or contribs
        # would come from stale path tables
        x, y = _nan_data(seed=3)
        bst = _train(x, y, extra={"boosting": "dart", "drop_rate": 0.3,
                                  "drop_seed": 7}, rounds=10)
        dev = bst.predict(x[:120], pred_contrib=True)
        _assert_close(dev, _oracle(bst, x[:120]))


class TestMissingTypeMatrix:
    """All three reference missing routings: None, Zero, NaN."""

    def test_missing_none(self):
        rng = np.random.RandomState(2)
        x = rng.randn(500, 6)
        y = (x[:, 0] + x[:, 1] > 0.3).astype(np.float64)
        bst = _train(x, y, extra={"use_missing": False})
        dev = bst.predict(x[:150], pred_contrib=True)
        _assert_close(dev, _oracle(bst, x[:150]))

    def test_missing_zero(self):
        rng = np.random.RandomState(4)
        x = rng.randn(500, 6)
        x[::5, 1] = 0.0
        y = ((x[:, 0] > 0) & (x[:, 1] != 0)).astype(np.float64)
        bst = _train(x, y, extra={"zero_as_missing": True})
        probe = x[:150].copy()
        probe[3, 0] = np.nan  # NaN routes like zero under MissingType.Zero
        dev = bst.predict(probe, pred_contrib=True)
        _assert_close(dev, _oracle(bst, probe))

    def test_missing_nan(self):
        x, y = _nan_data(seed=5)
        bst = _train(x, y)
        probe = x[:150].copy()
        probe[::3, 4] = np.nan  # NaNs on a feature with no train NaNs
        dev = bst.predict(probe, pred_contrib=True)
        _assert_close(dev, _oracle(bst, probe))


# ----------------------------------------------------------------------
# invariants
class TestInvariants:
    def test_additivity(self):
        x, y = _nan_data(seed=6)
        bst = _train(x, y)
        dev = bst.predict(x[:200], pred_contrib=True)
        raw = bst.predict(x[:200], raw_score=True)
        err = np.abs(dev.sum(axis=1) - raw).max() / max(
            np.abs(raw).max(), 1.0)
        assert err <= TOL, f"additivity rel err {err:g}"

    def test_iteration_slicing_parity(self):
        x, y = _nan_data(seed=7)
        bst = _train(x, y, rounds=10)
        for start, num in ((0, 4), (3, 5), (2, -1)):
            dev = bst.predict(x[:100], pred_contrib=True,
                              start_iteration=start, num_iteration=num)
            _assert_close(dev, _oracle(bst, x[:100], start, num))

    def test_linear_trees_rejected(self):
        x, y = _nan_data(seed=8)
        x2 = np.nan_to_num(x)
        bst = _train(x2, y, extra={"linear_tree": True})
        with pytest.raises(ValueError, match="linear"):
            bst.predict(x2[:10], pred_contrib=True)

    def test_row_bucket_is_pow2_and_capped(self):
        assert shap_row_bucket(1, 4096) == 16      # lowlat floor
        assert shap_row_bucket(17, 4096) == 32
        assert shap_row_bucket(700, 4096) == 1024  # pow2, NOT grain 768
        assert shap_row_bucket(5000, 4096) == 4096  # chunk cap
        assert shap_row_bucket(100, 64) == 64
        assert MAX_CHUNK_ROWS == 4096


# ----------------------------------------------------------------------
# shape stability: uneven row counts must reuse the warm bucket set
class TestRecompileStability:
    def test_zero_steady_state_recompiles(self):
        x, y = _nan_data(seed=9)
        bst = _train(x, y)
        rng = np.random.RandomState(0)
        big = rng.randn(512, x.shape[1])
        for b in (16, 32, 64, 128, 256, 512):  # warm the pow2 ladder
            bst.predict(big[:b], pred_contrib=True)
        base = global_metrics.recompiles(SHAP_TRACE_TAG)
        for n in (1, 3, 16, 17, 129, 255, 256, 300, 511, 512, 7):
            bst.predict(big[:n], pred_contrib=True)
        assert global_metrics.recompiles(SHAP_TRACE_TAG) == base


# ----------------------------------------------------------------------
# served explain route
class TestServedExplain:
    def test_explain_bit_identical_to_direct(self):
        from lightgbm_tpu.serve import ModelRegistry, ModelServer

        x, y = _nan_data(seed=10)
        bst = _train(x, y)
        registry = ModelRegistry()
        registry.load("m", booster=bst)
        direct = registry.get("m").model
        server = ModelServer(registry, max_batch_rows=512,
                             max_wait_ms=1.0)
        rng = np.random.RandomState(1)
        xt = rng.randn(600, x.shape[1])
        xt[::9, 2] = np.nan
        sizes = (1, 40, 130, 3, 64, 200, 17)
        bounds = np.concatenate([[0], np.cumsum(sizes)])

        async def run():
            try:
                return await asyncio.gather(*[
                    server.explain("m", xt[bounds[i]:bounds[i + 1]])
                    for i in range(len(sizes))])
            finally:
                await server.close()

        outs = asyncio.run(run())
        for i, out in enumerate(outs):
            want = direct.predict_contrib(xt[bounds[i]:bounds[i + 1]])
            assert np.array_equal(out, want), f"request {i} diverged"

    def test_check_shap_tool(self, capsys):
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools"))
        import check_shap
        assert check_shap.main() == 0
