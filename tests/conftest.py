"""Test configuration: force an 8-device virtual CPU mesh so sharding
paths are exercised without TPU hardware (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the axon TPU plugin overrides JAX_PLATFORMS; force CPU explicitly
jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache, keyed on HLO: every Booster builds
# fresh jit partials, so identical programs recompile once per TEST
# without it. The disk cache dedupes them within one pytest run (the
# in-memory jit cache is per-callable and can't) and across runs — a
# warm cache cuts JAX-heavy files by ~40-50% (measured on
# test_quantized: 75s cold/uncached -> 39s warm), which is what lets
# the full tier-1 sweep fit its timeout. Opt out: LGBM_TPU_NO_JAX_CACHE=1.
if not os.environ.get("LGBM_TPU_NO_JAX_CACHE"):
    import tempfile
    _cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), "lgbm-tpu-jax-cache"))
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


# Quick tier (VERDICT r4 #9): `pytest -m quick` runs a <=15-min subset —
# one config per family + the semantics/unit tests — so verification
# stops competing with development; the full 2h+ grid stays the default
# `pytest tests/` (plus LGBM_TPU_FULL_CONSISTENCY=1 for the stochastic
# tier). Membership is per-module: every test in these files is cheap.
QUICK_FILES = {
    "test_binning.py", "test_bundling.py", "test_sparse.py",
    "test_native.py", "test_param_honesty.py", "test_objectives.py",
    "test_metrics.py", "test_model_io.py", "test_learner.py",
    "test_booster_surface.py", "test_ingestion.py", "test_waved.py",
    "test_predict_engine.py", "test_serve.py", "test_codegen.py",
    "test_bin_pack.py", "test_perf_gate.py", "test_memory_model.py",
    "test_obs_export.py", "test_health.py", "test_resilience.py",
    "test_stream.py", "test_coldstart.py", "test_profile.py",
    "test_fleet.py", "test_watchdog.py", "test_shap.py",
    "test_scatter.py",
}


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")
    config.addinivalue_line(
        "markers", "quick: <=15-min verification tier (see QUICK_FILES)")


def pytest_collection_modifyitems(config, items):
    import pytest as _pytest
    for item in items:
        if os.path.basename(str(item.fspath)) in QUICK_FILES:
            item.add_marker(_pytest.mark.quick)


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def make_regression(n=1000, f=8, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] * 2.0 - X[:, 1] + 0.5 * X[:, 2] ** 2
         + 0.1 * r.randn(n)).astype(np.float32)
    return X, y


def make_binary(n=1000, f=8, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    logit = X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.3 * X[:, 2] * X[:, 3]
    y = (logit + 0.2 * r.randn(n) > 0.5).astype(np.float32)
    return X, y


def make_multiclass(n=1200, f=8, k=4, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    centers = r.randn(k, f) * 2.0
    d = ((X[:, None, :] - centers[None]) ** 2).sum(-1)
    y = np.argmin(d + 0.5 * r.randn(n, k), axis=1).astype(np.float32)
    return X, y


def make_ranking(num_queries=50, docs_per_query=20, f=6, seed=0):
    r = np.random.RandomState(seed)
    n = num_queries * docs_per_query
    X = r.randn(n, f)
    rel = X[:, 0] + 0.5 * X[:, 1] + 0.3 * r.randn(n)
    y = np.zeros(n, np.float32)
    for q in range(num_queries):
        s = q * docs_per_query
        seg = rel[s:s + docs_per_query]
        qs = np.quantile(seg, [0.5, 0.75, 0.9])
        y[s:s + docs_per_query] = np.digitize(seg, qs)
    group = np.full(num_queries, docs_per_query)
    return X, y, group
