#!/usr/bin/env python
"""CI smoke for the reduce-scatter data-parallel learner
(tpu_hist_reduce=scatter; parallel/scatter.py + the sharded builders in
learner.py).

Three assertions, mirroring tools/check_shap.py for the scatter
subsystem:

1. **Oracle bit-parity**: a quick data-parallel train with
   ``tpu_hist_reduce=scatter`` produces a ``model_to_string`` that is
   BYTE-identical to the full-histogram psum oracle on the virtual
   8-device CPU mesh — the whole point of the embed-at-oracle-shape
   split search (ref: data_parallel_tree_learner.cpp:287-297).
2. **Wire payload**: the runtime collective counters (obs/health.py)
   show the scatter histogram collective carrying exactly 1/W of the
   psum oracle's bytes at the same issue count, and the winner
   exchange gathering exactly one SplitInfo per shard per searched
   record — O(W * sizeof(SplitInfo)), not O(L * F * B).
3. **Metrics lint**: the rendered OpenMetrics document carries the new
   collective tags (``hist/psum_scatter``, ``split/allgather_best``)
   under the ``lgbmtpu_health_collective_*`` families, and the booster
   publishes the modeled ``collective_reduction`` meta that bench.py
   folds into its JSON line.

Skips (exit 0 with a notice) when fewer than 2 devices are visible —
the scatter mode demotes itself to psum there, so there is nothing to
check. Exit 0 = pass. Usage: python tools/check_scatter.py
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> int:
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.learner import collective_traffic_model
    from lightgbm_tpu.obs.export import render_openmetrics
    from lightgbm_tpu.obs.health import global_health
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.ops.split import split_info_nbytes

    width = len(jax.devices())
    if width < 2:
        print("check_scatter: skipped (single device — scatter demotes "
              "to psum)")
        return 0

    failures = 0
    rng = np.random.RandomState(0)
    n, f = 512, 8
    x = rng.randn(n, f)
    y = (x[:, 0] * 2.0 - x[:, 1] + 0.5 * x[:, 2] ** 2
         + 0.1 * rng.randn(n)).astype(np.float32)
    # pallas impl so the psum oracle also routes through the
    # instrumented shard_map builder (the GSPMD xla path's collectives
    # are partitioner-inserted and carry no runtime counters)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "tree_learner": "data",
              "tpu_hist_impl": "pallas", "verbosity": -1}
    rounds = 3

    def train(reduce):
        bst = lgb.train({**params, "tpu_hist_reduce": reduce},
                        lgb.Dataset(x, label=y), num_boost_round=rounds)
        return bst, {t: dict(e) for t, e in global_health.runtime.items()}

    global_health.reset()
    global_health.enable()
    try:
        bst_psum, psum_rt = train("psum")
        global_health.reset()
        bst_scat, scat_rt = train("scatter")
        doc = render_openmetrics()
    finally:
        global_health.disable()
        global_health.reset()

    # 1. bit-parity vs the psum oracle (the echoed knob line itself is
    # the one legitimate difference)
    def model_str(bst):
        return "\n".join(l for l in bst.model_to_string().splitlines()
                         if not l.startswith("[tpu_hist_reduce:"))

    if model_str(bst_scat) != model_str(bst_psum):
        print("FAIL: scatter model differs from the psum oracle "
              "(model_to_string mismatch)")
        failures += 1

    # 2. the wire payload actually shrank
    pw = psum_rt.get("hist/psum_wave")
    sc = scat_rt.get("hist/psum_scatter")
    ag = scat_rt.get("split/allgather_best")
    if pw is None or sc is None or ag is None:
        print(f"FAIL: runtime counters missing (psum tags "
              f"{sorted(psum_rt)}, scatter tags {sorted(scat_rt)})")
        failures += 1
    else:
        if sc["calls"] != pw["calls"] or sc["bytes"] * width != pw["bytes"]:
            print(f"FAIL: scatter hist collective not 1/{width} of the "
                  f"psum bytes at equal issue count (psum {pw}, "
                  f"scatter {sc})")
            failures += 1
        shape = bst_scat._gbdt._resolved_hist_shape()
        model = collective_traffic_model(
            num_features=f, max_bins=shape["max_bins"],
            num_leaves=params["num_leaves"], wave_max=shape["wave_max"],
            width=width, reduction="scatter")
        want_ag = rounds * model["split_collective_bytes_per_iter"]
        if ag["bytes"] != want_ag:
            print(f"FAIL: winner all_gather carried {ag['bytes']} B, "
                  f"model says {want_ag} B "
                  f"({width} shards x {split_info_nbytes(shape['max_bins'])}"
                  f" B per searched record)")
            failures += 1
        if ag["bytes"] + sc["bytes"] >= pw["bytes"]:
            print(f"FAIL: scatter total ({ag['bytes']} + {sc['bytes']} B) "
                  f"did not undercut the psum oracle ({pw['bytes']} B)")
            failures += 1

    # 3. OpenMetrics lint + published byte model
    for needle in ('tag="hist/psum_scatter"', 'tag="split/allgather_best"',
                   "lgbmtpu_health_collective_bytes_total"):
        if needle not in doc:
            print(f"FAIL: {needle} missing from the rendered OpenMetrics "
                  "document")
            failures += 1
    ct = global_metrics.meta.get("collective_traffic")
    red = global_metrics.meta.get("collective_reduction")
    if not ct or ct.get("reduction") != "scatter":
        print(f"FAIL: booster did not publish scatter collective_traffic "
              f"meta (got {ct})")
        failures += 1
    elif red is None or red < 1.8:
        print(f"FAIL: published collective_reduction {red} < 1.8x")
        failures += 1

    if failures:
        print(f"check_scatter: {failures} failure(s)")
        return 1
    print(f"check_scatter: OK (bit-parity with the psum oracle on "
          f"{width} shards, hist collective bytes /{width}, winner "
          f"exchange {ag['bytes']} B = {rounds} iters x {width} x "
          f"SplitInfo, modeled reduction {red:.2f}x)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
