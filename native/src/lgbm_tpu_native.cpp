// lightgbm_tpu native host runtime: text parsing, quantile binning,
// multithreaded bin transform.
//
// TPU-native counterpart of the reference's C++ IO layer
// (ref: src/io/parser.hpp CSV/TSV/LibSVM parsers, src/io/bin.cpp:81
// GreedyFindBin / :247 FindBinWithZeroAsOneBin, BinMapper::ValueToBin).
// The compute path (histograms, split search) lives in XLA/Pallas; this
// library covers the host-side data plane the reference implements in
// C++: turning text into a dense matrix and a matrix into the bin tensor
// that ships to the device. Exposed as a C ABI consumed via ctypes.
//
// Semantics intentionally bit-match lightgbm_tpu/binning.py (the portable
// fallback); tests assert equality between the two paths.

#include <algorithm>
#include <atomic>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <memory>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr double kZeroThreshold = 1e-35;
constexpr int kMissingNone = 0;
constexpr int kMissingZero = 1;
constexpr int kMissingNan = 2;

inline double FastAtof(const char* p, const char** end) {
  char* e = nullptr;
  double v = std::strtod(p, &e);
  *end = e;
  if (e == p) v = std::numeric_limits<double>::quiet_NaN();
  return v;
}

inline bool IsNaToken(const char* s, size_t len) {
  if (len == 0) return true;
  if (len == 1 && *s == '?') return true;
  static const char* kTokens[] = {"na", "nan", "null", "none"};
  char buf[8];
  if (len >= sizeof(buf)) return false;
  for (size_t i = 0; i < len; ++i) buf[i] = std::tolower(s[i]);
  buf[len] = 0;
  for (const char* t : kTokens)
    if (std::strcmp(buf, t) == 0) return true;
  return false;
}

size_t NumThreads() {
  unsigned hc = std::thread::hardware_concurrency();
  return hc ? hc : 4;
}

// Run fn(t, begin, end) over [0, n) split across threads.
template <typename F>
void ParallelFor(size_t n, F fn) {
  size_t nt = std::min(NumThreads(), n ? n : size_t(1));
  if (nt <= 1) {
    fn(0, 0, n);
    return;
  }
  std::vector<std::thread> threads;
  size_t chunk = (n + nt - 1) / nt;
  for (size_t t = 0; t < nt; ++t) {
    size_t b = t * chunk, e = std::min(n, b + chunk);
    if (b >= e) break;
    threads.emplace_back(fn, t, b, e);
  }
  for (auto& th : threads) th.join();
}

struct ParseResult {
  std::vector<double> data;   // row-major [n, f]
  std::vector<double> label;  // [n]
  int64_t num_rows = 0;
  int32_t num_cols = 0;  // feature count (label excluded)
  std::string error;
};

// ---------------------------------------------------------------------
// Parsing. Format detection mirrors io/text_loader.py: a token with ':'
// after the first -> libsvm; '\t' -> tsv; ',' -> csv.
// ---------------------------------------------------------------------

std::vector<std::pair<const char*, const char*>> SplitLines(
    const char* buf, size_t len) {
  std::vector<std::pair<const char*, const char*>> lines;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* le = nl ? nl : end;
    const char* trimmed = le;
    while (trimmed > p && (trimmed[-1] == '\r' || trimmed[-1] == ' ')) {
      --trimmed;
    }
    bool blank = true;
    for (const char* q = p; q < trimmed; ++q) {
      if (!std::isspace(static_cast<unsigned char>(*q))) { blank = false; break; }
    }
    if (!blank) lines.emplace_back(p, trimmed);
    if (!nl) break;
    p = nl + 1;
  }
  return lines;
}

char DetectSep(const char* b, const char* e, bool* is_libsvm) {
  *is_libsvm = false;
  bool first_token_done = false;
  for (const char* p = b; p < e; ++p) {
    if (*p == ':' && first_token_done) { *is_libsvm = true; return ' '; }
    if (*p == '\t' || *p == ' ' || *p == ',') first_token_done = true;
  }
  for (const char* p = b; p < e; ++p) if (*p == '\t') return '\t';
  for (const char* p = b; p < e; ++p) if (*p == ',') return ',';
  return '\t';
}

void ParseDelimitedRow(const char* b, const char* e, char sep,
                       std::vector<double>* out) {
  const char* p = b;
  while (p <= e) {
    const char* q = p;
    while (q < e && *q != sep) ++q;
    size_t len = q - p;
    if (IsNaToken(p, len)) {
      out->push_back(std::numeric_limits<double>::quiet_NaN());
    } else {
      const char* fe;
      out->push_back(FastAtof(p, &fe));
    }
    if (q >= e) break;
    p = q + 1;
  }
}

ParseResult* ParseBuffer(const char* buf, size_t len, int label_idx,
                         int has_header) {
  auto res = std::make_unique<ParseResult>();
  auto lines = SplitLines(buf, len);
  if (lines.empty()) {
    res->error = "empty data file";
    return res.release();
  }
  // scan up to 10 lines; stop at the first line with a definitive
  // signal (a label-only row must not hide a LibSVM file; mirrors
  // text_loader._detect_format)
  bool is_libsvm = false;
  char sep = '\t';
  size_t probe_n = std::min<size_t>(lines.size(), 10);
  for (size_t i = 0; i < probe_n; ++i) {
    const char* b = lines[i].first;
    const char* e = lines[i].second;
    bool lsvm = false;
    char s = DetectSep(b, e, &lsvm);
    if (lsvm) { is_libsvm = true; break; }
    bool has_sep = false;
    for (const char* p = b; p < e; ++p) {
      if (*p == '\t' || *p == ',') { has_sep = true; break; }
    }
    if (has_sep) { sep = s; break; }
  }
  size_t start = 0;
  if (has_header && !is_libsvm) start = 1;
  size_t n = lines.size() - start;
  res->num_rows = static_cast<int64_t>(n);
  res->label.assign(n, 0.0);

  if (is_libsvm) {
    // pass 1: max feature index (parallel)
    std::vector<int32_t> maxf(NumThreads(), -1);
    ParallelFor(n, [&](size_t t, size_t b, size_t e) {
      int32_t mx = -1;
      for (size_t i = b; i < e; ++i) {
        const char* p = lines[start + i].first;
        const char* le = lines[start + i].second;
        while (p < le) {
          const char* colon = static_cast<const char*>(
              memchr(p, ':', le - p));
          if (!colon) break;
          const char* ks = colon;
          while (ks > p && ks[-1] != ' ' && ks[-1] != '\t') --ks;
          int32_t k = std::atoi(std::string(ks, colon - ks).c_str());
          mx = std::max(mx, k);
          p = colon + 1;
        }
      }
      maxf[t] = std::max(maxf[t], mx);
    });
    int32_t f = 0;
    for (int32_t m : maxf) f = std::max(f, m + 1);
    res->num_cols = f;
    res->data.assign(n * static_cast<size_t>(f), 0.0);
    ParallelFor(n, [&](size_t, size_t b, size_t e) {
      for (size_t i = b; i < e; ++i) {
        const char* p = lines[start + i].first;
        const char* le = lines[start + i].second;
        const char* fe;
        res->label[i] = FastAtof(p, &fe);
        p = fe;
        double* row = res->data.data() + i * static_cast<size_t>(f);
        while (p < le) {
          while (p < le && (*p == ' ' || *p == '\t')) ++p;
          const char* colon = static_cast<const char*>(
              memchr(p, ':', le - p));
          if (!colon) break;
          int32_t k = std::atoi(std::string(p, colon - p).c_str());
          double v = FastAtof(colon + 1, &fe);
          if (k >= 0 && k < f) row[k] = v;
          p = fe;
        }
      }
    });
    return res.release();
  }

  // delimited: column count from first data row
  std::vector<double> probe;
  ParseDelimitedRow(lines[start].first, lines[start].second, sep, &probe);
  int32_t total_cols = static_cast<int32_t>(probe.size());
  if (label_idx < 0 || label_idx >= total_cols) {
    res->error = "label_column out of range";
    return res.release();
  }
  int32_t f = total_cols - 1;
  res->num_cols = f;
  res->data.assign(n * static_cast<size_t>(f), 0.0);
  std::atomic<bool> bad_row{false};
  ParallelFor(n, [&](size_t, size_t b, size_t e) {
    std::vector<double> vals;
    vals.reserve(total_cols);
    for (size_t i = b; i < e; ++i) {
      vals.clear();
      ParseDelimitedRow(lines[start + i].first, lines[start + i].second,
                        sep, &vals);
      if (static_cast<int32_t>(vals.size()) != total_cols) {
        bad_row = true;
        continue;
      }
      res->label[i] = vals[label_idx];
      double* row = res->data.data() + i * static_cast<size_t>(f);
      int32_t c = 0;
      for (int32_t j = 0; j < total_cols; ++j) {
        if (j == label_idx) continue;
        row[c++] = vals[j];
      }
    }
  });
  if (bad_row) res->error = "inconsistent column count across rows";
  return res.release();
}

// ---------------------------------------------------------------------
// Binning: GreedyFindBin + zero-as-one-bin composition, matching
// binning.py bit for bit.
// ---------------------------------------------------------------------

void GreedyFindBin(const double* dv, const double* cnt, int64_t nd,
                   int max_bin, int64_t total_cnt, int min_data_in_bin,
                   std::vector<double>* bounds) {
  const double kInf = std::numeric_limits<double>::infinity();
  if (nd == 0) {
    bounds->push_back(kInf);
    return;
  }
  if (nd <= max_bin) {
    double cur = 0;
    for (int64_t i = 0; i < nd - 1; ++i) {
      cur += cnt[i];
      if (cur >= min_data_in_bin) {
        bounds->push_back((dv[i] + dv[i + 1]) / 2.0);
        cur = 0;
      }
    }
    bounds->push_back(kInf);
    return;
  }
  max_bin = std::max(1, max_bin);
  double mean_bin_size = static_cast<double>(total_cnt) / max_bin;
  std::vector<bool> is_big(nd);
  double big_sum = 0;
  int64_t n_big = 0;
  for (int64_t i = 0; i < nd; ++i) {
    is_big[i] = cnt[i] >= mean_bin_size;
    if (is_big[i]) { big_sum += cnt[i]; ++n_big; }
  }
  int64_t rest_bins = max_bin - n_big;
  if (rest_bins > 0) {
    mean_bin_size = (total_cnt - big_sum) / static_cast<double>(rest_bins);
  }
  double bin_cnt = 0;
  int64_t bins_left = max_bin;
  for (int64_t i = 0; i < nd; ++i) {
    bin_cnt += cnt[i];
    bool next_big = (i + 1 < nd) ? is_big[i + 1] : false;
    if (i == nd - 1) break;
    if (is_big[i] || bin_cnt >= mean_bin_size ||
        (next_big && bin_cnt >= std::max(1.0, mean_bin_size * 0.5))) {
      if (bin_cnt >= min_data_in_bin || is_big[i]) {
        bounds->push_back((dv[i] + dv[i + 1]) / 2.0);
        bin_cnt = 0;
        if (--bins_left <= 1) break;
      }
    }
  }
  bounds->push_back(kInf);
}

}  // namespace

extern "C" {

// ------------------------------ parsing ------------------------------

void* LGT_ParseFile(const char* path, int label_idx, int has_header) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) {
    auto* res = new ParseResult();
    res->error = std::string("cannot open file: ") + path;
    return res;
  }
  std::fseek(fp, 0, SEEK_END);
  long sz = std::ftell(fp);
  std::fseek(fp, 0, SEEK_SET);
  std::vector<char> buf(static_cast<size_t>(sz) + 1);
  size_t rd = std::fread(buf.data(), 1, sz, fp);
  std::fclose(fp);
  buf[rd] = 0;
  return ParseBuffer(buf.data(), rd, label_idx, has_header);
}

int64_t LGT_ParseNumRows(void* h) {
  return static_cast<ParseResult*>(h)->num_rows;
}
int32_t LGT_ParseNumCols(void* h) {
  return static_cast<ParseResult*>(h)->num_cols;
}
const char* LGT_ParseError(void* h) {
  ParseResult* r = static_cast<ParseResult*>(h);
  return r->error.empty() ? nullptr : r->error.c_str();
}
void LGT_ParseCopy(void* h, double* data_out, double* label_out) {
  ParseResult* r = static_cast<ParseResult*>(h);
  std::memcpy(data_out, r->data.data(), r->data.size() * sizeof(double));
  std::memcpy(label_out, r->label.data(), r->label.size() * sizeof(double));
}
void LGT_ParseFree(void* h) { delete static_cast<ParseResult*>(h); }

// ------------------------------ binning ------------------------------

// Numerical bounds with zero-as-one-bin (ref: bin.cpp:247). `values` may
// contain NaN. Returns the number of bounds written to `bounds_out`
// (capacity must be >= max_bin + 2), or -1 on error.
int32_t LGT_FindNumericalBounds(const double* values, int64_t n,
                                int max_bin, int min_data_in_bin,
                                int missing_type, int zero_as_missing,
                                double* bounds_out) {
  const double kInf = std::numeric_limits<double>::infinity();
  std::vector<double> clean;
  clean.reserve(n);
  for (int64_t i = 0; i < n; ++i) {
    double v = values[i];
    if (std::isnan(v)) continue;
    if (zero_as_missing && std::fabs(v) <= kZeroThreshold) continue;
    clean.push_back(v);
  }
  if (clean.empty()) {
    bounds_out[0] = kInf;
    return 1;
  }
  std::sort(clean.begin(), clean.end());
  // distinct + counts
  std::vector<double> dv;
  std::vector<double> cnt;
  dv.reserve(clean.size());
  for (double v : clean) {
    if (dv.empty() || v != dv.back()) {
      dv.push_back(v);
      cnt.push_back(1);
    } else {
      cnt.back() += 1;
    }
  }
  int64_t nd = static_cast<int64_t>(dv.size());

  int64_t n_neg = 0, n_pos = 0;
  for (double v : dv) {
    if (v < -kZeroThreshold) ++n_neg;
    else if (v > kZeroThreshold) ++n_pos;
  }
  int64_t zero_distincts = nd - n_neg - n_pos;
  double neg_cnt = 0, pos_cnt = 0, zero_cnt = 0;
  for (int64_t i = 0; i < nd; ++i) {
    if (dv[i] < -kZeroThreshold) neg_cnt += cnt[i];
    else if (dv[i] > kZeroThreshold) pos_cnt += cnt[i];
    else zero_cnt += cnt[i];
  }

  int avail = (missing_type == kMissingNan)
      ? std::max(max_bin - 1, 1) : max_bin;
  // share bins between halves proportional to distinct counts
  // (mirror of binning.py: round-half-even via nearbyint to match
  // Python round())
  double ratio = static_cast<double>(n_neg) /
      std::max<int64_t>(n_neg + n_pos, 1);
  int left_max = static_cast<int>(std::nearbyint(avail * ratio));
  left_max = std::min(std::max(left_max, n_neg ? 1 : 0),
                      avail - (n_pos ? 1 : 0));
  int right_max = avail - left_max - 1;

  std::vector<double> bounds;
  if (n_neg) {
    std::vector<double> lb;
    GreedyFindBin(dv.data(), cnt.data(), n_neg, std::max(left_max, 1),
                  static_cast<int64_t>(neg_cnt), min_data_in_bin, &lb);
    for (size_t i = 0; i + 1 < lb.size(); ++i) bounds.push_back(lb[i]);
    bounds.push_back(-kZeroThreshold);
  }
  if (n_pos) {
    bounds.push_back(kZeroThreshold);
    std::vector<double> rb;
    int64_t pos_start = nd - n_pos;
    GreedyFindBin(dv.data() + pos_start, cnt.data() + pos_start, n_pos,
                  std::max(right_max, 1), static_cast<int64_t>(pos_cnt),
                  min_data_in_bin, &rb);
    for (size_t i = 0; i + 1 < rb.size(); ++i) bounds.push_back(rb[i]);
  } else if (zero_cnt > 0 || n_neg) {
    bounds.push_back(kZeroThreshold);
  }
  bounds.push_back(kInf);
  std::sort(bounds.begin(), bounds.end());
  bounds.erase(std::unique(bounds.begin(), bounds.end()), bounds.end());
  (void)zero_distincts;

  int32_t nb = static_cast<int32_t>(bounds.size());
  if (nb > max_bin + 2) nb = max_bin + 2;
  std::memcpy(bounds_out, bounds.data(), nb * sizeof(double));
  return nb;
}

}  // extern "C"

namespace {

// lower_bound index as a branchless comparison count — auto-vectorizes
// (the per-value loop over <=255 sorted bounds turns into a handful of
// SIMD compares), unlike the branchy binary search it replaces.
inline int32_t CountBin(const double* bounds, int32_t nb, double v) {
  if (nb > 512) {  // wide-bin fallback: binary search wins again
    const double* it = std::lower_bound(bounds, bounds + nb, v);
    return static_cast<int32_t>(it - bounds);
  }
  int32_t c = 0;
  for (int32_t k = 0; k < nb; ++k) c += bounds[k] < v ? 1 : 0;
  return c;
}

// One feature's binning parameters — the single place the per-value
// missing-type + searchsorted + clamp semantics live (shared by the
// column, v1-matrix, and v2-matrix entry points).
struct FeatureBinSpec {
  const double* bounds;
  int32_t nb;
  int32_t missing_type;
  int32_t default_bin;
  int32_t num_bins;
};

inline int32_t BinOne(const FeatureBinSpec& s, double v) {
  bool isnan = std::isnan(v);
  if (s.missing_type == kMissingZero && isnan) {
    v = 0.0;
    isnan = false;
  }
  if (isnan) {
    return (s.missing_type == kMissingNan) ? s.num_bins - 1 : s.default_bin;
  }
  int32_t bin = CountBin(s.bounds, s.nb, v);
  return bin > s.nb - 1 ? s.nb - 1 : bin;
}

std::vector<FeatureBinSpec> BuildSpecs(int32_t f, const double* bounds_flat,
                                       const int64_t* bounds_offsets,
                                       const int32_t* missing_types,
                                       const int32_t* default_bins,
                                       const int32_t* num_bins) {
  std::vector<FeatureBinSpec> specs(f);
  for (int32_t j = 0; j < f; ++j) {
    specs[j] = {bounds_flat + bounds_offsets[j],
                static_cast<int32_t>(bounds_offsets[j + 1] -
                                     bounds_offsets[j]),
                missing_types[j], default_bins[j], num_bins[j]};
  }
  return specs;
}

template <typename T, typename OutT>
void TransformColMajor(const T* data, int64_t n, int32_t f,
                       const FeatureBinSpec* specs, OutT* out) {
  ParallelFor(static_cast<size_t>(f), [&](size_t, size_t b, size_t e) {
    for (size_t j = b; j < e; ++j) {
      const T* col = data + static_cast<int64_t>(j) * n;
      OutT* dst = out + static_cast<int64_t>(j) * n;
      const FeatureBinSpec s = specs[j];
      for (int64_t i = 0; i < n; ++i) {
        dst[i] = static_cast<OutT>(BinOne(s, static_cast<double>(col[i])));
      }
    }
  });
}

// Row-major input without a global transposed copy: row tiles are staged
// through an L2-resident per-feature buffer, so the [n, f] matrix is read
// exactly once sequentially while the output stays feature-major.
template <typename T, typename OutT>
void TransformRowMajor(const T* data, int64_t n, int32_t f,
                       const FeatureBinSpec* specs, OutT* out) {
  if (n == 0 || f == 0) return;
  const int64_t kTileElems = int64_t(1) << 17;  // ~1MB staged at f64
  int64_t tile = kTileElems / f;
  if (tile < 64) tile = 64;
  if (tile > n) tile = n;
  const int64_t num_tiles = (n + tile - 1) / tile;
  ParallelFor(static_cast<size_t>(num_tiles), [&](size_t, size_t tb,
                                                  size_t te) {
    std::vector<double> local(static_cast<size_t>(tile));
    for (size_t t = tb; t < te; ++t) {
      const int64_t r0 = static_cast<int64_t>(t) * tile;
      const int64_t rows = std::min(tile, n - r0);
      for (int32_t j = 0; j < f; ++j) {
        const T* src = data + r0 * f + j;
        for (int64_t i = 0; i < rows; ++i) {
          local[i] = static_cast<double>(src[i * f]);
        }
        OutT* dst = out + static_cast<int64_t>(j) * n + r0;
        const FeatureBinSpec s = specs[j];
        for (int64_t i = 0; i < rows; ++i) {
          dst[i] = static_cast<OutT>(BinOne(s, local[i]));
        }
      }
    }
  });
}

template <typename T>
void TransformDispatchOut(const T* data, int32_t row_major, int64_t n,
                          int32_t f, const FeatureBinSpec* specs,
                          int elem_size, void* out) {
  if (elem_size == 1) {
    auto* o = static_cast<uint8_t*>(out);
    row_major ? TransformRowMajor(data, n, f, specs, o)
              : TransformColMajor(data, n, f, specs, o);
  } else {
    auto* o = static_cast<uint16_t*>(out);
    row_major ? TransformRowMajor(data, n, f, specs, o)
              : TransformColMajor(data, n, f, specs, o);
  }
}

}  // namespace

extern "C" {

// value -> bin over one column (multithreaded searchsorted; ref:
// BinMapper::ValueToBin). bins_out is int32 [n].
void LGT_TransformColumn(const double* values, int64_t n,
                         const double* bounds, int32_t num_bounds,
                         int missing_type, int32_t default_bin,
                         int32_t num_bins, int32_t* bins_out) {
  const FeatureBinSpec s = {bounds, num_bounds, missing_type, default_bin,
                            num_bins};
  ParallelFor(static_cast<size_t>(n), [&](size_t, size_t b, size_t e) {
    for (size_t i = b; i < e; ++i) bins_out[i] = BinOne(s, values[i]);
  });
}

// v1 matrix binning: [n, f] float64 column-major only (kept for stale
// cached libraries' callers; new code uses LGT_TransformMatrix2).
void LGT_TransformMatrix(const double* data_cm, int64_t n, int32_t f,
                         const double* bounds_flat,
                         const int64_t* bounds_offsets,
                         const int32_t* missing_types,
                         const int32_t* default_bins,
                         const int32_t* num_bins, int elem_size,
                         void* bins_out_fm) {
  auto specs = BuildSpecs(f, bounds_flat, bounds_offsets, missing_types,
                          default_bins, num_bins);
  TransformDispatchOut(data_cm, /*row_major=*/0, n, f, specs.data(),
                       elem_size, bins_out_fm);
}

// v2 matrix binning: accepts float32 or float64 input in row- or
// column-major order directly (the v1 entry point forced callers into a
// full float64 column-major copy — at 10.5M x 28 that copy alone cost
// seconds and 2.3 GB of traffic).
void LGT_TransformMatrix2(const void* data, int32_t is_f32,
                          int32_t row_major, int64_t n, int32_t f,
                          const double* bounds_flat,
                          const int64_t* bounds_offsets,
                          const int32_t* missing_types,
                          const int32_t* default_bins,
                          const int32_t* num_bins, int elem_size,
                          void* bins_out_fm) {
  auto specs = BuildSpecs(f, bounds_flat, bounds_offsets, missing_types,
                          default_bins, num_bins);
  if (is_f32) {
    TransformDispatchOut(static_cast<const float*>(data), row_major, n, f,
                         specs.data(), elem_size, bins_out_fm);
  } else {
    TransformDispatchOut(static_cast<const double*>(data), row_major, n, f,
                         specs.data(), elem_size, bins_out_fm);
  }
}

int32_t LGT_Version() { return 2; }

}  // extern "C"
