"""Streaming ingestion + the shared double-buffered host->device feed.

Two halves:

1. ``DatasetBuilder`` — chunked row pushes, the TPU-native analog of the
   reference's ChunkedArray + streaming C API
   (ref: include/LightGBM/utils/chunked_array.hpp, c_api.cpp:1330
   LGBM_DatasetPushRows*, tests/cpp_tests/test_stream.cpp:253).
   Producers push row blocks (with per-block label/weight/init-score/
   group slices) as they arrive; `finalize()` coalesces once and bins —
   the same copy-on-finalize contract ChunkedArray gives the
   reference's distributed ingestion (Spark/SynapseML streaming).

2. The **double-buffered feed** — ``double_buffered()`` stages item
   i+1's host->device transfer before the caller consumes item i, so
   upload overlaps device compute. This is the ONE pipeline
   implementation behind both the predict engine (ops/predict.py chunk
   feed) and out-of-core streaming training (``HostSlabBins`` slabs fed
   to the histogram/partition slab programs). ``StreamStats`` is the
   process-global accounting the bench `--stream` line and the
   ``lgbmtpu_stream_*`` OpenMetrics families read: slab/upload counts,
   upload vs kernel wall seconds, and the measured overlap ratio (the
   fraction of upload wall-time issued while device compute from the
   same pipeline was still in flight)."""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np


class StreamStats:
    """Process-global streaming-pipeline accounting (always-on, O(1)
    per slab). ``overlap_ratio`` is upload wall-time issued while >= 1
    dispatched-but-unconsumed device computation existed (``_inflight``
    clears at the next host sync, ``note_block``). That is DISPATCH
    overlap — an upper bound on true transfer/compute overlap (a
    dispatched program may already have finished when the upload
    starts; per-op completion would need device events we don't have).
    It still catches the realistic pipeline breakages: a feed that
    stages only after the host blocks (the double buffer wired out, or
    synchronous staging after a sync point) drops the ratio toward
    zero, which is what perf-gate check 9's floor guards."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.slabs_total = 0
        self.uploads_total = 0
        self.bytes_uploaded_total = 0
        self.upload_seconds_total = 0.0
        self.overlapped_uploads_total = 0
        self.overlapped_upload_seconds = 0.0
        self.kernel_seconds_total = 0.0
        self.waves_total = 0
        self.iterations_total = 0
        self._inflight = 0

    # -- pipeline hooks -------------------------------------------------
    def note_upload(self, seconds: float, nbytes: int) -> None:
        overlapped = self._inflight > 0
        self.uploads_total += 1
        self.bytes_uploaded_total += int(nbytes)
        self.upload_seconds_total += float(seconds)
        if overlapped:
            self.overlapped_uploads_total += 1
            self.overlapped_upload_seconds += float(seconds)

    def note_dispatch(self, n: int = 1) -> None:
        """A device computation consuming staged data was dispatched
        (async); uploads staged from now on overlap it."""
        self._inflight += n

    def note_block(self, seconds: float) -> None:
        """The host blocked `seconds` waiting on pipeline compute; all
        in-flight dispatches are now consumed."""
        self.kernel_seconds_total += float(seconds)
        self._inflight = 0

    @property
    def overlap_ratio(self) -> float:
        if self.upload_seconds_total <= 0.0:
            return 0.0
        return self.overlapped_upload_seconds / self.upload_seconds_total

    def summary(self) -> Dict[str, Any]:
        return {
            "slabs_total": self.slabs_total,
            "uploads_total": self.uploads_total,
            "bytes_uploaded_total": self.bytes_uploaded_total,
            "upload_seconds_total": round(self.upload_seconds_total, 6),
            "overlapped_uploads_total": self.overlapped_uploads_total,
            "overlapped_upload_seconds":
                round(self.overlapped_upload_seconds, 6),
            "kernel_seconds_total": round(self.kernel_seconds_total, 6),
            "overlap_ratio": round(self.overlap_ratio, 6),
            "waves_total": self.waves_total,
            "iterations_total": self.iterations_total,
        }


global_stream_stats = StreamStats()


def double_buffered(items, stage, stats: Optional[StreamStats] = None):
    """Yield ``stage(item)`` for each item, staging item i+1 BEFORE
    yielding item i — so the caller's (async) compute dispatch on item i
    overlaps item i+1's host->device transfer. This is the exact
    enqueue order the predict engine has always used (stage next, then
    dispatch current); factoring it here makes training slabs and
    predict chunks ride one pipeline implementation.

    ``stats`` (optional) times each stage call and classifies it as
    overlapped when the caller reported in-flight compute via
    ``stats.note_dispatch``."""
    items = list(items)
    if not items:
        return

    def timed_stage(item):
        if stats is None:
            return stage(item)
        t0 = time.perf_counter()
        out = stage(item)
        dt = time.perf_counter() - t0
        nbytes = 0
        for probe in (out if isinstance(out, tuple) else (out,)):
            nb = getattr(probe, "nbytes", None)
            if isinstance(nb, (int, np.integer)):
                nbytes += int(nb)
        stats.note_upload(dt, nbytes)
        return out

    nxt = timed_stage(items[0])
    for i in range(len(items)):
        cur = nxt
        nxt = timed_stage(items[i + 1]) if i + 1 < len(items) else None
        yield cur


class HostSlabBins:
    """Host-resident binned matrix cut into section-aligned row slabs —
    the out-of-core storage behind ``tpu_stream`` training.

    The full ``[F, N]`` bin tensor never ships to the device. Each slab
    covers a contiguous row range ``[lo, hi)`` and is stored host-side
    as its own section-aligned ``ops.bin_pack.PackedBins`` (or a raw
    uint8/uint16 slice when the bin width does not admit packing);
    ``feed()`` streams slabs through ``double_buffered`` so slab k+1's
    upload overlaps the fused histogram/partition program consuming
    slab k. With a device mesh, uploads land row-sharded over the data
    axis (mirroring the resident data-parallel layout) whenever the
    slab's row count divides the mesh.

    Flows through the growers in the ``bins_fm`` argument slot like
    ``PackedBins``/``SparseBins``; consumers dispatch on isinstance
    (the streamed grower is the only in-tree consumer).

    Host-RAM note: the slabs are COPIES of ``bins_fm`` rows (packed
    slabs halve them at ``max_bin <= 15``), and the dataset's own host
    matrix stays alive for the host-side tree paths (rollback, DART
    drops, binned leaf prediction) — so unpacked streaming costs up to
    2x bins in host RAM. On-disk slab paging via ``io/binary_format``
    is the ROADMAP follow-up for datasets bigger than host RAM."""

    def __init__(self, bins_fm: np.ndarray, max_bins: int, slab_rows: int,
                 pack: bool = True, mesh=None):
        from ..ops import bin_pack as bp
        self.num_features = int(bins_fm.shape[0])
        self.num_data = int(bins_fm.shape[1])
        self.max_bins = int(max_bins)
        self.bounds = bp.slab_bounds(self.num_data, slab_rows, max_bins)
        self.slab_rows = (self.bounds[0][1] - self.bounds[0][0]
                          if self.bounds else 0)
        self._slabs = [bp.pack_bins_range(bins_fm, max_bins, lo, hi, pack)
                       for lo, hi in self.bounds]
        first = self._slabs[0] if self._slabs else None
        self.vpb = getattr(first, "vpb", 1)
        self.mesh = mesh
        self.stats = global_stream_stats

    @property
    def n_slabs(self) -> int:
        return len(self._slabs)

    @property
    def shape(self):
        """Logical (num_features, num_data) — keeps bins_fm.shape[1]
        call sites working like PackedBins.shape does."""
        return (self.num_features, self.num_data)

    @property
    def nbytes_host(self) -> int:
        return sum(int(s.nbytes) for s in self._slabs)

    def _sharding(self, n_rows: int):
        if self.mesh is None or self.mesh.size <= 1:
            return None
        from ..parallel import mesh as mesh_lib
        if n_rows % self.mesh.size:
            return None  # uneven tail: replicated upload (GSPMD copes)
        return mesh_lib.data_sharding(self.mesh, ndim=2, row_axis=1)

    def stage(self, i: int):
        """Enqueue slab i's host->device transfer; returns the device
        slab (PackedBins with jnp data, or a jnp array)."""
        import jax
        from ..ops.bin_pack import PackedBins
        slab = self._slabs[i]
        lo, hi = self.bounds[i]
        if isinstance(slab, PackedBins):
            sh = self._sharding(slab.data.shape[1])
            data = (jax.device_put(slab.data, sh) if sh is not None
                    else jax.device_put(slab.data))
            return PackedBins(data, slab.num_data, slab.vpb)
        sh = self._sharding(hi - lo)
        return (jax.device_put(slab, sh) if sh is not None
                else jax.device_put(slab))

    def stage_noted(self, i: int):
        """``stage(i)`` with upload accounting (the single-upload path
        of the cross-iteration double buffer; ``feed()`` times its
        uploads through ``double_buffered`` instead)."""
        t0 = time.perf_counter()
        dev = self.stage(i)
        nb = getattr(dev, "nbytes", 0)
        self.stats.note_upload(time.perf_counter() - t0,
                               int(nb) if isinstance(nb, (int, np.integer))
                               else 0)
        self.stats.slabs_total += 1
        return dev

    def feed(self):
        """Double-buffered iterator over ``(slab_index, device_slab)``;
        upload timing/overlap recorded into ``global_stream_stats``."""
        self.stats.slabs_total += self.n_slabs
        idx = range(self.n_slabs)
        staged = double_buffered(
            idx, lambda i: (i, self.stage(i)), self.stats)
        for i, dev in staged:
            yield i, dev


class DatasetBuilder:
    """Accumulate row chunks, then produce a constructed Dataset.

    Example:
        b = DatasetBuilder(num_features=28, params={"max_bin": 63})
        for X_chunk, y_chunk in producer:
            b.push_rows(X_chunk, label=y_chunk)
        ds = b.finalize()
    """

    def __init__(self, num_features: int,
                 params: Optional[Dict[str, Any]] = None,
                 reference=None):
        self.num_features = int(num_features)
        self.params = dict(params or {})
        self.reference = reference
        self._chunks: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []
        self._weights: List[np.ndarray] = []
        self._init_scores: List[np.ndarray] = []
        self._groups: List[np.ndarray] = []
        self._finalized = False

    @property
    def num_pushed(self) -> int:
        return sum(c.shape[0] for c in self._chunks)

    def push_rows(self, data, label=None, weight=None, init_score=None,
                  group=None) -> "DatasetBuilder":
        """Append a [n, F] block (ref: LGBM_DatasetPushRows c_api.cpp).
        Metadata slices are per-block and optional, but each field must
        be provided either for every block or for none."""
        if self._finalized:
            raise RuntimeError("builder already finalized")
        block = np.atleast_2d(np.asarray(data, np.float64))
        if block.shape[1] != self.num_features:
            raise ValueError(
                f"pushed block has {block.shape[1]} features, expected "
                f"{self.num_features}")
        # validate everything BEFORE mutating, so a rejected push leaves
        # the builder unchanged
        fields = []
        for value, store, name in (
                (label, self._labels, "label"),
                (weight, self._weights, "weight"),
                (init_score, self._init_scores, "init_score"),
                (group, self._groups, "group")):
            if value is not None:
                if self._chunks and not store:
                    raise ValueError(
                        f"{name} was missing for earlier blocks but "
                        "provided for this one (all-or-none per field)")
                arr = np.asarray(value)
                if name != "group" and arr.shape[0] != block.shape[0]:
                    raise ValueError(
                        f"{name} slice has {arr.shape[0]} rows, block has "
                        f"{block.shape[0]}")
                fields.append((store, arr))
            elif store:
                raise ValueError(
                    f"{name} was provided for earlier blocks but missing "
                    "for this one")
        self._chunks.append(block)
        for store, arr in fields:
            store.append(arr)
        return self

    def finalize(self):
        """Coalesce chunks and construct the Dataset (one copy — the
        ChunkedArray coalesce contract)."""
        from ..basic import Dataset
        if self._finalized:
            raise RuntimeError("builder already finalized")
        if not self._chunks:
            raise ValueError("no rows pushed")
        self._finalized = True
        X = (self._chunks[0] if len(self._chunks) == 1
             else np.concatenate(self._chunks, axis=0))

        def _cat(parts):
            if not parts:
                return None
            return parts[0] if len(parts) == 1 else np.concatenate(parts)

        ds = Dataset(X, label=_cat(self._labels),
                     weight=_cat(self._weights),
                     init_score=_cat(self._init_scores),
                     group=_cat(self._groups),
                     reference=self.reference,
                     params=self.params)
        self._chunks.clear()
        self._labels.clear()
        self._weights.clear()
        self._init_scores.clear()
        self._groups.clear()
        return ds.construct()
