from .mesh import get_mesh, shard_data, replicate  # noqa: F401
