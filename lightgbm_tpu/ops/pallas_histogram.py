"""Pallas TPU histogram kernel.

The performance-critical op (ref: the CUDA shared-memory histogram kernels,
src/treelearner/cuda/cuda_histogram_constructor.cu:21). The XLA one-hot
formulation materializes the [N, B] one-hot in HBM (~B x 4 bytes per
element); this kernel builds one-hot tiles in VMEM only, so HBM traffic
drops to one read of the bin matrix (1 byte/element) plus the gh vectors —
the bandwidth floor.

Layout: bins [F, N] (feature-major), gh [3, N] (grad, hess, count rows,
pre-masked), output hist [F, 3, B].

Grid: (feature_blocks, row_chunks); row chunks accumulate into the same
output block (TPU grids execute sequentially, minor-dim fastest).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _hist_kernel(bins_ref, gh_ref, out_ref, *, f_blk: int, max_bins: int,
                 precise: bool):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    gh = gh_ref[...]  # [3, C] f32
    chunk = gh.shape[1]
    prec = lax.Precision.HIGHEST if precise else lax.Precision.DEFAULT

    # static unroll: dynamic sublane indexing into a uint8 tile is not
    # supported by Mosaic; keep f_blk * chunk * B * 4 bytes under VMEM
    for f in range(f_blk):
        b = bins_ref[f, :].astype(jnp.int32)  # [C]
        onehot = (b[:, None] == lax.broadcasted_iota(
            jnp.int32, (chunk, max_bins), 1)).astype(jnp.float32)
        out_ref[f, :, :] += jax.lax.dot(gh, onehot, precision=prec)


@functools.partial(jax.jit,
                   static_argnames=("max_bins", "f_blk", "row_chunk",
                                    "precise", "interpret"))
def hist_pallas(bins_fm: jax.Array, gh3: jax.Array, *, max_bins: int,
                f_blk: int = 8, row_chunk: int = 0,
                precise: bool = True, interpret: bool = False) -> jax.Array:
    """bins_fm [F, N] uint8/uint16, gh3 [3, N] f32 (pre-masked) ->
    hist [F, B, 3] f32."""
    num_features, n = bins_fm.shape
    if row_chunk == 0:
        # keep the f_blk unrolled one-hot buffers under ~8 MB of VMEM
        budget = 8 * 1024 * 1024 // (f_blk * max_bins * 4)
        row_chunk = max(512, min(2048, (budget // 512) * 512))
    # pad N to a multiple of row_chunk (pad bins with max_bins -> one-hot
    # of the padded rows is all-zero, and gh pads with zeros anyway)
    pad_n = (-n) % row_chunk
    if pad_n:
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_n)),
                          constant_values=max_bins)
        gh3 = jnp.pad(gh3, ((0, 0), (0, pad_n)))
    pad_f = (-num_features) % f_blk
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)),
                          constant_values=max_bins)
    fp = bins_fm.shape[0]
    npad = bins_fm.shape[1]

    grid = (fp // f_blk, npad // row_chunk)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, f_blk=f_blk, max_bins=max_bins,
                          precise=precise),
        grid=grid,
        in_specs=[
            pl.BlockSpec((f_blk, row_chunk), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, row_chunk), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((f_blk, 3, max_bins), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fp, 3, max_bins), jnp.float32),
        interpret=interpret,
    )(bins_fm, gh3)
    # [F, 3, B] -> [F, B, 3] to match the XLA path's layout
    return jnp.swapaxes(out[:num_features], 1, 2)
