"""Device mesh utilities.

TPU-native replacement for the reference Network layer
(ref: src/network/network.cpp, include/LightGBM/network.h:90). Machine
lists, sockets and Bruck/recursive-halving collectives are replaced by a
`jax.sharding.Mesh` over ICI/DCN: arrays carry shardings and XLA's SPMD
partitioner inserts the all-reduce / reduce-scatter / all-gather
collectives that the reference implements by hand.

Axis names:
  "data" — row (data-parallel) axis: the analog of
           DataParallelTreeLearner's machine axis (parallel_tree_learner.h:54).
  "dcn"/"ici" — hierarchical data-parallel axes (get_hierarchical_mesh):
           rows shard over BOTH; histogram reduce-scatter runs over the
           fast in-process "ici" axis, and only each shard's owned
           feature slice crosses the slow "dcn" (cross-process) axis.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"

_active_mesh: Optional[Mesh] = None


def shard_map(f, mesh: Mesh, in_specs, out_specs):
    """`shard_map` across jax versions: new jax exposes `jax.shard_map`
    (replication check flag `check_vma`), older releases only
    `jax.experimental.shard_map.shard_map` (`check_rep`). Every
    shard_mapped program in this framework goes through here."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        except TypeError:  # pre-rename flag spelling
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as sm_exp
    return sm_exp(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def get_mesh(num_shards: int = 0, devices=None) -> Mesh:
    """Build (or fetch) a 1-D data-parallel mesh.

    num_shards=0 -> all local devices. A mesh with one device degrades to
    the serial learner (XLA elides the collectives).
    """
    global _active_mesh
    if devices is None:
        devices = jax.devices()
    if num_shards and num_shards > 0:
        devices = devices[:num_shards]
    if (_active_mesh is not None
            and list(_active_mesh.devices.flat) == list(devices)):
        return _active_mesh
    _active_mesh = Mesh(np.asarray(devices), (DATA_AXIS,))
    return _active_mesh


DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def get_hierarchical_mesh(devices=None,
                          num_groups: int = 0) -> Mesh:
    """2-D ("dcn", "ici") mesh for hierarchical reduce-scatter.

    Groups devices by process (one "dcn" row per host, its local devices
    along "ici"), matching the physical topology: ICI links within a
    process, data-center network between processes. On a single process
    ``num_groups`` can force an artificial split for testing. Row
    sharding uses BOTH axes (shard_data handles tuple specs); the
    learner's builders reduce-scatter over the last ("ici") axis and
    psum the surviving 1/W slice over "dcn" — see
    learner._sharded_pallas_multi and ISSUE/docs for the byte model.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if num_groups and num_groups > 1:
        groups = num_groups
    else:
        procs = sorted({d.process_index for d in devices})
        groups = len(procs)
        if groups > 1:
            by_proc = {p: [d for d in devices if d.process_index == p]
                       for p in procs}
            per = min(len(v) for v in by_proc.values())
            grid = np.asarray([by_proc[p][:per] for p in procs])
            return Mesh(grid, (DCN_AXIS, ICI_AXIS))
        groups = 1
    if len(devices) % groups != 0:
        raise ValueError(
            f"{len(devices)} devices do not split into {groups} groups")
    grid = np.asarray(devices).reshape(groups, -1)
    return Mesh(grid, (DCN_AXIS, ICI_AXIS))


def rows_spec(mesh: Mesh, ndim: int, row_axis: int = 0) -> P:
    """PartitionSpec sharding `row_axis` over ALL mesh axes (1-D "data"
    meshes and hierarchical ("dcn","ici") meshes alike)."""
    names = mesh.axis_names
    spec = [None] * ndim
    spec[row_axis] = names[0] if len(names) == 1 else tuple(names)
    return P(*spec)


def shard_data(mesh: Mesh, array, row_axis: int):
    """Place `array` sharded along its row dimension (rows over the mesh's
    data axis, or over all axes of a hierarchical mesh)."""
    sharding = NamedSharding(mesh, rows_spec(mesh, array.ndim, row_axis))
    return jax.device_put(array, sharding)


def replicate(mesh: Mesh, array):
    return jax.device_put(array, NamedSharding(mesh, P()))


def num_machines() -> int:
    """Reference Network::num_machines analog."""
    return _active_mesh.size if _active_mesh is not None else 1


def data_sharding(mesh: Mesh, ndim: int, row_axis: int = 0) -> NamedSharding:
    """NamedSharding placing an ndim-array's `row_axis` over "data" —
    the serving engine uses this to land prediction chunks pre-sharded
    so the shard_mapped traversal starts without a reshard
    (ops/predict.py predict_raw_cached)."""
    spec = [None] * ndim
    spec[row_axis] = DATA_AXIS
    return NamedSharding(mesh, P(*spec))


def is_replicated_on(mesh: Mesh, array) -> bool:
    """True when `array` physically holds a full copy on every device of
    `mesh` — the precondition for the cross-shard drift sentinels
    (obs/health.py): only state that is SUPPOSED to be identical on
    every chip can meaningfully be digest-compared across them."""
    sharding = getattr(array, "sharding", None)
    if sharding is None or not getattr(sharding, "is_fully_replicated",
                                       False):
        return False
    try:
        devices = set(sharding.device_set)
    except Exception:
        return False
    return set(mesh.devices.flat).issubset(devices)


def pad_rows_to_shards(n: int, mesh: Mesh) -> int:
    """Smallest row count >= n divisible by the mesh's data axis (row
    blocks fed to shard_map must split evenly across devices)."""
    s = max(mesh.size, 1)
    return -(-n // s) * s
