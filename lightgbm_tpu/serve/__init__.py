"""Model serving subsystem: async multi-tenant server over the
tree-parallel inference engine (ops/predict.py).

The engine speaks large offline batches; production traffic is many
small concurrent requests. This package turns one into the other:

- ``registry``  — multi-tenant model registry: named models, LRU
  eviction of their packed-ensemble bytes under a configurable budget.
- ``batcher``   — deadline-bounded micro-batching: concurrent requests
  coalesce into one engine dispatch that lands in the already-warm
  shape buckets (max-wait + max-batch knobs; results bit-identical to
  calling ``predict`` directly, because row traversal is independent
  per row and the per-row f32 accumulation order never changes).
- ``lowlat``    — the dedicated B<=64 path: per-model AOT-compiled
  traversal executables that bypass the batch machinery entirely
  (plus the matching ``LowLatencyExplainer`` ladder for the
  SHAP-contribution ``explain`` route).
- ``artifacts`` — serialized AOT executables on disk: a replica
  restart or an LRU re-admission warms the lowlat ladder from the
  artifact store in milliseconds instead of recompiling (fingerprint-
  keyed; any mismatch falls back to a fresh, bit-identical compile).
- ``server``    — the asyncio front that routes requests by size,
  tracks per-request latency into ``obs.metrics`` p50/p95/p99
  reservoirs, and backs ``python -m lightgbm_tpu serve`` and
  ``bench.py --serve``.
- ``fleet``     — the failure-domain layer: ``FleetRouter`` fronts N
  replicas (in-process or subprocess) with health-gated routing,
  quarantine/reinstate, failover retry of idempotent predicts, hedged
  dispatch, and the SIGTERM drain / exit-75 contract.
"""

from .artifacts import ArtifactStore, serialize_available  # noqa: F401
from .registry import ModelRegistry, ServedModel  # noqa: F401
from .batcher import MicroBatcher  # noqa: F401
from .lowlat import (SERVE_EXPLAIN_TAG, SERVE_LOWLAT_TAG,  # noqa: F401
                     LowLatencyExplainer, LowLatencyPredictor)
from .server import (ModelServer, registry_from_config, replay,  # noqa: F401
                     serve_file, server_from_config)
from .fleet import (FleetRouter, HTTPReplica,  # noqa: F401
                    InProcessReplica, aggregate_counter_totals,
                    build_inprocess_fleet)

__all__ = [
    "ArtifactStore", "serialize_available",
    "ModelRegistry", "ServedModel", "MicroBatcher",
    "LowLatencyPredictor", "SERVE_LOWLAT_TAG",
    "LowLatencyExplainer", "SERVE_EXPLAIN_TAG",
    "ModelServer", "replay", "serve_file",
    "registry_from_config", "server_from_config",
    "FleetRouter", "HTTPReplica", "InProcessReplica",
    "aggregate_counter_totals", "build_inprocess_fleet",
]
