"""Tree-parallel inference engine (ops/predict.py): parity, chunk-shape
recompile stability, incremental packing, sharded predict, knob plumbing.

Parity tiers:
- vmapped/batched traversal vs the per-tree scan it replaced must be
  BIT-identical (same f32 accumulation order by construction)
- save/load round trips run the identical XLA program -> bit-equal
- predict_leaf_index vs the pure-NumPy host traversal oracle
  (tree.py Tree.predict_leaf)
"""

import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.metrics import global_metrics
from lightgbm_tpu.ops import predict as pred_ops
from lightgbm_tpu.ops.predict import (
    EnsemblePacker, PREDICT_TRACE_TAG, pack_ensemble, predict_leaf_index,
    predict_raw_multiclass, predict_raw_scan)

pytestmark = pytest.mark.quick


def _data(n=400, f=8, seed=0, nans=False, zeros=False, cats=False):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f)
    if cats:
        x[:, 0] = rng.randint(0, 12, n)  # categorical columns
        x[:, 1] = rng.randint(0, 5, n)
    if nans:
        x[::7, 2] = np.nan
    if zeros:
        x[::5, 3] = 0.0
    y = ((np.nan_to_num(x[:, 2]) + x[:, 4]
          + (x[:, 0] % 3 == 1) * 2.0 + (x[:, 1] == 2) * 1.5)
         > 1.0).astype(np.float64)
    return x, y


def _train(x, y, extra=None, rounds=10, categorical=None):
    params = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
              "verbosity": -1}
    params.update(extra or {})
    ds = lgb.Dataset(x, label=y, params=params,
                     categorical_feature=categorical or "auto")
    return lgb.train(params, ds, num_boost_round=rounds)


def _trees(bst):
    return [t for it in bst._gbdt.models for t in it]


# ----------------------------------------------------------------------
# parity: engine vs the per-tree scan path it replaced
class TestTraversalParity:
    def test_binary_bit_identical_to_scan(self):
        x, y = _data(nans=True)
        bst = _train(x, y)
        ens = pack_ensemble(_trees(bst))
        xb = jnp.asarray(x, jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(predict_raw_multiclass(ens, xb)),
            np.asarray(predict_raw_scan(ens, xb)))

    def test_categorical_bit_identical_to_scan(self):
        x, y = _data(cats=True, nans=True)
        bst = _train(x, y, {"min_data_per_group": 2, "cat_smooth": 1.0},
                     categorical=[0, 1])
        trees = _trees(bst)
        assert any(t.num_cat > 0 for t in trees), "no categorical splits"
        ens = pack_ensemble(trees)
        assert ens.has_categorical
        xb = jnp.asarray(x, jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(predict_raw_multiclass(ens, xb)),
            np.asarray(predict_raw_scan(ens, xb)))

    @pytest.mark.slow
    def test_multiclass_single_program_bit_identical(self):
        x, _ = _data(n=600)
        rng = np.random.RandomState(3)
        y = rng.randint(0, 3, 600).astype(np.float64)
        bst = _train(x, y, {"objective": "multiclass", "num_class": 3,
                            "num_leaves": 7}, rounds=6)
        trees = _trees(bst)
        ens = pack_ensemble(trees, 3)
        xb = jnp.asarray(x, jnp.float32)
        out = np.asarray(predict_raw_multiclass(ens, xb))
        assert out.shape == (600, 3)
        np.testing.assert_array_equal(out,
                                      np.asarray(predict_raw_scan(ens, xb)))

    def test_leaf_index_vs_numpy_host_oracle(self):
        x, y = _data(cats=True, nans=True, zeros=True)
        bst = _train(x, y, {"min_data_per_group": 2}, categorical=[0, 1])
        trees = _trees(bst)
        ens = pack_ensemble(trees)
        leaves = np.asarray(predict_leaf_index(ens,
                                               jnp.asarray(x, jnp.float32)))
        oracle = np.stack([t.predict_leaf(np.asarray(x, np.float64))
                           for t in trees], axis=1)
        np.testing.assert_array_equal(leaves, oracle)


# ----------------------------------------------------------------------
# save/load bit-equality through the shared engine
class TestSaveLoadParity:
    @pytest.mark.parametrize("variant", ["missing_none", "missing_nan",
                                         "missing_zero"])
    def test_roundtrip_bit_equal_all_missing_types(self, variant):
        x, y = _data(cats=True, nans=variant == "missing_nan")
        extra = {"min_data_per_group": 2}
        if variant == "missing_zero":
            extra["zero_as_missing"] = True
        elif variant == "missing_none":
            extra["use_missing"] = False
        bst = _train(x, y, extra, categorical=[0, 1])
        assert any(t.num_cat > 0 for t in _trees(bst))
        loaded = lgb.Booster(model_str=bst.model_to_string())
        xq = np.ascontiguousarray(x[::3])
        np.testing.assert_array_equal(bst.predict(xq, raw_score=True),
                                      loaded.predict(xq, raw_score=True))

    def test_engine_output_unchanged_by_chunking(self):
        x, y = _data(n=700)
        bst = _train(x, y)
        full = bst.predict(x, raw_score=True)
        for chunk in (64, 100, 1024):
            np.testing.assert_array_equal(
                full, bst.predict(x, raw_score=True,
                                  tpu_predict_chunk=chunk))


# ----------------------------------------------------------------------
# chunk-shape stability: uneven N must never trigger a fresh JIT
class TestRecompileStability:
    def test_no_recompile_across_chunk_shapes(self):
        from lightgbm_tpu.ops.predict import _row_bucket
        chunk = 256
        x, y = _data(n=1200)
        bst = _train(x, y, {"tpu_predict_chunk": chunk})
        xt = np.random.RandomState(5).randn(1600, x.shape[1])
        # warm the (small, bounded) bucket set by predicting once at
        # each bucket size — exactly what the first requests of a
        # serving process do
        uneven = (257, 300, 511, 700, 1000, 1023, 777, 1500, 41, 39)
        buckets = {_row_bucket(n % chunk or chunk, chunk, None)
                   for n in uneven} | {chunk}
        for b in sorted(buckets):
            bst.predict(xt[:b], raw_score=True)
        warm = global_metrics.recompiles(PREDICT_TRACE_TAG)
        out_even = bst.predict(xt[:1024], raw_score=True)
        # every N here is NOT divisible by the 256-row chunk; none may
        # compile a fresh traversal program
        for n in uneven:
            bst.predict(xt[:n], raw_score=True)
        assert global_metrics.recompiles(PREDICT_TRACE_TAG) == warm, \
            "uneven chunk tails recompiled the traversal program"
        # and the outputs stay bit-stable while shapes bucket
        np.testing.assert_array_equal(out_even,
                                      bst.predict(xt[:1024], raw_score=True))

    def test_bucket_count_is_bounded(self):
        from lightgbm_tpu.ops.predict import _row_bucket
        buckets = {_row_bucket(r, 1 << 20, None) for r in
                   range(1, 1 << 20, 997)}
        assert len(buckets) <= 4 + 16 + 16  # pow2 tiers + grain multiples


# ----------------------------------------------------------------------
# incremental packing: per-iteration eval must not repack all T trees
class TestIncrementalPacking:
    def test_training_eval_packs_linear_not_quadratic(self):
        x, y = _data(n=800)
        params = {"objective": "binary", "num_leaves": 15,
                  "min_data_in_leaf": 5, "verbosity": -1}
        bst = lgb.Booster(params, lgb.Dataset(x, label=y, params=params))
        iters = 24
        xq = x[:64]
        for _ in range(iters):
            bst.update()
            bst.predict(xq, raw_score=True)  # per-iteration eval predict
        packers = list(bst._gbdt._packers.values())
        assert len(packers) == 1
        pk = packers[0]
        quadratic = iters * (iters + 1) // 2
        # amortized-doubling bound: ~3T packs total, nowhere near O(T^2)
        assert pk.trees_packed <= 4 * iters < quadratic
        # steady state appends exactly the K new trees per iteration
        before = pk.trees_packed
        bst.update()
        bst.predict(xq, raw_score=True)
        assert pk.trees_packed - before == 1

    def test_packer_detects_mutation_and_rollback(self):
        x, y = _data()
        bst = _train(x, y, rounds=6)
        p0 = bst.predict(x, raw_score=True)
        gbdt = bst._gbdt
        # rollback truncates the packed tail rather than serving it stale
        gbdt.rollback_one_iter()
        p1 = bst.predict(x, raw_score=True)
        assert not np.array_equal(p0, p1)
        # in-place leaf mutation (the DART-normalize shape: past trees
        # rescaled while the model keeps evolving) bumps pack_version,
        # so the next key change repacks the mutated prefix instead of
        # incrementally appending past it
        tree = gbdt.models[0][0]
        v0 = tree.pack_version
        tree.apply_shrinkage(0.5)
        assert tree.pack_version == v0 + 1
        host_expect = gbdt._predict_raw_host(np.asarray(x, np.float64), 0,
                                             len(gbdt.models))
        gbdt._packed_key = None  # out-of-band edit -> capi invalidation
        p2 = bst.predict(x, raw_score=True)
        assert not np.array_equal(p1, p2)
        np.testing.assert_allclose(p2, host_expect[:, 0], rtol=1e-6,
                                   atol=1e-7)

    def test_one_shot_pack_is_exact_shape(self):
        x, y = _data()
        bst = _train(x, y, rounds=5)
        trees = _trees(bst)
        ens = pack_ensemble(trees)
        assert ens.split_feature.shape[0] == len(trees) == ens.num_trees
        packer = EnsemblePacker()
        padded = packer.update(trees, 1)  # serving packer: exact first pack
        assert padded.split_feature.shape[0] == len(trees)


# ----------------------------------------------------------------------
# mesh-sharded predict
class TestShardedPredict:
    def test_sharded_bit_identical(self):
        import jax
        if len(jax.devices()) < 2:
            pytest.skip("needs multi-device (XLA_FLAGS host platform count)")
        x, y = _data(n=900)
        bst = _train(x, y)
        xt = np.random.RandomState(7).randn(1003, x.shape[1])  # odd N
        p_serial = bst.predict(xt, raw_score=True)
        bst._gbdt.config.tpu_num_shards = 4
        bst._gbdt._packed_key = None  # drop the serial-program cache
        try:
            p_sharded = bst.predict(xt, raw_score=True)
        finally:
            bst._gbdt.config.tpu_num_shards = 0
        np.testing.assert_array_equal(p_serial, p_sharded)


# ----------------------------------------------------------------------
# knob plumbing + serving telemetry + backend sniff
class TestPlumbingAndTelemetry:
    def test_chunk_knob_param_and_alias(self):
        x, y = _data(n=500)
        bst = _train(x, y, {"tpu_predict_chunk": 128})
        assert bst._gbdt.config.tpu_predict_chunk == 128
        alias = _train(x, y, {"predict_chunk": 99})
        assert alias._gbdt.config.tpu_predict_chunk == 99
        np.testing.assert_array_equal(bst.predict(x, raw_score=True),
                                      alias.predict(x, raw_score=True))

    def test_chunk_knob_reaches_loaded_model(self):
        x, y = _data()
        bst = _train(x, y)
        loaded = lgb.Booster({"tpu_predict_chunk": 77},
                             model_str=bst.model_to_string())
        assert loaded._loaded.predict_chunk == 77
        np.testing.assert_array_equal(bst.predict(x, raw_score=True),
                                      loaded.predict(x, raw_score=True))

    def test_sklearn_predict_kwarg_passthrough(self):
        from lightgbm_tpu.sklearn import LGBMClassifier
        x, y = _data()
        clf = LGBMClassifier(n_estimators=5, num_leaves=7).fit(x, y)
        np.testing.assert_array_equal(
            clf.predict_proba(x),
            clf.predict_proba(x, tpu_predict_chunk=64))

    def test_predict_rows_per_sec_accumulates(self):
        x, y = _data()
        bst = _train(x, y, rounds=3)
        rows0 = global_metrics.predict_rows_total
        bst.predict(x, raw_score=True)
        assert global_metrics.predict_rows_total == rows0 + len(x)
        assert global_metrics.predict_rows_per_sec() > 0

    def test_cpu_backend_sniff_catches_only_runtime_error(self, monkeypatch):
        import jax
        from lightgbm_tpu.ops import histogram as hist_ops

        def boom():
            raise RuntimeError("Unable to initialize backend 'axon'")

        monkeypatch.setattr(jax, "default_backend", boom)
        assert hist_ops.cpu_backend() is True

        def bug():
            raise ValueError("a real bug")

        monkeypatch.setattr(jax, "default_backend", bug)
        with pytest.raises(ValueError):
            hist_ops.cpu_backend()
