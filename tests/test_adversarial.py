"""Adversarial semantics tests (VERDICT r2 #9): grid monotonicity on
deep trees with conflicting interactions, and wide-categorical bitset
round-trips (ref: monotone_constraints.hpp, tree.h:375 categorical
bitset decisions)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _train(X, y, params, rounds=30):
    ds = lgb.Dataset(X, label=y, params=dict(params))
    return lgb.train(dict(params), ds, num_boost_round=rounds)


@pytest.mark.slow
def test_monotone_grid_deep_tree_conflicting_interactions():
    """y depends on x0 through a sign-flipping interaction (x0*x1): an
    unconstrained model is non-monotone in x0; with monotone +1 on x0
    every prediction slice along x0 must be nondecreasing, at every
    depth of a deep tree (this catches constraint-propagation bugs that
    shallow smooth checks miss)."""
    rng = np.random.RandomState(0)
    n = 4000
    X = rng.uniform(-2, 2, (n, 4))
    y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2]
         + 0.2 * rng.randn(n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 63,
              "min_data_in_leaf": 5, "learning_rate": 0.2,
              "verbosity": -1,
              "monotone_constraints": [1, 0, 0, 0]}
    bst = _train(X, y, params)

    # sanity: the unconstrained model IS non-monotone on this target
    un = _train(X, y, {**params, "monotone_constraints": [0, 0, 0, 0]})
    sweep = np.linspace(-2, 2, 41)
    base = rng.uniform(-2, 2, (60, 4))
    violated_unconstrained = False
    max_violation = 0.0
    for row in base:
        grid = np.tile(row, (len(sweep), 1))
        grid[:, 0] = sweep
        p = bst.predict(grid)
        diffs = np.diff(p)
        max_violation = max(max_violation, float(-(diffs.min()))
                            if diffs.size else 0.0)
        pu = un.predict(grid)
        if np.any(np.diff(pu) < -1e-6):
            violated_unconstrained = True
    assert violated_unconstrained, (
        "fixture too easy: unconstrained model is already monotone")
    assert max_violation <= 1e-6, (
        f"monotone violation {max_violation} on constrained model")


def _monotone_sweep_violation(bst, rng, ncols, col=0, lo=-2, hi=2):
    sweep = np.linspace(lo, hi, 41)
    worst = 0.0
    for row in rng.uniform(lo, hi, (50, ncols)):
        grid = np.tile(row, (len(sweep), 1))
        grid[:, col] = sweep
        diffs = np.diff(bst.predict(grid))
        if diffs.size:
            worst = max(worst, float(-diffs.min()))
    return worst


def _monotone_fixture(seed=0, n=4000):
    rng = np.random.RandomState(seed)
    X = rng.uniform(-2, 2, (n, 4))
    y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2]
         + 0.2 * rng.randn(n)).astype(np.float32)
    return X, y, rng


@pytest.mark.slow
def test_monotone_methods_grid():
    """intermediate/advanced (exact pairwise leaf-box bounds, ref:
    monotone_constraints.hpp:517,859) must stay strictly monotone on
    both growers, like basic."""
    X, y, rng = _monotone_fixture()
    for method in ("intermediate", "advanced"):
        for wave in (0, 42):
            params = {"objective": "regression", "num_leaves": 31,
                      "min_data_in_leaf": 5, "learning_rate": 0.2,
                      "verbosity": -1, "tpu_wave_max": wave,
                      "monotone_constraints": [1, 0, 0, 0],
                      "monotone_constraints_method": method}
            bst = _train(X, y, params, rounds=15)
            v = _monotone_sweep_violation(bst, rng, 4)
            assert v <= 1e-6, (method, wave, v)


@pytest.mark.slow
def test_monotone_intermediate_less_constraining_than_basic():
    """The reference's selling point for intermediate/advanced: much
    less constraining than basic, so the constrained fit recovers more
    accuracy (ref: docs monotone_constraints_method). Train both and
    compare training MSE."""
    X, y, rng = _monotone_fixture(seed=3)
    base = {"objective": "regression", "num_leaves": 63,
            "min_data_in_leaf": 5, "learning_rate": 0.2,
            "verbosity": -1, "tpu_wave_max": 0,
            "monotone_constraints": [1, 0, 0, 0]}
    mse = {}
    for method in ("basic", "intermediate", "advanced"):
        bst = _train(X, y, {**base,
                            "monotone_constraints_method": method},
                     rounds=30)
        mse[method] = float(np.mean((bst.predict(X) - y) ** 2))
        assert _monotone_sweep_violation(bst, rng, 4) <= 1e-6, method
    # pairwise bounds must not fit WORSE than midpoint propagation
    assert mse["intermediate"] <= mse["basic"] * 1.02, mse
    assert mse["advanced"] <= mse["basic"] * 1.02, mse


def test_monotone_decreasing_with_bagging_and_depth_cap():
    rng = np.random.RandomState(1)
    n = 3000
    X = rng.uniform(-1, 1, (n, 3))
    y = (-X[:, 0] * np.abs(X[:, 1]) + 0.3 * X[:, 2]
         + 0.1 * rng.randn(n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 31, "max_depth": 6,
              "bagging_fraction": 0.7, "bagging_freq": 1,
              "min_data_in_leaf": 5, "verbosity": -1,
              "monotone_constraints": [-1, 0, 0]}
    bst = _train(X, y, params, rounds=20)
    sweep = np.linspace(-1, 1, 31)
    for row in rng.uniform(-1, 1, (40, 3)):
        grid = np.tile(row, (len(sweep), 1))
        grid[:, 0] = sweep
        assert np.all(np.diff(bst.predict(grid)) <= 1e-6)


def test_wide_categorical_bitset_roundtrip():
    """>64 categories forces multi-word bitsets. The chain
    train -> device predict -> text serialize -> reload -> host predict
    must agree exactly on category routing."""
    rng = np.random.RandomState(2)
    n, cats = 5000, 80
    c = rng.randint(0, cats, n)
    x1 = rng.randn(n)
    group_effect = (c % 7 == 0) * 2.0 - (c % 11 == 3) * 1.5
    y = (group_effect + 0.5 * x1 + 0.2 * rng.randn(n)).astype(np.float32)
    X = np.column_stack([c.astype(np.float64), x1])
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 5, "min_data_per_group": 1,
              "max_cat_threshold": 64, "cat_smooth": 1.0,
              "verbosity": -1, "categorical_feature": [0]}
    ds = lgb.Dataset(X, label=y, categorical_feature=[0],
                     params=dict(params))
    bst = lgb.train(dict(params), ds, num_boost_round=20)

    used_cat_split = any(
        (t.num_cat or 0) > 0
        for it in bst._gbdt.models for t in it)
    assert used_cat_split, "fixture never split on the categorical"
    # multi-word bitsets actually exercised (80 cats > 32-bit word)
    assert any(
        len(t.cat_threshold) > (t.cat_boundaries[1] - t.cat_boundaries[0]
                                if t.num_cat else 0) or
        any(np.diff(t.cat_boundaries) > 1)
        for it in bst._gbdt.models for t in it if t.num_cat)

    direct = bst.predict(X)
    text = bst.model_to_string()
    from lightgbm_tpu.model_io import load_model_from_string
    loaded = load_model_from_string(text)
    via_text = np.asarray(loaded.predict_raw(X)).reshape(-1)
    np.testing.assert_allclose(direct, via_text, rtol=1e-5, atol=1e-6)

    # unseen categories route by the default (missing) direction and
    # must not crash (ref: CategoricalDecision out-of-range -> default)
    X_unseen = X.copy()
    X_unseen[:10, 0] = cats + 500
    p_unseen = bst.predict(X_unseen)
    assert np.all(np.isfinite(p_unseen))


def test_categorical_monotone_combination():
    """Monotone constraint on a numerical feature while a categorical
    feature drives interactions — the constraint must hold regardless
    of category routing."""
    rng = np.random.RandomState(3)
    n, cats = 4000, 12
    c = rng.randint(0, cats, n)
    x1 = rng.uniform(-1, 1, n)
    slope = np.where(c % 2 == 0, 2.0, -1.0)  # conflicting slopes by cat
    y = (slope * x1 + 0.1 * rng.randn(n)).astype(np.float32)
    X = np.column_stack([c.astype(np.float64), x1])
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 5, "verbosity": -1,
              "categorical_feature": [0],
              "monotone_constraints": [0, 1]}
    ds = lgb.Dataset(X, label=y, categorical_feature=[0],
                     params=dict(params))
    bst = lgb.train(dict(params), ds, num_boost_round=20)
    sweep = np.linspace(-1, 1, 21)
    for cat in range(cats):
        grid = np.column_stack([np.full(len(sweep), float(cat)), sweep])
        assert np.all(np.diff(bst.predict(grid)) >= -1e-6), \
            f"monotone violated within category {cat}"
