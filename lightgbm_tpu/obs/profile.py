"""Device-time attribution: the fourth obs pillar (ISSUE 16).

Everything timed elsewhere in the obs stack is a host-side wall span,
and every device-side number is a *static* XLA cost-analysis estimate
(obs/xla.py). This module measures where device time actually goes,
keyed back to the existing obs program tags (``boosting/fused_iter``,
``boosting/grow``, ``predict/traversal``, ...):

* **Profiler capture** — ``jax.profiler.start_trace`` /
  ``stop_trace`` around a bounded window of training iterations or
  serve requests (armed by the ``tpu_profile=off/window/bench`` knob;
  ``LGBM_TPU_PROFILE_DIR`` selects the trace directory and turns the
  real profiler on). The emitted trace-events JSON is parsed into
  per-program device-busy seconds via the jitted function names
  ``instrumented_jit`` registers at wrap time.
* **Profiler-free fallback** — while a window is open, every
  ``instrumented_jit`` dispatch is re-timed with a
  ``jax.block_until_ready`` sync (``timed_call``), and the AOT
  executables obs/xla.py caches are re-run at window close
  (``block_until_ready`` micro-reruns, best-of-N) — so CPU CI
  exercises the identical attribution plumbing with no profiler.
* **Roofline layer** — ``roofline()`` joins measured device seconds
  with XLA cost-analysis flops/bytes (obs/xla.py) and the analytic
  ``learner.hist_traffic_model`` bytes already published under
  ``meta["hist_traffic"]``, divides by the per-platform peaks tabled in
  ``hostenv.platform_peaks`` (env-overridable), and emits achieved
  bytes/s + utilization-vs-peak + a memory-bound/compute-bound verdict
  per tag. Surfaced in bench JSON (``device_seconds_by_tag``,
  ``roofline``), OpenMetrics (``lgbmtpu_profile_*``), the Chrome trace
  (a separate device-lane pid, obs/trace.py) and perf-gate check 11.

Windows never nest; ``start_window``/``stop_window`` accumulate across
repeated windows. Capture changes no computed values (a sync is
observationally pure), so models are bit-identical profiling on vs off.
The disabled path is a single attribute check (``capturing``).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .metrics import global_metrics

MAX_SLICES = 20000  # bounded per-call slice buffer for the trace lane
_ENV_DIR = "LGBM_TPU_PROFILE_DIR"
_ENV_MODE = "LGBM_TPU_PROFILE"

DEVICE_LANE_NAME = "lightgbm_tpu device"


def _detect_platform() -> str:
    """Backend platform if jax is already live; never forces backend
    init (hostenv module docstring: the axon relay hangs on probes)."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            return str(jax_mod.default_backend())
        except Exception:
            pass
    return "cpu"


def parse_trace_events(events: List[Dict[str, Any]],
                       name_to_tag: Dict[str, str]
                       ) -> Tuple[Dict[str, float],
                                  List[Tuple[str, float, float]]]:
    """Attribute profiler trace events to obs program tags.

    -> ({tag: device_busy_seconds}, [(tag, ts_us, dur_us), ...]).

    Pure function (importable for tests). Device pids are identified by
    ``process_name`` metadata (``/device:``, ``TPU``, ``GPU`` — the
    names the XLA profiler plugin emits); when no pid is identifiably a
    device (single-process CPU traces) every pid counts. A complete
    event is attributed to the tag whose registered jitted-function
    name appears in the event name, longest name first so e.g.
    ``_fused_iter_impl`` wins over ``_iter``."""
    dev_pids = set()
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            nm = str((ev.get("args") or {}).get("name", ""))
            if "/device:" in nm or nm.startswith(("TPU", "GPU", "Device")):
                dev_pids.add(ev.get("pid"))
    names = sorted(((n, t) for n, t in name_to_tag.items() if n),
                   key=lambda kv: -len(kv[0]))
    secs: Dict[str, float] = {}
    slices: List[Tuple[str, float, float]] = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        if dev_pids and ev.get("pid") not in dev_pids:
            continue
        dur = ev.get("dur")
        if not isinstance(dur, (int, float)) or dur <= 0:
            continue
        ev_name = str(ev.get("name", ""))
        for fname, tag in names:
            if fname in ev_name:
                secs[tag] = secs.get(tag, 0.0) + float(dur) / 1e6
                if len(slices) < MAX_SLICES:
                    ts = ev.get("ts")
                    slices.append((tag,
                                   float(ts) if isinstance(
                                       ts, (int, float)) else 0.0,
                                   float(dur)))
                break
    return secs, slices


def load_profiler_trace(log_dir: str) -> Optional[List[Dict[str, Any]]]:
    """Newest ``*.trace.json(.gz)`` under a ``jax.profiler`` log dir,
    parsed to its event list — or None when the profiler emitted no
    chrome-format trace (xplane-only versions)."""
    paths = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        paths.extend(glob.glob(os.path.join(log_dir, pat), recursive=True))
    if not paths:
        return None
    path = max(paths, key=os.path.getmtime)
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt") as fh:
                doc = json.load(fh)
        else:
            with open(path) as fh:
                doc = json.load(fh)
    except Exception:
        return None
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
        return events if isinstance(events, list) else None
    return doc if isinstance(doc, list) else None


class ProfileRegistry:
    """Global device-time attribution state (see module docstring).

    ``capturing`` is the one-attribute fast gate obs/xla.py checks per
    dispatch; everything else only runs inside an open window."""

    def __init__(self) -> None:
        self.capturing = False
        self.mode = "off"
        self._lock = threading.Lock()
        self._fallback_s: Dict[str, float] = {}
        self._profiler_s: Dict[str, float] = {}
        self._calls: Dict[str, int] = {}
        self._phase: Dict[str, str] = {}
        self._rerun_s: Dict[str, float] = {}
        self._slices: List[Tuple[str, float, float, str]] = []
        self._dropped_slices = 0
        self._entries: Dict[str, Tuple[Any, tuple, dict]] = {}
        self._name_to_tag: Dict[str, str] = {}
        self._wall_s = 0.0
        self._t0: Optional[float] = None
        self._t0_ns = 0
        self._n_windows = 0
        self._trace_dir: Optional[str] = None
        self._tracing = False
        self.last_roofline: Optional[Dict[str, Any]] = None

    # -- registration (always-on, negligible) --------------------------
    def register_tag(self, tag: str, phase: Optional[str],
                     fn_name: str) -> None:
        """Called once per instrumented_jit wrap: maps the jitted
        function name back to the obs tag for profiler-trace parsing."""
        with self._lock:
            if fn_name:
                self._name_to_tag[fn_name] = tag
            if phase:
                self._phase.setdefault(tag, phase)

    # -- window lifecycle ----------------------------------------------
    def start_window(self, source: str = "window",
                     profile_dir: Optional[str] = None) -> None:
        """Open a capture window. Idempotent while one is open. When a
        profile dir is given (arg or LGBM_TPU_PROFILE_DIR) the real
        ``jax.profiler`` trace starts too; the fallback timing always
        runs so both paths share one attribution pipeline."""
        with self._lock:
            if self.capturing:
                return
            self._t0 = time.perf_counter()
            self._t0_ns = time.perf_counter_ns()
            self._n_windows += 1
            self.capturing = True
        if self.mode == "off":
            self.mode = source if source in ("window", "bench") else "window"
        target = profile_dir or os.environ.get(_ENV_DIR, "")
        if target:
            try:
                import jax.profiler
                jax.profiler.start_trace(target)
                self._trace_dir = target
                self._tracing = True
            except Exception:
                self._tracing = False

    def stop_window(self) -> Dict[str, Any]:
        """Close the window: stop/parse the profiler trace if one ran,
        micro-rerun the registered AOT executables, drop the retained
        call args, cache the roofline. Returns ``summary()``.
        Idempotent — safe to call with no window open."""
        with self._lock:
            was_open = self.capturing
            self.capturing = False
            if was_open and self._t0 is not None:
                self._wall_s += time.perf_counter() - self._t0
            self._t0 = None
        if not was_open:
            return self.summary()
        if self._tracing:
            self._tracing = False
            try:
                import jax.profiler
                jax.profiler.stop_trace()
                self._ingest_profiler_dir(self._trace_dir)
            except Exception:
                pass
        self._micro_rerun()
        with self._lock:
            self._entries.clear()  # drop retained device buffers
        try:
            self.last_roofline = self.roofline()
        except Exception:
            self.last_roofline = None
        return self.summary()

    maybe_stop = stop_window  # crash/egress-path alias (idempotent)

    def reset(self) -> None:
        """Testing hook: drop measurements; tag registrations persist
        (they are wrap-time facts, not window state)."""
        with self._lock:
            self.capturing = False
            self.mode = "off"
            self._fallback_s.clear()
            self._profiler_s.clear()
            self._calls.clear()
            self._rerun_s.clear()
            self._slices.clear()
            self._dropped_slices = 0
            self._entries.clear()
            self._wall_s = 0.0
            self._t0 = None
            self._n_windows = 0
            self._tracing = False
            self._trace_dir = None
            self.last_roofline = None

    # -- fallback measurement (obs/xla.py dispatch hooks) --------------
    def timed_call(self, tag: str, phase: Optional[str], fn: Callable,
                   args: tuple, kwargs: dict):
        """Run one dispatch with a device sync and attribute its wall
        time to `tag`. A sync changes no values — profiling on vs off
        is bit-identical — it only serializes the dispatch, which is
        the price of honest per-program time without a profiler."""
        t0 = time.perf_counter_ns()
        out = fn(*args, **kwargs)
        try:
            import jax
            jax.block_until_ready(out)
        except Exception:
            pass
        dt_ns = time.perf_counter_ns() - t0
        with self._lock:
            self._fallback_s[tag] = (self._fallback_s.get(tag, 0.0)
                                     + dt_ns / 1e9)
            self._calls[tag] = self._calls.get(tag, 0) + 1
            if phase:
                self._phase.setdefault(tag, phase)
            if len(self._slices) < MAX_SLICES:
                self._slices.append((tag, float(t0), float(dt_ns),
                                     "fallback"))
            else:
                self._dropped_slices += 1
        return out

    def register_entry(self, tag: str, phase: Optional[str], entry: Any,
                       args: tuple, kwargs: dict) -> None:
        """Retain the latest (executable, concrete args) per tag while a
        window is open, for ``stop_window``'s micro-reruns. Cleared at
        window close so device buffers are not pinned past it."""
        with self._lock:
            self._entries[tag] = (entry, args, kwargs)
            if phase:
                self._phase.setdefault(tag, phase)

    def _micro_rerun(self, reps: int = 2) -> None:
        """Re-time each retained AOT executable best-of-`reps` with
        block_until_ready — the pure device+runtime cost of one call,
        free of the Python dispatch the inline timing includes. Skips
        entries whose buffers were donated/freed (best-effort)."""
        with self._lock:
            items = list(self._entries.items())
        for tag, (entry, args, kwargs) in items:
            try:
                import jax
                best = None
                for _ in range(max(reps, 1)):
                    t0 = time.perf_counter()
                    out = entry(*args, **kwargs)
                    jax.block_until_ready(out)
                    dt = time.perf_counter() - t0
                    best = dt if best is None else min(best, dt)
                with self._lock:
                    self._rerun_s[tag] = best
            except Exception:
                continue

    # -- profiler ingestion --------------------------------------------
    def _ingest_profiler_dir(self, log_dir: Optional[str]) -> None:
        if not log_dir:
            return
        events = load_profiler_trace(log_dir)
        if not events:
            return
        with self._lock:
            mapping = dict(self._name_to_tag)
        secs, slices = parse_trace_events(events, mapping)
        if not secs:
            return
        base_us = min(ts for _, ts, _ in slices) if slices else 0.0
        with self._lock:
            for tag, s in secs.items():
                self._profiler_s[tag] = self._profiler_s.get(tag, 0.0) + s
            for tag, ts_us, dur_us in slices:
                if len(self._slices) >= MAX_SLICES:
                    self._dropped_slices += 1
                    continue
                # rebase the profiler clock onto the window's
                # perf_counter_ns origin so host+device lanes align
                t0_ns = self._t0_ns + (ts_us - base_us) * 1e3
                self._slices.append((tag, t0_ns, dur_us * 1e3,
                                     "profiler"))

    # -- reporting ------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        """Attribution snapshot; live-readable while capturing.
        ``device_seconds_by_tag`` prefers profiler-measured seconds per
        tag, falling back to the sync-timed dispatches."""
        with self._lock:
            fallback = dict(self._fallback_s)
            profiler = dict(self._profiler_s)
            calls = dict(self._calls)
            phase = dict(self._phase)
            rerun = dict(self._rerun_s)
            wall = self._wall_s
            if self.capturing and self._t0 is not None:
                wall += time.perf_counter() - self._t0
            n_windows = self._n_windows
            mode = self.mode
        merged = dict(fallback)
        merged.update(profiler)
        total = sum(merged.values())
        coverage = (total / wall) if wall > 0 else None
        out: Dict[str, Any] = {
            "mode": mode,
            "source": "profiler" if profiler else "fallback",
            "n_windows": n_windows,
            "window_wall_s": round(wall, 6),
            "device_seconds_total": round(total, 6),
            "device_seconds_by_tag": {t: round(s, 6)
                                      for t, s in merged.items()},
            "calls_by_tag": calls,
            "phase_by_tag": {t: phase.get(t, "") for t in merged},
        }
        if coverage is not None:
            out["coverage"] = round(coverage, 4)
        if rerun:
            out["rerun_seconds_by_tag"] = {t: round(s, 6)
                                           for t, s in rerun.items()}
        return out

    def roofline(self, platform: Optional[str] = None,
                 peaks: Optional[Dict[str, float]] = None
                 ) -> Dict[str, Any]:
        """Join measured device seconds with XLA cost-analysis flops /
        bytes and the analytic histogram-traffic bytes, against the
        per-platform peaks (hostenv.platform_peaks): achieved bytes/s
        and flops/s, utilization-vs-peak, and a memory-bound /
        compute-bound verdict per tag. Fields are absent (not zero)
        where unattributable — check 11 skips gracefully on absence."""
        s = self.summary()
        if peaks is None:
            from ..hostenv import platform_peaks
            platform = platform or _detect_platform()
            peaks = platform_peaks(platform)
        platform = platform or "unknown"
        peak_b = float(peaks.get("bytes_per_s", 0.0))
        peak_f = float(peaks.get("flops_per_s", 0.0))
        ridge = (peak_f / peak_b) if peak_b > 0 and peak_f > 0 else None
        by_tag_cost: Dict[str, Any] = {}
        try:
            from .xla import global_xla
            by_tag_cost = global_xla.summary().get("by_tag", {})
        except Exception:
            pass
        hist = (global_metrics.meta or {}).get("hist_traffic") or {}
        by_tag: Dict[str, Dict[str, Any]] = {}
        for tag, dev_s in s["device_seconds_by_tag"].items():
            calls = int(s["calls_by_tag"].get(tag, 0))
            row: Dict[str, Any] = {"device_s": dev_s, "calls": calls,
                                   "phase": s["phase_by_tag"].get(tag, "")}
            cost = by_tag_cost.get(tag) or {}
            progs = max(int(cost.get("programs", 0)), 1)
            oi = None
            fl = cost.get("flops")
            byts = cost.get("bytes_accessed")
            if isinstance(byts, (int, float)) and byts > 0:
                bpc = byts / progs
                row["bytes_per_call"] = round(bpc, 1)
                if dev_s > 0 and calls > 0:
                    abps = bpc * calls / dev_s
                    row["achieved_bytes_per_s"] = round(abps, 1)
                    if peak_b > 0:
                        row["bytes_utilization"] = round(abps / peak_b, 8)
            if isinstance(fl, (int, float)) and fl > 0:
                fpc = fl / progs
                row["flops_per_call"] = round(fpc, 1)
                if dev_s > 0 and calls > 0:
                    afps = fpc * calls / dev_s
                    row["achieved_flops_per_s"] = round(afps, 1)
                    if peak_f > 0:
                        row["flops_utilization"] = round(afps / peak_f, 8)
                if isinstance(byts, (int, float)) and byts > 0:
                    oi = fl / byts
                    row["operational_intensity"] = round(oi, 4)
            if oi is not None and ridge is not None:
                row["verdict"] = ("memory-bound" if oi < ridge
                                  else "compute-bound")
            else:
                row["verdict"] = "unknown"
            by_tag[tag] = row
        out: Dict[str, Any] = {
            "platform": platform,
            "peaks": {"bytes_per_s": peak_b, "flops_per_s": peak_f},
            "window_wall_s": s["window_wall_s"],
            "source": s["source"],
            "by_tag": by_tag,
        }
        if ridge is not None:
            out["ridge_flops_per_byte"] = round(ridge, 4)
        if "coverage" in s:
            out["coverage"] = s["coverage"]
        if isinstance(hist.get("hist_bytes_per_iter"), (int, float)):
            out["model_hist_bytes_per_iter"] = hist["hist_bytes_per_iter"]
        return out

    # -- Chrome trace device lane (obs/trace.py merges these) ----------
    def device_lane_events(self, pid: int) -> List[Dict[str, Any]]:
        """Captured device slices as Chrome trace events on their own
        pid — metadata first (check_trace.py requires a process_name
        per pid and a thread_name per track), then the spans sorted by
        start so per-track ts stays monotonic."""
        with self._lock:
            slices = list(self._slices)
        if not slices:
            return []
        events: List[Dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": DEVICE_LANE_NAME}},
            {"name": "process_sort_index", "ph": "M", "pid": pid,
             "tid": 0, "args": {"sort_index": 1}},
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": "device programs (attributed)"}},
        ]
        for tag, t0_ns, dur_ns, source in sorted(slices,
                                                 key=lambda s: s[1]):
            events.append({"name": tag, "ph": "X", "pid": pid, "tid": 0,
                           "ts": t0_ns / 1e3, "dur": dur_ns / 1e3,
                           "args": {"tag": tag, "source": source}})
        return events


global_profile = ProfileRegistry()
