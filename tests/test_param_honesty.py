"""Audit: no accepted-but-silently-ignored parameters.

Every parameter the config accepts must either change behavior (tested
by effect) or warn when explicitly set (tested by log capture). This
guards the round-2 verdict's 'silent wrong-model territory' list:
extra_trees, feature_fraction_bynode, DART weighted drop, enable_bundle,
monotone_constraints_method, set_network.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import log as log_mod
from tests.conftest import make_binary


class _Capture:
    def __init__(self):
        self.msgs = []

    def info(self, m):
        self.msgs.append(m)

    def warning(self, m):
        self.msgs.append(m)


@pytest.fixture
def captured_log():
    from lightgbm_tpu import config as config_mod
    config_mod._WARNED_UNSUPPORTED.clear()
    log_mod.set_verbosity(1)  # earlier tests may have left level at fatal
    cap = _Capture()
    log_mod.register_logger(cap)
    yield cap
    log_mod._logger = None


def _train(params, rounds=5):
    X, y = make_binary(800)
    return lgb.train({"objective": "binary", "num_leaves": 15,
                      "min_data_in_leaf": 5, "verbosity": 0, **params},
                     lgb.Dataset(X, label=y), num_boost_round=rounds), X


def test_extra_trees_changes_model():
    b0, X = _train({"verbosity": -1})
    b1, _ = _train({"extra_trees": True, "verbosity": -1})
    assert not np.allclose(b0.predict(X), b1.predict(X))


def test_feature_fraction_bynode_changes_model():
    b0, X = _train({"verbosity": -1})
    b1, _ = _train({"feature_fraction_bynode": 0.4, "verbosity": -1})
    assert not np.allclose(b0.predict(X), b1.predict(X))


def test_dart_weighted_drop_differs_from_uniform():
    common = {"boosting": "dart", "drop_rate": 0.5, "verbosity": -1}
    b0, X = _train({**common, "uniform_drop": True}, rounds=10)
    b1, _ = _train({**common, "uniform_drop": False}, rounds=10)
    assert not np.allclose(b0.predict(X), b1.predict(X))


def test_enable_bundle_bundles_sparse_features():
    """enable_bundle is real now: mutually-exclusive one-hot columns are
    stored bundled (fewer stored columns than logical features)."""
    import lightgbm_tpu as lgb
    r = np.random.RandomState(0)
    n = 400
    labels = r.randint(0, 8, n)
    X = np.zeros((n, 8))
    X[np.arange(n), labels] = 1.0  # strict one-hot: zero conflicts
    y = (labels % 2).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1,
                                         "min_data_in_bin": 1})
    ds.construct()
    binned = ds._binned
    assert binned.bundle_info is not None
    assert binned.bins_fm.shape[0] < binned.num_features


def test_monotone_method_advanced_no_warning(captured_log):
    """intermediate/advanced are implemented (exact pairwise leaf-box
    bounds — see ops/split.py compute_box_bounds), so requesting them
    must NOT warn a downgrade anymore."""
    _train({"monotone_constraints": [1, 0, 0, 0, 0, 0, 0, 0],
            "monotone_constraints_method": "advanced"})
    assert not any("monotone_constraints_method" in m
                   for m in captured_log.msgs)


def test_set_network_warns(captured_log):
    bst, _ = _train({})
    bst.set_network(["host1:123", "host2:123"], num_machines=2)
    assert any("set_network" in m for m in captured_log.msgs)


def test_unset_params_do_not_warn(captured_log):
    _train({})
    assert not any("has no effect" in m for m in captured_log.msgs)
