#!/usr/bin/env python
"""CI smoke for the serving subsystem (serve/).

Trains a small model, starts the in-process async server, warms the
serving program set, then fires 200 mixed-size concurrent requests
(B=1..64 low-latency path interleaved with medium coalesced batches)
and asserts:

1. every response is BIT-identical to calling `predict` directly on
   that request's rows, and
2. ZERO steady-state recompiles after warmup, on both the engine
   traversal tag and the AOT low-latency tag, via the always-on
   obs.metrics recompile counters.

Exit 0 = pass. Usage: python tools/check_serve.py
"""

import asyncio
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> int:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.ops.predict import PREDICT_TRACE_TAG
    from lightgbm_tpu.serve import (ModelRegistry, ModelServer,
                                    SERVE_LOWLAT_TAG)
    from lightgbm_tpu.serve.server import replay

    rng = np.random.RandomState(0)
    n, f = 1200, 10
    x = rng.randn(n, f)
    x[::7, 2] = np.nan
    y = ((np.nan_to_num(x[:, 2]) + x[:, 4]) > 0.5).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                    num_boost_round=10)

    registry = ModelRegistry()
    registry.load("smoke", booster=bst)
    direct = registry.get("smoke").model
    server = ModelServer(registry, max_batch_rows=2048, max_wait_ms=1.0)
    server.warm("smoke", f)

    warm_lowlat = global_metrics.recompiles(SERVE_LOWLAT_TAG)
    warm_traversal = global_metrics.recompiles(PREDICT_TRACE_TAG)

    # 200 mixed-size requests: the small/medium cycle repeated
    cycle = (1, 3, 8, 17, 40, 64, 2, 130, 31, 257, 5, 700, 16, 64,
             1, 1000, 23, 90, 11, 512)
    sizes = [cycle[i % len(cycle)] for i in range(200)]
    xt = rng.randn(sum(sizes), f)
    xt[::9, 2] = np.nan

    async def run():
        try:
            return await replay(server, "smoke", xt, sizes,
                                raw_score=True)
        finally:
            await server.close()

    t0 = time.perf_counter()
    outs = asyncio.run(run())
    elapsed = time.perf_counter() - t0

    failures = 0
    lo = 0
    for i, (s, out) in enumerate(zip(sizes, outs)):
        hi = lo + s
        want = direct.predict(xt[lo:hi], raw_score=True)
        if not np.array_equal(out, want):
            print(f"FAIL: request {i} ({s} rows) != direct predict "
                  f"(max abs diff {np.abs(out - want).max():g})")
            failures += 1
        lo = hi

    d_lowlat = global_metrics.recompiles(SERVE_LOWLAT_TAG) - warm_lowlat
    d_traversal = (global_metrics.recompiles(PREDICT_TRACE_TAG)
                   - warm_traversal)
    if d_lowlat or d_traversal:
        print(f"FAIL: steady-state recompiles (lowlat={d_lowlat}, "
              f"traversal={d_traversal}) — the warm bucket set leaked")
        failures += 1

    lat = global_metrics.latency_summary("serve/request")
    counters = {k: v for k, v in sorted(global_metrics.counters.items())
                if k.startswith("serve/")}
    print(f"served {len(outs)} requests ({lo} rows) in {elapsed:.2f}s "
          f"({lo / elapsed:.0f} rows/s); p50={lat['p50_ms']:.2f}ms "
          f"p99={lat['p99_ms']:.2f}ms; counters={counters}")
    if failures:
        print(f"check_serve: {failures} failure(s)")
        return 1
    print("check_serve: OK (bit-parity on 200 mixed requests, "
          "zero steady-state recompiles)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
