"""Training / CV entry points (ref: python-package/lightgbm/engine.py:109
train, :626 cv)."""

from __future__ import annotations

import copy
import signal
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset, LightGBMError
from .config import Config
from .obs.flightrec import global_flightrec
from .obs.health import HealthError
from .resilience import checkpoint as ckpt_mod
from .resilience import faults as faults_mod
from .resilience.errors import EXIT_PREEMPTED, PeerLostError


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          feval=None, init_model=None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None) -> Booster:
    """(ref: engine.py:109)"""
    params = dict(params or {})
    cfg = Config.from_params(params)
    # persistent compile cache at the train entry (compile_cache.py):
    # Booster.__init__ arms it too, but the explicit entry-point call
    # keeps the warm-start contract visible where ISSUE 14 pinned it
    from .compile_cache import configure as _configure_compile_cache
    _configure_compile_cache(cfg.tpu_compile_cache,
                             cfg.tpu_compile_cache_dir or None)
    if cfg.num_iterations != 100 and "num_boost_round" not in params:
        num_boost_round = cfg.num_iterations
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        callbacks = list(callbacks or [])
        callbacks.append(callback_mod.early_stopping(
            cfg.early_stopping_round, cfg.first_metric_only,
            verbose=cfg.verbosity > 0,
            min_delta=cfg.early_stopping_min_delta))

    booster = Booster(params=params, train_set=train_set)
    if init_model is not None:
        booster._load_init_model(init_model)

    valid_sets = valid_sets or []
    valid_names = valid_names or [f"valid_{i}" for i in range(len(valid_sets))]
    is_valid_contain_train = False
    train_data_name = "training"
    for vs, name in zip(valid_sets, valid_names):
        if vs is train_set:
            is_valid_contain_train = True
            train_data_name = name
            continue
        booster.add_valid(vs, name)

    callbacks = list(callbacks or [])
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                       if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    # attaching a telemetry callback opts the run into the metrics
    # registry (like needs_eval opts into per-iteration evals); scoped —
    # a telemetry run must not leave recording overhead enabled for
    # later unrelated trains in the same process
    from .obs.metrics import global_metrics
    restore_telemetry = _scoped_telemetry_enable(callbacks)

    # ------------------------------------------------------------------
    # fault-tolerant training (resilience/checkpoint.py): resume from an
    # existing checkpoint at tpu_checkpoint_path, snapshot every
    # tpu_checkpoint_every iterations, and turn SIGTERM into
    # finish-iteration -> snapshot -> exit(EXIT_PREEMPTED)
    ckpt_path = str(cfg.tpu_checkpoint_path or "")
    ckpt_every = int(cfg.tpu_checkpoint_every)
    booster.best_iteration = -1  # before restore: a resumed checkpoint
    # re-installs the best-iteration/score it recorded
    start_iteration = 0
    if ckpt_path:
        state = ckpt_mod.try_load(ckpt_path)  # corrupt file -> raises
        if state is not None:
            if init_model is not None:
                from . import log
                log.warning("tpu_checkpoint_path: checkpoint found; "
                            "its state supersedes init_model")
            start_iteration = ckpt_mod.restore_booster(booster, state)
            if state.get("finished"):
                # the checkpointed run had already DECIDED to stop
                # (early stopping / no splittable leaves): resuming
                # must not train the remaining rounds
                start_iteration = num_boost_round
            from . import log
            log.info(f"resumed from checkpoint {ckpt_path} at iteration "
                     f"{start_iteration}/{num_boost_round}")
            if global_flightrec.armed:
                global_flightrec.record("resume", iteration=start_iteration,
                                        path=ckpt_path)
    preempt = {"flag": False}
    prev_sigterm = _install_sigterm(preempt) if ckpt_path else None

    # distributed-training watchdog (resilience/watchdog.py): with
    # tpu_watchdog_deadline_s set, every iteration boundary runs a
    # deadline-bounded heartbeat; a hung peer becomes PeerLostError ->
    # checkpoint + exit(EXIT_PREEMPTED) instead of an infinite stall
    from .resilience import watchdog as watchdog_mod
    watchdog = watchdog_mod.from_config(cfg)

    interrupted = False
    try:
        for i in range(start_iteration, num_boost_round):
            faults = faults_mod.global_faults
            if faults.armed:
                faults.maybe_poison_labels(booster, i)
            try:
                for cb in callbacks_before:
                    cb(callback_mod.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=0, end_iteration=num_boost_round,
                        evaluation_result_list=None))
                should_stop = booster.update()
                telemetry = (global_metrics.snapshot()
                             if global_metrics.enabled else None)

                evaluation_result_list = []
                needs_eval = any(getattr(cb, "needs_eval", False)
                                 for cb in callbacks_after)
                if (valid_sets or cfg.is_provide_training_metric) and \
                        (needs_eval or (cfg.metric_freq > 0
                                        and (i + 1) % cfg.metric_freq == 0)):
                    if is_valid_contain_train or \
                            cfg.is_provide_training_metric:
                        evaluation_result_list.extend(
                            booster.eval_train(feval))
                    evaluation_result_list.extend(booster.eval_valid(feval))
                    if evaluation_result_list:
                        # eval-loss anomaly detector (obs/health.py): one
                        # attribute check when health isn't armed
                        from .obs.health import global_health
                        if global_health.enabled:
                            global_health.note_evals(
                                i, evaluation_result_list)
                try:
                    for cb in callbacks_after:
                        cb(callback_mod.CallbackEnv(
                            model=booster, params=params, iteration=i,
                            begin_iteration=0,
                            end_iteration=num_boost_round,
                            evaluation_result_list=evaluation_result_list,
                            telemetry=telemetry))
                except callback_mod.EarlyStopException as e:
                    booster.best_iteration = e.best_iteration + 1
                    for item in e.best_score:
                        booster.best_score.setdefault(
                            item[0], {})[item[1]] = item[2]
                    break
            except HealthError as exc:
                # black box first (obs/flightrec.py): the dump carries
                # the offending iteration's events, then the structured
                # alarm propagates unchanged
                if global_flightrec.armed:
                    global_flightrec.record(
                        "health_anomaly", iteration=i,
                        error=type(exc).__name__, detail=str(exc)[:500])
                    global_flightrec.maybe_dump(reason=type(exc).__name__)
                raise
            except (KeyboardInterrupt, SystemExit) as exc:
                # interrupt safety: finalize and hand back the
                # best-so-far booster (trees are only appended at
                # iteration granularity, so the model is consistent)
                # instead of propagating with a half-updated booster
                interrupted = True
                if global_flightrec.armed:
                    global_flightrec.record("interrupted", iteration=i,
                                            error=type(exc).__name__)
                from . import log
                log.warning(
                    f"training interrupted at iteration {i} "
                    f"({type(exc).__name__}); returning the booster "
                    f"with {booster.current_iteration()} completed "
                    "iterations")
                break

            # -- iteration boundary: peer-liveness heartbeat. Outside
            # the inner try on purpose: its SystemExit escalation must
            # not be swallowed by the interrupt-safety handler above.
            if watchdog is not None:
                try:
                    watchdog.beat(i)
                except PeerLostError as exc:
                    from . import log
                    if ckpt_path:
                        ckpt_mod.save_checkpoint(booster, ckpt_path,
                                                 num_boost_round,
                                                 finished=False)
                        if global_flightrec.armed:
                            global_flightrec.record("checkpoint",
                                                    iteration=i + 1,
                                                    path=ckpt_path)
                    log.warning(
                        f"peer lost at iteration {i} ({exc}); "
                        + (f"snapshot written to {ckpt_path}; "
                           if ckpt_path else "")
                        + f"exiting with code {EXIT_PREEMPTED} for "
                        "elastic resume on the surviving mesh")
                    if global_flightrec.armed:
                        global_flightrec.record(
                            "peer_lost", iteration=i,
                            deadline_s=exc.deadline_s,
                            exit_code=EXIT_PREEMPTED)
                    _flush_obs_egress(reason="peer_lost")
                    raise SystemExit(EXIT_PREEMPTED)

            # -- iteration boundary: durable snapshot / preemption exit
            if ckpt_path:
                if faults.armed and faults.kill_now(i):
                    preempt["flag"] = True  # injected preemption
                periodic = ckpt_every > 0 and (i + 1) % ckpt_every == 0
                if preempt["flag"] or periodic:
                    # finished=should_stop: a snapshot taken on the
                    # iteration that decided to stop (no splittable
                    # leaves) must make a resume return immediately,
                    # not train rounds the straight run never ran
                    ckpt_mod.save_checkpoint(booster, ckpt_path,
                                             num_boost_round,
                                             finished=should_stop)
                    if global_flightrec.armed:
                        global_flightrec.record("checkpoint",
                                                iteration=i + 1,
                                                path=ckpt_path)
                if preempt["flag"]:
                    from . import log
                    log.warning(
                        f"preempted: snapshot at iteration {i + 1} "
                        f"written to {ckpt_path}; exiting with code "
                        f"{EXIT_PREEMPTED}")
                    if global_flightrec.armed:
                        global_flightrec.record("preempt", iteration=i + 1,
                                                exit_code=EXIT_PREEMPTED)
                    _flush_obs_egress(reason="preempt")
                    raise SystemExit(EXIT_PREEMPTED)
            if should_stop:
                break
        # a SIGTERM that landed during an iteration whose callbacks
        # raised EarlyStopException breaks out ABOVE the boundary
        # block (the should_stop case reaches it and snapshots
        # finished=True there): still honor the preemption contract
        # (snapshot + exit 75). The snapshot is marked finished — the
        # run already decided to stop, so the supervisor's re-run
        # returns immediately with the recorded best iteration instead
        # of training the remaining rounds.
        if ckpt_path and preempt["flag"] and not interrupted:
            ckpt_mod.save_checkpoint(booster, ckpt_path,
                                     num_boost_round, finished=True)
            if global_flightrec.armed:
                global_flightrec.record("preempt", exit_code=EXIT_PREEMPTED,
                                        path=ckpt_path)
            _flush_obs_egress(reason="preempt")
            raise SystemExit(EXIT_PREEMPTED)
    finally:
        if watchdog is not None:
            watchdog.close()
        if prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, prev_sigterm)
            except (ValueError, OSError):
                pass
        restore_telemetry()
    if interrupted:
        _flush_obs_egress(reason="interrupted")
    if booster.best_iteration <= 0:
        booster.best_iteration = booster.current_iteration()
    return booster


def _install_sigterm(preempt: Dict[str, bool]):
    """SIGTERM -> request a graceful preemption: the training loop
    finishes the in-flight iteration, snapshots, and exits with
    EXIT_PREEMPTED. Returns the previous handler (to restore), or None
    when handlers cannot be installed here (non-main thread)."""
    def _on_sigterm(signum, frame):
        preempt["flag"] = True

    try:
        return signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        return None


def _flush_obs_egress(reason: str = "egress") -> None:
    """Push pending observability out before an abnormal return: the
    OpenMetrics textfile (if armed), the Chrome trace (if the tracer
    was given a path) and the flight-recorder black box (if armed) must
    reflect the run that just died."""
    try:
        from .obs.export import global_flusher
        global_flusher.maybe_flush(force=True)
        from .obs.trace import global_tracer
        if global_tracer.enabled and getattr(global_tracer, "trace_path",
                                             None):
            global_tracer.export_chrome(global_tracer.trace_path)
        global_flightrec.maybe_dump(reason=reason)
    except Exception:
        pass  # telemetry egress must never mask the real outcome


def _scoped_telemetry_enable(callbacks) -> Callable[[], None]:
    """Enable the metrics registry when a telemetry callback is attached;
    returns a restore function that puts the registry AND the tracer
    (switched on by metrics.enable()) back to their prior state, so the
    opt-in does not outlive the run it was requested for."""
    from .obs.health import global_health
    from .obs.memory import global_watermarks
    from .obs.metrics import global_metrics
    from .obs.trace import global_tracer
    from .obs.xla import global_xla
    if not any(getattr(cb, "needs_telemetry", False)
               for cb in (callbacks or [])):
        return lambda: None
    metrics_was, tracer_was = global_metrics.enabled, global_tracer.enabled
    xla_was = global_xla.enabled
    watermarks_was = global_watermarks.enabled
    health_was = global_health.enabled
    global_metrics.enable()

    def restore() -> None:
        if not metrics_was:
            global_metrics.disable()
            if not tracer_was:
                global_tracer.disable()
            if not xla_was:
                global_xla.disable()
            if not watermarks_was:
                global_watermarks.disable()
            if not health_was:
                global_health.disable()
    return restore


def continual_train(params: Dict[str, Any], chunks,
                    num_features: Optional[int] = None,
                    registry=None, serve_name: str = "continual",
                    on_generation: Optional[Callable] = None):
    """Continual-training entry point (resilience/continual.py): drive
    one generation per ingested chunk through the long-lived
    ``ContinualTrainer`` — ``init_model`` continuation (or refit),
    eval-anomaly accept-vs-rollback, and validated hot-swap into
    `registry` when given. `chunks` yields ``(X, y)`` or
    ``(X, y, weight)``; `on_generation` (if given) is called with each
    :class:`GenerationResult`. Returns the trainer (its ``booster()``
    is the last-good model; ``summary()`` the lgbmtpu_continual_*
    export payload). Knobs: ``tpu_continual_*``, ``tpu_elastic_resume``
    and the PR-8 ``tpu_checkpoint_*`` family (a kill mid-generation
    exits 75 and the re-run resumes that generation)."""
    from .resilience.continual import ContinualTrainer
    trainer = None
    for chunk in chunks:
        X, y = chunk[0], chunk[1]
        w = chunk[2] if len(chunk) > 2 else None
        if trainer is None:
            nf = int(num_features if num_features is not None
                     else np.atleast_2d(np.asarray(X)).shape[1])
            trainer = ContinualTrainer(params, nf, registry=registry,
                                       serve_name=serve_name)
        trainer.push_rows(X, label=y, weight=w)
        result = trainer.step()
        if on_generation is not None:
            on_generation(result)
    if trainer is None:
        raise ValueError("continual_train received no chunks")
    return trainer


class CVBooster:
    """Ensemble of per-fold boosters (ref: engine.py:299 CVBooster)."""

    def __init__(self, model_file=None):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> "CVBooster":
        self.boosters.append(booster)
        return self

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _mean_fold_telemetry(fold_snaps):
    """Cross-fold telemetry for one cv round: numeric metrics and phase
    times averaged over the folds' per-iteration records (a single
    fold's snapshot would misrepresent the round). None when empty."""
    if not fold_snaps:
        return None
    out: Dict[str, Any] = {"folds": len(fold_snaps)}
    keys = {k for s in fold_snaps for k in s if k != "phases"}
    for k in keys:
        vals = [s[k] for s in fold_snaps
                if isinstance(s.get(k), (int, float))]
        if vals:
            out[k] = (fold_snaps[0][k] if k == "iteration"
                      else float(np.mean(vals)))
    pnames = {p for s in fold_snaps for p in s.get("phases", {})}
    if pnames:
        out["phases"] = {p: float(np.mean(
            [s.get("phases", {}).get(p, 0.0) for s in fold_snaps]))
            for p in pnames}
    return out


def _make_n_folds(full_data: Dataset, nfold: int, params, seed: int,
                  stratified: bool, shuffle: bool):
    full_data.construct()
    num_data = full_data.num_data()
    rng = np.random.RandomState(seed)
    label = np.asarray(full_data.label)
    if stratified:
        # stratified folds by label value
        folds = [[] for _ in range(nfold)]
        for val in np.unique(label):
            idx = np.flatnonzero(label == val)
            if shuffle:
                rng.shuffle(idx)
            for j, chunk in enumerate(np.array_split(idx, nfold)):
                folds[j].extend(chunk.tolist())
        test_indices = [np.asarray(sorted(f)) for f in folds]
    else:
        idx = np.arange(num_data)
        if shuffle:
            rng.shuffle(idx)
        test_indices = [np.sort(chunk) for chunk in np.array_split(idx, nfold)]
    for test_idx in test_indices:
        train_idx = np.setdiff1d(np.arange(num_data), test_idx)
        yield train_idx, test_idx


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True,
       shuffle: bool = True, metrics=None, feval=None,
       init_model=None, seed: int = 0,
       callbacks: Optional[List[Callable]] = None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    """(ref: engine.py:626)"""
    params = dict(params or {})
    if metrics is not None:
        params["metric"] = metrics
    cfg = Config.from_params(params)
    from .compile_cache import configure as _configure_compile_cache
    _configure_compile_cache(cfg.tpu_compile_cache,
                             cfg.tpu_compile_cache_dir or None)
    if cfg.num_iterations != 100 and "num_boost_round" not in params:
        num_boost_round = cfg.num_iterations
    if cfg.objective in ("binary", "multiclass", "multiclassova") \
            and stratified is None:
        stratified = True
    if cfg.objective in ("lambdarank", "rank_xendcg"):
        stratified = False

    if folds is not None:
        fold_iter = folds
    else:
        fold_iter = _make_n_folds(train_set, nfold, params, seed, stratified
                                  and cfg.objective in
                                  ("binary", "multiclass", "multiclassova"),
                                  shuffle)

    cvbooster = CVBooster()
    fold_data = []
    for train_idx, test_idx in fold_iter:
        dtrain = train_set.subset(train_idx)
        dvalid = train_set.subset(test_idx)
        fold_data.append((dtrain, dvalid))

    results: Dict[str, List[float]] = {}
    boosters = []
    for dtrain, dvalid in fold_data:
        bst = Booster(params=params, train_set=dtrain)
        if init_model is not None:
            # continued training per fold (ref: engine.py cv fpreproc-less
            # path passes init_model through to each fold booster)
            bst._load_init_model(init_model)
        bst.add_valid(dvalid, "valid")
        boosters.append(bst)
        cvbooster.append(bst)

    cb_early = None
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        cb_early = callback_mod.early_stopping(
            cfg.early_stopping_round, cfg.first_metric_only,
            verbose=cfg.verbosity > 0)

    from .obs.metrics import global_metrics
    restore_telemetry = _scoped_telemetry_enable(callbacks)

    try:
        for i in range(num_boost_round):
            all_results: Dict[str, List[float]] = {}
            fold_telemetry: List[Dict[str, Any]] = []
            for bst in boosters:
                bst.update()
                if global_metrics.enabled and global_metrics.snapshot():
                    fold_telemetry.append(global_metrics.snapshot())
                res = bst.eval_valid(feval)
                if eval_train_metric:
                    res = bst.eval_train(feval) + res
                for name, metric, value, hib in res:
                    all_results.setdefault(
                        f"{name} {metric}", []).append(value)
                    all_results.setdefault(
                        f"__hib {name} {metric}", []).append(hib)
            evaluation_result_list = []
            for key, values in all_results.items():
                if key.startswith("__hib"):
                    continue
                hib = all_results[f"__hib {key}"][0]
                mean, std = float(np.mean(values)), float(np.std(values))
                results.setdefault(key + "-mean", []).append(mean)
                results.setdefault(key + "-stdv", []).append(std)
                evaluation_result_list.append(("cv_agg", key, mean, hib))
            try:
                env = callback_mod.CallbackEnv(
                    model=cvbooster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=evaluation_result_list,
                    telemetry=_mean_fold_telemetry(fold_telemetry))
                if cb_early is not None:
                    cb_early(env)
                for cb in (callbacks or []):
                    cb(env)
            except callback_mod.EarlyStopException as e:
                cvbooster.best_iteration = e.best_iteration + 1
                for key in list(results.keys()):
                    results[key] = results[key][:cvbooster.best_iteration]
                break
    finally:
        restore_telemetry()

    if return_cvbooster:
        results["cvbooster"] = cvbooster
    return results
