"""Text data loading: CSV / TSV / LibSVM auto-detect.

(ref: src/io/parser.hpp:19,57,94 CSVParser/TSVParser/LibSVMParser and the
format auto-detection in parser.cpp:261; sidecar `.weight` / `.query`
files as in src/io/metadata.cpp LoadWeights/LoadQueryBoundaries.)

The C-accelerated parser lives in native/src/lgbm_tpu_native.cpp (used
automatically when the native library builds); this numpy
path is the portable fallback.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np


def _detect_format(first_lines: List[str]) -> str:
    for line in first_lines:
        if not line.strip():
            continue
        tokens = line.replace("\t", " ").split()
        if any(":" in t for t in tokens[1:]):
            return "libsvm"
        if "\t" in line:
            return "tsv"
        if "," in line:
            return "csv"
    return "tsv"


def load_svmlight_or_csv(path: str, params: Dict
                         ) -> Tuple[np.ndarray, Optional[np.ndarray],
                                    Optional[np.ndarray],
                                    Optional[np.ndarray]]:
    """Returns (data [N, F], label [N], weight or None, group sizes or None).

    Label column defaults to column 0 (ref: config label_column).
    """
    has_header = str(params.get("header", params.get("has_header", "false"))
                     ).lower() in ("true", "1")
    label_column = params.get("label_column", params.get("label", ""))

    # native parser fast path (ref: src/io/parser.hpp; built from
    # native/src/lgbm_tpu_native.cpp). Name-based label columns need the
    # header names, resolved here before delegating.
    if not isinstance(label_column, str) or \
            not label_column.startswith("name:"):
        from .. import native as _native
        label_idx_n = int(label_column) if str(label_column).isdigit() else 0
        parsed = None
        try:
            parsed = _native.parse_file(path, label_idx_n, has_header)
        except ValueError:
            parsed = None  # malformed for the fast path; numpy decides
        if parsed is not None:
            data, label = parsed
            return data, label, _sidecar_weight(path), _sidecar_group(path)

    with open(path) as fh:
        lines = [ln.rstrip("\n") for ln in fh]
    lines = [ln for ln in lines if ln.strip()]
    if not lines:
        raise ValueError(f"empty data file: {path}")
    fmt = _detect_format(lines[:10])

    header_names: Optional[List[str]] = None
    if has_header and fmt in ("csv", "tsv"):
        sep = "," if fmt == "csv" else "\t"
        header_names = lines[0].split(sep)
        lines = lines[1:]

    label_idx = 0
    if isinstance(label_column, str) and label_column.startswith("name:"):
        name = label_column[5:]
        if header_names and name in header_names:
            label_idx = header_names.index(name)
    elif str(label_column).isdigit():
        label_idx = int(label_column)

    if fmt == "libsvm":
        labels = np.empty(len(lines), np.float64)
        rows: List[Dict[int, float]] = []
        max_feat = -1
        for i, line in enumerate(lines):
            toks = line.replace("\t", " ").split()
            labels[i] = float(toks[0])
            row = {}
            for t in toks[1:]:
                if ":" not in t:
                    continue
                k, v = t.split(":", 1)
                k = int(k)
                row[k] = float(v)
                max_feat = max(max_feat, k)
            rows.append(row)
        data = np.zeros((len(lines), max_feat + 1), np.float64)
        for i, row in enumerate(rows):
            for k, v in row.items():
                data[i, k] = v
        label = labels
    else:
        sep = "," if fmt == "csv" else "\t"
        rows = [[_parse_float(x) for x in ln.split(sep)] for ln in lines]
        widths = {len(r) for r in rows}
        if len(widths) > 1:
            raise ValueError(
                f"{path}: inconsistent column count across rows "
                f"(saw {sorted(widths)})")
        mat = np.array(rows, dtype=np.float64)
        label = mat[:, label_idx].copy()
        data = np.delete(mat, label_idx, axis=1)

    return data, label, _sidecar_weight(path), _sidecar_group(path)


def _sidecar_weight(path: str) -> Optional[np.ndarray]:
    wfile = path + ".weight"
    if os.path.exists(wfile):
        return np.loadtxt(wfile, dtype=np.float64).reshape(-1)
    return None


def sidecar_init_score(path: str) -> Optional[np.ndarray]:
    """<data>.init initial scores (ref: metadata.cpp:763-766
    LoadInitialScore auto-detects the sidecar). Multi-column files
    (multiclass) are returned class-major [k*N + i] as the reference
    stores them (metadata.cpp SetInitScore layout), which is what
    GBDT.__init__'s reshape(K, N) expects."""
    ifile = path + ".init"
    if os.path.exists(ifile):
        return np.loadtxt(ifile, dtype=np.float64, ndmin=2).T.reshape(-1)
    return None


def sidecar_position(path: str) -> Optional[np.ndarray]:
    """<data>.position per-row positions for position-bias ranking
    (ref: metadata.cpp:735-741 LoadPositions — position entries are
    arbitrary strings mapped to dense ids by first appearance)."""
    pfile = path + ".position"
    if not os.path.exists(pfile):
        return None
    with open(pfile) as fh:
        entries = [ln.strip() for ln in fh if ln.strip()]
    try:
        return np.asarray([int(e) for e in entries], np.int64)
    except ValueError:
        ids: Dict[str, int] = {}
        return np.asarray([ids.setdefault(e, len(ids)) for e in entries],
                          np.int64)


def _sidecar_group(path: str) -> Optional[np.ndarray]:
    qfile = path + ".query"
    if os.path.exists(qfile):
        return np.loadtxt(qfile, dtype=np.int64).reshape(-1)
    return None


def _parse_float(tok: str) -> float:
    tok = tok.strip()
    if not tok or tok.lower() in ("na", "nan", "null", "none", "?"):
        return float("nan")
    try:
        return float(tok)
    except ValueError:
        return float("nan")
