"""scikit-learn estimator API tests.

(ref: python-package/lightgbm/sklearn.py:535 LGBMModel and
tests/python_package_test/test_sklearn.py — fit/predict semantics,
classes_ mapping, params round-trip, early stopping, ranker groups.)
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,
                                  LGBMRegressor)

from conftest import make_binary, make_regression


def _make_multiclass(n=800, f=8, k=3, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (np.abs(X[:, 0]) + X[:, 1] + 0.3 * r.randn(n))
    y = np.digitize(y, np.quantile(y, np.linspace(0, 1, k + 1)[1:-1]))
    return X, y.astype(np.int64)


# -- regressor ---------------------------------------------------------

def test_regressor_fit_predict():
    X, y = make_regression(800)
    m = LGBMRegressor(n_estimators=20, num_leaves=15)
    m.fit(X, y)
    pred = m.predict(X)
    assert pred.shape == (800,)
    assert m.score(X, y) > 0.7


def test_regressor_objective_l1():
    X, y = make_regression(500)
    m = LGBMRegressor(n_estimators=10, objective="regression_l1")
    m.fit(X, y)
    assert np.isfinite(m.predict(X)).all()


def test_regressor_sparse_input():
    sp = pytest.importorskip("scipy.sparse")
    X, y = make_regression(500)
    X[np.abs(X) < 0.8] = 0.0
    m = LGBMRegressor(n_estimators=10, num_leaves=7)
    m.fit(sp.csr_matrix(X), y)
    assert m.n_features_ == X.shape[1]
    np.testing.assert_allclose(m.predict(sp.csr_matrix(X)), m.predict(X),
                               rtol=1e-6, atol=1e-9)


# -- classifier --------------------------------------------------------

def test_classifier_binary():
    X, y = make_binary(800)
    m = LGBMClassifier(n_estimators=20, num_leaves=15)
    m.fit(X, y)
    assert m.n_classes_ == 2
    assert set(m.predict(X)) <= set(m.classes_)
    proba = m.predict_proba(X)
    assert proba.shape == (800, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    assert m.score(X, y) > 0.8


def test_classifier_label_mapping():
    """Non-contiguous labels must map back through classes_."""
    X, y01 = make_binary(600)
    y = np.where(y01 > 0, 7, 3)
    m = LGBMClassifier(n_estimators=10)
    m.fit(X, y)
    np.testing.assert_array_equal(m.classes_, [3, 7])
    assert set(m.predict(X)) <= {3, 7}
    # proba column order follows classes_
    proba = m.predict_proba(X)
    acc = np.mean(np.where(proba[:, 1] > 0.5, 7, 3) == y)
    assert acc > 0.8


@pytest.mark.slow
def test_classifier_multiclass():
    X, y = _make_multiclass()
    m = LGBMClassifier(n_estimators=15, num_leaves=15)
    m.fit(X, y)
    assert m.n_classes_ == 3
    proba = m.predict_proba(X)
    assert proba.shape == (800, 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)
    assert m.score(X, y) > 0.6


def test_classifier_class_weight_balanced():
    X, y = make_binary(800)
    # unbalance the data
    keep = np.concatenate([np.flatnonzero(y == 0)[:50],
                           np.flatnonzero(y == 1)])
    Xu, yu = X[keep], y[keep]
    m = LGBMClassifier(n_estimators=10, class_weight="balanced")
    m.fit(Xu, yu)
    # balanced weighting should not collapse to the majority class
    assert 0 < np.mean(m.predict(Xu) == 0)


def test_classifier_raw_score_and_leaf():
    X, y = make_binary(400)
    m = LGBMClassifier(n_estimators=5, num_leaves=7)
    m.fit(X, y)
    raw = m.predict(X, raw_score=True)
    assert raw.dtype.kind == "f" and np.abs(raw).max() > 0
    leaves = m.predict(X, pred_leaf=True)
    assert leaves.shape == (400, 5)
    assert leaves.dtype.kind == "i"


# -- eval sets + early stopping ---------------------------------------

def test_eval_set_early_stopping():
    X, y = make_binary(1200)
    Xt, Xv, yt, yv = X[:800], X[800:], y[:800], y[800:]
    m = LGBMClassifier(n_estimators=200, num_leaves=31, learning_rate=0.3)
    m.fit(Xt, yt, eval_set=[(Xv, yv)], eval_metric="binary_logloss",
          callbacks=[lgb.early_stopping(5, verbose=False)])
    assert 0 < m.best_iteration_ < 200
    assert "valid_0" in m.best_score_
    # predict honors best_iteration automatically
    p_best = m.predict_proba(Xv)[:, 1]
    p_all = m.booster_.predict(Xv, num_iteration=m.booster_.num_trees())
    assert p_best.shape == p_all.shape


def test_eval_set_reuses_train():
    X, y = make_binary(500)
    evals = {}
    m = LGBMClassifier(n_estimators=8)
    m.fit(X, y, eval_set=[(X, y)], eval_metric="auc",
          callbacks=[lgb.record_evaluation(evals)])
    (name,) = evals.keys()
    assert len(evals[name]["auc"]) == 8


# -- params round-trip -------------------------------------------------

def test_get_set_params_roundtrip():
    m = LGBMClassifier(n_estimators=42, num_leaves=9, my_custom=3)
    p = m.get_params()
    assert p["n_estimators"] == 42 and p["num_leaves"] == 9
    assert p["my_custom"] == 3
    m2 = LGBMClassifier()
    m2.set_params(**p)
    assert m2.get_params() == p


def test_set_params_kwargs_bucket():
    m = LGBMRegressor()
    m.set_params(max_bin=127)
    assert m.get_params()["max_bin"] == 127
    X, y = make_regression(300)
    m.set_params(n_estimators=5)
    m.fit(X, y)
    assert m.booster_.num_trees() == 5


def test_clone_compatible():
    try:
        from sklearn.base import clone
    except ImportError:
        pytest.skip("sklearn not installed")
    m = LGBMClassifier(n_estimators=7, num_leaves=5)
    m2 = clone(m)
    assert m2.get_params()["n_estimators"] == 7


# -- introspection -----------------------------------------------------

def test_feature_importances_and_names():
    X, y = make_binary(500)
    m = LGBMClassifier(n_estimators=10, importance_type="gain")
    m.fit(X, y, feature_name=[f"f{i}" for i in range(X.shape[1])])
    imp = m.feature_importances_
    assert imp.shape == (X.shape[1],)
    assert imp.sum() > 0
    assert m.feature_name_ == [f"f{i}" for i in range(X.shape[1])]


def test_not_fitted_errors():
    m = LGBMClassifier()
    with pytest.raises(lgb.LightGBMError):
        m.predict(np.zeros((2, 3)))
    with pytest.raises(lgb.LightGBMError):
        _ = m.feature_importances_


# -- ranker ------------------------------------------------------------

def test_ranker_fit_with_groups():
    r = np.random.RandomState(0)
    n_q, per_q = 40, 12
    n = n_q * per_q
    X = r.randn(n, 6)
    rel = np.clip((X[:, 0] + 0.4 * r.randn(n)) * 1.2 + 1.5, 0, 4)
    y = rel.astype(int)
    group = np.full(n_q, per_q)
    m = LGBMRanker(n_estimators=15, num_leaves=7,
                   min_child_samples=5)
    m.fit(X, y, group=group, eval_set=[(X, y)], eval_group=[group],
          eval_metric="ndcg")
    scores = m.predict(X)
    assert scores.shape == (n,)
    # ranking quality: top-scored docs in each query should have higher
    # mean relevance than bottom-scored
    tops, bots = [], []
    for q in range(n_q):
        s = scores[q * per_q:(q + 1) * per_q]
        rq = y[q * per_q:(q + 1) * per_q]
        order = np.argsort(-s)
        tops.append(rq[order[:3]].mean())
        bots.append(rq[order[-3:]].mean())
    assert np.mean(tops) > np.mean(bots)


def test_ranker_requires_group():
    X, y = make_binary(100)
    with pytest.raises(lgb.LightGBMError):
        LGBMRanker().fit(X, y)


def test_top_level_exports():
    assert lgb.LGBMClassifier is LGBMClassifier
    assert lgb.LGBMRegressor is LGBMRegressor
    assert lgb.LGBMRanker is LGBMRanker
    assert lgb.LGBMModel is LGBMModel
