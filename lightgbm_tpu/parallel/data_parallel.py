"""Data-parallel boosting over a device mesh.

TPU-native replacement for the reference's distributed tree learners
(ref: src/treelearner/data_parallel_tree_learner.cpp — rows sharded,
histograms ReduceScatter-summed, best split Allgather'd; and NCCLGBDT
src/boosting/cuda/nccl_gbdt.hpp:30 for single-process multi-GPU).

Architecture: rows are sharded over the mesh "data" axis. The one-hot
histogram contraction contracts over the sharded row dimension, so XLA's
SPMD partitioner automatically inserts the cross-device reduce (the
psum that replaces HistogramSumReducer + ReduceScatter at
data_parallel_tree_learner.cpp:287-297). Split finding then runs
replicated on every shard — equivalent state, no explicit sync needed
(the reference's Allgather of SplitInfo becomes redundant by replication).
Voting-parallel's top-k filtered reduce is a bandwidth optimization of the
same program and is handled by the same partitioner.

One jitted program per tree spans the whole mesh — the reference's
per-split network round-trips disappear.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..boosting import DART, GBDT, RF
from ..config import Config
from ..dataset import BinnedDataset
from ..obs.metrics import global_metrics
from ..obs.trace import global_tracer
from ..objectives import ObjectiveFunction
from . import mesh as mesh_lib


class _DataParallelMixin:
    """Shards row-indexed device state over the mesh data axis."""

    def _setup_sharding(self, num_shards: int):
        with global_tracer.span("parallel/setup_sharding"):
            self._setup_sharding_inner(num_shards)
        global_metrics.set_meta("mesh_size", int(self.mesh.size))
        global_metrics.set_meta("tree_learner",
                                str(self.config.tree_learner))
        # timed collective microprobe (obs/health.py): one psum + one
        # all_gather over the real mesh, device-synchronized — the
        # measured per-byte rate the runtime byte counters are priced
        # with. Health-enabled runs only; never on a 1-device mesh.
        from ..obs.health import global_health
        if global_health.enabled and self.mesh.size > 1:
            global_health.probe_collectives(self.mesh)

    def _setup_sharding_inner(self, num_shards: int):
        self.mesh = mesh_lib.get_mesh(num_shards)
        if jax.process_count() > 1:
            with global_tracer.span("parallel/setup_multihost"):
                self._setup_multihost()
            return
        if self.num_data % max(self.mesh.size, 1) != 0:
            # NamedSharding needs equal shards. Eligible learners pad the
            # row tensors with masked rows to the next mesh multiple and
            # keep storage FULLY SHARDED (pad rows carry sample_mask 0,
            # so they contribute no statistics; every host consumer
            # slices back to the real row count). Configurations whose
            # row state can't be padded uniformly fall back to
            # replicated row tensors: the pallas histogram path still
            # distributes its passes (the shard_map wrapper pads rows
            # internally, learner._pad_rows), the XLA path degrades to
            # a replicated program.
            import warnings
            if self.mesh.size > 1 and self._row_pad_eligible():
                self._pad_and_shard_rows()
                self.feature_meta = jax.tree_util.tree_map(
                    lambda a: mesh_lib.replicate(self.mesh, a),
                    self.feature_meta)
                self._build_grow_sharded()
                return
            warnings.warn(
                f"num_data={self.num_data} is not divisible by the "
                f"{self.mesh.size}-device mesh and this configuration "
                "cannot pad row state; row tensors are kept replicated "
                "(pad the dataset to a mesh multiple for fully sharded "
                "storage)")
            self.feature_meta = jax.tree_util.tree_map(
                lambda a: mesh_lib.replicate(self.mesh, a),
                self.feature_meta)
            if self.mesh.size > 1:
                # scatter needs genuinely row-sharded builds (replicated
                # rows would change the psum-oracle's accumulation
                # grouping and break bit-parity) — force the psum path
                self._build_grow_sharded(scatter_ok=False)
            return
        if self._stream is not None:
            # out-of-core streaming: bins stay HOST-resident; only the
            # row-indexed device state shards. Slab uploads land
            # row-sharded over the data axis (HostSlabBins.stage) and
            # the XLA histogram contraction partitions under GSPMD —
            # the grower is rebuilt with the mesh below.
            self.scores = mesh_lib.shard_data(self.mesh, self.scores,
                                              row_axis=1)
            self._sample_mask = mesh_lib.shard_data(
                self.mesh, self._sample_mask, row_axis=0)
            self.feature_meta = jax.tree_util.tree_map(
                lambda a: mesh_lib.replicate(self.mesh, a),
                self.feature_meta)
            if self.mesh.size > 1:
                self._stream.mesh = self.mesh
                self._build_grow_sharded()
            return
        # bins [F, N]: rows sharded, features replicated
        self.bins_fm = mesh_lib.shard_data(self.mesh, self.bins_fm, row_axis=1)
        # scores [K, N]: rows sharded
        self.scores = mesh_lib.shard_data(self.mesh, self.scores, row_axis=1)
        self._sample_mask = mesh_lib.shard_data(self.mesh, self._sample_mask,
                                                row_axis=0)
        self.feature_meta = jax.tree_util.tree_map(
            lambda a: mesh_lib.replicate(self.mesh, a), self.feature_meta)
        if self.mesh.size > 1:
            self._build_grow_sharded()

    def _row_pad_eligible(self) -> bool:
        """Whether this learner's ROW state can be uniformly padded to a
        mesh multiple (the non-divisible satellite of the reduce-scatter
        learner). Conservative: plain GBDT with a built-in pointwise
        objective only — ranking objectives hold query-shaped state,
        linear trees / streaming / COO run host-side row logic, and
        DART/RF mutate scores outside the guarded jit paths."""
        if getattr(self, "boosting_type", "") != "gbdt":
            return False
        if self.objective is None or getattr(self.objective,
                                             "is_ranking", False):
            return False
        if self.config.linear_tree:
            return False
        if self._stream is not None or self._sparse_shape is not None:
            return False
        bins = self.bins_fm
        return (isinstance(bins, jax.Array) and bins.ndim == 2
                and bins.shape[1] == self.num_data)

    def _pad_and_shard_rows(self) -> None:
        """Pad every row-indexed device tensor with masked rows to the
        next mesh multiple and shard it — `self.num_data` keeps the REAL
        row count and `self._row_pad` records the tail, which the
        sampling/quantization draws and the host-facing score reads
        respect (boosting.py guards). Pad rows carry sample_mask 0 and
        zero bins, so they contribute nothing to any statistic."""
        import warnings
        mult = int(self.mesh.size)
        pad = (-self.num_data) % mult
        warnings.warn(
            f"num_data={self.num_data} is not divisible by the "
            f"{mult}-device mesh; padding row tensors with {pad} masked "
            "rows to keep storage fully sharded")
        self._row_pad = pad
        self.bins_fm = mesh_lib.shard_data(
            self.mesh, jnp.pad(jnp.asarray(self.bins_fm),
                               ((0, 0), (0, pad))), row_axis=1)
        self.scores = mesh_lib.shard_data(
            self.mesh, jnp.pad(jnp.asarray(self.scores),
                               ((0, 0), (0, pad))), row_axis=1)
        self._sample_mask = mesh_lib.shard_data(
            self.mesh, jnp.pad(jnp.asarray(self._sample_mask),
                               (0, pad)), row_axis=0)
        # objective device buffers, same shape dispatch as the
        # multi-host assembly above: [N]-leading pads+shards on axis 0,
        # [.., N] on axis 1, everything else replicates
        if self.objective is not None:
            n = self.num_data
            for name, arr in list(vars(self.objective).items()):
                if not isinstance(arr, jax.Array):
                    continue
                if arr.ndim >= 1 and arr.shape[0] == n:
                    cfg = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
                    garr = mesh_lib.shard_data(
                        self.mesh, jnp.pad(arr, cfg), row_axis=0)
                elif arr.ndim >= 2 and arr.shape[1] == n:
                    cfg = [(0, 0), (0, pad)] + [(0, 0)] * (arr.ndim - 2)
                    garr = mesh_lib.shard_data(
                        self.mesh, jnp.pad(arr, cfg), row_axis=1)
                else:
                    garr = mesh_lib.replicate(self.mesh, arr)
                setattr(self.objective, name, garr)

    def _build_grow_sharded(self, scatter_ok: bool = True):
        """pallas_call does not auto-partition under GSPMD, so the pallas
        histogram kernels run per-shard inside shard_map with an explicit
        reduce (learner._sharded_pallas_{build,multi}); the XLA one-hot
        path partitions its contraction automatically under psum, and
        runs inside the same shard_map builders when the reduce-scatter
        protocol is on (tpu_hist_reduce=scatter, parallel/scatter.py)."""
        from ..ops import histogram as hist_ops
        from .scatter import resolve_hist_reduce
        impl = hist_ops.resolve_impl(self.config.tpu_hist_impl)
        hr = resolve_hist_reduce(self.config.tpu_hist_reduce, self.mesh,
                                 int(self.train_set.num_features))
        if not scatter_ok or self._stream is not None or \
                self._sparse_shape is not None:
            hr = "psum"
        if impl == "pallas":
            self._build_grow("pallas", shard_mesh=self.mesh,
                             hist_reduce=hr)
        elif hr == "scatter":
            self._build_grow("xla", shard_mesh=self.mesh,
                             hist_reduce="scatter")
        else:
            self._build_grow("xla")

    def _setup_multihost(self):
        """Assemble globally-sharded state from this process's row shard
        (ref: distributed loading at dataset_loader.cpp:211 — every
        machine holds its own rows; plus GBDT's init-score mean sync at
        gbdt.cpp:322). Requires jax.distributed to be initialized
        (parallel.distributed.init_distributed) and every process to
        hold an equal-size shard divisible by its local device count."""
        from . import distributed as dist
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self.mesh
        n_local = int(self.train_set.num_data)
        n_dev_local = len(jax.local_devices())
        if n_local % n_dev_local != 0:
            raise ValueError(
                f"multi-host shard of {n_local} rows is not divisible by "
                f"the {n_dev_local} local devices; pad or repartition "
                "the input (the reference pre-partitions too, "
                "tests/distributed/_test_distributed.py)")

        host_bins = np.asarray(self.train_set.bins_fm)
        self.bins_fm = dist.make_global_array(mesh, host_bins, row_axis=1)
        self.num_data = self.bins_fm.shape[1]
        # preserve whatever the base init put into the local scores
        # (dataset init_score offsets) — still process-local here
        self.scores = dist.make_global_array(
            mesh, np.asarray(self.scores, np.float32), row_axis=1)
        self._sample_mask = dist.make_global_array(
            mesh, np.asarray(self._sample_mask, np.float32), row_axis=0)
        self.feature_meta = jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a),
                                     NamedSharding(mesh, P())),
            self.feature_meta)
        # ranking objectives hold query-padded state whose shape/content
        # is per-process under local init; rebuild them from GLOBAL
        # metadata (labels + query sizes allgathered in process order,
        # matching the row-shard order) so every process carries the
        # IDENTICAL global state — the global program then computes
        # exact global lambdas, where the reference's distributed
        # lambdarank approximates with machine-local ones
        # (rank_objective.hpp works per-machine)
        if self.objective is not None and getattr(
                self.objective, "is_ranking", False):
            from jax.experimental import multihost_utils as mh
            from ..dataset import Metadata
            meta_l = self.train_set.metadata
            if meta_l.query_boundaries is None:
                raise ValueError(
                    "ranking objective requires group/query data on "
                    "every worker's partition")
            sizes_l = np.diff(meta_l.query_boundaries).astype(np.int64)
            nproc = jax.process_count()
            nq = np.asarray(mh.process_allgather(
                np.asarray([len(sizes_l)], np.int64))).reshape(-1)
            maxq = int(nq.max())
            pad_sizes = np.zeros(maxq, np.int64)
            pad_sizes[:len(sizes_l)] = sizes_l
            all_sizes = np.asarray(
                mh.process_allgather(pad_sizes)).reshape(nproc, maxq)
            glob_sizes = np.concatenate(
                [all_sizes[p, :int(nq[p])] for p in range(nproc)])
            lab = np.asarray(mh.process_allgather(np.asarray(
                meta_l.label, np.float32))).reshape(-1)
            total_n = int(lab.shape[0])
            gmeta = Metadata(total_n)
            gmeta.set_label(lab)
            gmeta.set_group(glob_sizes)
            if meta_l.weight is not None:
                gmeta.set_weight(np.asarray(mh.process_allgather(
                    np.asarray(meta_l.weight, np.float32))).reshape(-1))
            if meta_l.positions is not None:
                gmeta.positions = np.asarray(mh.process_allgather(
                    np.asarray(meta_l.positions,
                               np.int32))).reshape(-1)
            self.objective.init(gmeta, total_n)

        # objective device buffers: [N_local]-leading arrays become row
        # shards of the global array; everything else is replicated
        if self.objective is not None:
            for name, arr in list(vars(self.objective).items()):
                if not isinstance(arr, jax.Array):
                    continue
                if arr.ndim >= 1 and arr.shape[0] == n_local:
                    garr = dist.make_global_array(mesh, np.asarray(arr),
                                                  row_axis=0)
                elif arr.ndim >= 2 and arr.shape[1] == n_local:
                    garr = dist.make_global_array(mesh, np.asarray(arr),
                                                  row_axis=1)
                else:
                    garr = jax.device_put(np.asarray(arr),
                                          NamedSharding(mesh, P()))
                setattr(self.objective, name, garr)
        self._build_grow_sharded()

    def _sync_init_scores(self, scores: np.ndarray) -> np.ndarray:
        # per-machine init scores averaged across processes
        # (ref: gbdt.cpp:322 Network::GlobalSyncUpByMean)
        if jax.process_count() <= 1:
            return scores
        from jax.experimental import multihost_utils
        allv = np.asarray(multihost_utils.process_allgather(
            scores.astype(np.float32)))  # [P, K]
        return allv.mean(axis=0).astype(np.float64)

    @property
    def num_machines(self) -> int:
        return self.mesh.size


class DataParallelGBDT(_DataParallelMixin, GBDT):
    def __init__(self, config: Config, train_set: BinnedDataset,
                 objective: Optional[ObjectiveFunction] = None,
                 num_shards: int = 0):
        super().__init__(config, train_set, objective)
        self._setup_sharding(num_shards)


class VotingParallelGBDT(_DataParallelMixin, GBDT):
    """PV-tree voting-parallel learner: rows sharded, local histograms,
    top-k vote + candidate-only psum (ref:
    voting_parallel_tree_learner.cpp; see parallel/voting.py)."""

    def __init__(self, config: Config, train_set: BinnedDataset,
                 objective: Optional[ObjectiveFunction] = None,
                 num_shards: int = 0):
        super().__init__(config, train_set, objective)
        self._setup_sharding(num_shards)
        if self._forced is not None or \
                self._interaction_groups is not None:
            import warnings
            warnings.warn("forced splits / interaction constraints are "
                          "not supported by tree_learner=voting; ignoring")
        if self.mesh.size > 1 and self.num_data % self.mesh.size != 0 \
                and getattr(self, "_row_pad", 0) == 0:
            # the voting grower's shard_map shards rows over the mesh,
            # which needs equal slices; padded-row storage (see
            # _pad_and_shard_rows) already provides them, and otherwise
            # the data-parallel grower the mixin installed handles this
            # case (its pallas wrapper pads internally, its XLA path
            # runs replicated)
            import warnings
            warnings.warn(
                f"tree_learner=voting needs num_data divisible by the "
                f"{self.mesh.size}-device mesh (have {self.num_data}); "
                "using the data-parallel grower instead")
            return
        if self.mesh.size > 1:
            if config.extra_trees or config.feature_fraction_bynode < 1.0:
                import warnings
                warnings.warn(
                    "extra_trees / feature_fraction_bynode are not "
                    "supported by the sharded voting learner; ignoring")
            from ..ops import histogram as hist_ops
            from .scatter import resolve_hist_reduce
            from .voting import make_sharded_voting_grow
            top_k = max(1, min(int(config.top_k),
                               self.train_set.num_features))
            static = dict(self._static)
            # voting scatters over its top-candidate axis and pads it
            # internally, so auto takes scatter for ANY feature count
            grow = make_sharded_voting_grow(
                self.mesh, top_k=top_k,
                hist_impl=("xla" if config.deterministic_hist else
                           hist_ops.resolve_impl(config.tpu_hist_impl)),
                hist_deterministic=bool(config.deterministic_hist),
                has_categorical=self._has_categorical,
                hist_reduce=resolve_hist_reduce(
                    config.tpu_hist_reduce, self.mesh,
                    self.train_set.num_features, pad_ok=True),
                **static)

            def _grow_adapter(bins, g, h, m, fm, meta, hp, md,
                              forced=None, node_key=None):
                return grow(bins, g, h, m, fm, meta, hp, md)
            self._grow = _grow_adapter

    def _fast_path_ok(self, custom_grad) -> bool:
        return False


class FeatureParallelGBDT(GBDT):
    """Feature-parallel learner: data replicated, feature slices per
    shard, all-gathered best splits (ref:
    feature_parallel_tree_learner.cpp; see parallel/feature_parallel.py)."""

    def __init__(self, config: Config, train_set: BinnedDataset,
                 objective: Optional[ObjectiveFunction] = None,
                 num_shards: int = 0):
        super().__init__(config, train_set, objective)
        self.mesh = mesh_lib.get_mesh(num_shards)
        if self._forced is not None or \
                self._interaction_groups is not None:
            import warnings
            warnings.warn("forced splits / interaction constraints are "
                          "not supported by tree_learner=feature; ignoring")
        if self.mesh.size > 1:
            if config.extra_trees or config.feature_fraction_bynode < 1.0:
                import warnings
                warnings.warn(
                    "extra_trees / feature_fraction_bynode are not "
                    "supported by the sharded feature learner; ignoring")
            # replicate everything; sharding is over the computation
            self.bins_fm = mesh_lib.replicate(self.mesh, self.bins_fm)
            self.scores = mesh_lib.replicate(self.mesh, self.scores)
            self._sample_mask = mesh_lib.replicate(self.mesh,
                                                   self._sample_mask)
            self.feature_meta = jax.tree_util.tree_map(
                lambda a: mesh_lib.replicate(self.mesh, a),
                self.feature_meta)
            from ..ops import histogram as hist_ops
            from .feature_parallel import make_sharded_feature_grow
            static = dict(self._static)
            grow = make_sharded_feature_grow(
                self.mesh,
                hist_impl=("xla" if config.deterministic_hist else
                           hist_ops.resolve_impl(config.tpu_hist_impl)),
                hist_deterministic=bool(config.deterministic_hist),
                has_categorical=self._has_categorical, **static)

            def _grow_adapter(bins, g, h, m, fm, meta, hp, md,
                              forced=None, node_key=None):
                return grow(bins, g, h, m, fm, meta, hp, md)
            self._grow = _grow_adapter
            self._fused = None
            global_metrics.set_meta("mesh_size", int(self.mesh.size))
            global_metrics.set_meta("tree_learner", "feature")
            from ..obs.health import global_health
            if global_health.enabled:
                global_health.probe_collectives(self.mesh)

    def _fast_path_ok(self, custom_grad) -> bool:
        return False

    @property
    def num_machines(self) -> int:
        return self.mesh.size


class DataParallelDART(_DataParallelMixin, DART):
    def __init__(self, config, train_set, objective=None, num_shards: int = 0):
        super().__init__(config, train_set, objective)
        self._setup_sharding(num_shards)


class DataParallelRF(_DataParallelMixin, RF):
    def __init__(self, config, train_set, objective=None, num_shards: int = 0):
        super().__init__(config, train_set, objective)
        self._setup_sharding(num_shards)


def create_parallel_boosting(config: Config, train_set: BinnedDataset,
                             objective: Optional[ObjectiveFunction] = None
                             ) -> GBDT:
    """Factory for distributed training, dispatching the three reference
    strategies (ref: tree_learner.cpp:17 CreateTreeLearner):
      data    — rows sharded, GSPMD auto-partitioned histogram psum
      voting  — rows sharded, PV-tree top-k vote + candidate-only psum
      feature — data replicated, feature-slice compute + split all_gather
    DART/RF boosting run on the data-parallel program.
    """
    num_shards = int(config.tpu_num_shards or 0)
    if config.boosting == "gbdt" and config.tree_learner == "voting":
        return VotingParallelGBDT(config, train_set, objective,
                                  num_shards=num_shards)
    if config.boosting == "gbdt" and config.tree_learner == "feature":
        return FeatureParallelGBDT(config, train_set, objective,
                                   num_shards=num_shards)
    cls = {"gbdt": DataParallelGBDT, "dart": DataParallelDART,
           "rf": DataParallelRF}[config.boosting]
    return cls(config, train_set, objective, num_shards=num_shards)
