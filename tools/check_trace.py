#!/usr/bin/env python
"""Validate a Chrome trace-event JSON file produced by
``lightgbm_tpu.obs.trace`` (``LGBM_TPU_TRACE=/path.json`` or the
``trace_output`` param).

Checks, in order:
  1. the file is valid JSON;
  2. it is either a bare event list or an object with a
     ``traceEvents`` list (both forms are valid Chrome traces);
  3. every event has the required fields with the right types
     (``name`` str, ``ph`` str, and for complete events ``ph == "X"``:
     numeric non-negative ``ts`` and ``dur``);
  4. per (pid, tid) track, ``ts`` is monotonically non-decreasing in
     file order (the exporter sorts by start time; a violation means a
     corrupted or hand-edited trace).

Usage:  python tools/check_trace.py TRACE.json
Exit 0 when the trace is valid; 1 with a diagnostic otherwise — so a
CI or bench run can assert trace integrity with one command.
"""

from __future__ import annotations

import json
import sys
from typing import Any, List, Tuple


def check_trace(path: str) -> Tuple[bool, str]:
    """-> (ok, message). Importable for tests; no side effects."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except OSError as exc:
        return False, f"cannot read {path}: {exc}"
    except json.JSONDecodeError as exc:
        return False, f"{path} is not valid JSON: {exc}"

    if isinstance(doc, list):
        events: List[Any] = doc
    elif isinstance(doc, dict):
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            return False, "top-level object has no 'traceEvents' list"
    else:
        return False, f"unexpected top-level JSON type {type(doc).__name__}"

    last_ts = {}  # (pid, tid) -> ts
    n_complete = 0
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            return False, f"event {i} is not an object"
        name, ph = ev.get("name"), ev.get("ph")
        if not isinstance(name, str) or not name:
            return False, f"event {i} has no string 'name'"
        if not isinstance(ph, str) or not ph:
            return False, f"event {i} ({name!r}) has no string 'ph'"
        if ph != "X":
            continue  # metadata/counter events need no ts ordering
        n_complete += 1
        ts, dur = ev.get("ts"), ev.get("dur")
        if not isinstance(ts, (int, float)) or ts < 0:
            return False, f"event {i} ({name!r}) has invalid ts={ts!r}"
        if not isinstance(dur, (int, float)) or dur < 0:
            return False, f"event {i} ({name!r}) has invalid dur={dur!r}"
        track = (ev.get("pid"), ev.get("tid"))
        prev = last_ts.get(track)
        if prev is not None and ts < prev:
            return False, (f"event {i} ({name!r}) breaks ts monotonicity "
                           f"on track {track}: {ts} < {prev}")
        last_ts[track] = ts
    return True, f"ok: {n_complete} complete spans on {len(last_ts)} track(s)"


def main(argv: List[str]) -> int:
    if len(argv) != 2:
        print("usage: python tools/check_trace.py TRACE.json",
              file=sys.stderr)
        return 2
    ok, msg = check_trace(argv[1])
    print(msg, file=sys.stdout if ok else sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
