"""Out-of-core streaming training (tpu_stream; ISSUE 13).

Covers:
- slab packing/bounds (ops/bin_pack) and the shared double-buffered
  feed + stats (io/streaming) — the one pipeline behind predict chunks
  and training slabs;
- streamed-vs-resident bit-identity across the sampling matrix
  (plain/bagging/GOSS/DART/quantized/2-shard/RF) at a fits-in-HBM
  fixture (single-slab plan => the SAME fused program on an uploaded
  operand);
- slab-boundary semantics: int8-quantized streaming is bit-identical
  at ANY slab count (exact integer partial sums, uneven tails
  included), f32 multi-slab agrees to float-add-association tolerance;
- preflight honesty: a clamped HBM budget keeps ``fits`` False for
  resident while ``fits_streaming`` goes True with a ``tpu_stream``
  recommendation, and ``tpu_stream=auto`` then actually streams;
- PR-8 interplay: SIGTERM mid-stream checkpoints and the resumed run
  finishes bit-identically to the never-killed streamed run;
- knob honesty, obs meta/OpenMetrics export, and the quick-tier tools
  (tools/check_stream.py, perf-gate check 9).
"""

import json
import os
import re
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io import streaming as stream_mod
from lightgbm_tpu.io.streaming import (HostSlabBins, StreamStats,
                                       double_buffered,
                                       global_stream_stats)
from lightgbm_tpu.obs.metrics import global_metrics
from lightgbm_tpu.ops import bin_pack as bp

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


def _data(n=1500, f=6, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.3).astype(np.float32)
    return X, y


def _train(X, y, extra, iters=3, rounds=None):
    params = {**dict(objective="binary", num_leaves=15, learning_rate=0.1,
                     max_bin=63, min_data_in_leaf=5, verbosity=-1),
              **extra}
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    return lgb.train(params, ds, num_boost_round=rounds or iters)


def _strip_params(s):
    """Streamed/resident models differ only in the echoed params block
    (tpu_stream on vs auto); strip it for bit-identity compares."""
    return re.sub(r"\nparameters:.*?end of parameters", "", s, flags=re.S)


# ---------------------------------------------------------------------------
class TestSlabPacking:
    def test_bounds_are_section_aligned(self):
        align = bp.slab_align(15)  # vpb=2 -> 4096 rows
        assert align == 2 * bp.PACK_ALIGN
        bounds = bp.slab_bounds(10_000, 1, 15)
        assert bounds[0] == (0, align)
        assert bounds[-1][1] == 10_000
        for lo, hi in bounds[:-1]:
            assert (hi - lo) == align

    def test_single_slab_when_rows_cover(self):
        assert bp.slab_bounds(1000, 1000, 63) == [(0, 1000)]

    def test_pack_bins_range_matches_full_pack_slice(self):
        r = np.random.RandomState(1)
        bins = r.randint(0, 15, size=(4, 5000)).astype(np.uint8)
        slab = bp.pack_bins_range(bins, 15, 2048, 4096)
        assert isinstance(slab, bp.PackedBins)
        assert slab.num_data == 2048
        # unpacking the slab reproduces the raw slice exactly
        import jax.numpy as jnp
        dev = bp.PackedBins(jnp.asarray(slab.data), slab.num_data,
                            slab.vpb)
        assert np.array_equal(np.asarray(bp.unpack_bins(dev)),
                              bins[:, 2048:4096])

    def test_unpackable_width_returns_raw_slice(self):
        r = np.random.RandomState(1)
        bins = r.randint(0, 63, size=(4, 3000)).astype(np.uint8)
        slab = bp.pack_bins_range(bins, 63, 0, 2048)
        assert isinstance(slab, np.ndarray)
        assert np.array_equal(slab, bins[:, :2048])

    def test_host_slab_bins_plan(self):
        r = np.random.RandomState(2)
        bins = r.randint(0, 63, size=(3, 5000)).astype(np.uint8)
        plan = HostSlabBins(bins, 63, 2048)
        assert plan.n_slabs == 3
        assert plan.bounds == [(0, 2048), (2048, 4096), (4096, 5000)]
        assert plan.shape == (3, 5000)
        assert plan.nbytes_host == 3 * 5000


class TestDoubleBufferedFeed:
    def test_order_preserved(self):
        staged = []
        out = list(double_buffered([1, 2, 3], lambda x: staged.append(x)
                                   or x * 10))
        assert out == [10, 20, 30]
        assert staged == [1, 2, 3]

    def test_stage_runs_ahead_of_consumption(self):
        events = []
        gen = double_buffered([0, 1, 2], lambda i: events.append(
            ("stage", i)) or i)
        first = next(gen)
        events.append(("consume", first))
        # by the time item 0 is consumable, item 1 is already staged
        assert events == [("stage", 0), ("stage", 1), ("consume", 0)]

    def test_stats_overlap_accounting(self):
        st = StreamStats()
        items = [np.zeros(10, np.uint8)] * 3

        def stage(x):
            return x
        gen = double_buffered(items, stage, st)
        for _ in gen:
            st.note_dispatch()
        assert st.uploads_total == 3
        # items 0 and 1 stage before any compute dispatches; item 2
        # stages while item 0's dispatched compute is in flight
        assert st.overlapped_uploads_total == 1
        st.note_block(0.01)
        assert st.kernel_seconds_total > 0
        assert 0.0 <= st.overlap_ratio <= 1.0

    def test_empty(self):
        assert list(double_buffered([], lambda x: x)) == []


# ---------------------------------------------------------------------------
MATRIX = {
    "plain": {},
    "bagging": {"bagging_fraction": 0.7, "bagging_freq": 1},
    "goss": {"data_sample_strategy": "goss"},
    "dart": {"boosting": "dart", "drop_rate": 0.5, "max_drop": 5},
    "quantized": {"use_quantized_grad": True},
    "2shard": {"tree_learner": "data", "tpu_num_shards": 2},
    "rf": {"boosting": "rf", "bagging_fraction": 0.7, "bagging_freq": 1},
}


class TestBitIdentity:
    @pytest.mark.parametrize("name", sorted(MATRIX))
    def test_streamed_matches_resident(self, name):
        X, y = _data()
        resident = _train(X, y, MATRIX[name]).model_to_string()
        streamed = _train(X, y, {**MATRIX[name], "tpu_stream": "on"}
                          ).model_to_string()
        assert _strip_params(streamed) == _strip_params(resident)

    def test_streamed_matches_resident_with_valid_set(self):
        X, y = _data()
        Xv, yv = _data(400, seed=5)

        def run(extra):
            params = dict(objective="binary", num_leaves=15,
                          max_bin=63, min_data_in_leaf=5,
                          verbosity=-1, **extra)
            ds = lgb.Dataset(X, label=y, params=params)
            vs = lgb.Dataset(Xv, label=yv, params=params, reference=ds)
            bst = lgb.train(params, ds, num_boost_round=3,
                            valid_sets=[vs])
            return bst.model_to_string()
        assert _strip_params(run({"tpu_stream": "on"})) == \
            _strip_params(run({}))

    def test_multiclassova_streams(self):
        X, _ = _data()
        y = (np.abs(X[:, 0] * 3).astype(int) % 3).astype(np.float32)
        extra = {"objective": "multiclassova", "num_class": 3}
        a = _train(X, y, extra, iters=2).model_to_string()
        b = _train(X, y, {**extra, "tpu_stream": "on"},
                   iters=2).model_to_string()
        assert _strip_params(a) == _strip_params(b)


class TestSlabBoundaries:
    """Multi-slab semantics at forced small slabs (tpu_stream_slab_rows)."""

    def test_quantized_bit_identical_across_slab_counts(self):
        # 2048-row slabs give [2048, 2048, 904]: an uneven tail AND a
        # slab exactly equal to the section alignment
        X, y = _data(5000)
        q = {"use_quantized_grad": True, "tpu_stream": "on"}
        one = _train(X, y, {**q, "tpu_stream_slab_rows": 4096}
                     ).model_to_string()
        three = _train(X, y, {**q, "tpu_stream_slab_rows": 2048}
                       ).model_to_string()
        assert _strip_params(one) == _strip_params(three)

    def test_quantized_exact_slab_multiple(self):
        # num_data an exact multiple of the slab size (no tail): the
        # exact integer accumulation makes streamed predictions
        # BIT-equal to resident quantized training (leaf values derive
        # from identical int32 histogram totals)
        X, y = _data(4096)
        q = {"use_quantized_grad": True}
        streamed = _train(X, y, {**q, "tpu_stream": "on",
                                 "tpu_stream_slab_rows": 2048})
        assert streamed._gbdt._stream.n_slabs == 2
        resident = _train(X, y, q)
        pr = resident.predict(X[:512], raw_score=True)
        ps = streamed.predict(X[:512], raw_score=True)
        assert np.array_equal(pr, ps)

    def test_f32_multi_slab_predictions_close(self):
        # f32 slab partials accumulate in slab order: association-only
        # drift vs the resident single contraction
        X, y = _data(5000)
        resident = _train(X, y, {})
        streamed = _train(X, y, {"tpu_stream": "on",
                                 "tpu_stream_slab_rows": 2048})
        pr = resident.predict(X[:512], raw_score=True)
        ps = streamed.predict(X[:512], raw_score=True)
        np.testing.assert_allclose(ps, pr, rtol=2e-4, atol=2e-4)

    def test_multi_slab_plan_shape(self):
        X, y = _data(5000)
        bst = _train(X, y, {"tpu_stream": "on",
                            "tpu_stream_slab_rows": 2048})
        plan = bst._gbdt._stream
        assert plan is not None and plan.n_slabs == 3
        assert plan.bounds[-1] == (4096, 5000)


# ---------------------------------------------------------------------------
class TestPreflight:
    def test_clamped_budget_recommends_streaming(self, monkeypatch):
        from lightgbm_tpu.obs import memory as obs_memory
        from lightgbm_tpu.config import Config
        params = {"objective": "binary", "num_leaves": 15,
                  "max_bin": 63, "tpu_fused_grad": "off",
                  "verbosity": -1}
        n, f = 5000, 6
        kw = obs_memory._resolve_train_knobs(
            Config.from_params(dict(params)), n, f, 1)
        kw["valid_rows"] = []
        resident = obs_memory.train_memory_model(**kw)["peak_bytes"]
        streamed = obs_memory.train_memory_model(
            **kw, stream_slab_rows=bp.slab_align(63))["peak_bytes"]
        assert streamed < resident
        clamp = (streamed + resident) // 2
        r = lgb.preflight(dict(params), shape=(n, f),
                          capacity_bytes=clamp)
        assert r.fits is False          # resident verdict stays honest
        assert r.fits_streaming is True
        recs = {x["knob"]: x for x in r.recommendations}
        assert "tpu_stream" in recs
        assert recs["tpu_stream"]["slab_rows"] >= bp.slab_align(63)
        assert "slab_rows" in r.render() or "tpu_stream" in r.render()

    def test_auto_streams_under_clamp(self, monkeypatch):
        from lightgbm_tpu.obs import memory as obs_memory
        from lightgbm_tpu.config import Config
        params = {"tpu_fused_grad": "off"}
        n = 5000
        X, y = _data(n)
        base = dict(objective="binary", num_leaves=15, max_bin=63,
                    min_data_in_leaf=5, verbosity=-1, **params)
        kw = obs_memory._resolve_train_knobs(
            Config.from_params(dict(base)), n, 6, 1)
        kw["valid_rows"] = []
        resident = obs_memory.train_memory_model(**kw)["peak_bytes"]
        streamed = obs_memory.train_memory_model(
            **kw, stream_slab_rows=bp.slab_align(63))["peak_bytes"]
        monkeypatch.setenv("LGBM_TPU_HBM_BYTES",
                           str((streamed + resident) // 2))
        bst = _train(X, y, params)
        plan = bst._gbdt._stream
        assert plan is not None and plan.n_slabs >= 2
        pred = bst.predict(X[:32])
        assert np.all(np.isfinite(pred))

    def test_auto_respects_preflight_off(self, monkeypatch):
        monkeypatch.setenv("LGBM_TPU_HBM_BYTES", "1000")
        X, y = _data(1200)
        bst = _train(X, y, {"tpu_preflight": "off"})
        assert bst._gbdt._stream is None

    def test_streaming_memory_model_published(self):
        X, y = _data(5000)
        _train(X, y, {"tpu_stream": "on", "tpu_stream_slab_rows": 2048})
        mm = global_metrics.meta.get("mem_model")
        assert mm and mm["stream_slab_rows"] == 2048
        # device bins budget = the double-buffered slab pair, not [F, N]
        assert mm["components"]["bins"] < 6 * 5000


class TestKnobs:
    def test_bad_value_raises(self):
        X, y = _data(600)
        with pytest.raises(ValueError, match="tpu_stream"):
            _train(X, y, {"tpu_stream": "sometimes"})

    def test_forced_on_ineligible_raises(self):
        X, _ = _data(600)
        y3 = (np.abs(X[:, 0] * 3).astype(int) % 3).astype(np.float32)
        # coupled multiclass resolves to exact-order growth: no twin
        with pytest.raises(ValueError, match="tpu_stream=on"):
            _train(X, y3, {"tpu_stream": "on", "objective": "multiclass",
                           "num_class": 3}, iters=1)

    def test_auto_ineligible_stays_resident(self):
        X, _ = _data(600)
        y3 = (np.abs(X[:, 0] * 3).astype(int) % 3).astype(np.float32)
        bst = _train(X, y3, {"objective": "multiclass", "num_class": 3},
                     iters=1)
        assert bst._gbdt._stream is None

    def test_off_never_streams(self, monkeypatch):
        monkeypatch.setenv("LGBM_TPU_HBM_BYTES", "1")
        X, y = _data(600)
        bst = _train(X, y, {"tpu_stream": "off"})
        assert bst._gbdt._stream is None


# ---------------------------------------------------------------------------
class TestResumeInterplay:
    def test_sigterm_mid_stream_resumes_bit_identically(self, tmp_path):
        """PR-8 interplay: a kill mid-streamed-run checkpoints at the
        iteration boundary; the resumed (still streamed) run finishes
        bit-identical to the never-killed streamed run."""
        from lightgbm_tpu.resilience import faults as faults_mod
        from lightgbm_tpu.resilience.errors import EXIT_PREEMPTED
        X, y = _data(5000)
        ck = str(tmp_path / "stream.ckpt")
        params = dict(objective="binary", num_leaves=15, max_bin=63,
                      min_data_in_leaf=5, verbosity=-1,
                      tpu_stream="on", tpu_stream_slab_rows=2048,
                      tpu_checkpoint_path=ck)
        straight = lgb.train(dict(params), lgb.Dataset(X, label=y),
                             num_boost_round=5).model_to_string()
        if os.path.exists(ck):  # no periodic snapshots were requested,
            os.remove(ck)       # but stay robust to engine behavior

        faults_mod.install(faults_mod.FaultPlan(kill_at_iter=2))
        try:
            with pytest.raises(SystemExit) as ei:
                lgb.train(dict(params), lgb.Dataset(X, label=y),
                          num_boost_round=5)
            assert ei.value.code == EXIT_PREEMPTED
        finally:
            faults_mod.reset()
        assert os.path.exists(ck)
        resumed = lgb.train(dict(params), lgb.Dataset(X, label=y),
                            num_boost_round=5)
        assert resumed.current_iteration() == 5
        assert resumed.model_to_string() == straight

    def test_resume_refuses_slab_drift(self, tmp_path):
        """A checkpoint taken under one slab plan must not silently
        resume under another (the f32 accumulation order would change
        mid-run)."""
        from lightgbm_tpu.resilience import faults as faults_mod
        from lightgbm_tpu.resilience.errors import (EXIT_PREEMPTED,
                                                    ResumeMismatchError)
        X, y = _data(5000)
        ck = str(tmp_path / "drift.ckpt")
        params = dict(objective="binary", num_leaves=15, max_bin=63,
                      min_data_in_leaf=5, verbosity=-1,
                      tpu_stream="on", tpu_stream_slab_rows=2048,
                      tpu_checkpoint_path=ck)
        faults_mod.install(faults_mod.FaultPlan(kill_at_iter=1))
        try:
            with pytest.raises(SystemExit) as ei:
                lgb.train(dict(params), lgb.Dataset(X, label=y),
                          num_boost_round=4)
            assert ei.value.code == EXIT_PREEMPTED
        finally:
            faults_mod.reset()
        params["tpu_stream_slab_rows"] = 4096
        with pytest.raises(ResumeMismatchError):
            lgb.train(dict(params), lgb.Dataset(X, label=y),
                      num_boost_round=4)


# ---------------------------------------------------------------------------
class TestObsExport:
    def test_stream_meta_and_families(self):
        X, y = _data(5000)
        global_stream_stats.reset()
        _train(X, y, {"tpu_stream": "on", "tpu_stream_slab_rows": 2048})
        sm = global_metrics.meta.get("stream")
        assert sm and sm["n_slabs"] == 3 and sm["slab_rows"] == 2048
        assert sm["uploads_total"] >= 3
        assert sm["overlap_ratio"] > 0.0
        assert sm["upload_seconds_total"] > 0.0
        from lightgbm_tpu.obs.export import render_openmetrics
        doc = render_openmetrics()
        for fam in ("lgbmtpu_stream_slabs_total",
                    "lgbmtpu_stream_upload_seconds_total",
                    "lgbmtpu_stream_overlap_ratio",
                    "lgbmtpu_stream_n_slabs"):
            assert fam in doc, fam

    def test_slow_path_streaming_publishes_meta(self):
        # RF rides the slow driver through the streamed grower adapter:
        # the same always-on accounting must flow (and the per-
        # iteration sync resets the overlap classifier's in-flight
        # count so later pipelines don't inherit stale dispatches)
        X, y = _data(5000)
        global_stream_stats.reset()
        global_metrics.set_meta("stream", None)
        _train(X, y, {"boosting": "rf", "bagging_fraction": 0.7,
                      "bagging_freq": 1, "tpu_stream": "on",
                      "tpu_stream_slab_rows": 2048})
        sm = global_metrics.meta.get("stream")
        assert sm and sm["iterations_total"] == 3
        assert sm["uploads_total"] >= 3
        assert global_stream_stats._inflight == 0

    def test_single_slab_streaming_uploads_once(self):
        X, y = _data(1200)
        global_stream_stats.reset()
        _train(X, y, {"tpu_stream": "on"}, iters=3)
        st = global_stream_stats.summary()
        # the immutable single slab stages once and is cached — not
        # re-uploaded per iteration
        assert st["uploads_total"] == 1
        assert st["bytes_uploaded_total"] > 0
        assert st["iterations_total"] == 3


# ---------------------------------------------------------------------------
class TestToolsWiring:
    @pytest.mark.slow
    def test_check_stream_tool(self):
        import check_stream
        assert check_stream.main() == 0

    def _floor(self):
        return {"stream": {"max_overhead_vs_resident": 1.25,
                           "max_overhead_vs_resident_cpu": 2.6,
                           "min_overlap_ratio": 0.05}}

    def _candidate(self, tmp_path, vs_resident, overlap,
                   platform="cpu"):
        rec = {"metric": "stream_rows_per_sec", "value": 1.0,
               "unit": f"rows/sec (platform={platform})",
               "vs_baseline": vs_resident,
               "stream": {"vs_resident": vs_resident,
                          "stream_overlap_ratio": overlap,
                          "n_slabs": 4}}
        p = tmp_path / "BENCH_cand.json"
        p.write_text(json.dumps(rec))
        return str(p)

    def test_gate_check9_passes(self, tmp_path):
        import check_perf_gate
        failures = []
        check_perf_gate.check_stream_overhead(
            self._floor(), failures,
            self._candidate(tmp_path, vs_resident=0.5, overlap=0.9))
        assert failures == []

    def test_gate_check9_fails_on_slowdown_and_overlap(self, tmp_path):
        import check_perf_gate
        failures = []
        check_perf_gate.check_stream_overhead(
            self._floor(), failures,
            self._candidate(tmp_path, vs_resident=0.2, overlap=0.01))
        assert len(failures) == 2
        assert "resident wall-time" in failures[0]
        assert "overlap ratio" in failures[1]

    def test_gate_check9_accelerator_ceiling(self, tmp_path):
        import check_perf_gate
        failures = []
        check_perf_gate.check_stream_overhead(
            self._floor(), failures,
            self._candidate(tmp_path, vs_resident=0.5, overlap=0.9,
                            platform="tpu"))
        assert failures and "1.25x" in failures[0]

    def test_gate_check9_graceful_skip(self, tmp_path, capsys):
        import check_perf_gate
        failures = []
        check_perf_gate.check_stream_overhead(self._floor(), failures,
                                              str(tmp_path / "nope.json"))
        assert failures == []
        assert "skipped" in capsys.readouterr().out
