"""Histogram construction ops (device).

TPU-native replacement for the reference histogram kernels
(ref: src/io/dense_bin.hpp ConstructHistogram, src/treelearner/cuda/
cuda_histogram_constructor.cu:21). Instead of scatter-adds (slow on TPU),
histograms are built as one-hot contractions that XLA maps onto the MXU:
for each feature, ``hist[b] = sum_i [bin_i == b] * (g_i, h_i, m_i)``.

Layout: bins are stored feature-major ``[F, N]`` (col-wise access pattern,
ref: Dataset col-wise path dataset.h:727) and histograms are
``[F, B, 3]`` with channels (sum_grad, sum_hess, count).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..obs.metrics import global_metrics
from .bin_pack import PackedBins, unpack_bins

GRAD, HESS, COUNT = 0, 1, 2
NUM_HIST_CHANNELS = 3


def _kahan_scan(fn, init, xs):
    """Kahan-compensated accumulation of ``fn`` over the scanned chunks:
    the running error term keeps the final sum within ~1 ulp of the
    exact chunk-sum regardless of chunk count — the `deterministic_hist`
    accumulation primitive (sharding/regrouping changes which rows land
    in which chunk; compensation makes the result insensitive to it)."""
    def step(carry, inp):
        acc, comp = carry
        y = fn(inp) - comp
        t = acc + y
        comp = (t - acc) - y
        return (t, comp), None

    (acc, _), _ = lax.scan(step, (init, jnp.zeros_like(init)), xs)
    return acc


def _hist_all_features(bins_fm: jax.Array, gh: jax.Array, max_bins: int,
                       dtype) -> jax.Array:
    """``[F, N] x [N, 3] -> [F, B, 3]`` one-hot contraction, scanning features."""
    bidx = jnp.arange(max_bins, dtype=bins_fm.dtype)

    def one_feature(carry, feat_bins):
        onehot = (feat_bins[:, None] == bidx[None, :]).astype(dtype)  # [N, B]
        # HIGHEST precision: the TPU MXU would otherwise truncate the f32
        # grad/hess operand to bf16 (the one-hot side is exact either way)
        h = jax.lax.dot(onehot.T, gh, precision=jax.lax.Precision.HIGHEST)
        return carry, h  # [B, 3]

    _, hist = lax.scan(one_feature, None, bins_fm)
    return hist


def cpu_backend() -> bool:
    """True when the default jax backend is CPU (or unavailable) —
    the shared sniff for backend-dependent implementation choices.
    Only the backend-unavailable RuntimeError maps to "cpu"; any other
    failure is a real bug in backend sniffing and must surface."""
    try:
        return jax.default_backend() == "cpu"
    except RuntimeError:  # "Unable to initialize backend ..."
        return True


def default_impl() -> str:
    """'pallas' on TPU backends, 'xla' elsewhere (CPU tests, interpret)."""
    return "xla" if cpu_backend() else "pallas"


def resolve_impl(cfg_impl: str) -> str:
    """Config tpu_hist_impl -> concrete impl ('auto' = default_impl())."""
    return default_impl() if cfg_impl in (None, "", "auto") else cfg_impl


@functools.partial(jax.jit, static_argnames=("max_bins", "dtype", "row_chunk",
                                             "impl", "precision",
                                             "deterministic"))
def build_histogram(bins_fm: jax.Array, grad: jax.Array, hess: jax.Array,
                    mask: jax.Array, *, max_bins: int,
                    dtype=jnp.float32, row_chunk: int = 0,
                    impl: str = "xla", precision: str = "highest",
                    deterministic: bool = False) -> jax.Array:
    """Build per-feature (grad, hess, count) histograms for one leaf.

    Args:
      bins_fm: ``[F, N]`` integer bin ids, feature-major (or a
        bit-packed ``bin_pack.PackedBins`` — the pallas path unpacks
        nibbles in-kernel, the XLA path unpacks on the fly and lets the
        fusion keep the HBM read at the packed bytes).
      grad, hess: ``[N]`` float gradients / hessians.
      mask: ``[N]`` float weights in {0, 1} (or bagging weights) selecting
        the rows of the leaf; zero rows contribute nothing.
      max_bins: static B (max bins over features).
      row_chunk: if >0, rows are processed in chunks of this size (bounds the
        transient one-hot buffer to ``row_chunk * B`` per feature).
      deterministic: fixed-size chunking + Kahan-compensated cross-chunk
        accumulation (the `deterministic_hist` knob): the result is
        insensitive to how rows are regrouped by sharding or chunking.

    Returns:
      ``[F, B, 3]`` histogram in `dtype`.
    """
    # trace-time only: counts histogram-pass (re)compilations, never
    # executes per iteration (obs.metrics module docstring)
    global_metrics.note_trace("ops/histogram")
    if impl == "pallas" and not deterministic:
        from .pallas_histogram import hist_pallas
        gh3 = jnp.stack([grad * mask, hess * mask, mask]).astype(jnp.float32)
        return hist_pallas(bins_fm, gh3, max_bins=max_bins,
                           precise=precision).astype(dtype)
    if isinstance(bins_fm, PackedBins):
        bins_fm = unpack_bins(bins_fm).astype(jnp.uint8)

    gh = jnp.stack([grad * mask, hess * mask, mask], axis=-1).astype(dtype)  # [N, 3]
    num_features = bins_fm.shape[0]
    n = gh.shape[0]

    if deterministic:
        # 2048 is the measured sweet spot: small enough that the
        # UNcompensated within-chunk dot error stays below the 1e-4
        # parity target, large enough that the Kahan-compensated scan
        # doesn't dominate runtime (N/2048 steps)
        row_chunk = 2048
    if row_chunk and n > row_chunk:
        pad = (-n) % row_chunk
        gh_p = jnp.pad(gh, ((0, pad), (0, 0)))
        bins_p = jnp.pad(bins_fm, ((0, 0), (0, pad)),
                         constant_values=max_bins)  # pad bin id out of range
        nchunk = (n + pad) // row_chunk
        gh_c = gh_p.reshape(nchunk, row_chunk, NUM_HIST_CHANNELS)
        bins_c = bins_p.reshape(num_features, nchunk, row_chunk)
        bins_c = jnp.swapaxes(bins_c, 0, 1)  # [nchunk, F, C]

        init = jnp.zeros((num_features, max_bins, NUM_HIST_CHANNELS), dtype)
        if deterministic:
            return _kahan_scan(
                lambda inp: _hist_all_features(inp[0], inp[1], max_bins,
                                               dtype),
                init, (bins_c, gh_c))

        def one_chunk(acc, inputs):
            bins_chunk, gh_chunk = inputs
            return acc + _hist_all_features(bins_chunk, gh_chunk, max_bins,
                                            dtype), None

        hist, _ = lax.scan(one_chunk, init, (bins_c, gh_c))
        return hist

    return _hist_all_features(bins_fm, gh, max_bins, dtype)


def build_histogram_sparse(sb, grad: jax.Array, hess: jax.Array,
                           mask: jax.Array, *, num_features: int,
                           max_bins: int, dtype=jnp.float32) -> jax.Array:
    """Single-leaf histogram from COO storage (ref: the sparse row-wise
    MultiValBin ConstructHistogram, multi_val_sparse_bin.hpp:70): one
    O(nnz) segment-sum over explicit entries, then the implicit-zero bin
    of every feature receives (leaf totals - explicit sums). Work scales
    with nnz instead of N*F*B — the scaling axis wide-sparse data needs.
    """
    global_metrics.note_trace("ops/histogram_sparse")
    gh = jnp.stack([grad * mask, hess * mask, mask], axis=-1).astype(dtype)
    flat = sb.coo_feat * max_bins + sb.coo_bin
    hist = jax.ops.segment_sum(gh[sb.coo_row], flat,
                               num_segments=num_features * max_bins)
    hist = hist.reshape(num_features, max_bins, NUM_HIST_CHANNELS)
    totals = jnp.sum(gh, axis=0)                     # [3] leaf totals
    resid = totals[None, :] - jnp.sum(hist, axis=1)  # [F, 3]
    return hist.at[jnp.arange(num_features), sb.zero_bins].add(resid)


def hist_multi_sparse(sb, ghT: jax.Array, row_leaf: jax.Array,
                      leaf_ids: jax.Array, *, num_features: int,
                      max_bins: int, num_slots: int) -> jax.Array:
    """Multi-leaf wave histogram from COO storage: rows route to their
    leaf's slot (or a dropped overflow slot), one segment-sum covers all
    slots' explicit entries, and each slot's implicit-zero mass is
    recovered from its own totals. Returns [S, F, B, 3]."""
    global_metrics.note_trace("ops/histogram_multi_sparse")
    eq = row_leaf[:, None] == leaf_ids[None, :]       # [N, S]
    slot = jnp.where(jnp.any(eq, axis=1),
                     jnp.argmax(eq, axis=1), num_slots)
    f, b, s = num_features, max_bins, num_slots
    rs = slot[sb.coo_row]
    flat = (rs * f + sb.coo_feat) * b + sb.coo_bin
    hist = jax.ops.segment_sum(ghT[sb.coo_row], flat,
                               num_segments=(s + 1) * f * b)
    hist = hist[:s * f * b].reshape(s, f, b, NUM_HIST_CHANNELS)
    slot_tot = jax.ops.segment_sum(ghT, slot, num_segments=s + 1)[:s]
    resid = slot_tot[:, None, :] - jnp.sum(hist, axis=2)  # [S, F, 3]
    return hist.at[:, jnp.arange(f), sb.zero_bins].add(resid)


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Sibling histogram via subtraction (ref: serial_tree_learner.cpp:582,
    FeatureHistogram::Subtract). Hessians/counts clamped at 0 to absorb
    floating-point cancellation."""
    sib = parent - child
    return sib.at[..., HESS:].max(0.0)
