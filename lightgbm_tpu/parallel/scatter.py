"""Reduce-scatter histogram aggregation + feature-sharded split search.

The reference's data-parallel learner never all-reduces full histograms:
it ReduceScatter-sums so each machine aggregates only a feature subset,
finds its local best split there, and Allgathers ONE SplitInfo record
(ref: data_parallel_tree_learner.cpp:287-297). This module is that
protocol for the mesh growers:

- ``resolve_hist_reduce`` maps the ``tpu_hist_reduce`` knob
  (auto/psum/scatter) to the mode a given mesh + feature count runs;
- ``make_scatter_split`` builds the shard_map'd split stage: each shard
  holds its owned 1/W feature slice of the (already reduce-scattered)
  histogram, embeds it at its GLOBAL feature offset in a zeros
  [F, B, 3] tensor, masks ``feature_mask`` down to owned features, and
  runs the stock ``ops/split.find_best_split``; per-shard winners then
  combine through one tiny all_gather + argmax of SplitInfo records.

Bit-parity contract (the ``tpu_hist_reduce=psum`` oracle stays
available for A/B): ``lax.psum_scatter`` slices are bitwise equal to
the matching rows of ``lax.psum`` (validated on CPU meshes, and exact
by construction for the int32 quantized path), and the embed keeps the
split-search arithmetic at the ORACLE's [F, B, V] shape and feature
positions — computing gains on a [F/W, B, V] slice instead lets XLA
pick a different cumsum/fma schedule and drifts gains by ~1 ulp.
Non-owned features carry feature_mask=False, which ``_gain_tensors``
maps to exactly K_MIN_SCORE, so the cross-shard argmax (first max ->
lowest shard -> lowest global feature) reproduces the oracle's flat
first-max tie-break over ordered disjoint slices.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..obs import health as obs_health
from ..ops.split import find_best_split
from .mesh import shard_map as _shard_map

__all__ = [
    "resolve_hist_reduce", "make_scatter_split", "allgather_argmax_best",
    "scatter_axis",
]


def scatter_axis(shard_mesh):
    """The mesh axis the feature partition lives on: the LAST axis.

    1-D data meshes scatter over their only axis; hierarchical
    ("dcn", "ici") meshes scatter over the fast in-process ICI axis and
    psum the owned slice over the slow DCN axis (see
    learner._sharded_pallas_multi), so split search and the winner
    all_gather stay ICI-local.
    """
    return shard_mesh.axis_names[-1]


def resolve_hist_reduce(knob: str, shard_mesh, num_features: int, *,
                        pad_ok: bool = False) -> str:
    """Map the ``tpu_hist_reduce`` knob to the mode this mesh runs.

    auto: scatter when the mesh actually spans devices and the feature
    count partitions evenly (``pad_ok`` callers — the voting learner,
    which pads its candidate axis internally — take scatter for any
    count); psum otherwise. Explicit scatter is honored even for uneven
    counts (the builders zero-pad the feature axis to a mesh multiple).
    """
    if knob not in ("auto", "psum", "scatter"):
        raise ValueError(
            f"tpu_hist_reduce={knob!r}: expected auto, psum or scatter")
    if shard_mesh is None or shard_mesh.size <= 1:
        return "psum"
    if knob != "auto":
        return knob
    width = shard_mesh.shape[scatter_axis(shard_mesh)]
    if width <= 1:
        return "psum"
    return "scatter" if (pad_ok or num_features % width == 0) else "psum"


def allgather_argmax_best(info, axis_name: str, *, tag: str,
                          loop_factor: int = 1):
    """All_gather per-shard SplitInfo winners and keep the best.

    ``jnp.argmax`` takes the FIRST maximum, i.e. the lowest shard index
    on exact ties — with ordered feature slices that is the lowest
    global feature id, matching the replicated search's flat-argmax
    tie-break (and the reference's SyncUpGlobalBestSplit,
    feature_parallel_tree_learner.cpp:63).
    """
    gathered = obs_health.all_gather(info, axis_name, tag=tag,
                                     loop_factor=loop_factor)
    winner = jnp.argmax(gathered.gain)
    return jax.tree_util.tree_map(lambda x: x[winner], gathered)


def make_scatter_split(shard_mesh, *, num_features: int,
                       hist_features: int, has_categorical: bool,
                       batched: bool, loop_factor: int = 1):
    """Shard_map'd best-split search over a feature-scattered histogram.

    The returned callable mirrors ``find_best_split``'s signature with
    meta/hp passed per call::

        fn(hist, pg, ph, pc, meta, hp, fmask, parent_out, min_b, max_b,
           depth, rand_bins)

    ``hist`` is the reduce-scattered histogram — a GSPMD value whose
    feature axis (axis 1 when ``batched``, else 0) is sharded over the
    mesh's scatter axis at ``hist_features`` (= F zero-padded to a mesh
    multiple) — and all other operands are replicated. ``batched`` runs
    a leading S axis through ``jax.vmap`` exactly like the oracle
    boundary search does (the vmapped kernel shape must match the
    oracle's for bit-parity, see module docstring). Returns a
    replicated SplitInfo (batched: [S]-leading) whose feature ids are
    GLOBAL — the embed searches features at their true offsets, so no
    post-hoc index shifting is needed.
    """
    axes = shard_mesh.axis_names
    axis = axes[-1]
    width = shard_mesh.shape[axis]
    assert hist_features % width == 0, (hist_features, width)
    f_local = hist_features // width
    F = num_features

    def _local(hist_loc, pg, ph, pc, meta, hp, fmask, parent_out,
               min_b, max_b, depth, rand_bins):
        idx = lax.axis_index(axis)
        offset = idx * f_local
        if batched:
            S = hist_loc.shape[0]
            full = jnp.zeros((S, hist_features) + hist_loc.shape[2:],
                             hist_loc.dtype)
            full = lax.dynamic_update_slice(
                full, hist_loc,
                (jnp.int32(0), offset) + (jnp.int32(0),) * (full.ndim - 2))
            full = full[:, :F]
        else:
            full = jnp.zeros((hist_features,) + hist_loc.shape[1:],
                             hist_loc.dtype)
            full = lax.dynamic_update_slice(
                full, hist_loc,
                (offset,) + (jnp.int32(0),) * (full.ndim - 1))
            full = full[:F]
        owned = ((jnp.arange(F) >= offset)
                 & (jnp.arange(F) < offset + f_local))
        fm = fmask & (owned[None, :] if batched else owned)

        if batched:
            if rand_bins is None:
                info = jax.vmap(
                    lambda hh, a, b, c, f2, po, mn, mx, dp:
                    find_best_split(hh, a, b, c, meta, hp, f2, po, mn,
                                    mx, dp, has_categorical))(
                    full, pg, ph, pc, fm, parent_out, min_b, max_b, depth)
            else:
                info = jax.vmap(
                    lambda hh, a, b, c, f2, po, mn, mx, dp, rb:
                    find_best_split(hh, a, b, c, meta, hp, f2, po, mn,
                                    mx, dp, has_categorical, rb))(
                    full, pg, ph, pc, fm, parent_out, min_b, max_b,
                    depth, rand_bins)
        else:
            info = find_best_split(full, pg, ph, pc, meta, hp, fm,
                                   parent_out, min_b, max_b, depth,
                                   has_categorical, rand_bins)
        return allgather_argmax_best_sliced(info, axis,
                                            loop_factor=loop_factor,
                                            batched=batched)

    hist_spec = (P(None, axis, None, None) if batched
                 else P(axis, None, None))
    # two shard_map variants: extra-trees passes a rand_bins operand,
    # everyone else passes None — a None leaf under a spec is fragile
    # across shard_map implementations, so dispatch in python instead
    fn_rb = _shard_map(
        _local, mesh=shard_mesh,
        in_specs=(hist_spec,) + (P(),) * 11,
        out_specs=P())

    def _local_norb(hist_loc, pg, ph, pc, meta, hp, fmask, parent_out,
                    min_b, max_b, depth):
        return _local(hist_loc, pg, ph, pc, meta, hp, fmask, parent_out,
                      min_b, max_b, depth, None)

    fn_norb = _shard_map(
        _local_norb, mesh=shard_mesh,
        in_specs=(hist_spec,) + (P(),) * 10,
        out_specs=P())

    def fn(hist, pg, ph, pc, meta, hp, fmask, parent_out, min_b, max_b,
           depth, rand_bins=None):
        if rand_bins is None:
            return fn_norb(hist, pg, ph, pc, meta, hp, fmask,
                           parent_out, min_b, max_b, depth)
        return fn_rb(hist, pg, ph, pc, meta, hp, fmask, parent_out,
                     min_b, max_b, depth, rand_bins)
    return fn


def allgather_argmax_best_sliced(info, axis_name: str, *,
                                 loop_factor: int, batched: bool):
    """Winner combine for (optionally [S]-batched) per-shard winners:
    O(W * sizeof(SplitInfo)) on the wire, NOT O(L * F * B)."""
    gathered = obs_health.all_gather(info, axis_name,
                                     tag="split/allgather_best",
                                     loop_factor=loop_factor)
    if not batched:
        winner = jnp.argmax(gathered.gain)
        return jax.tree_util.tree_map(lambda x: x[winner], gathered)
    S = gathered.gain.shape[1]
    winner = jnp.argmax(gathered.gain, axis=0)          # [S]
    sel = jnp.arange(S)
    return jax.tree_util.tree_map(lambda x: x[winner, sel], gathered)
