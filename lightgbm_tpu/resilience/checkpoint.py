"""Atomic checkpoint / resume of full boosting state.

``init_model`` continuation carries only the trees; everything else a
resumed run needs for *bit-identical* continuation — the iteration
counter, the live bagging mask, the evolving host RNG streams
(feature sampling, DART drop selection), the exact f32 score buffers,
DART's drop-history device buffers and weight bookkeeping, objective
init scores and evolving device state, best-iteration/eval results —
is rebuilt approximately or lost. This module snapshots ALL of it at an
iteration boundary, so

    train N iterations straight
    == train k, get killed, resume, train N-k

holds to the last bit of ``model_to_string()`` (asserted across the
fixture matrix by tests/test_resilience.py: plain, bagging, GOSS,
DART, linear-tree, quantized, 2-shard mesh).

Container format (version 1)::

    LGBMTPU-CKPT-v1\\n          magic
    <pickle payload>            numpy-only state dict (no jax arrays)
    \\n#LGBMTPU-CKPT-SHA256:<64 hex>\\n   digest footer over the payload

Writes are atomic (tmp file + ``os.replace``), so a preemption during
the write leaves the previous checkpoint intact; loads verify the
digest footer before unpickling and raise ``CorruptCheckpointError``
(naming the corrupt byte span) on any mismatch or truncation.

Known scope limit: user callback CLOSURES are not serializable, so the
``early_stopping`` callback's internal counters (rounds-without-
improvement, its own best scores) restart at the resume point — the
bit-identical contract is stated for fixed-round training. A run that
already STOPPED early checkpoints its final ``best_iteration``/
``best_score`` and a resume returns immediately, but a kill mid-run
with early stopping may stop at a different round than the
uninterrupted run would have.

Sharded state restores through the target's *current* sharding
(``jax.device_put(host, like.sharding)``): a resume on a resized mesh
re-bins and re-shards through the normal setup path and the restored
row state follows it. When ``tpu_health`` is armed on a multi-device
mesh, the restored replicated score state is digest-compared across
shards before the first resumed iteration contributes (obs/health.py
drift sentinel) — a half-restored replica fails fast.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .errors import CorruptCheckpointError

MAGIC = b"LGBMTPU-CKPT-v1\n"
_FOOTER_TAG = b"\n#LGBMTPU-CKPT-SHA256:"
_FOOTER_LEN = len(_FOOTER_TAG) + 64 + 1  # tag + hex digest + newline
CHECKPOINT_VERSION = 1

# always-on checkpoint accounting (snapshot count / seconds) — feeds
# obs meta -> bench JSON -> perf-gate check 7's overhead ceiling
_totals = {"checkpoints": 0, "seconds_total": 0.0, "last_iteration": -1}


def checkpoint_totals() -> Dict[str, Any]:
    return dict(_totals)


def reset_totals() -> None:
    _totals.update(checkpoints=0, seconds_total=0.0, last_iteration=-1)


def _np_tree(obj):
    """jax/numpy pytree -> plain numpy (host transfer), recursively."""
    if isinstance(obj, dict):
        return {k: _np_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_np_tree(v) for v in obj]
        return out if isinstance(obj, list) else tuple(out)
    if hasattr(obj, "shape") and hasattr(obj, "dtype"):
        return np.asarray(obj)
    return obj


def _jnp_tree(obj):
    """numpy pytree -> jax arrays (leaves only), recursively."""
    import jax.numpy as jnp
    if isinstance(obj, dict):
        return {k: _jnp_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_jnp_tree(v) for v in obj]
        return out if isinstance(obj, list) else tuple(out)
    if isinstance(obj, np.ndarray):
        return jnp.asarray(obj)
    return obj


def _put_like(host: np.ndarray, like):
    """Device-put `host` with the sharding of the freshly-built `like`
    buffer — the restore path's answer to resized meshes: whatever
    layout the rebuilt booster chose, the restored state follows.
    An UNCOMMITTED `like` (e.g. valid scores, which every fresh run
    holds as plain single-device arrays that jit replicates onto the
    mesh at dispatch) must stay uncommitted: committing it to device 0
    conflicts with the mesh-committed train state inside one program
    ("incompatible devices for jitted computation" on elastic resume
    with registered valid sets)."""
    import jax
    try:
        if not getattr(like, "committed", True):
            import jax.numpy as jnp
            return jnp.asarray(np.asarray(host))
        return jax.device_put(np.asarray(host), like.sharding)
    except Exception:
        import jax.numpy as jnp
        return jnp.asarray(host)


# ---------------------------------------------------------------------------
# capture
def _fingerprint(gbdt) -> Dict[str, Any]:
    from .elastic import mesh_shards_of
    return {
        "boosting_type": gbdt.boosting_type,
        "objective": getattr(gbdt.objective, "name", None),
        "num_data": int(gbdt.num_data),
        "num_features": int(gbdt.train_set.num_features),
        "num_tree_per_iteration": int(gbdt.num_tree_per_iteration),
        "num_leaves": int(gbdt.config.num_leaves),
        "num_valid_sets": len(gbdt._valid_sets),
        # mesh width at snapshot time: the ONE key an elastic resume
        # (resilience/elastic.py, tpu_elastic_resume) may tolerate
        # drifting — a resized-mesh restore is a named event, not a
        # silent accident
        "mesh_shards": mesh_shards_of(gbdt),
        # out-of-core slab plan (tpu_stream): a resume whose slab size
        # drifted (e.g. a different LGBM_TPU_HBM_BYTES) would silently
        # change the f32 slab-accumulation order mid-run — refuse it
        # like any other structural drift
        "stream_slab_rows": (int(gbdt._stream.slab_rows)
                             if getattr(gbdt, "_stream", None) is not None
                             else 0),
    }


def _capture_dart(gbdt) -> Dict[str, Any]:
    st = {
        "drop_rng": gbdt._drop_rng.get_state(),
        "tree_weights": list(gbdt._tree_weights),
        "sum_tree_weight": float(gbdt._sum_tree_weight),
        "cur_shrinkage": float(gbdt._cur_shrinkage),
        "num_init_iteration": int(gbdt._num_init_iteration),
        "fast_disabled": bool(gbdt._dart_fast_disabled),
        "dart_t": int(gbdt._dart_t),
        "dart_base": int(gbdt._dart_base),
        "unshrunk": gbdt._dart_unshrunk,
        "factor_snapshot": getattr(gbdt, "_dart_factor_snapshot", None),
        "buffers": None,
    }
    if gbdt._dart is not None:
        st["buffers"] = {
            "leaf_hist": np.asarray(gbdt._dart["leaf_hist"]),
            "vhist": [np.asarray(v) for v in gbdt._dart["vhist"]],
            "leaf_vals": np.asarray(gbdt._dart["leaf_vals"]),
            "factors": np.asarray(gbdt._dart["factors"]),
        }
    return st


def capture_state(booster, target_rounds: int = -1,
                  finished: bool = False) -> Dict[str, Any]:
    """Snapshot `booster`'s full boosting state as a numpy-only dict.
    Must be called at an iteration boundary (engine.train's loop is the
    only caller); materializes pending device records first, which is
    the same math the uninterrupted run applies at save time.

    ``model_str`` is stored ALONGSIDE the exact tree arrays on
    purpose: restore never reads it, but it lets operators inspect a
    checkpoint with any LightGBM tooling and gives a cross-version
    escape hatch (``init_model`` continuation) if the pickled layout
    ever changes. At production shape the [K, N] f32 score buffers
    dominate the container, so the duplication is noise there.
    ``target_rounds`` is likewise inspection metadata, NOT enforced on
    restore — resuming with a different ``num_boost_round`` is
    supported (extend or cut a run) and governed by the loop range."""
    gbdt = booster._gbdt
    if gbdt is None:
        raise ValueError("checkpointing requires a training booster")
    gbdt._materialize_records()
    state: Dict[str, Any] = {
        "version": CHECKPOINT_VERSION,
        "fingerprint": _fingerprint(gbdt),
        "iteration": int(gbdt.iter),
        "target_rounds": int(target_rounds),
        # True when the RUN decided it was done (early stopping / no
        # splittable leaves) before the snapshot: a resume must return
        # immediately instead of training the remaining rounds
        "finished": bool(finished),
        "model_str": booster.model_to_string(),
        "trees": gbdt._host_models,       # exact float64 host arrays
        "init_scores": list(gbdt.init_scores),
        "init_done": bool(gbdt._init_done),
        "shrinkage_rate": float(gbdt.shrinkage_rate),
        "scores": np.asarray(gbdt.scores),
        "sample_mask": np.asarray(gbdt._sample_mask),
        "valid_scores": [np.asarray(v) for v in gbdt._valid_scores],
        "feature_rng": gbdt._feature_rng.get_state(),
        "rng": gbdt._rng.get_state(),
        "cegb_used": np.asarray(gbdt._cegb_used).copy(),
        "objective_state": _np_tree(
            gbdt.objective.device_state(evolving_only=True)
            if gbdt.objective is not None else None),
        "best_iteration": int(booster.best_iteration),
        "best_score": dict(booster.best_score),
        "dart": (_capture_dart(gbdt)
                 if gbdt.boosting_type == "dart" else None),
    }
    return state


# ---------------------------------------------------------------------------
# container I/O
def write_checkpoint(state: Dict[str, Any], path: str) -> Dict[str, Any]:
    """Serialize + atomically write `state`; returns totals meta."""
    t0 = time.perf_counter()
    payload = pickle.dumps(state, protocol=4)
    digest = hashlib.sha256(payload).hexdigest().encode()
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(MAGIC)
        fh.write(payload)
        fh.write(_FOOTER_TAG + digest + b"\n")
        fh.flush()
        os.fsync(fh.fileno())  # durable BEFORE the rename: a host
        # crash right after replace must not leave torn pages behind
        # the only checkpoint
    os.replace(tmp, path)  # a reader never sees a torn checkpoint
    try:  # make the rename itself durable (best-effort on odd FSes)
        dfd = os.open(os.path.dirname(os.path.abspath(path)),
                      os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    # fault plan: corrupt-a-byte runs AFTER the atomic rename, so the
    # on-disk artifact is what the digest check must reject
    from .faults import global_faults
    if global_faults.armed:
        global_faults.maybe_corrupt_checkpoint(path)
    dt = time.perf_counter() - t0
    _totals["checkpoints"] += 1
    _totals["seconds_total"] += dt
    _totals["last_iteration"] = int(state.get("iteration", -1))
    from ..obs.metrics import global_metrics
    global_metrics.inc_counter("resilience/checkpoints")
    global_metrics.set_meta("resilience_checkpoint", checkpoint_totals())
    return checkpoint_totals()


def save_checkpoint(booster, path: str, target_rounds: int = -1,
                    finished: bool = False) -> Dict[str, Any]:
    return write_checkpoint(
        capture_state(booster, target_rounds, finished=finished), path)


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Read + digest-verify + unpickle a checkpoint container."""
    with open(path, "rb") as fh:
        data = fh.read()
    if not data.startswith(MAGIC):
        raise CorruptCheckpointError(
            "not a lightgbm_tpu checkpoint (bad magic)", offset=0,
            path=path)
    if len(data) < len(MAGIC) + _FOOTER_LEN or \
            not data[-_FOOTER_LEN:].startswith(_FOOTER_TAG):
        raise CorruptCheckpointError(
            "checkpoint truncated: digest footer missing",
            offset=len(data), path=path)
    footer = data[-_FOOTER_LEN:]
    want = footer[len(_FOOTER_TAG):-1]
    payload = data[len(MAGIC):-_FOOTER_LEN]
    got = hashlib.sha256(payload).hexdigest().encode()
    if got != want:
        raise CorruptCheckpointError(
            f"checkpoint digest mismatch over payload bytes "
            f"{len(MAGIC)}..{len(MAGIC) + len(payload)}",
            offset=len(MAGIC), path=path)
    try:
        state = pickle.loads(payload)
    except Exception as exc:
        raise CorruptCheckpointError(
            f"checkpoint payload failed to deserialize: {exc!r}",
            offset=len(MAGIC), path=path)
    if state.get("version") != CHECKPOINT_VERSION:
        raise CorruptCheckpointError(
            f"unsupported checkpoint version {state.get('version')!r}",
            offset=len(MAGIC), path=path)
    return state


# ---------------------------------------------------------------------------
# restore
def _restore_dart(gbdt, st: Dict[str, Any]) -> None:
    import jax.numpy as jnp
    gbdt._drop_rng.set_state(st["drop_rng"])
    gbdt._tree_weights = list(st["tree_weights"])
    gbdt._sum_tree_weight = float(st["sum_tree_weight"])
    gbdt._cur_shrinkage = float(st["cur_shrinkage"])
    gbdt._num_init_iteration = int(st["num_init_iteration"])
    gbdt._dart_fast_disabled = bool(st["fast_disabled"])
    gbdt._dart_t = int(st["dart_t"])
    gbdt._dart_base = int(st["dart_base"])
    gbdt._dart_unshrunk = list(st["unshrunk"])
    if st.get("factor_snapshot") is not None:
        gbdt._dart_factor_snapshot = np.asarray(st["factor_snapshot"])
    gbdt._dart_fused = None
    gbdt._dart = None
    if st.get("buffers") is not None:
        buf = st["buffers"]
        gbdt._dart = {
            "leaf_hist": jnp.asarray(buf["leaf_hist"]),
            "vhist": [jnp.asarray(v) for v in buf["vhist"]],
            "leaf_vals": jnp.asarray(buf["leaf_vals"]),
            "factors": jnp.asarray(buf["factors"]),
        }


def restore_booster(booster, state: Dict[str, Any]) -> int:
    """Install `state` into a freshly-constructed Booster (same params,
    same train/valid data, possibly a different mesh size). Returns the
    iteration to resume from."""
    from . import elastic
    gbdt = booster._gbdt
    if gbdt is None:
        raise ValueError("resume requires a training booster")
    # structural drift always refuses; mesh-shape drift alone is an
    # elastic resume when tpu_elastic_resume allows it
    resized = elastic.check_fingerprint(
        state["fingerprint"], _fingerprint(gbdt),
        elastic.elastic_enabled(gbdt.config))

    gbdt._host_models = list(state["trees"])
    gbdt._device_records = []
    gbdt._record_lrs = []
    gbdt.iter = int(state["iteration"])
    gbdt.init_scores = list(state["init_scores"])
    gbdt._init_done = bool(state["init_done"])
    gbdt.shrinkage_rate = float(state["shrinkage_rate"])
    gbdt.scores = _put_like(state["scores"], gbdt.scores)
    gbdt._sample_mask = _put_like(state["sample_mask"], gbdt._sample_mask)
    gbdt._valid_scores = [
        _put_like(v, gbdt._valid_scores[i])
        for i, v in enumerate(state["valid_scores"])]
    gbdt._feature_rng.set_state(state["feature_rng"])
    gbdt._rng.set_state(state["rng"])
    gbdt._cegb_used = np.asarray(state["cegb_used"]).copy()
    if state.get("objective_state") is not None and \
            gbdt.objective is not None:
        gbdt.objective.swap_device_state(
            _jnp_tree(state["objective_state"]))
    if state.get("dart") is not None and gbdt.boosting_type == "dart":
        _restore_dart(gbdt, state["dart"])
    booster.best_iteration = int(state["best_iteration"])
    booster.best_score = dict(state["best_score"])
    gbdt._fused = None  # rebuild against the restored buffers

    # rejoin gate (resilience/elastic.py): digest-validate the restored
    # state across the (possibly resized) mesh BEFORE the first resumed
    # iteration votes; a diverged shard raises ElasticResumeError.
    # Also counts resilience/resumes (+ mesh_resizes when resized).
    elastic.gate_rejoin(gbdt, state, resized=resized)
    return gbdt.iter


def try_load(path: str) -> Optional[Dict[str, Any]]:
    """Load the checkpoint at `path` if one exists; None when absent.
    Corruption still raises — silently retraining over a torn
    checkpoint is exactly the failure mode the digest exists for."""
    if not path or not os.path.exists(path):
        return None
    return load_checkpoint(path)
