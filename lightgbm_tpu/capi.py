"""In-process backend of the C-ABI shim.

`native/src/lgbm_tpu_capi.cpp` embeds a CPython interpreter, imports this
module, and forwards every `LGBM_*` call here with raw pointers passed as
integers. This module wraps those pointers with ctypes/NumPy, drives the
ordinary Python API (`basic.Dataset`/`basic.Booster`), and returns
primitive values the C side can marshal back — giving reference harnesses
and third-party tooling the familiar `lib_lightgbm` calling convention
(ref: include/LightGBM/c_api.h; internal Booster wrapper c_api.cpp:170).

Handles are small integers into a registry (the C side casts them to the
opaque `DatasetHandle`/`BoosterHandle` pointers the reference API uses).
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict

import numpy as np

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    # honor an explicit CPU pin even under the axon sitecustomize, whose
    # PJRT plugin overrides JAX_PLATFORMS (see hostenv.cpu_child_env)
    import jax
    jax.config.update("jax_platforms", "cpu")

from .basic import Booster, Dataset
from .config import Config

# C_API_DTYPE_* (ref: c_api.h:36-39)
_DTYPES = {0: ctypes.c_float, 1: ctypes.c_double,
           2: ctypes.c_int32, 3: ctypes.c_int64}
_NP_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}

# C_API_PREDICT_* (ref: c_api.h:41-44)
_PREDICT_NORMAL, _PREDICT_RAW, _PREDICT_LEAF, _PREDICT_CONTRIB = range(4)

_registry: Dict[int, object] = {}
_next_handle = [1]


def _new_handle(obj) -> int:
    h = _next_handle[0]
    _next_handle[0] += 1
    _registry[h] = obj
    return h


def _get(handle: int):
    try:
        return _registry[handle]
    except KeyError:
        raise ValueError(f"invalid handle {handle}")


def _array_from_ptr(ptr: int, count: int, dtype: int) -> np.ndarray:
    if count == 0:
        return np.empty(0, _NP_DTYPES[dtype])
    ct = _DTYPES[dtype]
    buf = (ct * count).from_address(ptr)
    return np.asarray(np.ctypeslib.as_array(buf), _NP_DTYPES[dtype]).copy()


def _write_doubles(ptr: int, values: np.ndarray) -> int:
    values = np.ascontiguousarray(values, np.float64)
    ctypes.memmove(ptr, values.ctypes.data, values.nbytes)
    return int(values.size)


def _parse_params(parameters: str) -> Dict[str, str]:
    return Config.kv2map((parameters or "").split())


# -- dataset ---------------------------------------------------------------
def dataset_create_from_mat(data_ptr: int, data_type: int, nrow: int,
                            ncol: int, is_row_major: int, parameters: str,
                            reference: int) -> int:
    """(ref: LGBM_DatasetCreateFromMat c_api.cpp:1311)"""
    flat = _array_from_ptr(data_ptr, nrow * ncol, data_type)
    mat = (flat.reshape(nrow, ncol) if is_row_major
           else flat.reshape(ncol, nrow).T)
    ref = _get(reference) if reference else None
    ds = Dataset(np.asarray(mat, np.float64), reference=ref,
                 params=_parse_params(parameters))
    return _new_handle(ds)


def _csr_from_ptrs(indptr_ptr: int, indptr_type: int, indices_ptr: int,
                   data_ptr: int, data_type: int, nindptr: int,
                   nelem: int, num_col: int):
    from scipy import sparse
    indptr = _array_from_ptr(indptr_ptr, nindptr, indptr_type)
    indices = _array_from_ptr(indices_ptr, nelem, 2)  # int32
    data = _array_from_ptr(data_ptr, nelem, data_type)
    return sparse.csr_matrix(
        (np.asarray(data, np.float64), indices, indptr),
        shape=(nindptr - 1, num_col))


def dataset_create_from_csr(indptr_ptr: int, indptr_type: int,
                            indices_ptr: int, data_ptr: int,
                            data_type: int, nindptr: int, nelem: int,
                            num_col: int, parameters: str,
                            reference: int) -> int:
    """(ref: LGBM_DatasetCreateFromCSR c_api.cpp:1311) — feeds the
    densification-free sparse ingestion path."""
    csr = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                         data_type, nindptr, nelem, num_col)
    ref = _get(reference) if reference else None
    ds = Dataset(csr, reference=ref, params=_parse_params(parameters))
    return _new_handle(ds)


def _predict_into(bst, matrix, predict_type: int, start_iteration: int,
                  num_iteration: int, out_ptr: int) -> int:
    """Shared predict dispatch + result write for the dense and CSR
    entry points."""
    pred = bst.predict(matrix, start_iteration=start_iteration,
                       num_iteration=num_iteration,
                       raw_score=predict_type == _PREDICT_RAW,
                       pred_leaf=predict_type == _PREDICT_LEAF,
                       pred_contrib=predict_type == _PREDICT_CONTRIB)
    return _write_doubles(out_ptr, np.asarray(pred).reshape(-1))


def booster_predict_for_csr(handle: int, indptr_ptr: int, indptr_type: int,
                            indices_ptr: int, data_ptr: int,
                            data_type: int, nindptr: int, nelem: int,
                            num_col: int, predict_type: int,
                            start_iteration: int, num_iteration: int,
                            out_ptr: int) -> int:
    """(ref: LGBM_BoosterPredictForCSR c_api.cpp)"""
    csr = _csr_from_ptrs(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                         data_type, nindptr, nelem, num_col)
    return _predict_into(_get(handle), csr, predict_type, start_iteration,
                         num_iteration, out_ptr)


def dataset_create_from_file(filename: str, parameters: str,
                             reference: int) -> int:
    """(ref: LGBM_DatasetCreateFromFile c_api.cpp:1044)"""
    ref = _get(reference) if reference else None
    ds = Dataset(filename, reference=ref, params=_parse_params(parameters))
    return _new_handle(ds)


def dataset_set_field(handle: int, field: str, ptr: int, count: int,
                      dtype: int) -> None:
    """(ref: LGBM_DatasetSetField c_api.cpp)"""
    ds = _get(handle)
    values = _array_from_ptr(ptr, count, dtype)
    if field == "label":
        ds.set_label(values)
    elif field == "weight":
        ds.set_weight(values)
    elif field in ("group", "query"):
        ds.set_group(values)
    elif field == "init_score":
        ds.set_init_score(values)
    else:
        raise ValueError(f"unknown field {field}")


def dataset_num_data(handle: int) -> int:
    return int(_get(handle).num_data())


def dataset_num_feature(handle: int) -> int:
    return int(_get(handle).num_feature())


def handle_free(handle: int) -> None:
    _registry.pop(handle, None)
    _eval_counts.pop(handle, None)


# -- booster ---------------------------------------------------------------
def booster_create(train_handle: int, parameters: str) -> int:
    """(ref: LGBM_BoosterCreate c_api.cpp:1998)"""
    bst = Booster(_parse_params(parameters), _get(train_handle))
    return _new_handle(bst)


def booster_create_from_modelfile(filename: str) -> tuple:
    """(ref: LGBM_BoosterCreateFromModelfile)"""
    bst = Booster(model_file=filename)
    return _new_handle(bst), int(bst.num_trees())


def booster_add_valid_data(handle: int, valid_handle: int) -> None:
    bst = _get(handle)
    bst.add_valid(_get(valid_handle),
                  f"valid_{len(bst._name_valid_sets)}")


def booster_update_one_iter(handle: int) -> int:
    """Returns 1 when training is finished
    (ref: LGBM_BoosterUpdateOneIter c_api.cpp:2121)."""
    return int(bool(_get(handle).update()))


def booster_current_iteration(handle: int) -> int:
    return int(_get(handle).current_iteration())


_eval_counts: Dict[int, int] = {}


def booster_get_eval_counts(handle: int) -> int:
    # the metric set is fixed after Booster creation; cache so harnesses
    # polling the count each iteration don't pay a full evaluation
    if handle not in _eval_counts:
        _eval_counts[handle] = len(_get(handle).eval_train())
    return _eval_counts[handle]


def booster_get_eval(handle: int, data_idx: int, out_ptr: int) -> int:
    """data_idx 0 = train, 1.. = valid sets (ref: LGBM_BoosterGetEval)."""
    bst = _get(handle)
    if data_idx == 0:
        results = bst.eval_train()
    else:
        name = bst._name_valid_sets[data_idx - 1]
        results = [r for r in bst.eval_valid() if r[0] == name]
    return _write_doubles(out_ptr, np.asarray([r[2] for r in results]))


def booster_predict_for_mat(handle: int, data_ptr: int, data_type: int,
                            nrow: int, ncol: int, is_row_major: int,
                            predict_type: int, start_iteration: int,
                            num_iteration: int, out_ptr: int) -> int:
    """(ref: LGBM_BoosterPredictForMat c_api.cpp:2558)"""
    flat = _array_from_ptr(data_ptr, nrow * ncol, data_type)
    mat = (flat.reshape(nrow, ncol) if is_row_major
           else flat.reshape(ncol, nrow).T)
    return _predict_into(_get(handle), np.asarray(mat, np.float64),
                         predict_type, start_iteration, num_iteration,
                         out_ptr)


def booster_save_model(handle: int, start_iteration: int,
                       num_iteration: int, importance_type: int,
                       filename: str) -> None:
    """(ref: LGBM_BoosterSaveModel)"""
    _get(handle).save_model(
        filename, num_iteration=num_iteration,
        start_iteration=start_iteration,
        importance_type="gain" if importance_type == 1 else "split")


def booster_save_model_to_string(handle: int, start_iteration: int,
                                 num_iteration: int,
                                 importance_type: int) -> str:
    return _get(handle).model_to_string(
        num_iteration=num_iteration, start_iteration=start_iteration,
        importance_type="gain" if importance_type == 1 else "split")


def booster_num_feature(handle: int) -> int:
    return int(_get(handle).num_feature())
