"""SHAP feature contributions (TreeSHAP).

(ref: include/LightGBM/tree.h PredictContrib + the treeshap recursion in
src/io/tree.cpp; algorithm from Lundberg et al. "Consistent
Individualized Feature Attribution for Tree Ensembles".)

Exact path-dependent TreeSHAP over the host tree arrays. Output layout
matches the reference: [N, (F+1) * K] with the last slot per class being
the expected value (bias).

`pred_contrib` dispatches to the batched device kernel (ops/shap.py:
pack-time path decomposition + vectorized permutation weights) unless
the `tpu_shap` knob says off or the model has linear-tree leaves; the
recursion below is retained as the parity oracle and the fallback, with
the same chunked dispatch and `note_predict` accounting as the main
predict path so even the fallback is observable and memory-bounded.
"""

from __future__ import annotations

import time

import numpy as np


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction",
                 "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0, one_fraction=0.0,
                 pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight


def _extend_path(path, unique_depth, zero_fraction, one_fraction,
                 feature_index):
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += (one_fraction * path[i].pweight * (i + 1)
                                / (unique_depth + 1))
        path[i].pweight = (zero_fraction * path[i].pweight
                           * (unique_depth - i) / (unique_depth + 1))


def _unwind_path(path, unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = (next_one_portion * (unique_depth + 1)
                               / ((i + 1) * one_fraction))
            next_one_portion = tmp - path[i].pweight * zero_fraction * \
                (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = (path[i].pweight * (unique_depth + 1)
                               / (zero_fraction * (unique_depth - i)))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path, unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = (next_one_portion * (unique_depth + 1)
                   / ((i + 1) * one_fraction))
            total += tmp
            next_one_portion = (path[i].pweight - tmp * zero_fraction *
                                ((unique_depth - i) / (unique_depth + 1)))
        else:
            total += (path[i].pweight / zero_fraction
                      / ((unique_depth - i) / (unique_depth + 1)))
    return total


def _tree_shap(tree, row, phi, node, unique_depth, parent_path,
               parent_zero_fraction, parent_one_fraction,
               parent_feature_index):
    path = [_PathElement(p.feature_index, p.zero_fraction, p.one_fraction,
                         p.pweight) for p in parent_path[:unique_depth]] + \
        [_PathElement() for _ in range(2)]
    _extend_path(path, unique_depth, parent_zero_fraction,
                 parent_one_fraction, parent_feature_index)

    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += (w * (el.one_fraction - el.zero_fraction)
                                      * tree.leaf_value[leaf])
        return

    hot, cold = _decide_children(tree, node, row)
    node_count = tree.internal_count[node]
    hot_count = _child_count(tree, hot)
    cold_count = _child_count(tree, cold)
    incoming_zero_fraction = 1.0
    incoming_one_fraction = 1.0
    feature = tree.split_feature[node]

    # dedup: if we've seen this feature before on the path, unwind it
    path_index = 0
    while path_index <= unique_depth:
        if path[path_index].feature_index == feature:
            break
        path_index += 1
    if path_index != unique_depth + 1:
        incoming_zero_fraction = path[path_index].zero_fraction
        incoming_one_fraction = path[path_index].one_fraction
        _unwind_path(path, unique_depth, path_index)
        unique_depth -= 1

    denom = node_count if node_count > 0 else 1
    _tree_shap(tree, row, phi, hot, unique_depth + 1, path,
               hot_count / denom * incoming_zero_fraction,
               incoming_one_fraction, feature)
    _tree_shap(tree, row, phi, cold, unique_depth + 1, path,
               cold_count / denom * incoming_zero_fraction, 0.0, feature)


def _child_count(tree, child):
    if child < 0:
        return float(tree.leaf_count[~child])
    return float(tree.internal_count[child])


def _decide_children(tree, node, row):
    go_left = tree._decide(node, row[tree.split_feature[node]])
    if go_left:
        return tree.left_child[node], tree.right_child[node]
    return tree.right_child[node], tree.left_child[node]


def _expected_value(tree) -> float:
    if tree.num_internal == 0:
        return float(tree.leaf_value[0])
    total = tree.leaf_count.sum()
    if total <= 0:
        return float(np.mean(tree.leaf_value))
    return float(np.sum(tree.leaf_value * tree.leaf_count) / total)


def _contrib_over_trees(tree_of, n_iters: int, k: int, data: np.ndarray,
                        num_feat: int, start_iteration: int,
                        num_iteration: int,
                        chunk: int = 1 << 20) -> np.ndarray:
    """Shared TreeSHAP accumulation (host recursion; the device oracle).
    tree_of(it, ki) -> Tree. Rows dispatch in `chunk`-sized blocks with
    the same note_predict accounting as the device engines."""
    if n_iters > 0 and k > 0 and getattr(tree_of(0, 0), "is_linear", False):
        raise ValueError(
            "pred_contrib is not supported for linear trees (the "
            "reference raises the same restriction)")
    n = data.shape[0]
    chunk = max(int(chunk or (1 << 20)), 1)
    out = np.zeros((n, k, num_feat + 1))
    end = n_iters if num_iteration < 0 else min(
        n_iters, start_iteration + num_iteration)
    window = [(it, ki) for it in range(start_iteration, end)
              for ki in range(k)]
    t0 = time.perf_counter()
    for it, ki in window:
        out[:, ki, -1] += _expected_value(tree_of(it, ki))
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        for it, ki in window:
            tree = tree_of(it, ki)
            if tree.num_internal == 0:
                continue
            for r in range(lo, hi):
                phi = np.zeros(num_feat + 1)
                _tree_shap(tree, data[r], phi, 0, 0, [], 1.0, 1.0, -1)
                out[r, ki, :-1] += phi[:-1]
    if n:
        from .obs.metrics import global_metrics
        global_metrics.note_predict(n, time.perf_counter() - t0)
    return out.reshape(n, k * (num_feat + 1)) if k > 1 else \
        out.reshape(n, num_feat + 1)


def _use_device(tpu_shap, trees) -> bool:
    """Route to the batched device kernel unless the knob says off or
    the model carries linear-tree leaves (the host path owns those —
    it raises the reference's linear-tree restriction)."""
    mode = str(tpu_shap if tpu_shap is not None else "auto").lower()
    if mode in ("off", "false", "0", "host"):
        return False
    if not trees or any(getattr(t, "is_linear", False) for t in trees):
        return False
    return True


def loaded_pred_contrib(model, data: np.ndarray, start_iteration: int = 0,
                        num_iteration: int = -1,
                        predict_chunk=None) -> np.ndarray:
    """SHAP values for a model loaded from text (model_io.LoadedModel)."""
    data = np.asarray(data, np.float64)
    k = max(model.num_tree_per_iteration, 1)
    n_iters = model.num_iterations
    end = n_iters if num_iteration < 0 else min(
        n_iters, start_iteration + num_iteration)
    chunk = int(predict_chunk or model.predict_chunk or (1 << 20))
    trees = model.trees[start_iteration * k:end * k]
    if _use_device(model.params.get("tpu_shap", "auto"), trees):
        from .ops.shap import shap_contrib_cached
        # same cache key convention as LoadedModel.predict_raw, so the
        # path pack rides the same owner packer as the traversal pack
        return shap_contrib_cached(
            model, trees, k, data, model.max_feature_idx + 1,
            cache_key=(start_iteration, end, len(model.trees)),
            chunk=chunk)
    return _contrib_over_trees(
        lambda it, ki: model.trees[it * k + ki], n_iters, k,
        data, model.max_feature_idx + 1, start_iteration, num_iteration,
        chunk=chunk)


def predict_contrib(booster, data: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1,
                    predict_chunk=None) -> np.ndarray:
    data = np.asarray(data, np.float64)
    k = max(booster.num_tree_per_iteration, 1)
    n_iters = len(booster.models)
    end = n_iters if num_iteration < 0 else min(
        n_iters, start_iteration + num_iteration)
    cfg = getattr(booster, "config", None)
    mode = getattr(cfg, "tpu_shap", "auto")
    chunk = int(predict_chunk
                or getattr(cfg, "tpu_predict_chunk", 0) or (1 << 20))
    trees = [booster.models[it][ki]
             for it in range(start_iteration, end) for ki in range(k)]
    num_feat = booster.train_set.num_total_features
    if _use_device(mode, trees):
        from .ops.shap import shap_contrib_cached
        # same cache key convention as GBDT.predict_raw
        return shap_contrib_cached(
            booster, trees, k, data, num_feat,
            cache_key=(start_iteration, end, booster.current_iteration()),
            chunk=chunk)
    return _contrib_over_trees(
        lambda it, ki: booster.models[it][ki], n_iters, k, data,
        num_feat, start_iteration, num_iteration, chunk=chunk)
