"""Run the REFERENCE's own C API test driver, unmodified, against
lib_lightgbm_tpu.so (ref: tests/c_api_test/test_.py — the reference's
ctypes smoke test). The driver is imported from its read-only location;
a synthetic `lightgbm.basic` module hands it our shim as `_LIB`, so the
exact byte-for-byte reference harness exercises this framework's ABI.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SO_PATH = REPO / "lightgbm_tpu" / "lib_lightgbm_tpu.so"
REF_DRIVER = Path("/root/reference/tests/c_api_test/test_.py")

RUNNER = r"""
import ctypes, importlib.util, sys, types, tempfile
from pathlib import Path

so_path, driver_path = sys.argv[1], sys.argv[2]
# hand the reference driver OUR shim as lightgbm.basic._LIB
pkg = types.ModuleType("lightgbm")
basic = types.ModuleType("lightgbm.basic")
basic._LIB = ctypes.CDLL(so_path)
pkg.basic = basic
sys.modules["lightgbm"] = pkg
sys.modules["lightgbm.basic"] = basic

spec = importlib.util.spec_from_file_location("ref_capi_test", driver_path)
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)

with tempfile.TemporaryDirectory() as td:
    mod.test_dataset(Path(td))
print("REF-DATASET-OK")
with tempfile.TemporaryDirectory() as td:
    mod.test_booster(Path(td))
print("REF-BOOSTER-OK")
mod.test_max_thread_control()
print("REF-THREADS-OK")
"""


@pytest.mark.slow
def test_reference_c_api_driver(tmp_path):
    if not REF_DRIVER.exists():
        pytest.skip("reference c_api_test driver not available")
    from test_capi import _ensure_built
    _ensure_built()
    runner = tmp_path / "runner.py"
    runner.write_text(RUNNER)
    from lightgbm_tpu.hostenv import cpu_child_env
    env = cpu_child_env()
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, str(runner), str(SO_PATH), str(REF_DRIVER)],
        env=env, capture_output=True, text=True, timeout=2400)
    assert proc.returncode == 0, proc.stderr[-3000:]
    for marker in ("REF-DATASET-OK", "REF-BOOSTER-OK", "REF-THREADS-OK"):
        assert marker in proc.stdout, (marker, proc.stdout[-2000:],
                                       proc.stderr[-2000:])
