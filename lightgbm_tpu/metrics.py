"""Evaluation metrics.

Re-implementation of the reference metric layer
(ref: src/metric/metric.cpp:22 factory; regression_metric.hpp,
binary_metric.hpp, multiclass_metric.hpp, rank_metric.hpp,
xentropy_metric.hpp, dcg_calculator.cpp). Metrics run on host numpy over
raw scores pulled back once per eval round (the reference evaluates on CPU
as well). Each metric returns (name, value, is_higher_better).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .config import Config
from .dataset import Metadata


class Metric:
    name = "none"
    is_higher_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data
        self.label = metadata.label if metadata.label is not None else \
            np.zeros(num_data, np.float32)
        self.weight = metadata.weight
        self.sum_weight = (float(np.sum(self.weight))
                           if self.weight is not None else float(num_data))

    def _avg(self, values: np.ndarray) -> float:
        if self.weight is not None:
            return float(np.sum(values * self.weight) / self.sum_weight)
        return float(np.mean(values))

    def eval(self, prob: np.ndarray, raw: np.ndarray) -> List[Tuple[str, float, bool]]:
        """prob: objective-converted output; raw: raw scores."""
        raise NotImplementedError


# --- regression (ref: src/metric/regression_metric.hpp) -------------------
class _PointwiseMetric(Metric):
    def point_loss(self, label, pred):
        raise NotImplementedError

    def transform(self, value: float) -> float:
        return value

    def eval(self, prob, raw):
        v = self.transform(self._avg(self.point_loss(self.label, prob)))
        return [(self.name, v, self.is_higher_better)]


class L2Metric(_PointwiseMetric):
    name = "l2"

    def point_loss(self, label, pred):
        return (label - pred) ** 2


class RMSEMetric(L2Metric):
    name = "rmse"

    def transform(self, value):
        return float(np.sqrt(value))


class L1Metric(_PointwiseMetric):
    name = "l1"

    def point_loss(self, label, pred):
        return np.abs(label - pred)


class QuantileMetric(_PointwiseMetric):
    name = "quantile"

    def point_loss(self, label, pred):
        a = self.config.alpha
        d = label - pred
        return np.where(d >= 0, a * d, (a - 1.0) * d)


class HuberMetric(_PointwiseMetric):
    name = "huber"

    def point_loss(self, label, pred):
        a = self.config.alpha
        d = np.abs(label - pred)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseMetric):
    name = "fair"

    def point_loss(self, label, pred):
        c = self.config.fair_c
        x = np.abs(label - pred)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseMetric):
    name = "poisson"

    def point_loss(self, label, pred):
        eps = 1e-10
        return pred - label * np.log(np.maximum(pred, eps))


class MAPEMetric(_PointwiseMetric):
    name = "mape"

    def point_loss(self, label, pred):
        return np.abs((label - pred) / np.maximum(1.0, np.abs(label)))


class GammaMetric(_PointwiseMetric):
    """Gamma negative log-likelihood with psi = 1
    (ref: regression_metric.hpp GammaMetric)."""
    name = "gamma"

    def point_loss(self, label, pred):
        eps = 1e-10
        p = np.maximum(pred, eps)
        lab = np.maximum(label, eps)
        # -log L = y/mu + log(mu) - log(y)   (unit shape)
        return lab / p + np.log(p) - np.log(lab)


class GammaDevianceMetric(_PointwiseMetric):
    name = "gamma_deviance"

    def point_loss(self, label, pred):
        eps = 1e-10
        f = label / np.maximum(pred, eps)
        return 2.0 * (f - np.log(np.maximum(f, eps)) - 1.0)


class TweedieMetric(_PointwiseMetric):
    name = "tweedie"

    def point_loss(self, label, pred):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        p = np.maximum(pred, eps)
        a = label * np.power(p, 1.0 - rho) / (1.0 - rho)
        b = np.power(p, 2.0 - rho) / (2.0 - rho)
        return -a + b


class R2Metric(Metric):
    name = "r2"
    is_higher_better = True

    def eval(self, prob, raw):
        w = self.weight if self.weight is not None else np.ones_like(self.label)
        mean = np.sum(self.label * w) / np.sum(w)
        ss_res = np.sum(w * (self.label - prob) ** 2)
        ss_tot = np.sum(w * (self.label - mean) ** 2)
        return [(self.name, float(1.0 - ss_res / max(ss_tot, 1e-300)), True)]


# --- binary (ref: src/metric/binary_metric.hpp) ---------------------------
class BinaryLoglossMetric(_PointwiseMetric):
    name = "binary_logloss"

    def point_loss(self, label, pred):
        eps = 1e-15
        p = np.clip(pred, eps, 1.0 - eps)
        y = (label > 0).astype(np.float64)
        return -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))


class BinaryErrorMetric(_PointwiseMetric):
    name = "binary_error"

    def point_loss(self, label, pred):
        y = (label > 0).astype(np.float64)
        return ((pred > 0.5) != (y > 0)).astype(np.float64)


def _auc(label, prob, weight=None) -> float:
    """Weighted ROC-AUC by rank-sum (ref: binary_metric.hpp AUCMetric)."""
    y = (label > 0)
    w = weight if weight is not None else np.ones(len(label))
    order = np.argsort(prob, kind="mergesort")
    p_s, y_s, w_s = prob[order], y[order], w[order]
    # tie-aware trapezoid accumulation
    pos_w = np.where(y_s, w_s, 0.0)
    neg_w = np.where(~y_s, w_s, 0.0)
    # group by distinct prob values
    boundaries = np.nonzero(np.diff(p_s))[0]
    idx = np.concatenate([boundaries, [len(p_s) - 1]])
    cpos = np.cumsum(pos_w)[idx]
    cneg = np.cumsum(neg_w)[idx]
    gpos = np.diff(np.concatenate([[0.0], cpos]))
    gneg = np.diff(np.concatenate([[0.0], cneg]))
    prev_neg = np.concatenate([[0.0], cneg[:-1]])
    area = np.sum(gpos * (prev_neg + gneg * 0.5))
    tot_pos, tot_neg = cpos[-1], cneg[-1]
    if tot_pos <= 0 or tot_neg <= 0:
        return 0.5
    return float(area / (tot_pos * tot_neg))


class AUCMetric(Metric):
    name = "auc"
    is_higher_better = True

    def eval(self, prob, raw):
        return [(self.name, _auc(self.label, prob, self.weight), True)]


class AveragePrecisionMetric(Metric):
    name = "average_precision"
    is_higher_better = True

    def eval(self, prob, raw):
        w = self.weight if self.weight is not None else np.ones(len(self.label))
        order = np.argsort(-prob, kind="mergesort")
        y = (self.label[order] > 0)
        ws = w[order]
        tp = np.cumsum(ws * y)
        fp = np.cumsum(ws * ~y)
        precision = tp / np.maximum(tp + fp, 1e-300)
        dtp = np.diff(np.concatenate([[0.0], tp]))
        total_pos = tp[-1]
        if total_pos <= 0:
            return [(self.name, 0.0, True)]
        return [(self.name, float(np.sum(precision * dtp) / total_pos), True)]


# --- multiclass (ref: src/metric/multiclass_metric.hpp) -------------------
class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, prob, raw):
        eps = 1e-15
        y = self.label.astype(int)
        p = np.clip(prob[np.arange(len(y)), y], eps, 1.0)
        losses = -np.log(p)
        return [(self.name, self._avg(losses), False)]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, prob, raw):
        y = self.label.astype(int)
        k = self.config.multi_error_top_k
        if k <= 1:
            err = (np.argmax(prob, axis=1) != y).astype(np.float64)
        else:
            ranks = np.argsort(-prob, axis=1)[:, :k]
            err = (~np.any(ranks == y[:, None], axis=1)).astype(np.float64)
        return [(self.name, self._avg(err), False)]


class AucMuMetric(Metric):
    """Multi-class AUC-mu (ref: multiclass_metric.hpp auc_mu)."""
    name = "auc_mu"
    is_higher_better = True

    def eval(self, prob, raw):
        y = self.label.astype(int)
        k = prob.shape[1]
        aucs = []
        for i in range(k):
            for j in range(i + 1, k):
                sel = (y == i) | (y == j)
                if not np.any(y[sel] == i) or not np.any(y[sel] == j):
                    continue
                # decision score: prob difference as 1-D discriminant
                s = prob[sel, i] - prob[sel, j]
                aucs.append(_auc((y[sel] == i).astype(np.float32), s))
        v = float(np.mean(aucs)) if aucs else 0.5
        return [(self.name, v, True)]


# --- cross-entropy (ref: src/metric/xentropy_metric.hpp) ------------------
class CrossEntropyMetric(_PointwiseMetric):
    name = "cross_entropy"

    def point_loss(self, label, pred):
        eps = 1e-15
        p = np.clip(pred, eps, 1.0 - eps)
        return -(label * np.log(p) + (1.0 - label) * np.log(1.0 - p))


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, prob, raw):
        # prob here = log1p(exp(raw)) from the objective's convert_output
        eps = 1e-15
        hhat = np.maximum(prob, eps)
        loss = hhat - self.label * np.log(np.maximum(hhat, eps))
        return [(self.name, self._avg(loss), False)]


class KLDivMetric(Metric):
    name = "kldiv"

    def eval(self, prob, raw):
        eps = 1e-15
        p = np.clip(prob, eps, 1.0 - eps)
        y = np.clip(self.label, eps, 1.0 - eps)
        kl = (y * np.log(y / p) + (1.0 - y) * np.log((1.0 - y) / (1.0 - p)))
        return [(self.name, self._avg(kl), False)]


# --- ranking (ref: src/metric/rank_metric.hpp, dcg_calculator.cpp) --------
class NDCGMetric(Metric):
    name = "ndcg"
    is_higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("ndcg metric requires query data")
        gains = self.config.label_gain
        if gains is None:
            max_label = int(self.label.max()) if num_data else 0
            gains = [(1 << i) - 1 for i in range(max(max_label + 1, 2))]
        self.label_gain = np.asarray(gains, np.float64)

    def _dcg_at(self, labels, order, k):
        top = order[:k]
        gains = self.label_gain[labels[top].astype(int)]
        return np.sum(gains / np.log2(np.arange(len(top)) + 2.0))

    def eval(self, prob, raw):
        qb = self.metadata.query_boundaries
        ks = self.config.eval_at
        sums = np.zeros(len(ks))
        cnt = 0
        for q in range(len(qb) - 1):
            s, e = qb[q], qb[q + 1]
            lab = self.label[s:e]
            sc = raw[s:e]
            order = np.argsort(-sc, kind="mergesort")
            ideal = np.argsort(-lab, kind="mergesort")
            for ki, k in enumerate(ks):
                idcg = self._dcg_at(lab, ideal, k)
                if idcg > 0:
                    sums[ki] += self._dcg_at(lab, order, k) / idcg
                else:
                    sums[ki] += 1.0
            cnt += 1
        return [(f"ndcg@{k}", float(sums[i] / max(cnt, 1)), True)
                for i, k in enumerate(ks)]


class MAPMetric(Metric):
    name = "map"
    is_higher_better = True

    def eval(self, prob, raw):
        qb = self.metadata.query_boundaries
        if qb is None:
            raise ValueError("map metric requires query data")
        ks = self.config.eval_at
        sums = np.zeros(len(ks))
        cnt = 0
        for q in range(len(qb) - 1):
            s, e = qb[q], qb[q + 1]
            rel = (self.label[s:e] > 0)
            order = np.argsort(-raw[s:e], kind="mergesort")
            rel_sorted = rel[order]
            hits = np.cumsum(rel_sorted)
            prec = hits / (np.arange(len(rel_sorted)) + 1.0)
            for ki, k in enumerate(ks):
                topk = rel_sorted[:k]
                npos = topk.sum()
                if npos > 0:
                    sums[ki] += np.sum(prec[:k] * topk) / npos
            cnt += 1
        return [(f"map@{k}", float(sums[i] / max(cnt, 1)), True)
                for i, k in enumerate(ks)]


# ---------------------------------------------------------------------------
_METRICS = {
    "l1": L1Metric, "l2": L2Metric, "rmse": RMSEMetric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MAPEMetric,
    "gamma": GammaMetric, "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric, "r2": R2Metric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "average_precision": AveragePrecisionMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "auc_mu": AucMuMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kldiv": KLDivMetric,
    "ndcg": NDCGMetric, "map": MAPMetric,
}


def create_metrics(config: Config, names: Optional[List[str]] = None
                   ) -> List[Metric]:
    """Factory (ref: Metric::CreateMetric, src/metric/metric.cpp:22)."""
    names = names if names is not None else config.metric
    out = []
    for n in names:
        if n in ("none", ""):
            continue
        cls = _METRICS.get(n)
        if cls is None:
            raise ValueError(f"Unknown metric: {n}")
        out.append(cls(config))
    return out
