"""HBM memory observability: analytic peak model, per-phase watermarks,
and the preflight capacity planner.

The training cost model (docs/PERF_PROJECTION.md) says iterations are
HBM-bound; ROADMAP item 2 (datasets bigger than HBM) needs a *capacity*
model to decide, before allocation, whether bins/gradients/histograms
fit device memory or must stream — the decision "Out-of-Core GPU
Gradient Boosting" (arXiv:2005.09148) makes per batch. PR 4's
``hist_traffic_model`` did this for bandwidth; this module does it for
capacity. Three layers:

1. **Analytic peak-HBM model** — ``train_memory_model`` /
   ``predict_memory_model``: per-phase byte accounting for every
   device-resident buffer class (bins packed/unpacked, fused vs
   materialized gradients, histogram pool + wave slabs, partition/node
   state, ensemble packs), parameterized by shape + config knobs +
   mesh shards. Exact for what the *program* allocates (shapes are
   trace-time constants); XLA fusion temporaries are outside it, which
   is why the gate band (tools/perf_floor.json ``model_vs_measured``)
   is 1.5x, not 1.0x.

2. **Live per-phase watermarks** — ``PhaseWatermarks``: a
   span-boundary sampler registered on the tracer's sink chain that
   attributes ``peak_bytes_in_use`` growth to the phase whose span just
   closed, across ALL local devices. Auto-off on backends whose
   ``memory_stats()`` is None (CPU); a single attribute check when
   disabled.

3. **Preflight capacity planner** — ``preflight`` (training) /
   ``preflight_predict`` (serving): compares the predicted peak
   against device capacity and, when it doesn't fit, produces concrete
   knob recommendations (``tpu_bin_pack``, ``use_quantized_grad``,
   ``tpu_fused_grad``, ``tpu_num_shards``, ``tpu_predict_chunk``) with
   the bytes each one saves — so a too-big config fails fast with a
   plan instead of OOMing mid-run. Hooked into ``GBDT.__init__``
   (``tpu_preflight`` knob: warn/error/off) and
   ``serve.ModelRegistry.load``.

Capacity comes from ``device.memory_stats()["bytes_limit"]`` when the
backend reports it; the ``LGBM_TPU_HBM_BYTES`` env var overrides it
(testing, or planning for a different chip than the one attached).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .metrics import global_metrics

F32 = 4
I32 = 4
F64 = 8


class PreflightError(RuntimeError):
    """Predicted peak HBM exceeds device capacity (tpu_preflight=error)."""


# ---------------------------------------------------------------------------
# device capacity
def device_capacity_bytes() -> Optional[int]:
    """Per-device HBM capacity in bytes, or None when unknown.

    ``LGBM_TPU_HBM_BYTES`` overrides (plan for a chip that isn't
    attached; also the test seam). Otherwise the MIN ``bytes_limit``
    over local devices — the planner asks "does the per-shard working
    set fit the smallest device", which is the OOM that matters.
    CPU backends report no memory_stats => None (preflight then has no
    verdict and stays silent)."""
    env = os.environ.get("LGBM_TPU_HBM_BYTES", "")
    if env:
        try:
            return int(float(env))
        except ValueError:
            pass
    stats = global_metrics.per_device_memory_stats()
    if not stats:
        return None
    limits = [s.get("bytes_limit") for s in stats
              if isinstance(s.get("bytes_limit"), (int, float))]
    return int(min(limits)) if limits else None


def measured_peak_bytes() -> Optional[int]:
    """Max ``peak_bytes_in_use`` across local devices (None on CPU)."""
    stats = global_metrics.per_device_memory_stats()
    if not stats:
        return None
    peaks = [s.get("peak_bytes_in_use", 0) or 0 for s in stats]
    return int(max(peaks)) if peaks else None


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length() if n > 1 else 1


# ---------------------------------------------------------------------------
# analytic models
def packed_bin_bytes(num_data: int, num_features: int, max_bins: int,
                     pack_vpb: int = 1) -> int:
    """Device bytes of the [F, N] bin tensor under the given packing
    factor — uint8 (uint16 above 256 bins) unpacked; the split-section
    PACK_ALIGN-padded byte layout of ops/bin_pack.py when packed."""
    if pack_vpb > 1:
        from ..ops.bin_pack import PACK_ALIGN
        section = -(-num_data // pack_vpb)
        section = -(-section // PACK_ALIGN) * PACK_ALIGN
        return num_features * section
    itemsize = 1 if max_bins <= 256 else 2
    return num_features * num_data * itemsize


def train_memory_model(*, num_data: int, num_features: int, max_bins: int,
                       num_leaves: int, num_class: int = 1,
                       num_iterations: int = 100,
                       pack_vpb: int = 1, quantized: bool = False,
                       fused_grad: bool = False, kernel_fused: bool = False,
                       waved: bool = True, wave_max: int = 42,
                       num_shards: int = 1, has_weight: bool = False,
                       valid_rows: Sequence[int] = (),
                       stream_slab_rows: int = 0) -> Dict[str, Any]:
    """Analytic per-device peak-HBM model of one training run.

    Accounts every buffer class the fused iteration program keeps
    resident or allocates per wave, per shard of the mesh data axis
    (row-indexed state divides by ``num_shards``; leaf/histogram state
    is replicated):

    - ``bins``        [F, N/s] uint8/16, or the packed byte layout
    - ``scores``      [K, N/s] f32 (+ per-valid-set scores)
    - ``objective``   label (+ weight) [N/s] f32
    - ``gradients``   grad/hess [K, N/s] f32 x2 — zero when the
                      gradient pass is fused (``tpu_fused_grad``)
    - ``ght``         the [N/s, 3] histogram operand — f32, int8 when
                      quantized, absent when fused IN-KERNEL
    - ``sample_mask`` / ``row_leaf`` [N/s]
    - ``hist_pool``   [L, F, B, 3] f32 parent-histogram pool
                      (subtraction needs parents resident)
    - ``hist_wave``   [S, F, B, 3] wave slab + split-scan gain tensors
    - ``partition``   the batched wave partition's per-row gather
                      transients
    - ``records``     per-iteration device TreeArrays (accumulate until
                      materialized)
    - ``valid``       per valid set: bins + scores

    Returns components, per-phase live-set sums, and
    ``peak_bytes`` = max over phases — the number bench.py publishes as
    ``mem_peak_model_bytes`` and tools/check_perf_gate.py floor-gates.
    """
    n = int(num_data)
    shards = max(int(num_shards), 1)
    n_s = -(-n // shards)  # rows per shard
    f = int(num_features)
    b = int(max_bins)
    l = int(num_leaves)
    k = max(int(num_class), 1)

    comp: Dict[str, int] = {}
    slab = int(stream_slab_rows)
    if slab > 0:
        # out-of-core streaming (tpu_stream): the [F, N] bin tensor is
        # HOST-resident; device HBM holds only the double-buffered slab
        # pair (slab k being consumed + slab k+1 uploading)
        comp["bins"] = 2 * packed_bin_bytes(min(slab, n_s), f, b, pack_vpb)
    else:
        comp["bins"] = packed_bin_bytes(n_s, f, b, pack_vpb)
    comp["scores"] = k * n_s * F32
    comp["objective"] = n_s * F32 * (2 if has_weight else 1)
    comp["sample_mask"] = n_s * F32
    comp["row_leaf"] = n_s * I32
    # materialized gradient buffers: grad + hess per class; the fused
    # gradient pass (tpu_fused_grad) derives them pointwise inside the
    # grower so they never exist as [N] buffers
    comp["gradients"] = 0 if fused_grad else 2 * k * n_s * F32
    # the [N, 3] (g*m, h*m, m) histogram operand: int8 when quantized,
    # absent entirely when the pallas kernel computes gh in VMEM
    if kernel_fused:
        comp["ght"] = 0
    else:
        comp["ght"] = n_s * 3 * (1 if quantized else F32)
    # parent-histogram pool for sibling subtraction: [L, F, B, 3] f32
    comp["hist_pool"] = l * f * b * 3 * F32
    # one wave's fresh histograms + the split scan's [S, F, B] stat/gain
    # tensors (~6 channels through find_best_split)
    from ..learner import HIST_SLOTS
    slots = min(max(int(wave_max), 1), HIST_SLOTS) if waved else 1
    comp["hist_wave"] = slots * f * b * 3 * F32
    comp["split_scan"] = slots * f * b * 6 * F32
    # batched wave partition: per-row split-feature id, gathered bin,
    # decision + new row_leaf (~16 B/row of transient)
    comp["partition"] = n_s * 16
    # device tree records pending materialization: ~12 L-sized f32/i32
    # arrays + the [L-1, B] categorical bitmask, per class per iteration
    comp["records"] = int(num_iterations) * k * (12 * l * F32 + (l - 1) * b)
    valid_bytes = 0
    for nv in valid_rows or ():
        nv_s = -(-int(nv) // shards)
        valid_bytes += packed_bin_bytes(nv_s, f, b, pack_vpb) \
            + k * nv_s * F32
    comp["valid"] = valid_bytes

    persistent = (comp["bins"] + comp["scores"] + comp["objective"]
                  + comp["sample_mask"] + comp["row_leaf"]
                  + comp["gradients"] + comp["hist_pool"]
                  + comp["records"] + comp["valid"])
    phases = {
        "gradients": persistent + comp["ght"],
        "histogram": persistent + comp["ght"] + comp["hist_wave"]
        + comp["split_scan"],
        "partition": persistent + comp["ght"] + comp["partition"],
    }
    peak_phase = max(phases, key=lambda p: phases[p])
    return {
        "kind": "train",
        "components": comp,
        "phases": phases,
        "persistent_bytes": persistent,
        "peak_bytes": phases[peak_phase],
        "peak_phase": peak_phase,
        "num_shards": shards,
        "stream_slab_rows": slab,
        "params": dict(num_data=n, num_features=f, max_bins=b,
                       num_leaves=l, num_class=k,
                       num_iterations=int(num_iterations),
                       pack_vpb=int(pack_vpb), quantized=bool(quantized),
                       fused_grad=bool(fused_grad),
                       kernel_fused=bool(kernel_fused), waved=bool(waved),
                       wave_max=int(wave_max), num_shards=shards,
                       has_weight=bool(has_weight),
                       valid_rows=[int(v) for v in (valid_rows or ())],
                       stream_slab_rows=slab),
    }


def stream_auto_slab_rows(kw: Dict[str, Any],
                          capacity_bytes: Optional[int]) -> int:
    """Auto slab size for out-of-core streaming (``tpu_stream`` with
    ``tpu_stream_slab_rows=0``): the largest section-aligned row count
    whose DOUBLE-BUFFERED slab pair fits the capacity left after the
    resident (non-bins) working set of the analytic model. Unknown
    capacity (CPU, no LGBM_TPU_HBM_BYTES) => one slab covering all
    rows — the degenerate plan that is bit-identical to resident
    training by construction. Never returns less than one aligned
    section even when nothing fits (preflight reports the shortfall
    separately)."""
    from ..ops.bin_pack import slab_align
    kw = {k: v for k, v in kw.items() if k != "stream_slab_rows"}
    n = int(kw["num_data"])
    align = slab_align(int(kw["max_bins"]))
    if capacity_bytes is None:
        return -(-n // align) * align
    resident = train_memory_model(**kw)
    non_bins = resident["peak_bytes"] - resident["components"]["bins"]
    budget = max(int(capacity_bytes) - non_bins, 0)
    bytes_per_row = max(
        packed_bin_bytes(align, int(kw["num_features"]),
                         int(kw["max_bins"]), int(kw["pack_vpb"])) / align,
        1e-9)
    rows = int(budget / (2 * bytes_per_row))
    rows = max(rows // align * align, align)
    return min(rows, -(-n // align) * align)


def _resolve_train_knobs(config, num_data: int, num_features: int,
                         num_class: int) -> Dict[str, Any]:
    """Config -> the model's semantic knobs, mirroring the resolution
    the booster itself performs (GBDT._maybe_pack_bins /
    _resolve_fused_grad / _resolved_wave_max) without needing a built
    booster — this is what lets ``preflight`` run BEFORE any device
    allocation."""
    from ..ops.bin_pack import pack_vpb as _pack_vpb
    from ..ops import histogram as hist_ops

    learner_kind = str(getattr(config, "tree_learner", "serial"))
    raw_shards = int(getattr(config, "tpu_num_shards", 0) or 0)
    if learner_kind in ("data", "voting"):
        shards = raw_shards
        if shards <= 0:
            try:
                import jax
                shards = len(jax.local_devices())
            except Exception:
                shards = 1
    else:
        shards = 1
    shards = max(shards, 1)

    # mirror _maybe_pack_bins exactly: packing refuses whenever
    # tpu_num_shards > 1 is SET, even on the serial learner
    vpb = 1
    if str(config.tpu_bin_pack) not in ("off", "0", "false", "False") \
            and learner_kind == "serial" and raw_shards <= 1:
        vpb = _pack_vpb(int(config.max_bin))

    k = max(int(num_class), 1)
    wave_max = int(config.tpu_wave_max)
    if wave_max < 0:  # auto: exact order for coupled multiclass
        coupled = (k > 1 and str(config.objective) != "multiclassova")
        wave_max = 0 if coupled else 42
    waved = wave_max > 0

    quantized = bool(config.use_quantized_grad) and waved \
        and int(config.num_grad_quant_bins) <= 126

    fused = False
    if str(config.tpu_fused_grad) not in ("off", "0", "false", "False"):
        fused = (waved and k == 1 and not quantized
                 and not bool(config.use_quantized_grad)
                 and str(config.data_sample_strategy) != "goss"
                 and str(config.objective) in ("binary", "regression"))
    kernel_fused = fused and \
        hist_ops.resolve_impl(str(config.tpu_hist_impl)) == "pallas"

    return dict(num_data=int(num_data), num_features=int(num_features),
                max_bins=int(config.max_bin), num_leaves=int(config.num_leaves),
                num_class=k, num_iterations=int(config.num_iterations),
                pack_vpb=vpb, quantized=quantized, fused_grad=fused,
                kernel_fused=kernel_fused, waved=waved,
                wave_max=max(wave_max, 1), num_shards=shards)


def predict_memory_model(*, num_rows: int, num_features: int,
                         num_trees: int, num_leaves: int,
                         num_class: int = 1, chunk_rows: int = 1 << 20,
                         pack_nbytes: Optional[int] = None,
                         resident_pack_bytes: int = 0,
                         contrib: bool = False,
                         shap_pack_nbytes: Optional[int] = None
                         ) -> Dict[str, Any]:
    """Analytic peak-HBM model of a serving dispatch: the device
    ensemble pack plus one chunk's traversal working set.

    - ``pack``      device + host-mirror packed ensemble tensors
                    (measured ``EnsemblePacker.nbytes*2`` when the pack
                    exists; otherwise the capacity-doubled analytic
                    estimate)
    - ``chunk_*``   per-chunk buffers at the effective chunk size
                    (``tpu_predict_chunk``, capped by the row-bucket the
                    request actually compiles): double-buffered f32
                    feature blocks, [B, T] int32 traversal state, [B, T]
                    leaf gather + [B, K] f64 output
    - ``resident_pack_bytes`` adds OTHER models' packs already resident
      (the serve registry's budgeted pool) so multi-tenant preflight
      sees the whole pool, not one model.

    With ``contrib=True`` the pred_contrib (TreeSHAP) dispatch is
    modeled instead of plain traversal: the depth-padded path-table
    pack (measured ``EnsemblePacker.shap_nbytes*2`` via
    ``shap_pack_nbytes`` when it exists; analytic T*L paths x padded
    depth x 14 f32 tables otherwise) plus the kernel's [B, Pc, D]
    pweight working set, which the packer sizes against its own
    128 MB budget (ops/predict._SHAP_BUDGET_BYTES) — the band
    tools/check_perf_gate.py check 13 holds the measured pack to."""
    t = int(num_trees)
    l = int(num_leaves)
    if pack_nbytes is None:
        max_i = _pow2(max(l - 1, 1))
        # 6 i32 fields + f64 threshold per internal slot, f32 leaf values
        pack_host = t * (max_i * (6 * I32 + F64) + _pow2(l) * F32)
    else:
        pack_host = int(pack_nbytes)
    chunk = min(int(chunk_rows), _pow2(max(int(num_rows), 16)))
    comp = {
        "pack": 2 * pack_host,
        "resident_packs": int(resident_pack_bytes),
        "chunk_features": 2 * chunk * int(num_features) * F32,
        "chunk_state": chunk * t * I32,
        "chunk_out": chunk * t * F32 + chunk * max(int(num_class), 1) * F64,
    }
    if contrib:
        from ..ops.predict import _SHAP_BUDGET_BYTES
        from ..ops.shap import MAX_CHUNK_ROWS
        paths = t * l
        # unique path elements ~ tree depth ~ log2(L) (+1 dummy slot),
        # padded to a multiple of 4 like the packer's depth bucketing
        d_est = max(l - 1, 1).bit_length() + 1
        depth = max(-(-d_est // 4) * 4, 4)
        if shap_pack_nbytes is None:
            # 13 path tables + leaf values: one 4-byte cell per slot
            shap_host = paths * depth * 14 * F32
        else:
            shap_host = int(shap_pack_nbytes)
        cchunk = min(chunk, MAX_CHUNK_ROWS)
        # [B, Pc, D] f32 recurrence tensors (~6 live at the extend/
        # unwind peak); Pc is the pow2 path-chunk the packer fits into
        # its budget, floored at 32 and capped at the path count
        per_path = cchunk * depth * F32 * 6
        pc = 1 << max(int(_SHAP_BUDGET_BYTES // max(per_path, 1)
                          ).bit_length() - 1, 0)
        pc = max(min(pc, _pow2(max(paths, 1))), 32)
        comp["shap_pack"] = 2 * shap_host
        comp["shap_chunk"] = pc * per_path
    peak = sum(comp.values())
    return {
        "kind": "predict",
        "components": comp,
        "phases": {"traverse": peak},
        "peak_bytes": peak,
        "peak_phase": "traverse",
        "chunk_rows": chunk,
        "params": dict(num_rows=int(num_rows),
                       num_features=int(num_features), num_trees=t,
                       num_leaves=l, num_class=int(num_class),
                       chunk_rows=int(chunk_rows),
                       contrib=bool(contrib)),
    }


# ---------------------------------------------------------------------------
# preflight planner
class PreflightReport:
    """Verdict of a capacity check. ``fits`` is True/False, or None when
    no capacity is known (CPU, no override). ``recommendations`` is a
    list of {knob, setting, saves_bytes, peak_bytes, reason} dicts,
    biggest saving first — each one re-runs the analytic model with
    that knob applied, so the numbers are projections, not guesses."""

    def __init__(self, model: Dict[str, Any], capacity_bytes: Optional[int],
                 recommendations: List[Dict[str, Any]],
                 stream: Optional[Dict[str, Any]] = None):
        self.model = model
        self.peak_bytes = int(model["peak_bytes"])
        self.capacity_bytes = capacity_bytes
        self.fits = (None if capacity_bytes is None
                     else self.peak_bytes <= int(capacity_bytes))
        self.headroom_bytes = (None if capacity_bytes is None
                               else int(capacity_bytes) - self.peak_bytes)
        self.recommendations = recommendations
        # out-of-core streaming verdict (training reports): `fits` stays
        # the RESIDENT verdict — honest about what a non-streamed run
        # would do — while `fits_streaming` says whether the tpu_stream
        # working set (host bins, double-buffered slab) fits. None when
        # capacity is unknown or the shape is stream-ineligible.
        self.stream = stream
        self.fits_streaming = (None if stream is None
                               else bool(stream.get("fits")))

    def render(self) -> str:
        gb = 1e9
        cap = ("unknown" if self.capacity_bytes is None
               else f"{self.capacity_bytes / gb:.2f} GB")
        lines = [f"predicted peak HBM {self.peak_bytes / gb:.2f} GB "
                 f"(phase: {self.model.get('peak_phase')}), "
                 f"device capacity {cap}"]
        if self.fits is False:
            lines[0] += " — DOES NOT FIT resident"
            for r in self.recommendations:
                setting = r["setting"]
                extra = (f" (slab_rows={r['slab_rows']})"
                         if "slab_rows" in r else "")
                lines.append(
                    f"  try {r['knob']}={setting}{extra}: predicted peak "
                    f"{r['peak_bytes'] / gb:.2f} GB "
                    f"(saves {r['saves_bytes'] / gb:.2f} GB) — {r['reason']}")
            if not self.recommendations:
                lines.append("  no single knob closes the gap; shrink the "
                             "dataset or shard it over more hosts")
        return "\n".join(lines)


def _rec(knob: str, setting, base_peak: int, model: Dict[str, Any],
         reason: str) -> Optional[Dict[str, Any]]:
    saved = base_peak - int(model["peak_bytes"])
    if saved <= 0:
        return None
    return {"knob": knob, "setting": setting, "saves_bytes": saved,
            "peak_bytes": int(model["peak_bytes"]), "reason": reason}


def _train_recommendations(kw: Dict[str, Any],
                           capacity: Optional[int],
                           stream_ok: bool = True) -> List[Dict[str, Any]]:
    """Knob projections that shrink the training peak, computed by
    re-running the model with one knob flipped at a time."""
    from ..ops.bin_pack import pack_vpb as _pack_vpb
    base = train_memory_model(**kw)["peak_bytes"]
    recs: List[Dict[str, Any]] = []

    if kw["pack_vpb"] == 1 and _pack_vpb(kw["max_bins"]) > 1:
        m = train_memory_model(**{**kw, "pack_vpb":
                                  _pack_vpb(kw["max_bins"])})
        r = _rec("tpu_bin_pack", "auto", base, m,
                 "bit-pack the bin tensor (ops/bin_pack.py)")
        if r:
            recs.append(r)
    elif kw["max_bins"] > 15:
        m = train_memory_model(**{**kw, "max_bins": 15, "pack_vpb": 2})
        r = _rec("max_bin", 15, base, m,
                 "15 bins admit 4-bit packed storage (tpu_bin_pack)")
        if r:
            recs.append(r)
    if not kw["quantized"]:
        m = train_memory_model(**{**kw, "quantized": True,
                                  "fused_grad": False,
                                  "kernel_fused": False})
        r = _rec("use_quantized_grad", True, base, m,
                 "int8 gradient operand for the histogram passes")
        if r:
            recs.append(r)
    if not kw["fused_grad"] and not kw["quantized"] and kw["waved"] \
            and kw["num_class"] == 1:
        m = train_memory_model(**{**kw, "fused_grad": True})
        r = _rec("tpu_fused_grad", "on", base, m,
                 "derive gradients in the histogram wave instead of "
                 "materializing [N] buffers")
        if r:
            recs.append(r)
    # shard the row-indexed state over the mesh: smallest power-of-two
    # device count whose per-shard peak fits (or the largest available)
    try:
        import jax
        n_dev = len(jax.local_devices())
    except Exception:
        n_dev = 1
    if n_dev > kw["num_shards"]:
        best = None
        s = kw["num_shards"] * 2
        while s <= n_dev:
            m = train_memory_model(**{**kw, "num_shards": s,
                                      "pack_vpb": 1})
            best = (s, m)
            if capacity is not None and m["peak_bytes"] <= capacity:
                break
            s *= 2
        if best is not None:
            r = _rec("tpu_num_shards", best[0], base, best[1],
                     "shard rows over the device mesh "
                     "(tree_learner=data)")
            if r:
                recs.append(r)
    if stream_ok:
        sm = stream_model(kw, capacity)
        r = _rec("tpu_stream", "on", base, sm["model"],
                 "keep bins host-resident and stream section-aligned "
                 "slabs through the histogram waves (io/streaming.py)")
        if r:
            r["slab_rows"] = sm["slab_rows"]
            recs.append(r)
    recs.sort(key=lambda r: -r["saves_bytes"])
    return recs


def stream_model(kw: Dict[str, Any],
                 capacity: Optional[int]) -> Dict[str, Any]:
    """The analytic model of the SAME shape trained out-of-core
    (tpu_stream): auto slab size + the streamed peak, with a fits
    verdict against `capacity`. Streaming keeps gradients materialized
    (the streamed prep program needs the [N] buffers), so fused-grad
    components are forced off."""
    kw = {**kw, "fused_grad": False, "kernel_fused": False}
    kw.pop("stream_slab_rows", None)
    slab = stream_auto_slab_rows(kw, capacity)
    model = train_memory_model(**kw, stream_slab_rows=slab)
    fits = (None if capacity is None
            else model["peak_bytes"] <= int(capacity))
    return {"model": model, "slab_rows": int(slab),
            "peak_bytes": int(model["peak_bytes"]), "fits": fits}


def stream_config_ineligible(config,
                             num_class: Optional[int] = None
                             ) -> Optional[str]:
    """Why a CONFIG cannot stream out-of-core, or None. This is THE
    config-level gate list — ``GBDT._stream_ineligible`` delegates to
    it (adding the storage-level gates only a built dataset knows: EFB
    bundling, COO sparsity), so ``preflight``'s recommendation and the
    booster's resolve decision cannot drift. A recommendation may still
    be optimistic about storage (preflight sees shapes, not bins)."""
    if getattr(config, "forcedsplits_filename", ""):
        return "forced splits need the exact (non-waved) grower"
    if getattr(config, "interaction_constraints", None):
        return "interaction constraints are not streamed"
    if bool(getattr(config, "linear_tree", False)):
        return "linear trees fit per-leaf models from raw rows"
    if getattr(config, "monotone_constraints", None) and \
            str(getattr(config, "monotone_constraints_method", "basic")) \
            in ("intermediate", "advanced"):
        return "pairwise monotone modes are not streamed"
    wm = int(getattr(config, "tpu_wave_max", -1))
    k = int(num_class if num_class is not None
            else getattr(config, "num_class", 1))
    coupled = k > 1 and str(getattr(config, "objective", "")) \
        != "multiclassova"
    if wm == 0 or (wm < 0 and coupled):
        return ("exact-order growth (tpu_wave_max=0; coupled "
                "multiclass objectives resolve to it) has no "
                "streamed twin")
    learner = str(getattr(config, "tree_learner", "serial"))
    if learner not in ("serial", "data"):
        return (f"tree_learner={learner} replaces the grower with its "
                "own adapter")
    try:
        import jax
        if jax.process_count() > 1:
            return ("multi-host training assembles globally-sharded "
                    "bins (per-host slab plans are not wired yet)")
    except RuntimeError:
        pass  # backend not initialized: single-process
    return None


def stream_config_eligible(config) -> bool:
    """True when the config admits out-of-core streaming AND the
    ``tpu_stream`` knob is not off — the screen ``preflight`` uses to
    decide whether a streaming recommendation/verdict is on the table."""
    if str(getattr(config, "tpu_stream", "auto")).lower() in (
            "off", "0", "false", "none"):
        return False
    return stream_config_ineligible(config) is None


def train_report(kw: Dict[str, Any],
                 capacity_bytes: Optional[int] = None,
                 stream_ok: bool = True) -> PreflightReport:
    """PreflightReport for already-resolved model kwargs — the entry the
    booster hook uses (it knows the ACTUAL resolved knobs: pack factor,
    fused/quantized state, mesh size), while ``preflight`` resolves them
    from a config for the before-any-allocation path.

    ``stream_ok``: the shape/config admits out-of-core streaming; the
    report then carries the streamed-model verdict (``fits_streaming``)
    and a ``tpu_stream`` recommendation when resident does not fit."""
    model = train_memory_model(**kw)
    cap = capacity_bytes if capacity_bytes is not None \
        else device_capacity_bytes()
    recs: List[Dict[str, Any]] = []
    stream = None
    active_slab = int(kw.get("stream_slab_rows", 0) or 0)
    if active_slab > 0:
        # the caller's model already IS the streamed one (tpu_stream on)
        stream = {"model": model, "slab_rows": active_slab,
                  "peak_bytes": int(model["peak_bytes"]),
                  "fits": (None if cap is None
                           else model["peak_bytes"] <= int(cap))}
    elif stream_ok:
        stream = stream_model(kw, cap)
    if cap is not None and model["peak_bytes"] > cap:
        recs = _train_recommendations(kw, cap, stream_ok=stream_ok)
    return PreflightReport(model, cap, recs, stream=stream)


def preflight(params=None, shape: Optional[Tuple[int, int]] = None, *,
              num_class: Optional[int] = None,
              valid_rows: Sequence[int] = (),
              capacity_bytes: Optional[int] = None) -> PreflightReport:
    """Capacity-check a training config BEFORE allocating anything.

    ``params`` is a params dict or a ``Config``; ``shape`` is
    ``(n_rows, n_features)``. Capacity defaults to the attached
    device's (``LGBM_TPU_HBM_BYTES`` overrides; None on CPU => no
    verdict). Returns a ``PreflightReport`` — callers decide whether a
    non-fit warns or raises (the booster's ``tpu_preflight`` knob)."""
    from ..config import Config
    if not isinstance(params, Config):
        params = Config.from_params(dict(params or {}))
    if shape is None:
        raise ValueError("preflight needs shape=(n_rows, n_features)")
    n_rows, n_features = int(shape[0]), int(shape[1])
    k = int(num_class if num_class is not None else params.num_class)
    kw = _resolve_train_knobs(params, n_rows, n_features, k)
    kw["valid_rows"] = list(valid_rows or ())
    return train_report(kw, capacity_bytes,
                        stream_ok=stream_config_eligible(params))


def preflight_predict(*, num_rows: int, num_features: int, num_trees: int,
                      num_leaves: int, num_class: int = 1,
                      chunk_rows: int = 1 << 20,
                      pack_nbytes: Optional[int] = None,
                      resident_pack_bytes: int = 0,
                      contrib: bool = False,
                      shap_pack_nbytes: Optional[int] = None,
                      capacity_bytes: Optional[int] = None
                      ) -> PreflightReport:
    """Serving-side capacity check (hooked into ModelRegistry.load):
    ensemble pack + chunk working set vs device capacity, recommending
    a smaller ``tpu_predict_chunk`` when the chunk buffers are what
    doesn't fit. ``contrib=True`` models the pred_contrib (TreeSHAP)
    dispatch — path-table pack + pweight working set — instead of
    plain traversal."""
    kw = dict(num_rows=num_rows, num_features=num_features,
              num_trees=num_trees, num_leaves=num_leaves,
              num_class=num_class, chunk_rows=chunk_rows,
              pack_nbytes=pack_nbytes,
              resident_pack_bytes=resident_pack_bytes,
              contrib=contrib, shap_pack_nbytes=shap_pack_nbytes)
    model = predict_memory_model(**kw)
    cap = capacity_bytes if capacity_bytes is not None \
        else device_capacity_bytes()
    recs: List[Dict[str, Any]] = []
    if cap is not None and model["peak_bytes"] > cap:
        base = model["peak_bytes"]
        chunk = int(model["chunk_rows"])
        while chunk > 1 << 14:
            chunk //= 2
            m = predict_memory_model(**{**kw, "chunk_rows": chunk})
            if m["peak_bytes"] <= cap or chunk == 1 << 14:
                r = _rec("tpu_predict_chunk", chunk, base, m,
                         "smaller serving chunks shrink the per-dispatch "
                         "working set")
                if r:
                    recs.append(r)
                break
        if resident_pack_bytes:
            m = predict_memory_model(**{**kw, "resident_pack_bytes": 0})
            r = _rec("serve_cache_bytes", "(lower)", base, m,
                     "LRU-evict other models' resident packs "
                     "(serve/registry.py)")
            if r:
                recs.append(r)
        recs.sort(key=lambda r: -r["saves_bytes"])
    return PreflightReport(model, cap, recs)


# ---------------------------------------------------------------------------
# live per-phase watermarks
class PhaseWatermarks:
    """Span-boundary HBM watermark sampler.

    Registered on the tracer sink chain: each completed span samples
    ``peak_bytes_in_use`` across all local devices and attributes the
    growth since the previous sample to the span that just closed — the
    live counterpart of the analytic model's per-phase peaks. The
    attribution is by closing order (a parent span inherits growth its
    unsampled children caused only if no child span closed in between),
    which is exactly right for the leaf phases the trainer emits
    (train/gradients, train/grow, train/iteration, ...).

    Disabled => one attribute check per span. ``enable()`` probes the
    backend once and stays off where ``memory_stats()`` is None (CPU),
    so the tracer can run everywhere with the sampler armed only where
    it means something. ``stats_fn`` is injectable for tests."""

    def __init__(self, stats_fn=None) -> None:
        self.enabled = False
        self._supported: Optional[bool] = None
        self._stats_fn = (stats_fn if stats_fn is not None
                          else global_metrics.per_device_memory_stats)
        self._lock = threading.Lock()
        self._last_peak: Optional[int] = None
        self.phases: Dict[str, Dict[str, int]] = {}

    def enable(self) -> bool:
        """Arm the sampler. Backend support is probed LAZILY on the
        first completed span, not here: enabling can happen at import
        time (LGBM_TPU_TELEMETRY in the environment) when probing
        devices could initialize — or hang on — a backend nobody asked
        for yet; a completed span implies jax is already running."""
        if self._supported is False:
            return False
        self.enabled = True
        return True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self.phases.clear()
            self._last_peak = None

    # the tracer sink: (name, dur_seconds, self_seconds)
    def sink(self, name: str, dur_s: float, self_s: float) -> None:
        if not self.enabled:
            return
        stats = self._stats_fn()
        if not stats:
            # no memory_stats on this backend (CPU): disarm for good —
            # the disabled check above keeps every later span O(1)
            self._supported = False
            self.enabled = False
            return
        self._supported = True
        peak = max(int(s.get("peak_bytes_in_use", 0) or 0) for s in stats)
        in_use = sum(int(s.get("bytes_in_use", 0) or 0) for s in stats)
        with self._lock:
            prev = self._last_peak
            self._last_peak = max(peak, prev or 0)
            ph = self.phases.get(name)
            if ph is None:
                ph = self.phases[name] = {
                    "delta_bytes": 0, "peak_bytes": 0,
                    "bytes_in_use": 0, "samples": 0}
            if prev is not None and peak > prev:
                ph["delta_bytes"] += peak - prev
            ph["peak_bytes"] = max(ph["peak_bytes"], peak)
            ph["bytes_in_use"] = max(ph["bytes_in_use"], in_use)
            ph["samples"] += 1

    def summary(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {name: dict(ph) for name, ph in self.phases.items()}


global_watermarks = PhaseWatermarks()

# span-boundary feed: every completed span samples device memory when
# the sampler is armed (obs/__init__ imports this module, so the sink
# is registered whenever obs is)
from .trace import global_tracer as _gt  # noqa: E402
_gt.add_sink(global_watermarks.sink)
if global_metrics.enabled:  # env-enabled telemetry arms the sampler too
    global_watermarks.enable()
