"""Booster attribute/introspection surface + native sanitizer tier
(ref: python-package basic.py attr/set_attr/trees_to_dataframe:3775;
sanitizer tier ref: CMakeLists.txt:11-19 USE_SANITIZER + cpp_tests)."""

import shutil
import subprocess
from pathlib import Path

import numpy as np
import pytest

from conftest import make_binary

import lightgbm_tpu as lgb

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def booster():
    X, y = make_binary(400, 5)
    return lgb.train({"objective": "binary", "num_leaves": 7,
                      "min_data_in_leaf": 5, "verbosity": -1},
                     lgb.Dataset(X, label=y), num_boost_round=3)


class TestAttributes:
    def test_set_get_delete(self, booster):
        assert booster.attr("note") is None
        booster.set_attr(note="hello", other="1")
        assert booster.attr("note") == "hello"
        assert booster.attr("other") == "1"
        booster.set_attr(note=None)
        assert booster.attr("note") is None
        assert booster.attr("other") == "1"

    def test_non_string_rejected(self, booster):
        with pytest.raises(lgb.basic.LightGBMError):
            booster.set_attr(bad=42)


class TestTreesToDataframe:
    def test_schema_and_consistency(self, booster):
        df = booster.trees_to_dataframe()
        expected = ["tree_index", "node_depth", "node_index", "left_child",
                    "right_child", "parent_index", "split_feature",
                    "split_gain", "threshold", "decision_type",
                    "missing_direction", "missing_type", "value", "weight",
                    "count"]
        assert list(df.columns) == expected
        assert df["tree_index"].nunique() == booster.num_trees()
        # every tree: one root at depth 1 with no parent
        roots = df[df["node_depth"] == 1]
        assert len(roots) == booster.num_trees()
        assert roots["parent_index"].isna().all()
        # split rows have children that exist; leaf rows have none
        splits = df[df["left_child"].notna()]
        leaves = df[df["left_child"].isna()]
        ids = set(df["node_index"])
        assert set(splits["left_child"]).issubset(ids)
        assert set(splits["right_child"]).issubset(ids)
        assert leaves["split_feature"].isna().all()
        # node counts: internal = leaves - 1 per tree
        for t, g in df.groupby("tree_index"):
            n_leaf = g["left_child"].isna().sum()
            assert len(g) == 2 * n_leaf - 1
        # root count equals the training rows
        assert (roots["count"] == 400).all()

    def test_text_loaded_model(self, booster, tmp_path):
        """Boosters loaded from a model file parse too (the reference's
        most common inspection use case)."""
        path = tmp_path / "model.txt"
        booster.save_model(str(path))
        loaded = lgb.Booster(model_file=str(path))
        df_live = booster.trees_to_dataframe()
        df_loaded = loaded.trees_to_dataframe()
        assert len(df_loaded) == len(df_live)
        assert list(df_loaded["node_index"]) == list(df_live["node_index"])
        np.testing.assert_allclose(
            df_loaded["value"].astype(float),
            df_live["value"].astype(float), rtol=1e-5, atol=1e-7)

    def test_empty_booster_raises(self):
        X, y = make_binary(100, 4)
        bst = lgb.Booster({"objective": "binary", "verbosity": -1},
                          lgb.Dataset(X, label=y))
        with pytest.raises(lgb.basic.LightGBMError):
            bst.trees_to_dataframe()


@pytest.mark.slow
def test_native_sanitizer_tier():
    """`make -C native check-sanitize` builds the native runtime with
    ASan/UBSan and runs the threaded self-test — the reference's
    USE_SANITIZER tier."""
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    proc = subprocess.run(["make", "-C", str(REPO / "native"),
                           "check-sanitize"], capture_output=True,
                          text=True, timeout=600)
    err = proc.stderr or ""
    # skip ONLY on a missing sanitizer runtime — an actual
    # AddressSanitizer/UBSan report must FAIL, not skip
    missing_runtime = ("cannot find -lasan" in err
                       or "cannot find -lubsan" in err
                       or "unrecognized command-line option" in err)
    if proc.returncode != 0 and missing_runtime and \
            "AddressSanitizer" not in err and "runtime error:" not in err:
        pytest.skip("toolchain lacks sanitizer runtime")
    assert proc.returncode == 0, err
    assert "native selftest OK" in proc.stdout


def test_training_produces_no_nans_under_debug():
    """JAX debug tier: a representative fused training run under
    jax_debug_nans — any NaN materializing in the per-iteration program
    raises instead of silently propagating."""
    import jax
    X, y = make_binary(300, 5)
    jax.config.update("jax_debug_nans", True)
    try:
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "min_data_in_leaf": 5, "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=3)
        pred = bst.predict(X)
    finally:
        jax.config.update("jax_debug_nans", False)
    assert np.isfinite(pred).all()
