"""Elastic continual training: a long-lived model that keeps learning.

Production traffic drifts; a model trained once decays. This module
composes the pieces earlier PRs built in isolation into the online loop
ROADMAP item 4 asks for:

- **Ingest** — fresh rows arrive in chunks through
  ``io/streaming.DatasetBuilder`` (``push_rows``), the same
  copy-on-finalize contract the distributed ingestion path uses.
- **Extend** — each :meth:`step` trains one GENERATION: the pushed
  chunk becomes a Dataset, a held-out tail slice becomes the
  generation's eval set, and ``engine.train`` continues the long-lived
  model via ``init_model`` continuation (``tpu_continual_mode=extend``)
  or refreshes leaf values on the fresh chunk via ``refit.py``
  (``refit``).
- **Accept vs rollback** — every per-iteration eval result feeds the
  obs/health.py NaN/spike/plateau anomaly detector (ONE detector whose
  history spans generations, so a quality regression versus the
  previous generation registers as a spike). A generation that raises a
  rollback-class anomaly is REJECTED: the last-good snapshot stays the
  model, the rollback counter ticks, and nothing reaches serving. A
  bounded deque retains the last ``tpu_continual_retain`` accepted
  snapshots for operator-driven :meth:`rollback`.
- **Validated hot-swap** — an ACCEPTED generation is re-parsed from its
  serialized bytes, asserted bit-identical to the training booster on a
  probe slice (reload parity), and only then registered into the serve
  ``ModelRegistry`` through the transactional validate-predict path —
  a rejected generation is never observable from the serve side, and a
  reload-parity failure rejects the generation too.

Preemption interplay (PR 8): with ``tpu_checkpoint_path`` set, each
generation checkpoints under ``<path>.gen<G>`` — a kill mid-generation
exits 75 and re-running :meth:`step` with the same pushed chunk resumes
that generation (elastically, if the mesh was resized in between:
resilience/elastic.py). Everything is exported as
``lgbmtpu_continual_*`` (obs/export.py) and summarized in
``bench.py --continual``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

# eval anomaly kinds that reject a generation by default; "plateau" is
# informational (fresh data legitimately stops helping) unless opted in
DEFAULT_ROLLBACK_ON = ("nan", "spike")


class GenerationResult:
    """Outcome of one continual generation (one :meth:`step`)."""

    __slots__ = ("generation", "accepted", "reason", "anomalies",
                 "eval_history", "rounds", "model_iterations",
                 "train_seconds", "swap_seconds", "resumed")

    def __init__(self, generation: int, accepted: bool, reason: str,
                 anomalies: Dict[str, int],
                 eval_history: List[tuple], rounds: int,
                 model_iterations: int, train_seconds: float,
                 swap_seconds: float, resumed: bool):
        self.generation = generation
        self.accepted = accepted
        self.reason = reason
        self.anomalies = anomalies
        self.eval_history = eval_history
        self.rounds = rounds
        self.model_iterations = model_iterations
        self.train_seconds = train_seconds
        self.swap_seconds = swap_seconds
        self.resumed = resumed

    def __repr__(self) -> str:  # operator-friendly one-liner
        verdict = "accepted" if self.accepted else \
            f"ROLLED BACK ({self.reason})"
        return (f"<generation {self.generation}: {verdict}, "
                f"{self.model_iterations} total iterations>")


class ContinualTrainer:
    """The long-lived continual-training driver (``lgb.continual_train``
    wraps it; tools/check_continual.py chaos-tests it)."""

    def __init__(self, params: Dict[str, Any], num_features: int,
                 registry=None, serve_name: str = "continual",
                 rollback_on=DEFAULT_ROLLBACK_ON,
                 probe_rows: int = 16):
        from ..config import Config
        self.params = dict(params or {})
        self.num_features = int(num_features)
        cfg = Config.from_params(self.params)
        self.rounds = max(int(cfg.tpu_continual_rounds), 1)
        self.retain = max(int(cfg.tpu_continual_retain), 1)
        self.eval_fraction = min(max(
            float(cfg.tpu_continual_eval_fraction), 0.0), 0.9)
        mode = str(cfg.tpu_continual_mode).lower()
        if mode not in ("extend", "refit"):
            raise ValueError(
                f"tpu_continual_mode={cfg.tpu_continual_mode!r} is not "
                "one of extend/refit")
        self.mode = mode
        self.refit_decay = float(cfg.refit_decay_rate)
        self._ckpt_base = str(cfg.tpu_checkpoint_path or "")
        self.registry = registry
        self.serve_name = str(serve_name)
        self.rollback_on = tuple(rollback_on)
        self.probe_rows = max(int(probe_rows), 1)

        # ONE anomaly detector across generations: its eval history is
        # what makes "worse than the last few generations" a spike
        from ..obs.health import HealthRegistry
        self._detector = HealthRegistry()

        self._model_str: Optional[str] = None      # last-good snapshot
        self._retained = deque(maxlen=self.retain)  # (gen, model_str)
        self._builder = None
        self.generation = 0        # attempts (accepted + rolled back)
        self.accepted = 0
        self.rollbacks = 0
        self.swaps = 0
        self.swap_seconds_total = 0.0
        self.last_swap_seconds = 0.0
        self.train_seconds_total = 0.0
        self.model_iterations = 0
        self.history: List[GenerationResult] = []
        self._publish()

    # ------------------------------------------------------------------
    # ingestion (io/streaming.py)
    def push_rows(self, data, label, weight=None) -> "ContinualTrainer":
        """Buffer one fresh chunk for the next generation (chunked-push
        contract of ``io/streaming.DatasetBuilder``)."""
        if self._builder is None:
            from ..io.streaming import DatasetBuilder
            self._builder = DatasetBuilder(self.num_features,
                                           params=self._data_params())
        self._builder.push_rows(data, label=label, weight=weight)
        return self

    def _data_params(self) -> Dict[str, Any]:
        # binning/dataset params only — engine knobs ride self.params
        keep = {k: v for k, v in self.params.items()
                if k in ("max_bin", "min_data_in_bin", "categorical_feature",
                         "feature_pre_filter", "bin_construct_sample_cnt")}
        return keep

    @property
    def pending_rows(self) -> int:
        return self._builder.num_pushed if self._builder is not None else 0

    # ------------------------------------------------------------------
    def step(self) -> GenerationResult:
        """Train one generation on everything pushed since the last
        step; accept (and hot-swap) or roll back. Raises if no rows are
        pending."""
        if self._builder is None or self._builder.num_pushed == 0:
            raise ValueError("no rows pushed for this generation "
                             "(call push_rows first)")
        builder, self._builder = self._builder, None
        ds = builder.finalize()
        probe = np.asarray(ds.data, np.float64)[:self.probe_rows]

        gen = self.generation
        t0 = time.perf_counter()
        if self.mode == "refit" and self._model_str is not None:
            bst, eval_hist, resumed = self._refit_generation(ds)
        else:
            n = ds.num_data()
            cut = n - int(round(n * self.eval_fraction))
            cut = min(max(cut, 1), n)
            dtrain = ds.subset(np.arange(cut)) if cut < n else ds
            dvalid = ds.subset(np.arange(cut, n)) if cut < n else None
            bst, eval_hist, resumed = self._train_generation(
                gen, dtrain, dvalid)
        train_s = time.perf_counter() - t0

        # -- accept-vs-rollback: feed the detector, collect fresh flags
        flags: Dict[str, int] = {}
        for (it, data_name, metric, value, hib) in eval_hist:
            for f in self._detector.note_eval(it, data_name, metric,
                                              value, hib):
                flags[f] = flags.get(f, 0) + 1
        reason = next((f for f in self.rollback_on if f in flags), "")

        swap_s = 0.0
        if not reason:
            model_str = bst.model_to_string()
            swap_err = self._hot_swap(model_str, probe, bst)
            if swap_err:
                reason = swap_err
            else:
                swap_s = self.last_swap_seconds
                self._model_str = model_str
                self._retained.append((gen, model_str))
                self.accepted += 1
                self.model_iterations = bst.current_iteration()

        self.generation += 1
        self.train_seconds_total += train_s
        accepted = not reason
        if not accepted:
            self.rollbacks += 1
        from ..obs.metrics import global_metrics
        global_metrics.inc_counter("continual/generations")
        global_metrics.inc_counter("continual/accepted" if accepted
                                   else "continual/rollbacks")
        result = GenerationResult(
            generation=gen, accepted=accepted, reason=reason,
            anomalies=flags, eval_history=eval_hist, rounds=self.rounds,
            model_iterations=self.model_iterations,
            train_seconds=train_s, swap_seconds=swap_s, resumed=resumed)
        self.history.append(result)
        self._publish()
        if not accepted:
            from .. import log
            log.warning(
                f"continual generation {gen} ROLLED BACK ({reason}): "
                f"model stays at the last-good snapshot "
                f"({self.model_iterations} iterations); serve registry "
                "untouched")
        return result

    # ------------------------------------------------------------------
    def _train_generation(self, gen: int, dtrain, dvalid):
        """One init_model-continuation generation; returns
        (booster, eval_history, resumed)."""
        from .. import callback as callback_mod
        from ..engine import train as engine_train
        from ..obs.metrics import global_metrics

        eval_hist: List[tuple] = []

        def record_evals(env) -> None:
            for item in (env.evaluation_result_list or ()):
                eval_hist.append((env.iteration, item[0], item[1],
                                  float(item[2]), bool(item[3])))
        record_evals.needs_eval = True
        record_evals.order = 30

        params = dict(self.params)
        params["verbosity"] = params.get("verbosity", -1)
        ckpt = ""
        if self._ckpt_base:
            # per-generation checkpoint: a kill mid-generation resumes
            # THIS generation; a stale path from an earlier generation
            # must never fingerprint-collide with this chunk's shapes
            ckpt = f"{self._ckpt_base}.gen{gen}"
            params["tpu_checkpoint_path"] = ckpt
        resumes_before = int(global_metrics.counters.get(
            "resilience/resumes", 0))
        init_model = None
        if self._model_str is not None:
            # engine.train treats a str init_model as a FILENAME; the
            # retained snapshot is serialized bytes — parse them here
            from ..model_io import load_model_from_string
            init_model = load_model_from_string(self._model_str)
        bst = engine_train(
            params, dtrain, num_boost_round=self.rounds,
            valid_sets=[dvalid] if dvalid is not None else None,
            valid_names=["continual_eval"] if dvalid is not None else None,
            init_model=init_model,
            callbacks=[record_evals])
        resumed = int(global_metrics.counters.get(
            "resilience/resumes", 0)) > resumes_before
        if ckpt:
            import os
            try:  # the generation completed; its checkpoint is spent
                os.remove(ckpt)
            except OSError:
                pass
        return bst, eval_hist, resumed

    def _refit_generation(self, ds):
        """Refit mode: keep tree structures, refresh leaf values on the
        fresh chunk (refit.py), then eval once for the detector."""
        from ..basic import Booster
        from ..refit import refit_booster
        base = Booster(model_str=self._model_str)
        X = np.asarray(ds.data, np.float64)
        y = np.asarray(ds.label, np.float32)
        bst = refit_booster(base, X, y, decay_rate=self.refit_decay)
        # one summary eval on the refit chunk feeds the detector: raw-
        # score RMSE against the labels — not a proper likelihood for
        # every objective, but finite, consistent across generations,
        # and NaN exactly when the refit leaves went non-finite
        pred = np.asarray(bst.predict(X, raw_score=True), np.float64)
        rmse = float(np.sqrt(np.mean(
            (pred.reshape(len(y), -1)[:, 0] - y) ** 2)))
        eval_hist = [(self.generation, "continual_eval", "refit_rmse",
                      rmse, False)]
        return bst, eval_hist, False

    # ------------------------------------------------------------------
    # validated hot-swap
    def _hot_swap(self, model_str: str, probe: np.ndarray,
                  bst) -> str:
        """Register an accepted generation for serving. Returns "" on
        success, or a rejection reason. Order matters: the reload-parity
        assertion runs BEFORE the registry is touched, and registration
        itself is transactional (serve/registry.py) — a failure at any
        point leaves the previous generation fully served."""
        t0 = time.perf_counter()
        from ..model_io import load_model_from_string
        try:
            reloaded = load_model_from_string(model_str)
        except Exception as exc:
            from .. import log
            log.warning(f"continual hot-swap: reload failed: {exc!r}")
            return "reload_error"
        if probe is not None and len(probe) and reloaded.trees:
            direct = np.asarray(bst.predict(probe, raw_score=True))
            served = reloaded.predict(probe, raw_score=True)
            served = np.asarray(served)
            if direct.shape != served.shape or \
                    not np.array_equal(direct, served):
                from ..obs.metrics import global_metrics
                global_metrics.inc_counter("continual/swap_mismatches")
                return "reload_mismatch"
        if self.registry is not None:
            try:
                self.registry.load(self.serve_name, model=reloaded,
                                   validate=True)
            except Exception as exc:
                from .. import log
                log.warning(f"continual hot-swap: transactional "
                            f"registration failed: {exc!r}")
                return "swap_error"
        dt = time.perf_counter() - t0
        self.swaps += 1
        self.swap_seconds_total += dt
        self.last_swap_seconds = dt
        from ..obs.metrics import global_metrics
        global_metrics.inc_counter("continual/swaps")
        global_metrics.note_latency("continual/swap", dt)
        return ""

    # ------------------------------------------------------------------
    def rollback(self) -> bool:
        """Operator rollback: discard the newest retained snapshot and
        reinstall (and re-serve) the one before it. False when no older
        snapshot is retained. Transactional: the re-serve registration
        runs FIRST (serve/registry.py's load is itself transactional),
        so a failed re-serve leaves the trainer AND the registry on the
        current generation — training and serving never point at
        different generations."""
        if len(self._retained) < 2:
            return False
        gen, model_str = self._retained[-2]
        if self.registry is not None:
            self.registry.load(self.serve_name, model_str=model_str,
                               validate=True)
        self._retained.pop()
        self._model_str = model_str
        self.model_iterations = self._iterations_of(model_str)
        self.rollbacks += 1
        from ..obs.metrics import global_metrics
        global_metrics.inc_counter("continual/rollbacks")
        self._publish()
        return True

    @staticmethod
    def _iterations_of(model_str: str) -> int:
        from ..model_io import load_model_from_string
        return load_model_from_string(model_str).num_iterations

    @property
    def model_str(self) -> Optional[str]:
        """The last-good serialized model (what serving sees)."""
        return self._model_str

    def booster(self):
        """The last-good snapshot as a Booster (prediction-only)."""
        from ..basic import Booster
        if self._model_str is None:
            raise ValueError("no accepted generation yet")
        return Booster(model_str=self._model_str)

    # ------------------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        """The ``lgbmtpu_continual_*`` exporter's source of truth (also
        folded into ``bench.py --continual``'s JSON line)."""
        from .elastic import resume_summary
        out: Dict[str, Any] = {
            "generations": self.generation,
            "accepted": self.accepted,
            "rollbacks": self.rollbacks,
            "swaps": self.swaps,
            "swap_seconds_total": round(self.swap_seconds_total, 6),
            "last_swap_seconds": round(self.last_swap_seconds, 6),
            "train_seconds_total": round(self.train_seconds_total, 6),
            "model_iterations": self.model_iterations,
            "retained_snapshots": len(self._retained),
            "rounds_per_generation": self.rounds,
            "mode": self.mode,
        }
        rs = resume_summary()
        if rs:
            out["resumes"] = rs.get("resumes", 0)
            out["mesh_resizes"] = rs.get("mesh_resizes", 0)
        anomalies = dict(self._detector.eval_anomalies)
        if anomalies:
            out["eval_anomalies"] = anomalies
        return out

    def _publish(self) -> None:
        from ..obs.metrics import global_metrics
        global_metrics.set_meta("continual", self.summary())
