"""C-ABI shim tests: drive the framework through lib_lightgbm_tpu.so the
way reference harnesses drive lib_lightgbm.so (ref: include/LightGBM/
c_api.h; tests/c_api_test/test_.py is the reference's ctypes smoke test).

Two tiers: ctypes from this process (cheap), and a genuinely external C
program that embeds the interpreter through the shim (the third-party
tooling path)."""

import ctypes
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from conftest import make_binary

REPO = Path(__file__).resolve().parent.parent
SO_PATH = REPO / "lightgbm_tpu" / "lib_lightgbm_tpu.so"


def _ensure_built():
    if not SO_PATH.exists():
        subprocess.run(["make", "-C", str(REPO / "native"), "capi"],
                       check=True, capture_output=True)
    return SO_PATH


@pytest.fixture(scope="module")
def lib():
    lib = ctypes.CDLL(str(_ensure_built()))
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.LGBM_GetLastError().decode()


class TestCApiInProcess:
    def test_dataset_booster_lifecycle(self, lib):
        X, y = make_binary(500, 6)
        X64 = np.ascontiguousarray(X, np.float64)
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X64.ctypes.data_as(ctypes.c_void_p), 1,  # C_API_DTYPE_FLOAT64
            ctypes.c_int32(X64.shape[0]), ctypes.c_int32(X64.shape[1]),
            1, b"max_bin=63", None, ctypes.byref(ds)))
        y32 = np.ascontiguousarray(y, np.float32)
        _check(lib, lib.LGBM_DatasetSetField(
            ds, b"label", y32.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(len(y32)), 0))  # C_API_DTYPE_FLOAT32

        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
        assert n.value == 500
        _check(lib, lib.LGBM_DatasetGetNumFeature(ds, ctypes.byref(n)))
        assert n.value == 6

        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=15 min_data_in_leaf=5 "
                b"metric=auc verbosity=-1", ctypes.byref(bst)))
        fin = ctypes.c_int()
        for _ in range(10):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bst,
                                                      ctypes.byref(fin)))
        it = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst,
                                                        ctypes.byref(it)))
        assert it.value == 10

        # train AUC via GetEval(data_idx=0)
        cnt = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetEvalCounts(bst, ctypes.byref(cnt)))
        assert cnt.value >= 1
        res = (ctypes.c_double * cnt.value)()
        out_len = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetEval(bst, 0, ctypes.byref(out_len),
                                            res))
        assert out_len.value == cnt.value
        assert res[0] > 0.8  # AUC on train

        # predict (normal = probability)
        out = (ctypes.c_double * 500)()
        out_len64 = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, X64.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(500), ctypes.c_int32(6), 1, 0, 0, -1, b"",
            ctypes.byref(out_len64), out))
        assert out_len64.value == 500
        pred = np.asarray(out[:500])
        assert 0.0 <= pred.min() and pred.max() <= 1.0
        auc_gap = pred[y > 0.5].mean() - pred[y <= 0.5].mean()
        assert auc_gap > 0.2

        # save -> load -> identical raw predictions
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "model.txt")
            _check(lib, lib.LGBM_BoosterSaveModel(bst, 0, -1, 0,
                                                  path.encode()))
            loaded = ctypes.c_void_p()
            iters = ctypes.c_int()
            _check(lib, lib.LGBM_BoosterCreateFromModelfile(
                path.encode(), ctypes.byref(iters), ctypes.byref(loaded)))
            assert iters.value == 10
            out2 = (ctypes.c_double * 500)()
            _check(lib, lib.LGBM_BoosterPredictForMat(
                loaded, X64.ctypes.data_as(ctypes.c_void_p), 1,
                ctypes.c_int32(500), ctypes.c_int32(6), 1, 1, 0, -1, b"",
                ctypes.byref(out_len64), out2))
            out1 = (ctypes.c_double * 500)()
            _check(lib, lib.LGBM_BoosterPredictForMat(
                bst, X64.ctypes.data_as(ctypes.c_void_p), 1,
                ctypes.c_int32(500), ctypes.c_int32(6), 1, 1, 0, -1, b"",
                ctypes.byref(out_len64), out1))
            np.testing.assert_allclose(np.asarray(out2[:500]),
                                       np.asarray(out1[:500]),
                                       rtol=1e-5, atol=1e-6)
            _check(lib, lib.LGBM_BoosterFree(loaded))

        # model string
        buf_len = 1 << 20
        buf = ctypes.create_string_buffer(buf_len)
        str_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterSaveModelToString(
            bst, 0, -1, 0, ctypes.c_int64(buf_len), ctypes.byref(str_len),
            buf))
        assert 0 < str_len.value <= buf_len
        assert buf.value.decode().startswith("tree")

        _check(lib, lib.LGBM_BoosterFree(bst))
        _check(lib, lib.LGBM_DatasetFree(ds))

    def test_csr_dataset_and_predict(self, lib):
        """CSR creation + prediction through the C ABI (ref:
        LGBM_DatasetCreateFromCSR c_api.cpp:1311) must match the dense
        path on the same data."""
        from scipy import sparse
        rng = np.random.RandomState(5)
        X = rng.randn(400, 8)
        X[rng.rand(400, 8) < 0.6] = 0.0  # sparse-ish
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
        csr = sparse.csr_matrix(X)
        indptr = np.ascontiguousarray(csr.indptr, np.int32)
        indices = np.ascontiguousarray(csr.indices, np.int32)
        vals = np.ascontiguousarray(csr.data, np.float64)

        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromCSR(
            indptr.ctypes.data_as(ctypes.c_void_p), 2,  # INT32
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.c_void_p), 1,  # FLOAT64
            ctypes.c_int64(len(indptr)), ctypes.c_int64(len(vals)),
            ctypes.c_int64(8), b"max_bin=63", None, ctypes.byref(ds)))
        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
        assert n.value == 400
        _check(lib, lib.LGBM_DatasetSetField(
            ds, b"label", y.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(400), 0))
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=15 min_data_in_leaf=5 "
                b"verbosity=-1", ctypes.byref(bst)))
        fin = ctypes.c_int()
        for _ in range(8):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bst,
                                                      ctypes.byref(fin)))
        out_csr = (ctypes.c_double * 400)()
        out_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForCSR(
            bst, indptr.ctypes.data_as(ctypes.c_void_p), 2,
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int64(len(indptr)), ctypes.c_int64(len(vals)),
            ctypes.c_int64(8), 1, 0, -1, b"",
            ctypes.byref(out_len), out_csr))
        assert out_len.value == 400
        X64 = np.ascontiguousarray(X, np.float64)
        out_dense = (ctypes.c_double * 400)()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, X64.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(400), ctypes.c_int32(8), 1, 1, 0, -1, b"",
            ctypes.byref(out_len), out_dense))
        np.testing.assert_allclose(np.asarray(out_csr[:400]),
                                   np.asarray(out_dense[:400]),
                                   rtol=1e-6, atol=1e-7)
        _check(lib, lib.LGBM_BoosterFree(bst))
        _check(lib, lib.LGBM_DatasetFree(ds))

    def test_error_reporting(self, lib):
        bst = ctypes.c_void_p(0)
        fin = ctypes.c_int()
        rc = lib.LGBM_BoosterUpdateOneIter(
            ctypes.c_void_p(999999), ctypes.byref(fin))
        assert rc != 0
        assert b"invalid handle" in lib.LGBM_GetLastError()


C_DRIVER = r"""
#include <stdio.h>
#include <stdlib.h>

typedef void* H;
extern int LGBM_DatasetCreateFromMat(const void*, int, int, int, int,
                                     const char*, H, H*);
extern int LGBM_DatasetSetField(H, const char*, const void*, int, int);
extern int LGBM_BoosterCreate(H, const char*, H*);
extern int LGBM_BoosterUpdateOneIter(H, int*);
extern int LGBM_BoosterPredictForMat(H, const void*, int, int, int, int,
                                     int, int, int, const char*,
                                     long long*, double*);
extern int LGBM_BoosterFree(H);
extern int LGBM_DatasetFree(H);
extern const char* LGBM_GetLastError(void);

#define CHECK(x) if ((x) != 0) { \
    fprintf(stderr, "FAIL: %s\n", LGBM_GetLastError()); return 1; }

int main(void) {
  enum { N = 200, F = 4 };
  static double data[N * F];
  static float label[N];
  unsigned s = 42;
  for (int i = 0; i < N; ++i) {
    double t = 0;
    for (int j = 0; j < F; ++j) {
      s = s * 1103515245u + 12345u;
      data[i * F + j] = ((double)(s >> 16 & 0x7fff) / 16384.0) - 1.0;
      t += data[i * F + j];
    }
    label[i] = t > 0 ? 1.0f : 0.0f;
  }
  H ds = NULL, bst = NULL;
  CHECK(LGBM_DatasetCreateFromMat(data, 1, N, F, 1, "max_bin=31", NULL,
                                  &ds));
  CHECK(LGBM_DatasetSetField(ds, "label", label, N, 0));
  CHECK(LGBM_BoosterCreate(ds,
      "objective=binary num_leaves=7 min_data_in_leaf=5 verbosity=-1",
      &bst));
  int fin = 0;
  for (int i = 0; i < 5; ++i) CHECK(LGBM_BoosterUpdateOneIter(bst, &fin));
  static double out[N];
  long long out_len = 0;
  CHECK(LGBM_BoosterPredictForMat(bst, data, 1, N, F, 1, 0, 0, -1, "",
                                  &out_len, out));
  if (out_len != N) { fprintf(stderr, "bad out_len\n"); return 1; }
  double pos = 0, neg = 0; int np_ = 0, nn = 0;
  for (int i = 0; i < N; ++i) {
    if (label[i] > 0.5) { pos += out[i]; ++np_; } else { neg += out[i]; ++nn; }
  }
  if (pos / np_ <= neg / nn) { fprintf(stderr, "no signal\n"); return 1; }
  CHECK(LGBM_BoosterFree(bst));
  CHECK(LGBM_DatasetFree(ds));
  printf("C-API-OK\n");
  return 0;
}
"""


@pytest.mark.slow
def test_capi_external_c_program(tmp_path):
    """A plain C program (no Python involved on its side) trains and
    predicts through the shim — the reference's external-tooling
    contract."""
    _ensure_built()
    src = tmp_path / "driver.c"
    src.write_text(C_DRIVER)
    exe = tmp_path / "driver"
    subprocess.run(
        ["g++", "-x", "c", str(src), "-x", "none", "-o", str(exe),
         str(SO_PATH), f"-Wl,-rpath,{SO_PATH.parent}"],
        check=True, capture_output=True)
    from lightgbm_tpu.hostenv import cpu_child_env
    env = cpu_child_env()
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([str(exe)], env=env, capture_output=True,
                          text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr
    assert "C-API-OK" in proc.stdout


class TestCApiStreaming:
    """The reference streaming flow (ref: tests/cpp_tests/test_stream.cpp
    :253 PushDenseRowsWithMetadata, :304 PushSparseRowsWithMetadata):
    schema from sampled columns -> InitStreaming -> concurrent-style
    chunked pushes with metadata -> MarkFinished -> train."""

    def _sampled_schema(self, lib, X, params=b"max_bin=63"):
        n, f = X.shape
        cols = [np.ascontiguousarray(X[:, j], np.float64) for j in range(f)]
        idxs = [np.arange(n, dtype=np.int32) for _ in range(f)]
        dptrs = (ctypes.c_void_p * f)(
            *[c.ctypes.data for c in cols])
        iptrs = (ctypes.c_void_p * f)(
            *[ix.ctypes.data for ix in idxs])
        npc = np.full(f, n, np.int32)
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromSampledColumn(
            dptrs, iptrs, ctypes.c_int32(f),
            npc.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(n), ctypes.c_int32(n), ctypes.c_int64(n),
            params, ctypes.byref(ds)))
        # keep the per-column buffers alive until the call returns
        self._keep = (cols, idxs)
        return ds

    def test_stream_dense_with_metadata(self, lib):
        X, y = make_binary(400, 6)
        X64 = np.ascontiguousarray(X, np.float64)
        lab = np.ascontiguousarray(y, np.float32)
        w = np.ones(400, np.float32)
        ds = self._sampled_schema(lib, X64)
        _check(lib, lib.LGBM_DatasetInitStreaming(
            ds, 1, 0, 0, 1, 1, -1))
        # push in 4 chunks of 100 (the reference pushes per-thread blocks)
        for k in range(4):
            s = k * 100
            _check(lib, lib.LGBM_DatasetPushRowsWithMetadata(
                ds, X64[s:s + 100].ctypes.data_as(ctypes.c_void_p), 1,
                ctypes.c_int32(100), ctypes.c_int32(6), ctypes.c_int32(s),
                lab[s:s + 100].ctypes.data_as(
                    ctypes.POINTER(ctypes.c_float)),
                w[s:s + 100].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                None, None, ctypes.c_int32(0)))
        _check(lib, lib.LGBM_DatasetMarkFinished(ds))

        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
        assert n.value == 400

        # the streamed dataset trains like a directly-created one
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=15 min_data_in_leaf=5 "
                b"verbosity=-1", ctypes.byref(bst)))
        fin = ctypes.c_int()
        for _ in range(8):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bst,
                                                      ctypes.byref(fin)))
        out = (ctypes.c_double * 400)()
        out_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, X64.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(400), ctypes.c_int32(6), 1, 0, 0, -1, b"",
            ctypes.byref(out_len), out))
        pred = np.asarray(out[:400])
        assert pred[y > 0.5].mean() - pred[y <= 0.5].mean() > 0.2
        # label round-trips through GetField
        fptr = ctypes.c_void_p()
        flen = ctypes.c_int()
        ftype = ctypes.c_int()
        _check(lib, lib.LGBM_DatasetGetField(
            ds, b"label", ctypes.byref(flen), ctypes.byref(fptr),
            ctypes.byref(ftype)))
        assert flen.value == 400 and ftype.value == 0
        got = np.ctypeslib.as_array(
            ctypes.cast(fptr, ctypes.POINTER(ctypes.c_float)),
            shape=(400,))
        np.testing.assert_array_equal(got, lab)
        _check(lib, lib.LGBM_BoosterFree(bst))
        _check(lib, lib.LGBM_DatasetFree(ds))

    def test_stream_csr_auto_finish(self, lib):
        """PushRowsByCSR without manual finish: dataset finishes itself
        when the pushed rows reach num_total_row (ref: c_api.h:221)."""
        from scipy import sparse
        rng = np.random.RandomState(3)
        X = rng.randn(300, 5)
        X[rng.rand(300, 5) < 0.5] = 0.0
        y = (X[:, 0] > 0).astype(np.float32)
        X64 = np.ascontiguousarray(X, np.float64)

        ds = self._sampled_schema(lib, X64)
        half = 150
        for s in (0, half):
            csr = sparse.csr_matrix(X64[s:s + half])
            indptr = np.ascontiguousarray(csr.indptr, np.int32)
            indices = np.ascontiguousarray(csr.indices, np.int32)
            vals = np.ascontiguousarray(csr.data, np.float64)
            _check(lib, lib.LGBM_DatasetPushRowsByCSR(
                ds, indptr.ctypes.data_as(ctypes.c_void_p), 2,
                indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                vals.ctypes.data_as(ctypes.c_void_p), 1,
                ctypes.c_int64(len(indptr)), ctypes.c_int64(len(vals)),
                ctypes.c_int64(5), ctypes.c_int64(s)))
        # auto-finished: SetField + train must work without MarkFinished
        _check(lib, lib.LGBM_DatasetSetField(
            ds, b"label", y.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(300), 0))
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=7 verbosity=-1",
            ctypes.byref(bst)))
        fin = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
        _check(lib, lib.LGBM_BoosterFree(bst))
        _check(lib, lib.LGBM_DatasetFree(ds))


class TestCApiExtendedSurface:
    @pytest.fixture()
    def trained(self, lib):
        X, y = make_binary(400, 6)
        X64 = np.ascontiguousarray(X, np.float64)
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X64.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(400),
            ctypes.c_int32(6), 1, b"max_bin=63", None, ctypes.byref(ds)))
        y32 = np.ascontiguousarray(y, np.float32)
        _check(lib, lib.LGBM_DatasetSetField(
            ds, b"label", y32.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(400), 0))
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=15 min_data_in_leaf=5 "
                b"verbosity=-1 learning_rate=0.1", ctypes.byref(bst)))
        fin = ctypes.c_int()
        for _ in range(6):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bst,
                                                      ctypes.byref(fin)))
        yield lib, ds, bst, X64, y
        lib.LGBM_BoosterFree(bst)
        lib.LGBM_DatasetFree(ds)

    def test_reset_parameter_and_rollback(self, trained):
        lib, ds, bst, X64, y = trained
        _check(lib, lib.LGBM_BoosterResetParameter(
            bst, b"learning_rate=0.01"))
        fin = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
        it = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst,
                                                        ctypes.byref(it)))
        assert it.value == 7
        _check(lib, lib.LGBM_BoosterRollbackOneIter(bst))
        _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst,
                                                        ctypes.byref(it)))
        assert it.value == 6

    def test_counts_and_bounds(self, trained):
        lib, ds, bst, X64, y = trained
        n = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetNumClasses(bst, ctypes.byref(n)))
        assert n.value == 1
        _check(lib, lib.LGBM_BoosterNumModelPerIteration(bst,
                                                         ctypes.byref(n)))
        assert n.value == 1
        _check(lib, lib.LGBM_BoosterNumberOfTotalModel(bst,
                                                       ctypes.byref(n)))
        assert n.value == 6
        lo = ctypes.c_double()
        hi = ctypes.c_double()
        _check(lib, lib.LGBM_BoosterGetLowerBoundValue(bst,
                                                       ctypes.byref(lo)))
        _check(lib, lib.LGBM_BoosterGetUpperBoundValue(bst,
                                                       ctypes.byref(hi)))
        assert lo.value < hi.value
        out_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterCalcNumPredict(
            bst, 100, 0, 0, -1, ctypes.byref(out_len)))
        assert out_len.value == 100
        _check(lib, lib.LGBM_BoosterCalcNumPredict(
            bst, 100, 3, 0, -1, ctypes.byref(out_len)))  # contrib
        assert out_len.value == 100 * 7

    def test_eval_and_feature_names(self, trained):
        lib, ds, bst, X64, y = trained
        nbuf = 16
        buflen = 64
        bufs = [ctypes.create_string_buffer(buflen) for _ in range(nbuf)]
        arr = (ctypes.c_char_p * nbuf)(
            *[ctypes.cast(b, ctypes.c_char_p) for b in bufs])
        out_n = ctypes.c_int()
        out_sz = ctypes.c_size_t()
        _check(lib, lib.LGBM_BoosterGetFeatureNames(
            bst, nbuf, ctypes.byref(out_n), ctypes.c_size_t(buflen),
            ctypes.byref(out_sz), ctypes.cast(arr, ctypes.POINTER(
                ctypes.c_char_p))))
        assert out_n.value == 6
        assert bufs[0].value.decode().startswith("Column_")
        _check(lib, lib.LGBM_BoosterGetEvalNames(
            bst, nbuf, ctypes.byref(out_n), ctypes.c_size_t(buflen),
            ctypes.byref(out_sz), ctypes.cast(arr, ctypes.POINTER(
                ctypes.c_char_p))))
        assert out_n.value >= 1

    def test_leaf_value_surgery(self, trained):
        lib, ds, bst, X64, y = trained
        v = ctypes.c_double()
        _check(lib, lib.LGBM_BoosterGetLeafValue(bst, 0, 0,
                                                 ctypes.byref(v)))
        _check(lib, lib.LGBM_BoosterSetLeafValue(
            bst, 0, 0, ctypes.c_double(v.value + 1.0)))
        v2 = ctypes.c_double()
        _check(lib, lib.LGBM_BoosterGetLeafValue(bst, 0, 0,
                                                 ctypes.byref(v2)))
        assert abs(v2.value - v.value - 1.0) < 1e-12

    def test_fast_single_row_predict(self, trained):
        lib, ds, bst, X64, y = trained
        out = (ctypes.c_double * 400)()
        out_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, X64.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(400), ctypes.c_int32(6), 1, 0, 0, -1, b"",
            ctypes.byref(out_len), out))
        fc = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterPredictForMatSingleRowFastInit(
            bst, 0, 0, -1, 1, ctypes.c_int32(6), b"", ctypes.byref(fc)))
        single = (ctypes.c_double * 1)()
        for i in (0, 7, 123):
            row = np.ascontiguousarray(X64[i])
            _check(lib, lib.LGBM_BoosterPredictForMatSingleRowFast(
                fc, row.ctypes.data_as(ctypes.c_void_p),
                ctypes.byref(out_len), single))
            assert abs(single[0] - out[i]) < 1e-10
        _check(lib, lib.LGBM_FastConfigFree(fc))

    def test_predict_for_file(self, trained, tmp_path):
        lib, ds, bst, X64, y = trained
        data_file = tmp_path / "data.csv"
        lines = ["\t".join(str(v) for v in [0.0] + list(row))
                 for row in X64[:50]]
        data_file.write_text("\n".join(lines) + "\n")
        result_file = tmp_path / "preds.txt"
        _check(lib, lib.LGBM_BoosterPredictForFile(
            bst, str(data_file).encode(), 0, 0, 0, -1, b"",
            str(result_file).encode()))
        preds = np.array([float(l) for l in
                          result_file.read_text().splitlines()])
        out = (ctypes.c_double * 50)()
        out_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, np.ascontiguousarray(X64[:50]).ctypes.data_as(
                ctypes.c_void_p), 1,
            ctypes.c_int32(50), ctypes.c_int32(6), 1, 0, 0, -1, b"",
            ctypes.byref(out_len), out))
        np.testing.assert_allclose(preds, np.asarray(out[:50]),
                                   rtol=1e-6, atol=1e-8)

    def test_load_model_from_string_and_merge(self, trained):
        lib, ds, bst, X64, y = trained
        buf_len = 1 << 20
        buf = ctypes.create_string_buffer(buf_len)
        str_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterSaveModelToString(
            bst, 0, -1, 0, ctypes.c_int64(buf_len), ctypes.byref(str_len),
            buf))
        loaded = ctypes.c_void_p()
        iters = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterLoadModelFromString(
            buf.value, ctypes.byref(iters), ctypes.byref(loaded)))
        assert iters.value == 6
        _check(lib, lib.LGBM_BoosterFree(loaded))

    def test_global_utilities(self, lib):
        n = ctypes.c_int()
        _check(lib, lib.LGBM_SetMaxThreads(4))
        _check(lib, lib.LGBM_GetMaxThreads(ctypes.byref(n)))
        assert n.value == 4
        _check(lib, lib.LGBM_SetMaxThreads(-1))
        buf_len = 1 << 20
        buf = ctypes.create_string_buffer(buf_len)
        out_len = ctypes.c_int64()
        _check(lib, lib.LGBM_DumpParamAliases(
            ctypes.c_int64(buf_len), ctypes.byref(out_len), buf))
        assert b"num_iterations" in buf.value
        _check(lib, lib.LGBM_NetworkInit(b"127.0.0.1:12400", 12400, 120, 1))
        _check(lib, lib.LGBM_NetworkFree())


class TestCApiSerializedReference:
    """Schema shipping between processes (ref: test_stream.cpp:304 uses
    a serialized reference + streaming push): serialize a dataset's
    schema to a ByteBuffer, rebuild an aligned dataset from the bytes,
    fill it with PushRows, train."""

    def test_serialize_roundtrip_and_stream(self, lib):
        X, y = make_binary(300, 5)
        X64 = np.ascontiguousarray(X, np.float64)
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X64.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(300),
            ctypes.c_int32(5), 1, b"max_bin=31", None, ctypes.byref(ds)))
        buf = ctypes.c_void_p()
        blen = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetSerializeReferenceToBinary(
            ds, ctypes.byref(buf), ctypes.byref(blen)))
        assert blen.value > 50
        # read the bytes out through ByteBufferGetAt
        raw = bytearray(blen.value)
        v = ctypes.c_uint8()
        for i in range(blen.value):
            _check(lib, lib.LGBM_ByteBufferGetAt(
                buf, ctypes.c_int32(i), ctypes.byref(v)))
            raw[i] = v.value
        _check(lib, lib.LGBM_ByteBufferFree(buf))
        assert raw.startswith(b"{")

        # rebuild an aligned dataset from the serialized schema + stream
        ds2 = ctypes.c_void_p()
        cbuf = (ctypes.c_uint8 * blen.value).from_buffer(raw)
        _check(lib, lib.LGBM_DatasetCreateFromSerializedReference(
            cbuf, ctypes.c_int32(blen.value), ctypes.c_int64(300),
            ctypes.c_int32(1), b"max_bin=31", ctypes.byref(ds2)))
        _check(lib, lib.LGBM_DatasetPushRows(
            ds2, X64.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(300), ctypes.c_int32(5), ctypes.c_int32(0)))
        y32 = np.ascontiguousarray(y, np.float32)
        _check(lib, lib.LGBM_DatasetSetField(
            ds2, b"label", y32.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(300), 0))
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds2, b"objective=binary num_leaves=7 verbosity=-1",
            ctypes.byref(bst)))
        fin = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
        _check(lib, lib.LGBM_BoosterFree(bst))
        _check(lib, lib.LGBM_DatasetFree(ds))
        _check(lib, lib.LGBM_DatasetFree(ds2))

    def test_sparse_contrib_output(self, lib):
        from scipy import sparse
        rng = np.random.RandomState(2)
        X = rng.randn(200, 6)
        X[rng.rand(200, 6) < 0.5] = 0.0
        y = (X[:, 0] > 0).astype(np.float32)
        X64 = np.ascontiguousarray(X, np.float64)
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X64.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(200),
            ctypes.c_int32(6), 1, b"max_bin=31", None, ctypes.byref(ds)))
        _check(lib, lib.LGBM_DatasetSetField(
            ds, b"label", y.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(200), 0))
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=7 verbosity=-1",
            ctypes.byref(bst)))
        fin = ctypes.c_int()
        for _ in range(4):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bst,
                                                      ctypes.byref(fin)))
        csr = sparse.csr_matrix(X64)
        indptr = np.ascontiguousarray(csr.indptr, np.int32)
        indices = np.ascontiguousarray(csr.indices, np.int32)
        vals = np.ascontiguousarray(csr.data, np.float64)
        out_len = (ctypes.c_int64 * 2)()  # [nelem, nindptr] (c_api.h:1117)
        o_indptr = ctypes.c_void_p()
        o_indices = ctypes.POINTER(ctypes.c_int32)()
        o_data = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterPredictSparseOutput(
            bst, indptr.ctypes.data_as(ctypes.c_void_p), 2,
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int64(len(indptr)), ctypes.c_int64(len(vals)),
            ctypes.c_int64(6), 3, 0, -1, b"", 0,
            out_len, ctypes.byref(o_indptr),
            ctypes.byref(o_indices), ctypes.byref(o_data)))
        nelem = out_len[0]
        assert nelem > 0
        assert out_len[1] == 201  # nrow + 1
        got_indptr = np.ctypeslib.as_array(
            ctypes.cast(o_indptr, ctypes.POINTER(ctypes.c_int32)),
            shape=(int(out_len[1]),)).copy()
        got_data = np.ctypeslib.as_array(
            ctypes.cast(o_data, ctypes.POINTER(ctypes.c_double)),
            shape=(nelem,)).copy()
        # row sums of contributions equal raw predictions
        row_sums = np.add.reduceat(
            got_data, got_indptr[:-1][got_indptr[:-1] < nelem])
        out = (ctypes.c_double * 200)()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, X64.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(200), ctypes.c_int32(6), 1, 1, 0, -1, b"",
            ctypes.byref(out_len), out))
        np.testing.assert_allclose(row_sums[:5], np.asarray(out[:5]),
                                   rtol=1e-6, atol=1e-8)
        _check(lib, lib.LGBM_BoosterFreePredictSparse(
            o_indptr, o_indices, o_data, 2, 1))
        _check(lib, lib.LGBM_BoosterFree(bst))
        _check(lib, lib.LGBM_DatasetFree(ds))

    def test_loaded_param(self, lib):
        X, y = make_binary(200, 4)
        X64 = np.ascontiguousarray(X, np.float64)
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X64.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(200),
            ctypes.c_int32(4), 1, b"max_bin=31", None, ctypes.byref(ds)))
        y32 = np.ascontiguousarray(y, np.float32)
        _check(lib, lib.LGBM_DatasetSetField(
            ds, b"label", y32.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(200), 0))
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=7 verbosity=-1",
            ctypes.byref(bst)))
        buf = ctypes.create_string_buffer(1 << 16)
        out_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterGetLoadedParam(
            bst, ctypes.c_int64(1 << 16), ctypes.byref(out_len), buf))
        import json
        params = json.loads(buf.value.decode())
        assert params.get("objective") == "binary"
        _check(lib, lib.LGBM_BoosterFree(bst))
        _check(lib, lib.LGBM_DatasetFree(ds))


class TestCApiFullSurface:
    """The last entry points completing 98/98 reference C API coverage:
    CSC, multi-matrix, Arrow raw-struct ingestion/prediction,
    AddFeaturesFrom, and the C++ std::function CSR iterator."""

    def _trained(self, lib, X, y, params=b"objective=binary num_leaves=7 "
                                         b"verbosity=-1"):
        X64 = np.ascontiguousarray(X, np.float64)
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X64.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]), 1,
            b"max_bin=31", None, ctypes.byref(ds)))
        y32 = np.ascontiguousarray(y, np.float32)
        _check(lib, lib.LGBM_DatasetSetField(
            ds, b"label", y32.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(len(y32)), 0))
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(ds, params, ctypes.byref(bst)))
        fin = ctypes.c_int()
        for _ in range(4):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bst,
                                                      ctypes.byref(fin)))
        return ds, bst, X64

    def test_csc_create_and_predict(self, lib):
        from scipy import sparse
        rng = np.random.RandomState(4)
        X = rng.randn(300, 6)
        X[rng.rand(300, 6) < 0.4] = 0.0
        y = (X[:, 0] > 0).astype(np.float32)
        csc = sparse.csc_matrix(X)
        colptr = np.ascontiguousarray(csc.indptr, np.int32)
        indices = np.ascontiguousarray(csc.indices, np.int32)
        vals = np.ascontiguousarray(csc.data, np.float64)
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromCSC(
            colptr.ctypes.data_as(ctypes.c_void_p), 2,
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int64(len(colptr)), ctypes.c_int64(len(vals)),
            ctypes.c_int64(300), b"max_bin=31", None, ctypes.byref(ds)))
        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
        assert n.value == 300
        _check(lib, lib.LGBM_DatasetSetField(
            ds, b"label", y.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(300), 0))
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=7 verbosity=-1",
            ctypes.byref(bst)))
        fin = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
        out = (ctypes.c_double * 300)()
        out_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForCSC(
            bst, colptr.ctypes.data_as(ctypes.c_void_p), 2,
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int64(len(colptr)), ctypes.c_int64(len(vals)),
            ctypes.c_int64(300), 1, 0, -1, b"", ctypes.byref(out_len),
            out))
        assert out_len.value == 300
        X64 = np.ascontiguousarray(X, np.float64)
        out2 = (ctypes.c_double * 300)()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, X64.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(300), ctypes.c_int32(6), 1, 1, 0, -1, b"",
            ctypes.byref(out_len), out2))
        np.testing.assert_allclose(np.asarray(out[:300]),
                                   np.asarray(out2[:300]), rtol=1e-6)
        _check(lib, lib.LGBM_BoosterFree(bst))
        _check(lib, lib.LGBM_DatasetFree(ds))

    def test_create_from_mats(self, lib):
        X, y = make_binary(400, 5)
        X64 = np.ascontiguousarray(X, np.float64)
        a, b = X64[:150], X64[150:]
        ptrs = (ctypes.c_void_p * 2)(a.ctypes.data, b.ctypes.data)
        nrows = np.array([150, 250], np.int32)
        majors = np.array([1, 1], np.int32)
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMats(
            ctypes.c_int32(2), ptrs, 1,
            nrows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(5),
            majors.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            b"max_bin=31", None, ctypes.byref(ds)))
        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
        assert n.value == 400
        _check(lib, lib.LGBM_DatasetFree(ds))

    def test_arrow_create_and_predict(self, lib):
        from test_ingestion import _FakeArrowTable
        rng = np.random.RandomState(6)
        cols = [rng.randn(250) for _ in range(4)]
        y = (cols[0] > 0).astype(np.float32)
        table = _FakeArrowTable([np.asarray(c, np.float64) for c in cols],
                                [f"f{j}" for j in range(4)])
        schema_ptr = ctypes.addressof(table._schema)
        array_ptr = ctypes.addressof(table._array)
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromArrow(
            ctypes.c_int64(1), ctypes.c_void_p(array_ptr),
            ctypes.c_void_p(schema_ptr), b"max_bin=31", None,
            ctypes.byref(ds)))
        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
        assert n.value == 250
        # SetField from a primitive Arrow array
        from test_ingestion import _FakeArrowVector
        lab = _FakeArrowVector(np.asarray(y, np.float64))
        _check(lib, lib.LGBM_DatasetSetFieldFromArrow(
            ds, b"label", ctypes.c_int64(1),
            ctypes.c_void_p(ctypes.addressof(lab._child_arrays[0])),
            ctypes.c_void_p(ctypes.addressof(lab._child_schemas[0]))))
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=7 verbosity=-1",
            ctypes.byref(bst)))
        fin = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
        out = (ctypes.c_double * 250)()
        out_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForArrow(
            bst, ctypes.c_int64(1), ctypes.c_void_p(array_ptr),
            ctypes.c_void_p(schema_ptr), 0, 0, -1, b"",
            ctypes.byref(out_len), out))
        assert out_len.value == 250
        _check(lib, lib.LGBM_BoosterFree(bst))
        _check(lib, lib.LGBM_DatasetFree(ds))

    def test_add_features_from(self, lib):
        X, y = make_binary(200, 4)
        ds1, bst, X64 = self._trained(lib, X, y)
        lib.LGBM_BoosterFree(bst)
        X2 = np.ascontiguousarray(
            np.random.RandomState(1).randn(200, 2), np.float64)
        ds2 = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X2.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(200),
            ctypes.c_int32(2), 1, b"max_bin=31", None, ctypes.byref(ds2)))
        _check(lib, lib.LGBM_DatasetAddFeaturesFrom(ds1, ds2))
        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumFeature(ds1, ctypes.byref(n)))
        assert n.value == 6
        _check(lib, lib.LGBM_DatasetFree(ds1))
        _check(lib, lib.LGBM_DatasetFree(ds2))
