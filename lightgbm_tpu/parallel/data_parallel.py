"""Data-parallel boosting over a device mesh.

TPU-native replacement for the reference's distributed tree learners
(ref: src/treelearner/data_parallel_tree_learner.cpp — rows sharded,
histograms ReduceScatter-summed, best split Allgather'd; and NCCLGBDT
src/boosting/cuda/nccl_gbdt.hpp:30 for single-process multi-GPU).

Architecture: rows are sharded over the mesh "data" axis. The one-hot
histogram contraction contracts over the sharded row dimension, so XLA's
SPMD partitioner automatically inserts the cross-device reduce (the
psum that replaces HistogramSumReducer + ReduceScatter at
data_parallel_tree_learner.cpp:287-297). Split finding then runs
replicated on every shard — equivalent state, no explicit sync needed
(the reference's Allgather of SplitInfo becomes redundant by replication).
Voting-parallel's top-k filtered reduce is a bandwidth optimization of the
same program and is handled by the same partitioner.

One jitted program per tree spans the whole mesh — the reference's
per-split network round-trips disappear.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..boosting import DART, GBDT, RF
from ..config import Config
from ..dataset import BinnedDataset
from ..objectives import ObjectiveFunction
from . import mesh as mesh_lib


class _DataParallelMixin:
    """Shards row-indexed device state over the mesh data axis."""

    def _setup_sharding(self, num_shards: int):
        self.mesh = mesh_lib.get_mesh(num_shards)
        # bins [F, N]: rows sharded, features replicated
        self.bins_fm = mesh_lib.shard_data(self.mesh, self.bins_fm, row_axis=1)
        # scores [K, N]: rows sharded
        self.scores = mesh_lib.shard_data(self.mesh, self.scores, row_axis=1)
        self._sample_mask = mesh_lib.shard_data(self.mesh, self._sample_mask,
                                                row_axis=0)
        self.feature_meta = jax.tree_util.tree_map(
            lambda a: mesh_lib.replicate(self.mesh, a), self.feature_meta)
        if self.mesh.size > 1:
            # pallas_call does not auto-partition under GSPMD; the XLA
            # one-hot path partitions its contraction over the sharded row
            # axis (shard_map + pallas planned)
            self._build_grow("xla")

    @property
    def num_machines(self) -> int:
        return self.mesh.size


class DataParallelGBDT(_DataParallelMixin, GBDT):
    def __init__(self, config: Config, train_set: BinnedDataset,
                 objective: Optional[ObjectiveFunction] = None,
                 num_shards: int = 0):
        super().__init__(config, train_set, objective)
        self._setup_sharding(num_shards)


class VotingParallelGBDT(_DataParallelMixin, GBDT):
    """PV-tree voting-parallel learner: rows sharded, local histograms,
    top-k vote + candidate-only psum (ref:
    voting_parallel_tree_learner.cpp; see parallel/voting.py)."""

    def __init__(self, config: Config, train_set: BinnedDataset,
                 objective: Optional[ObjectiveFunction] = None,
                 num_shards: int = 0):
        super().__init__(config, train_set, objective)
        self._setup_sharding(num_shards)
        if self._forced is not None or \
                self._interaction_groups is not None:
            import warnings
            warnings.warn("forced splits / interaction constraints are "
                          "not supported by tree_learner=voting; ignoring")
        if self.mesh.size > 1:
            if config.extra_trees or config.feature_fraction_bynode < 1.0:
                import warnings
                warnings.warn(
                    "extra_trees / feature_fraction_bynode are not "
                    "supported by the sharded voting learner; ignoring")
            from .voting import make_sharded_voting_grow
            top_k = max(1, min(int(config.top_k),
                               self.train_set.num_features))
            grow = make_sharded_voting_grow(
                self.mesh, top_k=top_k, hist_impl="xla",
                has_categorical=self._has_categorical, **self._static)

            def _grow_adapter(bins, g, h, m, fm, meta, hp, md,
                              forced=None, node_key=None):
                return grow(bins, g, h, m, fm, meta, hp, md)
            self._grow = _grow_adapter

    def _fast_path_ok(self, custom_grad) -> bool:
        return False


class FeatureParallelGBDT(GBDT):
    """Feature-parallel learner: data replicated, feature slices per
    shard, all-gathered best splits (ref:
    feature_parallel_tree_learner.cpp; see parallel/feature_parallel.py)."""

    def __init__(self, config: Config, train_set: BinnedDataset,
                 objective: Optional[ObjectiveFunction] = None,
                 num_shards: int = 0):
        super().__init__(config, train_set, objective)
        self.mesh = mesh_lib.get_mesh(num_shards)
        if self._forced is not None or \
                self._interaction_groups is not None:
            import warnings
            warnings.warn("forced splits / interaction constraints are "
                          "not supported by tree_learner=feature; ignoring")
        if self.mesh.size > 1:
            if config.extra_trees or config.feature_fraction_bynode < 1.0:
                import warnings
                warnings.warn(
                    "extra_trees / feature_fraction_bynode are not "
                    "supported by the sharded feature learner; ignoring")
            # replicate everything; sharding is over the computation
            self.bins_fm = mesh_lib.replicate(self.mesh, self.bins_fm)
            self.scores = mesh_lib.replicate(self.mesh, self.scores)
            self._sample_mask = mesh_lib.replicate(self.mesh,
                                                   self._sample_mask)
            self.feature_meta = jax.tree_util.tree_map(
                lambda a: mesh_lib.replicate(self.mesh, a),
                self.feature_meta)
            from .feature_parallel import make_sharded_feature_grow
            grow = make_sharded_feature_grow(
                self.mesh, hist_impl="xla",
                has_categorical=self._has_categorical, **self._static)

            def _grow_adapter(bins, g, h, m, fm, meta, hp, md,
                              forced=None, node_key=None):
                return grow(bins, g, h, m, fm, meta, hp, md)
            self._grow = _grow_adapter
            self._fused = None

    def _fast_path_ok(self, custom_grad) -> bool:
        return False

    @property
    def num_machines(self) -> int:
        return self.mesh.size


class DataParallelDART(_DataParallelMixin, DART):
    def __init__(self, config, train_set, objective=None, num_shards: int = 0):
        super().__init__(config, train_set, objective)
        self._setup_sharding(num_shards)


class DataParallelRF(_DataParallelMixin, RF):
    def __init__(self, config, train_set, objective=None, num_shards: int = 0):
        super().__init__(config, train_set, objective)
        self._setup_sharding(num_shards)


def create_parallel_boosting(config: Config, train_set: BinnedDataset,
                             objective: Optional[ObjectiveFunction] = None
                             ) -> GBDT:
    """Factory for distributed training, dispatching the three reference
    strategies (ref: tree_learner.cpp:17 CreateTreeLearner):
      data    — rows sharded, GSPMD auto-partitioned histogram psum
      voting  — rows sharded, PV-tree top-k vote + candidate-only psum
      feature — data replicated, feature-slice compute + split all_gather
    DART/RF boosting run on the data-parallel program.
    """
    num_shards = int(config.tpu_num_shards or 0)
    if config.boosting == "gbdt" and config.tree_learner == "voting":
        return VotingParallelGBDT(config, train_set, objective,
                                  num_shards=num_shards)
    if config.boosting == "gbdt" and config.tree_learner == "feature":
        return FeatureParallelGBDT(config, train_set, objective,
                                   num_shards=num_shards)
    cls = {"gbdt": DataParallelGBDT, "dart": DataParallelDART,
           "rf": DataParallelRF}[config.boosting]
    return cls(config, train_set, objective, num_shards=num_shards)
