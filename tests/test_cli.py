"""CLI application tests (ref: the reference CLI examples/*/train.conf
workflow and tests/python_package_test/test_consistency.py pattern)."""

import ctypes
import os
import subprocess

import numpy as np
import pytest

from conftest import make_binary, make_multiclass

from lightgbm_tpu import Booster, Dataset
from lightgbm_tpu.cli import main, parse_cli_args


def _write_tsv(path, X, y):
    with open(path, "w") as fh:
        for label, row in zip(y, X):
            fh.write("\t".join([f"{label:g}"] + [f"{v:.6f}" for v in row]))
            fh.write("\n")


@pytest.fixture
def binary_files(tmp_path):
    X, y = make_binary(600, 6)
    Xt, yt = make_binary(200, 6, seed=1)
    train = tmp_path / "b.train"
    test = tmp_path / "b.test"
    _write_tsv(train, X, y)
    _write_tsv(test, Xt, yt)
    return train, test, (X, y, Xt, yt)


def test_parse_cli_args_precedence(tmp_path):
    conf = tmp_path / "t.conf"
    conf.write_text("num_trees = 50  # comment\nobjective=binary\n"
                    "# full-line comment\nlearning_rate = 0.2\n")
    params = parse_cli_args([f"config={conf}", "num_trees=7"])
    assert params["num_iterations"] == "7"     # CLI wins, alias resolved
    assert params["objective"] == "binary"
    assert params["learning_rate"] == "0.2"


def test_cli_train_and_predict(tmp_path, binary_files):
    train, test, (X, y, Xt, yt) = binary_files
    model = tmp_path / "model.txt"
    conf = tmp_path / "train.conf"
    conf.write_text(
        f"task = train\nobjective = binary\ndata = {train}\n"
        f"valid_data = {test}\nnum_trees = 10\nnum_leaves = 15\n"
        f"metric = binary_logloss,auc\noutput_model = {model}\n"
        "verbosity = -1\n")
    assert main([f"config={conf}"]) == 0
    assert model.exists()

    out = tmp_path / "preds.txt"
    assert main([f"task=predict", f"data={test}", f"input_model={model}",
                 f"output_result={out}", "verbosity=-1"]) == 0
    preds = np.loadtxt(out)
    assert preds.shape == (200,)
    assert np.all((preds >= 0) & (preds <= 1))
    # predictions should separate classes reasonably
    assert preds[yt == 1].mean() > preds[yt == 0].mean() + 0.1


def test_cli_predict_matches_python_api(tmp_path, binary_files):
    train, test, (X, y, Xt, yt) = binary_files
    model = tmp_path / "model.txt"
    assert main([f"task=train", f"data={train}", "objective=binary",
                 "num_trees=5", f"output_model={model}",
                 "verbosity=-1"]) == 0
    out = tmp_path / "p.txt"
    assert main([f"task=predict", f"data={test}", f"input_model={model}",
                 f"output_result={out}", "verbosity=-1"]) == 0
    cli_preds = np.loadtxt(out)
    api_preds = Booster(model_file=str(model)).predict(Xt)
    np.testing.assert_allclose(cli_preds, api_preds, rtol=1e-4)


def test_cli_refit_task(tmp_path, binary_files):
    train, test, _ = binary_files
    model = tmp_path / "model.txt"
    refitted = tmp_path / "refitted.txt"
    assert main([f"task=train", f"data={train}", "objective=binary",
                 "num_trees=5", f"output_model={model}", "verbosity=-1"]) == 0
    assert main([f"task=refit", f"data={test}", f"input_model={model}",
                 f"output_model={refitted}", "verbosity=-1"]) == 0
    assert refitted.exists()
    bst = Booster(model_file=str(refitted))
    assert bst.num_trees() == 5


def test_cli_save_binary_and_train_from_binary(tmp_path, binary_files):
    train, test, (X, y, Xt, yt) = binary_files
    assert main([f"task=save_binary", f"data={train}", "objective=binary",
                 "verbosity=-1"]) == 0
    bin_file = str(train) + ".bin"
    assert os.path.exists(bin_file)
    model = tmp_path / "model_from_bin.txt"
    assert main([f"task=train", f"data={bin_file}", "objective=binary",
                 "num_trees=5", f"output_model={model}", "verbosity=-1"]) == 0
    bst = Booster(model_file=str(model))
    preds = bst.predict(Xt)
    assert preds[yt == 1].mean() > preds[yt == 0].mean()


def test_cli_snapshot_freq(tmp_path, binary_files):
    train, _test, _ = binary_files
    model = tmp_path / "m.txt"
    assert main([f"task=train", f"data={train}", "objective=binary",
                 "num_trees=6", "snapshot_freq=2", f"output_model={model}",
                 "verbosity=-1"]) == 0
    assert (tmp_path / "m.txt.snapshot_iter_2").exists()
    assert (tmp_path / "m.txt.snapshot_iter_4").exists()


def test_cli_multiclass_predict_output(tmp_path):
    X, y = make_multiclass(400, 6, k=3)
    train = tmp_path / "mc.train"
    _write_tsv(train, X, y)
    model = tmp_path / "mc_model.txt"
    assert main([f"task=train", f"data={train}", "objective=multiclass",
                 "num_class=3", "num_trees=5", f"output_model={model}",
                 "verbosity=-1"]) == 0
    out = tmp_path / "mc_preds.txt"
    assert main([f"task=predict", f"data={train}", f"input_model={model}",
                 f"output_result={out}", "verbosity=-1"]) == 0
    preds = np.loadtxt(out)
    assert preds.shape == (400, 3)
    np.testing.assert_allclose(preds.sum(axis=1), 1.0, atol=1e-5)


def test_convert_model_compiles_and_matches(tmp_path, binary_files):
    """task=convert_model emits C++ that g++ compiles; the compiled
    predictor must agree with the framework (ref: Tree::ToIfElse)."""
    train, test, (X, y, Xt, yt) = binary_files
    model = tmp_path / "model.txt"
    assert main([f"task=train", f"data={train}", "objective=binary",
                 "num_trees=4", "num_leaves=8", f"output_model={model}",
                 "verbosity=-1"]) == 0
    cpp = tmp_path / "pred.cpp"
    assert main([f"task=convert_model", f"input_model={model}",
                 f"convert_model={cpp}", "verbosity=-1"]) == 0
    text = cpp.read_text()
    assert "PredictTree0" in text and "void Predict" in text

    so = tmp_path / "pred.so"
    wrapper = tmp_path / "wrap.cpp"
    wrapper.write_text(
        '#include "pred.cpp"\nextern "C" void PredictRows('
        "const double* rows, int n, int f, double* out) {\n"
        "  for (int i = 0; i < n; ++i) Predict(rows + i * f, out + i);\n}\n")
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", str(wrapper),
                    "-o", str(so)], check=True, cwd=tmp_path)
    lib = ctypes.CDLL(str(so))
    n, f = Xt.shape
    out = np.zeros(n)
    lib.PredictRows(
        np.ascontiguousarray(Xt).ctypes.data_as(
            ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int(n), ctypes.c_int(f),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    expected = Booster(model_file=str(model)).predict(Xt, raw_score=True)
    # the C++ codegen accumulates in f64 (reference contract) while the
    # booster's packed device ensemble accumulates in f32, so agreement
    # is at f32 resolution, not bitwise
    np.testing.assert_allclose(out, expected, rtol=1e-6, atol=1e-7)


def test_binary_dataset_roundtrip(tmp_path):
    X, y = make_binary(300, 5)
    w = np.abs(np.random.RandomState(0).randn(300)) + 0.5
    ds = Dataset(X, label=y, weight=w)
    ds.construct()
    path = tmp_path / "d.bin"
    ds.save_binary(path)
    from lightgbm_tpu.io.binary_format import load_dataset_binary
    ds2 = load_dataset_binary(path)
    np.testing.assert_array_equal(ds._binned.bins_fm, ds2._binned.bins_fm)
    np.testing.assert_allclose(ds._binned.metadata.label,
                               ds2._binned.metadata.label)
    np.testing.assert_allclose(ds._binned.metadata.weight,
                               ds2._binned.metadata.weight)
    assert [m.num_bins for m in ds._binned.mappers] == \
        [m.num_bins for m in ds2._binned.mappers]


REF_EXAMPLES = "/root/reference/examples"


@pytest.mark.skipif(not os.path.isdir(REF_EXAMPLES),
                    reason="reference examples not mounted")
def test_cli_on_reference_binary_example(tmp_path):
    """Train on the reference's example config/data (read-only mount) —
    the test_consistency.py pattern from SURVEY.md §4."""
    conf = os.path.join(REF_EXAMPLES, "binary_classification", "train.conf")
    model = tmp_path / "ref_model.txt"
    cwd = os.getcwd()
    os.chdir(os.path.join(REF_EXAMPLES, "binary_classification"))
    try:
        assert main([f"config={conf}", "num_trees=10",
                     f"output_model={model}", "verbosity=-1"]) == 0
    finally:
        os.chdir(cwd)
    bst = Booster(model_file=str(model))
    assert bst.num_trees() == 10
    # evaluate on the example's test split
    from lightgbm_tpu.io.text_loader import load_svmlight_or_csv
    data, label, weight, _ = load_svmlight_or_csv(
        os.path.join(REF_EXAMPLES, "binary_classification", "binary.test"),
        {})
    preds = bst.predict(data)
    pos, neg = preds[label == 1], preds[label == 0]
    auc = (pos[:, None] > neg[None, :]).mean() + \
        0.5 * (pos[:, None] == neg[None, :]).mean()
    assert auc > 0.7
