"""Model serialization round-trips (ref strategy:
tests/cpp_tests/test_serialize.cpp, test_engine.py save/load tests)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.model_io import load_model_from_string
from tests.conftest import make_binary, make_multiclass, make_regression


def _train_binary(n=800, rounds=10, **extra):
    X, y = make_binary(n)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, **extra}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=rounds)
    return bst, X, y


def test_string_roundtrip_predictions_match():
    bst, X, y = _train_binary()
    s = bst.model_to_string()
    loaded = lgb.Booster(model_str=s)
    np.testing.assert_allclose(loaded.predict(X), bst.predict(X),
                               rtol=1e-9, atol=1e-10)
    np.testing.assert_allclose(loaded.predict(X, raw_score=True),
                               bst.predict(X, raw_score=True),
                               rtol=1e-9, atol=1e-10)


def test_file_roundtrip(tmp_path):
    bst, X, y = _train_binary()
    path = tmp_path / "model.txt"
    bst.save_model(str(path))
    loaded = lgb.Booster(model_file=str(path))
    np.testing.assert_allclose(loaded.predict(X), bst.predict(X), rtol=1e-9)


def test_model_format_header():
    bst, X, y = _train_binary()
    s = bst.model_to_string()
    lines = s.splitlines()
    assert lines[0] == "tree"
    assert lines[1] == "version=v4"
    assert any(l.startswith("num_class=1") for l in lines)
    assert any(l.startswith("objective=binary sigmoid:") for l in lines)
    assert any(l.startswith("max_feature_idx=7") for l in lines)
    assert any(l.startswith("tree_sizes=") for l in lines)
    assert "end of trees" in s
    assert "feature_importances:" in s
    assert "parameters:" in s
    assert "end of parameters" in s
    assert s.rstrip().endswith("pandas_categorical:null")


def test_tree_sizes_index_correct():
    """tree_sizes= entries must equal the byte length of each tree block
    (ref: gbdt_model_text.cpp:369)."""
    bst, X, y = _train_binary(rounds=3)
    s = bst.model_to_string()
    sizes = [int(x) for x in
             [l for l in s.splitlines()
              if l.startswith("tree_sizes=")][0].split("=")[1].split()]
    # reconstruct blocks between "Tree=i" markers
    body = s.split("tree_sizes=")[1].split("\n", 1)[1]
    blocks = []
    cur = []
    for line in body.splitlines(keepends=True):
        if line.startswith("Tree=") and cur:
            blocks.append("".join(cur))
            cur = [line]
        elif line.strip() == "end of trees":
            blocks.append("".join(cur))
            break
        elif line.startswith("Tree=") or cur:
            cur.append(line)
    # strip the leading blank line that separates header from first tree
    blocks = [b.lstrip("\n") for b in blocks if b.strip()]
    assert len(blocks) == 3
    for size, block in zip(sizes, blocks):
        assert size == len(block.encode())


def test_multiclass_roundtrip():
    X, y = make_multiclass(900, k=3)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(loaded.predict(X), bst.predict(X), rtol=1e-9)
    assert loaded.num_trees() == 15


def test_regression_roundtrip_with_nan():
    X, y = make_regression(600)
    X = X.copy()
    X[::7, 0] = np.nan
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=8)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(loaded.predict(X), bst.predict(X), rtol=1e-9)


def test_dump_model_json():
    bst, X, y = _train_binary(rounds=2)
    d = bst.dump_model()
    assert d["num_class"] == 1
    assert len(d["tree_info"]) == 2
    node = d["tree_info"][0]["tree_structure"]
    assert "split_feature" in node
    # walk to a leaf
    while "leaf_value" not in node:
        node = node["left_child"]
    assert isinstance(node["leaf_value"], float)


def test_loaded_model_metadata():
    bst, X, y = _train_binary(rounds=4)
    m = load_model_from_string(bst.model_to_string())
    assert m.num_iterations == 4
    assert m.feature_names == [f"Column_{i}" for i in range(8)]
    assert m.objective_str.startswith("binary")
    assert m.params.get("num_leaves") == "15"


def test_first_tree_contains_init_bias():
    bst, X, y = _train_binary(rounds=1)
    raw = bst.predict(X, raw_score=True)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    raw2 = loaded.predict(X, raw_score=True)
    np.testing.assert_allclose(raw, raw2, rtol=1e-9)
    prior = np.log(y.mean() / (1 - y.mean()))
    assert abs(raw.mean() - prior) < 1.0


def test_shap_sums_to_prediction():
    bst, X, y = _train_binary(rounds=3)
    contrib = bst.predict(X[:20], pred_contrib=True)
    raw = bst.predict(X[:20], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-5,
                               atol=1e-5)


def test_refit():
    bst, X, y = _train_binary(rounds=5)
    rng = np.random.RandomState(9)
    X2, y2 = make_binary(400, seed=123)
    new_bst = bst.refit(X2, y2, decay_rate=0.5)
    p = new_bst.predict(X2)
    assert p.shape == (400,)
    # refit model differs from original but still predicts sensibly
    assert not np.allclose(new_bst.predict(X), bst.predict(X))
